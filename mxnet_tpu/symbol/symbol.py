"""Symbol — the declarative graph API.

Analog of the reference's ``python/mxnet/symbol/symbol.py`` over nnvm
(src/c_api/c_api_symbolic.cc, 3rdparty/tvm/nnvm Graph). TPU-native
design: a Symbol is a lightweight DAG node over the *same* op registry
the imperative API uses; binding an Executor turns the DAG into a
jit-compiled XLA computation (graph passes — shape inference, memory
planning, fusion — are XLA's job, replacing nnvm's InferShape/
PlanMemory/Gradient passes). The nnvm-JSON schema (nodes/arg_nodes/
heads) is kept for ``tojson``/``load`` so exported models round-trip.
"""
from __future__ import annotations

import json

import numpy as np

from ..base import MXNetError
from ..context import current_context
from ..name import NameManager
from ..attribute import AttrScope

_TRAINING_AWARE = {}
_POSITIONAL_NAMES = {}


def _accepts_training(op):
    """True when the op impl takes a ``_training`` kwarg (Dropout/RNN —
    the stateful ops the reference gates on Imperative::is_training)."""
    hit = _TRAINING_AWARE.get(op.name)
    if hit is None:
        import inspect
        try:
            hit = "_training" in inspect.signature(op.fn).parameters
        except (TypeError, ValueError):
            hit = False
        _TRAINING_AWARE[op.name] = hit
    return hit

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json"]


class Symbol:
    """A node (or group of output slots) in the symbolic graph."""

    def __init__(self, op=None, inputs=None, attrs=None, name=None,
                 num_outputs=1, output_index=None, base=None):
        self._op = op  # Op record or None for variables/groups
        self._inputs = list(inputs or [])
        self._attrs = dict(attrs or {})
        self._name = name
        self._num_outputs = num_outputs
        # slicing support: a Symbol may be a view of one output of `base`
        self._output_index = output_index
        self._base = base

    # -- construction helpers ---------------------------------------------
    @property
    def name(self):
        return self._name

    def attr(self, key):
        return self._attrs.get(key)

    def list_attr(self):
        return dict(self._attrs)

    def attr_dict(self):
        out = {}
        for node in self._topo():
            if node._name:
                out[node._name] = dict(node._attrs)
        return out

    def _set_attr(self, **kwargs):
        self._attrs.update({k: str(v) for k, v in kwargs.items()})

    # -- graph walking -----------------------------------------------------
    def _topo(self):
        seen = set()
        order = []

        def visit(node):
            node = node._base or node
            if id(node) in seen:
                return
            seen.add(id(node))
            for i in node._inputs:
                visit(i)
            order.append(node)

        visit(self)
        return order

    def list_arguments(self):
        return [n._name for n in self._topo() if n._op is None and not n._is_group()]

    def list_outputs(self):
        if self._is_group():
            return sum([i.list_outputs() for i in self._inputs], [])
        base = self._base or self
        if base._num_outputs > 1 and self._output_index is None:
            return [f"{base._name}_output{i}" for i in range(base._num_outputs)]
        return [f"{(self._base or self)._name}_output"]

    def list_auxiliary_states(self):
        return []

    def get_internals(self):
        outs = []
        for node in self._topo():
            if node._op is not None or node._is_group():
                outs.append(node)
            else:
                outs.append(node)
        return Group(outs)

    def _is_group(self):
        return self._op is None and self._inputs and self._name is None

    @property
    def num_outputs(self):
        if self._is_group():
            return len(self._inputs)
        return 1 if self._output_index is not None else self._num_outputs

    def __getitem__(self, index):
        if self._is_group():
            return self._inputs[index]
        if isinstance(index, str):
            for i, nm in enumerate(self.list_outputs()):
                if nm == index or nm == index + "_output":
                    index = i
                    break
            else:
                raise MXNetError(f"no output named {index}")
        if self._num_outputs == 1 and index == 0:
            return self
        if not 0 <= index < self._num_outputs:
            raise IndexError(
                f"output index {index} out of range for {self._name!r} "
                f"with {self._num_outputs} outputs")
        return Symbol(output_index=index, base=self._base or self,
                      name=f"{self._name}[{index}]")

    def __iter__(self):
        for i in range(self.num_outputs):
            yield self[i]

    def __len__(self):
        return self.num_outputs

    # -- arithmetic builds graph nodes ------------------------------------
    def _binary(self, other, op_name, scalar_name, reverse=False):
        from ..ndarray.register import get_op
        if isinstance(other, Symbol):
            ins = [other, self] if reverse else [self, other]
            return _make_node(get_op(op_name), ins, {})
        return _make_node(get_op(scalar_name), [self],
                          {"scalar": float(other), "reverse": reverse})

    def __add__(self, o):
        return self._binary(o, "broadcast_add", "broadcast_add_scalar")

    def __radd__(self, o):
        return self._binary(o, "broadcast_add", "broadcast_add_scalar", True)

    def __sub__(self, o):
        return self._binary(o, "broadcast_sub", "broadcast_sub_scalar")

    def __rsub__(self, o):
        return self._binary(o, "broadcast_sub", "broadcast_sub_scalar", True)

    def __mul__(self, o):
        return self._binary(o, "broadcast_mul", "broadcast_mul_scalar")

    def __rmul__(self, o):
        return self._binary(o, "broadcast_mul", "broadcast_mul_scalar", True)

    def __truediv__(self, o):
        return self._binary(o, "broadcast_div", "broadcast_div_scalar")

    def __rtruediv__(self, o):
        return self._binary(o, "broadcast_div", "broadcast_div_scalar", True)

    def __pow__(self, o):
        return self._binary(o, "broadcast_power", "broadcast_power_scalar")

    def __neg__(self):
        from ..ndarray.register import get_op
        return _make_node(get_op("negative"), [self], {})

    def __eq__(self, o):
        return self._binary(o, "broadcast_equal", "broadcast_equal_scalar")

    def __ne__(self, o):
        return self._binary(o, "broadcast_not_equal", "broadcast_not_equal_scalar")

    def __lt__(self, o):
        return self._binary(o, "broadcast_lesser", "broadcast_lesser_scalar")

    def __le__(self, o):
        return self._binary(o, "broadcast_lesser_equal", "broadcast_lesser_equal_scalar")

    def __gt__(self, o):
        return self._binary(o, "broadcast_greater", "broadcast_greater_scalar")

    def __ge__(self, o):
        return self._binary(o, "broadcast_greater_equal", "broadcast_greater_equal_scalar")

    __hash__ = object.__hash__

    # common tensor methods as graph nodes
    def reshape(self, shape):
        from ..ndarray.register import get_op
        return _make_node(get_op("reshape"), [self], {"shape": shape})

    def transpose(self, axes=None):
        from ..ndarray.register import get_op
        return _make_node(get_op("transpose"), [self], {"axes": axes})

    def sum(self, axis=None, keepdims=False):
        from ..ndarray.register import get_op
        return _make_node(get_op("sum"), [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        from ..ndarray.register import get_op
        return _make_node(get_op("mean"), [self], {"axis": axis, "keepdims": keepdims})

    def astype(self, dtype):
        from ..ndarray.register import get_op
        return _make_node(get_op("Cast"), [self], {"dtype": str(dtype)})

    # -- evaluation --------------------------------------------------------
    def _walk(self, bindings: dict, apply):
        """The one DAG evaluator: traverse base/group/variable/op nodes,
        memoize by node identity, and delegate op application to
        ``apply(op, flat_inputs, raw_attrs)``. Both the eager and the
        jit-traced executors run through here so traversal semantics
        cannot diverge."""
        cache: dict[int, object] = {}

        def ev(node):
            if node._base is not None:
                vals = ev(node._base)
                return vals[node._output_index] if isinstance(vals, (list, tuple)) else vals
            if id(node) in cache:
                return cache[id(node)]
            if node._is_group():
                out = [ev(i) for i in node._inputs]
            elif node._op is None:
                if node._name not in bindings:
                    raise MXNetError(f"unbound argument {node._name!r}")
                out = bindings[node._name]
            else:
                ins = [ev(i) for i in node._inputs]
                flat = []
                for x in ins:
                    flat.extend(x if isinstance(x, (list, tuple)) else [x])
                attrs = {k: v for k, v in node._attrs.items()
                         if not k.startswith("__")}
                out = apply(node._op, flat, attrs)
            cache[id(node)] = out
            return out

        result = ev(self)
        if not isinstance(result, (list, tuple)):
            result = [result]
        return list(result)

    def _eval(self, bindings: dict, training=False):
        """Evaluate the DAG with NDArray bindings (eager per-op dispatch;
        the MXNET_TPU_SYMBOLIC_JIT=0 debug ladder)."""
        from ..ndarray.register import invoke

        return self._walk(bindings, invoke)

    def _eval_raw(self, bindings: dict):
        """Evaluate the DAG with RAW jax arrays through the ops' pure jax
        impls — the jit-traceable walk behind the compiled executor
        (GraphExecutor analog: the whole graph becomes ONE XLA
        computation instead of a per-op engine push). Visible-output
        slicing mirrors invoke(); in-place `mutates` have no meaning on
        traced values and are skipped."""
        from ..ndarray import register as _reg
        from ..ndarray.register import _parse_param

        def apply(op, flat, attrs):
            if _reg._DISPATCH_CAST_HOOK is not None:  # AMP rewrite
                flat = _reg._DISPATCH_CAST_HOOK(op, flat)
            params = {k: _parse_param(v) for k, v in attrs.items()
                      if v is not None}
            from ..ndarray.register import _note_invocation
            _note_invocation(op)
            # stateful ops (Dropout/RNN) gate on the _training kwarg;
            # the eager wrappers inject it at invoke time (ndarray/
            # __init__.py) but this raw-fn walk bypasses them — without
            # the injection Dropout's default _training=True ran dropout
            # in predict-mode executors (caught by the ONNX inception
            # round-trip)
            if "_training" not in params and _accepts_training(op):
                from .. import autograd as _ag
                params["_training"] = _ag.is_training()
            # signature-aware binding: folded scalars that precede a
            # later Symbol arg (op(x, 2.0, y)) must not collide with the
            # positional tensors at call time
            out = _reg.call_op_fn(op, flat, params)
            vis = op.num_visible_outputs
            if vis is not None and isinstance(out, (tuple, list)):
                out = list(out[:vis])
                if len(out) == 1:
                    out = out[0]
            return out

        return self._walk(bindings, apply)

    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx or current_context(), kwargs)
        return ex.forward()

    # -- shape/type inference ---------------------------------------------
    def infer_shape(self, *args, **kwargs):
        """(arg_shapes, out_shapes, aux_shapes).

        The nnvm InferShape analog: forward-propagates shapes through the
        DAG; parameter (variable) shapes of the standard layer ops are
        back-filled from their data input via per-op hint rules (the
        reference's per-op FInferShape), then node outputs come from
        jax.eval_shape on the op impl — XLA's shape rules do the rest.
        """
        import jax

        arg_names = self.list_arguments()
        if args:
            kwargs = dict(zip(arg_names, args))
        known = {k: tuple(int(d) for d in v) for k, v in kwargs.items()
                 if v is not None}

        nodes = self._topo()
        out_shapes_by_node: dict[int, list] = {}

        def input_shapes(node):
            shapes = []
            for i in node._inputs:
                base = i._base or i
                if base._op is None and not base._is_group():
                    shapes.append(known.get(base._name))
                else:
                    outs = out_shapes_by_node.get(id(base))
                    if outs is None:
                        shapes.append(None)
                    else:
                        shapes.append(outs[i._output_index or 0])
            return shapes

        progress = True
        while progress:
            progress = False
            for node in nodes:
                if node._op is None or id(node) in out_shapes_by_node:
                    continue
                in_shapes = input_shapes(node)
                if any(s is None for s in in_shapes):
                    hint = _PARAM_SHAPE_HINTS.get(node._op.name)
                    if hint is not None:
                        filled = hint(in_shapes, node._attrs)
                        for idx, shape in (filled or {}).items():
                            src = node._inputs[idx]
                            base = src._base or src
                            if base._op is None and base._name not in known \
                                    and shape is not None:
                                known[base._name] = tuple(int(d) for d in shape)
                                progress = True
                    in_shapes = input_shapes(node)
                    if any(s is None for s in in_shapes):
                        continue
                params = {k: _parse_attr(v) for k, v in node._attrs.items()
                          if not k.startswith("__")}
                try:
                    from ..ndarray.register import call_op_fn
                    structs = [jax.ShapeDtypeStruct(s, np.float32)
                               for s in in_shapes]
                    out = jax.eval_shape(
                        lambda *xs: _sym_note(node._op, call_op_fn(
                            node._op, xs, params)), *structs)
                # not a worker loop: this fixpoint PROBES eval_shape per
                # node, and "this node won't infer yet" is the expected
                # negative — skip and let iteration retry
                except Exception:  # mxlint: disable=silent-except
                    continue
                if not isinstance(out, (tuple, list)):
                    out = [out]
                out_shapes_by_node[id(node)] = [tuple(o.shape) for o in out]
                progress = True

        arg_shapes = [known.get(n) for n in arg_names]
        if any(s is None for s in arg_shapes):
            return None, None, None
        base = self._base or self
        if self._is_group():
            outs = []
            for i in self._inputs:
                b = i._base or i
                node_outs = out_shapes_by_node.get(id(b))
                if node_outs is None and b._op is None:
                    # variable member: its "output" is its own shape
                    node_outs = [known.get(b._name)]
                outs.append(None if node_outs is None
                            else node_outs[i._output_index or 0])
        else:
            node_outs = out_shapes_by_node.get(id(base))
            if node_outs is None and base._op is None:
                node_outs = [known.get(base._name)]
            outs = [None if node_outs is None
                    else node_outs[self._output_index or 0]]
        return arg_shapes, outs, []

    def infer_shape_partial(self, *args, **kwargs):
        try:
            return self.infer_shape(*args, **kwargs)
        except Exception:
            return None, None, None

    def infer_type(self, *args, **kwargs):
        arg_names = self.list_arguments()
        dts = [np.float32 for _ in arg_names]
        return dts, [np.float32], []

    # -- binding -----------------------------------------------------------
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    stype_dict=None, group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, **kwargs):
        from ..executor import Executor
        from .. import ndarray as nd

        ctx = ctx or current_context()
        arg_shapes, _, _ = self.infer_shape(**kwargs)
        if arg_shapes is None:
            raise MXNetError(f"simple_bind needs shapes for all arguments "
                             f"({self.list_arguments()}), got {kwargs}")
        args = {}
        for name, shape in zip(self.list_arguments(), arg_shapes):
            dt = (type_dict or {}).get(name, "float32")
            args[name] = nd.zeros(shape, ctx=ctx, dtype=dt)
        return self.bind(ctx, args, grad_req=grad_req)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from ..executor import Executor
        return Executor(self, ctx or current_context(), args or {},
                        args_grad, grad_req, aux_states)

    # -- gradient ----------------------------------------------------------
    def simple_gradient(self, wrt):
        raise MXNetError("use Executor.backward (autograd-based)")

    # -- serialization (nnvm JSON schema) ----------------------------------
    def tojson(self):
        nodes = []
        node_ids = {}
        arg_nodes = []
        for node in self._topo():
            nid = len(nodes)
            node_ids[id(node)] = nid
            if node._op is None and not node._is_group():
                arg_nodes.append(nid)
                nodes.append({"op": "null", "name": node._name or f"arg{nid}",
                              "attrs": {k: str(v) for k, v in node._attrs.items()},
                              "inputs": []})
            elif node._is_group():
                continue
            else:
                nodes.append({
                    "op": node._op.name,
                    "name": node._name or f"{node._op.name.lower()}{nid}",
                    "attrs": {k: _attr_str(v) for k, v in node._attrs.items()},
                    "inputs": [[node_ids[id(i._base or i)],
                                i._output_index or 0, 0] for i in node._inputs],
                })
        if self._is_group():
            heads = [[node_ids[id(i._base or i)], i._output_index or 0, 0]
                     for i in self._inputs]
        else:
            base = self._base or self
            heads = [[node_ids[id(base)], self._output_index or 0, 0]]
        return json.dumps({"nodes": nodes, "arg_nodes": arg_nodes,
                           "node_row_ptr": list(range(len(nodes) + 1)),
                           "heads": heads,
                           "attrs": {"mxnet_version": ["int", 10600]}},
                          indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    def __repr__(self):
        if self._op is None and not self._inputs:
            return f"<Symbol {self._name}>"
        return f"<Symbol {self._name or (self._op.name if self._op else 'group')}>"


def _parse_attr(v):
    import ast
    if isinstance(v, str):
        try:
            return ast.literal_eval(v)
        except (ValueError, SyntaxError):
            return v
    return v


def _np_prod(t):
    out = 1
    for d in t:
        out *= d
    return out


def _tupleize(v, n=None):
    if v is None:
        return None
    v = _parse_attr(v)
    if isinstance(v, int):
        return (v,) * (n or 1)
    return tuple(v)


# per-op parameter-shape back-fill (FInferShape analog for the layer ops
# whose weight shapes derive from the data input)
def _hint_fully_connected(in_shapes, attrs):
    data = in_shapes[0]
    if data is None:
        return {}
    nh = int(_parse_attr(attrs.get("num_hidden", 0)))
    flatten = _parse_attr(attrs.get("flatten", True))
    in_units = _np_prod(data[1:]) if flatten else data[-1]
    out = {1: (nh, in_units)}
    if len(in_shapes) > 2:
        out[2] = (nh,)
    return out


def _hint_convolution(in_shapes, attrs):
    data = in_shapes[0]
    if data is None:
        return {}
    kernel = _tupleize(attrs.get("kernel"))
    nf = int(_parse_attr(attrs.get("num_filter", 0)))
    ng = int(_parse_attr(attrs.get("num_group", 1)))
    out = {1: (nf, data[1] // ng) + kernel}
    if len(in_shapes) > 2:
        out[2] = (nf,)
    return out


def _hint_deconvolution(in_shapes, attrs):
    data = in_shapes[0]
    if data is None:
        return {}
    kernel = _tupleize(attrs.get("kernel"))
    nf = int(_parse_attr(attrs.get("num_filter", 0)))
    ng = int(_parse_attr(attrs.get("num_group", 1)))
    out = {1: (data[1], nf // ng) + kernel}
    if len(in_shapes) > 2:
        out[2] = (nf,)
    return out


def _hint_channel_params(in_shapes, attrs):
    data = in_shapes[0]
    if data is None:
        return {}
    axis = int(_parse_attr(attrs.get("axis", 1)))
    c = data[axis % len(data)]
    return {i: (c,) for i in range(1, len(in_shapes))}


def _hint_layer_norm(in_shapes, attrs):
    data = in_shapes[0]
    if data is None:
        return {}
    axis = int(_parse_attr(attrs.get("axis", -1)))
    c = data[axis % len(data)]
    return {1: (c,), 2: (c,)}


def _hint_embedding(in_shapes, attrs):
    return {1: (int(_parse_attr(attrs.get("input_dim", 0))),
                int(_parse_attr(attrs.get("output_dim", 0))))}


def _hint_softmax_label(in_shapes, attrs):
    # label = data shape minus the class axis (reference back-infers it)
    data = in_shapes[0]
    if data is None:
        return {}
    if _parse_attr(attrs.get("multi_output", False)):
        # reference infers the FLATTENED spatial label (n, d1*...*dk)
        n = 1
        for d in data[2:]:
            n *= d
        return {1: (data[0], n) if len(data) > 2 else (data[0],)}
    return {1: tuple(data[:-1])}


def _hint_regression_label(in_shapes, attrs):
    data = in_shapes[0]
    if data is None:
        return {}
    return {1: tuple(data)}


_PARAM_SHAPE_HINTS = {
    "FullyConnected": _hint_fully_connected,
    "Convolution": _hint_convolution,
    "Deconvolution": _hint_deconvolution,
    "BatchNorm": _hint_channel_params,
    "InstanceNorm": _hint_channel_params,
    "GroupNorm": _hint_channel_params,
    "LayerNorm": _hint_layer_norm,
    "Embedding": _hint_embedding,
    "SoftmaxOutput": _hint_softmax_label,
    "LinearRegressionOutput": _hint_regression_label,
    "LogisticRegressionOutput": _hint_regression_label,
    "MAERegressionOutput": _hint_regression_label,
}


def _sym_note(op, out):
    # record only AFTER op.fn succeeded — a broken op must not satisfy
    # the coverage gate just by appearing in a shape-inference graph
    from ..ndarray.register import _note_invocation
    _note_invocation(op)
    return out


def _attr_str(v):
    if isinstance(v, (tuple, list)):
        return str(tuple(v))
    return str(v)


def _make_node(op, inputs, params, name=None):
    params = {k: v for k, v in params.items() if v is not None}
    name = NameManager.current().get(name, op.name.lower())
    attrs = AttrScope.current().get(None) if AttrScope.current() else {}
    merged = dict(attrs)
    merged.update(params)
    nout = 1
    if op.num_visible_outputs is not None:
        nout = op.num_visible_outputs
    if "num_outputs" in params and getattr(op, "dynamic_arity", False):
        # dynamic-arity ops (split/SliceChannel/amp_multicast, flagged
        # dynamic_arity=True at registration): the output count IS the
        # param — without this, sym[0] on a split returns the whole
        # tuple-producing node and the consumer gets every output
        # splatted as positional inputs. Ops without the flag keep
        # their registered arity even if a param happens to share the
        # name.
        try:
            nout = int(params["num_outputs"])
        except (TypeError, ValueError):
            pass
    if getattr(op, "infer_num_outputs", None) is not None:
        # param-dependent arity (mx.operator Custom: output count comes
        # from the registered CustomOpProp's list_outputs()). Params may
        # arrive JSON-stringified (load_json) — parse before counting,
        # or split((1,3)) graphs crash on reload (int('(1, 3)')).
        from ..ndarray.register import _parse_param
        nout = int(op.infer_num_outputs(
            {k: _parse_param(v) for k, v in params.items()
             if v is not None}))
    return Symbol(op=op, inputs=inputs, attrs=merged, name=name,
                  num_outputs=nout)


def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs):
    attrs = dict(attr or {})
    if shape is not None:
        attrs["__shape__"] = str(tuple(shape))
    if dtype is not None:
        attrs["__dtype__"] = str(dtype)
    if lr_mult is not None:
        attrs["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        attrs["__wd_mult__"] = str(wd_mult)
    if init is not None:
        attrs["__init__"] = init if isinstance(init, str) else init.dumps()
    return Symbol(name=name, attrs=attrs)


var = Variable


def Group(symbols):
    g = Symbol(inputs=list(symbols))
    return g


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def load_json(json_str):
    """Rebuild a Symbol DAG from nnvm-schema JSON."""
    from ..ndarray.register import get_op

    data = json.loads(json_str)
    nodes = data["nodes"]
    built: list[Symbol] = []

    def pick(src, out_idx):
        # output 0 of a MULTI-output node still needs a selector —
        # returning the bare node would splat every output (caught by
        # the sym.np.split json round-trip)
        if src.num_outputs > 1:
            return src[out_idx]
        return src if out_idx == 0 else src[out_idx]

    for n in nodes:
        if n["op"] == "null":
            built.append(Variable(n["name"], attr=n.get("attrs", {})))
        else:
            ins = []
            for nid, out_idx, _ in n["inputs"]:
                ins.append(pick(built[nid], out_idx))
            attrs = n.get("attrs", n.get("param", {}))
            sym = _make_node(get_op(n["op"]), ins, dict(attrs), name=n["name"])
            built.append(sym)
    heads = data.get("heads", [[len(built) - 1, 0, 0]])
    outs = [pick(built[nid], out_idx) for nid, out_idx, _ in heads]
    return outs[0] if len(outs) == 1 else Group(outs)


# creation-style symbol fns
def zeros(shape, dtype="float32", **kwargs):
    from ..ndarray.register import get_op
    v = Variable(NameManager.current().get(None, "zeros"))
    return _make_node(get_op("zeros_like"), [v], {})


def ones(shape, dtype="float32", **kwargs):
    from ..ndarray.register import get_op
    v = Variable(NameManager.current().get(None, "ones"))
    return _make_node(get_op("ones_like"), [v], {})


def arange(start, stop=None, step=1.0, repeat=1, name=None, dtype="float32"):
    raise MXNetError("symbol.arange: use arange_like or provide data")


# ops whose parameter inputs are auto-created as variables when omitted
# (reference: symbol composition auto-creates {name}_weight etc. for any
# unfilled op input; here declared per layer op)
_OP_INPUT_NAMES = {
    "FullyConnected": ["data", "weight", "bias"],
    "Convolution": ["data", "weight", "bias"],
    "Deconvolution": ["data", "weight", "bias"],
    "BatchNorm": ["data", "gamma", "beta", "moving_mean", "moving_var"],
    "LayerNorm": ["data", "gamma", "beta"],
    "InstanceNorm": ["data", "gamma", "beta"],
    "GroupNorm": ["data", "gamma", "beta"],
    "Embedding": ["data", "weight"],
    "LeakyReLU": ["data", "gamma"],
    "RNN": ["data", "parameters", "state", "state_cell"],
    # loss heads: the label input is auto-created as {name}_label when
    # omitted (reference: SoftmaxOutput's FListInputNames + symbol
    # composition), which is how Module's label binding finds it
    "SoftmaxOutput": ["data", "label"],
    "LinearRegressionOutput": ["data", "label"],
    "LogisticRegressionOutput": ["data", "label"],
    "MAERegressionOutput": ["data", "label"],
}


def _populate_symbol_ops(module):
    """Generate mx.sym.<op> builders from the shared registry."""
    from ..ndarray.register import _OPS

    def make(op):
        static_input_names = _OP_INPUT_NAMES.get(op.name)

        def positional_names():
            # op-fn parameter names in declaration order, for folding
            # scalar positional args (sym.clip(x, 0, 6)) into attrs —
            # raw scalars in _inputs would break every graph walker
            names = _POSITIONAL_NAMES.get(op.name)
            if names is None:
                import inspect
                try:
                    names = []
                    for p in inspect.signature(op.fn).parameters.values():
                        if p.kind not in (p.POSITIONAL_ONLY,
                                          p.POSITIONAL_OR_KEYWORD):
                            break  # *args/keyword-only: unmappable
                        names.append(p.name)
                except (TypeError, ValueError):
                    names = []
                _POSITIONAL_NAMES[op.name] = names
            return names

        def sym_fn(*args, **kwargs):
            name = kwargs.pop("name", None)
            if any(not isinstance(a, (Symbol, type(None))) for a in args):
                pos = positional_names()
                folded = []
                extra = {}
                for i, a in enumerate(args):
                    if isinstance(a, (Symbol, type(None))):
                        folded.append(a)
                    elif i < len(pos):
                        extra[pos[i]] = a
                    else:
                        raise MXNetError(
                            f"sym.{op.name}: positional argument {i} "
                            f"({a!r}) is neither a Symbol nor mappable "
                            "to a keyword parameter")
                args = tuple(folded)
                extra.update(kwargs)
                kwargs = extra
            input_names = static_input_names
            if input_names is None and \
                    getattr(op, "infer_input_names", None) is not None:
                # param-dependent input names (Custom: the prop's
                # list_arguments()) — lets tensor kwargs bind by name
                # in the declared order, and missing ones auto-create
                # variables (label binding for Module)
                input_names = op.infer_input_names(
                    {k: v for k, v in kwargs.items()
                     if not isinstance(v, Symbol)})
            rest = {}
            named_inputs = {}
            inputs = list(args)
            for k, v in kwargs.items():
                if isinstance(v, Symbol):
                    if input_names and k in input_names:
                        named_inputs[k] = v
                    else:
                        inputs.append(v)
                else:
                    rest[k] = v
            if input_names:
                name = NameManager.current().get(name, op.name.lower())
                no_bias = bool(_parse_attr(rest.get("no_bias", False)))
                full = []
                it = iter(inputs)
                for i, in_name in enumerate(input_names):
                    if in_name in named_inputs:
                        full.append(named_inputs[in_name])
                        continue
                    nxt = next(it, None)
                    if nxt is not None:
                        full.append(nxt)
                        continue
                    if in_name == "bias" and no_bias:
                        continue
                    if op.name == "LeakyReLU" and in_name == "gamma" and \
                            rest.get("act_type", "leaky") != "prelu":
                        continue
                    if op.name == "RNN" and in_name == "state_cell" and \
                            rest.get("mode") != "lstm":
                        continue
                    full.append(Variable(f"{name}_{in_name}"))
                return _make_node(op, full, rest, name=name)
            return _make_node(op, inputs, rest, name=name)

        sym_fn.__name__ = op.name
        sym_fn.__doc__ = op.fn.__doc__
        return sym_fn

    seen = {}
    for nm, op in _OPS.items():
        if nm not in seen:
            seen[nm] = True
            setattr(module, nm, make(op))
