"""mx.sym.random — symbolic sampling namespace."""
from __future__ import annotations

from .symbol import _make_node
from ..ndarray.register import get_op


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", name=None, **kwargs):
    return _make_node(get_op("random_uniform"), [],
                      {"low": low, "high": high, "shape": shape, "dtype": dtype},
                      name=name)


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", name=None, **kwargs):
    return _make_node(get_op("random_normal"), [],
                      {"loc": loc, "scale": scale, "shape": shape, "dtype": dtype},
                      name=name)
