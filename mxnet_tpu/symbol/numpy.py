"""``mx.sym.np`` — symbolic deep-NumPy namespace (op-backed subset).

Analog of the reference's ``python/mxnet/symbol/numpy/`` (v>=1.6):
NumPy-style graph building over the same registry ops the eager
``mx.np`` frontend dispatches. Coverage contract: every mx.np function
that lowers to ONE registry op is available symbolically (unaries,
binaries with python-scalar lifting via the ``_constant`` op,
reductions, single-op manipulation, contractions, np.linalg), and the
STATICALLY-shaped compositions (split family, meshgrid, the stack
helpers, atleast_*, broadcast_arrays, interp, around, average,
quantile/percentile) lower to dedicated registry ops with real
multi-output selectors. Only the value-dependent-shape functions
(nonzero/unique/histogram/bincount/argwhere) raise, with a pointer to
eager mx.np.
"""
from __future__ import annotations

import sys as _sys

from ..ndarray.register import get_op
from .symbol import Symbol, _make_node

__all__ = []


def _lift(x, ref_name):
    """Symbols pass through; python scalars become _constant nodes
    (symbolic graphs cannot hold runtime values). Scalar dtype follows
    the python type so integer ops (shifts/bitwise) stay integer and
    int arrays aren't silently promoted to float."""
    if isinstance(x, Symbol):
        return x
    if isinstance(x, bool):
        return _make_node(get_op("_constant"), [],
                          {"value": int(x), "dtype": "int32"}, name=None)
    if isinstance(x, int):
        return _make_node(get_op("_constant"), [],
                          {"value": x, "dtype": "int32"}, name=None)
    if isinstance(x, float):
        return _make_node(get_op("_constant"), [],
                          {"value": x, "dtype": "float32"}, name=None)
    raise TypeError(
        f"sym.np.{ref_name}: expected Symbol or python scalar, got "
        f"{type(x).__name__} (symbolic graphs cannot embed runtime "
        f"arrays — use mx.sym.zeros/ones/arange or hybridize)")


def _sfn(fname, opname, n_in=1, pos_params=()):
    """Build a symbolic np function: first ``n_in`` positionals are
    tensor inputs (scalar-lifted), further positionals bind to
    ``pos_params`` names, keywords pass through as op params."""

    def f(*args, name=None, **params):
        if len(args) < n_in:
            raise TypeError(
                f"sym.np.{fname} needs {n_in} tensor argument(s), "
                f"got {len(args)}")
        inputs = [_lift(a, fname) for a in args[:n_in]]
        extra = args[n_in:]
        if len(extra) > len(pos_params):
            raise TypeError(
                f"sym.np.{fname}: too many positional arguments")
        for pname, val in zip(pos_params, extra):
            params.setdefault(pname, val)
        return _make_node(get_op(opname), inputs, params, name=name)

    f.__name__ = fname
    f.__doc__ = f"Symbolic numpy.{fname}: registry op {opname}."
    return f


def _not_composable(fname):
    def f(*args, **kwargs):
        raise NotImplementedError(
            f"sym.np.{fname} is Python-composed in the eager frontend "
            f"and has no single-op symbolic lowering — hybridize the "
            f"block instead (the compiled path supports all of mx.np)")
    f.__name__ = fname
    return f


_mod = _sys.modules[__name__]


def _install(fname, fn):
    setattr(_mod, fname, fn)
    __all__.append(fname)


# unary + binary tables are shared with the eager frontend — the op
# mapping is the single source of truth
from ..numpy.multiarray import _UNARY_TABLE, _BINARY_TABLE  # noqa: E402

for _f, _o in _UNARY_TABLE.items():
    _install(_f, _sfn(_f, _o, n_in=1))
for _f, _o in _BINARY_TABLE.items():
    _install(_f, _sfn(_f, _o, n_in=2))

# reductions (axis/keepdims ride as params)
for _f, _o in {
    "sum": "sum", "mean": "mean", "prod": "prod", "max": "max",
    "min": "min", "amax": "max", "amin": "min", "nansum": "nansum",
    "nanprod": "nanprod", "cumsum": "cumsum",
    "std": "_npi_std", "var": "_npi_var", "median": "_npi_median",
    "ptp": "_npi_ptp", "all": "_npi_all", "any": "_npi_any",
    "count_nonzero": "_npi_count_nonzero", "cumprod": "_npi_cumprod",
    "nanmax": "_npi_nanmax", "nanmin": "_npi_nanmin",
    "nanmean": "_npi_nanmean", "diff": "_npi_diff",
}.items():
    _install(_f, _sfn(_f, _o, n_in=1, pos_params=("axis",)))

# manipulation (single-op)
_install("reshape", _sfn("reshape", "reshape", 1, ("shape",)))
_install("transpose", _sfn("transpose", "transpose", 1, ("axes",)))
_install("expand_dims", _sfn("expand_dims", "expand_dims", 1, ("axis",)))
_install("squeeze", _sfn("squeeze", "squeeze", 1, ("axis",)))
_install("broadcast_to", _sfn("broadcast_to", "_npi_broadcast_to", 1,
                              ("shape",)))
_install("tile", _sfn("tile", "tile", 1, ("reps",)))
_install("repeat", _sfn("repeat", "repeat", 1, ("repeats", "axis")))
_install("flip", _sfn("flip", "flip", 1, ("axis",)))
_install("roll", _sfn("roll", "_npi_roll", 1, ("shift", "axis")))
_install("rot90", _sfn("rot90", "_npi_rot90", 1, ("k", "axes")))
_install("moveaxis", _sfn("moveaxis", "_npi_moveaxis", 1,
                          ("source", "destination")))
_install("tril", _sfn("tril", "_npi_tril", 1, ("k",)))
_install("triu", _sfn("triu", "_npi_triu", 1, ("k",)))
_install("trace", _sfn("trace", "_npi_trace", 1,
                       ("offset", "axis1", "axis2")))
_install("diagonal", _sfn("diagonal", "_npi_diagonal", 1,
                          ("offset", "axis1", "axis2")))
_install("diagflat", _sfn("diagflat", "_npi_diagflat", 1, ("k",)))
_install("clip", _sfn("clip", "clip", 1, ("a_min", "a_max")))
_install("take", _sfn("take", "take", 2, ("axis",)))
_install("take_along_axis", _sfn("take_along_axis", "_npi_take_along_axis",
                                 2, ("axis",)))
_install("searchsorted", _sfn("searchsorted", "_npi_searchsorted", 2,
                              ("side",)))
_install("pad", _sfn("pad", "_npi_pad", 1, ("pad_width", "mode")))
_install("sort", _sfn("sort", "sort", 1, ("axis",)))
_install("argsort", _sfn("argsort", "argsort", 1, ("axis",)))
_install("argmax", _sfn("argmax", "argmax", 1, ("axis",)))
_install("argmin", _sfn("argmin", "argmin", 1, ("axis",)))


def where(condition, x, y, name=None):
    return _make_node(get_op("_npi_where"),
                      [_lift(condition, "where"), _lift(x, "where"),
                       _lift(y, "where")], {}, name=name)


_install("where", where)


def concatenate(seq, axis=0, name=None):
    syms = [_lift(s, "concatenate") for s in seq]
    if axis is None:
        # numpy: flatten every input first, then join along axis 0
        syms = [_make_node(get_op("reshape"), [s], {"shape": (-1,)})
                for s in syms]
        axis = 0
    return _make_node(get_op("concat"), syms, {"dim": axis}, name=name)


_install("concatenate", concatenate)


def stack(arrays, axis=0, name=None):
    return _make_node(get_op("stack"), [_lift(a, "stack") for a in arrays],
                      {"axis": axis}, name=name)


_install("stack", stack)

# contractions
_install("dot", _sfn("dot", "_npi_dot", 2))
_install("matmul", _sfn("matmul", "_npi_matmul", 2))
_install("inner", _sfn("inner", "_npi_inner", 2))
_install("outer", _sfn("outer", "_npi_outer", 2))
_install("vdot", _sfn("vdot", "_npi_vdot", 2))
_install("kron", _sfn("kron", "_npi_kron", 2))
_install("cross", _sfn("cross", "_npi_cross", 2, ("axis",)))
_install("tensordot", _sfn("tensordot", "_npi_tensordot", 2, ("axes",)))


def einsum(subscripts, *operands, name=None, **params):
    return _make_node(get_op("_npi_einsum"),
                      [_lift(o, "einsum") for o in operands],
                      {"subscripts": subscripts, **params}, name=name)


_install("einsum", einsum)

# dynamic-output-shape ops: single-op but value-dependent result
# shapes, which the jitted symbolic executor cannot bind — excluded
# with an accurate message (eager mx.np supports them)
def _dynamic_shape(fname):
    def f(*args, **kwargs):
        raise NotImplementedError(
            f"sym.np.{fname}: output shape depends on VALUES "
            f"(dynamic), which symbolic graph execution cannot bind — "
            f"use eager mx.np.{fname}")
    f.__name__ = fname
    return f


for _f in ("argwhere",):
    _install(_f, _dynamic_shape(_f))

# truly dynamic compositions (output shape depends on VALUES): clear
# error, not AttributeError
for _f in ("nonzero", "flatnonzero", "unique", "histogram", "bincount"):
    _install(_f, _not_composable(_f))


# statically-shaped compositions lower to dedicated registry ops
# (numpy/ops.py round-5 tail) — real symbolic output selectors for the
# multi-output ones (split/meshgrid/broadcast_arrays)
def _seq_fn(fname, opname):
    def f(seq, name=None):
        return _make_node(get_op(opname),
                          [_lift(s, fname) for s in seq], {}, name=name)
    f.__name__ = fname
    return f


for _f in ("vstack", "hstack", "dstack", "column_stack"):
    _install(_f, _seq_fn(_f, f"_npi_{_f}"))


def _split_fn(fname, axis_fixed=None):
    op = "_npi_array_split" if fname == "array_split" else "_npi_split_np"

    def f(ary, indices_or_sections, axis=0, name=None):
        if axis_fixed is not None and axis != 0:
            # numpy's vsplit/hsplit/dsplit take NO axis argument —
            # silently splitting on the fixed axis anyway would discard
            # the caller's intent
            raise TypeError(f"sym.np.{fname} does not accept axis "
                            f"(it always splits axis {axis_fixed})")
        ios = (tuple(int(i) for i in indices_or_sections)
               if isinstance(indices_or_sections, (list, tuple))
               else int(indices_or_sections))
        return _make_node(get_op(op), [_lift(ary, fname)],
                          {"indices_or_sections": ios,
                           "axis": axis_fixed if axis_fixed is not None
                           else axis}, name=name)
    f.__name__ = fname
    if axis_fixed is not None and axis_fixed > 0:
        f.__doc__ = (f"Symbolic numpy.{fname}; assumes input rank > "
                     f"{axis_fixed} (symbols carry no rank).")
    return f


_install("split", _split_fn("split"))
_install("array_split", _split_fn("array_split"))
_install("vsplit", _split_fn("vsplit", axis_fixed=0))
_install("hsplit", _split_fn("hsplit", axis_fixed=1))
_install("dsplit", _split_fn("dsplit", axis_fixed=2))


def meshgrid(*xi, indexing="xy", name=None):
    return _make_node(get_op("_npi_meshgrid"),
                      [_lift(x, "meshgrid") for x in xi],
                      {"indexing": indexing, "num_outputs": len(xi)},
                      name=name)


_install("meshgrid", meshgrid)


def broadcast_arrays(*args, name=None):
    return _make_node(get_op("_npi_broadcast_arrays"),
                      [_lift(a, "broadcast_arrays") for a in args],
                      {"num_outputs": len(args)}, name=name)


_install("broadcast_arrays", broadcast_arrays)

for _f in ("atleast_1d", "atleast_2d", "atleast_3d"):
    _install(_f, _sfn(_f, f"_npi_{_f}", 1))
_install("interp", _sfn("interp", "_npi_interp", 3, ("left", "right")))
_install("around", _sfn("around", "_npi_around", 1, ("decimals",)))
_install("quantile", _sfn("quantile", "_npi_quantile", 1, ("q", "axis")))
_install("percentile", _sfn("percentile", "_npi_percentile", 1,
                            ("q", "axis")))


def average(a, axis=None, weights=None, name=None):
    inputs = [_lift(a, "average")]
    if weights is not None:
        inputs.append(_lift(weights, "average"))
    return _make_node(get_op("_npi_average"), inputs, {"axis": axis},
                      name=name)


_install("average", average)


def __getattr__(attr):
    """Unknown names raise AttributeError carrying the pointer-at-
    hybridize message (eager mx.np has many functions with no single-op
    symbolic lowering — creation fns, composed helpers). AttributeError
    — not NotImplementedError — so hasattr()/getattr(..., default)
    introspection keeps working."""
    raise AttributeError(
        f"sym.np.{attr} has no symbolic lowering — hybridize the block "
        f"instead (the compiled path supports all of mx.np), or use "
        f"mx.sym.zeros/ones/arange for symbolic creation")


class _SymLinalg:
    """sym.np.linalg — symbolic lowering of the _npi linalg ops."""

    norm = staticmethod(_sfn("norm", "_npi_norm", 1, ("ord", "axis")))
    svd = staticmethod(_sfn("svd", "_npi_svd", 1))
    inv = staticmethod(_sfn("inv", "_npi_inv", 1))
    pinv = staticmethod(_sfn("pinv", "_npi_pinv", 1, ("rcond",)))
    det = staticmethod(_sfn("det", "_npi_det", 1))
    slogdet = staticmethod(_sfn("slogdet", "_npi_slogdet", 1))
    eigh = staticmethod(_sfn("eigh", "_npi_eigh", 1))
    eigvalsh = staticmethod(_sfn("eigvalsh", "_npi_eigvalsh", 1))
    qr = staticmethod(_sfn("qr", "_npi_qr", 1))
    cholesky = staticmethod(_sfn("cholesky", "_npi_cholesky", 1))
    solve = staticmethod(_sfn("solve", "_npi_solve", 2))
    matrix_power = staticmethod(_sfn("matrix_power", "_npi_matrix_power",
                                     1, ("n",)))


linalg = _SymLinalg()
__all__.append("linalg")
