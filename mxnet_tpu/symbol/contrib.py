"""mx.sym.contrib namespace — symbolic control flow + contrib ops."""
from __future__ import annotations

from .symbol import _make_node
from ..ndarray.register import get_op


def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None, name=None):
    return _make_node(get_op("_arange_like"), [data],
                      {"start": start, "step": step, "repeat": repeat,
                       "axis": axis}, name=name)
