"""mx.sym.contrib namespace — symbolic control flow + contrib ops."""
from __future__ import annotations

from .symbol import _make_node
from ..ndarray.register import get_op


def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None, name=None):
    return _make_node(get_op("_arange_like"), [data],
                      {"start": start, "step": step, "repeat": repeat,
                       "axis": axis}, name=name)


def _contrib_sym(op_name):
    def f(*inputs, name=None, **params):
        return _make_node(get_op(op_name), list(inputs), params, name=name)
    f.__name__ = op_name.replace("_contrib_", "")
    return f


MultiBoxPrior = _contrib_sym("_contrib_MultiBoxPrior")
MultiBoxTarget = _contrib_sym("_contrib_MultiBoxTarget")
MultiBoxDetection = _contrib_sym("_contrib_MultiBoxDetection")
box_nms = _contrib_sym("_contrib_box_nms")
box_iou = _contrib_sym("_contrib_box_iou")
bipartite_matching = _contrib_sym("_contrib_bipartite_matching")

ROIAlign = _contrib_sym("_contrib_ROIAlign")
BilinearResize2D = _contrib_sym("_contrib_BilinearResize2D")
AdaptiveAvgPooling2D = _contrib_sym("_contrib_AdaptiveAvgPooling2D")
box_decode = _contrib_sym("_contrib_box_decode")
box_encode = _contrib_sym("_contrib_box_encode")
DeformableConvolution = _contrib_sym("_contrib_DeformableConvolution")
PSROIPooling = _contrib_sym("_contrib_PSROIPooling")
