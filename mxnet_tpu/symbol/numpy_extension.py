"""``mx.sym.npx`` — symbolic numpy-extension namespace.

Mirrors the eager ``mx.npx`` nn-op surface for graph building
(reference python/mxnet/symbol/numpy_extension/): each function lowers
to the same registry op under NumPy-style lowercase names."""
from __future__ import annotations

import sys as _sys

from ..ndarray.register import get_op
from .symbol import Symbol, _make_node

__all__ = []

_NPX_OPS = {
    "relu": "relu", "sigmoid": "sigmoid", "log_sigmoid": "log_sigmoid",
    "softmax": "softmax", "log_softmax": "log_softmax",
    "softmin": "softmin", "activation": "Activation",
    "leaky_relu": "LeakyReLU", "gelu": "gelu", "erf": "erf",
    "erfinv": "erfinv", "gamma": "gamma", "gammaln": "gammaln",
    "digamma": "digamma", "smooth_l1": "smooth_l1",
    "batch_dot": "batch_dot", "fully_connected": "FullyConnected",
    "convolution": "Convolution", "deconvolution": "Deconvolution",
    "pooling": "Pooling", "dropout": "Dropout", "embedding": "Embedding",
    "batch_norm": "BatchNorm", "layer_norm": "LayerNorm",
    "group_norm": "GroupNorm", "instance_norm": "InstanceNorm",
    "l2_normalization": "L2Normalization", "rnn": "RNN",
    "roi_pooling": "ROIPooling", "ctc_loss": "ctc_loss",
    "one_hot": "one_hot", "pick": "pick", "topk": "topk",
    "gather_nd": "gather_nd", "scatter_nd": "scatter_nd",
    "arange_like": "arange_like", "broadcast_like": "broadcast_like",
    "sequence_mask": "SequenceMask", "reshape": "reshape",
    "reshape_like": "reshape_like",
    "multibox_prior": "_contrib_MultiBoxPrior",
    "multibox_target": "_contrib_MultiBoxTarget",
    "multibox_detection": "_contrib_MultiBoxDetection",
    "box_nms": "_contrib_box_nms", "box_iou": "_contrib_box_iou",
}

_mod = _sys.modules[__name__]


def _make(fname, opname):
    def f(*args, name=None, **params):
        bad = [a for a in args
               if not (isinstance(a, Symbol) or a is None)]
        if bad:
            raise TypeError(
                f"sym.npx.{fname}: positional argument of type "
                f"{type(bad[0]).__name__} is not a tensor input — pass "
                f"op parameters as keywords")
        inputs = list(args)
        # trailing optional inputs (e.g. bias=None) drop like eager
        while inputs and inputs[-1] is None:
            inputs.pop()
        if any(i is None for i in inputs):
            raise TypeError(
                f"sym.npx.{fname}: only TRAILING tensor inputs may be None")
        return _make_node(get_op(opname), inputs, params, name=name)

    f.__name__ = fname
    f.__doc__ = f"Symbolic npx.{fname}: registry op {opname}."
    return f


for _f, _o in _NPX_OPS.items():
    setattr(_mod, _f, _make(_f, _o))
    __all__.append(_f)


def reshape(data, newshape=None, reverse=False, name=None, **params):
    """Symbolic npx.reshape: same signature as the eager one
    (newshape maps to the op's ``shape`` param; special codes apply)."""
    if newshape is None:
        newshape = params.pop("shape", None)
    if newshape is None:
        raise TypeError("sym.npx.reshape requires newshape")
    return _make_node(get_op("reshape"), [data],
                      {"shape": tuple(newshape), "reverse": reverse},
                      name=name)


__all__.append("reshape")


def __getattr__(attr):
    # AttributeError (not NotImplementedError) keeps hasattr/getattr
    # introspection semantics while preserving the pointer message
    raise AttributeError(
        f"sym.npx.{attr} has no symbolic lowering — hybridize the "
        f"block instead (the compiled path supports all of mx.npx)")
