"""Device contexts over PJRT devices.

Analog of the reference's ``python/mxnet/context.py`` (`Context`,
``mx.cpu()/mx.gpu(i)``) and ``include/mxnet/base.h`` (C++ `Context`).
The TPU design maps a Context directly onto a PJRT device obtained from
``jax.devices()``; ``mx.tpu(i)`` is the new first-class device type the
north star requires. Device placement of an op's outputs is realized by
running the op under ``jax.default_device`` (see ndarray/register.py),
so XLA compiles/executes on the right chip — there is no per-op stream
management: PJRT's async dispatch subsumes the reference's
StreamManager (src/engine/stream_manager.h).
"""
from __future__ import annotations

import threading

import jax

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_gpus", "num_tpus", "default_device"]


class Context:
    """A device context.

    Parameters
    ----------
    device_type : str
        'cpu', 'gpu', 'tpu', or 'cpu_pinned'/'cpu_shared' (aliases of cpu
        on TPU systems — pinned host memory is a CUDA concept; host numpy
        buffers are already DMA-able by PJRT).
    device_id : int
        Device ordinal within its type.
    """

    # reference: Context::kCPU=1, kGPU=2, kCPUPinned=3, kCPUShared=5
    devtype2num = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}
    devnum2type = {v: k for k, v in devtype2num.items()}

    _default = threading.local()

    def __init__(self, device_type, device_id: int = 0):
        if isinstance(device_type, Context):
            device_type, device_id = device_type.device_type, device_type.device_id
        if device_type not in self.devtype2num:
            raise MXNetError(f"unknown device type {device_type!r}")
        self.device_type = device_type
        self.device_id = int(device_id)

    # -- identity ---------------------------------------------------------
    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    # -- PJRT mapping -----------------------------------------------------
    @property
    def jax_device(self):
        """The PJRT device backing this context.

        Resolution is PROCESS-LOCAL (jax.local_devices): under
        multi-process launch every worker's mx.cpu(0)/mx.tpu(0) is its
        own addressable chip — the reference's per-worker device ids —
        never another host's device from the global list."""
        kind = self.device_type
        if kind in ("cpu_pinned", "cpu_shared"):
            kind = "cpu"
        try:
            devs = jax.local_devices(backend=kind)
        except RuntimeError:
            # Requested backend not present. Mirror the reference's
            # behavior of allowing mx.gpu(0) objects to exist without a
            # GPU — failure happens at use time. For use-time resolution
            # fall back: tpu→any accelerator→cpu.
            if kind != "cpu":
                try:
                    devs = jax.local_devices()
                except RuntimeError:
                    devs = jax.local_devices(backend="cpu")
            else:
                raise
        if self.device_id >= len(devs):
            raise MXNetError(
                f"{self} does not exist: only {len(devs)} {kind} device(s) visible"
            )
        return devs[self.device_id]

    @property
    def real_device_type(self) -> str:
        """Resolved platform of the backing PJRT device."""
        return self.jax_device.platform

    def empty_cache(self):
        """Analog of mx.Context.empty_cache (GPU pool flush). PJRT manages
        its own HBM pool; this is a best-effort hint (no-op)."""

    # -- default-context scoping ------------------------------------------
    def __enter__(self):
        if not hasattr(Context._default, "stack"):
            Context._default.stack = []
        Context._default.stack.append(self)
        return self

    def __exit__(self, *exc):
        Context._default.stack.pop()
        return False


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def gpu(device_id: int = 0) -> Context:
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    """The TPU device context — the north-star addition (`mx.tpu(i)`)."""
    return Context("tpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def num_gpus() -> int:
    try:
        return len(jax.local_devices(backend="gpu"))
    except RuntimeError:
        return 0


def num_tpus() -> int:
    try:
        return len(jax.local_devices(backend="tpu"))
    except RuntimeError:
        return 0


def _best_context() -> Context:
    plat = jax.default_backend()
    if plat in ("tpu", "axon"):
        return tpu(0)
    if plat == "gpu":
        return gpu(0)
    return cpu(0)


def current_context() -> Context:
    """The active default context (innermost ``with ctx:`` scope, else the
    best available device — TPU when present)."""
    stack = getattr(Context._default, "stack", None)
    if stack:
        return stack[-1]
    return _best_context()


def default_device():
    """PJRT device of the current default context."""
    return current_context().jax_device


# module-level convenience mirroring mx.context.current_context()
Context.default_ctx = property(lambda self: current_context())
