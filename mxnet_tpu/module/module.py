"""Module — symbolic data-parallel training module
(python/mxnet/module/module.py + executor_group.py analog).

The reference slices each batch across a context list
(DataParallelExecutorGroup) and reduces gradients via KVStore. Here a
single Executor evaluates the bound symbol on the primary context —
device-level data parallelism on TPU belongs to the sharded Gluon
Trainer / pjit path (SURVEY §7), while Module keeps exact legacy API
behavior for porting old training scripts.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from ..context import current_context, cpu
from ..initializer import InitDesc
from .. import optimizer as opt
from .. import kvstore as _kvstore_mod
from .base_module import BaseModule

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger)
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._context = context if context is not None else current_context()
        if isinstance(self._context, (list, tuple)):
            self._context = self._context[0]  # see module docstring
        self._fixed_param_names = list(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        self._param_names = [n for n in arg_names
                             if n not in self._data_names and n not in self._label_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._arg_params = None
        self._aux_params = None
        self._exec = None
        self._optimizer = None
        self._kvstore = None
        self._updater = None
        self._update_on_kvstore = False
        self._data_shapes = None
        self._label_shapes = None
        self._monitor = None

    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return list(zip(self.output_names,
                        [tuple(o.shape) for o in self._exec.outputs]))

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        from .. import ndarray as nd

        self._data_shapes = list(data_shapes)
        self._label_shapes = list(label_shapes) if label_shapes else None
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

        shape_kwargs = {}
        for desc in self._data_shapes:
            name, shape = desc[0], desc[1]
            shape_kwargs[name] = shape
        if self._label_shapes:
            for desc in self._label_shapes:
                shape_kwargs[desc[0]] = desc[1]

        arg_shapes, _, _ = self._symbol.infer_shape(**shape_kwargs)
        if arg_shapes is None:
            raise MXNetError(f"cannot infer shapes from {shape_kwargs}")
        args = {}
        grads = {}
        req = {}
        for name, shape in zip(self._symbol.list_arguments(), arg_shapes):
            args[name] = nd.zeros(shape, ctx=self._context)
            if for_training and name in self._param_names and \
                    name not in self._fixed_param_names:
                grads[name] = nd.zeros(shape, ctx=self._context)
                req[name] = grad_req if isinstance(grad_req, str) else grad_req.get(name, "write")
            elif inputs_need_grad and name in self._data_names:
                grads[name] = nd.zeros(shape, ctx=self._context)
                req[name] = "write"
            else:
                req[name] = "null"
        self._exec = self._symbol.bind(self._context, args, grads, req)
        self.binded = True
        if shared_module is not None and shared_module.params_initialized:
            arg_p, aux_p = shared_module.get_params()
            self.set_params(arg_p, aux_p)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"
        from ..initializer import Uniform
        if initializer is None and not (arg_params or aux_params):
            initializer = Uniform(0.01)

        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                arg_params[name].copyto(arr)
            elif initializer is not None:
                initializer(InitDesc(name), arr)
            elif not allow_missing:
                raise MXNetError(f"parameter {name} missing and no initializer given")
        for name in self._aux_names:
            arr = self._exec.aux_dict.get(name)
            if arr is None:
                continue
            if aux_params is not None and name in aux_params:
                aux_params[name].copyto(arr)
            elif initializer is not None:
                initializer(InitDesc(name), arr)
        self.params_initialized = True

    def get_params(self):
        assert self.binded and self.params_initialized
        arg_params = {n: self._exec.arg_dict[n].copyto(cpu())
                      for n in self._param_names}
        aux_params = {n: self._exec.aux_dict[n].copyto(cpu())
                      for n in self._aux_names if n in self._exec.aux_dict}
        return arg_params, aux_params

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            optimizer_params = dict(optimizer_params)
            idx2name = {i: n for i, n in enumerate(self._param_names)}
            optimizer = opt.create(optimizer, param_idx2name=idx2name,
                                   **optimizer_params)
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)
        if kvstore:
            kv = kvstore if not isinstance(kvstore, str) else _kvstore_mod.create(kvstore)
            self._kvstore = kv
            for i, name in enumerate(self._param_names):
                kv.init(i, self._exec.arg_dict[name])
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        bindings = {}
        for name, arr in zip(self._data_names, data_batch.data):
            bindings[name] = arr.as_in_context(self._context)
        if data_batch.label:
            for name, arr in zip(self._label_names, data_batch.label):
                bindings[name] = arr.as_in_context(self._context)
        self._exec.forward(is_train=is_train, **bindings)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    def update(self):
        assert self.binded and self.params_initialized and self.optimizer_initialized
        for i, name in enumerate(self._param_names):
            weight = self._exec.arg_dict[name]
            grad = self._exec.grad_dict.get(name)
            if grad is None:
                continue
            if self._kvstore is not None:
                self._kvstore.push(i, grad)
                self._kvstore.pull(i, grad)
            self._updater(i, grad, weight)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return list(self._exec.outputs)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.inputs_need_grad
        return [self._exec.grad_dict[n] for n in self._data_names]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update(labels, self._exec.outputs)

    def install_monitor(self, mon):
        assert self.binded
        mon.install(self._exec)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        remove_amp_cast=True):
        from ..model import save_checkpoint
        arg_params, aux_params = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg_params, aux_params)
        if save_optimizer_states:
            self.save_optimizer_states(f"{prefix}-{epoch:04d}.states")

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        with open(fname, "wb") as f:
            f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from ..model import load_checkpoint
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = False
        mod._preloaded_params = (args, auxs)
        return mod
