"""Module — symbolic data-parallel training module
(python/mxnet/module/module.py + executor_group.py analog).

DataParallelExecutorGroup parity: a context LIST binds one compiled
executor per device, ``forward`` slices the batch across them,
``update`` reduces all parameter gradients in ONE fused kvstore
pushpull (the compiled all-reduce of parallel/comm.py) and applies the
optimizer to every replica — the reference's kvstore 'device' training
loop, with XLA collectives in place of P2P reduce trees.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from ..context import current_context, cpu
from ..initializer import InitDesc
from .. import optimizer as opt
from .. import kvstore as _kvstore_mod
from .base_module import BaseModule

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger)
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        ctx = context if context is not None else current_context()
        self._contexts = list(ctx) if isinstance(ctx, (list, tuple)) else [ctx]
        self._context = self._contexts[0]
        self._fixed_param_names = list(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        self._param_names = [n for n in arg_names
                             if n not in self._data_names and n not in self._label_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._arg_params = None
        self._aux_params = None
        self._exec = None
        self._optimizer = None
        self._kvstore = None
        self._updater = None
        self._update_on_kvstore = False
        self._data_shapes = None
        self._label_shapes = None
        self._monitor = None

    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return list(zip(self.output_names,
                        [tuple(o.shape) for o in self._exec.outputs]))

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        from .. import ndarray as nd

        self._data_shapes = list(data_shapes)
        self._label_shapes = list(label_shapes) if label_shapes else None
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

        shape_kwargs = {}
        for desc in self._data_shapes:
            name, shape = desc[0], desc[1]
            shape_kwargs[name] = shape
        if self._label_shapes:
            for desc in self._label_shapes:
                shape_kwargs[desc[0]] = desc[1]

        n_ctx = len(self._contexts)
        if n_ctx > 1:
            # executor_group batch slicing: per-device shapes divide the
            # batch axis evenly across the context list
            def _slice(shape):
                assert shape[0] % n_ctx == 0, \
                    f"batch size {shape[0]} must divide across {n_ctx} contexts"
                return (shape[0] // n_ctx,) + tuple(shape[1:])
            shape_kwargs = {k: _slice(v) for k, v in shape_kwargs.items()}

        arg_shapes, _, _ = self._symbol.infer_shape(**shape_kwargs)
        if arg_shapes is None:
            raise MXNetError(f"cannot infer shapes from {shape_kwargs}")
        self._execs = []
        for ctx in self._contexts:
            args = {}
            grads = {}
            req = {}
            for name, shape in zip(self._symbol.list_arguments(), arg_shapes):
                args[name] = nd.zeros(shape, ctx=ctx)
                if for_training and name in self._param_names and \
                        name not in self._fixed_param_names:
                    grads[name] = nd.zeros(shape, ctx=ctx)
                    req[name] = grad_req if isinstance(grad_req, str) else grad_req.get(name, "write")
                elif inputs_need_grad and name in self._data_names:
                    grads[name] = nd.zeros(shape, ctx=ctx)
                    req[name] = "write"
                else:
                    req[name] = "null"
            self._execs.append(self._symbol.bind(ctx, args, grads, req))
        self._exec = self._execs[0]
        self.binded = True
        if shared_module is not None and shared_module.params_initialized:
            arg_p, aux_p = shared_module.get_params()
            self.set_params(arg_p, aux_p)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"
        from ..initializer import Uniform
        if initializer is None and not (arg_params or aux_params):
            initializer = Uniform(0.01)

        attrs = self._symbol.attr_dict() if hasattr(self._symbol, "attr_dict") \
            else {}
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                arg_params[name].copyto(arr)
            elif initializer is not None:
                # per-variable __init__ attrs (e.g. mx.rnn LSTMCell's
                # LSTMBias forget-gate offset) override the global init
                initializer(InitDesc(name, attrs.get(name)), arr)
            elif not allow_missing:
                raise MXNetError(f"parameter {name} missing and no initializer given")
            for ex in self._execs[1:]:  # broadcast to replicas
                arr.copyto(ex.arg_dict[name])
        for name in self._aux_names:
            arr = self._exec.aux_dict.get(name)
            if arr is None:
                continue
            if aux_params is not None and name in aux_params:
                aux_params[name].copyto(arr)
            elif initializer is not None:
                initializer(InitDesc(name), arr)
            for ex in self._execs[1:]:
                if name in ex.aux_dict:
                    arr.copyto(ex.aux_dict[name])
        self.params_initialized = True

    def get_params(self):
        assert self.binded and self.params_initialized
        arg_params = {n: self._exec.arg_dict[n].copyto(cpu())
                      for n in self._param_names}
        aux_params = {n: self._exec.aux_dict[n].copyto(cpu())
                      for n in self._aux_names if n in self._exec.aux_dict}
        return arg_params, aux_params

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            optimizer_params = dict(optimizer_params)
            # per-device optimizer state: replica j of param i is keyed
            # i*n_ctx+j (reference executor_group convention
            # index*num_device+k) so momentum/Adam state is NOT shared
            # across replicas and update-count schedules advance once
            # per step per key
            n_ctx = len(self._contexts)
            idx2name = {i * n_ctx + j: n
                        for i, n in enumerate(self._param_names)
                        for j in range(n_ctx)}
            optimizer = opt.create(optimizer, param_idx2name=idx2name,
                                   **optimizer_params)
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)
        if kvstore:
            kv = kvstore if not isinstance(kvstore, str) else _kvstore_mod.create(kvstore)
            self._kvstore = kv
            for i, name in enumerate(self._param_names):
                kv.init(i, self._exec.arg_dict[name])
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        n_ctx = len(self._execs)
        if n_ctx == 1:
            bindings = {}
            for name, arr in zip(self._data_names, data_batch.data):
                bindings[name] = arr.as_in_context(self._context)
            if data_batch.label:
                for name, arr in zip(self._label_names, data_batch.label):
                    bindings[name] = arr.as_in_context(self._context)
            self._exec.forward(is_train=is_train, **bindings)
            return
        # DataParallelExecutorGroup: slice the batch across contexts
        from ..gluon.utils import split_and_load
        sliced = [dict() for _ in range(n_ctx)]
        for name, arr in zip(self._data_names, data_batch.data):
            for b, part in zip(sliced, split_and_load(arr, self._contexts)):
                b[name] = part
        if data_batch.label:
            for name, arr in zip(self._label_names, data_batch.label):
                for b, part in zip(sliced, split_and_load(arr, self._contexts)):
                    b[name] = part
        for ex, b in zip(self._execs, sliced):
            ex.forward(is_train=is_train, **b)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        if len(self._execs) == 1:
            self._exec.backward(out_grads=out_grads)
            return
        assert out_grads is None, \
            "multi-context Module.backward with explicit out_grads is not supported"
        for ex in self._execs:
            ex.backward()

    def update(self):
        assert self.binded and self.params_initialized and self.optimizer_initialized
        n_ctx = len(self._execs)
        if n_ctx > 1 or self._kvstore is not None:
            # ONE fused reduce over every key: all-reduces compiled and
            # bucketed by XLA (kvstore_nccl.h fused-pushpull analog).
            # Without a kvstore the reduce still must happen (reference
            # executor_group sums before update) — use the comm layer
            # directly.
            keys, grads = [], []
            for i, name in enumerate(self._param_names):
                g = [ex.grad_dict.get(name) for ex in self._execs]
                if g[0] is None:
                    continue
                keys.append(i)
                grads.append(g)
            if keys:
                if self._kvstore is not None:
                    self._kvstore.pushpull(keys, grads, out=grads)
                elif n_ctx > 1:
                    self._reduce_without_kvstore(grads)
        for i, name in enumerate(self._param_names):
            for j, ex in enumerate(self._execs):
                weight = ex.arg_dict[name]
                grad = ex.grad_dict.get(name)
                if grad is None:
                    continue
                self._updater(i * n_ctx + j, grad, weight)

    @staticmethod
    def _reduce_without_kvstore(grads):
        """Sum replica grads in one compiled all-reduce, write back."""
        from ..parallel import comm
        comm.reduce_grad_ndarrays_inplace(grads)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        if len(self._execs) == 1 or not merge_multi_context:
            return list(self._exec.outputs)
        from .. import ndarray as nd
        merged = []
        for outs in zip(*(ex.outputs for ex in self._execs)):
            merged.append(nd.concat(
                *[o.as_in_context(self._context) for o in outs], dim=0))
        return merged

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.inputs_need_grad
        if len(self._execs) == 1 or not merge_multi_context:
            return [self._exec.grad_dict[n] for n in self._data_names]
        from .. import ndarray as nd
        return [nd.concat(*[ex.grad_dict[n].as_in_context(self._context)
                            for ex in self._execs], dim=0)
                for n in self._data_names]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update(labels, self.get_outputs())

    def install_monitor(self, mon):
        assert self.binded
        for ex in self._execs:
            mon.install(ex)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        remove_amp_cast=True):
        from ..model import save_checkpoint
        arg_params, aux_params = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg_params, aux_params)
        if save_optimizer_states:
            self.save_optimizer_states(f"{prefix}-{epoch:04d}.states")

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        with open(fname, "wb") as f:
            f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from ..model import load_checkpoint
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = False
        mod._preloaded_params = (args, auxs)
        return mod
