"""BucketingModule — variable-length training via per-bucket modules
(python/mxnet/module/bucketing_module.py analog). Each bucket key binds
its own Module sharing parameters; on TPU each bucket is its own XLA
compilation (static shapes), exactly the reference's per-bucket
executors."""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger)
        assert default_bucket_key is not None
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._init_args = None

    @property
    def symbol(self):
        return self._curr_module.symbol if self._curr_module else None

    def _gen_module(self, bucket_key):
        sym, data_names, label_names = self._sym_gen(bucket_key)
        return Module(sym, data_names, label_names, self.logger,
                      self._context, fixed_param_names=self._fixed_param_names)

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        if bucket_key not in self._buckets:
            mod = self._gen_module(bucket_key)
            mod.bind(data_shapes, label_shapes, for_training=self.for_training)
            if self._curr_module is not None and self._curr_module.params_initialized:
                self._share_into(mod)
                mod.params_initialized = True
            elif self._init_args is not None:
                mod.init_params(**self._init_args)
            if self._curr_module is not None and self._curr_module.optimizer_initialized:
                # ONE optimizer/updater across buckets (shared state,
                # update counts advance once per step)
                mod._optimizer = self._curr_module._optimizer
                mod._updater = self._curr_module._updater
                mod._kvstore = self._curr_module._kvstore
                mod.optimizer_initialized = True
            self._buckets[bucket_key] = mod
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    def _share_into(self, mod):
        """Share the default bucket's parameter ARRAYS with a new bucket
        module (reference: bucket executors share memory via
        shared_module). Updates through any bucket are then visible to
        all — no copy-on-switch drift."""
        src = self._buckets[self._default_bucket_key]
        missing = [n for n in mod._param_names
                   if n not in src._execs[0].arg_dict]
        if missing:
            raise MXNetError(
                f"bucket parameters {missing} do not exist in the default "
                f"bucket {self._default_bucket_key!r}; the default bucket's "
                "graph must cover every parameter (reference contract)")
        for name in mod._param_names:
            for ex_dst, ex_src in zip(mod._execs, src._execs):
                ex_dst.arg_dict[name] = ex_src.arg_dict[name]
        for name in mod._aux_names:
            for ex_dst, ex_src in zip(mod._execs, src._execs):
                if name in ex_src.aux_dict:
                    ex_dst.aux_dict[name] = ex_src.aux_dict[name]

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        self.for_training = for_training
        self.switch_bucket(self._default_bucket_key, data_shapes, label_shapes)
        self.binded = True

    def init_params(self, initializer=None, **kwargs):
        # positional initializer for reference-signature parity
        # (base_module.py init_params(initializer=Uniform(0.01), ...))
        kwargs = dict(kwargs, initializer=initializer)
        self._init_args = kwargs
        self._curr_module.init_params(**kwargs)
        self.params_initialized = True

    def get_params(self):
        return self._curr_module.get_params()

    def init_optimizer(self, **kwargs):
        self._curr_module.init_optimizer(**kwargs)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        key = data_batch.bucket_key
        if key is None:
            key = self._default_bucket_key
        data_shapes = data_batch.provide_data or \
            [(f"data{i}" if False else d.name, d.shape) for d in []]
        if key != self._curr_bucket_key:
            self.switch_bucket(key, data_batch.provide_data,
                               data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        # parameters are shared by name; update the current bucket then
        # sync into siblings lazily at switch time
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels, pre_sliced)

    def install_monitor(self, mon):
        for mod in self._buckets.values():
            mod.install_monitor(mon)
