"""BaseModule — the fit/score/predict training loop
(python/mxnet/module/base_module.py analog)."""
from __future__ import annotations

import logging
import time

from ..base import MXNetError
from .. import metric as _metric
from ..model import BatchEndParam
from ..telemetry import events as _events
from ..telemetry import spans as _spans
from ..telemetry.registry import REGISTRY as _REGISTRY

__all__ = ["BaseModule"]


def _fit_telemetry(loop):
    """(step-time histogram child, samples/sec gauge child) for a fit
    loop — one family shared by module and gluon loops, labeled by
    which loop fed it."""
    hist = _REGISTRY.histogram(
        "mxnet_tpu_train_step_ms",
        "host wall per train step (fwd+bwd+update dispatch)", ("loop",))
    gauge = _REGISTRY.gauge(
        "mxnet_tpu_train_samples_per_sec",
        "most recent train-loop throughput", ("loop",))
    return hist.labels(loop=loop), gauge.labels(loop=loop)


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # -- abstract surface --------------------------------------------------
    def bind(self, *args, **kwargs):
        raise NotImplementedError

    def init_params(self, *args, **kwargs):
        raise NotImplementedError

    def init_optimizer(self, *args, **kwargs):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    # -- derived helpers ---------------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0, sparse_row_id_fn=None):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        eval_metric.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                      eval_metric=eval_metric, locals=locals())
                for cb in _as_list(batch_end_callback):
                    cb(param)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False, sparse_row_id_fn=None):
        from .. import ndarray as nd
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad or 0
            outs = [out[0:out.shape[0] - pad] for out in self.get_outputs()]
            output_list.append(outs)
        if not output_list:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            merged = [nd.concat(*[o[i] for o in output_list], dim=0)
                      for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return merged[0]
            return merged
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """The classic Module.fit loop (reference base_module.py fit)."""
        from ..initializer import Uniform
        assert num_epoch is not None, "please specify number of epochs"
        initializer = initializer or Uniform(0.01)

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)

        step_ms, samples_per_sec = _fit_telemetry("module_fit")
        for epoch in range(begin_epoch, num_epoch):
            tic = time.perf_counter()
            eval_metric.reset()
            nbatch = 0
            nsample = 0
            train_data.reset()
            # the epoch span is a tail-sampled local root (epochs are
            # slow, so they're kept); per-step child spans decompose
            # where the epoch went, capped by the recorder's
            # max-spans-per-trace bound
            with _spans.span("fit/epoch", loop="module_fit",
                             epoch=epoch) as _ep:
                for data_batch in train_data:
                    if monitor is not None:
                        monitor.tic()
                    t0 = time.perf_counter()
                    with _spans.span("fit/step", step=nbatch):
                        self.forward_backward(data_batch)
                        self.update()
                    # host wall of the dispatch; under async execution
                    # the device backpressure folds in over
                    # steady-state steps
                    dt = time.perf_counter() - t0
                    step_ms.observe(dt * 1e3)
                    try:
                        bsz = data_batch.data[0].shape[0]
                    except (AttributeError, IndexError, TypeError):
                        bsz = 0
                    if bsz and dt > 0:
                        samples_per_sec.set(bsz / dt)
                        nsample += bsz
                    self.update_metric(eval_metric, data_batch.label)
                    if monitor is not None:
                        monitor.toc_print()
                    if batch_end_callback is not None:
                        param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                              eval_metric=eval_metric,
                                              locals=locals())
                        for cb in _as_list(batch_end_callback):
                            cb(param)
                    nbatch += 1
                _ep.set_attr(batches=nbatch, samples=nsample)

            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.perf_counter() - tic)
            _events.emit("fit_epoch", loop="module_fit", epoch=epoch,
                         batches=nbatch, samples=nsample,
                         seconds=round(time.perf_counter() - tic, 3))

            arg_p, aux_p = self.get_params()
            self.set_params(arg_p, aux_p, allow_missing=False, force_init=True,
                            allow_extra=False)
            if epoch_end_callback is not None:
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)

            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch, name, val)

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def install_monitor(self, mon):
        raise NotImplementedError

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError


def _as_list(obj):
    if isinstance(obj, (list, tuple)):
        return obj
    return [obj]
