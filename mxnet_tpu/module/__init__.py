from .module import Module
from .base_module import BaseModule
from .bucketing_module import BucketingModule
