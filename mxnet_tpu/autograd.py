"""Define-by-run autograd.

Analog of the reference's ``python/mxnet/autograd.py`` frontend and the
C++ ``Imperative`` tape (src/imperative/imperative.cc:
``Imperative::RecordOp`` / ``Imperative::Backward``). The reference
records invoked ops as nnvm nodes and, at ``backward()``, runs the nnvm
``Gradient`` pass then executes the backward graph through the engine.

TPU-native design: each recorded op is executed through ``jax.vjp`` at
dispatch time (ndarray/register.py), so the tape stores ready-made
pullback closures whose residuals are device-resident jax.Arrays —
forward runs once, backward is a reverse sweep calling pullbacks and
accumulating cotangents. This replaces the Gradient-pass-over-nnvm-graph
machinery with JAX's native VJP while keeping MXNet's user contract:

    with autograd.record():
        out = net(x)
        loss = loss_fn(out, y)
    loss.backward()          # leaf.grad populated per grad_req

Versioned values: in-place NDArray mutation rebinds ``_data`` and bumps
``_version`` (the engine-variable version analog), so tape values are
keyed ``(id(ndarray), version)`` — a mutation after recording creates a
distinct value node and cannot corrupt earlier gradients.
"""
from __future__ import annotations

import threading
import weakref
from typing import Optional

import jax
import jax.numpy as jnp

from .base import MXNetError

__all__ = [
    "record",
    "pause",
    "train_mode",
    "predict_mode",
    "is_recording",
    "is_training",
    "mark_variables",
    "backward",
    "grad",
    "set_recording",
    "set_training",
    "Function",
]


class _TapeNode:
    __slots__ = ("op_name", "in_keys", "in_arrays", "out_keys", "out_refs",
                 "vjp_fn", "raw_multi", "n_raw_out", "out_shapes")

    def __init__(self, op_name, in_keys, in_arrays, out_keys, out_refs,
                 vjp_fn, raw_multi, n_raw_out, out_shapes):
        self.op_name = op_name
        self.in_keys = in_keys        # [(key, ndarray-or-None), ...] aligned w/ vjp positionals
        self.in_arrays = in_arrays    # NDArray refs (leaves need .grad writes)
        self.out_keys = out_keys
        self.out_refs = out_refs      # weakrefs to output NDArrays: a node
        # whose outputs were ALL collected is unreachable (consumers hold
        # strong input refs) — pruned at the next backward; this also
        # prevents a recycled id() from colliding with a stale out_key.
        self.vjp_fn = vjp_fn
        self.raw_multi = raw_multi
        self.n_raw_out = n_raw_out
        self.out_shapes = out_shapes  # [(shape, dtype)] of raw outputs


class _AutogradState(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False
        self.tape: list[_TapeNode] = []


_STATE = _AutogradState()


def is_recording() -> bool:
    return _STATE.recording


def is_training() -> bool:
    return _STATE.training


def set_recording(flag: bool) -> bool:
    prev, _STATE.recording = _STATE.recording, flag
    return prev


def set_training(flag: bool) -> bool:
    prev, _STATE.training = _STATE.training, flag
    return prev


class _Scope:
    def __init__(self, recording: Optional[bool], training: Optional[bool]):
        self._rec, self._train = recording, training

    def __enter__(self):
        if self._rec is not None:
            self._prev_rec = set_recording(self._rec)
        if self._train is not None:
            self._prev_train = set_training(self._train)
        return self

    def __exit__(self, *exc):
        if self._rec is not None:
            set_recording(self._prev_rec)
        if self._train is not None:
            set_training(self._prev_train)
        return False


def record(train_mode: bool = True):
    """Scope: record ops for autograd (and set train mode)."""
    return _Scope(True, train_mode)


def pause(train_mode: bool = False):
    """Scope: stop recording (e.g. for parameter updates)."""
    return _Scope(False, train_mode)


def train_mode():
    return _Scope(None, True)


def predict_mode():
    return _Scope(None, False)


def _key(nd):
    return (id(nd), nd._version)


def _record_op(op, inputs, outputs, vjp_fn, raw_multi, n_raw_out,
               raw_avals=None, in_keys=None):
    """Called by register.invoke for every differentiable op under record().

    ``in_keys`` are the (id, version) pairs snapshotted BEFORE any
    in-place write-back of the same dispatch (out=/mutates), so the tape
    references the values the op actually read."""
    from .ndarray.ndarray import NDArray

    if in_keys is None:
        in_keys = [_key(x) if isinstance(x, NDArray) else None for x in inputs]
    in_arrays = []
    for x in inputs:
        if isinstance(x, NDArray):
            in_arrays.append(x)
            x._in_graph = True
        else:
            in_arrays.append(None)
    out_keys = []
    out_refs = []
    for o in outputs:
        o._in_graph = True
        out_keys.append(_key(o))
        out_refs.append(weakref.ref(o))
    # raw outputs may exceed visible outputs (e.g. BatchNorm aux); vjp
    # needs cotangents for all of them — remember avals for zero-fill.
    _STATE.tape.append(
        _TapeNode(op.name, in_keys, in_arrays, out_keys, out_refs, vjp_fn,
                  raw_multi, n_raw_out, raw_avals)
    )


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers to arrays (MXAutogradMarkVariables)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req
        v._is_leaf = True


def _ones_like(a):
    return jnp.ones(a.shape, a.dtype)


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Run the reverse sweep from `heads`; leaf ``.grad`` is populated.

    Mirrors MXAutogradBackwardEx semantics: default head gradient is
    ones; grad_req 'write' overwrites, 'add' accumulates, 'null' skips.
    """
    from .ndarray.ndarray import NDArray

    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray):
        head_grads = [head_grads]

    # cotangent accumulator keyed by (id, version)
    cot: dict = {}
    for h, hg in zip(heads, head_grads):
        g = _ones_like(h._data) if hg is None else hg._data
        k = _key(h)
        cot[k] = cot[k] + g if k in cot else g

    tape = _STATE.tape
    # a head no tape node produced yields all-zero gradients — the
    # reference's documented no-op for unrecorded graphs, but ALSO the
    # classic silent footgun (loss.sum() OUTSIDE record() drops the
    # reduction off the tape). Keep the no-op semantics, but say so.
    taped_keys = {k for node in tape for k in node.out_keys}
    for h in heads:
        if _key(h) not in taped_keys:
            import warnings
            warnings.warn(
                "backward() head was not computed inside autograd."
                "record() (or was mutated since); gradients will not "
                "flow through it — did you call .sum() on the loss "
                "AFTER the record block?", stacklevel=2)
    touched_leaves = []
    leaf_slots: dict = {}  # id(leaf) → set of tape value-keys it fed
    used_nodes: set = set()  # nodes this sweep consumed (freed below)
    for node in reversed(tape):
        if all(r() is None for r in node.out_refs):
            # every output collected → no live head/consumer can reach
            # this node; prune it (and never match a recycled id)
            used_nodes.add(id(node))
            continue
        outs_cot = [cot.get(k) if r() is not None else None
                    for k, r in zip(node.out_keys, node.out_refs)]
        if all(c is None for c in outs_cot):
            continue
        used_nodes.add(id(node))
        # assemble cotangent structure matching the vjp output structure
        if node.raw_multi:
            # visible outputs lead; hidden raw outputs get zeros. We can
            # recover hidden shapes from the vjp function's expected
            # structure only by probing — instead keep zeros via the
            # visible outputs count; hidden outputs' cotangents are not
            # derivable from the tape, pass zeros of matching shape using
            # jax's None-aware api: jax.vjp requires exact pytree, so we
            # reconstruct with stored ShapeDtypeStructs on first use.
            cots = []
            for i in range(node.n_raw_out):
                if i < len(outs_cot) and outs_cot[i] is not None:
                    cots.append(outs_cot[i])
                else:
                    cots.append(None)
            cots = _fill_zeros(node, cots)
            in_cots = node.vjp_fn(tuple(cots))
        else:
            in_cots = node.vjp_fn(outs_cot[0])
        for slot, g, x in zip(node.in_keys, in_cots, node.in_arrays):
            if slot is None or g is None:
                continue
            if getattr(g, "dtype", None) == jax.dtypes.float0:
                continue  # integer-typed input (indices): no gradient
            if x is not None and getattr(x, "_released", False):
                # the producer subgraph of this value was freed by an
                # earlier backward — continuing would yield silently
                # partial gradients. Mirror the reference's error
                # (Imperative::Backward on released AGInfo).
                raise MXNetError(
                    f"cannot differentiate through input of op "
                    f"'{node.op_name}': its computation history was "
                    "already freed by a previous backward; pass "
                    "retain_graph=True to keep it")
            cot[slot] = cot[slot] + g if slot in cot else g
        for slot, x in zip(node.in_keys, node.in_arrays):
            if x is not None and getattr(x, "_is_leaf", False):
                touched_leaves.append(x)
                leaf_slots.setdefault(id(x), set()).add(slot)

    # write leaf gradients — read cotangents at the RECORDED value-keys
    # (a leaf mutated in place after recording has a newer version; its
    # gradient belongs to the version(s) the tape actually read)
    seen = set()
    for x in touched_leaves:
        if id(x) in seen:
            continue
        seen.add(id(x))
        req = getattr(x, "_grad_req", "null")
        if req == "null" or x._grad is None:
            continue
        g = None
        for slot in leaf_slots.get(id(x), ()):
            c = cot.get(slot)
            if c is not None:
                g = c if g is None else g + c
        if g is None:
            continue
        if req == "add":
            x._grad._set_data(x._grad._data + g)
        else:  # write
            x._grad._set_data(jnp.asarray(g, x._grad.dtype))

    if not retain_graph:
        # free only the subgraph this sweep consumed: other heads recorded
        # in the same scope (the per-device losses of a DP step — the
        # reference's `for l in losses: l.backward()` pattern) keep their
        # nodes until their own backward runs. Outputs of freed nodes are
        # marked released so a later backward that reaches one raises
        # instead of silently dropping the upstream gradient. Arrays a
        # user keeps alive without ever calling backward keep their nodes
        # (same retention as the reference's per-array AGInfo); dropped
        # arrays are pruned at the next sweep via the weakrefs.
        for n in tape:
            if id(n) in used_nodes:
                for r in n.out_refs:
                    o = r()
                    if o is not None:
                        o._released = True
        _STATE.tape = [n for n in _STATE.tape if id(n) not in used_nodes]


def _fill_zeros(node, cots):
    """Replace None cotangents with zeros matching the vjp's expectation
    (jax.vjp pytree-checks its argument, so every raw output needs a
    cotangent; non-visible aux outputs get zeros)."""
    shapes = node.out_shapes
    if shapes is None:
        raise MXNetError(
            f"op {node.op_name}: multi-output op missing raw output avals"
        )
    return [
        c if c is not None else jnp.zeros(s.shape, s.dtype)
        for c, s in zip(cots, shapes)
    ]


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False, train_mode=True):
    """Compute gradients of heads w.r.t variables and return them
    (MXAutogradBackwardEx with variables set)."""
    from .ndarray.ndarray import NDArray
    from .ndarray import zeros_like

    if create_graph:
        raise MXNetError("create_graph=True (higher-order grad) is not supported yet")
    if isinstance(variables, NDArray):
        variables = [variables]
    saved = [(getattr(v, "_grad", None), getattr(v, "_grad_req", "null"), getattr(v, "_is_leaf", False)) for v in variables]
    gradients = [zeros_like(v) for v in variables]
    mark_variables(variables, gradients, "write")
    try:
        backward(heads, head_grads, retain_graph=bool(retain_graph), train_mode=train_mode)
    finally:
        for v, (g, req, leaf) in zip(variables, saved):
            v._grad, v._grad_req, v._is_leaf = g, req, leaf
    return gradients


class Function:
    """Custom differentiable function (mx.autograd.Function analog).

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)`` operating on NDArrays.
    """

    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *arrays):
        self._saved = arrays

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray

        with pause():
            outputs = self.forward(*inputs)
        single = isinstance(outputs, NDArray)
        outs = [outputs] if single else list(outputs)
        if is_recording() and any(
            isinstance(x, NDArray) and x._requires_grad_somewhere() for x in inputs
        ):
            func = self

            def vjp_fn(cotangents):
                cts = cotangents if isinstance(cotangents, tuple) else (cotangents,)
                from .ndarray.ndarray import _wrap
                ct_nd = [_wrap(c, outs[0].ctx) for c in cts]
                with pause():
                    in_grads = func.backward(*ct_nd)
                if isinstance(in_grads, NDArray):
                    in_grads = [in_grads]
                return tuple(
                    (g._data if isinstance(g, NDArray) else g) for g in in_grads
                )

            raw_avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in outs]

            class _FakeOp:
                name = type(self).__name__

            _record_op(_FakeOp, list(inputs), outs, vjp_fn,
                       raw_multi=not single, n_raw_out=len(outs),
                       raw_avals=raw_avals)
        return outputs if single else outs


def get_symbol(*a, **k):  # legacy API stub (symbol extraction from tape)
    raise MXNetError("autograd.get_symbol is not supported on the TPU backend")
