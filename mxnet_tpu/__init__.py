"""mxnet_tpu — a TPU-native deep-learning framework with MXNet's API.

A ground-up re-design of Apache-MXNet-v1.x capabilities (reference:
junshipeng/mxnet) for TPU: the compute path is JAX/XLA/Pallas, device
parallelism is jax.sharding meshes with ICI/DCN collectives, and the
imperative engine contract (async ops, futures, sync points) rides on
PJRT's asynchronous dispatch. See SURVEY.md for the layer-by-layer
mapping to the reference.

Typical use mirrors mxnet::

    import mxnet_tpu as mx
    x = mx.nd.zeros((2, 3), ctx=mx.tpu(0))
    with mx.autograd.record():
        ...
"""
from __future__ import annotations

import os as _os

__version__ = "0.1.0"

# float32 numerics parity: TPU's default matmul precision is ONE bf16
# pass (~1-3% rel error) — reference users expect cuDNN-f32-class
# accuracy from f32 ops. 'high' (multi-pass bf16) restores ~f32 accuracy
# for f32 inputs and does not change bf16 compute (the perf path).
# Override with MXNET_TPU_MATMUL_PRECISION=default for max f32 speed.
import jax as _jax

from . import envvars

# Runtime concurrency sanitizer: must patch the threading factories
# BEFORE any mxnet_tpu module creates locks or threads, so every
# primitive the package mints is instrumented. Gated — the disabled
# path patches nothing.
if envvars.get("MXNET_TPU_SANITIZE"):
    from . import _sanitize as _sanitize_mod

    _sanitize_mod.install()

_prec = envvars.get("MXNET_TPU_MATMUL_PRECISION")
try:
    _jax.config.update("jax_default_matmul_precision", _prec)
except Exception:
    # an invalid override must not silently demote f32 numerics to the
    # single-pass-bf16 jax default — warn and keep the documented 'high'
    import warnings as _warnings
    _warnings.warn(
        f"invalid MXNET_TPU_MATMUL_PRECISION={_prec!r}; using 'high'")
    _jax.config.update("jax_default_matmul_precision", "high")
del _prec

from .base import MXNetError
from .context import (
    Context, cpu, gpu, tpu, cpu_pinned, current_context, num_gpus, num_tpus,
)
from . import engine
from . import random
from . import autograd
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray

from . import optimizer
from .optimizer import Optimizer
from . import lr_scheduler
from . import metric
from . import initializer
from .initializer import init  # alias namespace mx.init
from . import kvstore as kv
from . import kvstore
from . import gluon
from . import io
from . import recordio
from . import image
from . import image as img
from . import callback
from . import monitor
from . import model
from . import operator
from . import profiler
from . import parallel
from . import test_utils
from . import runtime
from . import checkpoint
from . import telemetry
from . import serving
from .util import is_np_array

from .attribute import AttrScope
from .name import NameManager

# mx.sym / mx.symbol — symbolic graph API
from . import symbol
from . import symbol as sym
from .symbol import Symbol
from .executor import Executor
from . import module
from . import module as mod
from . import rnn
from . import contrib

# mx.np / mx.npx — the deep-NumPy frontend (reference python/mxnet/numpy/,
# numpy_extension/, v>=1.6); imported after nd so registry + autograd exist
from . import numpy as np  # noqa: A001
from . import numpy  # noqa: F401  (mx.numpy, as upstream also exposes)
from . import numpy_extension as npx
from . import numpy_extension  # noqa: F401

# deep-numpy hybrid-forward convention: np-style blocks write
# F.np.dot(...) / F.npx.relu(...) — install the namespaces on the nd
# module handed to hybrid_forward (classic F.<op> names untouched).
# The Symbol path gets the SYMBOLIC np/npx namespaces (op-backed
# subset; Python-composed functions raise pointing at hybridize).
ndarray.np = np
ndarray.npx = npx

from .symbol import numpy as _sym_np  # noqa: E402
from .symbol import numpy_extension as _sym_npx  # noqa: E402

symbol.np = _sym_np
symbol.npx = _sym_npx
from . import visualization
from . import visualization as viz


def waitall():
    """Block until all asynchronously dispatched work completes
    (MXNDArrayWaitAll)."""
    engine.engine.wait_all()


def cpu_count():
    import os
    return os.cpu_count()
