"""mxsan — runtime concurrency sanitizer (``MXNET_TPU_SANITIZE=1``).

The static half (``tools/mxlint`` lock-order + lock-graph passes)
PROVES the declared lock graph acyclic; this module VERIFIES the proof
on every instrumented run, the way the paper's ``ThreadedEngine``
checks its dependency discipline at runtime rather than trusting the
scheduler. Under the gate, :func:`install` patches
``threading.Lock`` / ``threading.RLock`` / ``threading.Condition``
with wrappers that

- record per-thread acquisition stacks and maintain the OBSERVED
  lock-order graph keyed by lock *creation site* (two engines' locks
  born on the same line are one node — instance-insensitive, exactly
  like the static pass), reporting a cycle the moment an edge closes
  one (``order-cycle``): a potential deadlock is flagged even when the
  interleaving that would hang never happens in this run;
- time every hold and report holds longer than
  ``MXNET_TPU_SANITIZE_HOLD_MS`` *while another thread was waiting*
  (``long-hold``) — the contended-convoy shape, not mere slowness;
- track thread lifecycles (``Thread.start``/``join`` are wrapped) and
  report at teardown (``thread-leak``) non-daemon threads still alive
  past the session, and (``thread-unjoined``) non-daemon non-test
  threads that died without ever being joined.

The DISABLED path is free: nothing is patched unless
:func:`install` runs, and ``mxnet_tpu/__init__`` only calls it under
the env gate — ``threading.Lock`` stays the raw ``_thread``
factory (identity-asserted by the microbench guard in
``tests/test_sanitize.py``).

Findings are suppressed by a ``# mxsan: allow=<rule>`` comment on the
lock's creation line, the acquisition line, or the thread's start
line (``allow=all`` works too), and otherwise gated against the
committed ``tests/mxsan_baseline.json`` by the pytest plugin in
``tests/conftest.py`` — same contract as mxlint's baseline: the file
is committed EMPTY and the sanitized leg fails on any unbaselined
finding.

Only locks *created by repo code* are instrumented (the creation
frame must live under the repo root): stdlib/third-party internals —
every ``threading.Event``'s private lock, jax's pools — keep raw
primitives, which both bounds the graph and avoids false cycles
through shared stdlib creation sites.
"""
from __future__ import annotations

import json
import linecache
import os
import re
import sys
import threading
import time
import weakref

import _thread

from . import envvars

__all__ = ["Sanitizer", "Finding", "install", "uninstall", "active",
           "load_baseline", "unbaselined", "report", "RULES"]

RULES = ("order-cycle", "long-hold", "thread-leak", "thread-unjoined")

_RAW_LOCK = _thread.allocate_lock
_RAW_RLOCK = _thread.RLock
_RAW_CONDITION = threading.Condition
_RAW_THREAD_START = threading.Thread.start
_RAW_THREAD_JOIN = threading.Thread.join

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_OWN_FILE = os.path.abspath(__file__)
_ALLOW_RE = re.compile(r"#\s*mxsan:\s*allow=([\w,\-]+)")
_DIGITS_RE = re.compile(r"\d+")


def _caller_site():
    """(abs filename, lineno) of the nearest frame outside this
    module."""
    f = sys._getframe(1)
    while f is not None and f.f_code.co_filename == __file__:
        f = f.f_back
    if f is None:
        return "<unknown>", 0
    return f.f_code.co_filename, f.f_lineno


def _in_repo(filename):
    return filename.startswith(_REPO_ROOT + os.sep)


class _Site:
    """A lock/thread creation site: the order-graph node identity."""

    __slots__ = ("path", "rel", "line", "__weakref__")

    def __init__(self, path, line):
        self.path = path
        try:
            rel = os.path.relpath(path, _REPO_ROOT)
        except ValueError:
            rel = path
        self.rel = rel.replace(os.sep, "/")
        self.line = line

    def label(self):
        snippet = linecache.getline(self.path, self.line).strip()
        snippet = snippet.split("#")[0].strip()
        loc = f"{self.rel}:{self.line}"
        return f"{loc} ({snippet})" if snippet else loc

    def key(self):
        return f"{self.rel}:{self.line}"


class Finding:
    """One sanitizer finding (mxlint-shaped: rule + message + a stable
    key for the committed baseline)."""

    __slots__ = ("rule", "message", "sites", "meta")

    def __init__(self, rule, message, sites, meta=None):
        self.rule = rule
        self.message = message
        self.sites = tuple(sites)
        self.meta = meta or {}

    def key(self):
        return "|".join([self.rule] + sorted(s.key() for s in self.sites))

    def __repr__(self):
        return f"<mxsan {self.rule} {self.key()}>"


def _allowed(rule, sites, extra_lines=()):
    """True when any involved source line carries
    ``# mxsan: allow=<rule>`` (or ``allow=all``)."""
    lines = [(s.path, s.line) for s in sites]
    lines.extend(extra_lines)
    for path, line in lines:
        m = _ALLOW_RE.search(linecache.getline(path, line))
        if not m:
            continue
        allowed = {r.strip() for r in m.group(1).split(",")}
        if rule in allowed or "all" in allowed:
            return True
    return False


class _HeldEntry:
    """One live hold. Keeps a reference to the ACQUIRING thread's held
    list so a cross-thread release (a Lock used as a semaphore) can
    still retire the entry instead of leaving a stale hold that would
    fabricate edges forever."""

    __slots__ = ("lock", "site", "acq_path", "acq_line", "held_list")

    def __init__(self, lock, site, acq_path, acq_line, held_list):
        self.lock = lock
        self.site = site
        self.acq_path = acq_path
        self.acq_line = acq_line
        self.held_list = held_list


class _SanLock:
    """Instrumented non-reentrant lock (drop-in for
    ``threading.Lock()``)."""

    _reentrant = False

    __slots__ = ("_san", "_site", "_raw", "_owner", "_acq_mono",
                 "_acq_path", "_acq_line", "_waiters", "_contended",
                 "_entry", "__weakref__")

    def __init__(self, san, site):
        self._san = san
        self._site = site
        self._raw = _RAW_LOCK()
        self._owner = None
        self._acq_mono = 0.0
        self._acq_path = ""
        self._acq_line = 0
        self._waiters = 0
        self._contended = False
        self._entry = None

    def acquire(self, blocking=True, timeout=-1):
        got = self._raw.acquire(False)
        if not got:
            self._contended = True
            if not blocking:
                return False
            self._waiters += 1
            try:
                got = self._raw.acquire(True, timeout)
            finally:
                self._waiters -= 1
            if not got:
                return False
        self._san._acquired(self)
        return True

    def release(self):
        self._san._releasing(self)
        self._owner = None
        self._raw.release()

    def locked(self):
        return self._raw.locked()

    def _is_owned(self):
        return self._owner == threading.get_ident()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<mxsan Lock @ {self._site.key()}>"


class _SanRLock:
    """Instrumented reentrant lock (drop-in for
    ``threading.RLock()``, Condition-protocol complete)."""

    _reentrant = True

    __slots__ = ("_san", "_site", "_raw", "_owner", "_count",
                 "_acq_mono", "_acq_path", "_acq_line", "_waiters",
                 "_contended", "_entry", "__weakref__")

    def __init__(self, san, site):
        self._san = san
        self._site = site
        self._raw = _RAW_LOCK()
        self._owner = None
        self._count = 0
        self._acq_mono = 0.0
        self._acq_path = ""
        self._acq_line = 0
        self._waiters = 0
        self._contended = False
        self._entry = None

    def acquire(self, blocking=True, timeout=-1):
        me = threading.get_ident()
        if self._owner == me:
            self._count += 1
            return True
        got = self._raw.acquire(False)
        if not got:
            self._contended = True
            if not blocking:
                return False
            self._waiters += 1
            try:
                got = self._raw.acquire(True, timeout)
            finally:
                self._waiters -= 1
            if not got:
                return False
        self._owner = me
        self._count = 1
        self._san._acquired(self)
        return True

    def release(self):
        if self._owner != threading.get_ident():
            raise RuntimeError("cannot release un-acquired lock")
        self._count -= 1
        if self._count:
            return
        self._san._releasing(self)
        self._owner = None
        self._raw.release()

    def locked(self):
        return self._raw.locked()

    def _is_owned(self):
        return self._owner == threading.get_ident()

    # Condition protocol: wait() parks OUTSIDE the hold — the saved
    # recursion count is restored (and the hold re-timed, the edges
    # re-checked) on wakeup.
    def _release_save(self):
        count = self._count
        self._san._releasing(self)
        self._count = 0
        self._owner = None
        self._raw.release()
        return count

    def _acquire_restore(self, count):
        got = self._raw.acquire(False)
        if not got:
            self._contended = True
            self._waiters += 1
            try:
                self._raw.acquire()
            finally:
                self._waiters -= 1
        self._owner = threading.get_ident()
        self._count = count
        self._san._acquired(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<mxsan RLock @ {self._site.key()}>"


class Sanitizer:
    """The engine: observed order graph + hold timing + thread
    lifecycle, findings deduped by :meth:`Finding.key`.

    One process-global instance backs the patched ``threading``
    factories (:func:`install`); tests build private instances whose
    :meth:`lock`/:meth:`rlock`/:meth:`condition` wrap raw primitives
    directly, so goldens never pollute the session gate."""

    def __init__(self, hold_ms=None):
        if hold_ms is None:
            hold_ms = envvars.get("MXNET_TPU_SANITIZE_HOLD_MS")
        self.hold_ms = hold_ms
        self.findings = []
        self.suppressed = []
        self._keys = set()
        self._state_lock = _RAW_LOCK()
        self._edges = {}            # (src, dst) -> witness str
        self._adj = {}              # src -> set(dst)
        self._cycles_seen = set()   # frozenset(sites)
        self._sites = {}            # (path, line) -> _Site
        self._tls = threading.local()
        self._threads = weakref.WeakKeyDictionary()  # Thread -> _Site
        self._joined = weakref.WeakSet()
        self._preexisting = weakref.WeakSet()
        for t in threading.enumerate():
            self._preexisting.add(t)

    # -- explicit constructors (tests, non-patched embedding) ----------
    def lock(self):
        path, line = _caller_site()
        return _SanLock(self, self._site(path, line))

    def rlock(self):
        path, line = _caller_site()
        return _SanRLock(self, self._site(path, line))

    def condition(self, lock=None):
        if lock is None:
            path, line = _caller_site()
            lock = _SanRLock(self, self._site(path, line))
        return _RAW_CONDITION(lock)

    def _site(self, path, line):
        key = (path, line)
        s = self._sites.get(key)
        if s is None:
            # setdefault is atomic under the GIL: a racing creator
            # loses its throwaway _Site and both threads share ONE
            # node (edges key on site identity)
            s = self._sites.setdefault(key, _Site(path, line))
        return s

    # -- acquisition tracking ------------------------------------------
    def _held(self):
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _acquired(self, lk):
        acq_path, acq_line = _caller_site()
        lk._owner = threading.get_ident()
        lk._acq_mono = time.monotonic()
        lk._acq_path = acq_path
        lk._acq_line = acq_line
        lk._contended = False
        held = self._held()
        for e in tuple(held):
            if e.site is not lk._site:
                self._edge(e, lk, acq_path, acq_line)
        entry = _HeldEntry(lk, lk._site, acq_path, acq_line, held)
        lk._entry = entry
        held.append(entry)

    def _releasing(self, lk):
        entry = lk._entry
        lk._entry = None
        if entry is not None:
            try:
                entry.held_list.remove(entry)
            except ValueError:
                pass
        dur_ms = (time.monotonic() - lk._acq_mono) * 1000.0
        if dur_ms <= self.hold_ms:
            return
        if not (lk._waiters > 0 or lk._contended):
            return
        site = lk._site
        acq = (lk._acq_path, lk._acq_line)
        f = Finding(
            "long-hold",
            f"{site.label()} held {dur_ms:.0f} ms with waiter(s) "
            f"blocked (acquired at "
            f"{self._site(*acq).key()}, threshold "
            f"{self.hold_ms:.0f} ms) — every thread queued on this "
            f"lock convoys behind the hold; shrink the critical "
            f"section (snapshot under the lock, work outside)",
            [site, self._site(*acq)])
        self._report(f, extra_lines=[acq])

    def _edge(self, held_entry, lk, acq_path, acq_line):
        src, dst = held_entry.site, lk._site
        pair = (src, dst)
        if pair in self._edges:
            return
        tname = threading.current_thread().name
        holder_at = self._site(held_entry.acq_path,
                               held_entry.acq_line).key()
        witness = (f"thread {tname!r} acquired {dst.label()} at "
                   f"{self._site(acq_path, acq_line).key()} while "
                   f"holding {src.label()} (acquired at {holder_at})")
        with self._state_lock:
            if pair in self._edges:
                return
            self._edges[pair] = witness
            self._adj.setdefault(src, set()).add(dst)
            cycle = self._find_cycle(dst, src)
        if cycle is not None:
            self._report_cycle(cycle)

    def _find_cycle(self, start, goal):
        """DFS ``start`` → ``goal`` through the order graph (called
        with the state lock held, right after adding goal→start): a
        path back means the new edge closed a cycle. Returns the site
        path [goal, start, ..., goal] or None."""
        stack = [(start, [goal, start])]
        seen = {start}
        while stack:
            node, path = stack.pop()
            for nxt in self._adj.get(node, ()):
                if nxt is goal:
                    return path + [goal]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _report_cycle(self, cycle):
        ring = frozenset(cycle)
        with self._state_lock:
            if ring in self._cycles_seen:
                return
            self._cycles_seen.add(ring)
            legs = [self._edges.get((a, b), f"{a.key()} -> {b.key()}")
                    for a, b in zip(cycle, cycle[1:])]
        sites = sorted(set(cycle), key=lambda s: s.key())
        f = Finding(
            "order-cycle",
            f"observed lock-order cycle across {len(sites)} locks "
            f"({', '.join(s.key() for s in sites)}): "
            f"{'; '.join(legs)} — threads taking these legs "
            f"concurrently deadlock; impose one global order or "
            f"snapshot-and-call-outside",
            sites)
        self._report(f)

    def _report(self, finding, extra_lines=()):
        with self._state_lock:
            if finding.key() in self._keys:
                return
            self._keys.add(finding.key())
        if _allowed(finding.rule, finding.sites, extra_lines):
            self.suppressed.append(finding)
        else:
            self.findings.append(finding)

    # -- thread lifecycle ----------------------------------------------
    def track_thread(self, thread, site=None):
        if site is None:
            path, line = _caller_site()
            site = self._site(path, line)
        self._threads[thread] = site

    def track_join(self, thread):
        self._joined.add(thread)

    def teardown_check(self):
        """Run the end-of-session thread checks; returns the full
        findings list (lock findings included)."""
        main = threading.main_thread()
        for t in threading.enumerate():
            if t is main or t.daemon or not t.is_alive():
                continue
            if t in self._preexisting:
                continue
            site = self._threads.get(t)
            where = site.label() if site else "start site unknown"
            name = _DIGITS_RE.sub("N", t.name)
            self._report_thread(Finding(
                "thread-leak",
                f"non-daemon thread {t.name!r} (started at {where}) "
                f"still alive at teardown — it outlives the session "
                f"and wedges interpreter shutdown; join it, stop its "
                f"owner, or make it a daemon",
                [site] if site else [],
                meta={"thread": name}), name)
        for t, site in list(self._threads.items()):
            if t.is_alive() or t.daemon:
                continue
            if t in self._joined:
                continue
            if site.rel.startswith("tests/"):
                continue        # short-lived test helpers may just end
            name = _DIGITS_RE.sub("N", t.name)
            self._report_thread(Finding(
                "thread-unjoined",
                f"non-daemon thread {t.name!r} (started at "
                f"{site.label()}) died without ever being joined — "
                f"its owner has no teardown ordering; join it where "
                f"its work is consumed",
                [site], meta={"thread": name}), name)
        return list(self.findings)

    def _report_thread(self, finding, name):
        # thread findings key on (rule, sites, normalized name) so two
        # pool workers ("x_0", "x_1") dedupe to one finding
        key = finding.meta.get("key") or \
            "|".join([finding.rule]
                     + sorted(s.key() for s in finding.sites) + [name])
        with self._state_lock:
            if key in self._keys:
                return
            self._keys.add(key)
        finding.meta["key"] = key
        if finding.sites and _allowed(finding.rule, finding.sites):
            self.suppressed.append(finding)
        else:
            self.findings.append(finding)


# ---------------------------------------------------------------------------
# global install: patch the threading factories
# ---------------------------------------------------------------------------

_ACTIVE = None
_INSTALL_LOCK = _RAW_LOCK()


def _patched_lock():
    san = _ACTIVE
    if san is not None:
        path, line = _caller_site()
        if _in_repo(path):
            return _SanLock(san, san._site(path, line))
    return _RAW_LOCK()


def _patched_rlock():
    san = _ACTIVE
    if san is not None:
        path, line = _caller_site()
        if _in_repo(path):
            return _SanRLock(san, san._site(path, line))
    return _RAW_RLOCK()


def _patched_condition(lock=None):
    san = _ACTIVE
    if san is not None and lock is None:
        path, line = _caller_site()
        if _in_repo(path):
            lock = _SanRLock(san, san._site(path, line))
    return _RAW_CONDITION(lock)


def _patched_start(self):
    san = _ACTIVE
    if san is not None:
        path, line = _caller_site()
        san.track_thread(self, san._site(path, line))
    return _RAW_THREAD_START(self)


def _patched_join(self, timeout=None):
    san = _ACTIVE
    if san is not None:
        san.track_join(self)
    return _RAW_THREAD_JOIN(self, timeout)


def install(hold_ms=None):
    """Activate the global sanitizer and patch the ``threading``
    factories. Idempotent; returns the active :class:`Sanitizer`."""
    global _ACTIVE
    with _INSTALL_LOCK:
        if _ACTIVE is not None:
            return _ACTIVE
        san = Sanitizer(hold_ms=hold_ms)
        _ACTIVE = san
        threading.Lock = _patched_lock
        threading.RLock = _patched_rlock
        threading.Condition = _patched_condition
        threading.Thread.start = _patched_start
        threading.Thread.join = _patched_join
        return san


def uninstall():
    """Restore the raw factories (tests). Locks created while active
    keep working — their wrappers hold their own raw locks."""
    global _ACTIVE
    with _INSTALL_LOCK:
        if _ACTIVE is None:
            return
        _ACTIVE = None
        threading.Lock = _RAW_LOCK
        threading.RLock = _RAW_RLOCK
        threading.Condition = _RAW_CONDITION
        threading.Thread.start = _RAW_THREAD_START
        threading.Thread.join = _RAW_THREAD_JOIN


def active():
    return _ACTIVE


# ---------------------------------------------------------------------------
# baseline + reporting (the pytest plugin's surface)
# ---------------------------------------------------------------------------

def load_baseline(path):
    """The committed baseline: a JSON list of finding keys (empty in a
    healthy repo). Missing file == empty."""
    try:
        with open(path, encoding="utf-8") as fh:
            return set(json.load(fh))
    except (OSError, ValueError):
        return set()


def unbaselined(findings, baseline):
    return [f for f in findings
            if (f.meta.get("key") or f.key()) not in baseline]


def report(findings):
    lines = [f"mxsan: {len(findings)} unbaselined finding(s)"]
    for f in findings:
        lines.append(f"  [{f.rule}] {f.message}")
        lines.append(f"    key: {f.meta.get('key') or f.key()}")
    return "\n".join(lines)
