"""KVStore — data-parallel parameter/gradient store.

API-compatible re-design of the reference KVStore
(include/mxnet/kvstore.h, src/kvstore/kvstore_local.h `KVStoreLocal`,
comm.h `CommCPU/CommDevice`, kvstore_nccl.h `KVStoreNCCL`,
kvstore_dist.h + ps-lite for multi-node) per SURVEY §5.8: one backend,
XLA collectives. Semantics preserved:

- ``init/push/pull/pushpull/broadcast``, ``set_optimizer``/``_set_updater``
  (update_on_kvstore), ``rank``/``num_workers``, sparse ``row_sparse_pull``;
- push aggregates the per-device values (the CommDevice reduce / NCCL
  allreduce analog) and either overwrites the stored value or runs the
  updater on it — matching KVStoreLocal::PushImpl;
- 'local'/'device'/'nccl' are single-process modes. On TPU the
  per-device gradient copies of one process are already on chips of one
  slice, so the reduce is a jitted sum that XLA lowers to ICI
  collectives when inputs are sharded (no P2P ring code: the XLA
  partitioner emits AllReduce).
- 'dist_sync'/'dist_async'/'dist_device_sync' are multi-process modes:
  ``jax.distributed.initialize`` (driven by tools/launch.py setting
  coordinator env vars — the dmlc tracker analog) gives every process
  the global device view; cross-host aggregation is a psum over the
  global mesh's data axis riding DCN. No server processes exist:
  `update_on_kvstore` means "run the optimizer on the aggregated value
  locally, identically on every worker" — bitwise-identical by SPMD
  construction, replacing the parameter-server role.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

from . import envvars
from .base import MXNetError
from .ndarray import NDArray
from .ndarray.ndarray import _wrap
from .parallel import comm as _allreduce
from .telemetry import events as _events
from .telemetry import recorder as _recorder
from .telemetry import spans as _spans
from .telemetry.registry import REGISTRY as _REGISTRY
from .telemetry.trace import (current_trace_id as _current_trace_id,
                              new_trace_id as _new_trace_id)

__all__ = ["KVStore", "create"]


def _wire_metrics(side):
    """Registry families for the dist_async RPC channel, one set per
    side ('client' = worker RPCs, 'server' = the parameter server).
    Created lazily on first dist use — a local kvstore never touches
    them."""
    lat = _REGISTRY.histogram(
        f"mxnet_tpu_kvstore_{side}_rpc_ms",
        f"dist_async {side}-observed RPC latency by op", ("op",))
    byt = _REGISTRY.counter(
        f"mxnet_tpu_kvstore_{side}_bytes_total",
        f"dist_async {side} wire bytes by op and direction",
        ("op", "direction"))
    return lat, byt


def create(name="local") -> "KVStore":
    """mx.kv.create factory (src/kvstore/kvstore.cc KVStore::Create)."""
    name = name.lower()
    if name in ("local", "local_update_cpu", "local_allreduce_cpu",
                "device", "local_allreduce_device", "nccl"):
        return KVStore(name)
    if name == "dist_async":
        return AsyncDistKVStore()
    if name in ("dist_sync", "dist_device_sync", "dist_sync_device", "dist"):
        return DistKVStore(name)
    if name == "horovod":
        return HorovodKVStore()
    raise MXNetError(f"unknown kvstore type {name!r}")


class KVStore:
    """Single-process store: aggregates across this process's devices."""

    def __init__(self, kind="local"):
        self._kind = kind
        self._store: dict = {}
        self._updater = None
        self._optimizer = None
        self._grad_compression = None
        # error-feedback residual state per reduce signature (stacked
        # sharded arrays living on their devices)
        self._comp_state: dict = {}

    # -- identity ----------------------------------------------------------
    @property
    def type(self):
        return self._kind

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    # -- core ops ----------------------------------------------------------
    def init(self, key, value):
        keys, values = _normalize(key, value)
        for k, v in zip(keys, values):
            vs = v if isinstance(v, (list, tuple)) else [v]
            if k in self._store:
                raise MXNetError(f"key {k} already initialized")
            self._store[k] = vs[0].copy()

    def push(self, key, value, priority=0):
        keys, values = _normalize(key, value)
        for k, v in zip(keys, values):
            merged = self._reduce(v if isinstance(v, (list, tuple)) else [v],
                                  key=k)
            self._apply(k, merged)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = _normalize(key, out)
        for k, o in zip(keys, outs):
            stored = self._get(k)
            for dst in (o if isinstance(o, (list, tuple)) else [o]):
                stored.copyto(dst)

    def pushpull(self, key, value, out=None, priority=0):
        """Fused push+pull (reference MXKVStorePushPullEx / the NCCL
        fused-pushpull of kvstore_nccl.h). When no server-side updater is
        set, ALL keys reduce in ONE compiled XLA computation
        (parallel/comm.py) whose all-reduces the compiler buckets —
        Trainer.step dispatches exactly one executable per step."""
        keys, values = _normalize(key, value)
        outs = values if out is None else _normalize(key, out)[1]
        if self._updater is None and self._try_fused_pushpull(keys, values, outs):
            return
        self.push(key, value, priority)
        self.pull(key, out if out is not None else value, priority)

    # -- fused reduce fast path -------------------------------------------
    def _reduce_devices(self, value_lists):
        """Participating device tuple for the fused reduce, or None when
        the layout doesn't qualify. Single-process: the devices of the
        per-context replicas (must agree across keys)."""
        if not _allreduce.can_fast_reduce(value_lists):
            return None
        devs = tuple(v.device for v in value_lists[0])
        return devs if len(devs) > 1 else None

    def _try_fused_pushpull(self, keys, values, outs) -> bool:
        from .ndarray import sparse as _sp
        vlists = []
        for v in values:
            vs = v if isinstance(v, (list, tuple)) else [v]
            if any(isinstance(a, _sp.BaseSparseNDArray) for a in vs):
                return False
            vlists.append([a._data for a in vs])
        devices = self._reduce_devices(vlists)
        if devices is None:
            return False
        # every read-back target must sit inside the reduce mesh; a
        # stored value or out on a foreign device takes the copyto path
        devset = set(devices)
        for k, o in zip(keys, outs):
            if self._get(k)._data.device not in devset:
                return False
            for dst in (o if isinstance(o, (list, tuple)) else [o]):
                if dst._data.device not in devset:
                    return False
        reduced = self._compiled_reduce(tuple(keys), vlists, devices)
        for k, garr, o in zip(keys, reduced, outs):
            stored = self._get(k)
            sh = _allreduce.shard_for_device(garr, stored._data.device)
            stored._set_data(sh.astype(stored._data.dtype)
                             if sh.dtype != stored._data.dtype else sh)
            for dst in (o if isinstance(o, (list, tuple)) else [o]):
                sh = _allreduce.shard_for_device(garr, dst._data.device)
                dst._set_data(sh.astype(dst._data.dtype)
                              if sh.dtype != dst._data.dtype else sh)
        return True

    def broadcast(self, key, value, out=None, priority=0):
        self.init(key, value)
        if out is not None:
            self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows (sparse embedding path —
        reference kvstore sparse pull, src/kvstore/kvstore_local.h
        unique-rowid merge). TPU-native: sort/unique/gather run
        ON-DEVICE (XLA); the only host sync is the unique count that
        sizes the row_sparse result — no value round-trips through
        numpy (the Wide&Deep hot loop stays on-chip)."""
        import jax.numpy as jnp
        from .ndarray import sparse as _sp
        keys, outs = _normalize(key, out)
        _, rids = _normalize(key, row_ids)
        for k, o, r in zip(keys, outs, rids):
            stored = self._get(k)
            dense = stored.todense()._data \
                if isinstance(stored, _sp.BaseSparseNDArray) else stored._data
            dsts = o if isinstance(o, (list, tuple)) else [o]
            rows = r if isinstance(r, (list, tuple)) else [r] * len(dsts)
            for dst, rid in zip(dsts, rows):
                ids = rid._data.reshape(-1).astype(jnp.int64)
                uniq = jnp.unique(ids)
                picked = jnp.take(dense, uniq, axis=0)
                if isinstance(dst, _sp.RowSparseNDArray):
                    # rebuild the row_sparse triple in place
                    dst._data = picked.astype(dst._data.dtype)
                    dst._aux = uniq
                    dst._version += 1
                else:
                    full = jnp.zeros(stored.shape, dst.dtype)
                    full = full.at[uniq].set(picked.astype(dst.dtype))
                    dst._set_data(full)

    # -- optimizer / updater ----------------------------------------------
    def set_optimizer(self, optimizer):
        from .optimizer import get_updater
        self._optimizer = optimizer
        self._updater = get_updater(optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    def set_gradient_compression(self, compression_params):
        """Enable compressed gradient reduce with error feedback
        (reference src/kvstore/gradient_compression.cc 2-bit path).
        {'type': '2bit', 'threshold': t} maps each (grad+residual)
        element to {±t, 0}; {'type': 'int8'} uses symmetric per-tensor
        int8 with in-graph scales. The quantize/residual-update/reduce
        pipeline compiles into the fused all-reduce program
        (parallel/comm.py reduce_compressed_replica_lists)."""
        params = dict(compression_params)
        ctype = params.get("type", "2bit")
        if ctype not in ("2bit", "int8", "none"):
            raise MXNetError(f"unsupported gradient compression type {ctype!r}")
        self._grad_compression = None if ctype == "none" else params
        self._comp_state.clear()

    # -- optimizer state io (reference save/load via updater pickle) ------
    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("there is no optimizer set to this kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("there is no optimizer set to this kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    # -- telemetry ---------------------------------------------------------
    def expose(self, port=0, host="127.0.0.1"):
        """Start a telemetry exposition server for this store's
        process: Prometheus ``/metrics`` off the process registry
        (dist_async RPC latency/bytes land there on both ends),
        ``/healthz`` from :meth:`_healthz`, and ``/stats`` with store
        identity + key count. ``port=0`` picks a free port."""
        from .telemetry.expo import TelemetryServer

        if getattr(self, "_expo", None) is None:
            def stats():
                return {"type": self.type, "rank": self.rank,
                        "num_workers": self.num_workers,
                        "keys": len(self._store)}

            self._expo = TelemetryServer(healthz_fn=self._healthz,
                                         stats_fn=stats,
                                         port=port, host=host)
            _events.emit("telemetry_expose", component="kvstore",
                         port=self._expo.port, host=self._expo.host)
        return self._expo

    def _healthz(self):
        return True, {"type": self.type, "rank": self.rank}

    # -- internals ---------------------------------------------------------
    def _get(self, k):
        if k not in self._store:
            raise MXNetError(f"key {k} was not initialized")
        return self._store[k]

    def _compiled_reduce(self, sig, vlists, devices):
        """Fused reduce for a batch of keys — compressed (with
        per-signature error-feedback state) when set_gradient_compression
        configured a supported type, plain stacked-sum otherwise."""
        comp = self._grad_compression
        if comp and comp.get("type") in ("2bit", "int8"):
            state_key = (sig, devices,
                         tuple((tuple(v[0].shape), str(v[0].dtype))
                               for v in vlists))
            reduced, new_res = _allreduce.reduce_compressed_replica_lists(
                vlists, self._comp_state.get(state_key), devices=devices,
                ctype=comp["type"],
                threshold=float(comp.get("threshold", 0.5)))
            self._comp_state[state_key] = new_res
            return reduced
        return _allreduce.reduce_replica_lists(vlists, devices=devices)

    def _reduce(self, arrays, key=None):
        """Sum per-device values — a single compiled stacked-sum whose
        output sharding is replicated, which the XLA SPMD partitioner
        lowers to an ICI AllReduce (the CommDevice/NCCL analog)."""
        from .ndarray import sparse as _sp
        if any(isinstance(a, _sp.RowSparseNDArray) for a in arrays):
            return _merge_row_sparse(arrays)
        merged = arrays[0]
        if len(arrays) > 1:
            datas = [a._data for a in arrays]
            devices = self._reduce_devices([datas])
            if devices is not None:
                garr = self._compiled_reduce((key,), [datas], devices)[0]
                return _wrap(_allreduce.shard_for_device(garr, datas[0].device),
                             merged.ctx)
            # fallback: replicas sharing a device (tests) — eager add tree
            ctx = merged.ctx
            acc = merged._data
            for a in arrays[1:]:
                other = a._data
                if other.device != acc.device:
                    other = jax.device_put(other, acc.device)
                acc = acc + other
            merged = _wrap(acc, ctx)
        return merged

    def _apply(self, k, merged):
        from .ndarray import sparse as _sp
        stored = self._get(k)
        if isinstance(merged, _sp.BaseSparseNDArray):
            # keep the sparse type intact: the updater's optimizer routes
            # row_sparse grads to the lazy rsp update rules (astype would
            # silently strip indices and corrupt the update)
            if self._updater is not None:
                self._updater(k, merged, stored)
            else:
                stored._set_data(
                    merged.todense()._data.astype(stored.dtype))
            return
        if self._updater is not None:
            self._updater(k, merged.astype(stored.dtype), stored)
        else:
            stored._set_data(merged._data.astype(stored.dtype))

    def __repr__(self):
        return f"<KVStore {self._kind} rank={self.rank}/{self.num_workers}>"


class DistKVStore(KVStore):
    """Multi-process store over jax.distributed (the ps-lite analog —
    but serverless: every worker holds the aggregated value by SPMD)."""

    def __init__(self, kind="dist_sync"):
        super().__init__(kind)
        self._initialized = _maybe_init_distributed()

    @property
    def rank(self):
        return jax.process_index() if self._initialized else 0

    @property
    def num_workers(self):
        return jax.process_count() if self._initialized else 1

    def _reduce_devices(self, value_lists):
        """Cross-process fused reduce: when every process's local arrays
        cover exactly its addressable devices, the global device list
        forms the 1-D reduce mesh and the compiled sum IS the DCN/ICI
        AllReduce (every worker runs the same SPMD program — no server,
        no host gather)."""
        if self.num_workers > 1:
            if not _allreduce.can_fast_reduce(value_lists):
                return None
            if len(value_lists[0]) == jax.local_device_count():
                return tuple(jax.devices())
            return None
        return super()._reduce_devices(value_lists)

    def _reduce(self, arrays, key=None):
        if self.num_workers > 1:
            datas = [a._data for a in arrays]
            devices = self._reduce_devices([datas])
            if devices is not None:
                garr = self._compiled_reduce((key,), [datas], devices)[0]
                return _wrap(_allreduce.shard_for_device(garr, datas[0].device),
                             arrays[0].ctx)
            return _cross_process_allreduce(super()._reduce(arrays, key=key))
        return super()._reduce(arrays, key=key)

    def barrier(self):
        """_barrier analog (ps-lite Barrier): sync all workers."""
        if self.num_workers > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("mxnet_tpu_kvstore_barrier")


class _ParameterServer:
    """Host-side parameter server (the ps-lite server role) for
    ``dist_async``: runs as a daemon thread in worker 0's process,
    speaking length-prefixed TYPED frames over TCP (``_wire_encode`` —
    plain data + raw ndarray bytes, nothing executable; and the socket
    binds the launcher-announced interface, not 0.0.0.0). State and
    updates live
    in a plain local :class:`KVStore` on host-CPU NDArrays — exactly
    the reference's CPU server-side update path
    (src/kvstore/kvstore_dist_server.h); workers push gradients and
    pull weights with NO inter-worker synchronization, so updates
    apply in arrival order (stale gradients by design — the dist_async
    contract)."""

    def __init__(self, host, port, num_workers):
        import socket
        import threading
        import time as _time

        self._store = KVStore("local")
        self._lock = threading.Lock()
        self._opt_payload = None
        self._num_workers = num_workers
        self._barrier_count = 0
        self._barrier_cv = threading.Condition()
        self._barrier_gen = 0
        # watchdog surface: per-connection in-flight handles (thread
        # ident -> (op, started)) and a last-served heartbeat so a
        # handle wedged in an optimizer update is detectable; own lock
        # because handler threads mutate it while the watchdog reads
        self._inflight = {}
        self._inflight_lock = threading.Lock()
        self._last_handle = _time.monotonic()
        _REGISTRY.gauge(
            "mxnet_tpu_kvstore_server_last_handle_age_s",
            "seconds since the parameter server last served an RPC"
        ).set_function(lambda: _time.monotonic() - self._last_handle)
        _recorder.install()
        _recorder.register_probe("kvstore_server", self._watchdog_probe)
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            srv.bind((host, port))
        except OSError:
            # the launcher-announced address may be a NAT/bridged
            # front address not assigned to any local interface
            # (containerized deployments); availability beats the
            # narrower bind there — fall back loudly to all interfaces
            import sys
            print(f"mxnet_tpu dist_async server: cannot bind "
                  f"{host}:{port} locally; falling back to 0.0.0.0",
                  file=sys.stderr)
            srv.bind(("0.0.0.0", port))
        srv.listen(num_workers + 2)
        self._srv = srv
        threading.Thread(target=self._accept_loop,
                         name="mxnet_tpu_kvstore_accept",
                         daemon=True).start()

    def _accept_loop(self):
        import threading
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             name=f"mxnet_tpu_kvstore_serve_fd{conn.fileno()}",
                             daemon=True).start()

    def _watchdog_probe(self):
        """Anomaly when any in-flight handle has been running past the
        stall threshold (an optimizer update or store op wedged)."""
        import time as _time
        now = _time.monotonic()
        stall = _recorder.stall_seconds()
        with self._inflight_lock:
            inflight = list(self._inflight.values())
        for op, started in inflight:
            if now - started > stall:
                return {"kind": "kvstore_server_stall", "op": op,
                        "seconds_in_flight": round(now - started, 3)}
        return None

    def _serve(self, conn):
        import threading
        import time as _time
        lat, byt = _wire_metrics("server")
        try:
            while True:
                sized = _recv_msg_sized(conn)
                if sized is None:
                    return
                msg, nbytes_in = sized
                if not isinstance(msg, tuple) or len(msg) not in (3, 4, 5):
                    raise ValueError(
                        "RPC frame must be (op, key, payload[, trace_id"
                        f"[, span_id]]), got {type(msg).__name__}")
                op, key, payload = msg[:3]
                # trace id rides the frame (4th field) so this handle
                # correlates with the worker-side rpc event on one
                # push; the 5th field (new) is the worker's RPC span
                # id, which this handle span parents under — a
                # cross-process span tree on one trace
                tid = msg[3] if len(msg) >= 4 else None
                remote_span = msg[4] if len(msg) >= 5 else None
                opname = op if isinstance(op, str) else "?"
                t0 = _time.perf_counter()
                handle_span = _spans.start_span(
                    f"kvstore/server/{opname}", trace_id=tid,
                    parent_id=remote_span, local_root=True,
                    attrs={"op": opname, "key": key,
                           "bytes_in": nbytes_in})
                me = threading.get_ident()
                with self._inflight_lock:
                    self._inflight[me] = (opname, _time.monotonic())
                try:
                    with _spans.use_span(handle_span):
                        try:
                            reply = ("ok", self._handle(op, key, payload))
                        except (ConnectionError, EOFError, OSError):
                            raise
                        except Exception as e:  # reply, don't kill the
                            import traceback    # server
                            reply = ("err", f"{e!r}\n"
                                     f"{traceback.format_exc(limit=5)}")
                    nbytes_out = _send_msg(conn, reply)
                    handle_span.end(status="ok" if reply[0] == "ok"
                                    else "error",
                                    error=None if reply[0] == "ok"
                                    else str(reply[1])[:200])
                finally:
                    with self._inflight_lock:
                        self._inflight.pop(me, None)
                    self._last_handle = _time.monotonic()
                    # end() is idempotent (first end wins): on success
                    # the real status was already recorded above and
                    # this is a no-op; it only closes the span when
                    # handle/send blew up, so a dropped connection
                    # can't pin the trace's active buffer with an open
                    # local root forever
                    handle_span.end(error="connection lost mid-handle")
                ms = (_time.perf_counter() - t0) * 1e3
                lat.labels(op=opname).observe(ms)
                byt.labels(op=opname, direction="in").inc(nbytes_in)
                byt.labels(op=opname, direction="out").inc(nbytes_out)
                _events.emit("kvstore_server_handle", op=opname, key=key,
                             ms=round(ms, 3), bytes_in=nbytes_in,
                             bytes_out=nbytes_out, ok=reply[0] == "ok",
                             trace_id=tid, span_id=handle_span.span_id,
                             parent_span_id=remote_span)
        except (ConnectionError, EOFError, OSError):
            return
        except (ValueError, MXNetError) as e:
            # malformed/refused wire frame: drop THIS client, keep
            # serving the rest (and leave a trace for the operator)
            import sys
            _REGISTRY.counter(
                "mxnet_tpu_kvstore_wire_refusals_total",
                "dist_async frames refused by the typed codec").inc()
            _events.emit("wire_frame_refused", error=str(e))
            print(f"mxnet_tpu dist_async server: dropping connection on "
                  f"bad frame: {e}", file=sys.stderr)
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, op, key, payload):
        from .context import cpu as _cpu
        from . import ndarray as _ndmod
        if op == "init":
            with self._lock:
                if key not in self._store._store:
                    self._store.init(key, _ndmod.array(payload, ctx=_cpu(0)))
            return None
        if op == "push":
            with self._lock:
                # the server-side optimizer update is the dist_async
                # hot path; its span parents under this handle (which
                # parents under the worker's RPC span across the wire)
                with _spans.span("kvstore/server/optimizer_update",
                                 key=key,
                                 updater=self._store._updater is not None):
                    self._store.push(key,
                                     _ndmod.array(payload, ctx=_cpu(0)))
            return None
        if op == "pull":
            with self._lock:
                return self._store._get(key).asnumpy()
        if op == "setopt":
            with self._lock:
                # replace on a genuinely different optimizer (resets
                # updater state, as setting a new optimizer should);
                # equal re-sends from other workers are idempotent
                if payload != self._opt_payload:
                    from . import optimizer as _optmod
                    name, attrs, sched_spec = payload
                    opt = _optmod.create(name)
                    for k, v in attrs.items():
                        setattr(opt, k, dict(v) if isinstance(v, dict)
                                else v)
                    if sched_spec is not None:
                        opt.lr_scheduler = _rebuild_wire_scheduler(
                            sched_spec)
                    self._opt_payload = payload
                    self._store.set_optimizer(opt)
                    _events.emit("kvstore_optimizer_update", kind="setopt",
                                 optimizer=name)
            return None
        if op == "optattr":
            # per-step optimizer attribute sync (rescale_grad changes on
            # every Trainer.step; the pickled optimizer would go stale)
            name, value = payload
            with self._lock:
                if self._store._optimizer is not None:
                    setattr(self._store._optimizer, name, value)
            _events.emit("kvstore_optimizer_update", kind="optattr",
                         attr=name, value=value)
            return None
        if op == "barrier":
            with self._barrier_cv:
                gen = self._barrier_gen
                self._barrier_count += 1
                if self._barrier_count >= self._num_workers:
                    self._barrier_count = 0
                    self._barrier_gen += 1
                    self._barrier_cv.notify_all()
                elif not self._barrier_cv.wait_for(
                        lambda: self._barrier_gen != gen, timeout=300.0):
                    # a silent 'ok' after timeout would let the caller
                    # proceed on orderings the barrier was guarding
                    self._barrier_count -= 1
                    raise MXNetError(
                        "dist_async barrier timed out after 300 s "
                        "(a worker is stuck or gone)")
            return None
        raise MXNetError(f"unknown op {op!r}")


# -- dist_async wire codec ------------------------------------------------
# The typed, NON-EXECUTABLE frame codec was born here (replacing the
# pickled frames whose decode was remote code execution) and now lives
# in mxnet_tpu/serving/wire.py, shared with the serving dispatch wire.
# These thin wrappers keep kvstore's historical names — tests and the
# 2-process workers import them from here — and pin the dist_async
# channel's own frame cap. The import is lazy on purpose: kvstore
# loads BEFORE the serving package during `import mxnet_tpu`, and at
# RPC time everything is initialized.
_WIRE_MAX_FRAME = 1 << 33          # 8 GiB: no 'length bomb' allocations


def _wire_mod():
    from .serving import wire
    return wire


def _wire_encode(obj) -> bytes:
    return _wire_mod().wire_encode(obj)


def _wire_decode(data) -> object:
    return _wire_mod().wire_decode(data)


def _send_msg(sock, obj):
    """Encode + length-prefix + send; returns the frame's byte size so
    callers can account wire traffic without re-encoding."""
    return _wire_mod().send_frame(sock, obj, max_frame=_WIRE_MAX_FRAME)


def _recv_msg_sized(sock):
    """(decoded object, frame bytes) — None on a cleanly closed peer.
    An over-cap length prefix raises FrameTooLargeError (an MXNetError
    AND a ValueError, matching both historical refusal paths)."""
    return _wire_mod().recv_frame(sock, max_frame=_WIRE_MAX_FRAME)


def _recv_msg(sock):
    sized = _recv_msg_sized(sock)
    return sized[0] if sized is not None else None


def _optimizer_wire_spec(optimizer):
    """(registry name, scalar attr table, scheduler spec) — what
    set_optimizer sends instead of a pickled object. The server
    rebuilds via ``optimizer.create(name)`` and overwrites every
    scalar (and dict-of-scalar: lr_mult/wd_mult/idx2name) attribute,
    so tuned hyperparameters survive the wire. The lr_scheduler rides
    the same way — (class name in mxnet_tpu.lr_scheduler, scalar/list
    attr table) — because server-side updates must follow the SCHEDULED
    lr as the server's num_update advances (the pickled path did; a
    spec that dropped it would silently train at the base lr forever).
    Device-backed state (param_dict) and anything else callable does
    not ride — same trade the reference made sending the optimizer
    STRING to ps-lite servers."""
    def scalar(v):
        return v is None or isinstance(v, (bool, int, float, str))

    def listy(v):
        return (isinstance(v, (list, tuple))
                and all(scalar(x) for x in v))

    attrs = {}
    for k, v in vars(optimizer).items():
        if k in ("param_dict", "lr_scheduler", "sym"):
            continue
        if scalar(v):
            attrs[k] = v
        elif isinstance(v, dict) and all(
                scalar(kk) and scalar(vv) for kk, vv in v.items()):
            attrs[k] = v
    sched = getattr(optimizer, "lr_scheduler", None)
    sched_spec = None
    if sched is not None:
        sattrs = {k: (list(v) if listy(v) and not scalar(v) else v)
                  for k, v in vars(sched).items()
                  if scalar(v) or listy(v)}
        sched_spec = (type(sched).__name__, sattrs)
    return (type(optimizer).__name__.lower(), attrs, sched_spec)


def _rebuild_wire_scheduler(sched_spec):
    """Server side: rebuild the lr scheduler from its typed spec.
    Only classes defined in mxnet_tpu.lr_scheduler are eligible —
    the name is a lookup in ONE trusted module, never an import."""
    from . import lr_scheduler as _lrs
    cls_name, sattrs = sched_spec
    cls = getattr(_lrs, cls_name, None)
    if not (isinstance(cls, type) and issubclass(cls, _lrs.LRScheduler)):
        raise MXNetError(f"unknown lr scheduler {cls_name!r} on the wire")
    sched = cls.__new__(cls)    # attr bag; __call__ reads attrs only
    for k, v in sattrs.items():
        setattr(sched, k, list(v) if isinstance(v, tuple) else v)
    return sched


class AsyncDistKVStore(KVStore):
    """``dist_async``: true asynchronous multi-process training
    (reference dist_async semantics, src/kvstore/kvstore_dist.h with
    server-side updates): worker 0's process hosts a TCP parameter
    server; every worker pushes gradients (applied on arrival — no
    gradient aggregation barrier, no lockstep between workers) and
    pulls the latest weights. Progress is per-worker; staleness is the
    accepted trade, exactly as in the reference. jax.distributed is
    NOT required — the PS channel is plain host TCP (DCN), keeping the
    accelerators free for compute."""

    def __init__(self):
        super().__init__("dist_async")
        import socket
        import time as _time
        self._rank = int(envvars.get_raw("MXNET_TPU_PROC_ID")
                         or os.environ.get("DMLC_WORKER_ID") or 0)
        self._n = int(envvars.get_raw("MXNET_TPU_NUM_PROCS")
                      or os.environ.get("DMLC_NUM_WORKER") or 1)
        host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        # the jax.distributed coordinator (dist_sync) owns ROOT_PORT;
        # the async server claims a fixed offset above it
        port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9000")) + 1717
        self._server = None
        if self._rank == 0 and self._n > 1:
            # bind the launcher-announced interface (the address every
            # worker dials), NOT 0.0.0.0 — the parameter store should
            # not listen on interfaces the job never asked for
            self._server = _ParameterServer(host, port, self._n)
        import threading
        self._rpc_lock = threading.Lock()
        self._wire_metrics = _wire_metrics("client")
        self._sent_optattrs = {}
        self._sock = None
        self._rpc_inflight = None      # (op, monotonic started) or None
        if self._n > 1:
            _recorder.install()
            _recorder.register_probe(f"kvstore_worker_{self._rank}",
                                     self._rpc_watchdog_probe)
            deadline = _time.monotonic() + 60.0
            last = None
            while _time.monotonic() < deadline:
                try:
                    s = socket.create_connection((host, port), timeout=5.0)
                    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    s.settimeout(None)  # barriers block far past the
                    # connect timeout; blocking mode for the RPC stream
                    self._sock = s
                    break
                except OSError as e:
                    last = e
                    _time.sleep(0.2)
            if self._sock is None:
                raise MXNetError(
                    f"dist_async worker {self._rank} could not reach the "
                    f"parameter server at {host}:{port}: {last!r}")

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._n

    def _healthz(self):
        """dist_async liveness: this worker still holds its server
        connection; on rank 0, the parameter server socket is open."""
        detail = {"type": self.type, "rank": self._rank,
                  "workers": self._n}
        ok = self._n <= 1 or self._sock is not None
        if self._server is not None:
            srv_up = self._server._srv.fileno() != -1
            detail["server_listening"] = srv_up
            ok = ok and srv_up
        return ok, detail

    def _rpc_watchdog_probe(self):
        """Anomaly when one RPC has been in flight past the stall
        threshold — the server stopped answering (stale heartbeat from
        this worker's point of view)."""
        import time as _time
        inflight = self._rpc_inflight
        if inflight is None:
            return None
        op, started = inflight
        waited = _time.monotonic() - started
        if waited > _recorder.stall_seconds():
            return {"kind": "kvstore_rpc_stall", "op": op,
                    "rank": self._rank,
                    "seconds_in_flight": round(waited, 3)}
        return None

    def _rpc(self, op, key, payload=None):
        import time as _time
        # the active trace id (a serving request, a Trainer step's
        # scope) rides the frame; an RPC outside any context mints its
        # own so worker- and server-side logs still correlate. The RPC
        # span's id rides as the 5th frame field — the server's handle
        # span parents under it, one tree across two processes.
        with _spans.span(f"kvstore/rpc/{op}", op=op, key=key,
                         rank=self._rank) as sp:
            tid = _current_trace_id() or sp.trace_id \
                or _new_trace_id("kv")
            t0 = _time.perf_counter()
            with self._rpc_lock:
                # read + check the socket INSIDE the lock: a concurrent
                # RPC that lost the connection nulls it, and a waiter
                # must see MXNetError, not _send_msg(None) blowing up
                sock = self._sock
                if sock is None:
                    raise MXNetError(
                        "dist_async parameter server connection is down "
                        f"(lost on an earlier RPC); cannot send {op!r}")
                self._rpc_inflight = (op, _time.monotonic())
                try:
                    # _rpc_lock IS the socket mutex: request/reply pairs
                    # from concurrent pushers must not interleave on one
                    # TCP stream, so holding it across the round trip is
                    # the design, not an accident
                    # mxlint: disable=lock-blocking-call
                    nbytes_out = _send_msg(
                        sock, (op, key, payload, tid, sp.span_id))
                    sized = _recv_msg_sized(sock)  # mxlint: disable=lock-blocking-call
                except OSError:
                    self._sock = None   # /healthz must see the loss
                    raise
                finally:
                    self._rpc_inflight = None
                if sized is None:
                    # half-closed peer: mark the connection dead so
                    # liveness probes (and later RPCs) report it
                    # instead of a live sock
                    self._sock = None
            if sized is None:
                raise MXNetError(
                    "dist_async parameter server connection lost "
                    f"(worker 0's process gone?) during {op!r}")
            reply, nbytes_in = sized
            ms = (_time.perf_counter() - t0) * 1e3
            sp.set_attr(bytes_out=nbytes_out, bytes_in=nbytes_in)
            lat, byt = self._wire_metrics
            lat.labels(op=op).observe(ms)
            byt.labels(op=op, direction="out").inc(nbytes_out)
            byt.labels(op=op, direction="in").inc(nbytes_in)
            _events.emit("kvstore_rpc", op=op, key=key, ms=round(ms, 3),
                         bytes_out=nbytes_out, bytes_in=nbytes_in,
                         rank=self._rank, trace_id=tid,
                         span_id=sp.span_id)
            status, out = reply
            if status != "ok":
                raise MXNetError(f"dist_async server error: {out}")
            return out

    def init(self, key, value):
        if self._n <= 1:
            return super().init(key, value)
        keys, values = _normalize(key, value)
        for k, v in zip(keys, values):
            vs = v if isinstance(v, (list, tuple)) else [v]
            self._rpc("init", k, vs[0].asnumpy())
            # local replica for pulls into stored dtype/shape checks
            self._store[k] = vs[0].copy()

    def _sync_optattrs(self):
        """Mirror scalar optimizer attributes the worker mutates after
        set_optimizer through the optattr RPC, so the server's copy
        applies the CURRENT values: rescale_grad changes on every
        Trainer.step, lr/wd via Trainer.set_learning_rate /
        setattr(trainer.optimizer, 'wd', ...) — without this the
        server would keep applying the pickled-at-setopt values
        forever."""
        opt = self._optimizer
        if opt is None:
            return
        for name in ("rescale_grad", "lr", "wd"):
            val = getattr(opt, name, None)
            if val is not None and val != self._sent_optattrs.get(name):
                self._rpc("optattr", None, (name, val))
                self._sent_optattrs[name] = val

    def push(self, key, value, priority=0):
        if self._n <= 1:
            return super().push(key, value, priority)
        # the server applies updates with ITS optimizer copy — mirror
        # the attributes Trainer mutates per step before the gradients
        # they govern arrive
        self._sync_optattrs()
        keys, values = _normalize(key, value)
        for k, v in zip(keys, values):
            merged = self._reduce(v if isinstance(v, (list, tuple))
                                  else [v], key=k)
            self._rpc("push", k, merged.asnumpy())

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        if self._n <= 1:
            return super().pull(key, out, priority, ignore_sparse)
        from . import ndarray as _ndmod
        keys, outs = _normalize(key, out)
        for k, o in zip(keys, outs):
            arr = self._rpc("pull", k)
            for dst in (o if isinstance(o, (list, tuple)) else [o]):
                _ndmod.array(arr, ctx=dst.ctx,
                             dtype=str(dst.dtype)).copyto(dst)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        self.pull(key, out if out is not None else value, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        if self._n > 1:
            # the base implementation reads the LOCAL replica — refresh
            # it from the server first or sparse pulls would return
            # frozen init-time weights forever
            from . import ndarray as _ndmod
            keys, _ = _normalize(key, out)
            for k in keys:
                arr = self._rpc("pull", k)
                stored = self._store.get(k)
                if stored is None:
                    self._store[k] = _ndmod.array(arr)
                else:
                    _ndmod.array(arr, ctx=stored.ctx,
                                 dtype=str(stored.dtype)).copyto(stored)
        return super().row_sparse_pull(key, out, priority, row_ids)

    def set_optimizer(self, optimizer):
        if self._n <= 1:
            return super().set_optimizer(optimizer)
        # typed (name, scalar-attr-table) spec — nothing executable
        # crosses the wire; device-backed param_dict never rides (the
        # reference sends the optimizer string to servers the same way)
        self._rpc("setopt", None, _optimizer_wire_spec(optimizer))
        self._optimizer = optimizer  # tracked for per-step attr sync
        self._sent_optattrs = {}     # new server copy: resend attrs

    def barrier(self):
        if self._n > 1:
            self._rpc("barrier", None)


class HorovodKVStore(DistKVStore):
    """``kvstore='horovod'`` shim (reference python/mxnet/kvstore.py
    KVStoreHorovod, v>=1.5): the allreduce-only store. Upstream it
    delegates broadcast/pushpull to horovod.mxnet (MPI/NCCL rings) and
    supports ONLY ``broadcast`` + ``pushpull`` — no push/pull, no
    server-side optimizer (Trainer always updates locally). The
    TPU-native ring is the shared compiled XLA AllReduce: DistKVStore's
    reduce path covers both fabrics (ICI within a process, global-mesh /
    DCN psum across processes when jax.distributed is live), so this
    subclass only applies the horovod API restrictions on top."""

    def __init__(self):
        super().__init__("horovod")

    @property
    def local_rank(self):
        # set per worker by tools/launch.py (rank within this host);
        # single-process or unlaunched runs are local rank 0
        return envvars.get("MXNET_TPU_LOCAL_RANK")

    def push(self, key, value, priority=0):
        raise MXNetError("push is not supported by horovod kvstore; "
                         "use pushpull")

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        raise MXNetError("pull is not supported by horovod kvstore; "
                         "use pushpull or broadcast")

    def pushpull(self, key, value, out=None, priority=0):
        """hvd.allreduce(sum) analog: reduce values across all replicas
        into out (or in place). No store-side updater ever runs; the
        fused multi-key reduce (one compiled XLA program) is shared with
        the 'device'/'dist' stores. The stored value always ends up as
        the REDUCED result (so a later broadcast serves fresh data)."""
        keys, values = _normalize(key, value)
        outs = values if out is None else _normalize(key, out)[1]
        for k, v in zip(keys, values):
            if k not in self._store:
                vs = v if isinstance(v, (list, tuple)) else [v]
                self._store[k] = vs[0].copy()
        if self._try_fused_pushpull(keys, values, outs):
            return
        # fallback: the base push (reduce into store — no updater can
        # ever be set here) + pull (copy out), explicitly bypassing this
        # class's disabled overrides
        KVStore.push(self, key, value, priority)
        KVStore.pull(self, key, out if out is not None else value, priority)

    def broadcast(self, key, value, out=None, priority=0):
        """hvd.broadcast_parameters analog: the ROOT's (process 0's)
        CURRENT value wins — the store is overwritten on every call
        (upstream re-transmits each time; serving a stale stored value
        would silently drop updates). With num_workers > 1 the bytes
        really cross hosts via ``multihost_utils.broadcast_one_to_all``
        so rank-dependent initialization / rank-0-only checkpoint
        restores converge instead of silently diverging per worker."""
        keys, values = _normalize(key, value)
        firsts = [v[0] if isinstance(v, (list, tuple)) else v
                  for v in values]
        datas = [f._data for f in firsts]
        if self.num_workers > 1:
            from jax.experimental import multihost_utils
            # one pytree collective for the whole key list — N keys
            # cost one DCN round trip, not N host-synced ones
            datas = list(multihost_utils.broadcast_one_to_all(tuple(datas)))
        for k, f, new in zip(keys, firsts, datas):
            if self.num_workers == 1:
                # single-worker: ``new`` IS the caller's buffer and the
                # device_put below may alias it — the store must own a
                # copy (the caller may later donate its own buffer)
                new = new.copy()
            if k in self._store:
                stored = self._store[k]
                if new.dtype != stored.dtype:
                    new = new.astype(stored.dtype)
                # pin onto the stored replica's device (mirrors the
                # _try_fused_pushpull read-back path) so the store can't
                # drift off-device and decline the fused fast path later
                stored._set_data(jax.device_put(new, stored._data.device))
            else:
                self._store[k] = _wrap(jax.device_put(new, f._data.device),
                                       f.ctx)
        if out is not None:
            _, outs = _normalize(key, out)
            for k, o in zip(keys, outs):
                stored = self._get(k)
                for dst in (o if isinstance(o, (list, tuple)) else [o]):
                    stored.copyto(dst)

    def set_optimizer(self, optimizer):
        raise MXNetError("cannot set optimizer on horovod kvstore "
                         "(update_on_kvstore is always False)")

    def _set_updater(self, updater):
        raise MXNetError("cannot set updater on horovod kvstore")


def _maybe_init_distributed() -> bool:
    """jax.distributed.initialize from DMLC-compatible env (tools/launch.py
    sets MXNET_TPU_COORDINATOR / DMLC_PS_ROOT_URI+PORT, num/id).

    The env check runs FIRST: merely asking jax.process_count() would
    initialize the local XLA backend, after which the multi-process
    rendezvous is impossible (initialize() must precede any backend
    use)."""
    coord = envvars.get("MXNET_TPU_COORDINATOR")
    n = envvars.get_raw("MXNET_TPU_NUM_PROCS") or os.environ.get("DMLC_NUM_WORKER")
    pid = envvars.get_raw("MXNET_TPU_PROC_ID") or os.environ.get("DMLC_WORKER_ID")
    if not coord and os.environ.get("DMLC_PS_ROOT_URI"):
        coord = (os.environ["DMLC_PS_ROOT_URI"] + ":"
                 + os.environ.get("DMLC_PS_ROOT_PORT", "9000"))
    if coord and n and pid is not None:
        try:
            jax.distributed.initialize(coordinator_address=coord,
                                       num_processes=int(n),
                                       process_id=int(pid))
            return True
        except Exception as e:  # already initialized or single-proc fallback
            import sys
            print(f"mxnet_tpu: jax.distributed.initialize failed: {e!r}",
                  file=sys.stderr)
            return jax.process_count() > 1
    return jax.process_count() > 1


def _cross_process_allreduce(merged: NDArray) -> NDArray:
    """psum across processes over the global mesh data axis (DCN/ICI)."""
    from jax.experimental import multihost_utils
    # simplest correct eager path: gather-to-all then sum locally.
    summed = multihost_utils.process_allgather(merged._data).sum(axis=0)
    return _wrap(jax.device_put(summed, merged._data.device), merged.ctx)


def _merge_row_sparse(arrays):
    """Sum row_sparse replicas by unique row id (the reference
    kvstore_local.h unique-rowid merge, ComputeMergedRowsFromRsp):
    concatenate (indices, values), segment-sum into the union rows."""
    import jax.numpy as jnp
    import numpy as np
    from .ndarray import sparse as _sp

    if len(arrays) == 1:
        return arrays[0]
    dev = arrays[0]._data.device
    idx = jnp.concatenate([a._aux if a._aux.device == dev
                           else jax.device_put(a._aux, dev)
                           for a in arrays])
    dat = jnp.concatenate([a._data if a._data.device == dev
                           else jax.device_put(a._data, dev)
                           for a in arrays])
    uniq, inv = jnp.unique(idx, return_inverse=True)
    summed = jnp.zeros((uniq.shape[0],) + dat.shape[1:], dat.dtype) \
        .at[inv.reshape(-1)].add(dat)
    out = _sp.RowSparseNDArray.__new__(_sp.RowSparseNDArray)
    NDArray.__init__(out, summed, arrays[0].ctx)
    out._aux = uniq
    out.shape = arrays[0].shape
    return out


def _normalize(key, value):
    if isinstance(key, (list, tuple)):
        return list(key), list(value)
    return [key], [value]
