"""Persistent compilation cache + warm-restart manifests.

Every engine restart used to recompile every shape bucket from
scratch: the CachedOp contract is "one engine op per subgraph,
compiled once", and this module extends that *once* across process
lifetimes. It is the single place the framework configures JAX's
on-disk compilation cache (``bench.py``, ``CachedOp`` tracing in
``gluon/block.py`` and executor binding in ``executor.py`` all route
through :func:`configure`/:func:`ensure`), plus the warm-restart
manifest plumbing the serving fleet uses to replay visited shape
buckets before admitting traffic.

Cache keying: JAX keys each persisted executable on a hash of the
lowered computation (the traced graph — which embeds every input
shape/dtype, i.e. the serving shape bucket), the backend/platform,
the compile options, and the JAX version. Because CachedOp traces are
deterministic per (model, shape bucket, dtype/config) — parameter
*names* come from the per-process NameManager counters, which replay
identically for the same construction order — the same model served
in a fresh process lowers to an identical module and the executable
is fetched from disk instead of rebuilt: a ``persistent_hit``.

Hit/miss observability: a ``jax.monitoring`` listener counts the
cache's own ``cache_hits``/``cache_misses`` events into
``mxnet_tpu_compile_cache_persistent_total{result=...}``;
:func:`events_snapshot` + :func:`classify` let the serving engine
label each first-visit compile ``persistent_hit`` (served from disk)
vs ``miss`` (a fresh backend compile) next to its in-memory
``memory_hit`` outcomes.

Warmup manifests are plain JSON dicts::

    {"version": 1, "engines": ["e0", "e1"], "bucket_lens": [64, 256],
     "max_rows": 8, "shapes": [[1, 64], [2, 64], [8, 256]],
     "created": <wall ts>}

An engine exports its visited-shape manifest at ``/warmup`` (see
``ServingEngine.warmup_manifest``), the router's scoreboard poller
unions the fleet and persists it at ``MXNET_TPU_WARMUP_MANIFEST``,
and a restarting engine replays it with ``warmup(manifest=...)`` — a
rolling restart serves its first real request from a warm cache.

Env knobs (see ``envvars.py``): ``MXNET_TPU_COMPILE_CACHE`` (gate),
``MXNET_TPU_COMPILE_CACHE_DIR``, ``MXNET_TPU_COMPILE_CACHE_MIN_S``,
``MXNET_TPU_WARMUP_MANIFEST``.
"""
from __future__ import annotations

import json
import os
import threading
import time

from . import envvars

__all__ = ["configure", "ensure", "enabled", "state", "events_snapshot",
           "classify", "manifest_path", "new_manifest", "manifest_shapes",
           "merge_manifests", "save_manifest", "load_manifest"]

_DEFAULT_DIR = os.path.join("~", ".cache", "mxnet_tpu", "compile_cache")

_lock = threading.Lock()
_state = {"configured": False, "dir": None, "min_s": None}
_tally = {"persistent_hits": 0, "persistent_misses": 0}
_listener_installed = False

_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def _counters():
    from .telemetry.registry import REGISTRY

    fam = REGISTRY.counter(
        "mxnet_tpu_compile_cache_persistent_total",
        "on-disk compilation-cache outcomes (jax cache events), "
        "process-wide", ("result",))
    return {True: fam.labels(result="hit"),
            False: fam.labels(result="miss")}


def _on_cache_event(event, **kw):
    if event == _HIT_EVENT:
        hit = True
    elif event == _MISS_EVENT:
        hit = False
    else:
        return
    with _lock:
        _tally["persistent_hits" if hit else "persistent_misses"] += 1
    _on_cache_event._counters[hit].inc()


def _install_listener():
    global _listener_installed
    with _lock:
        # check-and-set under the lock: two engines' concurrent first
        # compiles must not register the listener twice (every cache
        # event would count double). A failed install (private-API
        # drift) also latches — the cache still works, only the
        # hit/miss split degrades (classify() then reports "miss").
        if _listener_installed:
            return
        _listener_installed = True
        try:
            from jax._src import monitoring as _mon
            _on_cache_event._counters = _counters()
            _mon.register_event_listener(_on_cache_event)
        except Exception:
            pass


def configure(cache_dir=None, min_compile_secs=None, force=False):
    """Point JAX's persistent compilation cache at an on-disk
    directory and install the hit/miss event listener. Idempotent —
    repeat calls with no arguments are no-ops once configured; pass
    explicit arguments (or ``force=True``) to re-point it.

    Returns the effective state dict ``{"configured", "dir",
    "min_s"}`` (``configured=False`` when the
    ``MXNET_TPU_COMPILE_CACHE`` gate is off or jax is unavailable).
    """
    if not envvars.get("MXNET_TPU_COMPILE_CACHE"):
        return dict(_state)
    with _lock:
        already = _state["configured"]
    if already and not force and cache_dir is None \
            and min_compile_secs is None:
        return dict(_state)
    path = (cache_dir
            or envvars.get("MXNET_TPU_COMPILE_CACHE_DIR")
            or os.path.expanduser(_DEFAULT_DIR))
    path = os.path.abspath(os.path.expanduser(path))
    min_s = (min_compile_secs if min_compile_secs is not None
             else envvars.get("MXNET_TPU_COMPILE_CACHE_MIN_S"))
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_s))
        # size floor off: whether an entry is worth persisting is the
        # compile-TIME knob's job (and tests set it to 0 to force
        # cross-process hits on trivially small computations)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # jax LATCHES "cache unused" on the first compile of the
        # process (is_cache_used memoizes per task) — any compile
        # before this point (model init, an eager op) would leave the
        # cache permanently inert despite the config. Reset so the
        # next compile re-initializes against the directory above.
        try:
            from jax._src import compilation_cache as _jax_cc
            _jax_cc.reset_cache()
        except Exception:
            pass        # private-API drift: fresh processes still work
    except Exception:
        return dict(_state)
    _install_listener()
    with _lock:
        changed = (_state["dir"] != path or _state["min_s"] != min_s
                   or not _state["configured"])
        _state.update(configured=True, dir=path, min_s=float(min_s))
    if changed:
        from .telemetry import events as _events
        _events.emit("compile_cache_configured", dir=path,
                     min_compile_secs=float(min_s))
    return dict(_state)


def ensure():
    """Cheap hot-path guard: configure with defaults on first use
    (CachedOp trace time / executor bind time call this)."""
    with _lock:
        if _state["configured"]:
            return dict(_state)
    return configure()


def enabled():
    return bool(envvars.get("MXNET_TPU_COMPILE_CACHE"))


def state():
    with _lock:
        return dict(_state)


# ---------------------------------------------------------------------------
# hit/miss classification (the serving engine's 3-way counter split)
# ---------------------------------------------------------------------------

def events_snapshot():
    """Process-cumulative ``{"persistent_hits": n, "persistent_misses":
    n}`` from the jax cache-event listener. Diff two snapshots around a
    first-visit forward to classify it."""
    with _lock:
        return dict(_tally)


def classify(before, after):
    """Label one first-visit compile window from two
    :func:`events_snapshot` readings: ``"persistent_hit"`` when every
    compile in the window was served from the on-disk cache (hits
    advanced, zero fresh misses), else ``"miss"``.

    The tally is process-global (jax events carry no attribution), so
    a CONCURRENT compile elsewhere in the process can only leak its
    miss events into this window and downgrade a true persistent_hit
    to miss — never upgrade a real miss (its own miss event keeps the
    delta nonzero). The warm-restart signal is thus conservative."""
    hits = after["persistent_hits"] - before["persistent_hits"]
    misses = after["persistent_misses"] - before["persistent_misses"]
    return "persistent_hit" if hits > 0 and misses == 0 else "miss"


# ---------------------------------------------------------------------------
# warmup manifests
# ---------------------------------------------------------------------------

def manifest_path():
    """The configured fleet-manifest path (None when unset)."""
    return envvars.get("MXNET_TPU_WARMUP_MANIFEST")


def new_manifest(engine_id, bucket_lens, max_rows, shapes):
    return {"version": 1,
            "engines": [str(engine_id)],
            "bucket_lens": sorted(int(b) for b in bucket_lens),
            "max_rows": int(max_rows),
            "shapes": sorted([int(r), int(l)] for r, l in shapes),
            "created": round(time.time(), 3)}


def manifest_shapes(manifest):
    """The manifest's visited buckets as ``[(rows, row_len), ...]``
    (empty for None/malformed input — a bad manifest degrades to a
    cold start, never a crash)."""
    try:
        return sorted((int(r), int(l))
                      for r, l in (manifest or {}).get("shapes", ()))
    except (TypeError, ValueError):
        return []


def merge_manifests(parts):
    """Fleet union of several manifests (None entries skipped):
    shapes/buckets/engines union, ``max_rows`` max — the router's
    scoreboard poller folds every live engine's manifest through this.
    A structurally malformed part (a version-skewed remote engine's
    ``/warmup`` reply) is SKIPPED, not raised — same degrade-to-cold
    contract as :func:`manifest_shapes`. Returns None when nothing
    contributed."""
    engines, lens, shapes = set(), set(), set()
    max_rows = 0
    for m in parts:
        if not m:
            continue
        try:        # parse the whole part before touching the union:
            e = {str(x) for x in m.get("engines", ())}
            b = {int(x) for x in m.get("bucket_lens", ())}
            s = {(int(r), int(l)) for r, l in m.get("shapes", ())}
            mr = int(m.get("max_rows", 0))
        except (TypeError, ValueError, AttributeError):
            continue    # a bad part contributes nothing, not a crash
        engines |= e
        lens |= b
        shapes |= s
        max_rows = max(max_rows, mr)
    if not engines and not shapes:
        return None
    return {"version": 1, "engines": sorted(engines),
            "bucket_lens": sorted(lens), "max_rows": max_rows,
            "shapes": sorted(list(s) for s in shapes),
            "created": round(time.time(), 3)}


def save_manifest(manifest, path=None):
    """Atomically persist a manifest (tmp + rename — a reader never
    sees half a file). ``path`` defaults to the registered env knob;
    returns the path written, or None when there is nowhere to write."""
    path = path or manifest_path()
    if not path or manifest is None:
        return None
    path = os.path.abspath(os.path.expanduser(path))
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    return path


def load_manifest(path=None):
    """Read a manifest back (None when the path is unset, missing, or
    unparsable — warm restart degrades to cold, loudly via the event)."""
    path = path or manifest_path()
    if not path:
        return None
    try:
        with open(os.path.expanduser(path)) as f:
            return json.load(f)
    except (OSError, ValueError):
        from .telemetry import events as _events
        _events.emit("warmup_manifest_unreadable", path=str(path))
        return None
