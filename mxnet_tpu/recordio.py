"""RecordIO read/write (python/mxnet/recordio.py + dmlc-core recordio).

Binary-compatible with the reference format: records framed by the
kMagic marker 0xced7230a with a length-or-continue control word, padded
to 4 bytes; IndexedRecordIO keeps a text ``.idx`` of key→offset.
``IRHeader``/``pack``/``unpack``/``pack_img``/``unpack_img`` match
python/mxnet/recordio.py so ``im2rec``-produced datasets load directly.
"""
from __future__ import annotations

import ctypes
import numbers
import os
import struct

import numpy as np

from . import filesystem as _fs
from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xCED7230A
# control word: upper 3 bits = cflag, lower 29 = length
_LFLAG_BITS = 29
_LFLAG_MASK = (1 << _LFLAG_BITS) - 1


def _encode_lrec(cflag, length):
    return (cflag << _LFLAG_BITS) | length


def _decode_lrec(rec):
    return rec >> _LFLAG_BITS, rec & _LFLAG_MASK


class MXRecordIO:
    """Sequential RecordIO reader/writer (dmlc::RecordIOWriter/Reader)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        if flag == "w":
            self.fhandle = _fs.open_uri(uri, "wb")
            self.writable = True
        elif flag == "r":
            self.fhandle = _fs.open_uri(uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % flag)
        self.is_open = True
        self.pid = os.getpid()

    def __del__(self):
        self.close()

    def __getstate__(self):
        d = dict(self.__dict__)
        d["is_open"] = False
        d.pop("fhandle", None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.open()

    def open(self):
        if getattr(self, "is_open", False):
            return
        self.fhandle = _fs.open_uri(self.uri, "wb" if self.flag == "w" else "rb")
        self.is_open = True
        self.pid = os.getpid()

    def close(self):
        if getattr(self, "is_open", False):
            self.fhandle.close()
            self.is_open = False

    def reset(self):
        self.close()
        self.open()

    def _check_pid(self, allow_reset=False):
        # reference guards against fork reusing handles
        if self.pid != os.getpid():
            if allow_reset:
                self.reset()
                self.pid = os.getpid()
            else:
                raise MXNetError("RecordIO handle used across fork; call reset()")

    def write(self, buf):
        assert self.writable
        self._check_pid(allow_reset=False)
        self.fhandle.write(struct.pack("<II", _MAGIC, _encode_lrec(0, len(buf))))
        self.fhandle.write(buf)
        pad = (4 - (len(buf) % 4)) % 4
        if pad:
            self.fhandle.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        self._check_pid(allow_reset=True)
        hdr = self.fhandle.read(8)
        if len(hdr) < 8:
            return None
        magic, lrec = struct.unpack("<II", hdr)
        if magic != _MAGIC:
            raise MXNetError(f"invalid RecordIO magic {magic:#x} in {self.uri}")
        cflag, length = _decode_lrec(lrec)
        buf = self.fhandle.read(length)
        pad = (4 - (length % 4)) % 4
        if pad:
            self.fhandle.read(pad)
        # multi-part records (cflag 1=begin 2=middle 3=end)
        while cflag in (1, 2):
            hdr = self.fhandle.read(8)
            magic, lrec = struct.unpack("<II", hdr)
            cflag, length = _decode_lrec(lrec)
            part = self.fhandle.read(length)
            pad = (4 - (length % 4)) % 4
            if pad:
                self.fhandle.read(pad)
            buf += part
        return buf

    def tell(self):
        return self.fhandle.tell()

    def seek(self, pos):
        assert not self.writable
        self.fhandle.seek(pos)


class MXIndexedRecordIO(MXRecordIO):
    """RecordIO + .idx sidecar for random access (python/mxnet IndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if not self.writable and _fs.exists(idx_path):
            with _fs.open_uri(idx_path, "r") as fin:
                for line in fin:
                    line = line.strip().split("\t")
                    key = key_type(line[0])
                    self.idx[key] = int(line[1])
                    self.keys.append(key)

    def close(self):
        if not getattr(self, "is_open", False):
            return
        if self.writable:
            with _fs.open_uri(self.idx_path, "w") as fout:
                for k in self.keys:
                    fout.write(f"{k}\t{self.idx[k]}\n")
        super().close()

    def read_idx(self, idx):
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


# header packed as: uint32 flag, float label, uint64 id, uint64 id2
IRHeader = __import__("collections").namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        header = header._replace(flag=0)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0)
        s = label.tobytes() + s
    return struct.pack(_IR_FORMAT, *header) + s


def unpack(s):
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    import io as _io
    encoded = _encode_image(img, quality, img_fmt)
    return pack(header, encoded)


def unpack_img(s, iscolor=-1):
    header, s = unpack(s)
    img = _decode_image(np.frombuffer(s, dtype=np.uint8), iscolor)
    return header, img


def _encode_image(img, quality, img_fmt):
    """PNG/JPEG encode without OpenCV: PIL if available, else raw npy."""
    import io as _io
    try:
        from PIL import Image
        buf = _io.BytesIO()
        fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
        Image.fromarray(np.asarray(img).astype(np.uint8)).save(buf, fmt, quality=quality)
        return buf.getvalue()
    except ImportError:
        buf = _io.BytesIO()
        np.save(buf, np.asarray(img))
        return b"NPY0" + buf.getvalue()


def _decode_image(raw, iscolor=-1):
    import io as _io
    b = raw.tobytes()
    if b[:4] == b"NPY0":
        return np.load(_io.BytesIO(b[4:]))
    try:
        from PIL import Image
        img = Image.open(_io.BytesIO(b))
        return np.asarray(img)
    except ImportError as e:
        raise MXNetError("image decoding requires PIL (not installed) "
                         "or NPY0-packed records") from e
