"""Network visualization (reference python/mxnet/visualization.py).

``print_summary`` walks the Symbol DAG and prints the layer table with
output shapes and parameter counts — the reference's keras-style
summary. ``plot_network`` renders the DAG via graphviz when available
(graphviz is not in the TPU image; the call raises ImportError with
instructions, matching the reference's optional-dependency behavior).
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def _order(symbol):
    """Post-order DAG walk — Symbol._topo already resolves indexed-output
    selections ("split0[1]") to their base node."""
    return symbol._topo()


def print_summary(symbol, shape=None, line_length=120, positions=(.44, .64, .74, 1.)):
    """Print a layer-by-layer summary of the symbol (reference
    visualization.py print_summary). ``shape`` is a dict of input
    name -> shape used to infer per-layer output shapes."""
    if symbol._is_group():
        raise MXNetError("print_summary expects a single-output symbol")
    shape_map = {}
    if shape is not None:
        # ONE inference over the internals group (not per-node — a
        # per-node loop re-walks the whole subgraph each time, O(n^2))
        try:
            internals = symbol.get_internals()
            _, out_shapes, _ = internals.infer_shape(**shape)
            if out_shapes:
                for s, os_ in zip(internals, out_shapes):
                    if os_ is not None and s.name:
                        shape_map[s.name] = os_
        except Exception:
            pass

    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(cols):
        line = ""
        for i, col in enumerate(cols):
            line = (line + str(col))[:positions[i] - 1].ljust(positions[i])
        print(line)

    print("_" * line_length)
    print_row(fields)
    print("=" * line_length)

    order = _order(symbol)

    total = 0
    arg_names = set(symbol.list_arguments())
    # variable internals' output shapes ARE the arg shapes — one
    # inference pass serves both columns
    shaped_args = shape_map

    counted = set()  # weight shared across nodes (unrolled RNNs) counts once
    for node in order:
        if node._op is None and shape and node._name in shape:  # data input
            print_row([f"{node._name} (input)",
                       shape.get(node._name, ""), 0, ""])
            print("_" * line_length)
            continue
        if node._op is None:
            continue  # weight/bias variables fold into their consumer
        params = 0
        prevs = []
        for inp in node._inputs:
            inp = inp._base or inp
            if inp._op is None and inp._name in arg_names \
                    and inp._name not in counted \
                    and not inp._name.endswith("label") \
                    and (shape is None or inp._name not in shape):
                counted.add(inp._name)
                s = shaped_args.get(inp._name)
                if s:
                    n = 1
                    for d in s:
                        n *= int(d)
                    params += n
            else:
                prevs.append(inp.name or "")
        total += params
        out_shape = shape_map.get(node.name, "")
        print_row([f"{node.name} ({node._op.name})", out_shape, params,
                   ",".join(p for p in prevs if p)[:40]])
        print("_" * line_length)
    print(f"Total params: {total}")
    print("=" * line_length)
    return total


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Render the DAG with graphviz (reference plot_network). The TPU
    image ships no graphviz; install it to use this (the printable
    fallback is print_summary)."""
    try:
        from graphviz import Digraph  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "plot_network requires the optional graphviz package "
            "(pip install graphviz); use mx.viz.print_summary for a "
            "text summary") from e
    _PARAM_SUFFIXES = ("weight", "bias", "gamma", "beta", "moving_mean",
                       "moving_var", "running_mean", "running_var")

    def _hidden(var_name):
        # hide PARAMETER variables only — data/label inputs stay visible
        # even without a shape dict (reference behavior)
        return hide_weights and var_name is not None \
            and var_name.endswith(_PARAM_SUFFIXES)

    dot = Digraph(name=title, format=save_format)
    order = _order(symbol)
    for node in order:
        if node._op is None:
            if _hidden(node._name):
                continue
            dot.node(str(id(node)), node._name or "var", shape="oval")
        else:
            dot.node(str(id(node)), f"{node.name}\n{node._op.name}",
                     shape="box")
    for node in order:
        if node._op is None:
            continue
        for inp in node._inputs:
            inp = inp._base or inp
            if inp._op is None and _hidden(inp._name):
                continue
            dot.edge(str(id(inp)), str(id(node)))
    return dot
