"""Runtime feature detection (src/libinfo.cc → mx.runtime.Features)."""
from __future__ import annotations

import jax

__all__ = ["Feature", "Features", "feature_list"]


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


def _detect():
    plats = set()
    for kind in ("tpu", "gpu", "cpu"):
        try:
            if jax.devices(kind):
                plats.add(kind)
        except RuntimeError:
            pass
    feats = {
        "TPU": "tpu" in plats,
        "CUDA": "gpu" in plats,
        "CUDNN": False,
        "XLA": True,
        "PALLAS": "tpu" in plats,
        "BLAS_OPEN": True,
        "DIST_KVSTORE": True,
        "INT64_TENSOR_SIZE": True,
        "SIGNAL_HANDLER": False,
        "MKLDNN": False,
        "OPENCV": False,
        "F16C": True,
    }
    return {k: Feature(k, v) for k, v in feats.items()}


class Features(dict):
    def __init__(self):
        super().__init__(_detect())

    def is_enabled(self, name):
        return self[name].enabled


def feature_list():
    return list(Features().values())
