// C++ unit tests for the native IO library — the reference's tests/cpp
// tier (tests/cpp/engine/threaded_engine_test.cc, storage_test.cc op
// micro-tests) adapted to this framework's native surface: RecordIO
// framing, the threaded record batcher, and the threaded image
// decode/resize batcher. Assert-based, no gtest dependency; exits
// non-zero on the first failure (driven by tests/test_native_cpp.py).
//
// Build & run:  make -C src/cc test
#include <cstdint>
#include <cstdio>
#include <cstdlib>

#include <jpeglib.h>
#include <cstring>
#include <random>
#include <set>
#include <string>
#include <vector>

extern "C" {
void* mxio_reader_open(const char* path);
int64_t mxio_reader_next(void* handle, const char** buf);
void mxio_reader_close(void* handle);
void* mxio_batcher_create(const char* rec_path, const char* idx_path,
                          int64_t batch_size, int num_threads, int shuffle,
                          uint64_t seed, int64_t num_parts, int64_t part_index);
int64_t mxio_batcher_num_batches(void* handle);
int64_t mxio_batcher_next(void* handle, void** batch_out, const char** data,
                          const int64_t** offsets);
void mxio_batcher_free_batch(void* batch);
void mxio_batcher_reset(void* handle);
void mxio_batcher_close(void* handle);
void* mximg_batcher_create(const char* rec_path, const char* idx_path,
                           int64_t batch_size, int out_h, int out_w,
                           int num_threads, int shuffle, uint64_t seed,
                           int64_t num_parts, int64_t part_index);
int64_t mximg_batcher_num_batches(void* handle);
int64_t mximg_batcher_next(void* handle, uint8_t* data, float* labels);
void mximg_batcher_reset(void* handle);
void mximg_batcher_close(void* handle);
int mximg_decode(const uint8_t* buf, int64_t len, int out_h, int out_w,
                 uint8_t* out_chw);
}

#define CHECK(cond)                                                       \
  do {                                                                    \
    if (!(cond)) {                                                        \
      std::fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__,       \
                   __LINE__, #cond);                                      \
      std::exit(1);                                                       \
    }                                                                     \
  } while (0)

namespace {

constexpr uint32_t kMagicT = 0xced7230a;
constexpr uint32_t kLenBitsT = 29;

// Write one framed record (dmlc recordio format: magic, cflag<<29|len,
// payload, zero-pad to 4) and return the record's start offset.
int64_t WriteRecord(std::FILE* f, const std::string& payload) {
  int64_t off = std::ftell(f);
  uint32_t hdr[2] = {kMagicT,
                     static_cast<uint32_t>(payload.size()) & ((1u << kLenBitsT) - 1)};
  std::fwrite(hdr, sizeof(uint32_t), 2, f);
  std::fwrite(payload.data(), 1, payload.size(), f);
  uint32_t pad = (4 - (payload.size() % 4)) % 4;
  const char zeros[4] = {0, 0, 0, 0};
  if (pad) std::fwrite(zeros, 1, pad, f);
  return off;
}

// In-memory JPEG encode of a solid-color HxW RGB image.
std::vector<uint8_t> EncodeSolidJpeg(int w, int h, uint8_t r, uint8_t g,
                                     uint8_t b) {
  jpeg_compress_struct cinfo;
  jpeg_error_mgr jerr;
  cinfo.err = jpeg_std_error(&jerr);
  jpeg_create_compress(&cinfo);
  unsigned char* mem = nullptr;
  unsigned long mem_size = 0;
  jpeg_mem_dest(&cinfo, &mem, &mem_size);
  cinfo.image_width = w;
  cinfo.image_height = h;
  cinfo.input_components = 3;
  cinfo.in_color_space = JCS_RGB;
  jpeg_set_defaults(&cinfo);
  jpeg_set_quality(&cinfo, 95, TRUE);
  jpeg_start_compress(&cinfo, TRUE);
  std::vector<uint8_t> row(static_cast<size_t>(w) * 3);
  for (int x = 0; x < w; ++x) {
    row[x * 3] = r;
    row[x * 3 + 1] = g;
    row[x * 3 + 2] = b;
  }
  JSAMPROW rp = row.data();
  while (cinfo.next_scanline < cinfo.image_height)
    jpeg_write_scanlines(&cinfo, &rp, 1);
  jpeg_finish_compress(&cinfo);
  std::vector<uint8_t> out(mem, mem + mem_size);
  jpeg_destroy_compress(&cinfo);
  free(mem);
  return out;
}

// IRHeader (flag, label, id, id2 = 24 bytes) + jpeg payload.
std::string PackImageRecord(float label, const std::vector<uint8_t>& jpeg) {
  std::string rec(24, '\0');
  uint32_t flag = 0;
  std::memcpy(&rec[0], &flag, 4);
  std::memcpy(&rec[4], &label, 4);
  rec.append(reinterpret_cast<const char*>(jpeg.data()), jpeg.size());
  return rec;
}

void TestRecordIOFraming(const std::string& dir) {
  std::string path = dir + "/t.rec";
  // payload lengths hitting every pad case (0..3) plus empty
  std::vector<std::string> payloads = {"", "a", "ab", "abc", "abcd",
                                       std::string(1000, 'x')};
  std::FILE* f = std::fopen(path.c_str(), "wb");
  CHECK(f);
  for (const auto& p : payloads) WriteRecord(f, p);
  std::fclose(f);

  void* r = mxio_reader_open(path.c_str());
  CHECK(r);
  const char* buf = nullptr;
  for (const auto& p : payloads) {
    int64_t n = mxio_reader_next(r, &buf);
    CHECK(n == static_cast<int64_t>(p.size()));
    CHECK(std::memcmp(buf, p.data(), p.size()) == 0);
  }
  CHECK(mxio_reader_next(r, &buf) == -1);  // clean EOF
  mxio_reader_close(r);
  std::printf("TestRecordIOFraming ok\n");
}

void TestRecordBatcher(const std::string& dir) {
  std::string path = dir + "/b.rec";
  std::FILE* f = std::fopen(path.c_str(), "wb");
  CHECK(f);
  const int kN = 23;
  for (int i = 0; i < kN; ++i)
    WriteRecord(f, "rec" + std::to_string(i));
  std::fclose(f);

  // no idx file: index built by scanning the framing
  void* b = mxio_batcher_create(path.c_str(), "", 4, 3, 0, 0, 1, 0);
  CHECK(b);
  CHECK(mxio_batcher_num_batches(b) == 6);  // ceil(23/4): partial tail kept
  int seen = 0;
  for (int epoch = 0; epoch < 2; ++epoch) {
    int i = 0;
    while (true) {
      void* batch = nullptr;
      const char* data = nullptr;
      const int64_t* offsets = nullptr;
      int64_t n = mxio_batcher_next(b, &batch, &data, &offsets);
      if (n == 0) break;
      CHECK(n == (i < 5 ? 4 : 3));  // last batch is the 3-record tail
      for (int64_t j = 0; j < n; ++j) {
        std::string rec(data + offsets[j], data + offsets[j + 1]);
        CHECK(rec == "rec" + std::to_string(i * 4 + j));  // order preserved
      }
      mxio_batcher_free_batch(batch);
      ++i;
      ++seen;
    }
    CHECK(i == 6);
    mxio_batcher_reset(b);
  }
  CHECK(seen == 12);
  mxio_batcher_close(b);

  // sharding: 2 parts must be DISJOINT and their union the full set
  // (multi-worker num_parts/part_index — duplicated data across
  // workers is the bug this exists to catch)
  void* s0 = mxio_batcher_create(path.c_str(), "", 2, 2, 0, 0, 2, 0);
  void* s1 = mxio_batcher_create(path.c_str(), "", 2, 2, 0, 0, 2, 1);
  CHECK(s0 && s1);
  CHECK(mxio_batcher_num_batches(s0) == 6);  // ceil(12/2) even-index records
  CHECK(mxio_batcher_num_batches(s1) == 6);  // ceil(11/2) odd-index records
  std::set<std::string> shard0, shard1;
  for (void* s : {s0, s1}) {
    auto& dst = (s == s0) ? shard0 : shard1;
    while (true) {
      void* batch = nullptr;
      const char* data = nullptr;
      const int64_t* offsets = nullptr;
      int64_t n = mxio_batcher_next(s, &batch, &data, &offsets);
      if (n == 0) break;
      for (int64_t j = 0; j < n; ++j)
        dst.emplace(data + offsets[j], data + offsets[j + 1]);
      mxio_batcher_free_batch(batch);
    }
  }
  CHECK(shard0.size() == 12 && shard1.size() == 11);
  for (const auto& r : shard1) CHECK(shard0.count(r) == 0);  // disjoint
  std::set<std::string> all(shard0);
  all.insert(shard1.begin(), shard1.end());
  CHECK(static_cast<int>(all.size()) == kN);  // union covers everything
  mxio_batcher_close(s0);
  mxio_batcher_close(s1);
  std::printf("TestRecordBatcher ok\n");
}

void TestImageBatcher(const std::string& dir) {
  std::string rec_path = dir + "/img.rec";
  std::string idx_path = dir + "/img.idx";
  std::FILE* f = std::fopen(rec_path.c_str(), "wb");
  std::FILE* fi = std::fopen(idx_path.c_str(), "w");
  CHECK(f && fi);
  const int kN = 10;
  for (int i = 0; i < kN; ++i) {
    std::string payload;
    if (i == 5) {
      // corrupt image: valid framing+header, garbage jpeg — must be
      // SKIPPED (compacted batch), not crash or zero-fill
      payload = PackImageRecord(static_cast<float>(i),
                                std::vector<uint8_t>{1, 2, 3, 4, 5});
    } else {
      payload = PackImageRecord(
          static_cast<float>(i),
          EncodeSolidJpeg(17 + i, 13 + i, static_cast<uint8_t>(20 * i), 100, 200));
    }
    int64_t off = WriteRecord(f, payload);
    std::fprintf(fi, "%d\t%lld\n", i, static_cast<long long>(off));
  }
  std::fclose(f);
  std::fclose(fi);

  const int H = 8, W = 8;
  void* b = mximg_batcher_create(rec_path.c_str(), idx_path.c_str(), 5, H, W,
                                 3, 0, 0, 1, 0);
  CHECK(b);
  CHECK(mximg_batcher_num_batches(b) == 2);
  std::vector<uint8_t> data(5 * 3 * H * W);
  std::vector<float> labels(5);
  // batch 1: records 0..4, all valid, emitted in order despite threads
  int64_t n = mximg_batcher_next(b, data.data(), labels.data());
  CHECK(n == 5);
  for (int j = 0; j < 5; ++j) {
    CHECK(labels[j] == static_cast<float>(j));
    // solid color survives decode+bilinear resize (JPEG is lossy: wide
    // tolerance, but channel ordering must be exact)
    const uint8_t* img = data.data() + j * 3 * H * W;
    int want_r = 20 * j;
    CHECK(std::abs(static_cast<int>(img[0]) - want_r) < 16);
    CHECK(std::abs(static_cast<int>(img[H * W]) - 100) < 16);
    CHECK(std::abs(static_cast<int>(img[2 * H * W]) - 200) < 16);
  }
  // batch 2: record 5 is corrupt -> compacted to 4 records
  n = mximg_batcher_next(b, data.data(), labels.data());
  CHECK(n == 4);
  CHECK(labels[0] == 6.0f && labels[3] == 9.0f);
  CHECK(mximg_batcher_next(b, data.data(), labels.data()) == -1);  // epoch end

  mximg_batcher_close(b);

  // shuffled epochs, driven through mximg_batcher_reset (the epoch
  // boundary path io/native.py uses): same seed -> identical per-epoch
  // order across independent batchers (determinism), successive epochs
  // reshuffle, and every epoch's labels are exactly the valid record
  // set (a permutation — nothing duplicated or dropped)
  auto drain = [&](void* bs) {
    std::vector<float> got;
    std::vector<float> lab(5);
    int64_t n;
    while ((n = mximg_batcher_next(bs, data.data(), lab.data())) != -1)
      got.insert(got.end(), lab.begin(), lab.begin() + n);
    return got;
  };
  auto epochs = [&](uint64_t seed) {
    void* bs = mximg_batcher_create(rec_path.c_str(), idx_path.c_str(), 5, H,
                                    W, 3, 1, seed, 1, 0);
    CHECK(bs);
    auto ep0 = drain(bs);
    mximg_batcher_reset(bs);  // production epoch-boundary path
    auto ep1 = drain(bs);
    mximg_batcher_close(bs);
    return std::make_pair(ep0, ep1);
  };
  auto a = epochs(42), c = epochs(42);
  CHECK(a.first == c.first);    // same seed, epoch 0 -> same order
  CHECK(a.second == c.second);  // same seed, epoch 1 (post-reset) too
  CHECK(a.first != a.second);   // reset advances the epoch -> reshuffled
  std::multiset<float> want = {0, 1, 2, 3, 4, 6, 7, 8, 9};
  for (const auto& e : {a.first, a.second}) {
    CHECK(e.size() == 9);  // 10 records minus the corrupt one
    CHECK(std::multiset<float>(e.begin(), e.end()) == want);
  }

  // stale idx / missing rec must fail at create, not hang
  CHECK(mximg_batcher_create((dir + "/nope.rec").c_str(), idx_path.c_str(), 2,
                             H, W, 2, 0, 0, 1, 0) == nullptr);
  std::printf("TestImageBatcher ok\n");
}

void TestSingleDecode() {
  auto jpeg = EncodeSolidJpeg(32, 24, 250, 10, 60);
  std::vector<uint8_t> chw(3 * 16 * 16);
  CHECK(mximg_decode(jpeg.data(), static_cast<int64_t>(jpeg.size()), 16, 16,
                     chw.data()) == 0);
  CHECK(std::abs(static_cast<int>(chw[0]) - 250) < 16);
  CHECK(std::abs(static_cast<int>(chw[16 * 16]) - 10) < 16);
  CHECK(std::abs(static_cast<int>(chw[2 * 16 * 16]) - 60) < 16);
  CHECK(mximg_decode(jpeg.data(), 3, 16, 16, chw.data()) == -1);  // truncated
  std::printf("TestSingleDecode ok\n");
}

}  // namespace

int main(int argc, char** argv) {
  std::string dir = argc > 1 ? argv[1] : "/tmp";
  TestRecordIOFraming(dir);
  TestRecordBatcher(dir);
  TestImageBatcher(dir);
  TestSingleDecode();
  std::printf("ALL NATIVE IO TESTS PASSED\n");
  return 0;
}
