// Native RecordIO reader + threaded prefetching batcher.
//
// TPU-native equivalent of the reference's C++ IO stack:
// dmlc-core recordio (src/recordio.cc framing: 0xced7230a magic +
// cflag/length control word, 4-byte aligned) and the threaded batch
// pipeline of src/io/iter_image_recordio_2.cc (OMP decode workers +
// PrefetcherIter). Here the native layer does record framing, index
// loading, shuffling and multi-threaded batch prefetch; pixel decode
// stays in Python (PIL/numpy) because the TPU image ships no OpenCV —
// the host-side bottleneck in the reference pipeline is IO+framing,
// which this covers, and batches land as contiguous buffers ready for
// one device_put.
//
// C ABI (ctypes-consumed by mxnet_tpu/io/native.py):
//   mxio_reader_open / mxio_reader_next / mxio_reader_close
//   mxio_batcher_create / mxio_batcher_next / mxio_batcher_free_batch /
//   mxio_batcher_reset / mxio_batcher_close

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <fstream>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenBits = 29;
constexpr uint32_t kLenMask = (1u << kLenBits) - 1;

struct Reader {
  std::FILE* f = nullptr;
  std::vector<char> buf;
};

bool ReadRecord(std::FILE* f, std::vector<char>* out) {
  out->clear();
  uint32_t hdr[2];
  for (;;) {
    if (std::fread(hdr, sizeof(uint32_t), 2, f) != 2) return false;
    if (hdr[0] != kMagic) return false;
    uint32_t cflag = hdr[1] >> kLenBits;
    uint32_t len = hdr[1] & kLenMask;
    size_t pos = out->size();
    out->resize(pos + len);
    if (len && std::fread(out->data() + pos, 1, len, f) != len) return false;
    uint32_t pad = (4 - (len % 4)) % 4;
    if (pad) std::fseek(f, pad, SEEK_CUR);
    // cflag: 0 whole, 1 begin, 2 middle, 3 end
    if (cflag == 0 || cflag == 3) return true;
  }
}

struct Batch {
  std::vector<char> data;       // concatenated record payloads
  std::vector<int64_t> offsets; // size = n+1
};

struct Batcher {
  std::string path;
  std::vector<int64_t> index;   // byte offsets of records
  std::vector<int64_t> order;   // iteration order (may be shuffled)
  size_t batch_size = 1;
  bool shuffle = false;
  uint64_t seed = 0;
  size_t epoch = 0;
  size_t cursor = 0;            // next record ordinal to schedule
  size_t prefetch = 4;
  int num_threads = 2;

  std::mutex mu;
  std::condition_variable cv_produce, cv_consume;
  std::deque<Batch*> ready;
  std::vector<std::thread> workers;
  std::atomic<bool> stop{false};
  size_t next_batch_id = 0;       // batch id to hand to a worker
  size_t emit_batch_id = 0;       // batch id the consumer expects
  std::deque<std::pair<size_t, Batch*>> out_of_order;

  ~Batcher() { Shutdown(); }

  void Shutdown() {
    stop.store(true);
    cv_produce.notify_all();
    cv_consume.notify_all();
    for (auto& t : workers)
      if (t.joinable()) t.join();
    workers.clear();
    for (auto* b : ready) delete b;
    ready.clear();
    for (auto& p : out_of_order) delete p.second;
    out_of_order.clear();
  }

  size_t NumBatches() const {
    return (order.size() + batch_size - 1) / batch_size;
  }

  void StartEpoch() {
    Shutdown();
    stop.store(false);
    if (shuffle) {
      std::mt19937_64 rng(seed + epoch);
      std::shuffle(order.begin(), order.end(), rng);
    }
    next_batch_id = 0;
    emit_batch_id = 0;
    for (int i = 0; i < num_threads; ++i)
      workers.emplace_back([this] { WorkerLoop(); });
  }

  void WorkerLoop() {
    std::FILE* f = std::fopen(path.c_str(), "rb");
    if (!f) return;
    std::vector<char> rec;
    while (!stop.load()) {
      size_t my_batch;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_produce.wait(lk, [this] {
          return stop.load() || (next_batch_id < NumBatches() &&
                                 ready.size() + out_of_order.size() < prefetch);
        });
        if (stop.load() || next_batch_id >= NumBatches()) break;
        my_batch = next_batch_id++;
      }
      auto* b = new Batch();
      b->offsets.push_back(0);
      size_t begin = my_batch * batch_size;
      size_t end = std::min(begin + batch_size, order.size());
      for (size_t i = begin; i < end; ++i) {
        std::fseek(f, static_cast<long>(index[order[i]]), SEEK_SET);
        if (!ReadRecord(f, &rec)) break;
        b->data.insert(b->data.end(), rec.begin(), rec.end());
        b->offsets.push_back(static_cast<int64_t>(b->data.size()));
      }
      {
        std::unique_lock<std::mutex> lk(mu);
        out_of_order.emplace_back(my_batch, b);
        // drain contiguously-ordered batches into the ready queue so
        // the consumer sees deterministic batch order regardless of
        // worker completion order
        bool moved = true;
        while (moved) {
          moved = false;
          for (auto it = out_of_order.begin(); it != out_of_order.end(); ++it) {
            if (it->first == NextReadyId()) {
              ready.push_back(it->second);
              out_of_order.erase(it);
              moved = true;
              break;
            }
          }
        }
        cv_consume.notify_all();
      }
    }
    std::fclose(f);
  }

  size_t NextReadyId() {
    // id of the batch that should enter `ready` next
    return emit_batch_id + ready.size();
  }

  Batch* Next() {
    std::unique_lock<std::mutex> lk(mu);
    cv_consume.wait(lk, [this] {
      return stop.load() || !ready.empty() ||
             (emit_batch_id >= NumBatches());
    });
    if (ready.empty()) return nullptr;  // epoch done
    Batch* b = ready.front();
    ready.pop_front();
    ++emit_batch_id;
    cv_produce.notify_all();
    return b;
  }
};

std::vector<int64_t> BuildIndexFromIdx(const std::string& idx_path) {
  std::vector<int64_t> out;
  std::ifstream in(idx_path);
  std::string line;
  while (std::getline(in, line)) {
    auto tab = line.find('\t');
    if (tab == std::string::npos) continue;
    out.push_back(std::stoll(line.substr(tab + 1)));
  }
  return out;
}

std::vector<int64_t> BuildIndexByScan(const std::string& path) {
  std::vector<int64_t> out;
  std::FILE* f = std::fopen(path.c_str(), "rb");
  if (!f) return out;
  std::vector<char> rec;
  for (;;) {
    long pos = std::ftell(f);
    if (!ReadRecord(f, &rec)) break;
    out.push_back(pos);
  }
  std::fclose(f);
  return out;
}

}  // namespace

extern "C" {

void* mxio_reader_open(const char* path) {
  auto* r = new Reader();
  r->f = std::fopen(path, "rb");
  if (!r->f) {
    delete r;
    return nullptr;
  }
  return r;
}

// returns length, or -1 at EOF; *buf points at internal storage valid
// until the next call
int64_t mxio_reader_next(void* handle, const char** buf) {
  auto* r = static_cast<Reader*>(handle);
  if (!ReadRecord(r->f, &r->buf)) return -1;
  *buf = r->buf.data();
  return static_cast<int64_t>(r->buf.size());
}

void mxio_reader_close(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  if (r->f) std::fclose(r->f);
  delete r;
}

void* mxio_batcher_create(const char* rec_path, const char* idx_path,
                          int64_t batch_size, int num_threads, int shuffle,
                          uint64_t seed, int64_t num_parts, int64_t part_index) {
  auto* b = new Batcher();
  b->path = rec_path;
  b->batch_size = static_cast<size_t>(batch_size);
  b->num_threads = num_threads > 0 ? num_threads : 2;
  b->shuffle = shuffle != 0;
  b->seed = seed;
  b->index = (idx_path && idx_path[0])
                 ? BuildIndexFromIdx(idx_path)
                 : BuildIndexByScan(rec_path);
  if (b->index.empty()) {
    delete b;
    return nullptr;
  }
  // dataset sharding for multi-worker training (num_parts/part_index,
  // the reference ImageRecordIter kwargs)
  for (size_t i = part_index < 0 ? 0 : static_cast<size_t>(part_index);
       i < b->index.size();
       i += (num_parts > 1 ? static_cast<size_t>(num_parts) : 1)) {
    b->order.push_back(static_cast<int64_t>(i));
  }
  b->StartEpoch();
  return b;
}

int64_t mxio_batcher_num_batches(void* handle) {
  return static_cast<int64_t>(static_cast<Batcher*>(handle)->NumBatches());
}

// Returns number of records in batch (0 = epoch end). Caller frees via
// mxio_batcher_free_batch. data/offsets are owned by the returned batch.
int64_t mxio_batcher_next(void* handle, void** batch_out, const char** data,
                          const int64_t** offsets) {
  auto* b = static_cast<Batcher*>(handle);
  Batch* batch = b->Next();
  if (!batch) return 0;
  *batch_out = batch;
  *data = batch->data.data();
  *offsets = batch->offsets.data();
  return static_cast<int64_t>(batch->offsets.size()) - 1;
}

void mxio_batcher_free_batch(void* batch) {
  delete static_cast<Batch*>(batch);
}

void mxio_batcher_reset(void* handle) {
  auto* b = static_cast<Batcher*>(handle);
  ++b->epoch;
  b->StartEpoch();
}

void mxio_batcher_close(void* handle) { delete static_cast<Batcher*>(handle); }

}  // extern "C"
