// Native threaded image-decode batcher — the full TPU-native equivalent
// of the reference's src/io/iter_image_recordio_2.cc: RecordIO framing,
// IRHeader parsing, libjpeg decode, bilinear resize and batch assembly
// all run on C++ threads (no GIL), handing Python one contiguous
// uint8 CHW batch + float labels per call.
//
// Record payload layout (python recordio.pack_img): IRHeader
// "<IfQQ" = {flag:u32, label:f32, id:u64, id2:u64}; flag>0 means `flag`
// float32 multi-labels follow the header; the JPEG stream follows.
//
// C ABI (ctypes-consumed by mxnet_tpu/io/native.py):
//   mximg_batcher_create / _next / _num_batches / _reset / _close
//   mximg_decode (single-image decode+resize, for tests)

#include <cstddef>
#include <cstdio>

#include <jpeglib.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <fstream>
#include <mutex>
#include <pthread.h>
#include <sched.h>

#include <cstdlib>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenBits = 29;
constexpr uint32_t kLenMask = (1u << kLenBits) - 1;
constexpr size_t kHeaderSize = 4 + 4 + 8 + 8;

bool ReadRecordAt(std::FILE* f, long offset, std::vector<char>* out) {
  std::fseek(f, offset, SEEK_SET);
  out->clear();
  uint32_t hdr[2];
  for (;;) {
    if (std::fread(hdr, sizeof(uint32_t), 2, f) != 2) return false;
    if (hdr[0] != kMagic) return false;
    uint32_t cflag = hdr[1] >> kLenBits;
    uint32_t len = hdr[1] & kLenMask;
    size_t pos = out->size();
    out->resize(pos + len);
    if (len && std::fread(out->data() + pos, 1, len, f) != len) return false;
    uint32_t pad = (4 - (len % 4)) % 4;
    if (pad) std::fseek(f, pad, SEEK_CUR);
    if (cflag == 0 || cflag == 3) return true;
  }
}

struct JpegErr {
  jpeg_error_mgr pub;
  jmp_buf jb;
};

void JpegErrExit(j_common_ptr cinfo) {
  auto* e = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(e->jb, 1);
}

// Decode JPEG to RGB HWC uint8; returns false on corrupt input.
bool DecodeJpeg(const uint8_t* buf, size_t len, std::vector<uint8_t>* out,
                int* w, int* h) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = JpegErrExit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf), len);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  *w = cinfo.output_width;
  *h = cinfo.output_height;
  out->resize(static_cast<size_t>(*w) * *h * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = out->data() + static_cast<size_t>(cinfo.output_scanline) * *w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// Bilinear resize HWC uint8 (src w0xh0) to (w1xh1).
void ResizeBilinear(const uint8_t* src, int w0, int h0, uint8_t* dst, int w1,
                    int h1) {
  if (w0 == w1 && h0 == h1) {
    std::memcpy(dst, src, static_cast<size_t>(w1) * h1 * 3);
    return;
  }
  const float sx = static_cast<float>(w0) / w1;
  const float sy = static_cast<float>(h0) / h1;
  for (int y = 0; y < h1; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    int y0 = std::max(0, static_cast<int>(fy));
    int y1 = std::min(h0 - 1, y0 + 1);
    float wy = fy - y0;
    if (wy < 0) wy = 0;
    for (int x = 0; x < w1; ++x) {
      float fx = (x + 0.5f) * sx - 0.5f;
      int x0 = std::max(0, static_cast<int>(fx));
      int x1 = std::min(w0 - 1, x0 + 1);
      float wx = fx - x0;
      if (wx < 0) wx = 0;
      for (int c = 0; c < 3; ++c) {
        float v00 = src[(static_cast<size_t>(y0) * w0 + x0) * 3 + c];
        float v01 = src[(static_cast<size_t>(y0) * w0 + x1) * 3 + c];
        float v10 = src[(static_cast<size_t>(y1) * w0 + x0) * 3 + c];
        float v11 = src[(static_cast<size_t>(y1) * w0 + x1) * 3 + c];
        float v = v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                  v10 * wy * (1 - wx) + v11 * wy * wx;
        dst[(static_cast<size_t>(y) * w1 + x) * 3 + c] =
            static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}

struct ImgBatch {
  std::vector<uint8_t> data;   // B*3*H*W (CHW per image)
  std::vector<float> labels;   // B
  int64_t n = 0;
};

struct ImgBatcher {
  std::string path;
  std::vector<int64_t> index;
  std::vector<int64_t> order;
  size_t batch_size = 1;
  int out_h = 224, out_w = 224;
  bool shuffle = false;
  uint64_t seed = 0;
  size_t epoch = 0;
  size_t prefetch = 6;
  int num_threads = 4;

  std::mutex mu;
  std::condition_variable cv_produce, cv_consume;
  std::deque<ImgBatch*> ready;
  std::deque<std::pair<size_t, ImgBatch*>> out_of_order;
  std::vector<std::thread> workers;
  std::atomic<bool> stop{false};
  size_t next_batch_id = 0;
  size_t emit_batch_id = 0;

  ~ImgBatcher() { Shutdown(); }

  size_t NumBatches() const { return order.size() / batch_size; }  // discard

  void Shutdown() {
    stop.store(true);
    cv_produce.notify_all();
    cv_consume.notify_all();
    for (auto& t : workers)
      if (t.joinable()) t.join();
    workers.clear();
    for (auto* b : ready) delete b;
    ready.clear();
    for (auto& p : out_of_order) delete p.second;
    out_of_order.clear();
  }

  void StartEpoch() {
    Shutdown();
    stop.store(false);
    if (shuffle) {
      std::mt19937_64 rng(seed + epoch);
      std::shuffle(order.begin(), order.end(), rng);
    }
    next_batch_id = 0;
    emit_batch_id = 0;
    for (int i = 0; i < num_threads; ++i)
      workers.emplace_back([this] { WorkerLoop(); });
    // MXTPU_DECODE_RT=1: move decode threads to SCHED_RR so they
    // preempt a spin-waiting accelerator client on shared cores (the
    // axon tunnel busy-polls while device work is in flight, starving
    // ordinary CFS threads to ~46% of a core). Decode threads block on
    // the prefetch bound regularly, so the client still gets cycles.
    // Needs CAP_SYS_NICE; failures are silently ignored (EPERM in
    // unprivileged containers).
    const char* rt = std::getenv("MXTPU_DECODE_RT");
    if (rt && rt[0] == '1') {
      sched_param sp{};
      sp.sched_priority = 1;
      for (auto& t : workers)
        pthread_setschedparam(t.native_handle(), SCHED_RR, &sp);
    }
  }

  size_t NextReadyId() { return emit_batch_id + ready.size(); }

  void WorkerLoop() {
    std::FILE* f = std::fopen(path.c_str(), "rb");
    if (!f) {
      // wake any blocked consumer instead of leaving it waiting forever
      stop.store(true);
      cv_consume.notify_all();
      cv_produce.notify_all();
      return;
    }
    std::vector<char> rec;
    std::vector<uint8_t> decoded;
    const size_t img_bytes = static_cast<size_t>(out_h) * out_w * 3;
    std::vector<uint8_t> resized(img_bytes);
    while (!stop.load()) {
      size_t my_batch;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_produce.wait(lk, [this] {
          return stop.load() || (next_batch_id < NumBatches() &&
                                 ready.size() + out_of_order.size() < prefetch);
        });
        if (stop.load() || next_batch_id >= NumBatches()) break;
        my_batch = next_batch_id++;
      }
      auto* b = new ImgBatch();
      b->data.resize(batch_size * img_bytes);
      b->labels.resize(batch_size, 0.0f);
      size_t begin = my_batch * batch_size;
      size_t filled = 0;  // corrupt records are SKIPPED, not zero-filled
      for (size_t i = 0; i < batch_size; ++i) {
        if (!ReadRecordAt(f, static_cast<long>(index[order[begin + i]]), &rec))
          continue;
        if (rec.size() < kHeaderSize) continue;
        uint32_t flag;
        float label;
        std::memcpy(&flag, rec.data(), 4);
        std::memcpy(&label, rec.data() + 4, 4);
        size_t img_off = kHeaderSize + (flag > 0 ? flag * 4ul : 0);
        if (flag > 0)  // multi-label: use the first
          std::memcpy(&label, rec.data() + kHeaderSize, 4);
        if (img_off >= rec.size()) continue;
        int w = 0, h = 0;
        if (!DecodeJpeg(reinterpret_cast<const uint8_t*>(rec.data()) + img_off,
                        rec.size() - img_off, &decoded, &w, &h))
          continue;
        ResizeBilinear(decoded.data(), w, h, resized.data(), out_w, out_h);
        // HWC -> CHW into the next filled slot (compacted batch)
        uint8_t* slot = b->data.data() + filled * img_bytes;
        const size_t plane = static_cast<size_t>(out_h) * out_w;
        for (size_t p = 0; p < plane; ++p) {
          slot[p] = resized[p * 3];
          slot[plane + p] = resized[p * 3 + 1];
          slot[2 * plane + p] = resized[p * 3 + 2];
        }
        b->labels[filled] = label;
        ++filled;
      }
      b->n = static_cast<int64_t>(filled);
      {
        std::unique_lock<std::mutex> lk(mu);
        out_of_order.emplace_back(my_batch, b);
        bool moved = true;
        while (moved) {
          moved = false;
          for (auto it = out_of_order.begin(); it != out_of_order.end(); ++it) {
            if (it->first == NextReadyId()) {
              ready.push_back(it->second);
              out_of_order.erase(it);
              moved = true;
              break;
            }
          }
        }
        cv_consume.notify_all();
      }
    }
    std::fclose(f);
  }

  ImgBatch* Next() {
    std::unique_lock<std::mutex> lk(mu);
    cv_consume.wait(lk, [this] {
      return stop.load() || !ready.empty() || (emit_batch_id >= NumBatches());
    });
    if (ready.empty()) return nullptr;
    ImgBatch* b = ready.front();
    ready.pop_front();
    ++emit_batch_id;
    cv_produce.notify_all();
    return b;
  }
};

std::vector<int64_t> LoadIdx(const std::string& idx_path) {
  std::vector<int64_t> out;
  std::ifstream in(idx_path);
  std::string line;
  while (std::getline(in, line)) {
    auto tab = line.find('\t');
    if (tab == std::string::npos) continue;
    out.push_back(std::stoll(line.substr(tab + 1)));
  }
  return out;
}

}  // namespace

extern "C" {

void* mximg_batcher_create(const char* rec_path, const char* idx_path,
                           int64_t batch_size, int out_h, int out_w,
                           int num_threads, int shuffle, uint64_t seed,
                           int64_t num_parts, int64_t part_index) {
  auto* b = new ImgBatcher();
  b->path = rec_path;
  b->batch_size = static_cast<size_t>(batch_size);
  b->out_h = out_h;
  b->out_w = out_w;
  b->num_threads = num_threads > 0 ? num_threads : 4;
  b->shuffle = shuffle != 0;
  b->seed = seed;
  // validate the .rec opens NOW: a stale idx pointing at a moved file
  // must fail at create(), not hang the first next()
  std::FILE* probe = std::fopen(rec_path, "rb");
  if (!probe) {
    delete b;
    return nullptr;
  }
  std::fclose(probe);
  b->index = LoadIdx(idx_path);
  if (b->index.empty()) {
    delete b;
    return nullptr;
  }
  for (size_t i = part_index < 0 ? 0 : static_cast<size_t>(part_index);
       i < b->index.size();
       i += (num_parts > 1 ? static_cast<size_t>(num_parts) : 1)) {
    b->order.push_back(static_cast<int64_t>(i));
  }
  if (b->order.size() < b->batch_size) {
    delete b;
    return nullptr;
  }
  b->StartEpoch();
  return b;
}

int64_t mximg_batcher_num_batches(void* handle) {
  return static_cast<int64_t>(static_cast<ImgBatcher*>(handle)->NumBatches());
}

// Copies the next batch into caller buffers (data: B*3*H*W uint8,
// labels: B float32). Returns records filled (may be < batch_size when
// corrupt records were skipped — the batch is compacted), or -1 at
// epoch end.
int64_t mximg_batcher_next(void* handle, uint8_t* data, float* labels) {
  auto* b = static_cast<ImgBatcher*>(handle);
  ImgBatch* batch = b->Next();
  if (!batch) return -1;
  std::memcpy(data, batch->data.data(), batch->data.size());
  std::memcpy(labels, batch->labels.data(),
              batch->labels.size() * sizeof(float));
  int64_t n = batch->n;
  delete batch;
  return n;
}

void mximg_batcher_reset(void* handle) {
  auto* b = static_cast<ImgBatcher*>(handle);
  ++b->epoch;
  b->StartEpoch();
}

void mximg_batcher_close(void* handle) {
  delete static_cast<ImgBatcher*>(handle);
}

// Single-image decode+resize for tests: returns 0 on success.
int mximg_decode(const uint8_t* buf, int64_t len, int out_h, int out_w,
                 uint8_t* out_chw) {
  std::vector<uint8_t> decoded;
  int w = 0, h = 0;
  if (!DecodeJpeg(buf, static_cast<size_t>(len), &decoded, &w, &h)) return -1;
  std::vector<uint8_t> resized(static_cast<size_t>(out_h) * out_w * 3);
  ResizeBilinear(decoded.data(), w, h, resized.data(), out_w, out_h);
  const size_t plane = static_cast<size_t>(out_h) * out_w;
  for (size_t p = 0; p < plane; ++p) {
    out_chw[p] = resized[p * 3];
    out_chw[plane + p] = resized[p * 3 + 1];
    out_chw[2 * plane + p] = resized[p * 3 + 2];
  }
  return 0;
}

}  // extern "C"
