"""Benchmark: ResNet-50 training throughput, images/sec/chip.

BASELINE config #2 (the north-star metric). Runs the full jitted
training step (forward + backward + SGD-momentum update, bf16 compute /
f32 master math where it matters) on synthetic ImageNet-shaped data on
ONE chip and prints a single JSON line.

``vs_baseline`` is computed against the historical upstream-MXNet
fp32 claim of ~375 img/s/GPU (BASELINE.md: the reference mount was
empty, "published": {} — 375 is the midpoint of the remembered
360–390 range, flagged there as unverified).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_IMGS_PER_SEC = 375.0
BATCH = int(os.environ.get("BENCH_BATCH", "64"))
IMAGE = 224
STEPS = int(os.environ.get("BENCH_STEPS", "20"))
WARMUP = 3
DTYPE = os.environ.get("BENCH_DTYPE", "bfloat16")


def main():
    import jax
    import jax.numpy as jnp

    # persistent compile cache: the ResNet-50 train step takes minutes to
    # compile through axon's remote compiler; cache it across runs/rounds
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.block import functionalize
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1

    ctx = mx.current_context()
    net = resnet50_v1(classes=1000)
    net.initialize(init=mx.initializer.Xavier(), ctx=ctx)
    if DTYPE != "float32":
        net.cast(DTYPE)
    warm = mx.nd.zeros((2, 3, IMAGE, IMAGE), ctx=ctx, dtype=DTYPE)
    with mx.autograd.predict_mode():
        net(warm)

    fn, params = functionalize(net, training=True, ctx=ctx)

    def loss_fn(params, rng, x, y):
        logits = fn(params, rng, x)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()

    def train_step(params, moms, rng, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, rng, x, y)
        new_moms = jax.tree_util.tree_map(
            lambda m, g: 0.9 * m + g.astype(jnp.float32), moms, grads)
        new_params = jax.tree_util.tree_map(
            lambda p, m: (p.astype(jnp.float32) - 0.1 * m).astype(p.dtype),
            params, new_moms)
        return new_params, new_moms, loss

    step = jax.jit(train_step, donate_argnums=(0, 1))

    moms = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    rng = jax.random.PRNGKey(0)
    x = jnp.asarray(np.random.RandomState(0)
                    .rand(BATCH, 3, IMAGE, IMAGE).astype(np.float32)
                    .astype(np.dtype("float32")), dtype=DTYPE)
    y = jnp.asarray(np.random.RandomState(1).randint(0, 1000, BATCH), jnp.int32)

    for _ in range(WARMUP):
        params, moms, loss = step(params, moms, rng, x, y)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(STEPS):
        params, moms, loss = step(params, moms, rng, x, y)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    imgs_per_sec = BATCH * STEPS / dt
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(imgs_per_sec, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(imgs_per_sec / BASELINE_IMGS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
