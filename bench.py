"""Benchmark: ResNet-50 training throughput, images/sec/chip.

BASELINE config #2 (the north-star metric). Runs the full jitted
training step (forward + backward + SGD-momentum update, bf16 compute /
f32 master math where it matters) on synthetic ImageNet-shaped data on
ONE chip and prints a single JSON line.

``vs_baseline`` is computed against the historical upstream-MXNet
fp32 claim of ~375 img/s/GPU (BASELINE.md: the reference mount was
empty, "published": {} — 375 is the midpoint of the remembered
360–390 range, flagged there as unverified).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_IMGS_PER_SEC = 375.0
# batch 128 measured fastest on v5e (sweep r2: 64→1846, 128→2223,
# 256→2193 img/s; NHWC knob ±0 — XLA layout assignment already optimal)
BATCH = int(os.environ.get("BENCH_BATCH", "128"))
IMAGE = 224
STEPS = int(os.environ.get("BENCH_STEPS", "20"))
WARMUP = 3
DTYPE = os.environ.get("BENCH_DTYPE", "bfloat16")
# Steps fused per dispatch (engine.chain_steps — the engine-bulking /
# async-pipelining analog). Each PJRT dispatch over the axon tunnel
# costs ~6 ms that SERIALIZES between steps (xprof: 47.0 ms device-busy
# vs 53.1 ms wall on ResNet b128); chaining runs CHAIN steps on-device
# per dispatch so the measurement reflects device throughput, as it
# would on a locally-attached TPU host where dispatch (~100 us)
# overlaps. Throughput figures count BATCH*STEPS*CHAIN examples.
# Sweep (2026-07-31, v5e): ResNet 1/4/10/16 -> 2472/2719/2776/2790
# img/s; W&D -> 449/569/606/628k ex/s; LSTM 4/10 -> 551/560k tok/s.
CHAIN = max(1, int(os.environ.get("BENCH_CHAIN", "10")))
# timing windows per measurement: median-of-3 for the headline configs
# (single windows swing a few % run-to-run over the tunnel), 1 for the
# long-tail extras where a ±3% swing doesn't change any conclusion but
# 3x windows cost real driver-budget minutes (r4 lesson: the suite
# outgrew the driver's timeout and the headline train number was lost)
WINDOWS = max(1, int(os.environ.get("BENCH_WINDOWS", "3")))



def _setup_cache():
    """Persistent compile cache — axon remote-compiles are minutes-slow.
    One code path with the framework proper (mxnet_tpu.compile_cache:
    CachedOp traces and executor binds configure the same cache), so
    bench legs, serving engines and tests all share one on-disk store.
    Bench keeps its historical ./.jax_cache default unless
    MXNET_TPU_COMPILE_CACHE_DIR points elsewhere."""
    try:
        from mxnet_tpu import compile_cache, envvars

        cache_dir = envvars.get("MXNET_TPU_COMPILE_CACHE_DIR") \
            or os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            ".jax_cache")
        compile_cache.configure(cache_dir=cache_dir)
    except Exception:
        pass


def _precompile(step, *args, **meta):
    """BENCH_PRECOMPILE=1: lower + compile the step WITHOUT executing
    it, so the executable lands in the persistent cache and the
    separately-launched measured leg starts warm — the seq2048 leg
    stops burning its per-config wall cap (the r5 rc=124) on a remote
    compile."""
    from mxnet_tpu import compile_cache

    t0 = time.perf_counter()
    step.lower(*args).compile()
    dt = time.perf_counter() - t0
    _report("precompile_seconds", dt, "seconds", 0.0,
            cache_dir=compile_cache.state().get("dir"), **meta)


def _peak_tflops():
    """Per-chip peak dense bf16 TFLOP/s of the local accelerator
    (override with MXNET_TPU_PEAK_TFLOPS). Sources: public TPU specs."""
    import jax

    from mxnet_tpu import envvars

    env = envvars.get("MXNET_TPU_PEAK_TFLOPS")
    if env:
        return env
    kind = jax.devices()[0].device_kind.lower()
    for tag, peak in (("v6e", 918.0), ("v6", 918.0), ("v5p", 459.0),
                      ("v5e", 197.0), ("v5 lite", 197.0), ("v4", 275.0),
                      ("v3", 123.0), ("v2", 45.0)):
        if tag in kind:
            return peak
    return 0.0  # unknown (CPU dev runs): mfu reported as 0


def _peak_hbm_gbps():
    """Per-chip peak HBM bandwidth GB/s (override with
    MXNET_TPU_PEAK_HBM_GBPS). Sources: public TPU specs."""
    import jax

    from mxnet_tpu import envvars

    env = envvars.get("MXNET_TPU_PEAK_HBM_GBPS")
    if env:
        return env
    kind = jax.devices()[0].device_kind.lower()
    for tag, peak in (("v6e", 1640.0), ("v6", 1640.0), ("v5p", 2765.0),
                      ("v5e", 819.0), ("v5 lite", 819.0), ("v4", 1228.0),
                      ("v3", 900.0), ("v2", 700.0)):
        if tag in kind:
            return peak
    return 0.0


def _step_cost(step, *args):
    """(flops, bytes_accessed) of one compiled step (XLA cost analysis).
    bytes_accessed counts every operand+output touch XLA models — an
    upper bound on true HBM traffic (re-reads that hit VMEM/fusion are
    still counted), so achieved-GB/s derived from it is conservative-
    high; good enough to tell "gather-bound" from "far off roofline"."""
    import jax

    try:
        cost = step.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return (float(cost.get("flops", 0.0)),
                float(cost.get("bytes accessed", 0.0)))
    except Exception:
        return 0.0, 0.0


def _report(metric, value, unit, vs_baseline, flops_per_step=0.0,
            sec_per_step=0.0, bytes_per_step=0.0, **extras):
    """One JSON line for the driver; mfu measures against the chip's
    peak (VERDICT round-1: progress is vs the hardware, not a ghost
    GPU number). When bytes_per_step is known the achieved HBM GB/s
    and fraction of peak bandwidth print too, so memory-bound configs
    (Wide&Deep gathers) are judged against the right roofline.

    HBM honesty (VERDICT r5 #2): cost-model bytes_accessed counts
    fused re-reads and can exceed the physical roofline, so headline
    ``hbm_gbs``/``hbm_frac`` prefer xprof hardware-counter values when
    the extras carry them (BENCH_XPROF=1), and the cost-model fallback
    is ALWAYS flagged ``hbm_est: true`` — an unflagged hbm_frac > 1.0
    can no longer reach the record."""
    rec = {"metric": metric, "value": round(value, 2), "unit": unit,
           "vs_baseline": round(vs_baseline, 3)}
    peak = _peak_tflops()
    if flops_per_step and sec_per_step and peak:
        rec["mfu"] = round(flops_per_step / sec_per_step / (peak * 1e12), 4)
        rec["tflops_per_sec"] = round(flops_per_step / sec_per_step / 1e12, 1)
    hbm_peak = _peak_hbm_gbps()
    if bytes_per_step and sec_per_step:
        gbs = bytes_per_step / sec_per_step / 1e9
        rec["hbm_gbs"] = round(gbs, 1)
        rec["hbm_est"] = True  # cost-model estimate, not a measurement
        if hbm_peak:
            rec["hbm_frac"] = round(gbs / hbm_peak, 4)
    rec.update(extras)
    if "hbm_frac_xprof" in rec:  # measured beats estimated
        rec["hbm_frac"] = rec["hbm_frac_xprof"]
        if "hbm_gbs_xprof" in rec:
            rec["hbm_gbs"] = rec["hbm_gbs_xprof"]
        rec["hbm_est"] = False
    if "telemetry" not in rec:
        # every leg's record carries its process's telemetry state
        # (nonzero counters + histogram counts); the suite summary
        # forwards it so one bench_suite_summary line shows what each
        # leg actually exercised
        try:
            from mxnet_tpu.telemetry import REGISTRY
            rec["telemetry"] = REGISTRY.snapshot_compact()
        except Exception:
            pass
    if "slowest_traces" not in rec:
        # the tail-sampled span ring's slowest retained traces: when a
        # leg ran slower than expected, these name the exact requests/
        # epochs to open with telemetry_dump.py --trace <id>
        try:
            from mxnet_tpu.telemetry import spans as _spans
            slowest = _spans.slowest_traces(3)
            if slowest:
                rec["slowest_traces"] = [
                    {"trace_id": t, "root": r, "ms": d}
                    for t, r, d in slowest]
        except Exception:
            pass
    if "resources" not in rec:
        # per-leg resource footprint: RSS/device-memory watermarks
        # (each leg is its own process, so the peak IS the leg's) —
        # a memory regression shows in bench_suite_summary, not in an
        # OOM three legs later
        try:
            from mxnet_tpu.telemetry import resources as _resources
            rec["resources"] = _resources.compact()
        except Exception:
            pass
    if "profile_top" not in rec:
        # where the leg's HOST time went, from the always-on sampling
        # profiler (empty when MXNET_TPU_PROF=0)
        try:
            from mxnet_tpu.telemetry import profiling as _profiling
            if _profiling.PROFILER.running:
                rec["profile_top"] = [
                    f"{t['frame']} {t['self_frac'] * 100:.0f}%"
                    for t in _profiling.top_self(3)]
        except Exception:
            pass
    print(json.dumps(rec))
    sys.stdout.flush()


def _make_momentum_sgd(loss_fn, lr):
    """Jitted momentum-SGD train step over (params, moms) pytrees.
    CHAIN>1 fuses that many steps into one dispatched executable
    (mxnet_tpu.engine.chain_steps).

    Cost accounting: XLA cost_analysis counts a lax.scan/while body
    ONCE regardless of trip count (verified empirically: the chained
    ResNet executable reports 2.86 TF — exactly the xprof-measured
    single-step flops), so the chained executable's cost IS the
    per-model-step cost. If an XLA upgrade ever switches to
    trip-multiplied counting, every measurement would read CHAIN-times
    over the physical bound and _guard_impossible would raise loudly
    rather than record inflated MFU."""
    import jax
    import jax.numpy as jnp

    def train_step(params, moms, *args):
        loss, grads = jax.value_and_grad(loss_fn)(params, *args)
        new_moms = jax.tree_util.tree_map(
            lambda m, g: 0.9 * m + g.astype(jnp.float32), moms, grads)
        new_params = jax.tree_util.tree_map(
            lambda p, m: (p.astype(jnp.float32) - lr * m).astype(p.dtype),
            params, new_moms)
        return new_params, new_moms, loss

    if CHAIN > 1:
        from mxnet_tpu.engine import chain_steps
        return chain_steps(train_step, CHAIN, donate_argnums=(0, 1))
    return jax.jit(train_step, donate_argnums=(0, 1))


def _zeros_moms(params):
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)


def _time_steps(step, params, moms, *args, flops_per_step=0.0,
                bytes_per_step=0.0):
    """Warmup then time STEPS iterations; returns (elapsed_sec).

    Sanity guard: a measured rate implying >1.5x the chip's peak FLOPs
    is physically impossible — observed once as an axon-tunnel timing
    glitch (block_until_ready returning early) that reported 18x MFU.
    Such a measurement is re-timed (up to twice) rather than recorded.
    """
    import jax

    def timed():
        nonlocal params, moms
        t0 = time.perf_counter()
        for _ in range(STEPS):
            params, moms, loss = step(params, moms, *args)
        jax.block_until_ready(loss)
        return time.perf_counter() - t0

    def timed_median():
        # median of WINDOWS windows: single windows swing a few %
        # run-to-run (tunnel dispatch latency); the guard sees the median
        return _median(timed, WINDOWS)

    for _ in range(WARMUP):
        params, moms, loss = step(params, moms, *args)
    jax.block_until_ready(loss)
    return _guard_impossible(timed_median, flops_per_step, bytes_per_step)


def _median(timed, windows):
    """True median of ``windows`` timing runs (even counts average the
    two middle values — indexing [n//2] alone would report the slower
    one)."""
    if windows == 1:
        return timed()
    xs = sorted(timed() for _ in range(windows))
    mid = len(xs) // 2
    return xs[mid] if len(xs) % 2 else 0.5 * (xs[mid - 1] + xs[mid])


def _guard_impossible(timed, flops_per_step, bytes_per_step=0.0):
    """Run ``timed()``; reject results implying >1.5x chip peak.

    Observed axon-tunnel failure mode: after a VERY slow remote
    compile, execution futures in that process go bogus and
    block_until_ready returns immediately (measured 7-18x "MFU");
    process restart with the persistent compile cache warm measures
    sanely. So: re-time twice, and if the impossibility persists,
    raise instead of reporting — rerun the bench (cache-warm) to get
    a real number.
    """
    dt = timed()
    peak = _peak_tflops()
    hbm = _peak_hbm_gbps()
    impossible = 0.0
    if flops_per_step > 0 and peak > 0:
        impossible = STEPS * flops_per_step / (1.5 * peak * 1e12)
    if bytes_per_step > 0 and hbm > 0:
        # memory-bound configs (Wide&Deep) evade the FLOPs bound — but
        # bytes_accessed OVER-counts true HBM traffic (fusion/VMEM
        # re-reads), so use a wide 8x slack: rejects the observed 54x
        # glitch class without false-positives on heavily fused steps
        impossible = max(impossible,
                         STEPS * bytes_per_step / (8.0 * hbm * 1e9))
    if impossible > 0:
        for _ in range(2):
            if dt >= impossible:
                break
            print(f"# suspect timing {dt:.4f}s (< physical bound "
                  f"{impossible:.4f}s) — re-timing", file=sys.stderr)
            dt = timed()
        if dt < impossible:
            raise RuntimeError(
                f"measured {STEPS} steps in {dt:.4f}s, below the "
                f"physical bound {impossible:.4f}s (compute {peak} "
                f"TFLOP/s / HBM {hbm} GB/s peaks) — axon timing glitch "
                "(usually after a minutes-long fresh compile); rerun "
                "with the compile cache warm")
    return dt


def main():
    import jax
    import jax.numpy as jnp

    _setup_cache()

    import mxnet_tpu as mx
    from mxnet_tpu import envvars
    from mxnet_tpu.gluon.block import functionalize
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1

    ctx = mx.current_context()
    s2d = os.environ.get("BENCH_S2D", "0") == "1"
    if os.environ.get("BENCH_DATA") in ("recordio", "pipeline"):
        # data-driven epoch legs step once per REAL batch — chaining
        # would replay one batch CHAIN times
        global CHAIN
        CHAIN = 1
    # BENCH_REMAT="2,3": per-block activation recompute on those stages
    # (jax.checkpoint in the traced step) — trades forward FLOPs for
    # backward HBM traffic on the bandwidth-bound bwd mega-fusions.
    # BENCH_REMAT_POLICY="names:conv_out" saves conv outputs and
    # recomputes only the elementwise BN/relu chain in backward.
    remat = tuple(int(s) for s in os.environ.get("BENCH_REMAT", "").split(",")
                  if s.strip())
    remat_policy = os.environ.get("BENCH_REMAT_POLICY") or None
    net = resnet50_v1(classes=1000, stem="s2d" if s2d else "conv",
                      remat_stages=remat, remat_policy=remat_policy)
    net.initialize(init=mx.initializer.Xavier(), ctx=ctx)
    if DTYPE != "float32":
        net.cast(DTYPE)
    warm = mx.nd.zeros((2, 3, IMAGE, IMAGE), ctx=ctx, dtype=DTYPE)
    with mx.autograd.predict_mode():
        net(warm)

    fn, params = functionalize(net, training=True, ctx=ctx)

    def loss_fn(params, rng, x, y):
        logits = fn(params, rng, x)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()

    step = _make_momentum_sgd(loss_fn, 0.1)
    moms = _zeros_moms(params)
    rng = jax.random.PRNGKey(0)
    x = jnp.asarray(np.random.RandomState(0)
                    .rand(BATCH, 3, IMAGE, IMAGE).astype(np.float32)
                    .astype(np.dtype("float32")), dtype=DTYPE)
    y = jnp.asarray(np.random.RandomState(1).randint(0, 1000, BATCH), jnp.int32)

    if os.environ.get("BENCH_INFER") in ("1", "int8"):
        # forward-only (inference) throughput — fwd runs ~35% MFU vs
        # ~21% for backward (transposed-conv grads), see BASELINE.md.
        # BENCH_INFER=int8: rewrite Dense/Conv2D to the s8xs8->s32 MXU
        # path (contrib.quantization) — v5e int8 peak is 2x bf16
        int8 = os.environ.get("BENCH_INFER") == "int8"
        # BOTH inference variants run predict-mode BN (training=False)
        # so the int8-vs-bf16 comparison measures the same forward
        if int8:
            from mxnet_tpu.contrib.quantization import quantize_net
            with mx.autograd.predict_mode():
                # CALIBRATED scales (static): dynamic per-batch ranges
                # add a min/max reduction per layer per step, measured
                # slower than bf16 (5596 vs 7218 img/s)
                calib = [[mx.nd.array(
                    np.random.RandomState(i).rand(8, 3, IMAGE, IMAGE)
                    .astype(np.float32), ctx=ctx, dtype=DTYPE)]
                    for i in range(4)]
                # BENCH_S8_IF=1: chain conv->relu->conv interfaces in
                # s8 (requantize epilogue) instead of bf16
                quantize_net(net, calib_data=calib, ctx=ctx,
                             s8_interfaces=os.environ.get(
                                 "BENCH_S8_IF") == "1")
                net(warm)  # re-trace materializes int8 weights
        fn, params = functionalize(net, training=False, ctx=ctx)
        if CHAIN > 1:
            # chain forward passes like the train path. A bare scan of
            # identical pure forwards would be DCE/dedup bait — thread
            # a numerically-exact zero (0 * sum(out)) through the input
            # so every iteration depends on the previous one and must
            # execute (the axon tunnel also dedupes identical calls;
            # see SKILL round-4 notes).
            def infer_fn(p, rng, x):
                def body(carry_x, _):
                    out = fn(p, rng, carry_x)
                    keep = (jnp.sum(out) * 0).astype(carry_x.dtype)
                    return carry_x + keep, jnp.sum(out)
                return jax.lax.scan(body, x, None, length=CHAIN)
        else:
            def infer_fn(p, rng, x):
                out = fn(p, rng, x)
                keep = (jnp.sum(out) * 0).astype(x.dtype)
                return x + keep, jnp.sum(out)
        # x threads through every call as a FRESH (numerically equal)
        # buffer so no two dispatches have identical input ids — the
        # tunnel dedupes identical executions (SKILL round-4)
        infer = jax.jit(infer_fn, donate_argnums=(2,))
        iflops, ibytes = _step_cost(infer, params, rng, x)
        def timed_infer():
            nonlocal x
            t0 = time.perf_counter()
            for _ in range(STEPS):
                x, out = infer(params, rng, x)
            jax.block_until_ready(out)
            return time.perf_counter() - t0

        for _ in range(WARMUP):
            x, out = infer(params, rng, x)
        jax.block_until_ready(out)
        dt = _guard_impossible(lambda: _median(timed_infer, WINDOWS),
                               iflops * CHAIN, ibytes * CHAIN)
        _report("resnet50_infer_images_per_sec_per_chip",
                BATCH * STEPS * CHAIN / dt,
                "images/sec/chip", 0.0, flops_per_step=iflops,
                sec_per_step=dt / STEPS / CHAIN, bytes_per_step=ibytes,
                batch=BATCH, dtype="int8" if int8 else DTYPE, chain=CHAIN)
        return

    flops, nbytes = _step_cost(step, params, moms, rng, x, y)

    if os.environ.get("BENCH_DATA") in ("recordio", "pipeline"):
        _resnet_from_recordio(loss_fn, params, moms, rng, flops)
        return

    extras = {}
    if os.environ.get("BENCH_XPROF") == "1":
        # BEFORE the timed loop: step donates params/moms, so the
        # capture runs on copies while the originals are still live
        extras = _xprof_true_hbm(step, (params, moms, rng, x, y))

    dt = _time_steps(step, params, moms, rng, x, y,
                     flops_per_step=flops * CHAIN,
                     bytes_per_step=nbytes * CHAIN)

    imgs_per_sec = BATCH * STEPS * CHAIN / dt
    _report("resnet50_train_images_per_sec_per_chip", imgs_per_sec,
            "images/sec/chip", imgs_per_sec / BASELINE_IMGS_PER_SEC,
            flops_per_step=flops, sec_per_step=dt / STEPS / CHAIN,
            bytes_per_step=nbytes, batch=BATCH, dtype=DTYPE,
            conv_nhwc=envvars.get("MXNET_TPU_CONV_NHWC"),
            s2d_stem=s2d, remat_stages=list(remat), chain=CHAIN, **extras)


def _xprof_true_hbm(step, args_):
    """BENCH_XPROF=1: measure TRUE HBM traffic of the step from an
    xprof capture (hlo_stats hbm_bw x self-time per fusion), because
    XLA cost-analysis ``bytes accessed`` counts fused re-reads and
    read >1.0 of the physical roofline on this config (BENCH_r04).
    Opt-in: a trace capture + parse costs ~15 s the driver's budget
    doesn't need to pay every run."""
    import tempfile

    import jax

    tdir = None
    try:
        tools_dir = os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tools")
        if tools_dir not in sys.path:
            sys.path.insert(0, tools_dir)
        import xprof_roofline as xr

        import jax.numpy as jnp

        tdir = tempfile.mkdtemp(prefix="bench_xprof_")
        # copies feed the donating step so the caller's buffers survive
        safe = tuple(jax.tree_util.tree_map(jnp.copy, a) for a in args_[:2])
        out = step(*safe, *args_[2:])
        jax.block_until_ready(out)
        n = 3
        with jax.profiler.trace(tdir):
            for _ in range(n):
                out = step(*out[:2], *args_[2:])
            jax.block_until_ready(out)
        rows = list(xr._rows(xr._tool_data(tdir)))
        total_us = sum(xr._f(r, "total_self_time") for r in rows)
        hbm_bytes = sum(xr._f(r, "hbm_bw") * 1e9 *
                        xr._f(r, "total_self_time") * 1e-6 for r in rows)
        if not total_us:
            return {}
        gbps = hbm_bytes / (total_us * 1e-6) / 1e9
        peak = _peak_hbm_gbps()
        # per-model-step: the capture runs chained executables too, so
        # normalize by captured device time, not step count
        rec = {"hbm_gbs_xprof": round(gbps, 1),
               "device_ms_per_step_xprof":
                   round(total_us / 1000.0 / (n * CHAIN), 3)}
        if peak:
            rec["hbm_frac_xprof"] = round(gbps / peak, 4)
        return rec
    except Exception as e:  # profiling must never sink the bench
        print(f"# BENCH_XPROF failed: {e}", file=sys.stderr)
        return {}
    finally:
        if tdir:
            import shutil
            shutil.rmtree(tdir, ignore_errors=True)


def _resnet_from_recordio(loss_fn, params, moms, rng, flops):
    """End-to-end input-pipeline bench (SURVEY §7 hard part #6): feed the
    same jitted ResNet step from a generated JPEG RecordIO file through
    the multiprocess decode pipeline + device prefetch, and report
    img/s plus pipeline-vs-compute utilization (the reference's
    iter_image_recordio_2.cc role)."""
    import tempfile

    import jax

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.data import DataLoader, DevicePrefetcher
    from mxnet_tpu.gluon.data.dataset import Dataset

    n_img = int(os.environ.get("BENCH_PIPELINE_IMAGES", str(BATCH * (STEPS + WARMUP))))
    workers = int(os.environ.get("BENCH_WORKERS", "8"))
    tmp = tempfile.mkdtemp(prefix="bench_rec_")
    rec_path = os.path.join(tmp, "synthetic.rec")
    idx_path = os.path.join(tmp, "synthetic.idx")
    rs = np.random.RandomState(0)
    rec = mx.recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(n_img):
        img = rs.randint(0, 255, (IMAGE, IMAGE, 3), dtype=np.uint8)
        header = mx.recordio.IRHeader(0, float(i % 1000), i, 0)
        rec.write_idx(i, mx.recordio.pack_img(header, img, quality=90))
    rec.close()

    class RecDataset(Dataset):
        """JPEG decode in the worker process. Ships uint8 CHW — 4x less
        IPC traffic than float32 (the shared-memory lesson of
        iter_image_recordio_2.cc); normalization happens on-device in
        the jitted step."""

        def __init__(self):
            self._rec = None  # opened lazily per worker process

        def __len__(self):
            return n_img

        def __getitem__(self, i):
            if self._rec is None:
                self._rec = mx.recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
            header, img = mx.recordio.unpack_img(self._rec.read_idx(i))
            return img.transpose(2, 0, 1), np.float32(header.label)

    # pipeline choice: the native C++ batcher (threaded libjpeg decode,
    # CHW batches, no GIL/no IPC) when it builds, else the python
    # multiprocess DataLoader
    pipeline = os.environ.get("BENCH_PIPELINE", "native")
    batcher = None
    if pipeline == "native":
        try:
            from mxnet_tpu.io.native import NativeImageBatcher
            batcher = NativeImageBatcher(
                rec_path, idx_path, batch_size=BATCH,
                data_shape=(3, IMAGE, IMAGE), num_threads=workers)
        except Exception:
            pipeline = "python"
    if batcher is None:
        loader = DataLoader(RecDataset(), batch_size=BATCH, shuffle=False,
                            num_workers=workers, last_batch="discard")

    # uint8→dtype normalize + label cast live INSIDE the jitted step:
    # eager per-batch conversion ops would each be a round-trip to the
    # (possibly remote) accelerator
    import jax.numpy as jnp

    def loss_u8(p, rng, x_u8, y_f32):
        x = x_u8.astype(jnp.dtype(DTYPE)) * np.asarray(1.0 / 255.0,
                                                       np.dtype(DTYPE))
        return loss_fn(p, rng, x, y_f32.astype(jnp.int32))

    step = _make_momentum_sgd(loss_u8, 0.1)

    def batches():
        if batcher is not None:
            while True:
                out = batcher.next()
                if out is None:
                    break
                yield out
            batcher.reset()
        else:
            yield from loader

    def run_epoch(p, m):
        n_steps = 0
        loss = None
        # DevicePrefetcher overlaps H2D with compute for BOTH pipelines
        for xb, yb in DevicePrefetcher(batches(), depth=3):
            p, m, loss = step(p, m, rng, xb._data, yb._data)
            n_steps += 1
        if loss is not None:
            jax.block_until_ready(loss)
        return n_steps, p, m

    pipeline_mode = os.environ.get("BENCH_DATA") == "pipeline"
    extras = {}
    if pipeline_mode:
        # leg 1 — standalone decode rate, measured with the device idle
        # (the axon tunnel spin-waits across host cores while device
        # work is in flight, poisoning any overlapped measurement of
        # host decode; see BASELINE.md "axon" notes)
        for _ in batches():  # warm pass: worker spawn + readahead
            pass
        nb = 0
        t0 = time.perf_counter()
        for _ in batches():
            nb += 1
        t_dec = time.perf_counter() - t0
        if nb == 0:
            raise RuntimeError(
                f"pipeline bench produced no full batches "
                f"(BENCH_PIPELINE_IMAGES={n_img} < batch {BATCH}?)")
        decode_rate = nb * BATCH / t_dec
        # leg 2 — synthetic compute rate on a fixed device batch
        xs = jnp.zeros((BATCH, 3, IMAGE, IMAGE), jnp.uint8)
        ys = jnp.zeros((BATCH,), jnp.float32)
        p, m = params, moms
        for _ in range(3):
            p, m, loss = step(p, m, rng, xs, ys)
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(10):
            p, m, loss = step(p, m, rng, xs, ys)
        jax.block_until_ready(loss)
        t_cmp = time.perf_counter() - t0
        compute_rate = 10 * BATCH / t_cmp
        params, moms = p, m
        try:
            usable_cores = len(os.sched_getaffinity(0))
        except AttributeError:
            usable_cores = os.cpu_count()
        decode_cores = min(workers, usable_cores)
        extras = {"decode_img_s": round(decode_rate, 1),
                  "compute_img_s": round(compute_rate, 1),
                  "host_cores": usable_cores,
                  "decode_ms_per_img_per_core":
                      round(1000.0 * decode_cores / decode_rate, 3)}

    # warmup epoch: compile + page cache (params are donated — thread
    # the returned state into the timed epoch)
    _, p, m = run_epoch(params, moms)
    t0 = time.perf_counter()
    n_steps, p, m = run_epoch(p, m)
    dt = time.perf_counter() - t0
    imgs_per_sec = n_steps * BATCH / dt
    if pipeline_mode:
        bound = min(extras["decode_img_s"], extras["compute_img_s"])
        extras["pipeline_utilization"] = round(imgs_per_sec / bound, 4)
    _report("resnet50_recordio_images_per_sec_per_chip", imgs_per_sec,
            "images/sec/chip", imgs_per_sec / BASELINE_IMGS_PER_SEC,
            flops_per_step=flops, sec_per_step=dt / max(n_steps, 1),
            batch=BATCH, dtype=DTYPE, workers=workers,
            pipeline=pipeline, pipeline_images=n_img, **extras)


def main_bert():
    """BERT-base MLM pretraining step, tokens/sec/chip (BASELINE #3).

    bf16 trunk, fused Pallas flash-attention/LayerNorm/softmax-CE path.
    No per-chip reference number exists (BASELINE.md: BERT lives in
    GluonNLP, mount empty) — vs_baseline reports 0.0.
    """
    import jax
    import jax.numpy as jnp

    _setup_cache()

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.block import functionalize
    from mxnet_tpu.gluon.model_zoo import bert_base
    from mxnet_tpu.gluon.model_zoo.bert import BERTMLMHead

    # batch 64 measured fastest (sweep r2: 32→103k, 64→109k, 128→108.5k
    # tok/s at 36.4% MFU)
    batch = int(os.environ.get("BENCH_BATCH", "64"))
    seqlen = int(os.environ.get("BENCH_SEQLEN", "128"))
    vocab = 30522
    ctx = mx.current_context()

    net = bert_base(vocab_size=vocab, max_length=max(512, seqlen),
                    dropout=0.0)
    head = BERTMLMHead(vocab, 768)
    net.initialize(init=mx.initializer.Normal(0.02), ctx=ctx)
    head.initialize(init=mx.initializer.Normal(0.02), ctx=ctx)
    if DTYPE != "float32":
        net.cast(DTYPE)
        head.cast(DTYPE)

    ids = mx.nd.zeros((2, seqlen), ctx=ctx, dtype="int32")
    tt = mx.nd.zeros((2, seqlen), ctx=ctx, dtype="int32")
    with mx.autograd.predict_mode():
        head(net(ids, tt)[0])

    fn, params = functionalize(net, training=True, ctx=ctx)
    hfn, hparams = functionalize(head, training=True, ctx=ctx)

    # BENCH_PADDED=1: variable-length MLM batch (lengths uniform in
    # [S/2, S]) — valid_length rides the flash kernel's per-row
    # kv-length path and the loss masks padded positions. The real
    # pretraining shape (VERDICT r3 #2).
    # BENCH_PACKED=1: the SAME length distribution, first-fit PACKED
    # into rows of BENCH_PACK_ROWLEN (default 4*S) slots — segment_ids
    # ride the kernel's block-diagonal path, positions restart per
    # sequence, the loss masks padding. Total slot count matches the
    # padded leg (rows * row_len == batch * seqlen) so the two legs
    # spend comparable step budgets; the win shows up as
    # valid_tokens_per_sec.
    padded = os.environ.get("BENCH_PADDED", "0") == "1"
    packed = os.environ.get("BENCH_PACKED", "0") == "1"

    rng = jax.random.PRNGKey(0)
    npr = np.random.RandomState(0)
    ps = (params, hparams)

    def xent(flat, labels_flat):
        from mxnet_tpu.ops import pallas as _pallas
        if _pallas.pallas_enabled():
            return _pallas.softmax_xent_fused(flat, labels_flat)
        logp = jax.nn.log_softmax(flat.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(
            logp, labels_flat[:, None], axis=-1)[:, 0]

    if packed:
        from mxnet_tpu.io.packing import pack_sequences, packing_efficiency

        row_len = int(os.environ.get("BENCH_PACK_ROWLEN", str(4 * seqlen)))
        rows = max(1, batch * seqlen // row_len)
        # pack a 4x-oversampled stream first-fit, keep the ROWS fullest
        # rows: first-fit's only low-occupancy rows are the open tail
        # rows of the stream, which a continuous reader would keep
        # filling — the kept rows are its steady state (measured ~0.99
        # occupancy on the U[S/2, S] distribution)
        n_pool = 4 * rows * row_len // (3 * seqlen // 4)
        lens_pool = npr.randint(seqlen // 2, seqlen + 1, n_pool)
        seq_pool = [npr.randint(0, vocab, n).astype(np.int32)
                    for n in lens_pool]
        lab_pool = [npr.randint(0, vocab, n).astype(np.int32)
                    for n in lens_pool]
        pb = pack_sequences(seq_pool, row_len, extras=[lab_pool])
        order = np.argsort(-pb.valid_length)[:rows]
        ids = jnp.asarray(pb.data[order], jnp.int32)
        segs = jnp.asarray(pb.segment_ids[order], jnp.int32)
        pos = jnp.asarray(pb.positions[order], jnp.int32)
        lens = jnp.asarray(pb.valid_length[order], jnp.int32)
        labels = jnp.asarray(pb.extras[0][order], jnp.int32)
        tt = jnp.zeros((rows, row_len), jnp.int32)
        pack_eff = packing_efficiency(pb.segment_ids[order])

        def loss_fn(ps, rng, ids, tt, lens, segs, pos, labels):
            p1, p2 = ps
            seq, _ = fn(p1, rng, ids, tt, lens, None, segs, pos)
            logits = hfn(p2, rng, seq)
            loss = xent(logits.reshape(-1, vocab), labels.reshape(-1))
            w = (segs > 0).astype(jnp.float32).reshape(-1)
            return (loss.astype(jnp.float32) * w).sum() / w.sum()

        args = (ids, tt, lens, segs, pos, labels)
    else:
        ids = jnp.asarray(npr.randint(0, vocab, (batch, seqlen)), jnp.int32)
        tt = jnp.zeros((batch, seqlen), jnp.int32)
        lens = jnp.asarray(npr.randint(seqlen // 2, seqlen + 1, batch)
                           if padded else np.full(batch, seqlen), jnp.int32)
        labels = jnp.asarray(npr.randint(0, vocab, (batch, seqlen)),
                             jnp.int32)

        def loss_fn(ps, rng, ids, tt, lens, labels):
            p1, p2 = ps
            if padded:
                seq, _ = fn(p1, rng, ids, tt, lens)
            else:
                seq, _ = fn(p1, rng, ids, tt)
            # model dtype logits: the CE kernel upcasts in VMEM
            loss = xent(hfn(p2, rng, seq).reshape(-1, vocab),
                        labels.reshape(-1))
            if padded:
                w = (jnp.arange(seqlen)[None, :] < lens[:, None]) \
                    .astype(jnp.float32).reshape(-1)
                return (loss.astype(jnp.float32) * w).sum() / w.sum()
            return loss.mean()

        args = (ids, tt, lens, labels)

    step = _make_momentum_sgd(loss_fn, 1e-3)
    moms = _zeros_moms(ps)

    if os.environ.get("BENCH_PRECOMPILE") == "1":
        _precompile(step, ps, moms, rng, *args,
                    seqlen=seqlen, batch=batch, chain=CHAIN, dtype=DTYPE)
        return

    flops, nbytes = _step_cost(step, ps, moms, rng, *args)
    dt = _time_steps(step, ps, moms, rng, *args,
                     flops_per_step=flops * CHAIN,
                     bytes_per_step=nbytes * CHAIN)

    # slots/sec uses all positions (directly comparable to the unmasked
    # config — same flops basis); valid tokens/sec is the useful-work
    # rate on the padded/packed batch
    slots = rows * row_len if packed else batch * seqlen
    slots_per_sec = slots * STEPS * CHAIN / dt
    extras = {}
    if packed:
        extras = {"packed": True, "row_len": row_len, "rows": rows,
                  "packing_efficiency": round(pack_eff, 4),
                  "valid_tokens_per_sec": round(slots_per_sec * pack_eff, 2)}
    elif padded:
        valid_frac = float(np.asarray(lens).sum()) / (batch * seqlen)
        extras = {"padded": True, "valid_frac": round(valid_frac, 4),
                  "valid_tokens_per_sec": round(slots_per_sec * valid_frac,
                                                2)}
    _report("bert_base_train_tokens_per_sec_per_chip", slots_per_sec,
            "tokens/sec/chip", 0.0,
            flops_per_step=flops, sec_per_step=dt / STEPS / CHAIN,
            bytes_per_step=nbytes, batch=rows if packed else batch,
            seqlen=seqlen, dtype=DTYPE, chain=CHAIN, **extras)


def main_causal_lm():
    """Packed CAUSAL LM training step, tokens/sec/chip (ROADMAP
    follow-up: the causal segment kernel path was tested but never
    benchmarked). GPT-small-shaped trunk at the bert_base budget
    (L=12, H=768, A=12 over a 30522 vocab), always packed: the same
    U[S/2, S] length mix as the packed BERT leg, first-fit into
    BENCH_PACK_ROWLEN-slot rows, per-segment causal attention via the
    flash kernel's segment_ids + causal path, next-token labels
    shifted within each segment."""
    import jax
    import jax.numpy as jnp

    _setup_cache()

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.block import functionalize
    from mxnet_tpu.io.packing import pack_sequences, packing_efficiency

    batch = int(os.environ.get("BENCH_BATCH", "64"))
    seqlen = int(os.environ.get("BENCH_SEQLEN", "512"))
    vocab = int(os.environ.get("BENCH_VOCAB", "30522"))
    units = int(os.environ.get("BENCH_LM_UNITS", "768"))
    layers = int(os.environ.get("BENCH_LM_LAYERS", "12"))
    heads = int(os.environ.get("BENCH_LM_HEADS", "12"))
    ctx = mx.current_context()

    class PackedCausalLM(mx.gluon.HybridBlock):
        """embed + per-segment positions -> causal encoder -> vocab."""

        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = mx.gluon.nn.Embedding(vocab, units)
                self.pos_embed = mx.gluon.nn.Embedding(seqlen, units)
                self.encoder = mx.gluon.nn.TransformerEncoder(
                    layers, units, 4 * units, heads, dropout=0.0,
                    attention_dropout=0.0, activation="gelu",
                    causal=True)
                self.decoder = mx.gluon.nn.Dense(vocab, flatten=False)

        def hybrid_forward(self, F, ids, positions, valid_length,
                           segment_ids):
            x = self.embed(ids) + self.pos_embed(positions)
            h = self.encoder(x, None, valid_length, segment_ids)
            return self.decoder(h)

    net = PackedCausalLM()
    net.initialize(init=mx.initializer.Normal(0.02), ctx=ctx)
    if DTYPE != "float32":
        net.cast(DTYPE)
    warm = mx.nd.zeros((2, seqlen), ctx=ctx, dtype="int32")
    with mx.autograd.predict_mode():
        net(warm, warm, mx.nd.array([seqlen, seqlen], ctx=ctx,
                                    dtype="int32"), warm)
    fn, params = functionalize(net, training=True, ctx=ctx)

    rng = jax.random.PRNGKey(0)
    npr = np.random.RandomState(0)
    row_len = int(os.environ.get("BENCH_PACK_ROWLEN", str(4 * seqlen)))
    rows = max(1, batch * seqlen // row_len)
    # same oversample-and-keep-fullest selection as the packed BERT leg
    n_pool = 4 * rows * row_len // (3 * seqlen // 4)
    lens_pool = npr.randint(seqlen // 2, seqlen + 1, n_pool)
    seq_pool = [npr.randint(0, vocab, n).astype(np.int32)
                for n in lens_pool]
    # next-token labels INSIDE each segment (the last position predicts
    # a fresh random token — same flops, honest LM shape)
    lab_pool = [np.concatenate([s[1:], npr.randint(0, vocab, 1)
                                .astype(np.int32)]) for s in seq_pool]
    pb = pack_sequences(seq_pool, row_len, extras=[lab_pool])
    order = np.argsort(-pb.valid_length)[:rows]
    ids = jnp.asarray(pb.data[order], jnp.int32)
    segs = jnp.asarray(pb.segment_ids[order], jnp.int32)
    pos = jnp.asarray(pb.positions[order], jnp.int32)
    lens = jnp.asarray(pb.valid_length[order], jnp.int32)
    labels = jnp.asarray(pb.extras[0][order], jnp.int32)
    pack_eff = packing_efficiency(pb.segment_ids[order])

    def xent(flat, labels_flat):
        from mxnet_tpu.ops import pallas as _pallas
        if _pallas.pallas_enabled():
            return _pallas.softmax_xent_fused(flat, labels_flat)
        logp = jax.nn.log_softmax(flat.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(
            logp, labels_flat[:, None], axis=-1)[:, 0]

    def loss_fn(params, rng, ids, pos, lens, segs, labels):
        logits = fn(params, rng, ids, pos, lens, segs)
        loss = xent(logits.reshape(-1, vocab), labels.reshape(-1))
        w = (segs > 0).astype(jnp.float32).reshape(-1)
        return (loss.astype(jnp.float32) * w).sum() / w.sum()

    step = _make_momentum_sgd(loss_fn, 1e-3)
    moms = _zeros_moms(params)
    args = (ids, pos, lens, segs, labels)

    flops, nbytes = _step_cost(step, params, moms, rng, *args)
    dt = _time_steps(step, params, moms, rng, *args,
                     flops_per_step=flops * CHAIN,
                     bytes_per_step=nbytes * CHAIN)

    slots = rows * row_len
    slots_per_sec = slots * STEPS * CHAIN / dt
    _report("causal_lm_train_tokens_per_sec_per_chip", slots_per_sec,
            "tokens/sec/chip", 0.0,
            flops_per_step=flops, sec_per_step=dt / STEPS / CHAIN,
            bytes_per_step=nbytes, batch=rows, seqlen=seqlen, dtype=DTYPE,
            chain=CHAIN, packed=True, causal=True, row_len=row_len,
            rows=rows, packing_efficiency=round(pack_eff, 4),
            valid_tokens_per_sec=round(slots_per_sec * pack_eff, 2))


def main_serving():
    """Closed-loop packed continuous-batching serving bench
    (mxnet_tpu/serving): synthetic variable-length traffic from
    BENCH_SERVE_CLIENTS closed-loop clients against a BERT
    encoder/embedder, reporting requests/sec, client-observed
    p50/p95/p99 latency, valid_tokens_per_sec, and the engine's batch
    packing_efficiency. The engine pre-compiles its whole shape
    universe (warmup) so the measured window is steady-state serving,
    not tracing."""
    _setup_cache()

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.bert import BERTModel, bert_serving_entry
    from mxnet_tpu.serving import ServingEngine

    tools_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    from serve_loadgen import run_load

    seqlen = int(os.environ.get("BENCH_SEQLEN", "512"))
    vocab = int(os.environ.get("BENCH_VOCAB", "30522"))
    units = int(os.environ.get("BENCH_SERVE_UNITS", "768"))
    layers = int(os.environ.get("BENCH_SERVE_LAYERS", "12"))
    heads = int(os.environ.get("BENCH_SERVE_HEADS", "12"))
    clients = int(os.environ.get("BENCH_SERVE_CLIENTS", "16"))
    reqs = int(os.environ.get("BENCH_SERVE_REQS", "16"))
    max_rows = int(os.environ.get("BENCH_SERVE_ROWS", "8"))
    buckets = tuple(int(b) for b in os.environ.get(
        "BENCH_SERVE_BUCKETS", f"{max(1, seqlen // 4)},{seqlen}")
        .split(","))
    ctx = mx.current_context()

    net = BERTModel(vocab_size=vocab, units=units, hidden_size=4 * units,
                    num_layers=layers, num_heads=heads, max_length=seqlen,
                    dropout=0.0, attention_dropout=0.0, use_pooler=False)
    net.initialize(init=mx.initializer.Normal(0.02), ctx=ctx)
    if DTYPE != "float32":
        net.cast(DTYPE)

    engine = ServingEngine(bert_serving_entry(net), ctx=ctx,
                           bucket_lens=buckets, max_rows=max_rows,
                           max_queue_depth=max(64, 8 * clients),
                           pool="mean")
    with engine:
        # scrape-side observability rides the measured run: the loadgen
        # cross-checks /metrics counter deltas against its own books
        metrics_url = engine.expose().url("/metrics")
        engine.warmup()
        # one throwaway closed-loop pass: page caches, thread spin-up
        run_load(engine, n_clients=min(4, clients), requests_per_client=2,
                 min_len=max(4, seqlen // 8), max_len=seqlen, vocab=vocab)
        # fresh stats: the reported packing/latency numbers must cover
        # ONLY the measured window, not the throwaway traffic
        engine.reset_stats()
        report = run_load(engine, n_clients=clients,
                          requests_per_client=reqs,
                          min_len=max(4, seqlen // 8), max_len=seqlen,
                          vocab=vocab, metrics_url=metrics_url)
    snap = report.pop("engine")
    assert report["completed"] == clients * reqs, report
    server = report.get("server", {})
    assert server.get("reconciled", True), server
    cost = report.get("cost", {})
    _report("bert_serving_requests_per_sec_per_chip",
            report["requests_per_sec"], "requests/sec/chip", 0.0,
            seqlen=seqlen, batch=max_rows, clients=clients,
            requests=report["completed"], dtype=DTYPE,
            p50_ms=report["p50_ms"], p95_ms=report["p95_ms"],
            p99_ms=report["p99_ms"],
            valid_tokens_per_sec=report["valid_tokens_per_sec"],
            packing_efficiency=snap["packing_efficiency"],
            serve_buckets=list(buckets),
            compute_p50_ms=snap["latency"]["compute"].get("p50_ms"),
            queue_p50_ms=snap["latency"]["queue"].get("p50_ms"),
            telemetry_reconciled=server.get("reconciled"),
            cost_reconciled=cost.get("reconciled"),
            device_s_per_1k_tokens=cost.get("device_s_per_1k_tokens"),
            slo_compliance=_slo_compliance(report),
            server_p50_ms_est=server.get("latency", {}).get("p50_ms_est"))


def _slo_compliance(report):
    """Error-budget remaining per declared objective off the loadgen's
    ``/slo`` fetch — the serving legs' one-line SLO answer (None when
    MXNET_TPU_SLO=0, or the engine predates the SLO engine)."""
    slo = report.get("slo")
    if not slo:
        return None
    return {name: row.get("error_budget_remaining")
            for name, row in sorted(slo.items())}


def _router_fleet_setup(clients_default, reqs_default):
    """Shared config + fresh-engine factory for the router-fronted
    serving legs (`bert_serving_router`, `bert_serving_restart`): a
    small BERT per engine, BENCH_* env overrides, one code path so the
    two legs cannot drift apart. ``make_engine(i)`` builds a FRESH
    model each call — a restart drill must pay a real re-trace,
    exactly what a process restart pays."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.bert import BERTModel, bert_serving_entry
    from mxnet_tpu.serving import ServingEngine

    tools_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)

    cfg = {
        "n_engines": int(os.environ.get("BENCH_ROUTER_ENGINES", "2")),
        "seqlen": int(os.environ.get("BENCH_SEQLEN", "256")),
        "vocab": int(os.environ.get("BENCH_VOCAB", "30522")),
        "units": int(os.environ.get("BENCH_SERVE_UNITS", "256")),
        "layers": int(os.environ.get("BENCH_SERVE_LAYERS", "4")),
        "heads": int(os.environ.get("BENCH_SERVE_HEADS", "8")),
        "clients": int(os.environ.get("BENCH_SERVE_CLIENTS",
                                      str(clients_default))),
        "reqs": int(os.environ.get("BENCH_SERVE_REQS",
                                   str(reqs_default))),
        "max_rows": int(os.environ.get("BENCH_SERVE_ROWS", "8")),
    }
    cfg["buckets"] = tuple(int(b) for b in os.environ.get(
        "BENCH_SERVE_BUCKETS",
        f"{max(1, cfg['seqlen'] // 4)},{cfg['seqlen']}").split(","))
    ctx = mx.current_context()

    def make_engine(i):
        net = BERTModel(vocab_size=cfg["vocab"], units=cfg["units"],
                        hidden_size=4 * cfg["units"],
                        num_layers=cfg["layers"], num_heads=cfg["heads"],
                        max_length=cfg["seqlen"], dropout=0.0,
                        attention_dropout=0.0, use_pooler=False)
        net.initialize(init=mx.initializer.Normal(0.02), ctx=ctx)
        if DTYPE != "float32":
            net.cast(DTYPE)
        # i is an index (classic legs) or a full engine-id string (the
        # chaos drill's autoscaler spawns replacements by name)
        eid = f"e{i}" if isinstance(i, int) else str(i)
        return ServingEngine(bert_serving_entry(net), ctx=ctx,
                             bucket_lens=cfg["buckets"],
                             max_rows=cfg["max_rows"],
                             max_queue_depth=max(64, 8 * cfg["clients"]),
                             pool="mean", engine_id=eid)

    return cfg, make_engine


def main_serving_router():
    """Multi-engine router serving bench: BENCH_ROUTER_ENGINES
    (default 2) in-process engines behind a ServingRouter, the same
    closed-loop traffic as the single-engine leg driven at the ROUTER.
    Reports router req/s, per-engine request share (least-outstanding
    should keep it near-even), failover count (0 in the happy path —
    nonzero means an engine died mid-bench), and the loadgen's
    reconciliation of the router's AGGREGATED /metrics against client
    accounting. Defaults are smaller than the single-engine leg: the
    number under test is the router plane, not one more BERT forward."""
    _setup_cache()

    from mxnet_tpu.serving import ServingRouter

    cfg, make_engine = _router_fleet_setup(clients_default=16,
                                           reqs_default=16)
    from serve_loadgen import run_load

    n_engines, seqlen, vocab, clients, reqs = (
        cfg["n_engines"], cfg["seqlen"], cfg["vocab"], cfg["clients"],
        cfg["reqs"])

    import contextlib
    with contextlib.ExitStack() as stack:
        engines = [stack.enter_context(make_engine(i))
                   for i in range(n_engines)]
        router = stack.enter_context(ServingRouter(engines=engines))
        metrics_url = router.expose().url("/metrics")
        for eng in engines:
            eng.warmup()
        run_load(router, n_clients=min(4, clients),
                 requests_per_client=2, min_len=max(4, seqlen // 8),
                 max_len=seqlen, vocab=vocab)
        for eng in engines:
            eng.reset_stats()
        report = run_load(router, n_clients=clients,
                          requests_per_client=reqs,
                          min_len=max(4, seqlen // 8), max_len=seqlen,
                          vocab=vocab, metrics_url=metrics_url)
    report.pop("engine")       # the router metric line stands alone;
    # a failed assert below must not dump the whole fleet snapshot
    assert report["completed"] == clients * reqs, report
    server = report.get("server", {})
    assert server.get("reconciled", True), server
    # per-engine share from the /metrics DELTA (window-exact; the
    # router's dispatched counts also cover the warmup pass)
    per_engine = (server.get("per_engine_completed")
                  or report["per_engine"])
    total = max(1, sum(per_engine.values()))
    _report("bert_serving_router_requests_per_sec",
            report["requests_per_sec"], "requests/sec", 0.0,
            seqlen=seqlen, clients=clients, engines=n_engines,
            requests=report["completed"], dtype=DTYPE,
            p50_ms=report["p50_ms"], p95_ms=report["p95_ms"],
            p99_ms=report["p99_ms"],
            valid_tokens_per_sec=report["valid_tokens_per_sec"],
            per_engine={eid: round(n / total, 3)
                        for eid, n in sorted(per_engine.items())},
            failover=report["failovers"],
            engines_up=report["engines_up"],
            cost_reconciled=report.get("cost", {}).get("reconciled"),
            device_s_per_1k_tokens=report.get("cost", {})
            .get("device_s_per_1k_tokens"),
            slo_compliance=_slo_compliance(report),
            telemetry_reconciled=server.get("reconciled"),
            server_p50_ms_est=server.get("latency", {}).get("p50_ms_est"))

    # -- wire-vs-JSON A/B: the same fleet REMOTE-fronted --------------------
    # The in-process run above measures the router plane; this phase
    # measures the DISPATCH TRANSPORT. The engines expose() and the
    # router fronts them by URL, once over the binary wire (persistent
    # multiplexed connections, raw typed ndarrays) and once pinned to
    # the HTTP/JSON long-poll — same engines, same traffic, so the
    # delta is pure serialization+transport. The wire must win on both
    # serialized bytes/request and dispatch-overhead p50.
    from mxnet_tpu.serving.metrics import (wire_bytes_counter,
                                           wire_fallback_counter)

    byt = wire_bytes_counter()
    fall = wire_fallback_counter()

    def _bytes(transport):
        return sum(byt.labels(side="router", transport=transport,
                              direction=d).value for d in ("in", "out"))

    def _fallbacks():
        return sum(fall.labels(engine_id=f"e{i}").value
                   for i in range(n_engines))

    ab = {}
    with contextlib.ExitStack() as stack:
        engines = [stack.enter_context(make_engine(i))
                   for i in range(n_engines)]
        urls = []
        for eng in engines:
            srv = eng.expose(port=0)
            urls.append(f"http://{srv.host}:{srv.port}")
            eng.warmup()
        for transport, wire_flag in (("wire", True), ("json", False)):
            router = ServingRouter(
                {f"e{i}": url for i, url in enumerate(urls)},
                wire=wire_flag, poll_interval_s=0.2)
            with router:
                if wire_flag:
                    deadline = time.perf_counter() + 15.0
                    while time.perf_counter() < deadline and not all(
                            row.get("transport") == "wire"
                            for row in router.scoreboard().values()):
                        time.sleep(0.1)
                    assert all(row.get("transport") == "wire"
                               for row in router.scoreboard().values()), \
                        router.scoreboard()
                b0, f0 = _bytes(transport), _fallbacks()
                rep = run_load(router, n_clients=clients,
                               requests_per_client=reqs,
                               min_len=max(4, seqlen // 8),
                               max_len=seqlen, vocab=vocab)
                nbytes = _bytes(transport) - b0
                assert rep["completed"] == clients * reqs, rep
                over = router.snapshot()["dispatch_overhead"] \
                    .get(transport) or {}
                ab[transport] = {
                    "requests_per_sec": rep["requests_per_sec"],
                    "p50_ms": rep["p50_ms"], "p99_ms": rep["p99_ms"],
                    "bytes_per_request": round(
                        nbytes / max(1, rep["completed"]), 1),
                    "dispatch_overhead_p50_ms": over.get("p50_ms"),
                    "dispatch_overhead_p99_ms": over.get("p99_ms"),
                    # nonzero on the wire run = it limped through HTTP
                    "fallbacks": (int(_fallbacks() - f0)
                                  if wire_flag else None)}
    wire_ab, json_ab = ab["wire"], ab["json"]
    # the acceptance bar: binary framing beats decimal-text JSON on
    # the serialized payload AND on what the transport costs on top
    # of the engine wall
    assert wire_ab["bytes_per_request"] < json_ab["bytes_per_request"], ab
    assert (wire_ab["dispatch_overhead_p50_ms"]
            < json_ab["dispatch_overhead_p50_ms"]), ab
    _report("bert_serving_router_wire_requests_per_sec",
            wire_ab["requests_per_sec"], "requests/sec", 0.0,
            seqlen=seqlen, clients=clients, engines=n_engines,
            dtype=DTYPE, transport="wire", wire=wire_ab, json=json_ab,
            bytes_per_request_ratio=round(
                wire_ab["bytes_per_request"]
                / max(1e-9, json_ab["bytes_per_request"]), 4),
            dispatch_overhead_p50_speedup=round(
                json_ab["dispatch_overhead_p50_ms"]
                / max(1e-9, wire_ab["dispatch_overhead_p50_ms"]), 2))


def main_serving_multitenant():
    """Multi-tenant multi-model serving bench
    (`bert_serving_multitenant`): two named models on every engine of
    a 2-seat router fleet, driven to OVERLOAD by a weighted tenant mix
    (priority:standard:best-effort closed-loop clients), with a live
    hot-swap of one model mid-load.

    The acceptance shape: best-effort absorbs the shedding while
    priority takes none and holds the tightest p99; every named
    tenant's bill reconciles against the server's tenant-slice
    counters; and the mid-load ``swap_model`` loses ZERO requests and
    leaves the new version warm (a post-swap probe answers in
    compile-free milliseconds)."""
    _setup_cache()

    import contextlib
    import threading

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.bert import BERTModel, bert_serving_entry
    from mxnet_tpu.serving import (ModelRegistry, ServingEngine,
                                   ServingRouter)

    tools_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    from serve_loadgen import parse_tenant_spec, run_load

    seqlen = int(os.environ.get("BENCH_SEQLEN", "128"))
    vocab = int(os.environ.get("BENCH_VOCAB", "30522"))
    units = int(os.environ.get("BENCH_SERVE_UNITS", "128"))
    layers = int(os.environ.get("BENCH_SERVE_LAYERS", "2"))
    heads = int(os.environ.get("BENCH_SERVE_HEADS", "4"))
    reqs = int(os.environ.get("BENCH_SERVE_REQS", "10"))
    max_rows = int(os.environ.get("BENCH_SERVE_ROWS", "2"))
    # small queue + rows ON PURPOSE: the tenant mix must overrun the
    # fleet (clients > queues + in-flight) so the WFQ eviction order
    # (best-effort first, priority never) is actually exercised, not
    # just plausible
    queue_depth = int(os.environ.get("BENCH_SERVE_QUEUE", "2"))
    tenants = parse_tenant_spec(os.environ.get(
        "BENCH_TENANTS", "priority:2,standard:4,best-effort:10"))
    p99_bound_ms = float(os.environ.get("BENCH_TENANT_P99_MS", "5000"))
    buckets = tuple(int(b) for b in os.environ.get(
        "BENCH_SERVE_BUCKETS",
        f"{max(1, seqlen // 4)},{seqlen}").split(","))
    model_ids = ("m-a", "m-b")
    ctx = mx.current_context()

    def make_entry():
        net = BERTModel(vocab_size=vocab, units=units,
                        hidden_size=4 * units, num_layers=layers,
                        num_heads=heads, max_length=seqlen, dropout=0.0,
                        attention_dropout=0.0, use_pooler=False)
        net.initialize(init=mx.initializer.Normal(0.02), ctx=ctx)
        if DTYPE != "float32":
            net.cast(DTYPE)
        return bert_serving_entry(net)

    with contextlib.ExitStack() as stack:
        engines = []
        for i in range(2):
            reg = ModelRegistry()
            entry = make_entry()
            for mid in model_ids:
                reg.register(mid, entry, version="v1")
            engines.append(stack.enter_context(ServingEngine(
                reg, ctx=ctx, bucket_lens=buckets, max_rows=max_rows,
                max_queue_depth=queue_depth, pool="mean",
                engine_id=f"e{i}")))
        router = stack.enter_context(ServingRouter(engines=engines))
        metrics_url = router.expose().url("/metrics")
        for eng in engines:
            eng.warmup()
        run_load(router, n_clients=4, requests_per_client=2,
                 min_len=max(4, seqlen // 8), max_len=seqlen,
                 vocab=vocab, model_ids=list(model_ids))
        for eng in engines:
            eng.reset_stats()

        # mid-load hot-swap: a fresh m-b v2 is warm-replayed and cut
        # over on BOTH seats while the tenant mix is in full flight
        swap = {"ms": None, "error": None}

        def swapper():
            time.sleep(0.5)
            try:
                entry2 = make_entry()
                t0 = time.perf_counter()
                for eng in engines:
                    eng.swap_model(entry2, model_id="m-b",
                                   version="v2")
                swap["ms"] = round((time.perf_counter() - t0) * 1e3, 3)
            except Exception as e:       # surfaced in the assert below
                swap["error"] = repr(e)

        th = threading.Thread(target=swapper,
                              name="bench_hot_swap", daemon=True)
        th.start()
        report = run_load(router, requests_per_client=reqs,
                          min_len=max(4, seqlen // 8), max_len=seqlen,
                          vocab=vocab, metrics_url=metrics_url,
                          tenants=tenants, model_ids=list(model_ids))
        th.join(timeout=600.0)
        # post-swap warmth: one direct v2 probe per seat — warm means
        # NO compile on the user path (milliseconds, not seconds)
        probe_ms = []
        for eng in engines:
            assert eng.snapshot()["models"]["m-b"] == "v2", \
                eng.snapshot()["models"]
            t0 = time.perf_counter()
            eng.submit(np.arange(1, min(buckets) + 1, dtype=np.int32),
                       model_id="m-b").result(timeout=600.0)
            probe_ms.append(round((time.perf_counter() - t0) * 1e3, 3))
    report.pop("engine")
    trep = report["tenants"]
    pri = trep["t-priority"]
    be = trep["t-best-effort"]
    # zero-loss through the swap: nothing errored; shedding is the
    # WFQ's deliberate overload answer, and it lands on best-effort
    # while priority takes none
    assert swap["error"] is None, swap
    assert report["errors"] == 0, report
    assert be["shed"] > 0, trep
    assert pri["shed"] == 0, trep
    assert pri["p99_ms"] is not None and pri["p99_ms"] <= p99_bound_ms, \
        trep
    assert report.get("tenants_reconciled", True), \
        report.get("tenant_mismatches")
    _report("bert_serving_multitenant_requests_per_sec",
            report["requests_per_sec"], "requests/sec", 0.0,
            seqlen=seqlen, clients=len(tenants),
            requests=report["completed"], dtype=DTYPE, engines=2,
            models=len(model_ids),
            p50_ms=report["p50_ms"], p99_ms=report["p99_ms"],
            tenants={t: {k: row[k] for k in
                         ("class", "completed", "shed", "p50_ms",
                          "p99_ms", "client_tokens")}
                     for t, row in sorted(trep.items())},
            priority_p99_ms=pri["p99_ms"],
            best_effort_shed=be["shed"],
            tenants_reconciled=report.get("tenants_reconciled"),
            swap_ms=swap["ms"], post_swap_probe_ms=probe_ms,
            cost_reconciled=report.get("cost", {}).get("reconciled"),
            slo_compliance=_slo_compliance(report))


def main_decode_serving():
    """Autoregressive decode serving bench (`lm_decode_serving`): a
    paged-KV causal LM behind the continuous-batching
    ``DecodeEngine``, streamed tokens end to end.

    Three phases in one leg:

    1. **Headline (router-fronted):** BENCH_ROUTER_ENGINES decode
       engines behind a ``ServingRouter``; closed-loop clients consume
       token STREAMS. Reports generated tokens/s, client-observed TTFT
       and inter-token p50/p99, peak KV-page occupancy, slot churn
       (joins/leaves), and the server-side reconciliation (requests +
       cost ledger with canary exclusion). Every stream is verified
       byte-identical to its final result.
    2. **Iteration-level vs STATIC batching A/B at equal rows:** the
       same traffic against one engine scheduling Orca-style
       (joins at any iteration boundary) vs classic cohort batching
       (joins only into an empty batch). Iteration-level must WIN on
       tokens/s — with varied generation lengths the static cohort
       idles finished slots until its longest member drains.
    3. **Wire-vs-JSON streamed dispatch A/B:** one engine
       remote-fronted; the same streamed traffic once over partial
       RESULT frames on the binary wire, once over chunked-JSON-lines
       HTTP. The wire must win serialized bytes/request.
    4. **Prefix KV reuse A/B:** shared-system-prompt traffic
       (``prompt_reuse=0.9``) against one engine with the prefix cache
       ON vs OFF, both on the chunked-prefill path. Reuse must WIN
       TTFT p50 AND device-seconds per 1k generated tokens — shared
       full pages skip their prefill chunks entirely.
    5. **Chunked-prefill A/B:** one LONG prompt admitted into a batch
       of running decodes, prefill budget 64 vs 0 (whole-prompt dense
       step). Chunking must WIN the background streams' inter-token
       p99 — the dense arm stalls every running decode for the whole
       long prefill. Long-prompt TTFT is reported for both arms.
    6. **Seeded-sampling failover:** two wire-fronted seats behind a
       router; a seeded (temperature>0) streamed request's carrying
       connection is KILLED mid-stream. The per-request seed rides the
       dispatch payload, so the sibling's re-run resamples the exact
       sequence: the client stream must stay gap-free and
       duplicate-free and match a solo same-seed run byte-identically
       (identical seeds ⇒ identical sequences, any seat).
    """
    _setup_cache()

    import contextlib

    from mxnet_tpu.serving import (DecodeEngine, PagedCausalLM,
                                   ServingRouter)

    tools_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    from serve_loadgen import run_decode_load

    vocab = int(os.environ.get("BENCH_VOCAB", "2048"))
    units = int(os.environ.get("BENCH_DECODE_UNITS", "128"))
    layers = int(os.environ.get("BENCH_DECODE_LAYERS", "2"))
    heads = int(os.environ.get("BENCH_DECODE_HEADS", "4"))
    max_len = int(os.environ.get("BENCH_DECODE_MAXLEN", "256"))
    max_new = int(os.environ.get("BENCH_DECODE_NEW", "24"))
    rows = int(os.environ.get("BENCH_SERVE_ROWS", "8"))
    clients = int(os.environ.get("BENCH_SERVE_CLIENTS", "8"))
    reqs = int(os.environ.get("BENCH_SERVE_REQS", "4"))
    n_engines = int(os.environ.get("BENCH_ROUTER_ENGINES", "2"))
    buckets = (16, 64)

    def make_engine(eid, iteration_level=True, model_wrap=None, **kw):
        lm = PagedCausalLM(vocab=vocab, units=units, layers=layers,
                           heads=heads,
                           max_len=kw.pop("max_len", max_len), seed=0)
        if model_wrap is not None:
            lm = model_wrap(lm)
        return DecodeEngine(lm,
                            prefill_bucket_lens=kw.pop("buckets",
                                                       buckets),
                            max_rows=rows, max_new_tokens=max_new,
                            iteration_level=iteration_level,
                            engine_id=eid, **kw)

    load_kw = dict(n_clients=clients, requests_per_client=reqs,
                   min_prompt=8, max_prompt=max(buckets), vocab=vocab,
                   min_new=max(2, max_new // 4), max_new=max_new)

    # -- phase 1: headline, router-fronted streamed decode ------------------
    with contextlib.ExitStack() as stack:
        engines = [stack.enter_context(make_engine(f"e{i}"))
                   for i in range(n_engines)]
        for eng in engines:
            eng.warmup()
        router = stack.enter_context(ServingRouter(engines=engines))
        metrics_url = router.expose().url("/metrics")
        # one throwaway pass (page caches, thread spin-up), then a
        # fresh measurement window
        run_decode_load(router, n_clients=min(4, clients),
                        requests_per_client=1, min_prompt=8,
                        max_prompt=max(buckets), vocab=vocab,
                        min_new=2, max_new=4)
        for eng in engines:
            eng.reset_stats()
        report = run_decode_load(router, metrics_url=metrics_url,
                                 watch_engines=engines, **load_kw)
    assert report["completed"] == clients * reqs, report
    assert report["stream_mismatches"] == 0, report
    server = report.get("server", {})
    assert server.get("reconciled", True), server
    # tail-latency attribution: every request's server-side critical
    # path rides the reply; the decompositions must sum to >=95% of
    # their own wall, the remainder explicitly unattributed
    from mxnet_tpu.telemetry import attribution as _attribution
    breakdown = report.get("breakdown")
    if _attribution.enabled():
        assert breakdown is not None, \
            "attribution enabled but no request carried a breakdown"
        assert breakdown["missing"] == 0, breakdown
        share = breakdown.get("attributed_share")
        assert share is not None and share >= 0.95, breakdown

    # -- phase 2: iteration-level vs static batching, equal rows ------------
    ab = {}
    for mode, iteration_level in (("iteration", True), ("static", False)):
        with make_engine(f"ab_{mode}",
                         iteration_level=iteration_level) as eng:
            eng.warmup()
            rep = run_decode_load(eng, watch_engines=[eng], **load_kw)
        assert rep["completed"] == clients * reqs, (mode, rep)
        assert rep["stream_mismatches"] == 0, (mode, rep)
        ab[mode] = {"tokens_per_sec": rep["tokens_per_sec"],
                    "ttft_p50_ms": rep["ttft_p50_ms"],
                    "inter_token_p99_ms": rep["inter_token_p99_ms"],
                    "kv_occupancy_peak": rep.get("kv_occupancy_peak"),
                    "slot_utilization":
                        rep["engine"]["decode"]["slot_utilization"]}
    # the acceptance bar: joins at iteration boundaries keep slots
    # busy; the static cohort idles finished rows until its longest
    # member drains
    assert (ab["iteration"]["tokens_per_sec"]
            > ab["static"]["tokens_per_sec"]), ab

    # -- phase 3: wire-vs-JSON streamed dispatch A/B ------------------------
    from mxnet_tpu.serving.metrics import wire_bytes_counter

    byt = wire_bytes_counter()

    def _bytes(transport):
        return sum(byt.labels(side="router", transport=transport,
                              direction=d).value for d in ("in", "out"))

    wire_ab = {}
    with make_engine("w0") as eng:
        srv = eng.expose(port=0)
        url = f"http://{srv.host}:{srv.port}"
        eng.warmup()
        for transport, wire_flag in (("wire", True), ("json", False)):
            router = ServingRouter({"w0": url}, wire=wire_flag,
                                   poll_interval_s=0.2)
            with router:
                if wire_flag:
                    deadline = time.perf_counter() + 15.0
                    while time.perf_counter() < deadline and not all(
                            row.get("transport") == "wire"
                            for row in router.scoreboard().values()):
                        time.sleep(0.1)
                    assert all(row.get("transport") == "wire"
                               for row in router.scoreboard().values()), \
                        router.scoreboard()
                b0 = _bytes(transport)
                rep = run_decode_load(router, n_clients=min(4, clients),
                                      requests_per_client=2,
                                      min_prompt=8,
                                      max_prompt=max(buckets),
                                      vocab=vocab,
                                      min_new=max(2, max_new // 4),
                                      max_new=max_new)
                nbytes = _bytes(transport) - b0
                assert rep["completed"] == min(4, clients) * 2, rep
                assert rep["stream_mismatches"] == 0, rep
                over = router.snapshot()["dispatch_overhead"] \
                    .get(transport) or {}
                wire_ab[transport] = {
                    "tokens_per_sec": rep["tokens_per_sec"],
                    "inter_token_p99_ms": rep["inter_token_p99_ms"],
                    "dispatch_overhead_p50_ms": over.get("p50_ms"),
                    "bytes_per_request": round(
                        nbytes / max(1, rep["completed"]), 1)}
    # the decode-transport bar: what the transport costs ON TOP of the
    # engine's generation wall. (Bytes are reported but not asserted:
    # per-token payloads are tiny dicts either way — the binary wire's
    # decode win is latency/overhead, unlike the encoder leg where raw
    # ndarray framing also wins the byte count.)
    assert (wire_ab["wire"]["dispatch_overhead_p50_ms"]
            < wire_ab["json"]["dispatch_overhead_p50_ms"]), wire_ab

    # -- phase 4: prefix KV reuse A/B (shared system prompts) ---------------
    long_len = int(os.environ.get("BENCH_DECODE_LONG_PROMPT", "192"))

    class _PrefillPaced:
        """Per-token prefill pacer, applied to BOTH arms of the prefix
        and chunking A/Bs: the bench model is small enough that a full
        dense prefill costs about one decode step, so without pacing
        the A/Bs measure dispatch overhead instead of the scheduling
        properties under test (a production-sized prefill runs
        proportional to its padded token count, which is exactly what
        the sleep models)."""

        def __init__(self, m, per_tok_s=0.5e-3):
            self._m, self._c = m, per_tok_s
            self.spec = m.spec

        def prefill(self, caches, ids, *a, **k):
            time.sleep(self._c * int(np.asarray(ids).shape[-1]))
            return self._m.prefill(caches, ids, *a, **k)

        def prefill_chunk(self, caches, ids, *a, **k):
            time.sleep(self._c * int(np.asarray(ids).shape[-1]))
            return self._m.prefill_chunk(caches, ids, *a, **k)

        def decode_step(self, *a, **k):
            return self._m.decode_step(*a, **k)

    # shared prefix = half the long bucket (several FULL pages, spanning
    # whole prefill chunks) — a hit must skip chunk-iterations, not just
    # trim one chunk's tail
    reuse_kw = dict(load_kw, min_prompt=long_len // 2,
                    max_prompt=long_len, prompt_reuse=0.9)
    reuse_ab = {}
    for mode, prefix_on in (("reuse", True), ("cold", False)):
        with make_engine(f"px_{mode}", prefix_cache=prefix_on,
                         model_wrap=_PrefillPaced,
                         max_len=max(max_len, 2 * long_len),
                         buckets=(16, long_len)) as eng:
            eng.warmup()
            murl = eng.expose(port=0).url("/metrics")
            # throwaway pass: spins client threads and (reuse arm)
            # seeds the prefix index with the shared system prompt —
            # the measured window then runs against a warm index
            run_decode_load(eng, n_clients=2, requests_per_client=1,
                            min_prompt=reuse_kw["min_prompt"],
                            max_prompt=reuse_kw["max_prompt"],
                            vocab=vocab, min_new=2, max_new=4,
                            prompt_reuse=1.0)
            rep = run_decode_load(eng, metrics_url=murl,
                                  watch_engines=[eng], **reuse_kw)
        assert rep["completed"] == clients * reqs, (mode, rep)
        assert rep["stream_mismatches"] == 0, (mode, rep)
        dev = rep["cost"]["client_device_s"]
        gen = max(1, rep["generated_tokens"])
        reuse_ab[mode] = {
            "ttft_p50_ms": rep["ttft_p50_ms"],
            "tokens_per_sec": rep["tokens_per_sec"],
            "device_s_per_1k_generated": round(dev * 1e3 / gen, 6),
            "prefix": rep.get("prefix")}
    # the acceptance bars: the reuse arm actually hit the index, and
    # skipping the shared pages' prefill chunks shows up both in
    # first-token latency and in device-seconds per generated token
    assert reuse_ab["reuse"]["prefix"]["hits"] > 0, reuse_ab
    assert (reuse_ab["reuse"]["ttft_p50_ms"]
            < reuse_ab["cold"]["ttft_p50_ms"]), reuse_ab
    assert (reuse_ab["reuse"]["device_s_per_1k_generated"]
            < reuse_ab["cold"]["device_s_per_1k_generated"]), reuse_ab

    # -- phase 5: chunked prefill A/B — long prompt into a running batch ----
    import threading

    from mxnet_tpu.serving.metrics import nearest_rank

    chunk_ab = {}
    for mode, budget in (("chunked", 64), ("dense", 0)):
        with make_engine(f"cp_{mode}", prefill_budget=budget,
                         model_wrap=_PrefillPaced,
                         max_len=max(max_len, 2 * long_len),
                         buckets=(16, long_len)) as eng:
            eng.warmup()
            rs = np.random.RandomState(7)
            long_prompt = rs.randint(1, vocab, long_len) \
                .astype(np.int32)
            gaps, lock = [], threading.Lock()
            n_bg, bg_new = min(4, rows - 1), 32
            first = [0]
            ready = threading.Event()

            def bg(cid):
                rsc = np.random.RandomState(100 + cid)
                toks = rsc.randint(1, vocab, 12).astype(np.int32)
                fut = eng.submit(toks, max_new_tokens=bg_new,
                                 stream=True)
                last = None
                for _ in fut.stream(timeout=600):
                    now = time.perf_counter()
                    with lock:
                        if last is None:
                            first[0] += 1
                            if first[0] == n_bg:
                                ready.set()
                        else:
                            gaps.append((now - last) * 1e3)
                    last = now
                fut.result(timeout=0)

            threads = [threading.Thread(
                target=bg, args=(c,), daemon=True,
                name=f"mxnet_tpu_bench_decode_bg{c}")
                for c in range(n_bg)]
            for t in threads:
                t.start()
            assert ready.wait(timeout=120), "background decode stalled"
            # the long prompt lands in a RUNNING batch: the dense arm
            # prefills it in one iteration-blocking step, the chunked
            # arm interleaves budget-sized slices between decode
            # iterations
            t0 = time.perf_counter()
            lfut = eng.submit(long_prompt, max_new_tokens=4,
                              stream=True)
            ttft = None
            for _ in lfut.stream(timeout=600):
                if ttft is None:
                    ttft = (time.perf_counter() - t0) * 1e3
            lfut.result(timeout=0)
            for t in threads:
                t.join()
            chunk_ab[mode] = {
                "bg_inter_token_p99_ms": round(
                    nearest_rank(sorted(gaps), 99), 3),
                "bg_gaps": len(gaps),
                "long_ttft_ms": round(ttft, 3),
                "prefill_chunks":
                    eng.decode_stats.snapshot()["prefill_chunks"]}
    assert chunk_ab["chunked"]["prefill_chunks"] > 0, chunk_ab
    # the acceptance bar: chunking bounds how long any running decode
    # waits behind the long prefill
    assert (chunk_ab["chunked"]["bg_inter_token_p99_ms"]
            < chunk_ab["dense"]["bg_inter_token_p99_ms"]), chunk_ab

    # -- phase 6: seeded sampling failover — replay is byte-identical -------
    class _Paced:
        """Decode-step pacer: slow generation enough that the kill
        lands mid-stream (same shim as the serving tests use)."""

        def __init__(self, m, delay_s=0.02):
            self._m, self._d = m, delay_s
            self.spec = m.spec

        def prefill(self, *a, **k):
            return self._m.prefill(*a, **k)

        def prefill_chunk(self, *a, **k):
            return self._m.prefill_chunk(*a, **k)

        def decode_step(self, *a, **k):
            time.sleep(self._d)
            return self._m.decode_step(*a, **k)

    sample = dict(temperature=0.8, top_k=40, top_p=0.95)
    seed_prompt = list(range(1, 9))
    s_engines = [make_engine(f"sd{i}", model_wrap=_Paced)
                 for i in range(2)]
    with s_engines[0], s_engines[1]:
        urls = {}
        for eng in s_engines:
            eng.warmup()
            srv = eng.expose(port=0)
            urls[eng.engine_id] = f"http://{srv.host}:{srv.port}"
        # identical seeds ⇒ identical sequences, on EITHER seat: the
        # sampling key is a pure function of (seed, position)
        solo = s_engines[0].infer(seed_prompt, max_new_tokens=12,
                                  seed=1234, **sample).tolist()
        twin = s_engines[1].infer(seed_prompt, max_new_tokens=12,
                                  seed=1234, **sample).tolist()
        assert solo == twin, (solo, twin)
        other = s_engines[0].infer(seed_prompt, max_new_tokens=12,
                                   seed=4321, **sample).tolist()
        with ServingRouter(urls, wire=True,
                           poll_interval_s=0.1) as s_router:
            deadline = time.perf_counter() + 30.0
            while time.perf_counter() < deadline and not all(
                    row.get("transport") == "wire"
                    for row in s_router.scoreboard().values()):
                time.sleep(0.1)
            fut = s_router.submit(seed_prompt, max_new_tokens=12,
                                  stream=True, seed=1234, **sample)
            seen, killed = [], [False]
            for part in fut.stream(timeout=120):
                seen.append(part)
                if len(seen) == 3 and not killed[0]:
                    killed[0] = True
                    busy = {eid for eid, row
                            in s_router.scoreboard().items()
                            if row.get("outstanding")}
                    for eng in s_engines:
                        if eng.engine_id in busy:
                            eng._wire.kill_connections()
            out = fut.result(timeout=0).tolist()
        assert killed[0]
        # the stream survived the mid-flight kill gap-free and
        # duplicate-free, and the failover re-run RESAMPLED the exact
        # sequence — the seed, not the seat, owns the randomness
        idxs = [p["index"] for p in seen]
        assert idxs == list(range(len(seen))), idxs
        assert [p["token"] for p in seen] == out, (seen, out)
        assert out == solo, (out, solo)
        assert other != solo, "distinct seeds produced equal sequences"
        failed_over = sum(e.stats.count("submitted")
                          for e in s_engines) >= 2
    seeded = {"stream_mismatches": 0 if [p["token"] for p in seen]
              == out else 1,
              "replayed_matches_solo": out == solo,
              "distinct_seed_differs": other != solo,
              "failover_reruns": failed_over}

    cost = report.get("cost", {})
    _report("lm_decode_serving_tokens_per_sec",
            report["tokens_per_sec"], "tokens/sec", 0.0,
            clients=clients, engines=n_engines, batch=rows,
            requests=report["completed"],
            generated_tokens=report["generated_tokens"], dtype=DTYPE,
            p50_ms=report["p50_ms"], p99_ms=report["p99_ms"],
            ttft_p50_ms=report["ttft_p50_ms"],
            ttft_p95_ms=report["ttft_p95_ms"],
            inter_token_p50_ms=report["inter_token_p50_ms"],
            inter_token_p99_ms=report["inter_token_p99_ms"],
            kv_occupancy=report.get("kv_occupancy_peak"),
            churn=report.get("churn"),
            per_engine=report.get("per_engine"),
            stream_mismatches=report["stream_mismatches"],
            static_tokens_per_sec=ab["static"]["tokens_per_sec"],
            iteration_speedup=round(
                ab["iteration"]["tokens_per_sec"]
                / max(1e-9, ab["static"]["tokens_per_sec"]), 3),
            decode_ab=ab, wire=wire_ab["wire"], json=wire_ab["json"],
            prefix_reuse_ab=reuse_ab,
            prefix_reuse_ttft_speedup=round(
                reuse_ab["cold"]["ttft_p50_ms"]
                / max(1e-9, reuse_ab["reuse"]["ttft_p50_ms"]), 3),
            chunked_prefill_ab=chunk_ab,
            chunked_prefill_p99_win=round(
                chunk_ab["dense"]["bg_inter_token_p99_ms"]
                / max(1e-9,
                      chunk_ab["chunked"]["bg_inter_token_p99_ms"]),
                3),
            seeded=seeded,
            attributed_share=(breakdown or {}).get("attributed_share"),
            unattributed_ms=(breakdown or {}).get("unattributed_ms"),
            stage_breakdown=breakdown,
            telemetry_reconciled=server.get("reconciled"),
            cost_reconciled=cost.get("reconciled"),
            device_s_per_1k_tokens=cost.get("device_s_per_1k_tokens"),
            slo_compliance=_slo_compliance(report))


def main_serving_restart():
    """Rolling-restart serving drill (the warm-restart acceptance
    leg): BENCH_ROUTER_ENGINES (default 2) engines behind a router
    under closed-loop load; mid-load one engine is KILLED (abort) —
    failover must requeue its in-flight work to siblings with zero
    request loss — and replaced twice: first COLD (fresh model, no
    warmup: the first request it serves pays trace+compile), then
    killed again and replaced WARM (fresh model, ``warmup`` replaying
    the router's fleet-union manifest against the persistent compile
    cache BEFORE the seat admits traffic). Reports the loadgen's
    observed time-to-first-token after each restart, the failover
    count, and asserts every submitted request completed."""
    _setup_cache()

    import contextlib
    import threading

    from mxnet_tpu.serving import ServingRouter

    # smaller closed-loop than the router leg: the number under test
    # is the restart/TTFT story, not sustained throughput
    cfg, make_engine = _router_fleet_setup(clients_default=8,
                                           reqs_default=24)
    from serve_loadgen import run_load

    n_engines, seqlen, vocab, clients, reqs = (
        cfg["n_engines"], cfg["seqlen"], cfg["vocab"], cfg["clients"],
        cfg["reqs"])

    total = clients * reqs
    victim = f"e{n_engines - 1}"
    drill = {}
    drill_err = []
    npr = np.random.RandomState(7)
    probe_tokens = npr.randint(1, vocab,
                               max(4, seqlen // 2)).astype(np.int32)

    def probe_ttft(eng):
        """Time-to-first-token of a just-(re)started engine: one
        direct request, wall-clocked — cold pays trace+compile, warm
        (manifest replayed) pays only the forward."""
        t0 = time.perf_counter()
        eng.submit(probe_tokens).result(timeout=600.0)
        return round((time.perf_counter() - t0) * 1e3, 3)

    with contextlib.ExitStack() as stack:
        engines = [stack.enter_context(make_engine(i))
                   for i in range(n_engines)]
        # replacement incarnations built UP FRONT (fresh params, never
        # traced) so the swap window under load is the restart itself,
        # not python model construction
        cold_eng = make_engine(n_engines - 1)
        warm_eng = make_engine(n_engines - 1)
        stack.callback(cold_eng.stop)
        stack.callback(warm_eng.stop)
        router = stack.enter_context(
            ServingRouter(engines=engines, poll_interval_s=0.2))
        metrics_url = router.expose().url("/metrics")
        for eng in engines:
            eng.warmup()

        def wait_completed(n, timeout_s=600.0):
            deadline = time.monotonic() + timeout_s
            while router.count("completed") < n \
                    and time.monotonic() < deadline:
                time.sleep(0.02)

        def controller():
            try:
                # phase 1: steady state reached -> kill + COLD restart
                # (no warmup: its first request pays trace+compile)
                wait_completed(max(1, total // 6))
                engines[-1].stop(drain=False)
                router.remove_engine(victim)
                cold_eng.start()
                drill["ttft_cold_ms"] = probe_ttft(cold_eng)
                router.add_engine(victim, cold_eng)
                # phase 2: kill the replacement too; WARM restart
                # replays the router's fleet manifest against the
                # persistent cache BEFORE admitting traffic
                wait_completed(max(2, total // 2))
                cold_eng.stop(drain=False)
                router.remove_engine(victim)
                warm_eng.start()
                warm_eng.warmup(manifest=router.warmup_manifest())
                drill["ttft_warm_ms"] = probe_ttft(warm_eng)
                router.add_engine(victim, warm_eng)
            except Exception as e:       # surface drill bugs loudly:
                drill_err.append(e)      # the leg must not hang silent

        ctl = threading.Thread(target=controller, daemon=True,
                               name="bench_restart_controller")
        ctl.start()
        report = run_load(router, n_clients=clients,
                          requests_per_client=reqs,
                          min_len=max(4, seqlen // 8), max_len=seqlen,
                          vocab=vocab, metrics_url=metrics_url)
        ctl.join(timeout=600.0)

    assert not drill_err, drill_err
    report.pop("engine")
    # ZERO LOST REQUESTS through two engine kills: every submitted
    # request completed (failover requeued the victim's work)
    assert report["completed"] == total, report
    assert report["errors"] == 0, report
    server = report.get("server", {})
    assert server.get("reconciled", True), server
    restarts = report.get("restarts") or []
    ttft_cold = drill.get("ttft_cold_ms")
    ttft_warm = drill.get("ttft_warm_ms")
    _report("bert_serving_restart_ttft_ms",
            ttft_warm if ttft_warm is not None else -1.0, "ms", 0.0,
            seqlen=seqlen, clients=clients, engines=n_engines,
            requests=report["completed"], dtype=DTYPE,
            ttft_cold_ms=ttft_cold, ttft_warm_ms=ttft_warm,
            restarts=restarts, failover=report["failovers"],
            lost=total - report["completed"],
            p50_ms=report["p50_ms"], p99_ms=report["p99_ms"],
            slo_compliance=_slo_compliance(report),
            telemetry_reconciled=server.get("reconciled"))


def main_serving_chaos():
    """Self-healing chaos drill leg (the ROADMAP 3a–c acceptance):
    BENCH_ROUTER_ENGINES (min 3) BERT engines behind TWO active/active
    routers under closed-loop load. The scripted faults and their
    asserted recoveries: an induced hot-spot sheds routing weight off
    the slow seat (per-seat share measurably moves), a seat kill is
    replaced manifest-warm by the autoscaler (TTFT-probed before it
    admits traffic), and a router kill hands every in-flight request
    to the surviving peer (journal adoption + client cid resubmit).
    Asserts SLO re-convergence, one correlated incident per induced
    fault, and ZERO lost requests. The suite entry pins the
    drill-speed judging clocks (window scale, eval period, latency
    objective) in its env."""
    _setup_cache()

    cfg, make_engine = _router_fleet_setup(clients_default=6,
                                           reqs_default=8)
    from serve_loadgen import run_chaos_drill

    n_engines = max(3, cfg["n_engines"])
    hot_ms = float(os.environ.get("BENCH_CHAOS_HOT_MS", "1500"))
    t0 = time.perf_counter()
    report = run_chaos_drill(
        make_engine, n_engines=n_engines, n_clients=cfg["clients"],
        hot_ms=hot_ms, phase_timeout_s=180.0, vocab=cfg["vocab"],
        min_len=max(4, cfg["seqlen"] // 8), max_len=cfg["seqlen"])
    wall = time.perf_counter() - t0
    assert report["lost"] == 0, report
    ph = report["phases"]
    _report("bert_serving_chaos_requests",
            float(report["completed"]), "requests", 0.0,
            seqlen=cfg["seqlen"], clients=cfg["clients"],
            engines=n_engines, dtype=DTYPE,
            lost=report["lost"],
            weight_min=ph["hotspot"]["weight_min"],
            hot_share=ph["hotspot"]["hot_share"],
            ttft_warm_ms=ph["seat_kill"]["ttft_ms"],
            manifest_shapes=ph["seat_kill"]["manifest_shapes"],
            adopted=ph["router_kill"]["adopted"],
            incidents=len(report["incidents"]),
            client_failovers=report["client_failovers"],
            drill_wall_s=round(wall, 1))


def main_lstm():
    """LSTM LM training step, tokens/sec/chip (BASELINE #4).

    The classic MXNet word-LM config (example/rnn/word_lm on
    WikiText-2): embed 650 → 2×LSTM(650) → tied-size decoder over a
    33k vocab; fused scan RNN op (cuDNN-RNN analog). No reference
    per-chip number (mount empty) — vs_baseline 0.0.
    """
    import jax
    import jax.numpy as jnp

    _setup_cache()

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.block import functionalize

    # batch 1024 measured fastest after the round-4 logits fixes
    # (sweep: 128→364k, 256→414k, 512→473k, 1024→520k, 2048→526k
    # tok/s — the 650-wide cell matmuls + vocab decoder fill the MXU
    # with batch; reference cuDNN word_lm used 32-80, but throughput
    # benches batch up the same way)
    batch = int(os.environ.get("BENCH_BATCH", "1024"))
    seqlen = int(os.environ.get("BENCH_SEQLEN", "35"))
    vocab, emb, hid, layers = 33278, 650, 650, 2
    ctx = mx.current_context()

    class WordLM(mx.gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = mx.gluon.nn.Embedding(vocab, emb)
                self.rnn = mx.gluon.rnn.LSTM(hid, num_layers=layers,
                                             layout="NTC")
                self.decoder = mx.gluon.nn.Dense(vocab, flatten=False)

        def hybrid_forward(self, F, x):
            seq = self.rnn(self.embed(x))
            # flatten BEFORE the 33k-vocab decoder: reshaping the small
            # (N, T, H) tensor is free, while reshaping (N, T, V) after
            # costs two 300 MB tile-repack copies (T=35 pads to 40
            # sublanes in the tiled layout) — measured 3 ms of a
            # 14.4 ms step
            return self.decoder(seq.reshape((-1, seq.shape[-1])))

    net = WordLM()
    net.initialize(init=mx.initializer.Xavier(), ctx=ctx)
    if DTYPE != "float32":
        net.cast(DTYPE)
    warm = mx.nd.zeros((2, seqlen), ctx=ctx, dtype="int32")
    with mx.autograd.predict_mode():
        net(warm)
    fn, params = functionalize(net, training=True, ctx=ctx)

    def loss_fn(params, rng, ids, labels):
        # keep logits in the model dtype (bf16): the CE kernel upcasts
        # per-tile in VMEM and emits bf16 dlogits — the f32
        # materialization of the (N*T, 33k) logits was measured at
        # ~6 ms of a 17.5 ms step (reshape/convert data movement)
        logits = fn(params, rng, ids)
        from mxnet_tpu.ops import pallas as _pallas
        flat = logits.reshape(-1, vocab)
        if _pallas.pallas_enabled():
            loss = _pallas.softmax_xent_fused(flat, labels.reshape(-1))
        else:
            logp = jax.nn.log_softmax(flat.astype(jnp.float32), axis=-1)
            loss = -jnp.take_along_axis(
                logp, labels.reshape(-1)[:, None], axis=-1)[:, 0]
        return loss.mean()

    step = _make_momentum_sgd(loss_fn, 1.0)
    moms = _zeros_moms(params)
    rng = jax.random.PRNGKey(0)
    npr = np.random.RandomState(0)
    ids = jnp.asarray(npr.randint(0, vocab, (batch, seqlen)), jnp.int32)
    labels = jnp.asarray(npr.randint(0, vocab, (batch, seqlen)), jnp.int32)

    flops, nbytes = _step_cost(step, params, moms, rng, ids, labels)
    dt = _time_steps(step, params, moms, rng, ids, labels,
                     flops_per_step=flops * CHAIN,
                     bytes_per_step=nbytes * CHAIN)

    tok_per_sec = batch * seqlen * STEPS * CHAIN / dt
    _report("lstm_lm_train_tokens_per_sec_per_chip", tok_per_sec,
            "tokens/sec/chip", 0.0,
            flops_per_step=flops, sec_per_step=dt / STEPS / CHAIN,
            bytes_per_step=nbytes, batch=batch, seqlen=seqlen,
            dtype=DTYPE, chain=CHAIN)


def main_widedeep():
    """Wide&Deep CTR training, examples/sec/chip (BASELINE #5).

    Criteo-shaped synthetic: 26 categorical fields + multi-hot wide
    features + 13 continuous. The sparse showcase (reference
    example/sparse/wide_deep); embedding gathers + fused MLP.
    """
    import jax
    import jax.numpy as jnp

    _setup_cache()

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.block import functionalize
    from mxnet_tpu.gluon.model_zoo import wide_deep

    # b8192 default (r4 sweep: 2048→266k, 8192→443k, 32768→537k,
    # 131072→556k ex/s — the gather-bound step amortizes fixed cost
    # with batch; large-batch CTR training is standard industrially)
    batch = int(os.environ.get("BENCH_BATCH", "8192"))
    wide_dim, n_fields, field_dim = 100000, 26, 10000
    n_wide, n_cont = 50, 13
    ctx = mx.current_context()

    net = wide_deep(wide_dim=wide_dim, num_fields=n_fields,
                    field_dim=field_dim, embed_dim=16,
                    fused_fields=os.environ.get("BENCH_WD_FUSED", "1") == "1")
    net.initialize(init=mx.initializer.Xavier(), ctx=ctx)

    npr = np.random.RandomState(0)
    warm = (mx.nd.zeros((2, n_wide), ctx=ctx, dtype="int32"),
            mx.nd.zeros((2, n_fields), ctx=ctx, dtype="int32"),
            mx.nd.zeros((2, n_cont), ctx=ctx))
    with mx.autograd.predict_mode():
        net(*warm)
    fn, params = functionalize(net, training=True, ctx=ctx)

    def loss_fn(params, rng, wx, cx, ct, y):
        logits = fn(params, rng, wx, cx, ct).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()

    step = _make_momentum_sgd(loss_fn, 0.05)
    moms = _zeros_moms(params)
    rng = jax.random.PRNGKey(0)
    wx = jnp.asarray(npr.randint(0, wide_dim, (batch, n_wide)), jnp.int32)
    cx = jnp.asarray(npr.randint(0, field_dim, (batch, n_fields)), jnp.int32)
    ct = jnp.asarray(npr.rand(batch, n_cont), jnp.float32)
    y = jnp.asarray(npr.randint(0, 2, batch), jnp.int32)

    flops, nbytes = _step_cost(step, params, moms, rng, wx, cx, ct, y)
    dt = _time_steps(step, params, moms, rng, wx, cx, ct, y,
                     flops_per_step=flops * CHAIN,
                     bytes_per_step=nbytes * CHAIN)

    ex_per_sec = batch * STEPS * CHAIN / dt
    _report("wide_deep_train_examples_per_sec_per_chip", ex_per_sec,
            "examples/sec/chip", 0.0,
            flops_per_step=flops, sec_per_step=dt / STEPS / CHAIN,
            bytes_per_step=nbytes, batch=batch, dtype=DTYPE,
            chain=CHAIN)


# The five BASELINE acceptance configs (+ long-seq/padded/packed BERT
# and predict-mode inference), each run in its OWN subprocess: an axon
# timing glitch after a slow fresh compile poisons a whole process, so
# per-config isolation keeps one bad compile from corrupting the rest
# of the suite.
#
# ORDER IS PRIORITY (r4 lesson: the driver's wall-clock budget truncated
# the suite and the ResNet-50 TRAIN headline — scheduled last — was lost
# from the round's record). The headline runs FIRST so it is always
# captured; its JSON line is RE-EMITTED as the very last stdout line so
# the driver's parsed-last-line headline stays the north-star metric,
# preceded by a bench_suite_summary line carrying EVERY config's result.
# Long-tail extras run with a single timing window (BENCH_WINDOWS=1).
_SUITE = (
    # headline; BENCH_XPROF sources its hbm_frac from hardware counters
    # (~15 s) so the north-star line is measured, not cost-modeled
    ("resnet50_train", "resnet50", {"BENCH_XPROF": "1"}),
    ("bert_seq128", "bert", {}),
    ("lstm", "lstm", {}),
    # chain=16 measured fastest for the gather-bound step (625.7k vs
    # 618.1k ex/s at chain=10; r5 A/B)
    ("widedeep", "widedeep", {"BENCH_CHAIN": "16"}),
    ("resnet50_infer", "resnet50", {"BENCH_INFER": "1"}),
    ("bert_seq512", "bert", {"BENCH_SEQLEN": "512", "BENCH_BATCH": "64",
                             "BENCH_WINDOWS": "1"}),
    ("bert_seq512_padded", "bert",
     {"BENCH_SEQLEN": "512", "BENCH_BATCH": "64", "BENCH_PADDED": "1",
      "BENCH_WINDOWS": "1"}),
    # packed leg: same U[S/2, S] length distribution as the padded leg,
    # first-fit into 2048-slot rows; 256x256 flash tiles so the
    # segment-range block skip actually drops cross-sequence tiles
    # (at the default 512x2048 tiling every pair shares a segment)
    ("bert_seq512_packed", "bert",
     {"BENCH_SEQLEN": "512", "BENCH_BATCH": "64", "BENCH_PACKED": "1",
      "BENCH_WINDOWS": "1", "MXNET_TPU_FLASH_BLOCK_Q": "256",
      "MXNET_TPU_FLASH_BLOCK_K": "256"}),
    # packed CAUSAL LM (ROADMAP follow-up): the kernel's causal segment
    # path under a real training step; same tiling/length mix as the
    # packed BERT leg so the two numbers compare directly
    ("lm_seq512_packed_causal", "causal_lm",
     {"BENCH_SEQLEN": "512", "BENCH_BATCH": "64", "BENCH_WINDOWS": "1",
      "MXNET_TPU_FLASH_BLOCK_Q": "256", "MXNET_TPU_FLASH_BLOCK_K": "256"}),
    # closed-loop packed continuous-batching serving (mxnet_tpu/serving)
    ("bert_serving", "serving", {"BENCH_WINDOWS": "1"}),
    # 2 engines behind the front-door router: req/s, per-engine share,
    # failover count, aggregated-/metrics reconciliation
    ("bert_serving_router", "serving_router", {"BENCH_WINDOWS": "1"}),
    # multi-tenant multi-model: 2 models × 3 WFQ tenant classes driven
    # to overload behind the router — priority p99 holds while
    # best-effort sheds, per-tenant bills reconcile, and a mid-load
    # hot-swap loses nothing and lands warm
    ("bert_serving_multitenant", "serving_multitenant",
     {"BENCH_WINDOWS": "1"}),
    # autoregressive DECODE serving: paged-KV causal LM, iteration-
    # level continuous batching, streamed tokens router-fronted —
    # tokens/s + TTFT + inter-token p50/p99 + KV occupancy + churn,
    # with the iteration-vs-static and wire-vs-JSON A/Bs inline
    ("lm_decode_serving", "decode_serving", {"BENCH_WINDOWS": "1"}),
    # rolling-restart drill: kill an engine mid-load, cold vs warm
    # (manifest-replay) time-to-first-token, zero-loss failover
    ("bert_serving_restart", "serving_restart", {"BENCH_WINDOWS": "1"}),
    # self-healing chaos drill: hot-spot weight shed + seat-kill
    # autoscaler replacement + two-router kill/adopt, zero lost
    # requests; env pins the drill-speed judging clocks
    ("bert_serving_chaos", "serving_chaos",
     {"BENCH_WINDOWS": "1", "BENCH_SERVE_CLIENTS": "6",
      "MXNET_TPU_SLO_WINDOW_SCALE": "0.01",
      "MXNET_TPU_SLO_EVAL_S": "0.2",
      "MXNET_TPU_SLO_LATENCY_MS": "700",
      "MXNET_TPU_CANARY_INTERVAL_S": "0.5"}),
    # seq2048 BEFORE seq1024 (it was the r5 rc=124 casualty) and with a
    # shorter chain/step budget: chain=4 compiles a 4-step scan instead
    # of 10 — the 420 s per-config cap was lost to trace+compile time,
    # not to the measurement itself. A DRY PRE-COMPILE leg runs first:
    # it only lowers+compiles (no execution), priming the persistent
    # cache in its own 420 s window so the measured leg starts warm
    # instead of burning its cap (the rc=124 mode) on a remote compile.
    ("bert_seq2048_precompile", "bert",
     {"BENCH_SEQLEN": "2048", "BENCH_BATCH": "8", "BENCH_WINDOWS": "1",
      "BENCH_CHAIN": "4", "BENCH_STEPS": "10", "BENCH_PRECOMPILE": "1"}),
    ("bert_seq2048", "bert",
     {"BENCH_SEQLEN": "2048", "BENCH_BATCH": "8", "BENCH_WINDOWS": "1",
      "BENCH_CHAIN": "4", "BENCH_STEPS": "10"}),
    ("bert_seq1024", "bert", {"BENCH_SEQLEN": "1024", "BENCH_BATCH": "32",
                              "BENCH_WINDOWS": "1"}),
    # LAST: the e2e input-pipeline diagnostic is environment-bound on
    # this tunnel host (BASELINE.md) — real model numbers outrank it
    # under the budget. 640 images (5 batches) keep the leg ≤60 s incl.
    # the 1-core JPEG generation, so the budget guard no longer drops it.
    ("resnet50_pipeline", "resnet50",
     {"BENCH_DATA": "pipeline", "BENCH_WINDOWS": "1",
      "BENCH_PIPELINE_IMAGES": "640"}),
)


# summary keys worth carrying per config (compact: the driver's captured
# tail must hold the WHOLE suite in one line)
_SUMMARY_KEYS = ("metric", "value", "unit", "mfu", "hbm_frac", "hbm_est",
                 "valid_frac", "valid_tokens_per_sec", "packing_efficiency",
                 "seqlen", "batch", "failed", "causal", "clients",
                 "p50_ms", "p99_ms", "telemetry_reconciled", "telemetry",
                 "slowest_traces", "per_engine", "failover", "engines_up",
                 "ttft_cold_ms", "ttft_warm_ms", "lost", "resources",
                 "profile_top", "cost_reconciled",
                 "device_s_per_1k_tokens", "slo_compliance",
                 "weight_min", "hot_share", "manifest_shapes",
                 "adopted", "incidents", "ttft_p50_ms",
                 "inter_token_p50_ms", "inter_token_p99_ms",
                 "kv_occupancy", "churn", "stream_mismatches",
                 "static_tokens_per_sec", "iteration_speedup",
                 "tenants", "priority_p99_ms", "best_effort_shed",
                 "tenants_reconciled", "swap_ms", "post_swap_probe_ms")


def _compact(rec):
    return {k: rec[k] for k in _SUMMARY_KEYS if k in rec}


def main_suite():
    """Default `python bench.py`: emit ALL acceptance configs as JSON
    lines (VERDICT r2 #8 — BENCH_rN.json should record the whole suite,
    not just ResNet). Wall-clock budget guard (BENCH_BUDGET_S, default
    1200 s): when the budget is spent, remaining configs are SKIPPED —
    recorded in the summary (no silent truncation) — instead of the
    driver's timeout killing the process mid-config. A config failure
    prints to stderr, records an explicit {"value": null, "failed":
    true} row, and the suite continues; exit is nonzero only if the
    headline config failed.

    The LAST TWO stdout lines are the round's record (VERDICT r5 #1a):
    a `bench_suite_summary` line carrying every headline metric keyed
    by config name, then the headline config's own line re-emitted —
    or, if the headline failed twice, an explicit failed-headline
    record so the driver can never mistake a stray line for the
    north-star number."""
    import subprocess

    # 1200 s + the last config's 420 s cap + headline slack keeps the
    # WHOLE process under ~30 min — the r4 driver cutoff class — even
    # cold-cache; priority ordering guarantees the core five configs
    budget = float(os.environ.get("BENCH_BUDGET_S", "1200"))
    t_start = time.perf_counter()
    headline_rc = 1
    headline_line = None
    results = {}
    skipped = []

    def launch(env, timeout):
        try:
            r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               env=env, capture_output=True, text=True,
                               timeout=max(timeout, 60.0))
        except subprocess.TimeoutExpired as e:
            out = e.stdout or ""
            if isinstance(out, bytes):
                out = out.decode(errors="replace")
            err = e.stderr or ""
            if isinstance(err, bytes):
                err = err.decode(errors="replace")
            sys.stderr.write(err)  # the WHY of the timeout lives here
            if out and not out.endswith("\n"):
                out += "\n"  # a truncated JSON fragment must not glue
                # onto the next line (the driver parses the LAST line)
            sys.stdout.write(out)
            sys.stdout.flush()
            return 124, out
        sys.stderr.write(r.stderr)
        sys.stdout.write(r.stdout)
        sys.stdout.flush()
        return r.returncode, r.stdout

    for i, (name, model, extra) in enumerate(_SUITE):
        remaining = budget - (time.perf_counter() - t_start)
        if i > 0 and remaining < 90.0:
            skipped.append(name)
            continue
        env = dict(os.environ, BENCH_MODEL=model, **extra)
        # headline gets a generous slice (fresh-cache compiles are
        # minutes-slow); each extra is capped at 7 min so one slow
        # config cannot starve everything behind it of the remaining
        # budget (r5 review: seq2048 running long would kill the legs
        # after it EVERY run, not just under pressure)
        r, out = launch(env, min(remaining, 420.0) if i
                        else max(remaining, 600.0))
        if r != 0 and (budget - (time.perf_counter() - t_start)) > 90.0:
            # one retry: axon remote-compiles fail transiently
            # ("response body closed" mid-compile) and the partial
            # compile IS cached, so the retry is usually warm+quick
            print(f"# bench config {name} failed rc={r}; retrying once",
                  file=sys.stderr)
            left = budget - (time.perf_counter() - t_start)
            r, out = launch(env, min(left, 420.0) if i else left)
        if r != 0:
            print(f"# bench config {name} failed rc={r}", file=sys.stderr)
        metric_line = None
        for line in out.splitlines():
            if line.startswith('{"metric"'):
                metric_line = line
        if metric_line is not None and r == 0:
            try:
                results[name] = _compact(json.loads(metric_line))
            except ValueError:
                results[name] = {"value": None, "failed": True}
        else:
            # explicit null record — a failed config must never leave
            # its slot to be filled by whatever printed last
            results[name] = {"value": None, "failed": True, "rc": r}
        if i == 0:
            headline_rc = r
            headline_line = metric_line if r == 0 else None

    print(json.dumps({"metric": "bench_suite_summary",
                      "value": len(results), "unit": "configs",
                      "vs_baseline": 0.0, "results": results,
                      "skipped": skipped}))
    if headline_line:
        # duplicate of the first config's line, by design: the driver
        # parses the LAST JSON line as the round's headline
        print(headline_line)
    else:
        # headline failed twice: an EXPLICIT failed record as the final
        # line (ADVICE r5 / bench.py:974) — never let a stray line
        # become the parsed headline
        print(json.dumps({
            "metric": "resnet50_train_images_per_sec_per_chip",
            "value": None, "unit": "images/sec/chip", "vs_baseline": 0.0,
            "failed": True}))
    sys.stdout.flush()
    raise SystemExit(headline_rc)


def _dispatch():
    _model = os.environ.get("BENCH_MODEL")
    if _model is not None:
        # every measured leg runs under the always-on sampling
        # profiler + resource sweep (MXNET_TPU_PROF=0 opts out): the
        # per-leg record then carries RSS/device-mem watermarks and
        # the top host-time frames
        try:
            from mxnet_tpu.telemetry import profiling as _profiling
            _profiling.ensure_started()
        except Exception:
            pass
    if _model is None:
        main_suite()
    elif _model == "bert":
        main_bert()
    elif _model == "causal_lm":
        main_causal_lm()
    elif _model == "decode_serving":
        main_decode_serving()
    elif _model == "serving":
        main_serving()
    elif _model == "serving_router":
        main_serving_router()
    elif _model == "serving_multitenant":
        main_serving_multitenant()
    elif _model == "serving_restart":
        main_serving_restart()
    elif _model == "serving_chaos":
        main_serving_chaos()
    elif _model == "lstm":
        main_lstm()
    elif _model == "widedeep":
        main_widedeep()
    else:
        main()


if __name__ == "__main__":
    try:
        _dispatch()
    except RuntimeError as e:
        if "timing glitch" not in str(e) \
                or os.environ.get("BENCH_NO_REEXEC") == "1":
            raise
        # the axon glitch poisons THIS process after a slow fresh
        # compile, but that compile is now in the persistent cache — a
        # fresh process measures sanely. Re-exec exactly once so the
        # driver's single `python bench.py` still yields a real number.
        import subprocess
        print(f"# {e}; re-running in a fresh process", file=sys.stderr)
        env = dict(os.environ, BENCH_NO_REEXEC="1")
        raise SystemExit(subprocess.call(
            [sys.executable, os.path.abspath(__file__)], env=env))
