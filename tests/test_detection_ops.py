"""SSD detection op family: MultiBoxPrior/Target/Detection, box_nms,
box_iou, bipartite_matching.

Goldens are hand-computed small cases mirroring the reference's
tests/python/unittest/test_contrib_operator.py strategy
(src/operator/contrib/multibox_*.cc + bounding_box.cc semantics).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def _nd(x, dtype="float32"):
    return nd.array(onp.asarray(x, dtype))


def test_box_iou_golden():
    a = _nd([[0.0, 0.0, 2.0, 2.0], [1.0, 1.0, 3.0, 3.0]])
    b = _nd([[0.0, 0.0, 2.0, 2.0], [10.0, 10.0, 11.0, 11.0]])
    iou = nd.contrib.box_iou(a, b).asnumpy()
    assert iou.shape == (2, 2)
    assert iou[0, 0] == pytest.approx(1.0, abs=1e-6)
    # boxes [0,0,2,2] vs [1,1,3,3]: inter 1, union 7
    assert iou[1, 0] == pytest.approx(1.0 / 7.0, abs=1e-6)
    assert iou[0, 1] == pytest.approx(0.0, abs=1e-6)


def test_box_iou_center_format():
    # same boxes expressed center-form must give identical IoU
    a_corner = onp.array([[0.0, 0.0, 2.0, 2.0]], "f")
    a_center = onp.array([[1.0, 1.0, 2.0, 2.0]], "f")
    i1 = nd.contrib.box_iou(_nd(a_corner), _nd(a_corner)).asnumpy()
    i2 = nd.contrib.box_iou(_nd(a_center), _nd(a_center),
                            format="center").asnumpy()
    assert i1 == pytest.approx(i2)


def test_bipartite_matching():
    d = _nd([[0.9, 0.1], [0.8, 0.7], [0.2, 0.3]])
    rows, cols = nd.contrib.bipartite_matching(d)
    rows, cols = rows.asnumpy(), cols.asnumpy()
    # greedy: (0,0)=0.9 first, then (1,1)=0.7
    assert rows.tolist() == [0.0, 1.0, -1.0]
    assert cols.tolist() == [0.0, 1.0]
    # threshold prunes the weaker pair
    rows2, _ = nd.contrib.bipartite_matching(d, threshold=0.8)
    assert rows2.asnumpy().tolist() == [0.0, -1.0, -1.0]


def test_box_nms_suppression_and_order():
    # three boxes: A and B overlap heavily (B weaker), C is separate
    rows = _nd([[0.0, 0.9, 0.0, 0.0, 1.0, 1.0],     # id, score, x1 y1 x2 y2
                [0.0, 0.8, 0.05, 0.05, 1.05, 1.05],
                [0.0, 0.7, 5.0, 5.0, 6.0, 6.0]])
    out = nd.contrib.box_nms(rows, overlap_thresh=0.5).asnumpy()
    # survivor rows sorted by score; suppressed row is all -1
    assert out[0, 1] == pytest.approx(0.9)
    assert out[1, 1] == pytest.approx(0.7)
    assert (out[2] == -1).all()
    # looser threshold keeps all three
    out2 = nd.contrib.box_nms(rows, overlap_thresh=0.99).asnumpy()
    assert (out2[:, 1] > 0).all()


def test_box_nms_class_aware_vs_force():
    # same overlap, different class ids: survives unless force_suppress
    rows = _nd([[0.0, 0.9, 0.0, 0.0, 1.0, 1.0],
                [1.0, 0.8, 0.0, 0.0, 1.0, 1.0]])
    keep = nd.contrib.box_nms(rows, overlap_thresh=0.5, id_index=0).asnumpy()
    assert (keep[:, 1] > 0).all()
    sup = nd.contrib.box_nms(rows, overlap_thresh=0.5, id_index=0,
                             force_suppress=True).asnumpy()
    assert (sup[1] == -1).all()


def test_box_nms_batch_and_topk():
    rs = onp.random.RandomState(3)
    batch = rs.rand(2, 8, 6).astype("f")
    batch[:, :, 0] = 0
    out = nd.contrib.box_nms(_nd(batch), overlap_thresh=0.9, topk=3)
    assert out.shape == (2, 8, 6)
    # topk=3 leaves at most 3 survivors per batch row
    surv = (out.asnumpy()[:, :, 1] >= 0).sum(axis=1)
    assert (surv <= 3).all()


def test_multibox_prior_golden():
    feat = nd.zeros((1, 3, 2, 2))
    anchors = nd.contrib.MultiBoxPrior(feat, sizes=(0.5,), ratios=(1.0,))
    a = anchors.asnumpy()
    assert a.shape == (1, 4, 4)
    # first cell center (0.25, 0.25), half-extent 0.25
    assert a[0, 0] == pytest.approx([0.0, 0.0, 0.5, 0.5], abs=1e-6)
    # last cell center (0.75, 0.75)
    assert a[0, 3] == pytest.approx([0.5, 0.5, 1.0, 1.0], abs=1e-6)
    # sizes+ratios count: len(sizes)+len(ratios)-1 anchors per cell
    anchors2 = nd.contrib.MultiBoxPrior(feat, sizes=(0.5, 0.25),
                                        ratios=(1.0, 2.0))
    assert anchors2.shape == (1, 2 * 2 * 3, 4)
    # ratio-2 anchor is wider than tall
    a2 = anchors2.asnumpy().reshape(2, 2, 3, 4)
    w = a2[0, 0, 2, 2] - a2[0, 0, 2, 0]
    h = a2[0, 0, 2, 3] - a2[0, 0, 2, 1]
    assert w > h
    # clip clamps into [0, 1]
    clipped = nd.contrib.MultiBoxPrior(feat, sizes=(1.5,), clip=True).asnumpy()
    assert clipped.min() >= 0.0 and clipped.max() <= 1.0


def test_multibox_target_matching():
    # 4 anchors, 1 GT that exactly matches anchor 0
    anchors = _nd([[[0.0, 0.0, 0.5, 0.5],
                    [0.5, 0.5, 1.0, 1.0],
                    [0.0, 0.5, 0.5, 1.0],
                    [0.48, 0.48, 0.98, 0.98]]])
    label = _nd([[[2.0, 0.0, 0.0, 0.5, 0.5]]])  # class 2 at anchor-0's box
    cls_pred = nd.zeros((1, 4, 4))  # (B, num_cls+1, N)
    loc_t, loc_m, cls_t = nd.contrib.MultiBoxTarget(anchors, label, cls_pred)
    cls_t = cls_t.asnumpy()[0]
    assert cls_t[0] == 3.0          # class id + 1
    assert cls_t[1] == 0.0 and cls_t[2] == 0.0
    lm = loc_m.asnumpy()[0].reshape(4, 4)
    assert (lm[0] == 1).all() and (lm[1] == 0).all()
    # matched anchor with exact fit has ~zero encoded offset
    lt = loc_t.asnumpy()[0].reshape(4, 4)
    assert onp.abs(lt[0]).max() < 1e-4
    # padded (-1) GT rows are ignored
    label2 = _nd([[[2.0, 0.0, 0.0, 0.5, 0.5],
                   [-1.0, 0.0, 0.0, 0.0, 0.0]]])
    _, _, cls_t2 = nd.contrib.MultiBoxTarget(anchors, label2, cls_pred)
    assert (cls_t2.asnumpy() == cls_t).all()


def test_multibox_target_negative_mining():
    anchors = _nd([[[0.0, 0.0, 0.5, 0.5],
                    [0.5, 0.5, 1.0, 1.0],
                    [0.0, 0.5, 0.5, 1.0],
                    [0.5, 0.0, 1.0, 0.5]]])
    label = _nd([[[0.0, 0.0, 0.0, 0.5, 0.5]]])
    # cls_pred: background row then 1 fg class; anchor 1 is the
    # "hardest" negative (largest fg-bg margin)
    cls_pred = _nd([[[0.0, 0.0, 0.0, 0.0],
                     [0.0, 5.0, 1.0, 0.5]]])
    _, _, cls_t = nd.contrib.MultiBoxTarget(
        anchors, label, cls_pred, negative_mining_ratio=1.0,
        negative_mining_thresh=0.3)
    c = cls_t.asnumpy()[0]
    assert c[0] == 1.0              # the positive
    assert c[1] == 0.0              # hardest negative kept as background
    assert c[2] == -1.0 and c[3] == -1.0  # mined out -> ignore_label


def test_multibox_detection_roundtrip():
    """Encode GT offsets with MultiBoxTarget, decode with
    MultiBoxDetection: the recovered box must equal the GT box."""
    anchors = _nd([[[0.1, 0.1, 0.4, 0.4],
                    [0.6, 0.6, 0.9, 0.9]]])
    gt = onp.array([[0.15, 0.12, 0.45, 0.40]], "f")
    label = _nd([[[1.0, 0.15, 0.12, 0.45, 0.40]]])
    cls_pred = nd.zeros((1, 3, 2))
    loc_t, loc_m, cls_t = nd.contrib.MultiBoxTarget(anchors, label, cls_pred)
    assert cls_t.asnumpy()[0, 0] == 2.0
    # build cls_prob selecting class 2 (fg id 1) on anchor 0
    cls_prob = _nd([[[0.05, 0.90],   # background
                     [0.05, 0.05],   # class 0
                     [0.90, 0.05]]])  # class 1  (shape B, C+1, N)
    det = nd.contrib.MultiBoxDetection(cls_prob, loc_t, anchors,
                                       threshold=0.5).asnumpy()[0]
    # one detection: class id 1, score 0.9, box == GT
    assert det[0, 0] == pytest.approx(1.0)
    assert det[0, 1] == pytest.approx(0.9, abs=1e-6)
    assert det[0, 2:6] == pytest.approx(gt[0], abs=1e-3)
    assert (det[1] == -1).all()


def test_multibox_detection_threshold_and_nms():
    anchors = _nd([[[0.1, 0.1, 0.5, 0.5],
                    [0.12, 0.12, 0.52, 0.52],
                    [0.6, 0.6, 0.9, 0.9]]])
    # all three anchors predict the same class; two overlap
    cls_prob = _nd([[[0.1, 0.2, 0.95],
                     [0.9, 0.8, 0.05]]])  # (B, 2, 3): bg + 1 class
    loc_pred = nd.zeros((1, 12))
    det = nd.contrib.MultiBoxDetection(cls_prob, loc_pred, anchors,
                                       threshold=0.5,
                                       nms_threshold=0.5).asnumpy()[0]
    kept = det[det[:, 0] >= 0]
    assert kept.shape[0] == 1      # overlapping weaker box suppressed,
    assert kept[0, 1] == pytest.approx(0.9, abs=1e-6)  # anchor-3 below thresh


def test_detection_ops_jit_and_npx():
    """The family jits whole (static shapes) and rides npx."""
    import jax
    feat = mx.np.zeros((1, 3, 4, 4))
    pri = mx.npx.multibox_prior(feat, sizes=(0.4,), ratios=(1.0, 2.0))
    assert type(pri).__name__ == "ndarray" and pri.shape == (1, 32, 4)
    rows = mx.np.array(onp.random.RandomState(0).rand(8, 6).astype("f"))
    out = mx.npx.box_nms(rows, overlap_thresh=0.7)
    assert out.shape == (8, 6)

    from mxnet_tpu.ops.detection import box_nms as _nms_fn
    jitted = jax.jit(lambda d: _nms_fn(d, overlap_thresh=0.7))
    o2 = jitted(rows._data)
    assert (onp.asarray(o2) == out.asnumpy()).all()


def test_detection_symbol_path():
    d = mx.sym.Variable("feat")
    pri = mx.sym.contrib.MultiBoxPrior(d, sizes=(0.5,), ratios=(1.0,))
    e = pri.bind(mx.current_context(), {"feat": nd.zeros((1, 2, 2, 2))})
    out = e.forward()[0]
    assert out.shape == (1, 4, 4)


def test_detection_review_regressions():
    """Review findings: (y, x) steps convention, NaN-safe bipartite
    matching under is_ascend, topk scoped to valid rows, nms_topk
    applied before suppression."""
    # steps/offsets are (y, x): explicit auto-equivalent steps on a
    # non-square map must reproduce the auto anchors
    feat = nd.zeros((1, 1, 2, 4))  # H=2, W=4
    auto = nd.contrib.MultiBoxPrior(feat, sizes=(0.1,)).asnumpy()
    manual = nd.contrib.MultiBoxPrior(feat, sizes=(0.1,),
                                      steps=(0.5, 0.25)).asnumpy()
    assert (auto == manual).all()
    # NaN never matches under is_ascend
    d = _nd([[onp.nan, 0.5], [0.2, 0.3]])
    rows, _ = nd.contrib.bipartite_matching(d, is_ascend=True)
    r = rows.asnumpy().tolist()
    assert r[1] == 0.0 and r[0] in (1.0,)  # (1,0)=0.2 first, then (0,1)
    # box_nms topk ranks only valid rows: high-score background rows
    # must not consume topk slots
    rows6 = _nd([[1.0, 0.99, 0.0, 0.0, 1.0, 1.0],     # background id 1
                 [0.0, 0.5, 3.0, 3.0, 4.0, 4.0],
                 [0.0, 0.4, 6.0, 6.0, 7.0, 7.0]])
    out = nd.contrib.box_nms(rows6, overlap_thresh=0.5, topk=2, id_index=0,
                             background_id=1).asnumpy()
    assert (out[:, 1] >= 0.4 - 1e-6).sum() == 2  # both real boxes kept
    # nms_topk prunes BEFORE suppression: a discarded candidate cannot
    # suppress a kept one
    anchors = _nd([[[0.1, 0.1, 0.5, 0.5],
                    [0.12, 0.12, 0.52, 0.52]]])
    cls_prob = _nd([[[0.1, 0.2], [0.9, 0.8]]])
    det = nd.contrib.MultiBoxDetection(cls_prob, nd.zeros((1, 8)), anchors,
                                       threshold=0.5, nms_threshold=0.5,
                                       nms_topk=1).asnumpy()[0]
    kept = det[det[:, 0] >= 0]
    assert kept.shape[0] == 1 and kept[0, 1] == pytest.approx(0.9, abs=1e-6)


def test_np_hstack_scalars():
    out = mx.np.hstack((1, 2))
    assert out.asnumpy().tolist() == [1, 2]
    cs = mx.np.column_stack((1.0, 2.0))
    assert cs.shape == (1, 2)


def test_multibox_prior_nonsquare_aspect():
    """Reference multibox_prior scales half-WIDTH by in_h/in_w so `size`
    is the same image fraction on both axes of a non-square map
    (advisor r4 medium: square maps hid the missing factor)."""
    feat = nd.zeros((1, 3, 2, 4))  # h=2, w=4 -> aspect 0.5
    a = nd.contrib.MultiBoxPrior(feat, sizes=(0.5,),
                                 ratios=(1.0,)).asnumpy().reshape(2, 4, 1, 4)
    # first cell: center (cx, cy) = (0.125, 0.25); half-width
    # 0.5 * (2/4) / 2 = 0.125, half-height 0.25
    assert a[0, 0, 0] == pytest.approx([0.0, 0.0, 0.25, 0.5], abs=1e-6)
    wid = a[0, 0, 0, 2] - a[0, 0, 0, 0]
    hei = a[0, 0, 0, 3] - a[0, 0, 0, 1]
    assert wid == pytest.approx(0.25, abs=1e-6)
    assert hei == pytest.approx(0.5, abs=1e-6)
    # ratio anchors get the same aspect correction
    a2 = nd.contrib.MultiBoxPrior(feat, sizes=(0.5,), ratios=(1.0, 4.0))
    a2 = a2.asnumpy().reshape(2, 4, 2, 4)
    w2 = a2[0, 0, 1, 2] - a2[0, 0, 1, 0]
    h2 = a2[0, 0, 1, 3] - a2[0, 0, 1, 1]
    # sqrt(4)=2: width 2x the corrected base, height half the base
    assert w2 == pytest.approx(0.5, abs=1e-6)
    assert h2 == pytest.approx(0.25, abs=1e-6)


def test_bipartite_matching_batched():
    """(B, N, M) input matches each batch row independently (gluoncv
    matcher contract; advisor r4)."""
    d0 = onp.array([[0.9, 0.1], [0.8, 0.7], [0.2, 0.3]], "f")
    d1 = onp.array([[0.1, 0.9], [0.7, 0.8], [0.3, 0.2]], "f")
    rows, cols = nd.contrib.bipartite_matching(_nd(onp.stack([d0, d1])))
    assert rows.shape == (2, 3) and cols.shape == (2, 2)
    assert rows.asnumpy()[0].tolist() == [0.0, 1.0, -1.0]
    assert rows.asnumpy()[1].tolist() == [1.0, 0.0, -1.0]
    assert cols.asnumpy()[1].tolist() == [1.0, 0.0]
    # matches the per-slice 2-D results exactly
    r0, c0 = nd.contrib.bipartite_matching(_nd(d0))
    assert rows.asnumpy()[0].tolist() == r0.asnumpy().tolist()
    # 4-D leading dims reshape through
    d4 = onp.stack([onp.stack([d0, d1]), onp.stack([d1, d0])])
    rows4, cols4 = nd.contrib.bipartite_matching(_nd(d4))
    assert rows4.shape == (2, 2, 3) and cols4.shape == (2, 2, 2)
    assert rows4.asnumpy()[0, 1].tolist() == rows.asnumpy()[1].tolist()
