"""Continuous profiling + resource & cost accounting (ISSUE 8).

Covers the always-on sampling profiler (mxnet_tpu/telemetry/profiling)
under a LIVE loaded serving engine, the per-bucket cost ledger's
exactness contract (sum of per-request amortized device time == batch
forward wall), the /profile and /costs scrape surface, resource
gauges/watermarks, flight-bundle profile.txt, the disabled-path
(MXNET_TPU_PROF=0) microbench guard, the loadgen cost cross-check, and
the xprof trace-id filter helper. Marker-clean tier-1.
"""
import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from mxnet_tpu import nd
from mxnet_tpu.serving import ServingEngine, ServingRouter
from mxnet_tpu.serving.metrics import CostLedger, merge_cost_buckets
from mxnet_tpu.telemetry import profiling, resources
from mxnet_tpu.telemetry.profiling import ContinuousProfiler

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _get(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


class StubModel:
    """Contract-shaped model; the sleep keeps the worker thread inside
    a NAMED frame long enough for the sampler to catch it."""

    def __init__(self, delay=0.0):
        self.delay = delay

    def __call__(self, ids, token_types, valid_length, segment_ids,
                 positions):
        if self.delay:
            time.sleep(self.delay)
        return nd.array(ids.asnumpy().astype(np.float32)[..., None])


# ---------------------------------------------------------------------------
# profiler unit: folded stacks, thread attribution, bounds
# ---------------------------------------------------------------------------

def test_profiler_folds_stacks_with_thread_attribution():
    prof = ContinuousProfiler(hz=250)
    stop = threading.Event()

    def _spin_hot_loop():
        while not stop.is_set():
            sum(i * i for i in range(500))

    t = threading.Thread(target=_spin_hot_loop, name="prof_test_spinner",
                         daemon=True)
    prof.start()
    t.start()
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            txt = prof.collapsed_text()
            if "prof_test_spinner" in txt and "_spin_hot_loop" in txt:
                break
            time.sleep(0.05)
    finally:
        stop.set()
        t.join(timeout=5)
        prof.stop()
    txt = prof.collapsed_text()
    # collapsed format: thread;root;...;leaf count — thread name is the
    # first segment, the hot function appears in its stack
    lines = [l for l in txt.splitlines()
             if l.startswith("prof_test_spinner;")]
    assert lines, txt
    assert any("_spin_hot_loop" in l for l in lines), lines
    head, _, count = lines[0].rpartition(" ")
    assert int(count) >= 1
    # self-time attribution sees the same frames
    snap = prof.snapshot()
    assert snap["samples"] > 0 and snap["top_self"]


def test_profiler_stack_table_is_bounded():
    prof = ContinuousProfiler(hz=100, max_stacks=2)
    with prof._lock:
        prof._counts[("a", ("x (f.py)",))] = 1
        prof._counts[("b", ("y (f.py)",))] = 1
    # the sampler excludes its own thread, so park a real one in a
    # distinctly-named frame for it to find
    stop = threading.Event()

    def _parked_sleeper():
        stop.wait(10.0)

    t = threading.Thread(target=_parked_sleeper, name="prof_test_parked",
                         daemon=True)
    t.start()
    try:
        time.sleep(0.05)
        # its distinct stack must fold into the overflow bucket, not
        # grow the table past its bound (plus the overflow keys)
        prof._sample_once()
    finally:
        stop.set()
        t.join(timeout=5)
    with prof._lock:
        keys = list(prof._counts)
    real = [k for k in keys if k[1] != ("(stack-table-full)",)]
    assert len(real) == 2, keys
    assert any(k[1] == ("(stack-table-full)",) for k in keys), keys


# ---------------------------------------------------------------------------
# resources: /proc gauges, device zeros, watermarks
# ---------------------------------------------------------------------------

def test_resources_sample_and_watermarks():
    snap = resources.sample()
    assert snap["rss_bytes"] > 0
    assert snap["open_fds"] > 0
    assert snap["threads"] >= 1
    # CPU backend: device stats may be zero, but never negative/None
    assert snap["device_bytes_in_use"] >= 0
    assert snap["live_buffer_bytes"] >= 0
    marks = resources.watermarks()
    assert marks["rss_peak_bytes"] >= snap["rss_bytes"] > 0
    compact = resources.compact()
    assert compact["rss_mb"] > 0 and compact["rss_peak_mb"] > 0


# ---------------------------------------------------------------------------
# the live-engine acceptance: /profile names the worker, /costs
# reconciles, amortized sums == batch forward
# ---------------------------------------------------------------------------

def test_live_engine_profile_and_costs_acceptance():
    profiling.PROFILER.configure(hz=200)
    eng = ServingEngine(StubModel(delay=0.004), bucket_lens=(16, 64),
                        max_rows=4, engine_id="prof-e0")
    futs = []
    with eng:
        srv = eng.expose()
        eng.warmup()
        rs = np.random.RandomState(3)
        deadline = time.monotonic() + 20.0
        worker_seen = False
        while time.monotonic() < deadline:
            batch = [eng.submit(rs.randint(1, 50, rs.randint(3, 40))
                                .tolist()) for _ in range(6)]
            for f in batch:
                f.result(timeout=30)
            futs.extend(batch)
            txt = _get(srv.url("/profile"))
            if any(l.startswith("mxnet_tpu_serving;")
                   for l in txt.splitlines()):
                worker_seen = True
                break
        # /profile is collapsed-stack text naming the serving worker
        # thread under load
        assert worker_seen, _get(srv.url("/profile"))
        profj = json.loads(_get(srv.url("/profile?format=json&top=5")))
        assert profj["running"] and profj["samples"] > 0
        assert profj["top_self"] and len(profj["top_self"]) <= 5

        # /costs: per-bucket ledger reconciles with what the clients saw
        costs = json.loads(_get(srv.url("/costs")))
    assert costs["engine_id"] == "prof-e0"
    totals = costs["totals"]
    bills = [f.cost for f in futs]
    assert all(b is not None for b in bills)
    assert totals["requests"] == len(futs)
    assert totals["valid_tokens"] == sum(b["tokens"] for b in bills)
    # the exactness contract: amortized per-request device time sums
    # back to the batch forward wall (ledger request_s) within 5%
    client_s = sum(b["device_s"] for b in bills)
    assert abs(client_s - totals["request_s"]) \
        <= 0.05 * totals["request_s"], (client_s, totals)
    # warmup compiles were accounted as compile/warmup, never device
    assert totals["compile_s"] > 0
    per_bucket = costs["buckets"]
    assert set(per_bucket) <= {"16", "64"}
    for row in per_bucket.values():
        if row["requests"]:
            assert row["device_ms_per_request"] > 0
            assert row["device_s_per_1k_tokens"] > 0


def test_cost_ledger_unit_and_merge():
    led = CostLedger("unit-e")
    led.observe_batch(64, 0.5, requests=2, valid_tokens=100,
                      compiled=False)
    led.observe_batch(64, 1.5, requests=1, valid_tokens=50, compiled=True)
    led.observe_warmup(256, 2.0, compiled=True)
    led.observe_warmup(256, 0.1, compiled=False)
    tbl = led.table()
    assert tbl["64"]["device_s"] == 0.5
    assert tbl["64"]["compile_s"] == 1.5
    assert tbl["64"]["request_s"] == 2.0          # both carried requests
    assert tbl["64"]["requests"] == 3
    assert tbl["256"]["compile_s"] == 2.0
    assert tbl["256"]["warmup_s"] == 0.1
    assert tbl["256"]["requests"] == 0
    tot = led.totals()
    assert tot["requests"] == 3 and tot["valid_tokens"] == 150
    assert tot["device_ms_per_request"] == pytest.approx(2000.0 / 3,
                                                         rel=1e-3)
    merged = merge_cost_buckets([tbl["64"], tbl["256"]])
    assert merged["compile_s"] == 3.5 and merged["batches"] == 4


# ---------------------------------------------------------------------------
# router: fleet /costs merge + cost bill propagation
# ---------------------------------------------------------------------------

def test_router_fleet_costs_and_bill_propagation(monkeypatch):
    # canary pinned off: its probes bill real device time into the
    # ledger, and this golden pins EXACT fleet request counts (the
    # canary-inclusive books are covered by the loadgen-exclusion
    # test in test_blackbox.py)
    monkeypatch.setenv("MXNET_TPU_CANARY", "0")
    engines = [ServingEngine(StubModel(), bucket_lens=(32,), max_rows=2,
                             engine_id=f"cost-e{i}") for i in range(2)]
    for e in engines:
        e.start()
        e.warmup()
    router = ServingRouter(engines=engines).start()
    try:
        futs = [router.submit(list(range(1, 6))) for _ in range(8)]
        for f in futs:
            f.result(timeout=30)
        # the engine's amortized bill rode through the router
        assert all(f.cost is not None for f in futs)
        assert {f.cost["engine_id"] for f in futs} \
            <= {"cost-e0", "cost-e1"}
        srv = router.expose()
        fleet = json.loads(_get(srv.url("/costs")))
        assert set(fleet["engines"]) == {"cost-e0", "cost-e1"}
        assert fleet["totals"]["requests"] == 8
        assert fleet["fleet"]["32"]["requests"] == 8
        client_s = sum(f.cost["device_s"] for f in futs)
        assert abs(client_s - fleet["totals"]["request_s"]) \
            <= 0.05 * max(fleet["totals"]["request_s"], 1e-9)
    finally:
        router.stop()
        for e in engines:
            e.stop()


def test_loadgen_cost_cross_check():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    from serve_loadgen import run_load

    eng = ServingEngine(StubModel(), bucket_lens=(64,), max_rows=4,
                        engine_id="lg-cost")
    with eng:
        srv = eng.expose()
        eng.warmup()
        report = run_load(eng, n_clients=4, requests_per_client=6,
                          min_len=4, max_len=32, vocab=60,
                          metrics_url=srv.url("/metrics"))
    cost = report["cost"]
    assert cost["reconciled"] is True, cost["mismatches"]
    assert cost["client_requests"] == 24 and cost["missing_bills"] == 0
    assert cost["ledger_delta"]["requests"] == 24
    assert cost["device_s_per_1k_tokens"] > 0


# ---------------------------------------------------------------------------
# flight bundle carries profile.txt
# ---------------------------------------------------------------------------

def test_flight_bundle_contains_profile_txt(tmp_path, monkeypatch):
    from mxnet_tpu.telemetry import recorder

    monkeypatch.setenv("MXNET_TPU_FLIGHT_DIR", str(tmp_path))
    profiling.ensure_started()
    time.sleep(0.15)                  # let the sampler take a wakeup
    path = recorder.dump("prof_test", min_interval_s=0.0)
    assert path is not None
    names = os.listdir(path)
    assert "profile.txt" in names, names
    with open(os.path.join(path, "profile.txt")) as f:
        head = f.readline()
    assert head.startswith("# mxnet_tpu continuous profile")


# ---------------------------------------------------------------------------
# disabled path: MXNET_TPU_PROF=0 costs ~nothing
# ---------------------------------------------------------------------------

def test_disabled_prof_and_ledger_paths_stay_cheap(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_PROF", "0")
    assert profiling.ensure_started() is None
    n = 5000
    t0 = time.perf_counter()
    for _ in range(n):
        profiling.ensure_started()
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 50e-6, f"ensure_started {per_call * 1e6:.1f}us"
    # the ledger's hot path (one observe per dispatched BATCH) stays
    # micro-cheap too — budget ~50x observed, catches regressions
    led = CostLedger("bench-led")
    t0 = time.perf_counter()
    for _ in range(n):
        led.observe_batch(64, 0.001, requests=4, valid_tokens=100,
                          compiled=False)
    per_obs = (time.perf_counter() - t0) / n
    assert per_obs < 200e-6, f"ledger observe {per_obs * 1e6:.1f}us"


# ---------------------------------------------------------------------------
# telemetry_dump --profile / --costs
# ---------------------------------------------------------------------------

def test_telemetry_dump_profile_and_costs(capsys):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    import telemetry_dump

    profiling.PROFILER.configure(hz=200)
    eng = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=2,
                        engine_id="dump-cost")
    with eng:
        srv = eng.expose()
        eng.warmup()
        eng.infer([1, 2, 3], timeout=30)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if profiling.PROFILER.snapshot()["samples"]:
                break
            time.sleep(0.05)
        rc = telemetry_dump.main(["--profile", "--costs",
                                  srv.url("/metrics")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "continuous profile" in out
    assert "self%" in out
    assert "costs, engine dump-cost" in out
    assert "bucket" in out and "device s" in out


# ---------------------------------------------------------------------------
# xprof trace-id filter helper (off-device unit)
# ---------------------------------------------------------------------------

def test_xprof_trace_id_filter_degrades_gracefully():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    from xprof_roofline import filter_rows_by_trace

    rows = [{"hlo_op_name": "fusion.1",
             "tf_op_name": "jit(step)/serving/forward#req3f-1c-0"},
            {"hlo_op_name": "fusion.2", "tf_op_name": "jit(step)/other"},
            {"hlo_op_name": "copy.3", "tf_op_name": None}]
    hit, matched = filter_rows_by_trace(rows, "req3f-1c-0")
    assert matched and [r["hlo_op_name"] for r in hit] == ["fusion.1"]
    # no match (off-device / annotation not propagated): full table
    # back with an honest flag, never an empty report
    out, matched = filter_rows_by_trace(rows, "req-unknown")
    assert not matched and out == rows
    # no filter requested: identity
    out, matched = filter_rows_by_trace(rows, None)
    assert matched and out is rows
