"""URI-routed filesystem layer (dmlc-core src/io/ Stream::Create
analog): local + memory:// schemes, pluggable registration, and the
RecordIO / NDArray-file surfaces riding it."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import filesystem as fs
from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError


def test_memory_scheme_roundtrip():
    with fs.open_uri("memory://a/b.bin", "wb") as f:
        f.write(b"hello")
    assert fs.exists("memory://a/b.bin")
    assert not fs.exists("memory://a/missing")
    with fs.open_uri("memory://a/b.bin", "rb") as f:
        assert f.read() == b"hello"
    # text mode
    with fs.open_uri("memory://t.txt", "w") as f:
        f.write("line\n")
    with fs.open_uri("memory://t.txt", "r") as f:
        assert f.read() == "line\n"


def test_unregistered_scheme_raises_clearly():
    with pytest.raises(MXNetError, match="register_scheme"):
        fs.open_uri("s3://bucket/key", "rb")


def test_register_scheme_plugs_in():
    store = {}

    def opener(path, mode):
        import io
        if "r" in mode:
            return io.BytesIO(store[path])
        buf = io.BytesIO()
        close = buf.close
        def closing():
            store[path] = buf.getvalue()
            close()
        buf.close = closing
        return buf
    fs.register_scheme("fake", opener, lambda p: p in store)
    with fs.open_uri("fake://x", "wb") as f:
        f.write(b"42")
    assert fs.exists("fake://x")
    with fs.open_uri("fake://x", "rb") as f:
        assert f.read() == b"42"


def test_ndarray_save_load_via_memory_uri():
    data = {"w": nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))}
    nd.save("memory://ckpt/model.params", data)
    back = nd.load("memory://ckpt/model.params")
    np.testing.assert_allclose(back["w"].asnumpy(), data["w"].asnumpy())


def test_recordio_via_memory_uri():
    rec = mx.recordio.MXRecordIO("memory://data/train.rec", "w")
    rec.write(b"one")
    rec.write(b"two")
    rec.close()
    rec = mx.recordio.MXRecordIO("memory://data/train.rec", "r")
    assert rec.read() == b"one"
    assert rec.read() == b"two"
    rec.close()


def test_memory_append_and_double_close():
    with fs.open_uri("memory://ap.bin", "wb") as f:
        f.write(b"ab")
    f2 = fs.open_uri("memory://ap.bin", "ab")
    f2.write(b"cd")
    f2.close()
    f2.close()  # idempotent, like real files
    with fs.open_uri("memory://ap.bin", "rb") as f:
        assert f.read() == b"abcd"
    with pytest.raises(MXNetError, match="update mode"):
        fs.open_uri("memory://ap.bin", "r+b")


def test_capability_gap_raises_not_false():
    fs.register_scheme("openonly", lambda p, m: None)
    with pytest.raises(MXNetError, match="exists"):
        fs.exists("openonly://x")
    with pytest.raises(MXNetError, match="list"):
        fs.list_prefix("openonly://x")


def test_indexed_recordio_via_memory_uri():
    rec = mx.recordio.MXIndexedRecordIO("memory://ix.idx", "memory://ix.rec", "w")
    for i in range(3):
        rec.write_idx(i, b"rec%d" % i)
    rec.close()
    rec = mx.recordio.MXIndexedRecordIO("memory://ix.idx", "memory://ix.rec", "r")
    assert rec.read_idx(1) == b"rec1"
    assert rec.read_idx(2) == b"rec2"
    rec.close()


def test_sharded_checkpoint_via_memory_uri():
    from mxnet_tpu import nd as _nd
    data = {"w": _nd.array(np.arange(8, dtype=np.float32).reshape(2, 4))}
    _nd.save_sharded("memory://shard/ckpt", data)
    back = _nd.load_sharded("memory://shard/ckpt")
    np.testing.assert_allclose(back["w"].asnumpy(), data["w"].asnumpy())
