"""mx.operator CustomOp/CustomOpProp registration API
(reference python/mxnet/operator.py + src/operator/custom/custom.cc;
tests mirror tests/python/unittest/test_operator.py::test_custom_op).

The TPU-native design runs the user's forward/backward inside the
trace (NDArrays wrap JAX tracers), with jax.custom_vjp holding the
gradient contract — so the same registration works from nd, Gluon,
Symbol/Module, and under jit.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.test_utils import assert_almost_equal


@mx.operator.register("t_sqr")
class SqrProp(mx.operator.CustomOpProp):
    """y = x^2 with a deliberately scaled backward (2.5x the true grad)
    so tests can tell the user backward ran, not autodiff."""

    def __init__(self, scale="2.5"):
        super().__init__(need_top_grad=True)
        self.scale = float(scale)

    def create_operator(self, ctx, in_shapes, in_dtypes):
        prop = self

        class Sqr(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                self.assign(out_data[0], req[0], in_data[0] * in_data[0])

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                self.assign(in_grad[0], req[0],
                            prop.scale * out_grad[0] * in_data[0])

        return Sqr()


@mx.operator.register("t_softmax_loss")
class SoftmaxLossProp(mx.operator.CustomOpProp):
    """The classic custom softmax loss (reference
    example/numpy-ops/custom_softmax.py): outputs the softmax, backward
    is softmax - onehot(label); no top grad."""

    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = [data_shape[0]]
        return [data_shape, label_shape], [data_shape], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        class SoftmaxLoss(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                self.assign(out_data[0], req[0],
                            nd.softmax(in_data[0], axis=-1))

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                label = in_data[1]
                y = out_data[0]
                oh = nd.one_hot(label, y.shape[-1], dtype=y.dtype)
                self.assign(in_grad[0], req[0], y - oh)
                self.assign(in_grad[1], req[1], nd.zeros_like(label))

        return SoftmaxLoss()


def test_custom_registered():
    names = mx.operator.get_all_registered_operators()
    assert "t_sqr" in names and "t_softmax_loss" in names
    assert hasattr(nd, "Custom") and hasattr(mx.sym, "Custom")


def test_custom_eager_forward_backward():
    x = nd.array(np.array([[1.0, -2.0], [3.0, 0.5]], np.float32))
    x.attach_grad()
    with autograd.record():
        y = nd.Custom(x, op_type="t_sqr")
        loss = y.sum()
    loss.backward()
    assert_almost_equal(y.asnumpy(), x.asnumpy() ** 2)
    # the user backward emits scale * out_grad * x — deliberately NOT
    # the true 2x grad, so matching it proves the registered backward
    # replaced autodiff
    assert_almost_equal(x.grad.asnumpy(), 2.5 * x.asnumpy())


def test_custom_param_reaches_prop():
    x = nd.array(np.ones((2, 2), np.float32))
    x.attach_grad()
    with autograd.record():
        y = nd.Custom(x, scale=4.0, op_type="t_sqr")
    y.backward()
    assert_almost_equal(x.grad.asnumpy(), 4.0 * np.ones((2, 2)))


def test_custom_softmax_loss_grad():
    rs = np.random.RandomState(0)
    logits = rs.randn(4, 5).astype(np.float32)
    labels = np.array([0, 2, 1, 4], np.float32)
    x = nd.array(logits)
    lab = nd.array(labels)
    x.attach_grad()
    with autograd.record():
        out = nd.Custom(x, lab, op_type="t_softmax_loss")
    out.backward()
    sm = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    assert_almost_equal(out.asnumpy(), sm, rtol=1e-5, atol=1e-6)
    oh = np.eye(5, dtype=np.float32)[labels.astype(int)]
    assert_almost_equal(x.grad.asnumpy(), sm - oh, rtol=1e-5, atol=1e-6)


def test_custom_in_gluon_training_loop():
    """A Gluon net trained with the custom softmax loss converges."""
    from mxnet_tpu.gluon import nn, Trainer

    rs = np.random.RandomState(1)
    w = rs.randn(8, 4).astype(np.float32)
    xs = rs.rand(256, 8).astype(np.float32)
    ys = (xs @ w).argmax(1).astype(np.float32)

    mx.random.seed(2)
    net = nn.Dense(4, in_units=8)
    net.initialize(init=mx.initializer.Xavier())
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 2e-2})
    x_all, y_all = nd.array(xs), nd.array(ys)
    for _ in range(200):
        with autograd.record():
            prob = nd.Custom(net(x_all), y_all, op_type="t_softmax_loss")
            # backward seeds from the output; the custom bwd ignores the
            # cotangent (need_top_grad=False) and emits softmax - onehot
        prob.backward()
        trainer.step(x_all.shape[0])
    acc = (prob.asnumpy().argmax(1) == ys).mean()
    assert acc > 0.85, f"custom-loss Gluon training failed to fit: acc={acc}"


def test_custom_under_hybridize_and_jit():
    """Custom inside a hybridized block: compiles into the cached graph
    and the user backward still defines the gradient."""
    from mxnet_tpu.gluon import nn

    class Net(mx.gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.fc = nn.Dense(3, in_units=3)

        def hybrid_forward(self, F, x):
            return F.Custom(self.fc(x), op_type="t_sqr")

    mx.random.seed(3)
    net = Net()
    net.initialize()
    x = nd.array(np.random.RandomState(2).rand(2, 3).astype(np.float32))
    eager = net(x).asnumpy()
    net.hybridize()
    x.attach_grad()
    with autograd.record():
        y = net(x)
        loss = y.sum()
    loss.backward()
    assert_almost_equal(y.asnumpy(), eager, rtol=1e-5, atol=1e-6)
    assert np.abs(x.grad.asnumpy()).sum() > 0


def test_custom_symbol_module_fit():
    """mx.sym.Custom trains under Module.fit (the reference's symbolic
    custom-op path: registered by name, label variable auto-created)."""
    rs = np.random.RandomState(4)
    w = rs.randn(6, 3).astype(np.float32)
    xs = rs.rand(240, 6).astype(np.float32)
    ys = (xs @ w).argmax(1).astype(np.float32)

    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    out = mx.sym.Custom(data=net, op_type="t_softmax_loss", name="softmax")
    assert "softmax_label" in out.list_arguments()

    train = mx.io.NDArrayIter(xs, ys, batch_size=48, shuffle=True,
                              label_name="softmax_label")
    mod = mx.module.Module(out, label_names=["softmax_label"])
    mod.fit(train, num_epoch=40, optimizer="adam",
            optimizer_params={"learning_rate": 2e-2})
    preds = mod.predict(mx.io.NDArrayIter(xs, ys, batch_size=48,
                                          label_name="softmax_label"))
    acc = (preds.asnumpy().argmax(1) == ys).mean()
    assert acc > 0.85, f"Module.fit with custom loss failed: acc={acc}"


def test_custom_symbol_infer_shape():
    """Shape inference flows through the prop's infer_shape callback."""
    data = mx.sym.var("data")
    lab = mx.sym.var("lab")
    out = mx.sym.Custom(data=data, label=lab, op_type="t_softmax_loss")
    _, out_shapes, _ = out.infer_shape(data=(7, 9), lab=(7,))
    assert tuple(out_shapes[0]) == (7, 9)


def test_custom_multi_output():
    @mx.operator.register("t_minmax")
    class MinMaxProp(mx.operator.CustomOpProp):
        def list_outputs(self):
            return ["mn", "mx"]

        def infer_shape(self, in_shape):
            return in_shape, [[in_shape[0][0]], [in_shape[0][0]]], []

        def create_operator(self, ctx, in_shapes, in_dtypes):
            class MinMax(mx.operator.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    self.assign(out_data[0], req[0], in_data[0].min(axis=1))
                    self.assign(out_data[1], req[1], in_data[0].max(axis=1))

                def backward(self, req, out_grad, in_data, out_data,
                             in_grad, aux):
                    self.assign(in_grad[0], req[0],
                                nd.zeros_like(in_data[0]))

            return MinMax()

    x = nd.array(np.array([[3.0, 1.0, 2.0], [5.0, 9.0, 4.0]], np.float32))
    mn, mxv = nd.Custom(x, op_type="t_minmax")
    assert_almost_equal(mn.asnumpy(), np.array([1.0, 4.0]))
    assert_almost_equal(mxv.asnumpy(), np.array([3.0, 9.0]))
    # symbolic arity follows list_outputs
    s = mx.sym.Custom(mx.sym.var("x"), op_type="t_minmax")
    assert s.num_outputs == 2


def test_custom_errors():
    with pytest.raises(mx.MXNetError, match="not registered"):
        nd.Custom(nd.zeros((2, 2)), op_type="nope_never_registered")
    with pytest.raises(mx.MXNetError, match="positionally"):
        nd.Custom(data=nd.zeros((2, 2)), op_type="t_sqr")
    with pytest.raises(mx.MXNetError, match="expects a CustomOpProp"):
        mx.operator.register("t_bad")(object)
