"""dist_async wire hardening: the parameter-server channel must never
evaluate executable encodings from the socket (ADVICE: pickle.loads on
network bytes = remote code execution in worker 0's process).

The 2-worker end-to-end contract lives in test_dist_kvstore.py; this
file owns the codec itself and the server's behavior on hostile bytes.
"""
import inspect
import os
import socket
import struct
import threading
import time

import numpy as np
import pytest

import mxnet_tpu.kvstore as kvmod
from mxnet_tpu.kvstore import (_ParameterServer, _optimizer_wire_spec,
                               _recv_msg, _send_msg, _wire_decode,
                               _wire_encode)


def test_no_pickle_on_the_wire():
    """The acceptance criterion, asserted directly: nothing in
    kvstore.py calls pickle at all (the wire codec is typed; optimizer
    state io lives in the updater, off-socket)."""
    src = inspect.getsource(kvmod)
    for needle in ("pickle.loads", "pickle.load(", "pickle.dumps",
                   "import pickle"):
        assert needle not in src, f"kvstore.py still uses {needle}"


def test_wire_codec_roundtrip():
    msgs = [
        None, True, False, 0, -(2 ** 40), 1.5, "héllo", b"\x00\xff",
        ("init", "w", np.full((4,), 1.0, np.float32)),
        ("ok", None),
        ("optattr", None, ("lr", 0.5)),
        ("setopt", None, ("sgd", {"lr": 0.5, "lr_mult": {}, "n": 3})),
        {"a": 1, 2: "b", "nested": {"x": None}},
        np.array(3.5),                       # 0-d
        np.arange(24).reshape(2, 3, 4).astype(np.int64),
        np.random.RandomState(0).rand(17, 5).astype(np.float16),
    ]
    for m in msgs:
        got = _wire_decode(_wire_encode(m))
        _assert_wire_equal(got, m)
    # non-contiguous arrays encode their logical content
    arr = np.arange(20).reshape(4, 5)[:, ::2]
    assert np.array_equal(_wire_decode(_wire_encode(arr)), arr)


def _assert_wire_equal(got, want):
    if isinstance(want, np.ndarray):
        assert isinstance(got, np.ndarray)
        assert got.dtype == want.dtype and np.array_equal(got, want)
    elif isinstance(want, (list, tuple)):
        assert isinstance(got, tuple) and len(got) == len(want)
        for g, w in zip(got, want):
            _assert_wire_equal(g, w)
    elif isinstance(want, dict):
        assert set(got) == set(want)
        for k in want:
            _assert_wire_equal(got[k], want[k])
    else:
        assert got == want and type(got) is type(want)


def test_wire_rejects_executable_and_garbage_frames(tmp_path):
    import pickle
    sentinel = tmp_path / "pwned"

    class Evil:
        def __reduce__(self):
            return (os.system, (f"touch {sentinel}",))

    for bad in (pickle.dumps(Evil()), b"\x80\x04K*.", b"zjunk",
                b"a\x02<8junk"):
        with pytest.raises(ValueError):
            _wire_decode(bad)
    assert not sentinel.exists(), "decoding executed code!"
    # trailing bytes after a valid object are refused too (no smuggling)
    with pytest.raises(ValueError):
        _wire_decode(_wire_encode("ok") + b"N")
    # non-data objects refuse to encode rather than falling back
    with pytest.raises(ValueError):
        _wire_encode(lambda: None)


def test_optimizer_wire_spec_rebuilds_scalars():
    from mxnet_tpu import optimizer as opt

    sgd = opt.SGD(learning_rate=0.25, momentum=0.9, wd=1e-4,
                  rescale_grad=1.0 / 64)
    sgd.lr_mult = {"dense0_weight": 2.0}
    name, attrs, sched_spec = _wire_decode(
        _wire_encode(_optimizer_wire_spec(sgd)))
    assert sched_spec is None           # no scheduler set on this one
    rebuilt = opt.create(name)
    for k, v in attrs.items():
        setattr(rebuilt, k, dict(v) if isinstance(v, dict) else v)
    assert isinstance(rebuilt, opt.SGD)
    assert rebuilt.lr == 0.25 and rebuilt.momentum == 0.9
    assert rebuilt.wd == 1e-4 and rebuilt.rescale_grad == 1.0 / 64
    assert rebuilt.lr_mult == {"dense0_weight": 2.0}
    # nothing device-backed or callable rode the wire
    assert "param_dict" not in attrs and "lr_scheduler" not in attrs


def test_optimizer_wire_spec_carries_lr_scheduler():
    """The scheduled lr must survive the typed wire: server-side
    updates follow lr_scheduler(num_update), so dropping the scheduler
    would silently train at the base lr forever."""
    from mxnet_tpu import lr_scheduler, optimizer as opt
    from mxnet_tpu.kvstore import _rebuild_wire_scheduler

    sched = lr_scheduler.FactorScheduler(step=10, factor=0.5)
    sgd = opt.SGD(learning_rate=0.5, lr_scheduler=sched)
    payload = _wire_decode(_wire_encode(_optimizer_wire_spec(sgd)))
    name, attrs, sspec = payload
    assert sspec[0] == "FactorScheduler"
    rebuilt = _rebuild_wire_scheduler(sspec)
    ref = lr_scheduler.FactorScheduler(step=10, factor=0.5, base_lr=0.5)
    for n in (0, 5, 15, 25, 40):            # both stateful; same walk
        assert rebuilt(n) == ref(n), n
    # list-valued scheduler attrs (MultiFactorScheduler.step) ride too
    msched = lr_scheduler.MultiFactorScheduler(step=[5, 15], factor=0.1)
    adam = opt.Adam(lr_scheduler=msched)
    _, _, mspec = _wire_decode(_wire_encode(_optimizer_wire_spec(adam)))
    mre = _rebuild_wire_scheduler(mspec)
    mref = lr_scheduler.MultiFactorScheduler(step=[5, 15], factor=0.1,
                                             base_lr=adam.lr)
    for n in (0, 7, 20):
        assert mre(n) == mref(n), n
    # only classes from mxnet_tpu.lr_scheduler rebuild — never imports
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError):
        _rebuild_wire_scheduler(("os", {}))


def test_server_setopt_applies_typed_spec_with_scheduler():
    from mxnet_tpu import lr_scheduler, optimizer as opt

    srv = _ParameterServer("127.0.0.1", 0, num_workers=1)
    try:
        sgd = opt.SGD(learning_rate=0.25, momentum=0.9,
                      lr_scheduler=lr_scheduler.FactorScheduler(
                          step=100, factor=0.5))
        srv._handle("setopt", None, _optimizer_wire_spec(sgd))
        rebuilt = srv._store._optimizer
        assert isinstance(rebuilt, opt.SGD)
        assert rebuilt.momentum == 0.9 and rebuilt.lr == 0.25
        assert isinstance(rebuilt.lr_scheduler,
                          lr_scheduler.FactorScheduler)
        assert rebuilt.lr_scheduler.step == 100
    finally:
        srv._srv.close()


@pytest.mark.timeout(60)
def test_server_survives_hostile_frame_and_binds_loopback():
    """Socket-level: a raw pickle frame must not execute anything and
    must not take the server down for well-behaved clients."""
    import pickle

    srv = _ParameterServer("127.0.0.1", 0, num_workers=1)
    host, port = srv._srv.getsockname()[:2]
    assert host == "127.0.0.1"  # launcher-announced interface, not 0.0.0.0
    try:
        hits = []

        class Evil:
            def __reduce__(self):
                return (hits.append, ("executed",))

        evil = pickle.dumps(Evil())
        s = socket.create_connection(("127.0.0.1", port), timeout=5)
        s.sendall(struct.pack("<Q", len(evil)) + evil)
        # server drops the connection instead of replying
        s.settimeout(5)
        assert s.recv(1) == b""
        s.close()
        assert hits == [], "hostile frame executed code"

        # a fresh well-formed client still gets served
        s2 = socket.create_connection(("127.0.0.1", port), timeout=5)
        _send_msg(s2, ("init", "k", np.full((3,), 2.0, np.float32)))
        status, _ = _recv_msg(s2)
        assert status == "ok"
        _send_msg(s2, ("pull", "k", None))
        status, arr = _recv_msg(s2)
        assert status == "ok" and np.allclose(arr, 2.0)
        s2.close()
    finally:
        srv._srv.close()
