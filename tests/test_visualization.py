"""mx.viz tests (reference python/mxnet/visualization.py)."""
import pytest

import mxnet_tpu as mx


def _mlp():
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="sm")


def test_print_summary_counts_params(capsys):
    total = mx.viz.print_summary(_mlp(), shape={"data": (1, 100)})
    assert total == 100 * 32 + 32 + 32 * 10 + 10
    out = capsys.readouterr().out
    assert "fc1" in out and "Total params: 3562" in out
    assert "(input)" in out  # data row present


def test_print_summary_without_shape(capsys):
    total = mx.viz.print_summary(_mlp())
    assert total == 0  # no shapes -> params unknown, layers still listed
    assert "fc2" in capsys.readouterr().out


def test_plot_network_gated_or_renders():
    try:
        dot = mx.viz.plot_network(_mlp(), shape={"data": (1, 100)})
    except ImportError as e:
        assert "graphviz" in str(e)
    else:
        assert "fc1" in dot.source


def test_print_summary_traverses_multi_output_graphs(capsys):
    """Indexed-output inputs (SliceChannel/split) must not hide their
    upstream layers (review regression: _walk resolving _base)."""
    cell = mx.rnn.LSTMCell(num_hidden=4, prefix="l_")
    data = mx.sym.Variable("data")
    outputs, _ = cell.unroll(2, data, merge_outputs=True)
    total = mx.viz.print_summary(outputs, shape={"data": (2, 2, 3)})
    out = capsys.readouterr().out
    assert "i2h" in out and "h2h" in out
    # i2h: 16x3 + 16; h2h: 16x4 + 16
    assert total == 16 * 3 + 16 + 16 * 4 + 16
