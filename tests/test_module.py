"""Module API tests (reference tests/python/unittest/test_module.py
scope): compiled symbolic execution + multi-context data parallelism
(executor_group parity) + checkpoint round-trip.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal

CTXS = [mx.cpu(0), mx.cpu(1)]


def _mlp_symbol(hidden=16, classes=3):
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _toy_data(n=128, d=8, c=3, seed=0):
    rs = np.random.RandomState(seed)
    w = rs.randn(d, c).astype(np.float32)
    x = rs.randn(n, d).astype(np.float32)
    y = (x @ w).argmax(1).astype(np.float32)
    return x, y


def test_module_fit_compiled_single_dispatch():
    """Module.fit's hot loop must dispatch ONE compiled graph op per
    forward, not per-op eager calls (VERDICT #5: SimpleBind compiles)."""
    from mxnet_tpu.ndarray import register as reg
    x, y = _toy_data()
    it = mx.io.NDArrayIter(x, y, batch_size=32, label_name="softmax_label")
    mod = mx.module.Module(_mlp_symbol(), label_names=["softmax_label"])
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05})

    calls = []
    orig = reg.invoke

    def spy(op, inputs, params=None, **kw):
        calls.append(op.name)
        return orig(op, inputs, params, **kw)

    reg.invoke = spy
    try:
        it.reset()
        batch = next(iter(it))
        mod.forward(batch, is_train=True)
    finally:
        reg.invoke = orig
    graph_calls = [c for c in calls if c.startswith("GraphExecutor")]
    assert len(graph_calls) == 1, calls
    # eager per-op dispatches (FullyConnected, Activation, ...) must not
    # appear in the compiled hot path
    assert not any(c in ("FullyConnected", "Activation", "SoftmaxOutput")
                   for c in calls), calls


def test_module_fit_converges_and_predicts():
    x, y = _toy_data()
    it = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True,
                           label_name="softmax_label")
    mod = mx.module.Module(_mlp_symbol(), label_names=["softmax_label"])
    mod.fit(it, num_epoch=10, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05})
    eval_it = mx.io.NDArrayIter(x, y, batch_size=32, label_name="softmax_label")
    preds = mod.predict(eval_it).asnumpy().argmax(1)
    assert (preds == y).mean() > 0.9


def test_module_multi_context_matches_single():
    """One fit step on [cpu(0), cpu(1)] with a split batch equals the
    single-context step (DataParallelExecutorGroup semantics), and the
    gradient reduce compiles to an all-reduce."""
    from mxnet_tpu.parallel import comm

    def one_step(ctx):
        mx.random.seed(3)
        x, y = _toy_data(n=32)
        it = mx.io.NDArrayIter(x, y, batch_size=32, label_name="softmax_label")
        mod = mx.module.Module(_mlp_symbol(), label_names=["softmax_label"],
                               context=ctx)
        mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
        mod.init_params(initializer=mx.initializer.Uniform(0.1))
        mod.init_optimizer(kvstore="device", optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1,
                                             "rescale_grad": 1.0 / 32})
        batch = next(iter(it))
        mod.forward(batch, is_train=True)
        out = mod.get_outputs()[0].asnumpy()
        mod.backward()
        mod.update()
        params, _ = mod.get_params()
        return out, {k: v.asnumpy() for k, v in params.items()}

    out1, p1 = one_step(mx.cpu(0))
    comm._LAST_HLO[0] = None
    out2, p2 = one_step(CTXS)
    assert_almost_equal(out2, out1, rtol=1e-5, atol=1e-6)
    for k in p1:
        assert_almost_equal(p2[k], p1[k], rtol=1e-5, atol=1e-6)
    hlo = comm.last_hlo_text()
    assert hlo and "all-reduce" in hlo


def test_module_multi_context_replicas_stay_synced():
    x, y = _toy_data(n=64)
    it = mx.io.NDArrayIter(x, y, batch_size=32, label_name="softmax_label")
    mod = mx.module.Module(_mlp_symbol(), label_names=["softmax_label"],
                           context=CTXS)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(kvstore="device", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05})
    for batch in it:
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    w0 = mod._execs[0].arg_dict["fc1_weight"].asnumpy()
    w1 = mod._execs[1].arg_dict["fc1_weight"].asnumpy()
    assert_almost_equal(w0, w1)


def test_module_multi_context_momentum_state_per_replica(caplog):
    """Optimizer state must be keyed per (param, replica) — shared state
    mutated n_ctx times per step diverges replicas and double-advances
    lr schedules (review regression; reference executor_group keys
    index*num_device+k)."""
    x, y = _toy_data(n=64)
    it = mx.io.NDArrayIter(x, y, batch_size=32, label_name="softmax_label")
    mod = mx.module.Module(_mlp_symbol(), label_names=["softmax_label"],
                           context=CTXS)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(kvstore="device", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9})
    for _ in range(3):
        it.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
    w0 = mod._execs[0].arg_dict["fc1_weight"].asnumpy()
    w1 = mod._execs[1].arg_dict["fc1_weight"].asnumpy()
    assert_almost_equal(w0, w1)
    # 3 epochs x 2 batches = 6 updates per key, regardless of replica count
    assert mod._optimizer.num_update == 6, mod._optimizer.num_update


def test_module_multi_context_no_kvstore_still_reduces():
    """kvstore=None with a context list must still sum replica grads
    before the update (reference executor_group semantics)."""
    def one_step(ctx, kvstore):
        mx.random.seed(3)
        x, y = _toy_data(n=32)
        it = mx.io.NDArrayIter(x, y, batch_size=32, label_name="softmax_label")
        mod = mx.module.Module(_mlp_symbol(), label_names=["softmax_label"],
                               context=ctx)
        mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
        mod.init_params(initializer=mx.initializer.Uniform(0.1))
        mod.init_optimizer(kvstore=kvstore, optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1,
                                             "rescale_grad": 1.0 / 32})
        batch = next(iter(it))
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
        params, _ = mod.get_params()
        return {k: v.asnumpy() for k, v in params.items()}

    ref = one_step(mx.cpu(0), None)
    multi = one_step(CTXS, None)
    for k in ref:
        assert_almost_equal(multi[k], ref[k], rtol=1e-5, atol=1e-6)


def test_module_checkpoint_roundtrip(tmp_path):
    x, y = _toy_data(n=32)
    it = mx.io.NDArrayIter(x, y, batch_size=32, label_name="softmax_label")
    mod = mx.module.Module(_mlp_symbol(), label_names=["softmax_label"])
    mod.fit(it, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05})
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 1)
    mod2 = mx.module.Module.load(prefix, 1, label_names=["softmax_label"])
    it.reset()
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod2.init_params(arg_params=mod2._preloaded_params[0],
                     aux_params=mod2._preloaded_params[1])
    eval_it = mx.io.NDArrayIter(x, y, batch_size=32, label_name="softmax_label")
    p1 = mod.predict(eval_it).asnumpy()
    eval_it.reset()
    p2 = mod2.predict(eval_it).asnumpy()
    assert_almost_equal(p2, p1, rtol=1e-5, atol=1e-6)


def test_executor_reshape_shares_compiled_cache():
    sym = _mlp_symbol()
    ex = sym.simple_bind(mx.cpu(0), data=(8, 4), softmax_label=(8,))
    ex.forward(is_train=False)
    n_before = len(ex._graph_cache)
    ex2 = ex.reshape(data=(4, 4), softmax_label=(4,))
    assert ex2._graph_cache is ex._graph_cache
    ex2.forward(is_train=False)
    assert len(ex._graph_cache) == n_before + 1
    # same shape again: cache hit, no growth
    ex3 = ex2.reshape(data=(8, 4), softmax_label=(8,))
    ex3.forward(is_train=False)
    assert len(ex._graph_cache) == n_before + 1


def test_bucketing_module_trains_across_buckets():
    """BucketingModule: per-bucket compiled executors sharing parameters
    (reference bucketing_module.py; variable-length training)."""
    rs = np.random.RandomState(0)
    w = rs.randn(6, 3).astype(np.float32)

    def sym_gen(seq_len):
        # variable-length input (N, seq_len, 6) mean-pooled over time —
        # parameter shapes are bucket-independent, as in RNN bucketing
        data = mx.sym.var("data")
        pooled = mx.sym.mean(data, axis=1)
        net = mx.sym.FullyConnected(pooled, num_hidden=16, name="fc1")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = mx.module.BucketingModule(sym_gen, default_bucket_key=6)
    mod.bind(data_shapes=[("data", (16, 6, 6))],
             label_shapes=[("softmax_label", (16,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})

    from mxnet_tpu.io import DataBatch
    losses = []
    for step in range(30):
        bucket = 6 if step % 2 == 0 else 4
        x = rs.randn(16, bucket, 6).astype(np.float32)
        y = (x.mean(1) @ w).argmax(1).astype(np.float32)
        batch = DataBatch(data=[nd.array(x)], label=[nd.array(y)],
                          bucket_key=bucket,
                          provide_data=[("data", (16, bucket, 6))],
                          provide_label=[("softmax_label", (16,))])
        mod.forward(batch, is_train=True)
        out = mod.get_outputs()[0].asnumpy()
        losses.append(-np.log(out[np.arange(16), y.astype(int)] + 1e-9).mean())
        mod.backward()
        mod.update()
    assert len(mod._buckets) == 2
    # both buckets' modules share the same improving parameters
    assert np.mean(losses[-6:]) < np.mean(losses[:6]), losses
    p6, _ = mod._buckets[6].get_params()
    p4, _ = mod._buckets[4].get_params()
    assert_almost_equal(p6["fc2_weight"].asnumpy(), p4["fc2_weight"].asnumpy())
