"""Sharded checkpoint save/load (SURVEY §5.4 extension: each host
writes its addressable shards). Exercised on the virtual 8-device CPU
mesh with genuinely sharded jax arrays."""
import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray.ndarray import _wrap
from mxnet_tpu.test_utils import assert_almost_equal


def test_sharded_roundtrip_plain_arrays(tmp_path):
    prefix = str(tmp_path / "ckpt")
    data = {"w": nd.array(np.arange(12, np.float32).reshape(3, 4) if False
                          else np.arange(12, dtype=np.float32).reshape(3, 4)),
            "b": nd.array(np.ones(5, np.float32))}
    fname = nd.save_sharded(prefix, data)
    assert "shard-00000-of-00001" in fname
    back = nd.load_sharded(prefix)
    assert set(back) == {"w", "b"}
    assert_almost_equal(back["w"].asnumpy(), data["w"].asnumpy())
    assert_almost_equal(back["b"].asnumpy(), data["b"].asnumpy())


def test_sharded_roundtrip_mesh_sharded_array(tmp_path):
    prefix = str(tmp_path / "ckpt")
    devs = jax.devices()[:4]
    mesh = Mesh(np.asarray(devs), ("dp",))
    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    garr = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
    data = {"sharded": _wrap(garr, mx.cpu(0)),
            "replicated": _wrap(jax.device_put(
                np.ones(3, np.float32), NamedSharding(mesh, P())), mx.cpu(0))}
    nd.save_sharded(prefix, data)
    back = nd.load_sharded(prefix)
    assert_almost_equal(back["sharded"].asnumpy(), x)
    assert_almost_equal(back["replicated"].asnumpy(), np.ones(3, np.float32))
