"""Worker body for the cross-process trace-correlation test: 2-process
dist_async launch where every worker writes a structured event log
(MXNET_TPU_EVENT_LOG points at a shared directory, one
events-<pid>.jsonl per process). Worker 1 pushes under an explicit
trace context; the test asserts the SAME trace id shows up in worker
1's client-side `kvstore_rpc` event and in worker 0's server-side
`kvstore_server_handle` event — the id crossed the wire inside the
typed frame.
"""
import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu import kvstore, nd
from mxnet_tpu.telemetry import trace_context


def main():
    kv = kvstore.create("dist_async")
    rank = kv.rank
    assert kv.num_workers == 2, kv.num_workers

    if rank == 0:
        kv.init("w", nd.array(np.zeros((4,), np.float32)))
    kv.barrier()

    if rank == 1:
        with trace_context("trace-golden-push"):
            kv.push("w", nd.array(np.full((4,), 2.0, np.float32)))
    kv.barrier()

    out = nd.zeros((4,))
    kv.pull("w", out=out)
    assert np.allclose(out.asnumpy(), 2.0), out.asnumpy()
    kv.barrier()
    print(f"TRACE_WORKER_{rank}_OK", flush=True)


if __name__ == "__main__":
    main()
