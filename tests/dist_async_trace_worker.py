"""Worker body for the cross-process trace-correlation test: 2-process
dist_async launch where every worker writes a structured event log
(MXNET_TPU_EVENT_LOG points at a shared directory, one
events-<pid>.jsonl per process). Worker 1 pushes under an explicit
trace context; the test asserts the SAME trace id shows up in worker
1's client-side `kvstore_rpc` event and in worker 0's server-side
`kvstore_server_handle` event — the id crossed the wire inside the
typed frame.

Span parenting crosses too (the 5th frame field): worker 1 prints the
span id of its client-side `kvstore/rpc/push` span
(``SPAN_RPC=<id>``); worker 0 finds the server-side
`kvstore/server/push` span for the same trace in ITS span ring and
prints that span's parent (``SPAN_HANDLE_PARENT=<id>``). The test
asserts the two ids are equal — one span tree across two processes.
"""
import os
import sys
import time

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["MXNET_TPU_TRACE_SLOW_MS"] = "0"   # keep every trace

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu import kvstore, nd
from mxnet_tpu.telemetry import spans, trace_context


def _find_span(trace_id, name, timeout_s=30.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        trace = spans.get_trace(trace_id)
        if trace:
            for s in trace["spans"]:
                if s["name"] == name:
                    return s
        time.sleep(0.1)
    return None


def main():
    kv = kvstore.create("dist_async")
    rank = kv.rank
    assert kv.num_workers == 2, kv.num_workers

    if rank == 0:
        kv.init("w", nd.array(np.zeros((4,), np.float32)))
    kv.barrier()

    if rank == 1:
        with trace_context("trace-golden-push"):
            kv.push("w", nd.array(np.full((4,), 2.0, np.float32)))
        rpc_span = _find_span("trace-golden-push", "kvstore/rpc/push")
        assert rpc_span is not None, "client rpc span not recorded"
        print(f"SPAN_RPC={rpc_span['span_id']}", flush=True)
    kv.barrier()

    if rank == 0:
        handle = _find_span("trace-golden-push", "kvstore/server/push")
        assert handle is not None, "server handle span not recorded"
        print(f"SPAN_HANDLE_PARENT={handle['parent_id']}", flush=True)
        opt = _find_span("trace-golden-push",
                         "kvstore/server/optimizer_update")
        assert opt is not None and opt["parent_id"] == handle["span_id"]

    out = nd.zeros((4,))
    kv.pull("w", out=out)
    assert np.allclose(out.asnumpy(), 2.0), out.asnumpy()
    kv.barrier()
    print(f"TRACE_WORKER_{rank}_OK", flush=True)


if __name__ == "__main__":
    main()
