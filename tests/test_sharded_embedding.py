"""Sharded embedding tables + all_to_all exchange
(parallel/sharded_embedding.py — SURVEY §2.4's TPU-native analog of the
reference kvstore row_sparse pull/push, src/kvstore/kvstore_dist.h
sparse path). Runs on the virtual 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mxnet_tpu.parallel import (build_mesh, make_sharded_embedding_fn,
                                shard_embedding_table)

N = 8


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < N:
        pytest.skip(f"needs {N} devices")
    return build_mesh({"ep": N})


def test_lookup_matches_unsharded(mesh):
    rs = np.random.RandomState(0)
    V, E, B = 64, 16, 32
    table = jnp.asarray(rs.randn(V, E), jnp.float32)
    ids = jnp.asarray(rs.randint(0, V, B), jnp.int32)
    lookup = make_sharded_embedding_fn(mesh, "ep")
    out = jax.jit(lookup)(shard_embedding_table(table, mesh), ids)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(table)[np.asarray(ids)],
                               rtol=1e-6)


def test_lookup_skewed_ids_one_shard(mesh):
    """Worst-case routing: every id lives on one shard (bucket capacity
    saturation) — and duplicate ids in the batch."""
    rs = np.random.RandomState(1)
    V, E, B = 64, 8, 16
    table = jnp.asarray(rs.randn(V, E), jnp.float32)
    ids = jnp.asarray(np.array([3, 5, 3, 7, 0, 1, 2, 3] * 2), jnp.int32)
    lookup = make_sharded_embedding_fn(mesh, "ep")
    out = jax.jit(lookup)(shard_embedding_table(table, mesh), ids)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(table)[np.asarray(ids)],
                               rtol=1e-6)


def test_gradient_scatter_adds_into_shards(mesh):
    rs = np.random.RandomState(2)
    V, E, B = 64, 16, 32
    table = jnp.asarray(rs.randn(V, E), jnp.float32)
    ids_np = rs.randint(0, V, B)
    ids = jnp.asarray(ids_np, jnp.int32)
    w = jnp.asarray(rs.randn(B, E), jnp.float32)
    lookup = make_sharded_embedding_fn(mesh, "ep")
    tbl = shard_embedding_table(table, mesh)

    g = jax.jit(jax.grad(lambda t, i: (lookup(t, i) * w).sum()))(tbl, ids)
    gref = np.zeros((V, E), np.float32)
    np.add.at(gref, ids_np, np.asarray(w))
    np.testing.assert_allclose(np.asarray(g), gref, rtol=1e-5, atol=1e-6)
    # the grad stays sharded like the table (no full-table gather);
    # trailing None dims are implicit in PartitionSpec equality
    assert tuple(g.sharding.spec)[:1] == tuple(tbl.sharding.spec)[:1] \
        and all(s is None for s in tuple(g.sharding.spec)[1:])


def test_all_to_all_in_hlo(mesh):
    rs = np.random.RandomState(3)
    table = jnp.asarray(rs.randn(64, 8), jnp.float32)
    ids = jnp.asarray(rs.randint(0, 64, 16), jnp.int32)
    lookup = make_sharded_embedding_fn(mesh, "ep")
    hlo = jax.jit(lookup).lower(
        shard_embedding_table(table, mesh), ids).compile().as_text()
    assert "all-to-all" in hlo


def test_training_step_converges(mesh):
    """A tiny CTR-style model over the sharded table trains end-to-end
    (the Wide&Deep EP configuration in miniature)."""
    rs = np.random.RandomState(4)
    V, E, B = 64, 8, 32
    w_true = rs.randn(V, 1).astype(np.float32)
    lookup = make_sharded_embedding_fn(mesh, "ep")
    table = shard_embedding_table(
        jnp.asarray(rs.randn(V, E) * 0.1, jnp.float32), mesh)
    proj = jnp.asarray(rs.randn(E, 1) * 0.1, jnp.float32)

    def loss_fn(params, ids, y):
        t, p = params
        logits = lookup(t, ids) @ p
        return ((logits - y) ** 2).mean()

    @jax.jit
    def step(params, ids, y):
        loss, g = jax.value_and_grad(loss_fn)(params, ids, y)
        return tuple(p - 0.5 * gg for p, gg in zip(params, g)), loss

    params = (table, proj)
    losses = []
    for i in range(60):
        ids_np = rs.randint(0, V, B)
        y = jnp.asarray(w_true[ids_np], jnp.float32)
        params, loss = step(params, jnp.asarray(ids_np, jnp.int32), y)
        losses.append(float(loss))
    assert losses[-1] < 0.3 * losses[0], (losses[0], losses[-1])


def test_sharded_embedding_dp_tp_composition():
    """batch_axis != table axis: ids shard over (dp, tp) jointly, the
    exchange rides tp within each dp row (the DLRM dp x ep layout);
    fwd + grad match the unsharded table (VERDICT r4 #7 groundwork)."""
    import jax
    import jax.numpy as jnp
    import numpy as onp
    from jax.sharding import Mesh

    from mxnet_tpu.parallel.sharded_embedding import (
        make_sharded_embedding_fn)

    devs = jax.devices()
    if len(devs) < 4:
        import pytest
        pytest.skip("needs 4 virtual devices")
    mesh = Mesh(onp.array(devs[:4]).reshape(2, 2), ("dp", "tp"))
    lookup = make_sharded_embedding_fn(mesh, "tp", batch_axis="dp")
    rs = onp.random.RandomState(0)
    table = jnp.asarray(rs.randn(12, 6), jnp.float32)
    ids = jnp.asarray(rs.randint(0, 12, 8), jnp.int32)
    w = jnp.asarray(rs.randn(8, 6), jnp.float32)
    out = jax.jit(lookup)(table, ids)
    assert onp.allclose(onp.asarray(out), onp.asarray(table)[onp.asarray(ids)],
                        atol=1e-6)
    g = jax.jit(jax.grad(lambda t: (lookup(t, ids) * w).sum()))(table)
    gref = onp.zeros((12, 6), onp.float32)
    onp.add.at(gref, onp.asarray(ids), onp.asarray(w))
    assert onp.allclose(onp.asarray(g), gref, atol=1e-5)
