"""Serving subsystem (mxnet_tpu/serving): queue admission control,
continuous packing batcher, engine correctness under concurrency, and
clean shutdown. Marker-clean — this IS the tier-1 CPU serving smoke.

The acceptance golden (closed-loop, >= 8 concurrent clients, every
response bit-matched against a solo forward within fp tolerance, zero
lost responses, distinct errors for deadline/shedding) lives in
``test_concurrent_clients_parity_and_stats``.
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.serving import (ContinuousBatcher, DeadlineExceededError,
                               EngineStoppedError, LatencySummary,
                               NoEngineAvailableError, QueueFullError,
                               Request, RequestQueue, RequestTooLongError,
                               ServingEngine, ServingRouter)
from mxnet_tpu.serving.queue import InferenceFuture


class StubModel:
    """Contract-shaped stand-in: out[b, s, 0] == ids[b, s], so a
    correctly-unpacked response equals the request's own tokens —
    any placement/slicing bug shows up as a value mismatch."""

    def __init__(self, delay=0.0):
        self.delay = delay
        self.started = threading.Event()
        self.shapes = []

    def __call__(self, ids, token_types, valid_length, segment_ids,
                 positions):
        self.started.set()
        if self.delay:
            time.sleep(self.delay)
        self.shapes.append(tuple(ids.shape))
        return nd.array(ids.asnumpy().astype(np.float32)[..., None])


def _tiny_bert():
    from mxnet_tpu.gluon.model_zoo.bert import BERTModel

    mx.random.seed(11)
    net = BERTModel(vocab_size=64, units=16, hidden_size=32, num_layers=1,
                    num_heads=2, max_length=16, dropout=0.0,
                    attention_dropout=0.0, use_pooler=False)
    net.initialize(init=mx.initializer.Normal(0.02))
    return net


# ---------------------------------------------------------------------------
# queue / future / metrics units
# ---------------------------------------------------------------------------

def test_request_queue_admission_and_poll():
    q = RequestQueue(max_depth=2)
    r1, r2 = Request([1, 2]), Request([3])
    q.put(r1)
    q.put(r2)
    with pytest.raises(QueueFullError):
        q.put(Request([4]))
    # poll drains what's there without waiting for more
    t0 = time.monotonic()
    got = q.poll(max_items=8, timeout=5.0)
    assert [g.id for g in got] == [r1.id, r2.id]
    assert time.monotonic() - t0 < 1.0
    assert all(g.t_drain is not None for g in got)
    # empty queue: poll waits at most timeout
    assert q.poll(4, timeout=0.05) == []
    q.close()
    with pytest.raises(EngineStoppedError):
        q.put(Request([5]))


def test_request_deadline_and_validation():
    r = Request([1, 2, 3], deadline_ms=1.0)
    time.sleep(0.01)
    assert r.expired()
    assert not Request([1]).expired()
    with pytest.raises(ValueError):
        Request([])
    with pytest.raises(ValueError):
        Request([1, 2], token_types=[0])


def test_future_result_and_exception():
    f = InferenceFuture()
    with pytest.raises(TimeoutError):
        f.result(timeout=0.01)
    f.set_result(42)
    assert f.done() and f.result() == 42 and f.exception() is None
    g = InferenceFuture()
    g.set_exception(DeadlineExceededError("late"))
    with pytest.raises(DeadlineExceededError):
        g.result()


def test_latency_summary_percentiles():
    s = LatencySummary(capacity=100)
    for v in range(1, 101):
        s.observe(float(v))
    snap = s.snapshot()
    assert snap["count"] == 100
    assert snap["p50_ms"] == 50.0
    assert snap["p99_ms"] == 99.0
    assert snap["max_ms"] == 100.0
    assert LatencySummary().snapshot() == {"count": 0}


# ---------------------------------------------------------------------------
# batcher units
# ---------------------------------------------------------------------------

def test_batcher_buckets_quantization_and_leftovers():
    b = ContinuousBatcher(bucket_lens=(8, 16), max_rows=4)
    # bucket: longest request picks the row length
    plan, left = b.plan([Request([1] * 3), Request([2] * 10)])
    assert plan.row_len == 16 and not left
    # row count quantizes to powers of two with 1-token dummy rows
    plan, _ = b.plan([Request([1] * 7), Request([2] * 7), Request([3] * 7)])
    assert plan.rows == 4 and plan.pad_rows == 1
    assert plan.valid_length[-1] == 1 and plan.segment_ids[-1, 0] == 1
    assert plan.valid_tokens == 21
    # overflow: requests beyond max_rows rows come back as leftovers
    reqs = [Request([9] * 8) for _ in range(6)]
    plan, left = b.plan(reqs)
    assert len(plan.entries) == 4 and len(left) == 2
    assert [r.id for r in left] == [reqs[4].id, reqs[5].id]
    # the compile budget is closed and small
    assert set(plan.data.shape for plan in [plan]) <= set(b.shape_universe())
    assert len(b.shape_universe()) == 6  # {1,2,4} rows x {8,16} lens


def test_batcher_packs_multiple_requests_per_row():
    b = ContinuousBatcher(bucket_lens=(16,), max_rows=2)
    reqs = [Request(np.arange(1, n + 1)) for n in (6, 5, 4, 9)]
    plan, left = b.plan(reqs)
    assert not left
    assert plan.rows == 2
    # every request's tokens are where its placement says
    for req, pl in plan.entries:
        got = plan.data[pl.row, pl.offset:pl.offset + pl.length]
        assert np.array_equal(got, req.tokens)
        seg = plan.segment_ids[pl.row, pl.offset:pl.offset + pl.length]
        assert (seg == pl.segment).all()
        pos = plan.positions[pl.row, pl.offset:pl.offset + pl.length]
        assert np.array_equal(pos, np.arange(pl.length))
    assert plan.packing_efficiency == 24 / 32.0


# ---------------------------------------------------------------------------
# engine behavior (stub model: no compiles, pure threading semantics)
# ---------------------------------------------------------------------------

def test_engine_roundtrip_and_placement_mapping():
    stub = StubModel()
    eng = ServingEngine(stub, bucket_lens=(16,), max_rows=2,
                        max_queue_depth=32)
    rs = np.random.RandomState(3)
    with eng:
        toks = [rs.randint(1, 60, n).astype(np.int32)
                for n in (3, 7, 12, 5, 9, 4)]
        outs = [eng.submit(t).result(timeout=30) for t in toks]
    for t, o in zip(toks, outs):
        assert o.shape == (len(t), 1)
        assert np.array_equal(o[:, 0].astype(np.int32), t)
    snap = eng.snapshot()
    assert snap["counters"]["completed"] == len(toks)
    assert snap["counters"]["submitted"] == len(toks)
    # every dispatched shape came from the batcher's closed universe
    universe = set(ContinuousBatcher((16,), 2).shape_universe())
    assert set(stub.shapes) <= universe


def test_engine_deadline_expiry_is_distinct_error():
    stub = StubModel(delay=0.3)
    eng = ServingEngine(stub, bucket_lens=(16,), max_rows=1,
                        max_queue_depth=8)
    with eng:
        f1 = eng.submit([1, 2, 3])          # occupies the worker
        assert stub.started.wait(10)
        f2 = eng.submit([4, 5], deadline_ms=10)  # expires in queue
        assert f1.result(timeout=30).shape == (3, 1)
        with pytest.raises(DeadlineExceededError):
            f2.result(timeout=30)
    assert eng.stats.count("expired") == 1
    assert eng.stats.count("completed") == 1


def test_engine_queue_full_sheds_with_backpressure():
    stub = StubModel(delay=0.4)
    eng = ServingEngine(stub, bucket_lens=(16,), max_rows=1,
                        max_queue_depth=2)
    with eng:
        first = eng.submit([1])             # drained into the worker
        assert stub.started.wait(10)
        ok = [eng.submit([2]), eng.submit([3])]   # fill the queue
        with pytest.raises(QueueFullError):
            eng.submit([4])
        assert eng.stats.count("rejected_queue_full") == 1
        for f in [first] + ok:
            f.result(timeout=30)            # nothing below the limit lost


def test_engine_rejects_oversize_requests():
    eng = ServingEngine(StubModel(), bucket_lens=(8, 16), max_rows=2)
    with eng:
        with pytest.raises(RequestTooLongError):
            eng.submit(list(range(17)))
    assert eng.stats.count("rejected_too_long") == 1


def test_engine_clean_shutdown_drains_in_flight():
    stub = StubModel(delay=0.05)
    eng = ServingEngine(stub, bucket_lens=(16,), max_rows=1,
                        max_queue_depth=64)
    eng.start()
    futs = [eng.submit([i + 1]) for i in range(10)]
    eng.stop(drain=True, timeout=60)        # returns only when drained
    for i, f in enumerate(futs):
        assert f.result(timeout=0.1)[0, 0] == i + 1
    assert eng.stats.count("completed") == 10
    assert not eng.running
    with pytest.raises(EngineStoppedError):
        eng.submit([1])


def test_engine_abort_fails_pending_loudly():
    stub = StubModel(delay=0.3)
    eng = ServingEngine(stub, bucket_lens=(16,), max_rows=1,
                        max_queue_depth=8)
    eng.start()
    f1 = eng.submit([1, 2])
    assert stub.started.wait(10)
    pending = [eng.submit([3]), eng.submit([4])]
    eng.stop(drain=False, timeout=60)
    assert f1.result(timeout=30).shape == (2, 1)  # in-flight finishes
    for f in pending:
        with pytest.raises(EngineStoppedError):
            f.result(timeout=5)
    assert eng.stats.count("cancelled") == 2


def test_engine_survives_model_failure():
    calls = {"n": 0}

    class Flaky(StubModel):
        def __call__(self, *args):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("boom")
            return super().__call__(*args)

    eng = ServingEngine(Flaky(), bucket_lens=(16,), max_rows=1)
    with eng:
        bad = eng.submit([1, 2, 3])
        with pytest.raises(RuntimeError):
            bad.result(timeout=30)
        ok = eng.submit([4, 5]).result(timeout=30)
        assert ok.shape == (2, 1)
    assert eng.stats.count("failed") == 1
    assert eng.stats.count("completed") == 1


def test_engine_model_failure_spares_carry():
    """A poison BATCH fails only its own requests: leftovers carried
    to the next iteration (never dispatched in the failed batch) must
    still be served."""
    calls = {"n": 0}

    class Flaky(StubModel):
        def __call__(self, *args):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("boom")
            return super().__call__(*args)

    stub = Flaky(delay=0.2)
    eng = ServingEngine(stub, bucket_lens=(16,), max_rows=1,
                        max_queue_depth=8)
    with eng:
        f1 = eng.submit([1] * 10)
        assert stub.started.wait(10)
        f2 = eng.submit([2] * 10)       # 10+10 > 16: r3 becomes carry
        f3 = eng.submit([3] * 10)
        assert f1.result(timeout=30).shape == (10, 1)
        with pytest.raises(RuntimeError):
            f2.result(timeout=30)
        assert f3.result(timeout=30)[0, 0] == 3.0
    assert eng.stats.count("failed") == 1
    assert eng.stats.count("completed") == 2


def test_future_first_write_wins():
    f = InferenceFuture()
    f.set_result(7)
    f.set_exception(RuntimeError("late sweep"))
    assert f.result() == 7              # the sweep must not clobber it


def test_future_callbacks_run_outside_lock_and_report_errors():
    """Done-callbacks are snapshot under the future's lock and invoked
    OUTSIDE it (the mxlint lock-callback contract): a callback that
    reenters the future — or raises — must neither deadlock nor lose
    the result, and a raising observer leaves a
    ``future_callback_error`` event."""
    from mxnet_tpu.telemetry import events as _events

    records = []
    _events.add_tap(records.append)
    try:
        f = InferenceFuture()
        f.trace_id = "req-reentrant"
        seen = []

        def reentrant(fut):
            # reentry: registering ANOTHER callback from inside a
            # callback takes the future's lock again — deadlocks if
            # callbacks ran under it
            fut.add_done_callback(lambda g: seen.append(g.result()))

        def broken(fut):
            raise RuntimeError("broken observer")

        f.add_done_callback(reentrant)
        f.add_done_callback(broken)
        f.set_result(41)
        assert f.result(timeout=1) == 41
        assert seen == [41]
        errs = [r for r in records if r["event"] == "future_callback_error"]
        assert errs and "broken observer" in errs[0]["error"]
        assert errs[0]["trace_id"] == "req-reentrant"
    finally:
        _events.remove_tap(records.append)


def test_reentrant_done_callback_cannot_deadlock_submit():
    """ISSUE-6 satellite regression: a done-callback that REENTERS
    ``engine.submit`` runs on the engine worker thread the moment it
    fulfils the future — if shed/expiry/completion notifications ran
    under the queue lock, this would deadlock the worker against its
    own admission path. Must complete well inside the timeout."""
    eng = ServingEngine(StubModel(), bucket_lens=(8,), max_rows=2)
    with eng:
        chained = []
        done = threading.Event()

        def resubmit(fut):
            # executes on the worker thread, mid-completion sweep
            chained.append(eng.submit([7, 8, 9]))
            done.set()

        first = eng.submit([1, 2, 3, 4])
        first.add_done_callback(resubmit)
        np.testing.assert_allclose(
            np.asarray(first.result(timeout=30)).reshape(-1)[:4],
            [1, 2, 3, 4])
        assert done.wait(30)
        np.testing.assert_allclose(
            np.asarray(chained[0].result(timeout=30)).reshape(-1)[:3],
            [7, 8, 9])
    assert eng.stats.count("completed") == 2


def test_engine_reset_stats_separates_windows():
    eng = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=1)
    with eng:
        eng.infer([1, 2], timeout=30)
        assert eng.stats.count("completed") == 1
        eng.reset_stats()
        assert eng.stats.count("completed") == 0
        eng.infer([3], timeout=30)
        assert eng.stats.count("completed") == 1
        assert eng.snapshot()["queue_depth"] == 0


# ---------------------------------------------------------------------------
# the acceptance golden: real model, 8 concurrent clients, solo parity
# ---------------------------------------------------------------------------

def test_concurrent_clients_parity_and_stats():
    from mxnet_tpu.gluon.model_zoo.bert import bert_serving_entry

    net = _tiny_bert()
    eng = ServingEngine(bert_serving_entry(net), bucket_lens=(16,),
                        max_rows=4, max_queue_depth=128)
    rs = np.random.RandomState(7)
    lens = [3, 5, 8, 11, 13, 15]            # few distinct solo shapes
    n_clients, per_client = 8, 4
    results = {}
    errors = []

    def client(cid):
        rc = np.random.RandomState(100 + cid)
        try:
            for j in range(per_client):
                toks = rc.randint(1, 60, lens[(cid + j) % len(lens)]) \
                    .astype(np.int32)
                out = eng.infer(toks, timeout=300)
                results[(cid, j)] = (toks, out)
        except Exception as e:  # surfaced below — a lost response fails
            errors.append((cid, e))

    with eng:
        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(600)
    assert not errors, errors
    assert len(results) == n_clients * per_client   # zero lost responses

    # per-request parity vs the same tokens run SOLO through the model
    solo_cache = {}
    for (cid, j), (toks, out) in sorted(results.items()):
        key = toks.tobytes()
        if key not in solo_cache:
            one = nd.array(toks[None, :], dtype="int32")
            tt = nd.zeros((1, len(toks)), dtype="int32")
            with mx.autograd.predict_mode():
                solo_cache[key] = net(one, tt).asnumpy()[0]
        np.testing.assert_allclose(out, solo_cache[key], rtol=2e-4,
                                   atol=2e-4,
                                   err_msg=f"client {cid} req {j}")

    snap = eng.snapshot()
    c = snap["counters"]
    assert c["completed"] == n_clients * per_client
    assert c["submitted"] == c["completed"]  # nothing shed in this run
    lat = snap["latency"]["total"]
    assert lat["count"] == c["completed"]
    assert 0 < lat["p50_ms"] <= lat["p99_ms"] <= lat["max_ms"]
    assert snap["packing_efficiency"] is not None
    assert snap["queue_depth"] == 0
    assert c["batches"] >= 1 and c["compiles"] >= 1


def test_loaded_traffic_packs_densely():
    """The packing acceptance number: under sustained load (the queue
    holds work while a batch computes — the continuous-batching steady
    state) the synthetic variable-length mix packs > 0.8 of dispatched
    slots with real tokens."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    from serve_loadgen import run_load

    stub = StubModel(delay=0.02)   # compute window lets the queue fill
    eng = ServingEngine(stub, bucket_lens=(64,), max_rows=4,
                        max_queue_depth=256)
    with eng:
        report = run_load(eng, n_clients=12, requests_per_client=8,
                          min_len=16, max_len=64, vocab=60)
    assert report["completed"] == 96
    assert report["errors"] == 0 and report["shed"] == 0
    snap = report["engine"]
    assert snap["packing_efficiency"] > 0.8, snap
    lat = report["p50_ms"], report["p99_ms"]
    assert 0 < lat[0] <= lat[1]
    assert snap["latency"]["queue"]["count"] == 96


@pytest.mark.slow
def test_bench_serving_leg_smoke():
    """bench.py BENCH_MODEL=serving end-to-end at toy size: emits the
    serving metric line with latency percentiles and packing stats."""
    import json
    import os
    import subprocess
    import sys

    env = dict(os.environ, BENCH_MODEL="serving", BENCH_SEQLEN="32",
               BENCH_VOCAB="200", BENCH_SERVE_UNITS="32",
               BENCH_SERVE_LAYERS="1", BENCH_SERVE_HEADS="2",
               BENCH_SERVE_CLIENTS="8", BENCH_SERVE_REQS="4",
               BENCH_SERVE_ROWS="4", BENCH_SERVE_BUCKETS="8,32",
               JAX_PLATFORMS="cpu")
    bench = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    r = subprocess.run([sys.executable, bench], env=env,
                       capture_output=True, text=True, timeout=560)
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads([ln for ln in r.stdout.splitlines()
                      if ln.startswith('{"metric"')][-1])
    assert rec["metric"] == "bert_serving_requests_per_sec_per_chip"
    assert rec["value"] > 0
    assert rec["requests"] == 32          # zero lost under the limit
    assert rec["p50_ms"] > 0 and rec["p99_ms"] >= rec["p50_ms"]
    assert 0 < rec["packing_efficiency"] <= 1.0


@pytest.mark.slow
def test_bench_serving_router_leg_smoke():
    """bench.py BENCH_MODEL=serving_router end-to-end at toy size:
    2 engines behind the router, per-engine share + failover count in
    the metric line, aggregated-/metrics reconciliation asserted."""
    import json
    import os
    import subprocess
    import sys

    env = dict(os.environ, BENCH_MODEL="serving_router",
               BENCH_SEQLEN="32", BENCH_VOCAB="200",
               BENCH_SERVE_UNITS="32", BENCH_SERVE_LAYERS="1",
               BENCH_SERVE_HEADS="2", BENCH_SERVE_CLIENTS="6",
               BENCH_SERVE_REQS="4", BENCH_SERVE_ROWS="2",
               BENCH_SERVE_BUCKETS="8,32", JAX_PLATFORMS="cpu")
    bench = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    r = subprocess.run([sys.executable, bench], env=env,
                       capture_output=True, text=True, timeout=560)
    assert r.returncode == 0, r.stderr[-2000:]
    recs = {rec["metric"]: rec for rec in
            (json.loads(ln) for ln in r.stdout.splitlines()
             if ln.startswith('{"metric"'))}
    rec = recs["bert_serving_router_requests_per_sec"]
    assert rec["value"] > 0
    assert rec["requests"] == 24
    assert rec["engines"] == 2 and rec["engines_up"] == 2
    assert set(rec["per_engine"]) == {"e0", "e1"}
    assert abs(sum(rec["per_engine"].values()) - 1.0) < 0.01
    assert rec["failover"] == 0
    assert rec["telemetry_reconciled"] is True
    # the wire-vs-JSON A/B record from the same leg: binary framing
    # must beat JSON on serialized bytes and dispatch overhead
    wab = recs["bert_serving_router_wire_requests_per_sec"]
    assert wab["value"] > 0 and wab["transport"] == "wire"
    assert wab["wire"]["bytes_per_request"] \
        < wab["json"]["bytes_per_request"]
    assert wab["wire"]["dispatch_overhead_p50_ms"] \
        < wab["json"]["dispatch_overhead_p50_ms"]
    assert wab["bytes_per_request_ratio"] < 1.0
    assert wab["wire"]["fallbacks"] == 0


@pytest.mark.slow
def test_bench_packed_causal_leg_smoke():
    """bench.py BENCH_MODEL=causal_lm (the packed CAUSAL ROADMAP
    follow-up) runs end-to-end at toy size."""
    import json
    import os
    import subprocess
    import sys

    env = dict(os.environ, BENCH_MODEL="causal_lm", BENCH_STEPS="2",
               BENCH_CHAIN="1", BENCH_WINDOWS="1", BENCH_BATCH="2",
               BENCH_SEQLEN="32", BENCH_PACK_ROWLEN="64",
               BENCH_VOCAB="200", BENCH_LM_UNITS="32",
               BENCH_LM_LAYERS="1", BENCH_LM_HEADS="2",
               JAX_PLATFORMS="cpu")
    bench = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    r = subprocess.run([sys.executable, bench], env=env,
                       capture_output=True, text=True, timeout=560)
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads([ln for ln in r.stdout.splitlines()
                      if ln.startswith('{"metric"')][-1])
    assert rec["metric"] == "causal_lm_train_tokens_per_sec_per_chip"
    assert rec["causal"] is True and rec["packed"] is True
    assert rec["packing_efficiency"] >= 0.9
    assert rec["valid_tokens_per_sec"] > 0


# ---------------------------------------------------------------------------
# engine-labeled metric families (ROADMAP per-chip router metrics)
# ---------------------------------------------------------------------------

def test_engine_metric_families_disjoint_per_engine():
    """REGRESSION for the shared-family collision: two engines in one
    process used to double-count one unlabeled family set; with
    engine_id labels each engine's counters stay disjoint and each
    equals that engine's own window counts exactly."""
    from mxnet_tpu.telemetry import REGISTRY

    a = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=1,
                      engine_id="disjoint-a")
    b = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=1,
                      engine_id="disjoint-b")
    with a, b:
        for _ in range(3):
            a.infer([1, 2], timeout=30)
        for _ in range(5):
            b.infer([3], timeout=30)
    req_total = REGISTRY.counter("mxnet_tpu_serving_requests_total", "",
                                 ("engine_id", "event"))
    for eng, n in ((a, 3), (b, 5)):
        for event in ("submitted", "completed"):
            child = req_total.labels(engine_id=eng.engine_id, event=event)
            assert child.value == n, (eng.engine_id, event, child.value)
    lat = REGISTRY.get("mxnet_tpu_serving_latency_ms")
    assert lat.labels(engine_id="disjoint-a", stage="total").count == 3
    assert lat.labels(engine_id="disjoint-b", stage="total").count == 5
    # the rendered exposition carries both engines' labeled children
    text = REGISTRY.render_prometheus()
    assert ('mxnet_tpu_serving_requests_total{engine_id="disjoint-a",'
            'event="completed"} 3') in text
    assert ('mxnet_tpu_serving_requests_total{engine_id="disjoint-b",'
            'event="completed"} 5') in text


# ---------------------------------------------------------------------------
# multi-engine router: routing, failover, shed, scoreboard
# ---------------------------------------------------------------------------

def _stub_engine(engine_id, delay=0.0, **kw):
    kw.setdefault("bucket_lens", (16,))
    kw.setdefault("max_rows", 2)
    return ServingEngine(StubModel(delay=delay), engine_id=engine_id, **kw)


def test_router_roundtrip_distribution_and_snapshot():
    a = _stub_engine("rt-a")
    b = _stub_engine("rt-b")
    router = ServingRouter(engines=[a, b], poll_interval_s=0.2)
    rs = np.random.RandomState(5)
    with a, b, router:
        toks = [rs.randint(1, 60, n).astype(np.int32)
                for n in (3, 7, 5, 9, 2, 6, 4, 8)]
        outs = [router.submit(t).result(timeout=30) for t in toks]
        for t, o in zip(toks, outs):
            assert np.array_equal(o[:, 0].astype(np.int32), t)
        snap = router.snapshot()
    c = snap["counters"]
    assert c["completed"] == len(toks) == c["submitted"]
    dispatched = {eid: row["dispatched"]
                  for eid, row in snap["engines"].items()}
    assert sum(dispatched.values()) == len(toks)
    # least-outstanding over sequential submits: both engines serve
    assert all(n > 0 for n in dispatched.values()), dispatched
    assert snap["engines_up"] == 2
    assert snap["latency"]["total"]["count"] == len(toks)


def test_router_failover_requeues_to_sibling():
    """An engine dying mid-load (stop drain=False) fails its
    admitted-but-undispatched requests with EngineStoppedError; the
    router re-queues them to the sibling — zero client-visible
    failures, failover counted per failed engine."""
    from mxnet_tpu.telemetry import REGISTRY

    live = _stub_engine("fo-live", max_rows=1)
    dying = _stub_engine("fo-dying", max_rows=1)
    live.start()
    dying.start()
    # poll slow enough that DISPATCH discovers the death, not the poll
    router = ServingRouter(engines=[live, dying], poll_interval_s=30.0)
    router.start()
    try:
        dying.stop(drain=False)
        futs = [router.submit([7, 8]) for _ in range(8)]
        outs = [f.result(timeout=30) for f in futs]
        assert all(o[0, 0] == 7.0 for o in outs)      # nothing lost
        snap = router.snapshot()
        assert snap["counters"]["completed"] == 8
        assert snap["counters"]["requeued"] >= 1
        assert snap["engines"]["fo-dying"]["routable"] is False
        fo = REGISTRY.counter("mxnet_tpu_router_failover_total", "",
                              ("engine_id",))
        assert fo.labels(engine_id="fo-dying").value >= 1
    finally:
        router.stop()
        live.stop()


def test_router_sheds_when_all_engines_down():
    """Fleet down => submit sheds with a DISTINCT error (and the shed
    trace is force-kept, same contract as engine sheds)."""
    from mxnet_tpu.telemetry import spans

    eng = _stub_engine("down-1")
    eng.start()
    router = ServingRouter(engines=[eng], poll_interval_s=0.1,
                           health_fail_after=1)
    router.start()
    try:
        assert router.infer([1, 2], timeout=30)[0, 0] == 1.0
        eng.stop(drain=True)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if not router.snapshot()["engines"]["down-1"]["routable"]:
                break
            time.sleep(0.05)
        snap = router.snapshot()
        assert snap["engines_up"] == 0, snap["engines"]
        with pytest.raises(NoEngineAvailableError):
            router.submit([3, 4])
        assert router.count("shed_no_engine") == 1
        kept = spans.traces_summary()["kept"]
        shed = [k for k in kept if k["root"] == "router/request"
                and k["status"] == "error"]
        assert shed, kept
    finally:
        router.stop()


def test_router_engine_overflow_fails_over_then_sheds():
    """A saturated engine (its own queue at bound) is an ENGINE
    failure from the router's view: the request retries a sibling;
    with no sibling left it sheds LOUDLY — and a stopped router
    refuses new work with a distinct error."""
    slow = ServingEngine(StubModel(delay=0.3), bucket_lens=(16,),
                         max_rows=1, max_queue_depth=1,
                         engine_id="ovf-slow")
    roomy = _stub_engine("ovf-roomy", max_rows=1)
    router = ServingRouter(engines=[slow, roomy], poll_interval_s=30.0)
    with slow, roomy, router:
        # saturate: one in flight + one queued at the slow engine, the
        # rest overflow — every overflow must land on the sibling
        futs = [router.submit([9, 9]) for _ in range(10)]
        outs = [f.result(timeout=60) for f in futs]
        assert all(o[0, 0] == 9.0 for o in outs)       # nothing lost
        snap = router.snapshot()
        assert snap["counters"]["completed"] == 10

    # single saturated engine, no sibling: the shed is explicit
    slow2 = ServingEngine(StubModel(delay=0.3), bucket_lens=(16,),
                          max_rows=1, max_queue_depth=1,
                          engine_id="ovf-solo")
    router2 = ServingRouter(engines=[slow2], poll_interval_s=30.0)
    with slow2, router2:
        futs, shed = [], 0
        for _ in range(8):
            futs.append(router2.submit([3]))
        for f in futs:
            try:
                f.result(timeout=60)
            except NoEngineAvailableError:
                shed += 1
        assert shed >= 1                 # overflow shed, not silent
        assert shed == router2.count("shed_no_engine")
        assert router2.count("completed") == len(futs) - shed
    with pytest.raises(EngineStoppedError):
        router2.submit([5])
    assert router2.count("rejected_stopped") == 1


def test_router_scoreboard_events_and_recovery(tmp_path):
    """up→down→up transitions emit router_engine_state events and the
    scoreboard gauges follow."""
    from mxnet_tpu.telemetry import REGISTRY, events

    events.configure(str(tmp_path / "router.jsonl"))
    try:
        eng = _stub_engine("sb-1")
        eng.start()
        srv = eng.expose()
        router = ServingRouter(poll_interval_s=0.1, health_fail_after=1)
        # remote seat against the engine's own exposition endpoint
        router.add_engine("sb-remote", f"http://127.0.0.1:{srv.port}")
        router.start()
        try:
            out = router.infer([5, 6], timeout=30)
            assert out.shape == (2, 1)
            eng.stop(drain=True)         # endpoint goes away
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                row = router.snapshot()["engines"]["sb-remote"]
                if not row["routable"]:
                    break
                time.sleep(0.05)
            assert not router.snapshot()["engines"]["sb-remote"][
                "routable"]
            up = REGISTRY.gauge("mxnet_tpu_router_engine_up", "",
                                ("engine_id",))
            assert up.labels(engine_id="sb-remote").value == 0
        finally:
            router.stop()
        log_path = events.get_log().path
    finally:
        events.configure(None)
    states = events.read_events(log_path, event="router_engine_state")
    assert any(e["engine_id"] == "sb-remote" and e["state"] == "down"
               for e in states), states


def test_engine_pool_modes():
    stub = StubModel()
    outs = {}
    for pool in ("tokens", "mean", "cls"):
        eng = ServingEngine(stub, bucket_lens=(16,), max_rows=1, pool=pool)
        with eng:
            outs[pool] = eng.infer([2, 4, 6], timeout=30)
    assert outs["tokens"].shape == (3, 1)
    assert outs["mean"].shape == (1,) and outs["mean"][0] == 4.0
    assert outs["cls"].shape == (1,) and outs["cls"][0] == 2.0
