"""mx.np / mx.npx — the deep-NumPy frontend.

Mirrors the reference's tests/python/unittest/test_numpy_op.py +
test_numpy_ndarray.py strategy: golden comparisons against real NumPy
for the function surface (the table covers every registered ``_npi_*``
op so the recorded coverage gate owns them), NumPy-semantics checks on
the ndarray type (zero-dim, boolean masks, bool comparisons), autograd
through np ops, classic<->np interop, and Gluon under ``npx.set_np``.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np, npx
from mxnet_tpu.test_utils import assert_almost_equal, device_tols

RTOL, ATOL = device_tols("float32")
RS = onp.random.RandomState(7)


def _a(*shape):
    return RS.randn(*shape).astype(onp.float32)


def _pos(*shape):
    return (RS.rand(*shape) + 0.5).astype(onp.float32)


def _i(*shape, high=5):
    return RS.randint(0, high, size=shape).astype(onp.int32)


def _chk(mx_out, onp_out, rtol=None, atol=None):
    if isinstance(mx_out, (list, tuple)):
        assert isinstance(onp_out, (list, tuple)) and len(mx_out) == len(onp_out)
        for m, o in zip(mx_out, onp_out):
            _chk(m, o, rtol, atol)
        return
    got = mx_out.asnumpy() if hasattr(mx_out, "asnumpy") else onp.asarray(mx_out)
    want = onp.asarray(onp_out)
    assert got.shape == want.shape, f"shape {got.shape} vs {want.shape}"
    if want.dtype == onp.bool_:
        assert (got == want).all()
    else:
        assert_almost_equal(got.astype(onp.float64), want.astype(onp.float64),
                            rtol=rtol or max(RTOL, 1e-4),
                            atol=atol or max(ATOL, 1e-4))


# ---------------------------------------------------------------------------
# golden table: every mx.np function vs numpy. Each _npi_* op is
# dispatched by at least one row (coverage-gate contract).
# ---------------------------------------------------------------------------
_X = _a(3, 4)
_Y = _a(3, 4)
_P = _pos(3, 4)
_SQ = (lambda m: m @ m.T + 3 * onp.eye(3).astype(onp.float32))(_a(3, 3))
_V = _a(6)
_I8 = _i(3, 4, high=4)
_B = RS.rand(3, 4) > 0.5

CASES = [
    # binaries (incl. every _npi binary)
    ("add", lambda n: n.add(_X, _Y), onp.add(_X, _Y)),
    ("subtract", lambda n: n.subtract(_X, _Y), onp.subtract(_X, _Y)),
    ("multiply", lambda n: n.multiply(_X, _Y), onp.multiply(_X, _Y)),
    ("divide", lambda n: n.divide(_X, _P), onp.divide(_X, _P)),
    ("floor_divide", lambda n: n.floor_divide(_X, _P), _X // _P),
    ("mod", lambda n: n.mod(_X, _P), onp.mod(_X, _P)),
    ("fmod", lambda n: n.fmod(_X, _P), onp.fmod(_X, _P)),
    ("power", lambda n: n.power(_P, _Y), onp.power(_P, _Y)),
    ("maximum", lambda n: n.maximum(_X, _Y), onp.maximum(_X, _Y)),
    ("minimum", lambda n: n.minimum(_X, _Y), onp.minimum(_X, _Y)),
    ("fmax", lambda n: n.fmax(_X, _Y), onp.fmax(_X, _Y)),
    ("fmin", lambda n: n.fmin(_X, _Y), onp.fmin(_X, _Y)),
    ("hypot", lambda n: n.hypot(_X, _Y), onp.hypot(_X, _Y)),
    ("arctan2", lambda n: n.arctan2(_X, _P), onp.arctan2(_X, _P)),
    ("logaddexp", lambda n: n.logaddexp(_X, _Y), onp.logaddexp(_X, _Y)),
    ("logaddexp2", lambda n: n.logaddexp2(_X, _Y), onp.logaddexp2(_X, _Y)),
    ("copysign", lambda n: n.copysign(_P, _X), onp.copysign(_P, _X)),
    ("ldexp", lambda n: n.ldexp(_X, _I8), onp.ldexp(_X, _I8)),
    ("heaviside", lambda n: n.heaviside(_X, _P), onp.heaviside(_X, _P)),
    ("gcd", lambda n: n.gcd(_I8, _I8 + 2), onp.gcd(_I8, _I8 + 2)),
    ("lcm", lambda n: n.lcm(_I8 + 1, _I8 + 2), onp.lcm(_I8 + 1, _I8 + 2)),
    ("bitwise_and", lambda n: n.bitwise_and(_I8, _I8 + 1),
     onp.bitwise_and(_I8, _I8 + 1)),
    ("bitwise_or", lambda n: n.bitwise_or(_I8, _I8 + 1),
     onp.bitwise_or(_I8, _I8 + 1)),
    ("bitwise_xor", lambda n: n.bitwise_xor(_I8, _I8 + 1),
     onp.bitwise_xor(_I8, _I8 + 1)),
    ("invert", lambda n: n.invert(_I8), onp.invert(_I8)),
    ("left_shift", lambda n: n.left_shift(_I8, 2), onp.left_shift(_I8, 2)),
    ("right_shift", lambda n: n.right_shift(_I8, 1), onp.right_shift(_I8, 1)),
    # comparisons — must be bool dtype
    ("equal", lambda n: n.equal(_I8, 2), onp.equal(_I8, 2)),
    ("not_equal", lambda n: n.not_equal(_I8, 2), onp.not_equal(_I8, 2)),
    ("greater", lambda n: n.greater(_X, _Y), onp.greater(_X, _Y)),
    ("greater_equal", lambda n: n.greater_equal(_X, _Y),
     onp.greater_equal(_X, _Y)),
    ("less", lambda n: n.less(_X, _Y), onp.less(_X, _Y)),
    ("less_equal", lambda n: n.less_equal(_X, _Y), onp.less_equal(_X, _Y)),
    ("logical_and", lambda n: n.logical_and(_B, ~_B), onp.logical_and(_B, ~_B)),
    ("logical_or", lambda n: n.logical_or(_B, ~_B), onp.logical_or(_B, ~_B)),
    ("logical_xor", lambda n: n.logical_xor(_B, _B), onp.logical_xor(_B, _B)),
    ("logical_not", lambda n: n.logical_not(_B), onp.logical_not(_B)),
    ("isclose", lambda n: n.isclose(_X, _X + 1e-8), onp.isclose(_X, _X + 1e-8)),
    ("signbit", lambda n: n.signbit(_X), onp.signbit(_X)),
    # unaries
    ("exp2", lambda n: n.exp2(_X), onp.exp2(_X)),
    ("nan_to_num", lambda n: n.nan_to_num(
        n.array([[1.0, onp.nan, onp.inf]])),
     onp.nan_to_num(onp.array([[1.0, onp.nan, onp.inf]], onp.float32))),
    ("positive", lambda n: n.positive(_X), _X),
    ("deg2rad", lambda n: n.deg2rad(_X), onp.deg2rad(_X)),
    # reductions / statistics
    ("std", lambda n: n.std(_X, axis=1), onp.std(_X, axis=1)),
    ("std_ddof", lambda n: n.std(_X, ddof=1), onp.std(_X, ddof=1)),
    ("var", lambda n: n.var(_X, axis=0), onp.var(_X, axis=0)),
    ("median", lambda n: n.median(_X, axis=1), onp.median(_X, axis=1)),
    ("quantile", lambda n: n.quantile(_X, 0.25), onp.quantile(_X, 0.25)),
    ("percentile", lambda n: n.percentile(_X, 75, axis=0),
     onp.percentile(_X, 75, axis=0)),
    ("average", lambda n: n.average(_X, axis=0, weights=_P[:, 0]),
     onp.average(_X, axis=0, weights=_P[:, 0])),
    ("cumprod", lambda n: n.cumprod(_P, axis=1), onp.cumprod(_P, axis=1)),
    ("all", lambda n: n.all(_B, axis=0), onp.all(_B, axis=0)),
    ("any", lambda n: n.any(_B, axis=1), onp.any(_B, axis=1)),
    ("count_nonzero", lambda n: n.count_nonzero(_I8, axis=1),
     onp.count_nonzero(_I8, axis=1)),
    ("ptp", lambda n: n.ptp(_X, axis=1), onp.ptp(_X, axis=1)),
    ("diff", lambda n: n.diff(_X, axis=1), onp.diff(_X, axis=1)),
    ("ediff1d", lambda n: n.ediff1d(_V), onp.ediff1d(_V)),
    ("bincount", lambda n: n.bincount(n.array(_I8.ravel()), minlength=6),
     onp.bincount(_I8.ravel(), minlength=6)),
    ("nanmax", lambda n: n.nanmax(_X, axis=0), onp.nanmax(_X, axis=0)),
    ("nanmin", lambda n: n.nanmin(_X, axis=0), onp.nanmin(_X, axis=0)),
    ("nanmean", lambda n: n.nanmean(_X, axis=1), onp.nanmean(_X, axis=1)),
    # shape / rearrangement
    ("roll", lambda n: n.roll(_X, 2, axis=1), onp.roll(_X, 2, axis=1)),
    ("rot90", lambda n: n.rot90(_X), onp.rot90(_X)),
    ("moveaxis", lambda n: n.moveaxis(n.array(_a(2, 3, 4)), 0, 2).shape,
     onp.zeros((3, 4, 2))),
    ("tril", lambda n: n.tril(_X), onp.tril(_X)),
    ("triu", lambda n: n.triu(_X, 1), onp.triu(_X, 1)),
    ("trace", lambda n: n.trace(_X), onp.trace(_X)),
    ("diagonal", lambda n: n.diagonal(_X, 1), onp.diagonal(_X, 1)),
    ("diagflat", lambda n: n.diagflat(_V[:3]), onp.diagflat(_V[:3])),
    ("searchsorted", lambda n: n.searchsorted(n.array(onp.sort(_V)), 0.1),
     onp.searchsorted(onp.sort(_V), onp.float32(0.1))),
    ("take_along_axis", lambda n: n.take_along_axis(
        _X, n.array(onp.argsort(_X, 1)), 1),
     onp.take_along_axis(_X, onp.argsort(_X, 1), 1)),
    ("pad", lambda n: n.pad(_X, ((1, 1), (2, 0))),
     onp.pad(_X, ((1, 1), (2, 0)))),
    ("append", lambda n: n.append(_X, _Y, axis=0), onp.append(_X, _Y, axis=0)),
    ("where3", lambda n: n.where(n.array(_B), _X, _Y), onp.where(_B, _X, _Y)),
    ("interp", lambda n: n.interp(n.array([0.5, 1.5]), n.array([0.0, 1.0, 2.0]),
                                  n.array([10.0, 20.0, 30.0])),
     onp.interp([0.5, 1.5], [0, 1, 2], [10.0, 20.0, 30.0]).astype("f")),
    ("cross", lambda n: n.cross(_a(4, 3), _a(4, 3), axis=1),
     None),  # filled below
    ("kron", lambda n: n.kron(_X[:2, :2], _Y[:2, :2]),
     onp.kron(_X[:2, :2], _Y[:2, :2])),
    ("flip", lambda n: n.flip(_X), onp.flip(_X)),
    ("fliplr", lambda n: n.fliplr(_X), onp.fliplr(_X)),
    ("flipud", lambda n: n.flipud(_X), onp.flipud(_X)),
    # contractions
    ("dot", lambda n: n.dot(_X, _Y.T), onp.dot(_X, _Y.T)),
    ("vdot", lambda n: n.vdot(_X, _Y), onp.vdot(_X, _Y)),
    ("inner", lambda n: n.inner(_X, _Y), onp.inner(_X, _Y)),
    ("outer", lambda n: n.outer(_V, _V), onp.outer(_V, _V)),
    ("matmul", lambda n: n.matmul(_X, _Y.T), onp.matmul(_X, _Y.T)),
    ("tensordot", lambda n: n.tensordot(_X, _Y, axes=([1], [1])),
     onp.tensordot(_X, _Y, axes=([1], [1]))),
    ("einsum", lambda n: n.einsum("ij,kj->ik", _X, _Y),
     onp.einsum("ij,kj->ik", _X, _Y)),
]
# fill the cross golden with the same operands the lambda regenerates —
# use fixed arrays instead
_C1, _C2 = _a(4, 3), _a(4, 3)
CASES = [c if c[0] != "cross" else
         ("cross", lambda n: n.cross(_C1, _C2, axis=1),
          onp.cross(_C1, _C2, axis=1)) for c in CASES]


@pytest.mark.parametrize("case", CASES, ids=[c[0] for c in CASES])
def test_np_golden(case):
    name, fn, want = case
    got = fn(np)
    if name == "moveaxis":
        assert got == (3, 4, 2)
        return
    _chk(got, want)


def test_np_creation():
    _chk(np.zeros((2, 3)), onp.zeros((2, 3), onp.float32))
    _chk(np.ones(4), onp.ones(4, onp.float32))
    _chk(np.full((2, 2), 7.0), onp.full((2, 2), 7.0, onp.float32))
    _chk(np.arange(2, 10, 2), onp.arange(2, 10, 2))
    _chk(np.linspace(0, 1, 5), onp.linspace(0, 1, 5).astype(onp.float32))
    _chk(np.logspace(0, 2, 3), onp.logspace(0, 2, 3).astype(onp.float32))
    _chk(np.eye(3, k=1), onp.eye(3, k=1, dtype=onp.float32))
    _chk(np.identity(2), onp.identity(2, onp.float32))
    _chk(np.zeros_like(np.array(_X)), onp.zeros_like(_X))
    _chk(np.ones_like(np.array(_X)), onp.ones_like(_X))
    _chk(np.full_like(np.array(_X), 3.5), onp.full_like(_X, 3.5))
    # float64 python input downcasts to f32 (mx.np default-dtype rule)
    assert np.array([1.5, 2.5]).dtype == onp.float32
    assert np.array(onp.ones(3, onp.int64)).dtype == onp.int64


def test_np_manipulation():
    x = np.array(_a(2, 3, 4))
    _chk(x.reshape(4, 6), x.asnumpy().reshape(4, 6))
    _chk(np.ravel(x), x.asnumpy().ravel())
    _chk(x.flatten(), x.asnumpy().ravel())  # numpy flatten, NOT mx Flatten
    _chk(np.concatenate([x, x], axis=1),
         onp.concatenate([x.asnumpy()] * 2, axis=1))
    _chk(np.concatenate([x, x], axis=None),
         onp.concatenate([x.asnumpy()] * 2, axis=None))
    _chk(np.stack([x, x], axis=1), onp.stack([x.asnumpy()] * 2, axis=1))
    _chk(np.vstack([_X, _Y]), onp.vstack([_X, _Y]))
    _chk(np.hstack([_X, _Y]), onp.hstack([_X, _Y]))
    _chk(np.dstack([_X, _Y]), onp.dstack([_X, _Y]))
    _chk(np.column_stack([_V, _V]), onp.column_stack([_V, _V]))
    for got, want in zip(np.split(np.array(_V), 3),
                         onp.split(_V, 3)):
        _chk(got, want)
    for got, want in zip(np.array_split(np.array(_a(7)), 3),
                         onp.array_split(_a(7) * 0 + _a(7), 3)):
        assert got.shape == want.shape
    for got, want in zip(np.hsplit(np.array(_X), 2), onp.hsplit(_X, 2)):
        _chk(got, want)
    for got, want in zip(np.vsplit(np.array(_a(4, 2)), 2),
                         onp.vsplit(_a(4, 2) * 0 + _a(4, 2), 2)):
        assert got.shape == want.shape
    _chk(np.broadcast_to(np.array(_V), (3, 6)), onp.broadcast_to(_V, (3, 6)))
    a, b = np.broadcast_arrays(np.array(_V), np.array(_a(3, 1)))
    assert a.shape == b.shape == (3, 6)
    _chk(np.atleast_2d(np.array(_V)), onp.atleast_2d(_V))
    assert np.atleast_3d(np.array(_X)).shape == (3, 4, 1)
    m = np.meshgrid(np.array([1.0, 2.0]), np.array([3.0, 4.0, 5.0]))
    mo = onp.meshgrid(onp.array([1.0, 2.0]), onp.array([3.0, 4.0, 5.0]))
    _chk(m[0], mo[0].astype("f")), _chk(m[1], mo[1].astype("f"))
    mi = np.meshgrid(np.array([1.0, 2.0]), np.array([3.0, 4.0, 5.0]),
                     indexing="ij")
    moi = onp.meshgrid(onp.array([1.0, 2.0]), onp.array([3.0, 4.0, 5.0]),
                       indexing="ij")
    _chk(mi[0], moi[0].astype("f")), _chk(mi[1], moi[1].astype("f"))


def test_np_nonzero_unique_histogram():
    x = onp.array([[0, 2, 0], [3, 0, 4]], onp.float32)
    got = np.nonzero(np.array(x))
    want = onp.nonzero(x)
    assert len(got) == 2
    for g, w in zip(got, want):
        assert (g.asnumpy() == w).all()
    _chk(np.flatnonzero(np.array(x)), onp.flatnonzero(x))
    u = onp.array([3, 1, 2, 3, 1], onp.int32)
    _chk(np.unique(np.array(u)), onp.unique(u))
    vals, counts = np.unique(np.array(u), return_counts=True)
    wv, wc = onp.unique(u, return_counts=True)
    assert (vals.asnumpy() == wv).all() and (counts.asnumpy() == wc).all()
    hist, edges = np.histogram(np.array(_V), bins=4)
    whist, wedges = onp.histogram(_V, bins=4)
    assert (hist.asnumpy() == whist).all()
    _chk(edges, wedges.astype("f"))


def test_np_linalg():
    sq = _SQ
    _chk(np.linalg.inv(np.array(sq)), onp.linalg.inv(sq), rtol=1e-3, atol=1e-3)
    _chk(np.linalg.det(np.array(sq)), onp.linalg.det(sq), rtol=1e-3, atol=1e-2)
    sgn, ld = np.linalg.slogdet(np.array(sq))
    wsgn, wld = onp.linalg.slogdet(sq)
    assert float(sgn) == wsgn and abs(float(ld) - wld) < 1e-2
    _chk(np.linalg.cholesky(np.array(sq)), onp.linalg.cholesky(sq),
         rtol=1e-3, atol=1e-3)
    b = _a(3, 2)
    _chk(np.linalg.solve(np.array(sq), np.array(b)), onp.linalg.solve(sq, b),
         rtol=1e-3, atol=1e-3)
    w, v = np.linalg.eigh(np.array(sq))
    ww = onp.linalg.eigh(sq)[0]
    _chk(w, ww, rtol=1e-3, atol=1e-3)
    # eigvalsh matches eigh values
    _chk(np.linalg.eigvalsh(np.array(sq)), ww, rtol=1e-3, atol=1e-3)
    # svd/qr: reconstruction + orthonormality (sign-convention-free)
    a = _a(4, 3)
    u, s, vh = np.linalg.svd(np.array(a))
    rec = (u.asnumpy() * s.asnumpy()) @ vh.asnumpy()
    assert_almost_equal(rec, a, rtol=1e-3, atol=1e-3)
    assert (onp.sort(s.asnumpy())[::-1] == s.asnumpy()).all()
    q, r = np.linalg.qr(np.array(a))
    assert_almost_equal(q.asnumpy() @ r.asnumpy(), a, rtol=1e-3, atol=1e-3)
    assert_almost_equal(q.asnumpy().T @ q.asnumpy(), onp.eye(3),
                        rtol=1e-3, atol=1e-3)
    _chk(np.linalg.pinv(np.array(a)), onp.linalg.pinv(a), rtol=1e-2, atol=1e-2)
    assert int(np.linalg.matrix_rank(np.array(a))) == onp.linalg.matrix_rank(a)
    _chk(np.linalg.matrix_power(np.array(sq), 2),
         onp.linalg.matrix_power(sq, 2), rtol=1e-3, atol=1e-2)
    _chk(np.linalg.multi_dot([np.array(_X), np.array(_Y.T), np.array(_X)]),
         onp.linalg.multi_dot([_X, _Y.T, _X]), rtol=1e-3, atol=1e-3)
    _chk(np.linalg.norm(np.array(_X)), onp.linalg.norm(_X))
    _chk(np.linalg.norm(np.array(_X), axis=1), onp.linalg.norm(_X, axis=1))


def test_np_lstsq_golden():
    a, b = _a(5, 3), _a(5)
    x = np.linalg.lstsq(np.array(a), np.array(b), rcond=None)[0]
    want = onp.linalg.lstsq(a, b, rcond=None)[0]
    assert_almost_equal(x.asnumpy(), want, rtol=5e-3, atol=5e-3)


def test_np_random():
    npx.seed(11)
    u = np.random.uniform(size=(2000,))
    assert type(u).__name__ == "ndarray" and 0.4 < float(u.mean()) < 0.6
    nrm = np.random.normal(2.0, 0.5, size=(2000,))
    assert abs(float(nrm.mean()) - 2.0) < 0.15
    assert np.random.randn(3, 2).shape == (3, 2)
    assert np.random.rand(4).shape == (4,)
    ri = np.random.randint(3, 9, size=(500,))
    assert int(ri.min()) >= 3 and int(ri.max()) <= 8
    b = np.random.beta(2.0, 5.0, size=(2000,))
    assert 0.0 < float(b.min()) and float(b.max()) < 1.0
    assert abs(float(b.mean()) - 2.0 / 7.0) < 0.1
    c = np.random.chisquare(3.0, size=(2000,))
    assert abs(float(c.mean()) - 3.0) < 0.5
    ln = np.random.lognormal(0.0, 0.25, size=(2000,))
    assert float(ln.min()) > 0
    lp = np.random.laplace(1.0, 1.0, size=(3000,))
    assert abs(float(lp.mean()) - 1.0) < 0.25
    lg = np.random.logistic(0.0, 1.0, size=(2000,))
    assert abs(float(lg.mean())) < 0.3
    g = np.random.gumbel(0.0, 1.0, size=(2000,))
    assert abs(float(g.mean()) - 0.5772) < 0.3
    p = np.random.pareto(3.0, size=(2000,))
    assert float(p.min()) >= 0
    r = np.random.rayleigh(1.0, size=(2000,))
    assert abs(float(r.mean()) - onp.sqrt(onp.pi / 2)) < 0.2
    w = np.random.weibull(1.0, size=(2000,))  # == Exp(1)
    assert abs(float(w.mean()) - 1.0) < 0.2
    pw = np.random.power(2.0, size=(2000,))
    assert 0.0 <= float(pw.min()) and float(pw.max()) <= 1.0
    e = np.random.exponential(0.5, size=(2000,))
    assert abs(float(e.mean()) - 0.5) < 0.1
    ch = np.random.choice(5, size=(300,))
    assert int(ch.min()) >= 0 and int(ch.max()) <= 4
    chp = np.random.choice(3, size=(800,), p=[0.8, 0.1, 0.1])
    counts = onp.bincount(chp.asnumpy().astype(int), minlength=3)
    assert counts[0] > 450
    pm = np.random.permutation(6)
    assert sorted(pm.asnumpy().tolist()) == [0, 1, 2, 3, 4, 5]
    mn = np.random.multinomial(50, [0.5, 0.5], size=4)
    assert mn.shape == (4, 2) and (mn.asnumpy().sum(1) == 50).all()
    arr = np.arange(8)
    np.random.shuffle(arr)
    assert sorted(arr.asnumpy().tolist()) == list(range(8))
    # determinism through the shared chain
    npx.seed(5)
    a1 = np.random.uniform(size=(16,)).asnumpy()
    npx.seed(5)
    a2 = np.random.uniform(size=(16,)).asnumpy()
    assert (a1 == a2).all()


def test_np_ndarray_semantics():
    x = np.array(_X)
    # zero-dim
    s = x.sum()
    assert s.shape == () and isinstance(float(s), float)
    # bool comparisons + masking
    m = x > 0
    assert m.dtype == onp.bool_
    assert (x[m].asnumpy() == _X[_X > 0]).all()
    # boolean mask assignment
    y = np.array(_X.copy())
    y[y > 0] = 0.0
    assert (y.asnumpy() <= 0).all()
    # fancy indexing
    idx = np.array(onp.array([2, 0], onp.int32))
    assert (x[idx].asnumpy() == _X[[2, 0]]).all()
    # dunders preserve the np class
    assert type(x + 1).__name__ == "ndarray"
    assert type(x @ np.array(_Y.T)).__name__ == "ndarray"
    assert type(-x).__name__ == "ndarray"
    assert type(x.copy()).__name__ == "ndarray"
    assert type(x.astype("float64")).__name__ == "ndarray"
    assert type(x.detach()).__name__ == "ndarray"
    # & | ^ ~ on bool arrays
    assert ((m & ~m).asnumpy() == False).all()  # noqa: E712
    assert ((m | ~m).asnumpy() == True).all()  # noqa: E712
    # scalar conversion & tolist
    assert np.array(3.5).item() == pytest.approx(3.5)
    assert np.array([1.0, 2.0]).tolist() == [1.0, 2.0]
    # in-place sort (numpy convention)
    z = np.array(onp.array([3.0, 1.0, 2.0], onp.float32))
    z.sort()
    assert z.asnumpy().tolist() == [1.0, 2.0, 3.0]
    # repr says array(...)
    assert repr(np.array([1.0])).startswith("array(")


def test_np_interop_and_tape():
    x = np.array(_X)
    c = x.as_nd_ndarray()
    assert type(c).__name__ == "NDArray"
    assert type(c.as_np_ndarray()).__name__ == "ndarray"
    # zero-copy outside record
    assert c._data is x._data
    # classic op on np input yields np output (any-input rule)
    out = mx.nd.relu(x)
    assert type(out).__name__ == "ndarray"
    # conversion under record is tape-linked: grads flow across
    leaf = np.array(_X)
    leaf.attach_grad()
    assert type(leaf.grad).__name__ == "ndarray"
    with mx.autograd.record():
        mid = leaf.as_nd_ndarray()          # np -> classic
        y = (mx.nd.square(mid)).as_np_ndarray()  # classic -> np
        loss = y.sum()
    loss.backward()
    assert_almost_equal(leaf.grad.asnumpy(), 2 * _X, rtol=1e-5, atol=1e-5)


def test_np_autograd():
    a = np.array(_a(3, 4))
    b = np.array(_a(4, 2))
    a.attach_grad(), b.attach_grad()
    with mx.autograd.record():
        out = np.einsum("ij,jk->ik", a, b).sum()
    out.backward()
    assert_almost_equal(a.grad.asnumpy(),
                        onp.ones((3, 2)) @ b.asnumpy().T, rtol=1e-4, atol=1e-4)
    # tensordot grad
    a2 = np.array(_a(3, 4))
    a2.attach_grad()
    with mx.autograd.record():
        z = np.tensordot(a2, np.array(_Y), axes=([0, 1], [0, 1]))
    z.backward()
    assert_almost_equal(a2.grad.asnumpy(), _Y, rtol=1e-5, atol=1e-5)
    # linalg solve grad is finite and flows
    sq = np.array(_SQ)
    sq.attach_grad()
    with mx.autograd.record():
        sol = np.linalg.solve(sq, np.array(_a(3))).sum()
    sol.backward()
    assert onp.isfinite(sq.grad.asnumpy()).all()
    assert float(np.abs(sq.grad).sum()) > 0


def test_np_mode_gluon_training():
    """Gluon trains under npx.set_np: np activations, np loss, Trainer
    step — the reference's test_numpy_gluon.py core case."""
    npx.set_np()
    try:
        net = mx.gluon.nn.HybridSequential()
        net.add(mx.gluon.nn.Dense(8, activation="relu"),
                mx.gluon.nn.Dense(1))
        net.initialize()
        trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                                   {"learning_rate": 0.05})
        xd = np.random.uniform(size=(16, 4))
        yd = (xd.sum(axis=1, keepdims=True) * 0.5)
        losses = []
        for _ in range(5):
            with mx.autograd.record():
                out = net(xd)
                assert type(out).__name__ == "ndarray"
                loss = ((out - yd) ** 2).mean()
            loss.backward()
            trainer.step(16)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
    finally:
        npx.reset_np()


def test_np_mode_hybridize():
    npx.set_np()
    try:
        net = mx.gluon.nn.Dense(3)
        net.initialize()
        net.hybridize()
        x = np.random.uniform(size=(2, 5))
        y1 = net(x)
        y2 = net(x)
        assert type(y1).__name__ == "ndarray"
        assert_almost_equal(y1.asnumpy(), y2.asnumpy(), rtol=1e-6, atol=1e-6)
    finally:
        npx.reset_np()


def test_use_np_decorator():
    @mx.util.use_np
    def f():
        assert mx.is_np_array()
        return mx.nd.ones((2,))

    assert not mx.is_np_array()
    out = f()
    assert type(out).__name__ == "ndarray"
    assert not mx.is_np_array()


def test_npx_surface():
    x = np.array(_X)
    assert (npx.relu(x).asnumpy() == onp.maximum(_X, 0)).all()
    _chk(npx.sigmoid(x), 1 / (1 + onp.exp(-_X)))
    _chk(npx.softmax(x, axis=-1),
         onp.exp(_X) / onp.exp(_X).sum(-1, keepdims=True))
    _chk(npx.log_softmax(x, axis=-1),
         _X - _X.max(-1, keepdims=True) -
         onp.log(onp.exp(_X - _X.max(-1, keepdims=True)).sum(-1, keepdims=True)))
    w = np.array(_a(5, 4))
    out = npx.fully_connected(x, w, None, num_hidden=5, no_bias=True)
    _chk(out, _X @ w.asnumpy().T, rtol=1e-3, atol=1e-3)
    oh = npx.one_hot(np.array(onp.array([0, 2], "int32")), depth=3)
    assert (oh.asnumpy() == onp.eye(3)[[0, 2]]).all()
    pk = npx.pick(x, np.array(onp.array([0, 1, 2], "int32")), axis=1)
    assert (pk.asnumpy() == _X[onp.arange(3), [0, 1, 2]]).all()
    # npx.reshape special codes (-2 copy rest, -3 merge, -4 split)
    t = np.array(_a(2, 3, 4))
    assert npx.reshape(t, (-1, -2)).shape == (2, 3, 4)
    assert npx.reshape(t, (-3, -2)).shape == (6, 4)
    t2 = np.array(_a(2, 4, 3))
    assert npx.reshape(t2, (0, -4, 2, 2, -2)).shape == (2, 2, 2, 3)
    # embedding
    emb = npx.embedding(np.array(onp.array([1, 0], "int32")), w,
                        input_dim=5, output_dim=4)
    assert (emb.asnumpy() == w.asnumpy()[[1, 0]]).all()
    # batch_dot
    a3 = np.array(_a(2, 3, 4))
    b3 = np.array(_a(2, 4, 2))
    _chk(npx.batch_dot(a3, b3), onp.matmul(a3.asnumpy(), b3.asnumpy()),
         rtol=1e-3, atol=1e-3)
    # conv on np arrays
    img = np.array(_a(1, 2, 5, 5))
    k = np.array(_a(3, 2, 3, 3))
    out = npx.convolution(img, k, None, kernel=(3, 3), num_filter=3,
                          no_bias=True)
    assert out.shape == (1, 3, 3, 3) and type(out).__name__ == "ndarray"
    # gather_nd / scatter_nd
    # MXNet gather_nd: leading indices axis indexes data dims
    # (output[n] = data[idx[0, n], idx[1, n]])
    gx = npx.gather_nd(x, np.array(onp.array([[0, 1], [0, 2]], "int32")))
    assert (gx.asnumpy() == _X[[0, 1], [0, 2]]).all()


def test_npx_save_load(tmp_path):
    f = str(tmp_path / "arrs.params")
    npx.save(f, {"a": np.array(_X), "b": np.array(_V)})
    loaded = npx.load(f)
    assert type(loaded["a"]).__name__ == "ndarray"
    assert (loaded["a"].asnumpy() == _X).all()
    assert (loaded["b"].asnumpy() == _V).all()


def test_np_waitall_and_constants():
    npx.waitall()
    assert np.pi == onp.pi and np.newaxis is None
    assert np.float32 is onp.float32
    assert np.inf == onp.inf
    assert isinstance(np.finfo("float32").eps, float) or True
    assert np.result_type(np.array([1.0]), onp.float64) == onp.float64
    assert not np.may_share_memory(np.array([1.0]), np.array([2.0]))
    assert np.allclose(np.array(_X), np.array(_X + 1e-9))
    assert np.array_equal(np.array(_V), np.array(_V))
    assert not np.array_equal(np.array(_V), np.array(_V[:3]))
    assert np.shape(np.array(_X)) == (3, 4)
    assert np.size(np.array(_X)) == 12
    assert np.ndim(np.array(_X)) == 2


def test_np_clip_take_where_single():
    x = np.array(_X)
    _chk(np.clip(x, -0.5, 0.5), onp.clip(_X, -0.5, 0.5))
    _chk(np.clip(x, None, 0.0), onp.clip(_X, None, 0.0))
    _chk(np.clip(x, 0.0, None), onp.clip(_X, 0.0, None))
    _chk(np.take(x, np.array(onp.array([1, 2], "int32")), axis=1),
         onp.take(_X, [1, 2], axis=1))
    # flat take (axis=None)
    _chk(np.take(x, np.array(onp.array([0, 5], "int32"))),
         onp.take(_X, [0, 5]))
    # 1-arg where == nonzero
    got = np.where(x > 0)
    want = onp.where(_X > 0)
    for g, w in zip(got, want):
        assert (g.asnumpy() == w).all()


def test_np_review_regressions():
    """Fixes from the round-6 code review of the np frontend."""
    # linspace/logspace default to f32 despite package-wide x64
    assert np.linspace(0, 1, 5).dtype == onp.float32
    assert np.logspace(0, 2, 3).dtype == onp.float32
    # around honors out= for decimals != 0
    buf = np.zeros(2)
    r = np.around(np.array([1.234, 5.678]), 2, out=buf)
    assert r is buf
    assert_almost_equal(buf.asnumpy(), onp.array([1.23, 5.68], "f"),
                        rtol=1e-5, atol=1e-5)
    # method-delegating np functions return np arrays for classic input
    classic = mx.nd.ones((2, 3))
    for fn in (lambda: np.transpose(classic), lambda: np.reshape(classic, 6),
               lambda: np.ravel(classic), lambda: np.copy(classic)):
        assert type(fn()).__name__ == "ndarray"
    # array(NDArray) inherits the source context
    src = mx.nd.ones((2,))
    assert np.array(src)._ctx == src._ctx
    # in-place ndarray.sort routes through the registry (engine sees it)
    from mxnet_tpu.ndarray import register as reg
    seen = set()
    prev = reg._INVOCATION_RECORD
    reg.record_invocations(seen)
    try:
        z = np.array(onp.array([2.0, 1.0], onp.float32))
        z.sort()
    finally:
        reg.record_invocations(prev)
        if prev is not None:
            prev |= seen
    assert "sort" in seen


def test_np_style_hybrid_block():
    """np-style HybridBlock: F.np / F.npx namespaces inside
    hybrid_forward (the deep-numpy convention), working eagerly AND
    hybridized."""
    npx.set_np()
    try:
        class NpBlock(mx.gluon.nn.HybridBlock):
            def __init__(self, **kw):
                super().__init__(**kw)
                with self.name_scope():
                    self.w = self.params.get("w", shape=(4, 3))

            def hybrid_forward(self, F, x, w):
                h = F.np.dot(x, w.reshape(3, 4))
                return F.npx.relu(h) - F.np.mean(h)

        blk = NpBlock()
        blk.initialize()
        x = np.random.uniform(size=(2, 3))
        y1 = blk(x)
        assert type(y1).__name__ == "ndarray" and y1.shape == (2, 4)
        blk.hybridize()
        y2 = blk(x)
        assert_almost_equal(y1.asnumpy(), y2.asnumpy(), rtol=1e-5, atol=1e-6)
        # gradients flow through the np-style graph
        x.attach_grad()
        with mx.autograd.record():
            out = blk(x).sum()
        out.backward()
        assert onp.abs(x.grad.asnumpy()).sum() > 0
    finally:
        npx.reset_np()


def test_np_symbolic_namespace():
    """mx.sym.np / mx.sym.npx: the op-backed symbolic numpy subset
    builds and EXECUTES graphs matching numpy goldens; Python-composed
    functions raise a named error pointing at hybridize."""
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    out = mx.sym.np.einsum("ij,kj->ik", mx.sym.np.tanh(a), b)
    e = out.bind(mx.current_context(), {"a": mx.nd.array(_X),
                                        "b": mx.nd.array(_Y)})
    got = e.forward()[0].asnumpy()
    _chk(got, onp.einsum("ij,kj->ik", onp.tanh(_X), _Y))
    # scalar lifting through _constant
    out2 = mx.sym.np.add(a, 2.5)
    e2 = out2.bind(mx.current_context(), {"a": mx.nd.array(_X)})
    _chk(e2.forward()[0], _X + 2.5)
    # reductions + manipulation + linalg
    out3 = mx.sym.np.sum(mx.sym.np.tril(a), axis=1)
    e3 = out3.bind(mx.current_context(), {"a": mx.nd.array(_X)})
    _chk(e3.forward()[0], onp.tril(_X).sum(1))
    sq = mx.sym.Variable("sq")
    out4 = mx.sym.np.linalg.cholesky(sq)
    e4 = out4.bind(mx.current_context(), {"sq": mx.nd.array(_SQ)})
    _chk(e4.forward()[0], onp.linalg.cholesky(_SQ), rtol=1e-3, atol=1e-3)
    # npx symbolic
    out5 = mx.sym.npx.relu(a)
    e5 = out5.bind(mx.current_context(), {"a": mx.nd.array(_X)})
    _chk(e5.forward()[0], onp.maximum(_X, 0))
    # np-style hybrid block now ALSO works on the Symbol path
    class NpBlock(mx.gluon.nn.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.npx.relu(F.np.multiply(x, 2.0))

    blk = NpBlock()
    blk.initialize()
    sym_out = blk(mx.sym.Variable("x"))
    ee = sym_out.bind(mx.current_context(), {"x": mx.nd.array(_X)})
    _chk(ee.forward()[0], onp.maximum(_X * 2.0, 0))
    # VALUE-dependent-shape functions raise with a pointer at eager np
    import pytest as _pytest
    with _pytest.raises(NotImplementedError, match="hybridize"):
        mx.sym.np.unique(a)
    # non-liftable input type raises a named TypeError
    with _pytest.raises(TypeError, match="Symbol or python scalar"):
        mx.sym.np.add(a, onp.ones(3))


def test_np_pickle_roundtrip():
    import pickle
    x = np.array(_X)
    y = pickle.loads(pickle.dumps(x))
    assert type(y).__name__ == "ndarray"
    assert (y.asnumpy() == _X).all()
    c = mx.nd.array(_X)
    c2 = pickle.loads(pickle.dumps(c))
    assert type(c2).__name__ == "NDArray"


def test_np_mode_dataloader_and_metric():
    """Under set_np the data pipeline emits np batches and metrics
    consume them (upstream test_numpy_gluon.py integration shape)."""
    npx.set_np()
    try:
        xs = onp.random.RandomState(0).rand(10, 3).astype("f")
        ys = onp.arange(10).astype("f") % 2
        ds = mx.gluon.data.ArrayDataset(xs, ys)
        loader = mx.gluon.data.DataLoader(ds, batch_size=5)
        for xb, yb in loader:
            assert type(xb).__name__ == "ndarray"
            assert type(yb).__name__ == "ndarray"
        m = mx.metric.Accuracy()
        pred = np.array(onp.eye(2)[ys.astype(int)])
        m.update([mx.nd.array(ys)], [pred.as_nd_ndarray()])
        assert m.get()[1] == 1.0
    finally:
        npx.reset_np()


def test_np_symbolic_review_regressions():
    """Round-6 review: int scalars stay integer through _lift, npx
    symbolic reshape matches the eager signature, concatenate
    axis=None flattens, unknown names raise the named error, and
    under-supplied ops fail at build time."""
    import pytest as _pytest
    a = mx.sym.Variable("a")
    ia = mx.nd.array(_I8)
    # int scalar keeps integer dtype (shift works; no float promotion)
    out = mx.sym.np.left_shift(a, 2)
    e = out.bind(mx.current_context(), {"a": ia})
    assert (e.forward()[0].asnumpy() == onp.left_shift(_I8, 2)).all()
    out2 = mx.sym.np.add(a, 2)
    e2 = out2.bind(mx.current_context(), {"a": ia})
    assert "int" in str(e2.forward()[0].dtype)
    # npx.reshape positional newshape, special codes
    r = mx.sym.npx.reshape(a, (-1, -2))
    er = r.bind(mx.current_context(), {"a": mx.nd.array(_X)})
    assert er.forward()[0].shape == _X.shape
    # concatenate axis=None flattens like numpy
    b = mx.sym.Variable("b")
    c = mx.sym.np.concatenate([a, b], axis=None)
    ec = c.bind(mx.current_context(), {"a": mx.nd.array(_X),
                                       "b": mx.nd.array(_Y)})
    assert ec.forward()[0].shape == (_X.size + _Y.size,)
    # unknown eager-only names raise the NAMED error — as
    # AttributeError so hasattr/getattr introspection still works
    with _pytest.raises(AttributeError, match="hybridize"):
        mx.sym.np.zeros((3,))
    with _pytest.raises(AttributeError, match="hybridize"):
        mx.sym.npx.save("f", {})
    assert not hasattr(mx.sym.np, "zeros")
    assert getattr(mx.sym.npx, "save", None) is None
    # under-supplied binary fails AT BUILD with a clear message
    with _pytest.raises(TypeError, match="tensor argument"):
        mx.sym.np.dot(a)
    # interleaved param named clearly in npx
    w = mx.sym.Variable("w")
    with _pytest.raises(TypeError, match="keywords"):
        mx.sym.npx.fully_connected(a, 128, w)


def test_np_tail_functions():
    """argwhere / dsplit / tri / vander / windows / indices /
    tril_indices — the remaining creation+index tail."""
    x = onp.array([[0, 1], [2, 0]], "f")
    _chk(np.argwhere(np.array(x)), onp.argwhere(x))
    t3 = _a(2, 4, 6)
    for got, want in zip(np.dsplit(np.array(t3), 3), onp.dsplit(t3, 3)):
        _chk(got, want)
    with pytest.raises(ValueError):
        np.dsplit(np.array(_X), 2)
    _chk(np.tri(3, 5, 1), onp.tri(3, 5, 1, dtype="f"))
    v = onp.array([1.0, 2.0, 3.0], "f")
    _chk(np.vander(np.array(v)), onp.vander(v))
    _chk(np.vander(np.array(v), 4, increasing=True),
         onp.vander(v, 4, increasing=True))
    for fn, ofn in ((np.hanning, onp.hanning), (np.hamming, onp.hamming),
                    (np.blackman, onp.blackman)):
        _chk(fn(8), ofn(8).astype("f"))
    _chk(np.indices((2, 3)), onp.indices((2, 3)))
    r, c = np.tril_indices(4, 1)
    wr, wc = onp.tril_indices(4, 1)
    assert (r.asnumpy() == wr).all() and (c.asnumpy() == wc).all()
    r2, c2 = np.triu_indices(3)
    wr2, wc2 = onp.triu_indices(3)
    assert (r2.asnumpy() == wr2).all() and (c2.asnumpy() == wc2).all()
    # vander differentiates (composed from power/expand_dims)
    a = np.array(v)
    a.attach_grad()
    with mx.autograd.record():
        out = np.vander(a, 3).sum()
    out.backward()
    # d/dx sum(x^2 + x + 1) = 2x + 1
    assert_almost_equal(a.grad.asnumpy(), 2 * v + 1, rtol=1e-5, atol=1e-5)
    # index-helper outputs index straight back into arrays
    m = np.array(_a(4, 4))
    low = m[np.tril_indices(4)]
    assert low.shape == (10,)


def test_np_vander_validation_and_sym_argwhere():
    with pytest.raises(ValueError, match="one-dimensional"):
        np.vander(np.array(_X))
    with pytest.raises(NotImplementedError, match="dynamic"):
        mx.sym.np.argwhere(mx.sym.Variable("a"))


def test_np_vander_exact_integer_powers():
    v = np.vander(np.array([1.0, 2.0, 3.0]))
    assert (v.asnumpy() == onp.vander(onp.array([1.0, 2.0, 3.0], "f"))).all()


def test_np_surface_audit_gate():
    """VERDICT r4 #8: the checked-in NP_SURFACE.md coverage list cannot
    go stale — the gate re-runs the audit and fails on any MISSING
    upstream function or on drift between the audit and the file."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import np_surface_audit as audit
    rows, missing, const_missing = audit.audit()
    assert not missing, f"upstream np functions missing: {missing}"
    assert not const_missing, const_missing
    n_yes = sum(1 for _, s, _ in rows if s == "yes")
    assert n_yes >= 200, n_yes
    # the checked-in list reflects the current audit
    path = os.path.join(os.path.dirname(__file__), "..", "NP_SURFACE.md")
    assert os.path.exists(path), "NP_SURFACE.md not checked in"
    text = open(path).read()
    assert "0 missing" in text, "NP_SURFACE.md is stale — regenerate " \
        "with python tools/np_surface_audit.py --write"
    for name, status, _ in rows:
        assert f"| {name} |" in text, f"{name} absent from NP_SURFACE.md"


def test_np_gap_functions_round5():
    """The 10 functions the round-5 audit found missing, golden-checked
    against numpy."""
    a = np.array([[1.0, 2.0], [3.0, 4.0]])
    assert np.row_stack([a, a]).shape == (4, 2)
    assert np.rollaxis(np.zeros((2, 3, 4)), 2, 0).shape == (4, 2, 3)
    assert np.delete(np.arange(5), 2).asnumpy().tolist() == [0, 1, 3, 4]
    assert np.insert(np.arange(4), 1, 9).asnumpy().tolist() == [0, 9, 1, 2, 3]
    r, c = np.diag_indices_from(a)
    assert a.asnumpy()[r.asnumpy(), c.asnumpy()].tolist() == [1.0, 4.0]
    u = np.unravel_index(np.array([5], dtype="int32"), (2, 3))
    assert [int(x.asnumpy()[0]) for x in u] == [1, 2]
    x = np.array([onp.inf, -onp.inf, 1.0])
    assert np.isposinf(x).asnumpy().tolist() == [True, False, False]
    assert np.isneginf(x).asnumpy().tolist() == [False, True, False]
    fp = np.float_power(np.array([2.0]), np.array([3.0]))
    assert str(fp.dtype) == "float64" and float(fp.asnumpy()[0]) == 8.0
    pv = np.polyval(np.array([1.0, 0.0, -1.0]), np.array([2.0, 3.0]))
    assert pv.asnumpy().tolist() == [3.0, 8.0]
    # polyval stays differentiable (Horner over registry ops)
    from mxnet_tpu import autograd
    xv = np.array([2.0])
    xv.attach_grad()
    with autograd.record():
        y = np.polyval(np.array([1.0, 0.0, -1.0]), xv)
    y.backward()
    assert float(xv.grad.asnumpy()[0]) == 4.0


def test_sym_np_composed_functions():
    """Round-5: statically-shaped compositions (split family, meshgrid,
    stack helpers, atleast_*, broadcast_arrays, interp, around,
    average, quantile/percentile) now lower to dedicated registry ops
    with real multi-output selectors on the symbolic path — goldens vs
    numpy through the compiled executor."""
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    A = _a(2, 6)
    B = _a(2, 6)
    ctx = mx.current_context()

    def run(sym, **feeds):
        ex = sym.bind(ctx, {k: mx.nd.array(v) for k, v in feeds.items()})
        return [o.asnumpy() for o in ex.forward()]

    # stack helpers
    _chk(run(mx.sym.np.vstack([a, b]), a=A, b=B)[0], onp.vstack([A, B]))
    _chk(run(mx.sym.np.hstack([a, b]), a=A, b=B)[0], onp.hstack([A, B]))
    _chk(run(mx.sym.np.dstack([a, b]), a=A, b=B)[0], onp.dstack([A, B]))
    _chk(run(mx.sym.np.column_stack([a, b]), a=A, b=B)[0],
         onp.column_stack([A, B]))

    # split family: multi-output selectors
    s = mx.sym.np.split(a, 3, axis=1)
    assert s.num_outputs == 3
    got = run(mx.sym.Group([s[i] for i in range(3)]), a=A)
    for g, w in zip(got, onp.split(A, 3, axis=1)):
        _chk(g, w)
    s2 = mx.sym.np.split(a, (1, 3), axis=1)
    assert s2.num_outputs == 3
    got2 = run(mx.sym.Group([s2[i] for i in range(3)]), a=A)
    for g, w in zip(got2, onp.split(A, (1, 3), axis=1)):
        _chk(g, w)
    s3 = mx.sym.np.array_split(a, 4, axis=1)  # uneven: 6 -> 2,2,1,1
    got3 = run(mx.sym.Group([s3[i] for i in range(4)]), a=A)
    for g, w in zip(got3, onp.array_split(A, 4, axis=1)):
        _chk(g, w)
    _chk(run(mx.sym.np.vsplit(a, 2)[0], a=A)[0], onp.vsplit(A, 2)[0])
    _chk(run(mx.sym.np.hsplit(a, 2)[1], a=A)[0], onp.hsplit(A, 2)[1])
    A3 = _a(2, 3, 4)
    _chk(run(mx.sym.np.dsplit(a, 2)[0], a=A3)[0], onp.dsplit(A3, 2)[0])

    # meshgrid / broadcast_arrays: N-output selectors
    v1, v2 = _a(3), _a(4)
    m = mx.sym.np.meshgrid(a, b)
    assert m.num_outputs == 2
    gm = run(mx.sym.Group([m[0], m[1]]), a=v1, b=v2)
    wm = onp.meshgrid(v1, v2)
    _chk(gm[0], wm[0]); _chk(gm[1], wm[1])
    br = mx.sym.np.broadcast_arrays(a, b)
    gb = run(mx.sym.Group([br[0], br[1]]), a=_a(1, 4), b=_a(3, 1))
    wb = onp.broadcast_arrays(_a(1, 4) * 0, _a(3, 1) * 0)
    assert gb[0].shape == wb[0].shape and gb[1].shape == wb[1].shape

    # atleast_* / interp / around / average / quantile / percentile
    _chk(run(mx.sym.np.atleast_1d(a), a=onp.float32(5.0))[0],
         onp.atleast_1d(onp.float32(5.0)))
    v3 = _a(3)
    _chk(run(mx.sym.np.atleast_2d(a), a=v3)[0], onp.atleast_2d(v3))
    _chk(run(mx.sym.np.atleast_3d(a), a=A)[0], onp.atleast_3d(A))
    xs = onp.sort(_a(8)); fs = _a(8); q = _a(5)
    _chk(run(mx.sym.np.interp(a, b, mx.sym.var("c")),
             a=q, b=xs, c=fs)[0], onp.interp(q, xs, fs), rtol=1e-5)
    _chk(run(mx.sym.np.around(a, 1), a=A)[0], onp.around(A, 1))
    _chk(run(mx.sym.np.average(a, axis=0), a=A)[0], onp.average(A, axis=0))
    w = onp.abs(_a(2, 6)) + 0.1
    _chk(run(mx.sym.np.average(a, axis=0, weights=b), a=A, b=w)[0],
         onp.average(A, axis=0, weights=w), rtol=1e-5)
    _chk(run(mx.sym.np.quantile(a, 0.25), a=A)[0], onp.quantile(A, 0.25),
         rtol=1e-5)
    _chk(run(mx.sym.np.percentile(a, 75, 1), a=A)[0],
         onp.percentile(A, 75, axis=1), rtol=1e-5)


def test_sym_np_split_json_roundtrip():
    """Round-5 review regression: tojson/load_json of graphs with
    multi-output selectors — load_json must rebuild output-0 of a
    multi-output node as a SELECTOR (the bare node splats every
    output), and infer_num_outputs must parse stringified params
    (int('(1, 3)') crashed)."""
    a = mx.sym.var("a")
    A = _a(2, 6)
    s = mx.sym.np.split(a, (1, 3), axis=1)
    g = mx.sym.load_json(mx.sym.Group([s[i] for i in range(3)]).tojson())
    outs = g.bind(mx.current_context(), {"a": mx.nd.array(A)}).forward()
    for o, w in zip(outs, onp.split(A, (1, 3), axis=1)):
        _chk(o, w)
    # legacy SliceChannel graphs had the same latent selector bug
    c = mx.sym.split(mx.sym.var("x"), num_outputs=2, axis=1)
    cg = mx.sym.load_json(mx.sym.Group([c[0], c[1]]).tojson())
    o = cg.bind(mx.current_context(), {"x": mx.nd.array(A)}).forward()
    assert o[0].shape == (2, 3) and o[1].shape == (2, 3)
    # numpy fixed-axis splits reject an axis argument
    with pytest.raises(TypeError, match="does not accept axis"):
        mx.sym.np.vsplit(a, 2, axis=1)
