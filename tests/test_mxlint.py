"""mxlint: per-rule fixture goldens + the tier-1 repo gate.

Two halves:

1. FIXTURES — every pass has a fixture under ``tools/mxlint/fixtures/``
   with positive, inline-suppressed and clean snippets; the goldens
   here pin the exact rule multiset (and spot-check anchor lines) so a
   pass that goes blind or trigger-happy fails loudly.
2. THE GATE — the real passes run over the acceptance scope
   (``mxnet_tpu/``, ``tools/``, ``bench.py``) and must report ZERO
   unbaselined findings with an EMPTY committed baseline; the README
   configuration reference must be regeneration-stable against
   ``mxnet_tpu/envvars.py``; the Grafana dashboard families must all
   exist. This is the CI contract from ISSUE 6.

No jax / device work anywhere here — the linter is pure stdlib AST.
"""
import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from tools.mxlint import core  # noqa: E402
from tools.mxlint.passes import all_passes  # noqa: E402
from tools.mxlint.passes.env_registry import (  # noqa: E402
    load_envvar_registry)
from tools.mxlint.passes.telemetry_consistency import (  # noqa: E402
    TelemetryConsistencyPass)

FIXTURES = os.path.join(ROOT, "tools", "mxlint", "fixtures")


def _lint_fixture(fname, relpath=None):
    with open(os.path.join(FIXTURES, fname), encoding="utf-8") as fh:
        source = fh.read()
    project = core.Project(root=ROOT)
    project.lint_source(source, relpath or f"fixtures/{fname}")
    project.finalize()
    return project, source


def _rules(project):
    return sorted(f.rule for f in project.findings)


def _line_mentions_rule(source, finding):
    """The fixture convention: every positive finding's anchor line
    carries a comment naming its rule (or the line right after, for
    findings anchored on multi-line statements)."""
    lines = source.splitlines()
    window = " ".join(lines[finding.line - 1:finding.line + 1])
    return finding.rule in window


# ---------------------------------------------------------------------------
# fixture goldens, one per pass
# ---------------------------------------------------------------------------

def test_fixture_lock_order():
    project, source = _lint_fixture("lock_order_fixture.py")
    assert _rules(project) == [
        "lock-blocking-call",       # time.sleep under lock
        "lock-blocking-call",       # urlopen under lock
        "lock-blocking-call",       # foreign Event.wait under lock
        "lock-blocking-call",       # thread join under lock
        "lock-callback",            # cb() under lock
        "lock-nested",              # via same-class method call
        "lock-nested",              # direct re-acquire
        "lock-order",               # the ABBA pair
    ]
    for f in project.findings:
        if f.rule in ("lock-blocking-call", "lock-callback"):
            assert _line_mentions_rule(source, f), f
    # the suppressed time.sleep was seen but silenced inline
    assert [f.rule for f in project.suppressed] == ["lock-blocking-call"]


def test_fixture_thread_hygiene():
    project, source = _lint_fixture("thread_hygiene_fixture.py")
    assert _rules(project) == [
        "executor-unnamed",         # ThreadPoolExecutor, no prefix
        "silent-except",
        "socketserver-daemon",      # UndecidedServer class
        "socketserver-daemon",      # bare ThreadingHTTPServer(...)
        "thread-daemon",            # unnamed_and_implicit
        "thread-daemon",            # named_but_undecided
        "thread-unjoined",
        "thread-unnamed",
    ]
    assert sorted(f.rule for f in project.suppressed) == [
        "executor-unnamed", "thread-daemon", "thread-unnamed"]
    silent = [f for f in project.findings if f.rule == "silent-except"]
    assert _line_mentions_rule(source, silent[0])
    for f in project.findings:
        if f.rule in ("executor-unnamed", "socketserver-daemon"):
            assert _line_mentions_rule(source, f), f


def test_fixture_telemetry_consistency():
    project, source = _lint_fixture("telemetry_fixture.py")
    assert _rules(project) == [
        "metric-engine-label",
        "metric-labels",
        "metric-tenant-label",
        "span-leak",
        "stage-name-registry",      # .labels(stage="warmupp")
        "stage-name-registry",      # match={"stage": "prefil"}
    ]
    leak = [f for f in project.findings if f.rule == "span-leak"]
    assert _line_mentions_rule(source, leak[0])
    tenant = [f for f in project.findings
              if f.rule == "metric-tenant-label"]
    assert "model" in tenant[0].message
    stages = [f for f in project.findings
              if f.rule == "stage-name-registry"]
    assert {"'warmupp'" in f.message or "'prefil'" in f.message
            for f in stages} == {True}
    for f in stages:
        assert _line_mentions_rule(source, f), f


def test_fixture_env_registry():
    project, source = _lint_fixture("env_registry_fixture.py")
    assert _rules(project) == [
        "env-raw-read",             # os.environ.get
        "env-raw-read",             # os.environ[...]
        "env-raw-read",             # os.getenv
        "env-raw-read",             # aliased env = os.environ.get
        "env-unregistered",
    ]
    assert [f.rule for f in project.suppressed] == ["env-raw-read"]
    unreg = [f for f in project.findings if f.rule == "env-unregistered"]
    assert "MXNET_TPU_NOT_A_REAL_KNOB" in unreg[0].message


def test_fixture_wire_safety():
    # the pass is scoped to the wire path: linted under a PRETEND
    # serving relpath it fires, under the fixture's real path it doesn't
    project, source = _lint_fixture("wire_safety_fixture.py",
                                    relpath="mxnet_tpu/serving/_fx.py")
    assert _rules(project) == [
        "wire-unsafe",              # import pickle
        "wire-unsafe",              # pickle.loads
        "wire-unsafe",              # eval
        "wire-unsafe",              # yaml.load
    ]
    assert [f.rule for f in project.suppressed] == ["wire-unsafe"]
    unscoped, _ = _lint_fixture("wire_safety_fixture.py")
    assert "wire-unsafe" not in _rules(unscoped)


def test_wire_safety_covers_loadgen_and_dump_tools():
    # ISSUE 11 satellite: the two tools that parse wire payloads off
    # live fleets are in scope now — the same fixture fires under
    # their relpaths
    for relpath in ("tools/serve_loadgen.py", "tools/telemetry_dump.py"):
        project, _ = _lint_fixture("wire_safety_fixture.py",
                                   relpath=relpath)
        assert "wire-unsafe" in _rules(project), relpath


def _lint_lock_graph_pair():
    project = core.Project(root=ROOT)
    # the whole-program pass only reports on full scans (a partial
    # graph would mis-resolve); the fixture pair stands in for one
    project.full_scan = True
    for fname in ("lock_graph_fixture_b.py", "lock_graph_fixture_a.py"):
        with open(os.path.join(FIXTURES, fname), encoding="utf-8") as fh:
            project.lint_source(fh.read(), f"fixtures/{fname}")
    project.finalize()
    return project


def test_fixture_lock_graph_cycle_via_callback():
    """The tentpole golden: router holds its lock entering the engine;
    the engine completes futures under ITS lock, firing the router's
    done-callback — a cycle NEITHER per-class pass can see. The
    finding must carry the full witness path."""
    project = _lint_lock_graph_pair()
    cycles = [f for f in project.findings if f.rule == "lock-graph-cycle"]
    assert len(cycles) == 1, project.findings
    msg = cycles[0].message
    # both legs of the witness, with the method chain spelled out
    assert "FixtureRouter._lock" in msg and "FixtureEngine._elock" in msg
    assert "FixtureRouter.submit" in msg          # leg 1: router->engine
    assert "FixtureEngine.submit" in msg          # leg 2: engine->callback
    assert "FixtureRouter._on_done" in msg        # the re-entry
    # the negative control participates in no cycle
    assert "CleanRouter" not in msg and "CleanEngine" not in msg


def test_fixture_lock_graph_blocking_escalation():
    project = _lint_lock_graph_pair()
    blocking = [f for f in project.findings
                if f.rule == "lock-graph-blocking"]
    assert len(blocking) == 1, project.findings
    assert "time.sleep()" in blocking[0].message
    assert "FixtureEngine.flush" in blocking[0].message
    # flush_quietly's identical shape was inline-suppressed
    assert "lock-graph-blocking" in [f.rule for f in project.suppressed]


def test_lock_graph_negative_control_alone_is_clean():
    """The clean pair linted WITHOUT the seeded classes: zero lock-graph
    findings (guards against the pass going trigger-happy on the
    snapshot-outside idiom itself)."""
    import re as _re
    project = core.Project(root=ROOT)
    project.full_scan = True
    for fname in ("lock_graph_fixture_b.py", "lock_graph_fixture_a.py"):
        with open(os.path.join(FIXTURES, fname), encoding="utf-8") as fh:
            src = fh.read()
        # keep only the Clean* halves of each fixture
        kept = _re.split(r"(?m)^class ", src)
        body = kept[0] + "".join("class " + part for part in kept[1:]
                                 if part.startswith("Clean")
                                 or part.startswith("FixtureFuture"))
        project.lint_source(body, f"fixtures/_clean_{fname}")
    project.finalize()
    assert [f for f in project.findings
            if f.rule.startswith("lock-graph")] == []


def test_lock_graph_blocking_survives_call_graph_cycle():
    """Review regression: mutually-recursive helpers must not freeze
    an incomplete transitive summary — the blocking call reachable
    only through the A<->B call cycle is still reported, regardless of
    method visit order."""
    src = '''
import threading
import time


class Pump:
    def __init__(self):
        self._lock = threading.Lock()

    def run(self):
        with self._lock:
            self.step_a()

    def step_a(self):
        self.step_b()

    def step_b(self):
        self.step_a()          # the cycle
        time.sleep(0.5)        # reachable only through it
'''
    project = core.Project(root=ROOT)
    project.full_scan = True
    project.lint_source(src, "fixtures/_cycle_pump.py")
    project.finalize()
    blocking = [f for f in project.findings
                if f.rule == "lock-graph-blocking"]
    assert len(blocking) == 1, project.findings
    assert "time.sleep()" in blocking[0].message


def test_lock_graph_silent_on_partial_scans():
    """Whole-program findings need the whole program: the same seeded
    pair linted WITHOUT full_scan (the --changed-only / explicit-path
    shape) reports nothing, so a pre-commit subset can never flag a
    finding the full CI graph disclaims."""
    project = core.Project(root=ROOT)
    for fname in ("lock_graph_fixture_b.py", "lock_graph_fixture_a.py"):
        with open(os.path.join(FIXTURES, fname), encoding="utf-8") as fh:
            project.lint_source(fh.read(), f"fixtures/{fname}")
    project.finalize()
    assert [f for f in project.findings
            if f.rule.startswith("lock-graph")] == []


def test_executor_positional_prefix_satisfies_rule():
    src = ("from concurrent.futures import ThreadPoolExecutor\n"
           "pool = ThreadPoolExecutor(4, 'mxnet_tpu_pool')\n")
    project = core.Project(root=ROOT)
    project.lint_source(src, "fixtures/_positional_prefix.py")
    project.finalize()
    assert "executor-unnamed" not in _rules(project)


def test_cli_write_baseline_rejects_changed_only():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", "--changed-only",
         "--write-baseline"],
        cwd=ROOT, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 2
    assert "truncate" in proc.stderr


def test_fixture_clock_discipline():
    project, source = _lint_fixture("clocks_fixture.py")
    assert _rules(project) == [
        "wall-clock-delta",         # direct time.time() - t0
        "wall-clock-delta",         # tainted local
        "wall-clock-delta",         # tainted self attr
    ]
    assert [f.rule for f in project.suppressed] == ["wall-clock-delta"]
    for f in project.findings:
        assert _line_mentions_rule(source, f), f


def test_suppression_mechanics():
    project = core.Project(root=ROOT)
    project.lint_source(
        "import time\n"
        "# mxlint: disable-file=thread-unnamed\n"
        "import threading\n"
        "def f(t0):\n"
        "    # mxlint: disable=wall-clock-delta\n"
        "    d = time.time() - t0\n"
        "    t = threading.Thread(target=print, daemon=True)\n"
        "    return d, t\n",
        "fixtures/_inline.py")
    project.finalize()
    assert _rules(project) == []            # both silenced
    assert sorted(f.rule for f in project.suppressed) == [
        "thread-unnamed", "wall-clock-delta"]


def test_alert_rule_family_cross_check():
    # the SLO/alert layer's family references resolve like dashboard
    # queries: a rule over a renamed family must fail lint. Checked in
    # finalize under full_scan (declarations span the whole repo scan).
    p = TelemetryConsistencyPass()
    project = core.Project(root=ROOT, passes=[p])
    with open(os.path.join(FIXTURES, "telemetry_fixture.py"),
              encoding="utf-8") as fh:
        source = fh.read()
    project.lint_source(source, "fixtures/telemetry_fixture.py")
    project.full_scan = True
    findings = [f for f in project.finalize()
                if f.rule == "alert-rule-family"]
    fams = sorted(f.message.split("family ")[1].split()[0]
                  for f in findings)
    # the kwarg ref AND the signature default fire; the rule over the
    # fixture-declared family does not
    assert fams == ["mxnet_tpu_fixture_default_gone_ms",
                    "mxnet_tpu_fixture_gone_total"], findings
    for f in findings:
        assert _line_mentions_rule(source, f), f


def test_history_rule_family_cross_check():
    # the history config's recording rules cross-check the same way:
    # capturing a renamed family stores nothing and every retro query
    # over it comes back empty — that must fail lint, while a rule
    # over a declared family stays clean
    p = TelemetryConsistencyPass()
    project = core.Project(root=ROOT, passes=[p])
    with open(os.path.join(FIXTURES, "telemetry_fixture.py"),
              encoding="utf-8") as fh:
        source = fh.read()
    project.lint_source(source, "fixtures/telemetry_fixture.py")
    project.full_scan = True
    findings = [f for f in project.finalize()
                if f.rule == "history-rule-family"]
    fams = sorted(f.message.split("family ")[1].split()[0]
                  for f in findings)
    assert fams == ["mxnet_tpu_fixture_history_gone_total"], findings
    for f in findings:
        assert _line_mentions_rule(source, f), f


def test_dashboard_cross_check_fires_when_family_missing():
    # a full-scan project that declared NO families must flag every
    # family the committed Grafana dashboard queries
    p = TelemetryConsistencyPass()
    project = core.Project(root=ROOT, passes=[p])
    project.lint_source("x = 1\n", "fixtures/_empty.py")
    project.full_scan = True
    findings = project.finalize()
    dash = [f for f in findings if f.rule == "dashboard-family"]
    assert dash, "dashboard cross-check never fired"
    assert any("mxnet_tpu_serving_requests_total" in f.message
               for f in dash)


# ---------------------------------------------------------------------------
# the env registry itself
# ---------------------------------------------------------------------------

def test_envvar_registry_typing(monkeypatch):
    mod = load_envvar_registry(ROOT)
    monkeypatch.delenv("MXNET_TPU_SPANS", raising=False)
    assert mod.get("MXNET_TPU_SPANS") is True
    monkeypatch.setenv("MXNET_TPU_SPANS", "0")
    assert mod.get("MXNET_TPU_SPANS") is False
    monkeypatch.setenv("MXNET_TPU_TRACE_BUFFER", "128")
    assert mod.get("MXNET_TPU_TRACE_BUFFER") == 128
    monkeypatch.setenv("MXNET_TPU_TRACE_BUFFER", "not-an-int")
    assert mod.get("MXNET_TPU_TRACE_BUFFER") == 64      # typo -> default
    monkeypatch.setenv("MXNET_TPU_WATCHDOG_STALL_S", "2.5")
    assert mod.get("MXNET_TPU_WATCHDOG_STALL_S") == 2.5
    assert mod.get("MXNET_TPU_PEAK_TFLOPS") is None
    with pytest.raises(KeyError):
        mod.get("MXNET_TPU_NOT_A_REAL_KNOB")
    assert mod.get_raw("MXNET_TPU_SPANS") == "0"
    # every declared name is a real MXNET_TPU_* name with a doc
    for var in mod.all_vars():
        assert var.name.startswith("MXNET_TPU_")
        assert var.doc


# ---------------------------------------------------------------------------
# the tier-1 gate
# ---------------------------------------------------------------------------

def test_repo_gate_zero_unbaselined_findings():
    project = core.run(root=ROOT)
    baseline = core.load_baseline(ROOT)
    new = [f for f in project.findings if f.key() not in baseline]
    assert not new, (
        "unbaselined mxlint findings (fix them or inline-suppress "
        "with justification):\n" + "\n".join(map(repr, new)))


def test_baseline_is_empty():
    """The acceptance bar: the committed baseline carries ZERO debt —
    in particular nothing from the lock-order, wire-safety or
    telemetry-consistency passes may ever be baselined away."""
    with open(core.baseline_path(ROOT), encoding="utf-8") as fh:
        data = json.load(fh)
    assert data["findings"] == []


def test_envdoc_is_regeneration_stable():
    """README's generated configuration reference matches the registry
    exactly (i.e. --write-envdoc would be a no-op)."""
    from tools.mxlint.__main__ import ENVDOC_BEGIN, ENVDOC_END
    mod = load_envvar_registry(ROOT)
    with open(os.path.join(ROOT, "README.md"), encoding="utf-8") as fh:
        text = fh.read()
    assert ENVDOC_BEGIN in text and ENVDOC_END in text
    body = text.split(ENVDOC_BEGIN, 1)[1].split(ENVDOC_END, 1)[0]
    assert body.strip() == mod.markdown_table().strip()
    for var in mod.ENVVARS.values():
        assert f"`{var.name}`" in body, f"{var.name} missing from table"


def test_ast_cache_shared_across_runs():
    """ISSUE 11 satellite: one parse per (file, mtime, size) per
    process — the repo gate, the alert cross-check and every fixture
    test share contexts instead of re-parsing the scope."""
    p = os.path.join(ROOT, "tools", "mxlint", "core.py")
    c1 = core.cached_context(p, "tools/mxlint/core.py")
    c2 = core.cached_context(p, "tools/mxlint/core.py")
    assert c1 is c2
    assert c1.tree is c2.tree
    # the shared preorder node list is computed once too
    assert c1.nodes is c2.nodes
    # a run() consumes the cached context rather than re-parsing
    project = core.run(root=ROOT, paths=("tools/mxlint/core.py",))
    assert any(ctx is c1 for ctx in project.contexts)


def test_warm_cache_parallel_jobs_matches_serial():
    from tools.mxlint.core import _CTX_CACHE
    paths = ("tools/mxlint",)
    serial = core.run(root=ROOT, paths=paths)
    serial_keys = sorted(f.key() for f in serial.findings)
    _CTX_CACHE.clear()
    n = core.warm_cache(ROOT, paths, jobs=2)
    assert n >= 5
    warm = core.run(root=ROOT, paths=paths)
    assert sorted(f.key() for f in warm.findings) == serial_keys


def test_changed_files_scope_filtered():
    rels = core.changed_files(ROOT)
    for rel in rels:
        assert rel.endswith(".py"), rel
        assert rel == "bench.py" or rel.split("/")[0] in (
            "mxnet_tpu", "tools"), rel
        assert "fixtures" not in rel.split("/"), rel


def test_cli_changed_only_exits_zero():
    # the repo gate holds zero unbaselined findings on the FULL scope,
    # so any changed-only subset must be clean too
    proc = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", "--changed-only", "-q"],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_smoke_exits_zero():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", "-q"],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "unbaselined" in proc.stdout


def test_cli_list_rules():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", "--list-rules"],
        cwd=ROOT, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0
    for rule in ("lock-blocking-call", "thread-unnamed", "metric-labels",
                 "env-raw-read", "wire-unsafe", "wall-clock-delta",
                 "lock-graph-cycle", "lock-graph-blocking",
                 "executor-unnamed", "socketserver-daemon"):
        assert rule in proc.stdout
