"""Worker body for the 3-process compressed-reduce distributed test —
the topology variant the round-2 VERDICT asked for (weak #9: one
2x2 topology only). 3 workers x 1 CPU device, 2-bit gradient
compression ACROSS processes: the quantize/error-feedback/reduce
pipeline must compile into the cross-process program and sum exactly
for values on the quantization lattice (+/-threshold).
"""
import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx
from mxnet_tpu import nd, kvstore


def main():
    kv = kvstore.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    assert nw == 3, f"expected 3 workers, got {nw}"
    assert jax.device_count() == 3, jax.device_count()

    t = 0.5
    kv.set_gradient_compression({"type": "2bit", "threshold": t})
    kv.init("g", nd.zeros((6, 4)))

    # values ON the quantization lattice: sign pattern varies per rank
    sign = 1.0 if rank % 2 == 0 else -1.0
    v = nd.full((6, 4), sign * t)
    out = nd.zeros((6, 4))
    kv.pushpull("g", v, out=out)
    # ranks 0,2 push +t; rank 1 pushes -t -> sum = +t
    want = t * (2 - 1)
    assert np.allclose(out.asnumpy(), want), out.asnumpy()

    kv.barrier()

    # second round exercises the error-feedback state cross-process:
    # push 0.4*t (below threshold -> quantizes to 0, residual kept);
    # then push 0.7*t (residual 0.4t + 0.7t = 1.1t -> quantizes to +t)
    v2 = nd.full((6, 4), 0.4 * t)
    out2 = nd.zeros((6, 4))
    kv.pushpull("g", v2, out=out2)
    assert np.allclose(out2.asnumpy(), 0.0), out2.asnumpy()
    v3 = nd.full((6, 4), 0.7 * t)
    out3 = nd.zeros((6, 4))
    kv.pushpull("g", v3, out=out3)
    assert np.allclose(out3.asnumpy(), 3 * t), out3.asnumpy()

    kv.barrier()
    print(f"DIST3_WORKER_{rank}_OK", flush=True)


if __name__ == "__main__":
    main()
