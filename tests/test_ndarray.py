"""NDArray basics (reference tests/python/unittest/test_ndarray.py scope)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal


def test_creation():
    x = nd.zeros((2, 3))
    assert x.shape == (2, 3)
    assert x.dtype == np.float32
    assert x.asnumpy().sum() == 0
    y = nd.ones((4,), dtype="int32")
    assert y.dtype == np.int32
    z = nd.full((2, 2), 7.0)
    assert (z.asnumpy() == 7).all()
    a = nd.array([[1, 2], [3, 4]])
    assert a.shape == (2, 2)
    r = nd.arange(0, 10, 2)
    assert (r.asnumpy() == np.arange(0, 10, 2)).all()


def test_python_float_defaults_to_f32():
    a = nd.array([1.5, 2.5])
    assert a.dtype == np.float32


def test_arith_broadcast():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([10.0, 20.0])
    c = a + b
    assert_almost_equal(c, np.array([[11, 22], [13, 24]], np.float32))
    d = a * 2 + 1
    assert_almost_equal(d, a.asnumpy() * 2 + 1)
    e = 1 - a
    assert_almost_equal(e, 1 - a.asnumpy())
    f = a / b
    assert_almost_equal(f, a.asnumpy() / b.asnumpy())
    g = a ** 2
    assert_almost_equal(g, a.asnumpy() ** 2)


def test_inplace_ops_bump_version():
    a = nd.ones((3,))
    v0 = a._version
    a += 1
    assert a._version == v0 + 1
    assert_almost_equal(a, np.full(3, 2.0, np.float32))
    a *= 3
    assert_almost_equal(a, np.full(3, 6.0, np.float32))


def test_indexing():
    a = nd.array(np.arange(24).reshape(2, 3, 4))
    assert_almost_equal(a[1], np.arange(24).reshape(2, 3, 4)[1])
    assert_almost_equal(a[:, 1:3], np.arange(24).reshape(2, 3, 4)[:, 1:3])
    assert float(a[1, 2, 3].asscalar()) == 23
    a[0] = 0
    assert a.asnumpy()[0].sum() == 0
    a[1, 0] = nd.array([9., 9, 9, 9])
    assert (a.asnumpy()[1, 0] == 9).all()


def test_reshape_special_codes():
    a = nd.zeros((2, 3, 4))
    assert a.reshape((4, -1)).shape == (4, 6)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.reshape((-2,)).shape == (2, 3, 4)
    assert a.reshape((-3, 4)).shape == (6, 4)
    assert a.reshape((2, -4, 1, 3, 4)).shape == (2, 1, 3, 4)


def test_reductions():
    a = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    assert float(a.sum().asscalar()) == 66
    assert_almost_equal(a.sum(axis=0), a.asnumpy().sum(0))
    assert_almost_equal(a.mean(axis=1, keepdims=True), a.asnumpy().mean(1, keepdims=True))
    assert float(a.max().asscalar()) == 11
    assert float(a.min().asscalar()) == 0
    assert_almost_equal(a.argmax(axis=1), a.asnumpy().argmax(1).astype(np.float32))
    assert abs(float(a.norm().asscalar()) - np.linalg.norm(a.asnumpy())) < 1e-4


def test_dot():
    a = nd.array(np.random.rand(3, 4).astype(np.float32))
    b = nd.array(np.random.rand(4, 5).astype(np.float32))
    assert_almost_equal(nd.dot(a, b), a.asnumpy() @ b.asnumpy())
    assert_almost_equal(nd.dot(a, b.T, transpose_b=True).shape, (3, 4) and nd.dot(a, b).shape)
    bd = nd.batch_dot(nd.array(np.random.rand(2, 3, 4).astype(np.float32)),
                      nd.array(np.random.rand(2, 4, 5).astype(np.float32)))
    assert bd.shape == (2, 3, 5)


def test_concat_split_stack():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    c = nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)
    parts = nd.split(c, num_outputs=2, axis=0)
    assert len(parts) == 2 and parts[0].shape == (2, 3)


def test_topk_sort():
    a = nd.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]])
    idx = nd.topk(a, k=2)
    assert idx.shape == (2, 2)
    assert (idx.asnumpy()[0] == [0, 2]).all()
    vals = nd.topk(a, k=1, ret_typ="value")
    assert (vals.asnumpy().ravel() == [3, 5]).all()
    srt = nd.sort(a, is_ascend=False)
    assert (srt.asnumpy()[0] == [3, 2, 1]).all()


def test_take_pick_onehot():
    w = nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    idx = nd.array([0, 2], dtype="int32")
    t = nd.take(w, idx)
    assert_almost_equal(t, w.asnumpy()[[0, 2]])
    p = nd.pick(w, nd.array([0, 1, 2, 0]), axis=1)
    assert (p.asnumpy() == [0, 4, 8, 9]).all()
    oh = nd.one_hot(nd.array([1, 0], dtype="int32"), 3)
    assert (oh.asnumpy() == [[0, 1, 0], [1, 0, 0]]).all()


def test_astype_copyto_context():
    a = nd.ones((2, 2))
    b = a.astype("float64")
    assert b.dtype == np.float64
    c = a.copyto(mx.current_context())
    assert (c.asnumpy() == 1).all()
    d = nd.zeros((2, 2))
    a.copyto(d)
    assert (d.asnumpy() == 1).all()


def test_wait_and_waitall():
    a = nd.ones((10, 10))
    b = a * 2
    b.wait_to_read()
    mx.waitall()
    assert (b.asnumpy() == 2).all()


def test_save_load_roundtrip(tmp_path):
    f = str(tmp_path / "arrs.params")
    d = {"w": nd.array([[1.0, 2.0]]), "b": nd.arange(0, 5)}
    nd.save(f, d)
    loaded = nd.load(f)
    assert set(loaded) == {"w", "b"}
    assert_almost_equal(loaded["w"], d["w"])
    assert_almost_equal(loaded["b"], d["b"])
    # list save
    nd.save(f, [nd.ones((2,))])
    lst = nd.load(f)
    assert isinstance(lst, list) and len(lst) == 1


def test_serialization_bf16_roundtrip(tmp_path):
    import jax.numpy as jnp
    f = str(tmp_path / "bf16.params")
    a = nd.ones((3, 3)).astype("bfloat16")
    nd.save(f, {"a": a})
    out = nd.load(f)["a"]
    assert str(out.dtype) == "bfloat16"
    assert (out.asnumpy().astype(np.float32) == 1).all()


def test_where_clip():
    a = nd.array([-1.0, 0.5, 2.0])
    assert (a.clip(0, 1).asnumpy() == [0, 0.5, 1]).all()
    w = nd.where(a > 0, a, nd.zeros_like(a))
    assert (w.asnumpy() == [0, 0.5, 2.0]).all()


def test_comparison_returns_input_dtype():
    a = nd.array([1.0, 2.0])
    b = nd.array([1.0, 3.0])
    eq = (a == b)
    assert eq.dtype == np.float32
    assert (eq.asnumpy() == [1, 0]).all()


def test_iter_len():
    a = nd.array(np.arange(6).reshape(3, 2))
    assert len(a) == 3
    rows = list(a)
    assert rows[1].shape == (2,)


def test_sgd_mom_update_rsp_matches_dense():
    # Sparse lazy momentum must use the same lr-inside convention as the
    # dense sgd_mom_update op, so momentum state is interchangeable and
    # trajectories agree on touched rows under an lr schedule
    # (ADVICE r1: sparse.py used lr-outside and diverged).
    from mxnet_tpu.ndarray.sparse import row_sparse_array, sgd_mom_update_rsp

    rng = np.random.RandomState(7)
    n, d = 10, 4
    w0 = rng.randn(n, d).astype(np.float32)
    m0 = rng.randn(n, d).astype(np.float32)
    rows = np.array([1, 4, 7])
    g = rng.randn(len(rows), d).astype(np.float32)

    w_s = nd.array(w0.copy())
    m_s = nd.array(m0.copy())
    grad = row_sparse_array((g, rows), shape=(n, d))
    w_d = nd.array(w0[rows].copy())
    m_d = nd.array(m0[rows].copy())

    for lr in (0.1, 0.03):  # schedule: convention mismatch shows up here
        sgd_mom_update_rsp(w_s, grad, m_s, lr=lr, momentum=0.9, wd=0.01)
        nd.sgd_mom_update(w_d, nd.array(g), m_d, lr=lr, momentum=0.9,
                          wd=0.01, out=w_d)

    assert_almost_equal(w_s.asnumpy()[rows], w_d.asnumpy(), rtol=1e-6,
                        atol=1e-6)
    assert_almost_equal(m_s.asnumpy()[rows], m_d.asnumpy(), rtol=1e-6,
                        atol=1e-6)
    untouched = np.setdiff1d(np.arange(n), rows)
    assert (w_s.asnumpy()[untouched] == w0[untouched]).all()
    assert (m_s.asnumpy()[untouched] == m0[untouched]).all()
