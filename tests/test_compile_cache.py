"""Persistent compilation cache + warm fleet restarts (ISSUE 7):
compile_cache configuration/manifests, the engine's 3-way
memory_hit/persistent_hit/miss split, cross-PROCESS cache-key
stability (subprocess golden: the second process serving the same
model/bucket records persistent_hit where the first recorded miss),
the watchdog's first-visit-compile tolerance, and the 2-engine
rolling-restart drill (zero request loss through failover, warm
replacement replays the router's fleet manifest)."""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401  (backend init before serving)
from mxnet_tpu import compile_cache, nd
from mxnet_tpu.serving import ServingEngine, ServingRouter
from mxnet_tpu.telemetry import events
from mxnet_tpu.telemetry import recorder as flight

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class StubModel:
    def __init__(self, delay=0.0):
        self.delay = delay
        self.shapes = []

    def __call__(self, ids, token_types, valid_length, segment_ids,
                 positions):
        if self.delay:
            time.sleep(self.delay)
        self.shapes.append(tuple(ids.shape))
        return nd.array(ids.asnumpy().astype(np.float32)[..., None])


# ---------------------------------------------------------------------------
# module units
# ---------------------------------------------------------------------------

def test_configure_respects_env_knobs(tmp_path, monkeypatch):
    import jax

    monkeypatch.setenv("MXNET_TPU_COMPILE_CACHE_DIR",
                       str(tmp_path / "cc"))
    monkeypatch.setenv("MXNET_TPU_COMPILE_CACHE_MIN_S", "0.25")
    st = compile_cache.configure(force=True)
    assert st["configured"]
    assert st["dir"] == str(tmp_path / "cc")
    assert st["min_s"] == 0.25
    assert os.path.isdir(st["dir"])
    assert jax.config.jax_compilation_cache_dir == str(tmp_path / "cc")
    assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.25
    # idempotent: the no-arg call does not re-point anything
    assert compile_cache.configure()["dir"] == str(tmp_path / "cc")
    # explicit argument wins over env
    st = compile_cache.configure(cache_dir=str(tmp_path / "cc2"))
    assert st["dir"] == str(tmp_path / "cc2")


def test_configure_gate_off(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_COMPILE_CACHE", "0")
    saved = dict(compile_cache._state)
    try:
        compile_cache._state.update(configured=False, dir=None,
                                    min_s=None)
        st = compile_cache.configure()
        assert not st["configured"]
        assert not compile_cache.enabled()
    finally:
        compile_cache._state.update(saved)


def test_classify_and_snapshot_delta():
    a = {"persistent_hits": 3, "persistent_misses": 5}
    hit = {"persistent_hits": 5, "persistent_misses": 5}
    fresh = {"persistent_hits": 5, "persistent_misses": 6}
    idle = {"persistent_hits": 3, "persistent_misses": 5}
    assert compile_cache.classify(a, hit) == "persistent_hit"
    assert compile_cache.classify(a, fresh) == "miss"
    # no compile at all (pure in-memory replay) is not a disk hit
    assert compile_cache.classify(a, idle) == "miss"


def test_manifest_merge_save_load_roundtrip(tmp_path):
    m0 = compile_cache.new_manifest("e0", (64, 256), 8,
                                    [(1, 64), (2, 64)])
    m1 = compile_cache.new_manifest("e1", (64,), 4, [(4, 64)])
    merged = compile_cache.merge_manifests([m0, None, m1])
    assert merged["engines"] == ["e0", "e1"]
    assert merged["bucket_lens"] == [64, 256]
    assert merged["max_rows"] == 8
    assert compile_cache.manifest_shapes(merged) == \
        [(1, 64), (2, 64), (4, 64)]
    path = compile_cache.save_manifest(merged,
                                       str(tmp_path / "m" / "fleet.json"))
    loaded = compile_cache.load_manifest(path)
    assert compile_cache.manifest_shapes(loaded) == \
        compile_cache.manifest_shapes(merged)
    # malformed file degrades to None, not a crash
    with open(path, "w") as f:
        f.write("{not json")
    assert compile_cache.load_manifest(path) is None
    assert compile_cache.load_manifest(str(tmp_path / "absent")) is None
    assert compile_cache.merge_manifests([None, None]) is None
    assert compile_cache.manifest_shapes({"shapes": "bogus"}) == []
    # a structurally malformed part (version-skewed remote) is
    # skipped, never raised — the valid parts still merge
    broken = {"engines": ["ev"], "bucket_lens": ["x"],
              "shapes": ["not-a-pair"], "max_rows": "?"}
    merged2 = compile_cache.merge_manifests([broken, m0])
    assert compile_cache.manifest_shapes(merged2) == [(1, 64), (2, 64)]


# ---------------------------------------------------------------------------
# manifest round-trip: engine export -> router collect/persist -> replay
# ---------------------------------------------------------------------------

def test_manifest_roundtrip_engine_router_replay(tmp_path, monkeypatch):
    manifest_file = str(tmp_path / "fleet_manifest.json")
    monkeypatch.setenv("MXNET_TPU_WARMUP_MANIFEST", manifest_file)
    e0 = ServingEngine(StubModel(), bucket_lens=(8, 16), max_rows=2,
                       engine_id="mr-e0").start()
    router = ServingRouter(engines=[e0], poll_interval_s=0.05).start()
    try:
        for toks in ([1, 2, 3], list(range(12)), [5] * 10):
            router.submit(toks).result(timeout=30)
        visited = set(compile_cache.manifest_shapes(e0.warmup_manifest()))
        assert visited                      # at least one bucket seen
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            persisted = compile_cache.load_manifest(manifest_file)
            if persisted and set(compile_cache.manifest_shapes(
                    persisted)) == visited:
                break
            time.sleep(0.05)
        assert persisted, "router never persisted the fleet manifest"
        assert set(compile_cache.manifest_shapes(persisted)) == visited
        assert persisted["engines"] == ["mr-e0"]
        assert router.snapshot()["manifest_shapes"] == len(visited)
    finally:
        router.stop()
        e0.stop()

    # a fresh engine replays EXACTLY the persisted manifest (not the
    # whole universe), straight from the file path
    stub = StubModel()
    e1 = ServingEngine(stub, bucket_lens=(8, 16), max_rows=2,
                       engine_id="mr-e1").start()
    try:
        e1.warmup(manifest=manifest_file)
        assert set(stub.shapes) == visited
        assert set(compile_cache.manifest_shapes(
            e1.warmup_manifest())) == visited
    finally:
        e1.stop()

    # incompatible bucket config: every manifest shape is skipped
    stub2 = StubModel()
    e2 = ServingEngine(stub2, bucket_lens=(64,), max_rows=1,
                       engine_id="mr-e2").start()
    try:
        e2.warmup(manifest=compile_cache.load_manifest(manifest_file))
        assert stub2.shapes == []
    finally:
        e2.stop()


def test_engine_snapshot_and_healthz_carry_cache_fields():
    eng = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=1,
                        engine_id="snap-e")
    with eng:
        eng.infer([1, 2], timeout=30)
        eng.infer([3, 4], timeout=30)
        snap = eng.snapshot()
        assert snap["compile_cache"]["memory_hit"] == 1
        assert (snap["compile_cache"]["miss"]
                + snap["compile_cache"]["persistent_hit"]) == 1
        assert snap["manifest_shapes"] == 1
        assert snap["compiling"] is False
        srv = eng.expose()
        import urllib.request
        hz = json.loads(urllib.request.urlopen(
            srv.url("/healthz"), timeout=10).read())
        assert hz["compiling"] is False
        man = json.loads(urllib.request.urlopen(
            srv.url("/warmup"), timeout=10).read())
        assert compile_cache.manifest_shapes(man) == [(1, 16)]


# ---------------------------------------------------------------------------
# cross-process golden: the cache key survives a process restart
# ---------------------------------------------------------------------------

def _run_golden_worker(cache_dir):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXNET_TPU_COMPILE_CACHE_DIR=str(cache_dir),
               MXNET_TPU_COMPILE_CACHE_MIN_S="0",
               MXNET_TPU_WATCHDOG="0")
    out = subprocess.run(
        [sys.executable,
         os.path.join(ROOT, "tests", "compile_cache_worker.py")],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-4000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_cross_process_persistent_hit_golden(tmp_path):
    """THE acceptance golden: process 1 cold-compiles (miss), process
    2 — same model, same bucket, same cache dir — serves off the disk
    cache and records persistent_hit without a fresh backend compile."""
    cache_dir = tmp_path / "shared_cache"
    first = _run_golden_worker(cache_dir)
    assert first["compile_cache"]["miss"] >= 1
    assert first["compile_cache"]["persistent_hit"] == 0
    assert first["state"]["dir"] == str(cache_dir)
    assert os.listdir(cache_dir), "nothing persisted to the cache dir"

    second = _run_golden_worker(cache_dir)
    assert second["compile_cache"]["persistent_hit"] >= 1
    assert second["compile_cache"]["miss"] == 0, \
        "second process recompiled despite the primed persistent cache"
    assert second["jax_events"]["persistent_hits"] >= 1


# ---------------------------------------------------------------------------
# watchdog compile tolerance (ROADMAP carried follow-up)
# ---------------------------------------------------------------------------

def test_watchdog_tolerates_first_visit_compile_but_trips_on_stall(
        tmp_path, monkeypatch):
    """A first-visit 'compile' longer than the stall threshold must
    NOT trip the serving-stall probe (the compile window widens it);
    a genuine stall on an already-compiled shape still must."""
    monkeypatch.setenv("MXNET_TPU_FLIGHT_DIR", str(tmp_path / "flight"))
    events.configure(str(tmp_path / "wd.jsonl"))
    saved = flight.configure()
    flight.configure(interval_s=0.05, stall_s=0.3,
                     min_dump_interval_s=0.0)
    gate = threading.Event()

    class CompileThenStall:
        """1st call per shape: slow (a compile). Later calls: instant,
        except the 3rd overall which blocks — a wedged forward."""

        def __init__(self):
            self.calls = 0

        def __call__(self, ids, token_types, valid_length, segment_ids,
                     positions):
            self.calls += 1
            if self.calls == 1:
                time.sleep(0.9)             # compile >> stall_s
            elif self.calls >= 3:
                gate.wait(30)               # genuine stall
            return nd.array(ids.asnumpy().astype(np.float32)[..., None])

    eng = ServingEngine(CompileThenStall(), bucket_lens=(16,),
                        max_rows=1)
    log_path = None
    try:
        eng.start()
        eng.infer([1, 2, 3], timeout=30)    # slow first-visit compile
        log_path = events.get_log().path
        time.sleep(0.4)                     # several watchdog polls
        trips = events.read_events(log_path, event="watchdog_anomaly")
        stalls = [t for t in trips
                  if t.get("kind") == "serving_worker_stall"]
        assert not stalls, f"compile window tripped the watchdog: {stalls}"
        compiles = events.read_events(log_path, event="compile_end")
        assert compiles and compiles[0]["result"] in ("miss",
                                                      "persistent_hit")

        eng.infer([4, 5], timeout=30)       # memory_hit, fast
        fut = eng.submit([6, 7, 8])         # 3rd call: wedges
        deadline = time.monotonic() + 20
        stalls = []
        while time.monotonic() < deadline and not stalls:
            trips = events.read_events(log_path, event="watchdog_anomaly")
            stalls = [t for t in trips
                      if t.get("kind") == "serving_worker_stall"]
            time.sleep(0.05)
        assert stalls, "genuine stall never tripped the watchdog"
    finally:
        gate.set()
        try:
            fut.result(timeout=30)
        except Exception:
            pass
        eng.stop()
        events.configure(None)
        flight.configure(**saved)
    # the compile window produced no bundle; the stall did
    root = str(tmp_path / "flight")
    bundles = [d for d in os.listdir(root)] if os.path.isdir(root) else []
    assert any("serving_worker_stall" in d for d in bundles
               if not d.endswith(".tmp"))


def test_router_poll_does_not_mark_compiling_engine_down():
    """The router's wedge detection (stale beat + queued work) must
    exempt an engine whose healthz reports an open compile window —
    but only within the SAME finite grace as the engine watchdog: a
    compile outliving stall+grace is a wedge."""
    eng = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=1,
                        engine_id="cw-e0")
    router = ServingRouter(engines=[eng], poll_interval_s=60.0,
                           health_fail_after=1)
    with eng:
        router.start()
        try:
            seat = router._seats["cw-e0"]
            # beat age above the stall threshold (30 s default) but
            # inside stall+grace (330 s default)
            snap = {"running": True, "queue_depth": 3,
                    "seconds_since_beat": 100.0, "compiling": True,
                    "manifest_shapes": 0, "counters": {}}
            with pytest.MonkeyPatch.context() as mp:
                mp.setattr(seat, "health",
                           lambda: (True, dict(snap)))
                router._poll_once()
                assert seat.routable        # compiling: exempt
                mp.setattr(seat, "health", lambda: (
                    True, dict(snap, seconds_since_beat=10_000.0)))
                router._poll_once()         # compile outlived grace
                assert not seat.routable
        finally:
            router.stop()


# ---------------------------------------------------------------------------
# rolling-restart drill (in-process, 2 engines)
# ---------------------------------------------------------------------------

def test_restart_drill_zero_loss_and_warm_replay(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_WARMUP_MANIFEST",
                       str(tmp_path / "drill_manifest.json"))
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    from serve_loadgen import run_load

    e0 = ServingEngine(StubModel(delay=0.02), bucket_lens=(8, 16),
                       max_rows=2, engine_id="rd-e0").start()
    e1 = ServingEngine(StubModel(delay=0.02), bucket_lens=(8, 16),
                       max_rows=2, engine_id="rd-e1").start()
    router = ServingRouter(engines=[e0, e1],
                           poll_interval_s=0.05).start()
    clients, reqs = 4, 24
    total = clients * reqs
    replacement = []
    drill_err = []

    def controller():
        try:
            while router.count("completed") < total // 6:
                time.sleep(0.01)
            # kill only while the victim actually has work IN FLIGHT:
            # the drill must always exercise the failover-requeue path
            # (a lucky kill between dispatches would count 0 failovers)
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                row = router.scoreboard().get("rd-e1") or {}
                if row.get("outstanding", 0) > 0:
                    break
                time.sleep(0.002)
            e1.stop(drain=False)
            router.remove_engine("rd-e1")
            stub = StubModel(delay=0.02)
            fresh = ServingEngine(stub, bucket_lens=(8, 16), max_rows=2,
                                  engine_id="rd-e1").start()
            manifest = router.warmup_manifest()
            fresh.warmup(manifest=manifest)
            replacement.append((fresh, stub, manifest))
            router.add_engine("rd-e1", fresh)
        except Exception as e:
            drill_err.append(e)

    ctl = threading.Thread(target=controller, daemon=True,
                           name="test_restart_controller")
    try:
        ctl.start()
        report = run_load(router, n_clients=clients,
                          requests_per_client=reqs, min_len=4,
                          max_len=16, vocab=100)
        ctl.join(timeout=60)
        assert not drill_err, drill_err
        # ZERO LOSS: the kill translated into failover requeues, every
        # submitted request completed, none errored
        assert report["completed"] == total, report
        assert report["errors"] == 0 and report["shed"] == 0, report
        assert report["failovers"] >= 1
        # the loadgen observed the restart and timed first service
        restarts = report.get("restarts")
        assert restarts and restarts[0]["engine_id"] == "rd-e1", report
        assert restarts[0]["ttft_ms"] is not None
        # warm replacement replayed the manifest it was handed (the
        # fleet manifest may GROW afterwards as traffic continues)
        fresh, stub, manifest = replacement[0]
        replayed = set(stub.shapes[:len(
            compile_cache.manifest_shapes(manifest))])
        assert replayed == set(compile_cache.manifest_shapes(manifest))
    finally:
        router.stop()
        e0.stop()
        for eng, *_ in replacement:
            eng.stop()


def test_remove_engine_unknown_raises():
    eng = ServingEngine(StubModel(), bucket_lens=(8,), max_rows=1,
                        engine_id="rm-e0")
    router = ServingRouter(engines=[eng])
    with pytest.raises(KeyError):
        router.remove_engine("nope")


# ---------------------------------------------------------------------------
# telemetry_dump split helper
# ---------------------------------------------------------------------------

def test_telemetry_dump_compile_cache_split():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    import telemetry_dump

    text = "\n".join([
        'mxnet_tpu_serving_compile_cache_total{engine_id="a",'
        'result="memory_hit"} 5',
        'mxnet_tpu_serving_compile_cache_total{engine_id="a",'
        'result="persistent_hit"} 2',
        'mxnet_tpu_serving_compile_cache_total{engine_id="b",'
        'result="miss"} 1',
        'mxnet_tpu_compile_cache_persistent_total{result="hit"} 2',
        'mxnet_tpu_compile_cache_persistent_total{result="miss"} 3',
    ]) + "\n"
    split = telemetry_dump.compile_cache_split(text)
    assert split["a"] == {"memory_hit": 5.0, "persistent_hit": 2.0}
    assert split["b"] == {"miss": 1.0}
    assert split["(jax)"] == {"persistent_hit": 2.0,
                              "persistent_miss": 3.0}
