"""Autograd (reference tests/python/unittest/test_autograd.py scope)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient


def test_simple_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy())


def test_chain_rule():
    x = nd.array([[0.5, -0.5], [1.0, 2.0]])
    x.attach_grad()
    with autograd.record():
        y = nd.exp(x) * 2
        z = y.sum()
    z.backward()
    assert_almost_equal(x.grad, 2 * np.exp(x.asnumpy()), rtol=1e-5)


def test_grad_req_add():
    x = nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = (x * 2).sum()
        y.backward()
    assert_almost_equal(x.grad, np.full(2, 6.0, np.float32))


def test_grad_req_null():
    x = nd.array([1.0])
    x.attach_grad(grad_req="null")
    with autograd.record():
        y = x * 2
    y.backward()  # should not raise; no grad written


def test_backward_non_scalar_uses_ones():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward()
    assert_almost_equal(x.grad, np.full(3, 3.0, np.float32))


def test_head_grad():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(nd.array([1.0, 10.0]))
    assert_almost_equal(x.grad, np.array([2.0, 40.0], np.float32))


def test_detach_stops_grad():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = y.detach() * x
    z.backward()
    # dz/dx through detach path only: z = const(4)*x -> grad 4... wait
    # y.detach() is constant 4; z = 4*x; dz/dx = 4
    assert_almost_equal(x.grad, np.array([4.0], np.float32))


def test_stop_gradient_op():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = nd.BlockGrad(x) * x
    y.backward()
    assert_almost_equal(x.grad, np.array([3.0], np.float32))


def test_pause_scope():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        with autograd.pause():
            w = x * 100  # not recorded
        z = y + w.detach()
    z.backward()
    assert_almost_equal(x.grad, np.array([2.0], np.float32))


def test_is_training_flags():
    assert not autograd.is_training()
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_training()
        assert autograd.is_recording()
        with autograd.predict_mode():
            assert not autograd.is_training()
            assert autograd.is_recording()
    with autograd.train_mode():
        assert autograd.is_training()


def test_grad_function():
    x = nd.array([1.0, 2.0])
    with autograd.record():
        y = (x * x * x).sum()
    grads = autograd.grad([y], [x])
    assert_almost_equal(grads[0], 3 * x.asnumpy() ** 2)


def test_multi_input_op_grads():
    a = nd.array([1.0, 2.0])
    b = nd.array([3.0, 4.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = (a * b).sum()
    c.backward()
    assert_almost_equal(a.grad, b.asnumpy())
    assert_almost_equal(b.grad, a.asnumpy())


def test_integer_input_no_grad():
    w = nd.array(np.random.rand(5, 3).astype(np.float32))
    idx = nd.array([0, 2], dtype="int32")
    w.attach_grad()
    with autograd.record():
        out = nd.Embedding(idx, w, input_dim=5, output_dim=3).sum()
    out.backward()
    expected = np.zeros((5, 3), np.float32)
    expected[[0, 2]] = 1
    assert_almost_equal(w.grad, expected)


def test_retain_graph():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(retain_graph=True)
    g1 = x.grad.asnumpy().copy()
    y.backward()
    assert_almost_equal(x.grad, g1)


def test_mark_variables():
    x = nd.array([1.0, 1.0])
    g = nd.zeros((2,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = (x * 5).sum()
    y.backward()
    assert_almost_equal(g, np.full(2, 5.0, np.float32))


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = 1 / (1 + nd.exp(-x))
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            y, = self.saved_tensors
            return dy * y * (1 - y)

    x = nd.array([0.0, 1.0])
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(x.grad, s * (1 - s), rtol=1e-5)


def test_numeric_gradient_matmul():
    check_numeric_gradient(
        lambda a, b: nd.dot(a, b),
        [np.random.rand(3, 4), np.random.rand(4, 2)],
        rtol=5e-2, atol=5e-3)


def test_sgd_update_inplace_during_record():
    """Optimizer writes must not corrupt earlier tape state (versioning)."""
    w = nd.array([1.0, 2.0])
    w.attach_grad()
    with autograd.record():
        loss = (w * w).sum()
    loss.backward()
    old_grad = w.grad.asnumpy().copy()
    # in-place update outside record
    nd.sgd_update(w, w.grad, lr=0.1, out=w)
    assert_almost_equal(w, np.array([1.0, 2.0]) - 0.1 * old_grad)


def test_multi_head_disjoint_backward():
    """`for l in losses: l.backward()` (the DP pattern): disjoint heads
    recorded in one scope each get a full, correct sweep."""
    x1 = nd.array([1.0, 2.0])
    x2 = nd.array([3.0, 4.0])
    x1.attach_grad()
    x2.attach_grad()
    with autograd.record():
        l1 = (x1 * x1).sum()
        l2 = (x2 * 3.0).sum()
    l1.backward()
    l2.backward()
    assert np.allclose(x1.grad.asnumpy(), [2.0, 4.0])
    assert np.allclose(x2.grad.asnumpy(), [3.0, 3.0])


def test_second_backward_through_freed_subgraph_raises():
    """Two heads SHARING a subgraph: the first non-retain backward frees
    the shared nodes; the second must raise (reference
    Imperative::Backward on released AGInfo) — never silently return a
    partial gradient."""
    import pytest
    from mxnet_tpu.base import MXNetError
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2.0
        l1 = (y * 3.0).sum()
        l2 = (y * 5.0).sum()
    l1.backward()
    with pytest.raises(MXNetError, match="already freed"):
        l2.backward()


def test_shared_subgraph_retain_graph():
    """retain_graph=True keeps the shared subgraph usable for the
    second head."""
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2.0
        l1 = (y * 3.0).sum()
        l2 = (y * 5.0).sum()
    l1.backward(retain_graph=True)
    assert np.allclose(x.grad.asnumpy(), [6.0, 6.0])
    l2.backward()
    assert np.allclose(x.grad.asnumpy(), [10.0, 10.0])
