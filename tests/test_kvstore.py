"""KVStore + multi-device Trainer tests.

Reference scope: tests/python/unittest/test_kvstore.py (multi-device
local store invariants) plus the VERDICT round-1 requirement that the
MXNet-shaped `net.hybridize(); trainer.step()` path reduces gradients
through ONE compiled XLA computation whose HLO contains an all-reduce
(the kvstore_nccl.h fused-pushpull analog), on a multi-device mesh.

Runs on the virtual 8-device CPU mesh from conftest.py.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon, kvstore
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.utils import split_and_load
from mxnet_tpu.parallel import comm as allreduce
from mxnet_tpu.test_utils import assert_almost_equal

NCTX = min(2, len(mx.context._all_devices("cpu")) if hasattr(mx.context, "_all_devices") else 2)
CTXS = [mx.cpu(0), mx.cpu(1)]


def test_kvstore_init_push_pull():
    kv = kvstore.create("local")
    kv.init(3, nd.ones((2, 3)))
    out = nd.zeros((2, 3))
    kv.pull(3, out=out)
    assert_almost_equal(out, np.ones((2, 3), np.float32))


def test_kvstore_push_multi_device_sums():
    kv = kvstore.create("device")
    kv.init("w", nd.zeros((4, 2)))
    vals = [nd.full((4, 2), float(i + 1), ctx=c) for i, c in enumerate(CTXS)]
    kv.push("w", vals)
    out = nd.zeros((4, 2))
    kv.pull("w", out=out)
    assert_almost_equal(out, np.full((4, 2), 3.0, np.float32))


def test_kvstore_fused_pushpull_multi_key():
    kv = kvstore.create("device")
    shapes = [(3,), (2, 2), (5, 1)]
    keys = list(range(len(shapes)))
    for k, s in zip(keys, shapes):
        kv.init(k, nd.zeros(s))
    grads = [[nd.full(s, float(k + 10 * i), ctx=c)
              for i, c in enumerate(CTXS)] for k, s in zip(keys, shapes)]
    kv.pushpull(keys, grads, out=grads)
    for k, s, g in zip(keys, shapes, grads):
        want = np.full(s, float(2 * k + 10), np.float32)
        for rep in g:
            assert_almost_equal(rep, want)
    # every replica of a key holds the identical reduced value
    hlo = allreduce.last_hlo_text()
    assert hlo and "all-reduce" in hlo, "fused pushpull did not compile to an all-reduce"


def _fit_one_step(ctx_list, x_np, y_np, lr=0.1, hybridize=True,
                  kvstore="device"):
    mx.random.seed(7)
    np.random.seed(7)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu", in_units=6), nn.Dense(3, in_units=8))
    net.initialize(init=mx.initializer.Xavier(), ctx=ctx_list)
    if hybridize:
        net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": lr},
                            kvstore=kvstore)
    xs = split_and_load(nd.array(x_np), ctx_list)
    ys = split_and_load(nd.array(y_np), ctx_list)
    with autograd.record():
        losses = [loss_fn(net(x), y) for x, y in zip(xs, ys)]
    for l in losses:
        l.backward()
    trainer.step(x_np.shape[0])
    return {name: p.data(ctx_list[0]).asnumpy()
            for name, p in net.collect_params().items()}


@pytest.mark.parametrize("hybridize", [False, True])
def test_trainer_multi_device_matches_single(hybridize):
    """DP invariant: one step on 2 devices with a split batch equals one
    step on 1 device with the full batch (reference executor_group /
    kvstore 'device' semantics)."""
    np.random.seed(3)
    x = np.random.randn(8, 6).astype(np.float32)
    y = np.random.randint(0, 3, (8,)).astype(np.float32)
    single = _fit_one_step([mx.cpu(0)], x, y, hybridize=hybridize)
    multi = _fit_one_step(CTXS, x, y, hybridize=hybridize)
    assert len(single) == len(multi)
    # param names differ only by the global name-scope counter; order is
    # construction order in both runs
    for (_, s), (_, m) in zip(single.items(), multi.items()):
        assert_almost_equal(m, s, rtol=1e-5, atol=1e-6)


def test_trainer_multi_device_compiles_to_allreduce():
    """The product path (Trainer.step over per-ctx replicas) must reduce
    via the jitted stacked-sum whose HLO contains an all-reduce — not an
    eager device_put+add chain (VERDICT round-1 item #1)."""
    allreduce._LAST_HLO[0] = None
    np.random.seed(4)
    x = np.random.randn(8, 6).astype(np.float32)
    y = np.random.randint(0, 3, (8,)).astype(np.float32)
    _fit_one_step(CTXS, x, y)
    hlo = allreduce.last_hlo_text()
    assert hlo is not None, "Trainer.step never reached the fused reduce path"
    assert "all-reduce" in hlo, hlo[:2000]


def test_trainer_step_one_reduce_dispatch(monkeypatch):
    """All params reduce in ONE reduce_replica_lists call per step."""
    calls = []
    orig = allreduce.reduce_replica_lists

    def spy(vlists, devices=None):
        calls.append(len(vlists))
        return orig(vlists, devices=devices)

    monkeypatch.setattr(allreduce, "reduce_replica_lists", spy)
    np.random.seed(5)
    x = np.random.randn(8, 6).astype(np.float32)
    y = np.random.randint(0, 3, (8,)).astype(np.float32)
    _fit_one_step(CTXS, x, y)
    assert len(calls) == 1, calls
    assert calls[0] == 4  # 2 layers x (weight, bias)


def test_row_sparse_pull_dense_and_sparse_dst():
    """On-device sparse pull: requested rows land in the dst (dense or
    row_sparse), duplicates merged, untouched rows zero — with no numpy
    round-trip (reference kvstore_local.h unique-rowid merge)."""
    from mxnet_tpu.ndarray import sparse as sp
    kv = kvstore.create("local")
    table = np.arange(20, dtype=np.float32).reshape(10, 2)
    kv.init("emb", nd.array(table))
    rid = nd.array(np.array([7, 2, 2, 5], np.int64))

    dense_dst = nd.zeros((10, 2))
    kv.row_sparse_pull("emb", out=dense_dst, row_ids=rid)
    want = np.zeros((10, 2), np.float32)
    want[[2, 5, 7]] = table[[2, 5, 7]]
    assert_almost_equal(dense_dst, want)

    rsp_dst = sp.row_sparse_array(
        (np.zeros((1, 2), np.float32), np.array([0], np.int64)), shape=(10, 2))
    kv.row_sparse_pull("emb", out=rsp_dst, row_ids=rid)
    assert rsp_dst.indices.asnumpy().tolist() == [2, 5, 7]
    assert_almost_equal(rsp_dst.data.asnumpy(), table[[2, 5, 7]])


def test_update_on_kvstore_multi_device():
    """update_on_kvstore=True: optimizer runs in the store on the summed
    gradient; weights pulled back identical across replicas."""
    np.random.seed(6)
    net = nn.Dense(3, in_units=4)
    net.initialize(init="ones", ctx=CTXS)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5}, kvstore="device",
                            update_on_kvstore=True)
    x = np.random.randn(4, 4).astype(np.float32)
    xs = split_and_load(nd.array(x), CTXS)
    with autograd.record():
        losses = [(net(xi) * net(xi)).sum() for xi in xs]
    for l in losses:
        l.backward()
    trainer.step(4)
    w0 = net.weight.data(CTXS[0]).asnumpy()
    w1 = net.weight.data(CTXS[1]).asnumpy()
    assert_almost_equal(w0, w1)
    assert not np.allclose(w0, np.ones_like(w0))  # an update happened


def test_fused_pushpull_foreign_device_falls_back():
    """Stored value on a device outside the reduce mesh: pushpull must
    take the copy path, not raise (review regression)."""
    kv = kvstore.create("device")
    with mx.cpu(3):
        kv.init("w", nd.zeros((2, 2), ctx=mx.cpu(3)))
    vals = [nd.full((2, 2), float(i + 1), ctx=c) for i, c in enumerate(CTXS)]
    kv.pushpull("w", vals, out=vals)
    for v in vals:
        assert_almost_equal(v, np.full((2, 2), 3.0, np.float32))


def test_gradient_compression_2bit_error_feedback():
    """2-bit compression: per step each element reduces to a multiple of
    the threshold; over many steps error feedback preserves the total
    gradient mass (reference gradient_compression.cc contract)."""
    kv = kvstore.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 1.0})
    kv.init("w", nd.zeros((4,)))
    g = 0.3
    total = np.zeros(4, np.float32)
    steps = 10
    for _ in range(steps):
        vals = [nd.full((4,), g, ctx=c) for c in CTXS]
        out = [nd.zeros((4,), ctx=c) for c in CTXS]
        kv.pushpull("w", vals, out=out)
        r = out[0].asnumpy()
        # each device contributes an element of {−1, 0, +1}·threshold
        assert np.all(np.isin(r, [-2.0, -1.0, 0.0, 1.0, 2.0])), r
        total += r
    want = steps * g * len(CTXS)
    assert np.all(np.abs(total - want) <= 1.0 + 1e-6), (total, want)


def test_gradient_compression_int8_close_to_exact():
    kv = kvstore.create("device")
    kv.set_gradient_compression({"type": "int8"})
    kv.init("w", nd.zeros((8,)))
    rs = np.random.RandomState(0)
    a = rs.randn(8).astype(np.float32)
    b = rs.randn(8).astype(np.float32)
    vals = [nd.array(a, ctx=CTXS[0]), nd.array(b, ctx=CTXS[1])]
    out = [nd.zeros((8,), ctx=c) for c in CTXS]
    kv.pushpull("w", vals, out=out)
    want = a + b
    amax = max(np.abs(a).max(), np.abs(b).max())
    assert np.abs(out[0].asnumpy() - want).max() <= 2 * amax / 127 + 1e-6


def test_gradient_compression_rejects_unknown_type():
    kv = kvstore.create("device")
    with pytest.raises(Exception, match="unsupported"):
        kv.set_gradient_compression({"type": "4bit"})


def test_trainer_with_compression_still_trains():
    np.random.seed(8)
    x = np.random.randn(16, 6).astype(np.float32)
    y = np.random.randint(0, 3, (16,)).astype(np.float32)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu", in_units=6), nn.Dense(3, in_units=8))
    net.initialize(init=mx.initializer.Xavier(), ctx=CTXS)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore="device",
                            compression_params={"type": "int8"})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    before = net[0].weight.data(CTXS[0]).asnumpy().copy()
    for _ in range(3):
        xs = split_and_load(nd.array(x), CTXS)
        ys = split_and_load(nd.array(y), CTXS)
        with autograd.record():
            losses = [loss_fn(net(xi), yi) for xi, yi in zip(xs, ys)]
        for l in losses:
            l.backward()
        trainer.step(16)
    after0 = net[0].weight.data(CTXS[0]).asnumpy()
    after1 = net[0].weight.data(CTXS[1]).asnumpy()
    assert not np.allclose(after0, before)
    assert_almost_equal(after0, after1)


def test_trainer_no_kvstore_still_reduces_replicas():
    """kvstore=None with multi-device replicas: grads must still sum
    (review regression — update-once-and-broadcast would otherwise drop
    every other replica's half of the batch)."""
    np.random.seed(9)
    x = np.random.randn(8, 6).astype(np.float32)
    y = np.random.randint(0, 3, (8,)).astype(np.float32)

    def one_step(ctx_list, kvstore):
        mx.random.seed(7)
        np.random.seed(7)
        net = nn.HybridSequential()
        net.add(nn.Dense(8, activation="relu", in_units=6),
                nn.Dense(3, in_units=8))
        net.initialize(init=mx.initializer.Xavier(), ctx=ctx_list)
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1}, kvstore=kvstore)
        xs = split_and_load(nd.array(x), ctx_list)
        ys = split_and_load(nd.array(y), ctx_list)
        with autograd.record():
            losses = [loss_fn(net(xi), yi) for xi, yi in zip(xs, ys)]
        for l in losses:
            l.backward()
        trainer.step(8)
        return [p.data(ctx_list[0]).asnumpy()
                for p in net.collect_params().values()]

    ref = one_step([mx.cpu(0)], None)
    multi = one_step(CTXS, None)
    for r, m in zip(ref, multi):
        assert_almost_equal(m, r, rtol=1e-5, atol=1e-6)


def test_horovod_kvstore_pushpull_and_restrictions():
    """kvstore='horovod' shim (reference KVStoreHorovod, v>=1.5):
    allreduce-only — pushpull/broadcast work, push/pull/optimizer raise."""
    kv = kvstore.create("horovod")
    assert kv.type == "horovod"
    assert kv.rank == 0 and kv.num_workers >= 1
    vals = [nd.full((4, 2), float(i + 1), ctx=c) for i, c in enumerate(CTXS)]
    kv.pushpull("w", vals, out=vals)
    for v in vals:
        assert_almost_equal(v, np.full((4, 2), 3.0, np.float32))
    with pytest.raises(mx.base.MXNetError):
        kv.push("w", vals)
    with pytest.raises(mx.base.MXNetError):
        kv.pull("w", out=nd.zeros((4, 2)))
    with pytest.raises(mx.base.MXNetError):
        kv.set_optimizer(mx.optimizer.SGD())
    # broadcast: root value lands in every out replica
    outs = [nd.zeros((3,), ctx=c) for c in CTXS]
    kv.broadcast("b", nd.arange(3), out=outs)
    for o in outs:
        assert_almost_equal(o, np.arange(3, dtype=np.float32))


def test_trainer_horovod_matches_device():
    """Trainer over the horovod store trains identically to 'device'
    (same compiled all-reduce underneath) and forbids
    update_on_kvstore=True."""
    np.random.seed(11)
    x = np.random.randn(8, 6).astype(np.float32)
    y = np.random.randint(0, 3, (8,)).astype(np.float32)
    dev = _fit_one_step(CTXS, x, y, kvstore="device")
    hvd = _fit_one_step(CTXS, x, y, kvstore="horovod")
    for (_, a), (_, b) in zip(dev.items(), hvd.items()):
        assert_almost_equal(b, a, rtol=1e-6, atol=1e-7)
    with pytest.raises(ValueError):
        gluon.Trainer(
            nn.Dense(2, in_units=2).collect_params(), "sgd", {},
            kvstore="horovod", update_on_kvstore=True)._init_kvstore()
