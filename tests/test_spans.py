"""Span / flight-recorder / watchdog tests (telemetry ISSUE 4): span
parent/child correctness under the threaded serving worker, tail-
sampling keep/drop decisions, cross-process span parenting over the
dist_async wire (old 3/4-tuple frames still accepted), watchdog trip
on an artificially stalled worker, SIGUSR2 flight-recorder bundle
contents, event-log rotation, and the disabled-path microbench guard
extended to span instrumentation.
"""
import json
import os
import signal
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, profiler
from mxnet_tpu.serving import ServingEngine
from mxnet_tpu.telemetry import events, spans, trace_context
from mxnet_tpu.telemetry import recorder as flight

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


class StubModel:
    def __call__(self, ids, token_types, valid_length, segment_ids,
                 positions):
        return nd.array(ids.asnumpy().astype(np.float32)[..., None])


@pytest.fixture()
def span_config():
    """Keep-everything span config, restored (with a clean ring) on
    exit so other tests see the defaults."""
    rec = spans.RECORDER
    saved = (spans.enabled(), rec.slow_ms, rec.max_traces, rec.max_spans)
    spans.configure(enabled=True, slow_ms=0.0)
    spans.reset()
    yield rec
    spans.configure(enabled=saved[0], slow_ms=saved[1],
                    max_traces=saved[2], max_spans=saved[3])
    spans.reset()


# ---------------------------------------------------------------------------
# span primitives
# ---------------------------------------------------------------------------

def test_span_nesting_parent_child_and_context(span_config):
    assert spans.current_span() is None
    with spans.span("outer", k=1) as outer:
        assert spans.current_span() is outer
        assert outer.parent_id is None and outer.local_root
        with spans.span("inner") as inner:
            assert inner.parent_id == outer.span_id
            assert inner.trace_id == outer.trace_id
            assert not inner.local_root
        assert spans.current_span() is outer
    assert spans.current_span() is None
    trace = spans.get_trace(outer.trace_id)
    assert trace is not None
    by_name = {s["name"]: s for s in trace["spans"]}
    assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
    # children finish (and record) before their parent
    assert trace["spans"][0]["name"] == "inner"


def test_span_error_status_propagates_and_reraises(span_config):
    with pytest.raises(RuntimeError):
        with spans.span("boom") as sp:
            raise RuntimeError("kapow")
    trace = spans.get_trace(sp.trace_id)
    assert trace["status"] == "error"
    assert trace["spans"][0]["status"] == "error"
    assert "kapow" in trace["spans"][0]["error"]


def test_manual_span_crosses_threads(span_config):
    """A start_span/end pair works across threads — the serving
    request root is started at submit and ended by the worker."""
    root = spans.start_span("root", trace_id="tid-threads")
    done = threading.Event()

    def worker():
        spans.record_span("child", "tid-threads",
                          parent_id=root.span_id,
                          mono_start=time.monotonic() - 0.01)
        done.set()

    threading.Thread(target=worker).start()
    assert done.wait(5)
    root.end()
    trace = spans.get_trace("tid-threads")
    names = {s["name"]: s for s in trace["spans"]}
    assert names["child"]["parent_id"] == names["root"]["span_id"]
    assert names["child"]["dur_us"] >= 9000


def test_tail_sampling_keep_and_drop_decisions():
    rec = spans.SpanRecorder(max_traces=4, slow_ms=50.0, max_spans=8,
                             max_active=8)
    saved = spans.RECORDER
    try:
        spans.RECORDER = rec

        with spans.span("fast"):
            pass                              # below threshold: drop
        with spans.span("errored") as e_sp:
            e_sp.end(error="x")               # error: keep
        with spans.span("shed") as f_sp:
            f_sp.force_keep()                 # forced: keep
        slow_sp = spans.start_span("slow")
        slow_sp.end(end_us=slow_sp.ts_us + 60_000)   # 60 ms: keep

        summary = rec.summary()
        kept = {k["root"]: k["keep_reason"] for k in summary["kept"]}
        assert kept == {"errored": "error", "shed": "forced",
                        "slow": "slow"}
        assert summary["dropped_traces"] == 1
        # ring bound: keeps evict oldest beyond max_traces
        for i in range(6):
            sp = spans.start_span(f"slow{i}")
            sp.end(end_us=sp.ts_us + 60_000)
        assert len(rec.summary()["kept"]) == 4
    finally:
        spans.RECORDER = saved


def test_late_spans_merge_into_already_kept_trace():
    """Two local roots on one trace: the first root's KEEP must not
    swallow spans recorded after it — a later dropping root merges its
    spans into the kept record instead of discarding them."""
    rec = spans.SpanRecorder(max_traces=4, slow_ms=50.0, max_spans=16,
                             max_active=8)
    saved = spans.RECORDER
    try:
        spans.RECORDER = rec
        with trace_context("tid-two-roots"):
            r1 = spans.start_span("r1")
            r2 = spans.start_span("r2", local_root=True)
            r1.end(end_us=r1.ts_us + 60_000)   # slow: keeps the trace
            spans.record_span("late", "tid-two-roots",
                              parent_id=r2.span_id,
                              mono_start=time.monotonic())
            r2.end()                           # fast: would drop
        trace = rec.get("tid-two-roots")
        names = {s["name"] for s in trace["spans"]}
        assert names == {"r1", "r2", "late"}, names
        assert rec.summary()["dropped_traces"] == 0
    finally:
        spans.RECORDER = saved


def test_event_log_keep_zero_still_enforces_cap(tmp_path):
    """keep=0 means rotate-without-retention: the live file truncates
    at the cap instead of growing unbounded."""
    path = str(tmp_path / "k0.jsonl")
    log = events.EventLog(path, max_bytes=1000, keep=0)
    for i in range(100):
        log.emit("tick", n=i)
    log.close()
    assert os.path.getsize(path) <= 1200      # one record past the cap
    assert not os.path.exists(path + ".1")
    # keep=0 retains at most the newest cap's worth (possibly nothing
    # when the last write landed exactly on the cap) — whatever is
    # left must parse cleanly
    recs = events.read_events(path, event="tick")
    assert all(0 <= r["n"] <= 99 for r in recs)


def test_span_cap_per_trace_counts_overflow():
    rec = spans.SpanRecorder(max_traces=4, slow_ms=0.0, max_spans=3,
                             max_active=8)
    saved = spans.RECORDER
    try:
        spans.RECORDER = rec
        with spans.span("root"):
            for i in range(5):
                with spans.span(f"c{i}"):
                    pass
        trace = rec.summary()["kept"][0]
        assert trace["spans"] == 3 and trace["dropped_spans"] == 3
    finally:
        spans.RECORDER = saved


# ---------------------------------------------------------------------------
# serving: span tree under the threaded worker + live /traces endpoint
# ---------------------------------------------------------------------------

def test_serving_request_span_tree_and_traces_endpoint(span_config,
                                                       tmp_path):
    profiler.set_state("run")
    try:
        eng = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=2)
        with eng:
            srv = eng.expose()
            futs = [eng.submit([1, 2, 3]), eng.submit([4, 5])]
            for f in futs:
                f.result(timeout=30)
            tid = futs[0].trace_id
            # the worker records batch-stage spans right before
            # set_result; poll briefly for the root to land
            deadline = time.monotonic() + 10
            trace = None
            while time.monotonic() < deadline:
                trace = spans.get_trace(tid)
                if trace and not trace.get("partial"):
                    break
                time.sleep(0.02)
            assert trace and not trace.get("partial"), trace
            # acceptance tree: submit -> queue -> pack ->
            # compile/forward -> complete, all under ONE trace id
            by_name = {s["name"]: s for s in trace["spans"]}
            root = by_name["serving/request"]
            assert root["parent_id"] is None
            for child in ("serving/queue", "serving/pack",
                          "serving/complete"):
                assert by_name[child]["parent_id"] == root["span_id"]
                assert by_name[child]["trace_id"] == tid
            fwd = by_name.get("serving/compile") \
                or by_name.get("serving/forward")
            assert fwd["parent_id"] == root["span_id"]
            assert fwd["attrs"]["rows"] >= 1
            # both requests produced their own trace, same span names
            trace2 = spans.get_trace(futs[1].trace_id)
            assert trace2 is not None and trace2["trace_id"] != tid

            # live /traces endpoint: summary + per-id span tree
            code, body = _get(srv.url("/traces"))
            assert code == 200
            summary = json.loads(body)
            assert any(k["trace_id"] == tid for k in summary["kept"])
            code, body = _get(srv.url(f"/traces/{tid}"))
            assert code == 200
            served = json.loads(body)
            assert {s["span_id"] for s in served["spans"]} \
                == {s["span_id"] for s in trace["spans"]}
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(srv.url("/traces/nonexistent-id"))
            assert ei.value.code == 404
    finally:
        profiler.set_state("stop")
    # Chrome-trace export merges span events with the profiler stream
    out = str(tmp_path / "trace.json")
    profiler.set_config(filename=out)
    profiler.dump()
    payload = json.load(open(out))
    span_events = [e for e in payload["traceEvents"]
                   if e.get("cat") == "span"]
    mine = [e for e in span_events if e["args"].get("trace_id") == tid]
    assert {"serving/request", "serving/queue"} <= \
        {e["name"] for e in mine}
    root_ev = [e for e in mine if e["name"] == "serving/request"][0]
    assert root_ev["args"]["span_id"] and root_ev["dur"] > 0


def test_shed_request_trace_is_force_kept(span_config):
    """Tail sampling keeps shed requests by contract even when fast."""
    eng = ServingEngine(StubModel(), bucket_lens=(8,), max_rows=1)
    spans.configure(slow_ms=1e9)           # nothing is "slow" now
    with eng:
        with pytest.raises(Exception):
            eng.submit(list(range(9)))     # too long -> shed
    kept = spans.traces_summary()["kept"]
    shed = [k for k in kept if k["root"] == "serving/request"
            and k["status"] == "error"]
    assert shed and shed[0]["keep_reason"] in ("forced", "error")


# ---------------------------------------------------------------------------
# router: cross-engine trace aggregation (ISSUE 5 acceptance goldens)
# ---------------------------------------------------------------------------

def test_router_failover_trace_spans_two_engines(span_config):
    """One REQUEST's merged tree spans >= 2 engines: the dying
    engine's errored serving/request and the sibling's served one both
    parent under the same router/request root."""
    from mxnet_tpu.serving import ServingEngine, ServingRouter

    live = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=1,
                         engine_id="span-live")
    dying = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=1,
                          engine_id="span-dying")
    live.start()
    dying.start()
    router = ServingRouter(engines=[live, dying], poll_interval_s=30.0)
    router.start()
    try:
        dying.stop(drain=False)
        futs = [router.submit([1, 2]) for _ in range(8)]
        for f in futs:
            f.result(timeout=30)
        assert router.count("requeued") >= 1
        # find a failed-over request: its trace carries BOTH engines'
        # serving/request spans under one router root
        merged = None
        for f in futs:
            t = router.get_trace(f.trace_id)
            if t and len([s for s in t["spans"]
                          if s["name"] == "serving/request"]) == 2:
                merged = t
                break
        assert merged is not None, "no failed-over trace found"
        assert set(merged["engines"]) == {"span-live", "span-dying"}
        root = [s for s in merged["spans"]
                if s["name"] == "router/request"][0]
        serving = [s for s in merged["spans"]
                   if s["name"] == "serving/request"]
        assert all(s["parent_id"] == root["span_id"] for s in serving)
        statuses = sorted(s["status"] for s in serving)
        assert statuses == ["error", "ok"]          # died, then served
        by_engine = {s["attrs"]["engine"]: s["status"] for s in serving}
        assert by_engine["span-dying"] == "error"
        assert by_engine["span-live"] == "ok"
    finally:
        router.stop()
        live.stop()


def test_router_cross_process_span_parenting_and_fleet_endpoints(
        span_config, tmp_path, capsys):
    """THE cross-process golden (mirrors the dist_async worker→server
    wire golden): an engine in another process parents its span tree
    under this process's router root via the dispatch-carried
    (trace_id, span_id); the router's /traces/<id> returns the merged
    tree, its /metrics the engine-labeled union, and telemetry_dump
    --fleet renders the scoreboard."""
    import subprocess

    from mxnet_tpu.serving import ServingEngine, ServingRouter

    worker = subprocess.Popen(
        [sys.executable,
         os.path.join(ROOT, "tests", "serving_router_engine_worker.py"),
         "proc-remote"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    try:
        line = worker.stdout.readline()
        assert line.startswith("PORT "), line
        port = int(line.split()[1])

        local = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=2,
                              engine_id="proc-local")
        local.start()
        router = ServingRouter(poll_interval_s=0.2)
        router.add_engine("proc-local", local)
        router.add_engine("proc-remote", f"http://127.0.0.1:{port}")
        router.start()
        try:
            srv = router.expose()
            # enough traffic that least-outstanding exercises BOTH
            futs = [router.submit([1, 2, 3]) for _ in range(8)]
            for f in futs:
                out = f.result(timeout=60)
                assert out.shape == (3, 1) and out[0, 0] == 1.0
            snap = router.snapshot()
            dispatched = {eid: r["dispatched"]
                          for eid, r in snap["engines"].items()}
            assert all(n > 0 for n in dispatched.values()), dispatched

            # a remote-served request: merged tree crosses processes
            remote_fut = next(
                f for f in futs
                if "proc-remote" in (router.get_trace(f.trace_id)
                                     or {}).get("engines", []))
            code, body = _get(srv.url(f"/traces/{remote_fut.trace_id}"))
            assert code == 200
            merged = json.loads(body)
            by_name = {s["name"]: s for s in merged["spans"]}
            root = by_name["router/request"]
            req_span = by_name["serving/request"]
            assert req_span["parent_id"] == root["span_id"]
            assert req_span["pid"] != root["pid"]     # truly 2 processes
            assert req_span["attrs"]["engine"] == "proc-remote"
            for child in ("serving/queue", "serving/complete"):
                assert by_name[child]["parent_id"] == req_span["span_id"]
            assert "proc-remote" in merged["engines"]

            # aggregated /metrics: both engines' labeled families in
            # ONE exposition (local registry + remote scrape-merge)
            code, text = _get(srv.url("/metrics"))
            assert code == 200
            for eid in ("proc-local", "proc-remote"):
                assert (f'mxnet_tpu_serving_requests_total{{'
                        f'engine_id="{eid}",event="completed"}}') in text
            from mxnet_tpu.telemetry import parse_prometheus_text
            parsed = parse_prometheus_text(text)
            fleet_completed = sum(
                v for k, v in parsed.items()
                if k.startswith("mxnet_tpu_serving_requests_total")
                and 'event="completed"' in k)
            assert fleet_completed >= len(futs)

            # merged /traces summary names the engine per kept trace
            code, body = _get(srv.url("/traces"))
            summary = json.loads(body)
            assert summary["sources"] >= 2
            mine = [k for k in summary["kept"]
                    if k["trace_id"] == remote_fut.trace_id]
            assert mine and "proc-remote" in mine[0]["engines"]

            # --fleet one-screen view (satellite smoke)
            sys.path.insert(0, os.path.join(ROOT, "tools"))
            import telemetry_dump
            rc = telemetry_dump.main(["--fleet", srv.url("")])
            out = capsys.readouterr().out
            assert rc == 0
            assert "proc-local" in out and "proc-remote" in out
            assert "engines up" in out
        finally:
            router.stop()
            local.stop()
    finally:
        worker.stdin.close()
        worker.wait(timeout=30)


def test_router_watchdog_bundle_contains_fleet_scoreboard(
        span_config, tmp_path, monkeypatch):
    """A dead engine trips the router's watchdog probe; the flight
    bundle carries router_scoreboard.json with the per-engine rows."""
    monkeypatch.setenv("MXNET_TPU_FLIGHT_DIR", str(tmp_path / "flight"))
    from mxnet_tpu.serving import ServingEngine, ServingRouter

    saved = flight.configure()
    flight.configure(interval_s=0.05, min_dump_interval_s=0.0)
    a = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=1,
                      engine_id="wd-a")
    b = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=1,
                      engine_id="wd-b")
    a.start()
    b.start()
    router = ServingRouter(engines=[a, b], poll_interval_s=0.05,
                           health_fail_after=1)
    router.start()
    try:
        assert router.infer([1], timeout=30) is not None
        b.stop(drain=True)               # one of two engines dies
        root = str(tmp_path / "flight")
        deadline = time.monotonic() + 20
        bundles = []
        while time.monotonic() < deadline:
            if os.path.isdir(root):
                bundles = [d for d in os.listdir(root)
                           if "router_engine_down" in d
                           and not d.endswith(".tmp")]
                if bundles:
                    break
            time.sleep(0.05)
        assert bundles, "router watchdog never dumped a bundle"
        bdir = os.path.join(root, bundles[0])
        assert "router_scoreboard.json" in os.listdir(bdir)
        board = json.load(open(
            os.path.join(bdir, "router_scoreboard.json")))
        rows = board["engines"]
        assert rows["wd-b"]["routable"] is False
        assert rows["wd-a"]["routable"] is True
        assert board["engines_up"] == 1
        # the fleet is still serving through the survivor
        assert router.infer([2], timeout=30)[0, 0] == 2.0
    finally:
        router.stop()
        a.stop()
        flight.configure(**saved)


def test_router_disabled_span_path_stays_cheap():
    """MXNET_TPU_SPANS=0: the router's per-request span bookkeeping
    (RouterRequest root span + end + registry bump) stays in the
    disabled-path budget — same guard philosophy as the engine's."""
    from mxnet_tpu.serving.router import RouterRequest

    saved = spans.enabled()
    spans.configure(enabled=False)
    try:
        n = 5000
        t0 = time.perf_counter()
        for _ in range(n):
            req = RouterRequest([1, 2, 3])
            req.span.set_attr(engine="x").end()
        per_req = (time.perf_counter() - t0) / n
        assert per_req < 200e-6, f"router request {per_req * 1e6:.1f}us"
    finally:
        spans.configure(enabled=saved)


# ---------------------------------------------------------------------------
# dist_async wire: cross-process parenting + legacy frames
# ---------------------------------------------------------------------------

def test_wire_span_parenting_and_legacy_frames(span_config):
    import socket

    from mxnet_tpu.kvstore import (_ParameterServer, _recv_msg,
                                   _send_msg)

    srv = _ParameterServer("127.0.0.1", 0, num_workers=1)
    try:
        port = srv._srv.getsockname()[1]
        s = socket.create_connection(("127.0.0.1", port), timeout=5)
        # legacy 3-tuple (pre-telemetry) still served
        _send_msg(s, ("init", "k", np.full((3,), 2.0, np.float32)))
        assert _recv_msg(s)[0] == "ok"
        # legacy 4-tuple (trace id, no span id) still served
        _send_msg(s, ("pull", "k", None, "tid-legacy4"))
        status, arr = _recv_msg(s)
        assert status == "ok" and np.allclose(arr, 2.0)
        # 5-tuple: the worker RPC span id rides the frame; the server
        # handle span parents under it — across the (real) socket
        _send_msg(s, ("push", "k", np.full((3,), 1.0, np.float32),
                      "tid-wire5", "remote-rpc-span-1"))
        assert _recv_msg(s)[0] == "ok"
        s.close()
        deadline = time.monotonic() + 10
        trace = None
        while time.monotonic() < deadline:
            trace = spans.get_trace("tid-wire5")
            if trace and any(s_["name"] == "kvstore/server/push"
                             for s_ in trace["spans"]):
                break
            time.sleep(0.05)
        by_name = {s_["name"]: s_ for s_ in trace["spans"]}
        handle = by_name["kvstore/server/push"]
        assert handle["parent_id"] == "remote-rpc-span-1"
        assert handle["trace_id"] == "tid-wire5"
        # the optimizer-update span parents under the handle span
        opt = by_name["kvstore/server/optimizer_update"]
        assert opt["parent_id"] == handle["span_id"]
        # the legacy 4-tuple handle still recorded a span (no parent)
        t4 = spans.get_trace("tid-legacy4")
        pull = [s_ for s_ in t4["spans"]
                if s_["name"] == "kvstore/server/pull"][0]
        assert pull["parent_id"] is None
    finally:
        srv._srv.close()
        flight.unregister_probe("kvstore_server")


# (The 2-REAL-process span-parenting assertions ride the existing
# dist_async launch in tests/test_telemetry.py::
# test_dist_async_trace_id_crosses_processes — one heavyweight launch
# verifies both the trace-id and the span-parent crossing.)


# ---------------------------------------------------------------------------
# watchdog + flight recorder
# ---------------------------------------------------------------------------

def test_watchdog_trips_on_stalled_worker_and_dumps_bundle(
        span_config, tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_FLIGHT_DIR", str(tmp_path / "flight"))
    # the stub wedges on its FIRST call, which since ISSUE 7 counts as
    # an open compile window (first-visit compiles are tolerated for
    # stall+grace). Zero the grace so this test keeps exercising the
    # plain stall trip; the compile-tolerance contract itself is
    # covered in tests/test_compile_cache.py.
    monkeypatch.setenv("MXNET_TPU_WATCHDOG_COMPILE_GRACE_S", "0")
    events.configure(str(tmp_path / "wd.jsonl"))
    saved = flight.configure()
    flight.configure(interval_s=0.05, stall_s=0.3,
                     min_dump_interval_s=0.0)
    gate = threading.Event()

    class Blocking:
        def __call__(self, ids, token_types, valid_length, segment_ids,
                     positions):
            gate.wait(30)
            return nd.array(ids.asnumpy().astype(np.float32)[..., None])

    eng = ServingEngine(Blocking(), bucket_lens=(16,), max_rows=2)
    try:
        eng.start()
        fut = eng.submit([1, 2, 3])
        log_path = events.get_log().path
        deadline = time.monotonic() + 20
        trips = []
        while time.monotonic() < deadline:
            trips = events.read_events(log_path,
                                       event="watchdog_anomaly")
            if trips:
                break
            time.sleep(0.05)
        assert trips, "watchdog never tripped on the stalled worker"
        assert trips[0]["kind"] == "serving_worker_stall"
        assert trips[0]["seconds_since_beat"] >= 0.3
        # the bundle: spans + registry snapshot + all-thread stacks
        deadline = time.monotonic() + 10
        bundles = []
        while time.monotonic() < deadline:
            root = str(tmp_path / "flight")
            bundles = [d for d in (os.listdir(root)
                                   if os.path.isdir(root) else [])
                       if "serving_worker_stall" in d
                       and not d.endswith(".tmp")]
            if bundles:
                break
            time.sleep(0.05)
        assert bundles, "no flight bundle written"
        bdir = os.path.join(str(tmp_path / "flight"), bundles[0])
        names = set(os.listdir(bdir))
        assert {"meta.json", "spans.json", "events.jsonl",
                "metrics.json", "threads.txt"} <= names
        stacks = open(os.path.join(bdir, "threads.txt")).read()
        # the worker thread's stack shows WHERE it is stuck
        assert "mxnet_tpu_serving" in stacks and "gate.wait" in stacks
        metrics = json.load(open(os.path.join(bdir, "metrics.json")))
        assert "mxnet_tpu_serving_requests_total" in metrics
        assert json.load(open(os.path.join(bdir, "meta.json")))[
            "reason"].startswith("watchdog_")
    finally:
        gate.set()
        try:
            fut.result(timeout=30)
        except Exception:
            pass
        eng.stop()
        events.configure(None)
        flight.configure(**saved)


def test_sigusr2_dumps_flight_bundle(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_FLIGHT_DIR", str(tmp_path))
    flight.install()
    with spans.span("sig-span"):
        pass
    os.kill(os.getpid(), signal.SIGUSR2)
    deadline = time.monotonic() + 10
    bundles = []
    while time.monotonic() < deadline:
        bundles = [d for d in os.listdir(str(tmp_path))
                   if "sigusr2" in d]
        if bundles:
            break
        time.sleep(0.05)
    assert bundles, "SIGUSR2 produced no bundle"
    bdir = os.path.join(str(tmp_path), bundles[0])
    assert {"meta.json", "spans.json", "events.jsonl", "metrics.json",
            "threads.txt"} <= set(os.listdir(bdir))
    assert json.load(open(os.path.join(bdir, "meta.json")))[
        "reason"] == "sigusr2"
    assert "MainThread" in open(os.path.join(bdir, "threads.txt")).read()


# ---------------------------------------------------------------------------
# event-log rotation (satellite)
# ---------------------------------------------------------------------------

def test_event_log_rotation_and_read_across(tmp_path):
    path = str(tmp_path / "rot.jsonl")
    log = events.EventLog(path, max_bytes=2000, keep=2)
    for i in range(200):
        log.emit("tick", n=i)
    log.close()
    sibs = sorted(os.listdir(str(tmp_path)))
    assert f"rot.jsonl.1" in [os.path.basename(p) for p in sibs]
    # count cap: never more than `keep` rotated files
    rotated = [p for p in sibs if ".jsonl." in p]
    assert 1 <= len(rotated) <= 2, sibs
    # read_events spans the rotations, oldest first, in order
    recs = events.read_events(path, event="tick")
    ns = [r["n"] for r in recs]
    assert ns == sorted(ns) and ns[-1] == 199
    # retention really spans rotations: a single 2000-byte file holds
    # ~22 of these ~90-byte records, and we kept noticeably more
    assert len(ns) > 30, len(ns)
    # the newest events are in the live file — or, when the final
    # write landed exactly on the cap (record size shifts with pid and
    # clock digit widths), in the freshest rotation
    live = [json.loads(l) for l in open(path) if l.strip()]
    if live:
        assert live[-1]["n"] == 199
    else:
        rot1 = [json.loads(l) for l in open(path + ".1") if l.strip()]
        assert rot1[-1]["n"] == 199


def test_event_log_rotation_thread_safe(tmp_path):
    path = str(tmp_path / "mt.jsonl")
    log = events.EventLog(path, max_bytes=1500, keep=3)
    n_threads, per = 4, 100

    def work(i):
        for j in range(per):
            log.emit("t", worker=i, j=j)

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    log.close()
    recs = events.read_events(path, event="t")
    # no torn lines: every surviving record parsed (rotation drops the
    # oldest files, so <= total; each kept line must be intact though)
    assert len(recs) <= n_threads * per
    assert all("worker" in r and "j" in r for r in recs)
    # retention spans rotations: one 1500-byte file holds ~13 of these
    # records; live + 3 rotated must hold several files' worth
    assert len(recs) > 26, len(recs)


def test_env_max_mb_configures_rotation(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_EVENT_LOG_MAX_MB", "0.001")
    monkeypatch.setenv("MXNET_TPU_EVENT_LOG_KEEP", "1")
    log = events.EventLog(str(tmp_path / "env.jsonl"))
    assert log.max_bytes == int(0.001 * 1024 * 1024)
    assert log.keep == 1
    for i in range(100):
        log.emit("e", i=i)
    log.close()
    assert os.path.exists(str(tmp_path / "env.jsonl.1"))
    assert not os.path.exists(str(tmp_path / "env.jsonl.2"))


# ---------------------------------------------------------------------------
# telemetry_dump: --traces / --trace (satellite smoke)
# ---------------------------------------------------------------------------

def test_telemetry_dump_traces_and_tree(span_config, capsys):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    import telemetry_dump

    eng = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=2)
    with eng:
        srv = eng.expose()
        fut = eng.submit([1, 2, 3])
        fut.result(timeout=30)
        tid = fut.trace_id
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            t = spans.get_trace(tid)
            if t and not t.get("partial"):
                break
            time.sleep(0.02)
        rc = telemetry_dump.main(["--traces", srv.url("/metrics")])
        out = capsys.readouterr().out
        assert rc == 0 and tid in out and "serving/request" in out
        rc = telemetry_dump.main(["--trace", tid, srv.url("")])
        out = capsys.readouterr().out
        assert rc == 0
        # indented tree with self-time columns
        assert "serving/request" in out and "  serving/queue" in out
        assert "self ms" in out
        # unknown trace id exits distinctly
        rc = telemetry_dump.main(["--trace", "no-such-id",
                                  srv.url("")])
        assert rc == 3


# ---------------------------------------------------------------------------
# fit loops produce epoch/step span trees
# ---------------------------------------------------------------------------

def test_gluon_fit_epoch_and_step_spans(span_config):
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.contrib.estimator import Estimator

    rs = np.random.RandomState(0)
    x = rs.randn(32, 4).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.float32)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
    net.initialize(init=mx.initializer.Xavier())
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    est = Estimator(net=net, loss=loss, trainer=trainer,
                    metrics=mx.metric.Accuracy())
    loader = gluon.data.DataLoader(
        gluon.data.ArrayDataset(x, y), batch_size=16)
    est.fit(train_data=loader, epochs=1)
    kept = spans.traces_summary()["kept"]
    epochs = [k for k in kept if k["root"] == "fit/epoch"]
    assert epochs, kept
    trace = spans.get_trace(epochs[0]["trace_id"])
    by_name = {}
    for s in trace["spans"]:
        by_name.setdefault(s["name"], []).append(s)
    root = by_name["fit/epoch"][0]
    steps = by_name["fit/step"]
    assert len(steps) == 2               # 32 samples / batch 16
    assert all(s["parent_id"] == root["span_id"] for s in steps)


# ---------------------------------------------------------------------------
# disabled-path microbench guard, extended to span instrumentation
# ---------------------------------------------------------------------------

def test_disabled_span_paths_stay_cheap():
    """With spans disabled, the span-instrumented hot paths (serving
    dispatch, kvstore RPC, fit steps) cost ~a microsecond per call —
    same guard philosophy as test_disabled_paths_stay_cheap, budgets
    ~50x observed so it catches regressions, not scheduler noise."""
    saved = spans.enabled()
    spans.configure(enabled=False)
    try:
        n = 20000
        t0 = time.perf_counter()
        for _ in range(n):
            with spans.span("hot"):
                pass
        per_ctx = (time.perf_counter() - t0) / n
        t0 = time.perf_counter()
        for _ in range(n):
            spans.start_span("hot").end()
        per_manual = (time.perf_counter() - t0) / n
        t0 = time.perf_counter()
        for _ in range(n):
            spans.record_span("hot", "t-x", mono_start=0.0, mono_end=0.0)
        per_record = (time.perf_counter() - t0) / n
        assert per_ctx < 50e-6, f"span ctx {per_ctx * 1e6:.1f}us"
        assert per_manual < 20e-6, f"start+end {per_manual * 1e6:.1f}us"
        assert per_record < 20e-6, f"record {per_record * 1e6:.1f}us"
    finally:
        spans.configure(enabled=saved)


# ---------------------------------------------------------------------------
# cross-process merge re-anchors remote axes (ISSUE 8 satellite golden)
# ---------------------------------------------------------------------------

def test_merge_reanchors_remote_spans_onto_router_axis():
    """Two rings from different PROCESSES carry wildly different
    perf_counter axes; after merge, every engine-side span lands
    INSIDE the router root's interval on ONE monotonic axis (no
    negative gaps) because wall stamps re-anchor the foreign pid's
    spans. Intra-process offsets stay exact."""
    wall0 = 1700000000.0
    # router process (pid 100): its perf_counter axis happens to start
    # near 50s. Root runs 0..100ms wall.
    root = {"trace_id": "t-x", "span_id": "r1", "parent_id": None,
            "name": "router/request", "pid": 100,
            "ts_us": 50_000_000, "dur_us": 100_000, "wall": wall0}
    # engine process (pid 200): an axis offset ~9000s away. Its
    # serving/request started 10ms after the root (per wall) and its
    # forward child 5ms after that.
    eng_root = {"trace_id": "t-x", "span_id": "e1", "parent_id": "r1",
                "name": "serving/request", "pid": 200,
                "ts_us": 9_000_000_000, "dur_us": 80_000,
                "wall": wall0 + 0.010}
    eng_fwd = {"trace_id": "t-x", "span_id": "e2", "parent_id": "e1",
               "name": "serving/forward", "pid": 200,
               "ts_us": 9_000_005_000, "dur_us": 60_000,
               "wall": wall0 + 0.015}
    merged = spans.merge_trace_records([
        (None, {"trace_id": "t-x", "spans": [root]}),
        ("e0", {"trace_id": "t-x", "spans": [eng_root, eng_fwd]})])
    assert merged["reanchored_pids"] == [200]
    by_id = {s["span_id"]: s for s in merged["spans"]}
    r, e1, e2 = by_id["r1"], by_id["e1"], by_id["e2"]
    # the router's own axis is untouched
    assert r["ts_us"] == 50_000_000
    # engine spans landed INSIDE the root interval: monotonic axis,
    # no negative gap at the process crossing
    assert r["ts_us"] <= e1["ts_us"] <= r["ts_us"] + r["dur_us"]
    assert e1["ts_us"] <= e2["ts_us"] <= e1["ts_us"] + e1["dur_us"]
    # intra-process delta survives EXACTLY (one rigid shift per pid)
    assert e2["ts_us"] - e1["ts_us"] == 5_000
    # wall-anchored placement is accurate to the wall precision
    assert abs(e1["ts_us"] - (r["ts_us"] + 10_000)) <= 2_000
    # merged output is sorted on the single re-anchored axis
    ts = [s["ts_us"] for s in merged["spans"]]
    assert ts == sorted(ts)


def test_merge_reanchors_colliding_pids_per_source():
    """Two containerized remote engines that are EACH pid 1 carry
    unrelated perf_counter axes: grouping must key on (source ring,
    pid), not pid alone, or the median pools both axes (and an engine
    sharing the reference pid would never shift at all)."""
    wall0 = 1700000000.0
    root = {"trace_id": "t-z", "span_id": "r1", "parent_id": None,
            "name": "router/request", "pid": 1,
            "ts_us": 50_000_000, "dur_us": 100_000, "wall": wall0}
    eng_a = {"trace_id": "t-z", "span_id": "a1", "parent_id": "r1",
             "name": "serving/request", "pid": 1,
             "ts_us": 9_000_000_000, "dur_us": 40_000,
             "wall": wall0 + 0.010}
    eng_b = {"trace_id": "t-z", "span_id": "b1", "parent_id": "r1",
             "name": "serving/request", "pid": 1,
             "ts_us": 123_000, "dur_us": 40_000,
             "wall": wall0 + 0.050}
    merged = spans.merge_trace_records([
        (None, {"trace_id": "t-z", "spans": [root]}),
        ("eA", {"trace_id": "t-z", "spans": [eng_a]}),
        ("eB", {"trace_id": "t-z", "spans": [eng_b]})])
    assert merged["reanchored_pids"] == [1]
    by_id = {s["span_id"]: s for s in merged["spans"]}
    r = by_id["r1"]
    assert r["ts_us"] == 50_000_000      # reference axis untouched
    # BOTH colliding-pid engines land inside the root interval at
    # their own wall offsets — one rigid shift per (source, pid)
    assert abs(by_id["a1"]["ts_us"] - (r["ts_us"] + 10_000)) <= 2_000
    assert abs(by_id["b1"]["ts_us"] - (r["ts_us"] + 50_000)) <= 2_000
    # the transient grouping key never leaks into the merged output
    assert all("_src" not in s for s in merged["spans"])


def test_merge_single_process_axes_untouched():
    a = {"trace_id": "t-y", "span_id": "a", "parent_id": None,
         "name": "root", "pid": 1, "ts_us": 1000, "dur_us": 10,
         "wall": 5.0}
    b = {"trace_id": "t-y", "span_id": "b", "parent_id": "a",
         "name": "child", "pid": 1, "ts_us": 1002, "dur_us": 5,
         "wall": 5.000002}
    merged = spans.merge_trace_records([(None, {"trace_id": "t-y",
                                                "spans": [a, b]})])
    assert "reanchored_pids" not in merged
    assert [s["ts_us"] for s in merged["spans"]] == [1000, 1002]
