"""Error-propagation and engine-contract tests
(tests/python/unittest/test_exc_handling.py analog, SURVEY §4/§5.2).

The reference's failure mode is an exception thrown inside an engine
worker thread that must resurface at the next sync point (WaitForVar /
asnumpy / WaitForAll). Under PJRT the async boundary is different:
shape/type errors surface synchronously at dispatch (tracing runs in
the caller), while device-side work is data-race-free by construction.
These tests pin down that contract plus the NaiveEngine sync ladder.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.engine import engine


def test_shape_error_raises_at_dispatch():
    a, b = nd.ones((2, 3)), nd.ones((4, 5))
    with pytest.raises(Exception):
        nd.broadcast_add(a, b)


def test_unknown_op_raises_mxnet_error():
    from mxnet_tpu.ndarray.register import get_op
    with pytest.raises(MXNetError, match="not registered"):
        get_op("definitely_not_an_op")


def test_uninitialized_kvstore_key_raises():
    from mxnet_tpu import kvstore
    kv = kvstore.create("local")
    with pytest.raises(MXNetError, match="not initialized"):
        kv.pull("nope", out=nd.zeros((1,)))


def test_wait_all_after_dispatch():
    outs = [nd.exp(nd.ones((8, 8))) for _ in range(300)]  # > old 256 cap
    engine.wait_all()
    for o in outs:
        assert np.isfinite(o.asnumpy()).all()


def test_sync_engine_mode():
    prev = engine.sync
    try:
        engine.set_sync(True)
        y = nd.exp(nd.ones((4, 4)))  # blocks at dispatch (NaiveEngine)
        assert np.isfinite(y.asnumpy()).all()
    finally:
        engine.set_sync(prev)


def test_wait_for_var():
    y = nd.exp(nd.ones((4, 4)))
    engine.wait_for_var(y._data)
    assert np.isfinite(y.asnumpy()).all()


def test_deferred_init_error_message():
    from mxnet_tpu import gluon
    p = gluon.Parameter("w", shape=(0, 4), allow_deferred_init=True)
    p.initialize()
    with pytest.raises(gluon.parameter.DeferredInitializationError):
        p.data()


def test_backward_outside_record_has_no_graph():
    import warnings
    x = nd.ones((2, 2))
    x.attach_grad()
    y = x * 2.0  # not recorded
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        y.backward()  # reference: no-op backward on unrecorded graph
    assert (x.grad.asnumpy() == 0).all()
    # ... but the silent-zero footgun (e.g. loss.sum() after the record
    # block) is loudly flagged
    assert any("not computed inside autograd" in str(wi.message)
               for wi in w)
