"""Subprocess body for the cross-process persistent-cache golden
(tests/test_compile_cache.py): build the SAME tiny model the same way,
serve one request per bucket through a ServingEngine, and print the
engine's compile-cache split as JSON. Run twice against one
MXNET_TPU_COMPILE_CACHE_DIR: the first process records ``miss`` (fresh
backend compiles), the second records ``persistent_hit`` for the same
(model, bucket) — the executable came off disk, proving the cache key
is stable across process lifetimes."""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main():
    import mxnet_tpu as mx
    from mxnet_tpu import compile_cache
    from mxnet_tpu.gluon.model_zoo.bert import BERTModel, bert_serving_entry
    from mxnet_tpu.serving import ServingEngine

    mx.random.seed(7)
    net = BERTModel(vocab_size=64, units=16, hidden_size=32, num_layers=1,
                    num_heads=2, max_length=16, dropout=0.0,
                    attention_dropout=0.0, use_pooler=False)
    net.initialize(init=mx.initializer.Normal(0.02))
    eng = ServingEngine(bert_serving_entry(net), bucket_lens=(8,),
                        max_rows=1, pool="mean", engine_id="golden")
    with eng:
        eng.infer([1, 2, 3, 4, 5], timeout=120)
        snap = eng.snapshot()
    print(json.dumps({"compile_cache": snap["compile_cache"],
                      "manifest_shapes": snap["manifest_shapes"],
                      "jax_events": compile_cache.events_snapshot(),
                      "state": compile_cache.state()}))


if __name__ == "__main__":
    sys.exit(main())
