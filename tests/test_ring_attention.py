"""Ring / Ulysses sequence-parallel attention vs single-device golden.

Runs on the virtual 8-device CPU mesh (conftest) — the analog of the
reference's localhost multi-process kvstore tests (SURVEY §4).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mxnet_tpu.parallel import build_mesh
from mxnet_tpu.parallel.ring_attention import (make_ring_attention_fn,
                                               make_ulysses_attention_fn)

# mesh tests need 8 devices; under MXNET_TPU_TEST_REAL_DEVICE on a
# single chip the whole file skips (the reference's multi-GPU tests
# skip the same way below their device requirement)
pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8,
    reason="sequence-parallel tests need an 8-device mesh")


def _attn_ref(q, k, v, causal=False):
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    if causal:
        sq = s.shape[-2]
        m = jnp.tril(jnp.ones((sq, sq), bool))
        s = jnp.where(m, s, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)


def _make_qkv(seed=0, B=2, H=8, S=128, D=32):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("maker", [make_ring_attention_fn,
                                   make_ulysses_attention_fn])
def test_seq_parallel_attention_matches_reference(causal, maker):
    mesh = build_mesh({"sp": 8})
    q, k, v = _make_qkv()
    fn = jax.jit(maker(mesh, axis_name="sp", causal=causal))
    out = fn(q, k, v)
    ref = _attn_ref(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_grads(causal):
    mesh = build_mesh({"sp": 8})
    q, k, v = _make_qkv(seed=1, S=64)
    w = jnp.asarray(np.random.RandomState(9).randn(*q.shape).astype(np.float32))
    fn = make_ring_attention_fn(mesh, axis_name="sp", causal=causal)
    g = jax.jit(jax.grad(lambda q, k, v: (fn(q, k, v) * w).sum(),
                         argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(lambda q, k, v: (_attn_ref(q, k, v, causal) * w).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for a, c in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-4, atol=1e-4)


def test_ulysses_grads():
    mesh = build_mesh({"sp": 8})
    q, k, v = _make_qkv(seed=2, S=64)
    w = jnp.asarray(np.random.RandomState(3).randn(*q.shape).astype(np.float32))
    fn = make_ulysses_attention_fn(mesh, axis_name="sp", causal=True)
    g = jax.jit(jax.grad(lambda q, k, v: (fn(q, k, v) * w).sum(),
                         argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(lambda q, k, v: (_attn_ref(q, k, v, True) * w).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for a, c in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-4, atol=1e-4)


def test_ring_attention_long_context_memory_shape():
    """Smoke: a sequence much longer than one device could score-matrix
    — 8k tokens over 8 devices — compiles and runs on the CPU mesh."""
    mesh = build_mesh({"sp": 8})
    rng = np.random.RandomState(5)
    B, H, S, D = 1, 2, 8192, 16
    q = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * 0.2
    k = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * 0.2
    v = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    fn = jax.jit(make_ring_attention_fn(mesh, axis_name="sp", causal=True))
    out = fn(q, k, v)
    assert out.shape == (B, H, S, D)
    assert bool(jnp.isfinite(out).all())


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_flash_fold(causal, monkeypatch):
    """The Pallas-kernel fold (use_flash=True, interpret kernels on CPU)
    must match the unsharded reference in forward AND gradients — the
    LSE combiner + dlse-aware kernel backward against plain attention."""
    monkeypatch.setenv("MXNET_TPU_PALLAS_INTERPRET", "1")
    mesh = build_mesh({"sp": 8})
    q, k, v = _make_qkv(seed=11, S=128)
    fn = jax.jit(make_ring_attention_fn(mesh, axis_name="sp", causal=causal,
                                        use_flash=True))
    out = fn(q, k, v)
    ref = _attn_ref(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)

    w = jnp.asarray(np.random.RandomState(13).randn(*q.shape)
                    .astype(np.float32))
    gfn = make_ring_attention_fn(mesh, axis_name="sp", causal=causal,
                                 use_flash=True)
    g = jax.jit(jax.grad(lambda q, k, v: (gfn(q, k, v) * w).sum(),
                         argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(lambda q, k, v: (_attn_ref(q, k, v, causal) * w).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for a, c in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-4, atol=1e-4)
