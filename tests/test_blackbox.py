"""Black-box fleet monitoring tests (ISSUE 13): synthetic canary
probing, alert egress, and the correlated incident timeline.

- CanaryProber goldens: golden-checksum trust-on-first-use, checksum
  mismatch on changed weights, billed-cost accounting, absence-rule
  lifecycle (declared per seat, removed with the seat);
- AlertNotifier retry/backoff/dedup with a scripted clock — the
  delivery-failure golden (N backoffs then dead-letter spool, spool
  replay on restart delivers exactly once) and the fingerprint dedup
  across the pending→firing→resolved walk;
- exemplar-aware ``merge_prometheus_texts`` over the canary families;
- incident tracker units (open/hold/release/close, bundle links,
  fleet merge) and the ``telemetry_dump --incidents`` exit-5 contract;
- THE end-to-end drill (ISSUE acceptance): 2 remote-seat router with
  canaries on both transports, one engine's worker loop wedged — the
  canary absence SLO walks pending→firing while the seat's /healthz
  still answers, the file-sink notifier receives exactly ONE deduped
  page carrying the incident id, ``/incidents`` shows one open
  incident correlating alert + watchdog trip + scoreboard transition
  over ONE amended flight bundle, and recovery resolves, notifies and
  closes with zero lost non-synthetic requests;
- disabled paths: ``MXNET_TPU_CANARY=0`` / ``MXNET_TPU_ALERT_EGRESS=0``
  spawn no threads and register no families (subprocess-verified) and
  the always-on incident tap stays microbench-cheap.
"""
import glob
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from mxnet_tpu import nd
from mxnet_tpu.serving import ServingEngine, ServingRouter
from mxnet_tpu.telemetry import egress as egress_mod
from mxnet_tpu.telemetry import incidents as incidents_mod
from mxnet_tpu.telemetry import recorder as flight
from mxnet_tpu.telemetry.canary import (CanaryProber, golden_tokens,
                                        response_checksum)
from mxnet_tpu.telemetry.egress import (AlertNotifier, FileSink,
                                        fingerprint)
from mxnet_tpu.telemetry.expo import (merge_prometheus_texts,
                                      parse_prometheus_text)
from mxnet_tpu.telemetry.registry import REGISTRY, MetricsRegistry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(ROOT, "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)


def _get_json(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


class StubModel:
    """Deterministic identity-ish model; ``scale`` changes the output
    so a 'wrong weights' seat is one attribute away."""

    def __init__(self, delay=0.0, scale=1.0):
        self.delay = delay
        self.scale = scale

    def __call__(self, ids, token_types, valid_length, segment_ids,
                 positions):
        if self.delay:
            time.sleep(self.delay)
        return nd.array(
            ids.asnumpy().astype(np.float32)[..., None] * self.scale)


class WedgeModel(StubModel):
    """Forward blocks while ``block`` is set — the wedged-worker-loop
    shape: the thread is alive (healthz green) but nothing completes."""

    def __init__(self):
        super().__init__()
        self.block = threading.Event()

    def __call__(self, *args):
        while self.block.is_set():
            time.sleep(0.01)
        return super().__call__(*args)


class FailingSink(egress_mod.Sink):
    name = "file"            # impersonates the file sink for replay

    def __init__(self):
        self.attempts = 0

    def send(self, payload):
        self.attempts += 1
        raise OSError("pager endpoint down")


class ListSink(egress_mod.Sink):
    name = "list"

    def __init__(self):
        self.sent = []

    def send(self, payload):
        self.sent.append(dict(payload))


def _transition(alert="lat_fast", owner="r0", to="firing",
                frm="pending", severity="page"):
    return {"alert": alert, "owner": owner, "severity": severity,
            "from": frm, "to": to, "ts": round(time.time(), 3),
            "detail": {"burn_long": 20.0}}


# ---------------------------------------------------------------------------
# alert egress: retry / backoff / dedup / spool goldens
# ---------------------------------------------------------------------------

def test_notifier_backoff_then_spool_then_replay_exactly_once(tmp_path):
    """The delivery-failure golden: N retries with exponential backoff
    + jitter, then the dead-letter spool; a restarted notifier replays
    the spool and delivers exactly once."""
    spool = str(tmp_path / "spool")
    sleeps = []
    failing = FailingSink()
    n1 = AlertNotifier(sinks=[failing], retries=3, backoff_s=0.5,
                       spool_dir=spool, registry=MetricsRegistry(),
                       sleep=sleeps.append,
                       rng=__import__("random").Random(0))
    note = n1.notify(_transition())
    assert note is not None and note["fingerprint"] \
        == fingerprint("r0", "lat_fast")
    assert n1.process_pending() == 1       # scripted clock: no thread
    # 3 retries = 4 attempts, 3 backoff sleeps doubling from 0.5 with
    # up to 50% jitter each
    assert failing.attempts == 4
    assert len(sleeps) == 3
    for i, s in enumerate(sleeps):
        base = 0.5 * (2 ** i)
        assert base <= s <= base * 1.5, sleeps
    spooled = [f for f in os.listdir(spool) if f.endswith(".json")]
    assert len(spooled) == 1, spooled
    body = json.load(open(os.path.join(spool, spooled[0])))
    assert body["_sink"] == "file" and body["alert"] == "lat_fast"

    # restart: a WORKING file sink under the same name replays the
    # spooled page exactly once, then the spool is empty
    out = tmp_path / "pages.jsonl"
    n2 = AlertNotifier(sinks=[FileSink(str(out))], retries=0,
                       spool_dir=spool, registry=MetricsRegistry(),
                       sleep=sleeps.append)
    assert n2.replay_spool() == 1
    assert n2.process_pending() == 1
    lines = [json.loads(l) for l in out.read_text().splitlines()]
    assert len(lines) == 1
    assert lines[0]["alert"] == "lat_fast" and lines[0]["replayed"]
    assert not [f for f in os.listdir(spool) if f.endswith(".json")]
    # nothing left to replay
    assert n2.replay_spool() == 0


def test_notifier_fingerprint_dedup_across_the_walk():
    """One firing episode = one page; the matching resolved notifies
    once and re-arms the fingerprint so a re-fire pages again. Pending
    transitions and ticket severities never leave the process."""
    sink = ListSink()
    n = AlertNotifier(sinks=[sink], retries=0,
                      registry=MetricsRegistry(), sleep=lambda s: None)
    # pending filtered, ticket severity filtered
    assert n.notify(_transition(to="pending", frm="inactive")) is None
    assert n.notify(_transition(severity="ticket")) is None
    # firing delivers once, the duplicate dedupes
    assert n.notify(_transition()) is not None
    assert n.notify(_transition()) is None
    # resolved delivers, then the episode re-arms: fire again → page
    assert n.notify(_transition(frm="firing", to="resolved")) is not None
    assert n.notify(_transition()) is not None
    n.process_pending()
    walk = [(p["to"], p["fingerprint"]) for p in sink.sent]
    fp = fingerprint("r0", "lat_fast")
    assert walk == [("firing", fp), ("resolved", fp), ("firing", fp)]
    # a DIFFERENT alert has a different fingerprint
    assert fingerprint("r0", "avail_fast") != fp


def test_notifier_spool_bound_drops_oldest(tmp_path):
    spool = str(tmp_path / "spool")
    n = AlertNotifier(sinks=[FailingSink()], retries=0, spool_max=2,
                      spool_dir=spool, registry=MetricsRegistry(),
                      sleep=lambda s: None)
    for i in range(4):
        n.notify(_transition(alert=f"a{i}"))
        n.notify(_transition(alert=f"a{i}", frm="firing", to="resolved"))
    n.process_pending()
    names = sorted(f for f in os.listdir(spool) if f.endswith(".json"))
    assert len(names) == 2, names
    kept = {json.load(open(os.path.join(spool, f)))["alert"]
            for f in names}
    assert kept == {"a3"}, kept     # newest firing+resolved survive


def test_default_notifier_env_gating(tmp_path, monkeypatch):
    egress_mod.reset_default()
    monkeypatch.setenv("MXNET_TPU_ALERT_EGRESS", "0")
    monkeypatch.setenv("MXNET_TPU_ALERT_EGRESS_FILE",
                       str(tmp_path / "p.jsonl"))
    assert egress_mod.default_notifier() is None
    egress_mod.reset_default()
    monkeypatch.setenv("MXNET_TPU_ALERT_EGRESS", "1")
    n = egress_mod.default_notifier()
    try:
        assert n is not None
        assert [s.name for s in n.sinks] == ["file"]
        # cached: same instance on re-ask
        assert egress_mod.default_notifier() is n
    finally:
        egress_mod.reset_default()
    # no sink configured → no notifier, no thread
    monkeypatch.delenv("MXNET_TPU_ALERT_EGRESS_FILE")
    assert egress_mod.default_notifier() is None
    egress_mod.reset_default()


# ---------------------------------------------------------------------------
# canary prober units
# ---------------------------------------------------------------------------

def test_canary_local_probe_golden_and_mismatch():
    eng = ServingEngine(StubModel(), bucket_lens=(32,), max_rows=2,
                        engine_id="cn-e0")
    reg = MetricsRegistry()
    with eng:
        eng.warmup()
        prober = CanaryProber(
            lambda: [{"engine_id": "cn-e0", "engine": eng}],
            owner_id="cn-t", interval_s=60.0, timeout_s=10.0,
            registry=reg)
        out = prober.probe_all()
        assert out == {"cn-e0": "ok"}
        golden = prober.golden_for("cn-e0")
        assert golden is not None and len(golden) == 16
        # the golden is the CONTENT hash: identical output → identical
        # checksum, any weight change → a different one
        direct = eng.infer(golden_tokens(), timeout=30)
        assert response_checksum(direct) == golden
        # a second round against the same weights stays ok
        assert prober.probe_all() == {"cn-e0": "ok"}
        c = reg.get("mxnet_tpu_canary_requests_total")
        assert c.labels(engine_id="cn-e0", transport="local",
                        outcome="ok", traffic="synthetic").value == 2
        # the probes were billed (and tagged for exclusion)
        billed = reg.get("mxnet_tpu_canary_billed_requests_total")
        assert billed.labels(engine_id="cn-e0",
                             traffic="synthetic").value == 2
        toks = reg.get("mxnet_tpu_canary_billed_tokens_total")
        assert toks.labels(engine_id="cn-e0",
                           traffic="synthetic").value \
            == 2 * golden_tokens().size

    # a seat serving the WRONG weights fails the PINNED fleet golden,
    # not the transport: black-box catches what healthz never could
    wrong = ServingEngine(StubModel(scale=2.0), bucket_lens=(32,),
                          max_rows=2, engine_id="cn-e1")
    with wrong:
        wrong.warmup()
        prober2 = CanaryProber(
            lambda: [{"engine_id": "cn-e1", "engine": wrong}],
            owner_id="cn-t2", interval_s=60.0, golden=golden,
            registry=MetricsRegistry())
        assert prober2.golden_for("cn-e1") == golden   # pinned
        assert prober2.probe_all() == {"cn-e1": "checksum_mismatch"}
    # per-seat trust-on-first-use: the same wrong-weights seat judged
    # against ITSELF is healthy — until its own output drifts
    drift = StubModel(scale=2.0)
    eng3 = ServingEngine(drift, bucket_lens=(32,), max_rows=2,
                         engine_id="cn-e2")
    with eng3:
        eng3.warmup()
        prober3 = CanaryProber(
            lambda: [{"engine_id": "cn-e2", "engine": eng3}],
            owner_id="cn-t3", interval_s=60.0,
            registry=MetricsRegistry())
        assert prober3.probe_all() == {"cn-e2": "ok"}
        drift.scale = 3.0          # a hot-swap gone wrong
        assert prober3.probe_all() == {"cn-e2": "checksum_mismatch"}


def test_canary_absence_rules_follow_the_fleet():
    """One PAGE absence rule per live seat; a seat leaving the fleet
    drops its rule (a removed engine must not page forever)."""
    from mxnet_tpu.telemetry.alerts import AlertDaemon
    from mxnet_tpu.telemetry.slo import SloEvaluator

    reg = MetricsRegistry()
    ev = SloEvaluator("cn-own", registry=reg, scale=0.01,
                      budget_s=1000.0)
    daemon = AlertDaemon(ev, registry=reg, on_page=lambda p: None)
    eng = ServingEngine(StubModel(), bucket_lens=(32,), max_rows=2,
                        engine_id="cn-a0")
    targets = [{"engine_id": "cn-a0", "engine": eng}]
    with eng:
        eng.warmup()
        prober = CanaryProber(lambda: targets, owner_id="cn-own",
                              alerts=daemon, interval_s=60.0,
                              registry=reg)
        prober.probe_all()
        rule = daemon.get("canary_absent_cn-a0")
        assert rule is not None and rule.severity == "page"
        assert rule.match == {"engine_id": "cn-a0", "outcome": "ok",
                              "traffic": "synthetic"}
        # a healthy seat evaluates inactive: successes keep landing
        # BETWEEN ticks, so the windowed delta stays positive
        daemon.evaluate_once()
        prober.probe_all()
        daemon.evaluate_once()
        assert daemon.state("canary_absent_cn-a0") == "inactive"
        # seat leaves the fleet → rule retired
        targets.clear()
        prober.probe_all()
        assert daemon.get("canary_absent_cn-a0") is None


def test_remove_rule_while_firing_emits_resolving_transition():
    """Retiring a PENDING/FIRING rule (a seat removed mid-incident)
    must emit a final resolved transition: the incident tracker's
    firing hold releases and the notifier delivers the clearing page
    — a silent pop would leave /incidents open forever."""
    from mxnet_tpu.telemetry.alerts import AlertDaemon, AlertRule
    from mxnet_tpu.telemetry.slo import SloEvaluator

    class AlwaysFiring(AlertRule):
        def condition(self, evaluator, now):
            return True, {"forced": True}

    reg = MetricsRegistry()
    ev = SloEvaluator("rm-own", registry=reg, scale=1.0, budget_s=10.0)
    daemon = AlertDaemon(ev, registry=reg, on_page=lambda p: None)
    seen = []
    daemon.add_listener(seen.append)
    daemon.add_rule(AlwaysFiring("stuck", severity="page", for_s=0.0))
    daemon.evaluate_once()
    assert daemon.state("stuck") == "firing"
    assert daemon.remove_rule("stuck") is True
    assert daemon.get("stuck") is None
    final = [r for r in seen if r["to"] == "resolved"]
    assert len(final) == 1
    assert final[0]["from"] == "firing"
    assert final[0]["detail"]["removed"] is True
    # the transition log carries the synthetic resolve too
    walk = [(t["from"], t["to"])
            for t in daemon.snapshot()["transitions"]]
    assert walk[-1] == ("firing", "resolved")
    # removing an INACTIVE rule stays silent (nothing to clear)
    daemon.add_rule(AlwaysFiring("quiet", severity="page", for_s=1e9))
    assert daemon.remove_rule("quiet") is True
    assert [r for r in seen if r["alert"] == "quiet"] == []
    assert daemon.remove_rule("ghost") is False


def test_merge_prometheus_texts_canary_families_keep_exemplars():
    """Two routers' canary expositions scrape-merge: buckets sum, and
    per series the worst (slowest) exemplar survives — the fleet
    exposition keeps the worst retrievable probe trace."""
    series = ('mxnet_tpu_canary_latency_ms_bucket{engine_id="e0",'
              'transport="wire",traffic="synthetic",le="100"}')
    a = ("# TYPE mxnet_tpu_canary_latency_ms histogram\n"
         f'{series} 3 # {{trace_id="canary-a"}} 40 1.0\n'
         'mxnet_tpu_canary_latency_ms_sum{engine_id="e0",'
         'transport="wire",traffic="synthetic"} 70\n'
         'mxnet_tpu_canary_latency_ms_count{engine_id="e0",'
         'transport="wire",traffic="synthetic"} 3\n'
         "# TYPE mxnet_tpu_canary_requests_total counter\n"
         'mxnet_tpu_canary_requests_total{engine_id="e0",'
         'transport="wire",outcome="ok",traffic="synthetic"} 3\n')
    b = ("# TYPE mxnet_tpu_canary_latency_ms histogram\n"
         f'{series} 2 # {{trace_id="canary-b"}} 90 2.0\n'
         "# TYPE mxnet_tpu_canary_requests_total counter\n"
         'mxnet_tpu_canary_requests_total{engine_id="e0",'
         'transport="wire",outcome="ok",traffic="synthetic"} 2\n')
    merged = merge_prometheus_texts([a, b])
    exemplars = {}
    parsed = parse_prometheus_text(merged, exemplars=exemplars)
    assert parsed[series] == 5.0
    assert exemplars[series]["trace_id"] == "canary-b"
    assert exemplars[series]["value"] == pytest.approx(90.0)
    key = ('mxnet_tpu_canary_requests_total{engine_id="e0",'
           'transport="wire",outcome="ok",traffic="synthetic"}')
    assert parsed[key] == 5.0
    # merged output re-merges without corruption
    assert parse_prometheus_text(
        merge_prometheus_texts([merged])) == parsed


# ---------------------------------------------------------------------------
# incident tracker units
# ---------------------------------------------------------------------------

def test_incident_open_hold_release_close():
    tr = incidents_mod.IncidentTracker(gap_s=0.15,
                                       registry=MetricsRegistry())
    # breadcrumbs alone never open an incident
    tr._signal("engine_start", {"event": "engine_start",
                                "engine_id": "e0"})
    assert tr.open_incidents() == []
    # a firing alert opens; a scoreboard down holds
    tr._signal("alert_state", _transition())
    tr._signal("router_engine_state",
               {"engine_id": "e0", "state": "down", "reason": "stall"})
    tr._signal("watchdog_anomaly", {"probe": "p", "kind": "stall"})
    tr._signal("flight_recorder_dump",
               {"reason": "watchdog_stall", "path": "/tmp/b1"})
    opens = tr.open_incidents()
    assert len(opens) == 1
    inc = opens[0]
    assert inc["counts"] == {"alert": 1, "scoreboard": 1,
                             "watchdog": 1, "bundle": 1}
    assert inc["firing"] == ["r0:lat_fast"]
    assert inc["down_engines"] == ["e0"]
    assert inc["bundles"] == ["/tmp/b1"]
    assert tr.id_for_alert("r0", "lat_fast") == inc["id"]
    # released but not yet quiet: still open
    tr._signal("alert_state", _transition(frm="firing", to="resolved"))
    tr._signal("router_engine_state", {"engine_id": "e0", "state": "up"})
    assert len(tr.open_incidents()) == 1
    time.sleep(0.2)
    assert tr.open_incidents() == []
    snap = tr.snapshot()
    assert snap["open"] == [] and len(snap["recent"]) == 1
    assert snap["recent"][0]["state"] == "closed"
    assert snap["recent"][0]["id"] == inc["id"]
    assert snap["total_opened"] == 1
    # post-close breadcrumbs do not resurrect it
    tr._signal("warmup_replay", {"event": "warmup_replay",
                                 "engine_id": "e0"})
    assert tr.open_incidents() == []


def test_incident_merge_snapshots_dedupes_by_id():
    row = {"id": "inc-1", "opened_ts": 10.0, "state": "open"}
    local = {"open": [row], "recent": [], "total_opened": 1}
    remote = {"open": [dict(row)],
              "recent": [{"id": "inc-0", "closed_ts": 5.0,
                          "state": "closed"}],
              "total_opened": 2}
    merged = incidents_mod.merge_snapshots(
        [(None, local), ("e1", remote), ("e2", None)])
    assert [r["id"] for r in merged["open"]] == ["inc-1"]
    assert [r["id"] for r in merged["recent"]] == ["inc-0"]
    assert merged["recent"][0]["source"] == "e1"
    assert merged["sources"] == {"local": "ok", "e1": "ok",
                                 "e2": "missing"}


def test_telemetry_dump_incidents_exit_codes(capsys):
    import telemetry_dump
    from mxnet_tpu.telemetry.expo import TelemetryServer

    tr = incidents_mod.TRACKER
    tr.reset()
    installed = tr.install()        # idempotent; default route reads it
    assert installed is tr
    srv = TelemetryServer()
    try:
        url = srv.url("/incidents")
        assert telemetry_dump.main(["--incidents", url]) == 0
        out = capsys.readouterr().out
        assert "0 open" in out
        tr._signal("alert_state", _transition())
        assert telemetry_dump.main(["--incidents", url]) == 5
        out = capsys.readouterr().out
        assert "1 open" in out and "lat_fast" in out
    finally:
        srv.close()
        tr.reset()


# ---------------------------------------------------------------------------
# loadgen: synthetic canary traffic excluded from the cost books
# ---------------------------------------------------------------------------

@pytest.fixture()
def drill_env(monkeypatch, tmp_path):
    """Drill-speed knobs: scaled SLO clock, fast canary rounds, fast
    watchdog, isolated flight dir; global state restored on exit."""
    monkeypatch.setenv("MXNET_TPU_SLO_WINDOW_SCALE", "0.01")
    monkeypatch.setenv("MXNET_TPU_SLO_EVAL_S", "0.1")
    # recovery latencies are SECONDS; the latency objective must not
    # page on them (this drill's page is the canary absence rule)
    monkeypatch.setenv("MXNET_TPU_SLO_LATENCY_MS", "30000")
    monkeypatch.setenv("MXNET_TPU_CANARY_INTERVAL_S", "0.1")
    monkeypatch.setenv("MXNET_TPU_CANARY_TIMEOUT_S", "0.5")
    monkeypatch.setenv("MXNET_TPU_FLIGHT_DIR", str(tmp_path / "flight"))
    saved = flight.configure()
    flight.configure(interval_s=0.2, stall_s=1.0, min_dump_interval_s=60)
    rec = flight.RECORDER
    rec._last_bundle = None
    rec._last_dump.clear()
    incidents_mod.TRACKER.reset()
    yield str(tmp_path / "flight")
    flight.configure(**{k: saved[k] for k in
                        ("interval_s", "stall_s", "min_dump_interval_s")})
    rec._last_bundle = None
    rec._last_dump.clear()
    incidents_mod.TRACKER.reset()


def test_loadgen_excludes_canary_from_cost_books(drill_env):
    """A router-side prober bills real device time into the ledger;
    the loadgen cost cross-check must still reconcile (≤5% device_s)
    by excluding the label-identified synthetic traffic, and the
    report carries the canary section."""
    from serve_loadgen import run_load

    engines = [ServingEngine(StubModel(), bucket_lens=(32,), max_rows=2,
                             engine_id=f"lg-e{i}") for i in range(2)]
    for e in engines:
        e.start()
        e.warmup()
    router = ServingRouter(engines=engines, poll_interval_s=0.2,
                           router_id="lg-router").start()
    try:
        srv = router.expose()
        # let at least one canary round land before the measured window
        deadline = time.monotonic() + 10
        c = REGISTRY.get("mxnet_tpu_canary_requests_total")
        while time.monotonic() < deadline:
            if all(c.labels(engine_id=f"lg-e{i}", transport="local",
                            outcome="ok", traffic="synthetic").value > 0
                   for i in range(2)):
                break
            time.sleep(0.05)
        report = run_load(router, n_clients=4, requests_per_client=8,
                          min_len=4, max_len=24, vocab=100,
                          metrics_url=srv.url("/metrics"))
        assert report["completed"] == 32
        assert report["server"]["reconciled"], \
            report["server"]["mismatches"]
        cost = report["cost"]
        assert cost["reconciled"] is True, cost["mismatches"]
        canary = report.get("canary")
        assert canary, report.keys()
        assert canary["by_transport"].get("local", 0) > 0
        assert canary["excluded"]["requests"] >= 1
        assert canary["excluded"]["tokens"] \
            >= canary["excluded"]["requests"] * golden_tokens().size
        ok = sum(r.get("ok", 0) for r in canary["probes"].values())
        assert ok >= canary["excluded"]["requests"] > 0
    finally:
        router.stop()
        for e in engines:
            e.stop()


# ---------------------------------------------------------------------------
# THE drill: wedged worker loop behind a 2-seat router (acceptance)
# ---------------------------------------------------------------------------

@pytest.mark.timeout(300)
def test_wedged_engine_blackbox_drill(drill_env, tmp_path):
    flight_dir = drill_env
    pages_path = str(tmp_path / "pages.jsonl")
    m0, m1 = WedgeModel(), WedgeModel()
    e0 = ServingEngine(m0, bucket_lens=(64,), max_rows=2,
                       engine_id="bb-e0", max_queue_depth=64)
    e1 = ServingEngine(m1, bucket_lens=(64,), max_rows=2,
                       engine_id="bb-e1", max_queue_depth=64)
    with e0, e1:
        s0, s1 = e0.expose(), e1.expose()
        e0.warmup()
        e1.warmup()
        router = ServingRouter(poll_interval_s=0.2,
                               router_id="bb-router")
        # remote seats: the canary probes them over BOTH transports
        router.add_engine("bb-e0", f"http://{s0.host}:{s0.port}")
        router.add_engine("bb-e1", f"http://{s1.host}:{s1.port}")
        notifier = AlertNotifier(sinks=[FileSink(pages_path)],
                                 registry=MetricsRegistry())
        with router:
            router.alerts.add_listener(notifier.notify)
            notifier.start()
            srv = router.expose()
            base = f"http://{srv.host}:{srv.port}"

            # phase 0: canaries green over wire AND http on both seats
            c = REGISTRY.get("mxnet_tpu_canary_requests_total")

            def ok_count(eid, tr):
                return c.labels(engine_id=eid, transport=tr,
                                outcome="ok",
                                traffic="synthetic").value

            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if all(ok_count(eid, tr) > 0
                       for eid in ("bb-e0", "bb-e1")
                       for tr in ("wire", "http")):
                    break
                time.sleep(0.1)
            assert all(ok_count(eid, tr) > 0
                       for eid in ("bb-e0", "bb-e1")
                       for tr in ("wire", "http")), \
                "canaries never went green on both transports"
            assert router.canary.golden_for("bb-e0") is not None

            # non-synthetic traffic in flight across the whole drill
            futs = [router.submit(np.arange(1, 9, dtype=np.int32))
                    for _ in range(4)]

            # phase 1: wedge e0's worker loop
            m0.block.set()
            hz = _get_json(f"http://{s0.host}:{s0.port}/healthz")
            assert hz["ok"] and hz["worker_alive"], hz  # the lie

            # phase 2: the absence rule walks pending→firing while
            # /healthz still answers green
            fired = None
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                al = _get_json(base + "/alerts")
                rows = [r for r in al["rules"]
                        if r["alert"] == "canary_absent_bb-e0"]
                if rows and rows[0]["state"] == "firing":
                    fired = rows[0]
                    break
                time.sleep(0.1)
            assert fired is not None, "canary absence never fired"
            assert fired["severity"] == "page"
            hz = _get_json(f"http://{s0.host}:{s0.port}/healthz")
            assert hz["ok"], "healthz should still be lying"
            walked = [(t["from"], t["to"]) for t in al["transitions"]
                      if t["alert"] == "canary_absent_bb-e0"]
            assert ("inactive", "pending") in walked, walked
            assert ("pending", "firing") in walked, walked

            # phase 3: exactly ONE deduped page, carrying the incident
            assert notifier.flush(15)
            pages = [json.loads(l) for l in
                     open(pages_path).read().splitlines()]
            firing_pages = [p for p in pages if p["to"] == "firing"]
            assert len(firing_pages) == 1, pages
            page = firing_pages[0]
            assert page["alert"] == "canary_absent_bb-e0"
            assert page["severity"] == "page"
            incident_id = page.get("incident_id")
            assert incident_id, page

            # phase 4: ONE open incident correlating alert + watchdog
            # trip + scoreboard transition, linked to ONE bundle
            inc = _get_json(base + "/incidents")
            assert len(inc["open"]) == 1, inc
            row = inc["open"][0]
            assert row["id"] == incident_id
            assert row["counts"].get("alert"), row["counts"]
            assert row["counts"].get("watchdog"), row["counts"]
            assert row["counts"].get("scoreboard"), row["counts"]
            assert "canary_absent_bb-e0" in row["alerts"]
            bundles = glob.glob(os.path.join(flight_dir, "2*"))
            assert len(bundles) == 1, bundles   # amended, not raced
            meta = json.load(open(os.path.join(bundles[0],
                                               "meta.json")))
            assert len(meta["causes"]) >= 2, meta["causes"]
            assert any(cs.startswith("watchdog_")
                       for cs in meta["causes"]), meta["causes"]
            assert any(cs.startswith("alert_canary_absent")
                       for cs in meta["causes"]), meta["causes"]
            assert meta.get("incident_id") == incident_id
            assert bundles[0] in row["bundles"], row["bundles"]

            # phase 5: recovery — resolve, notify, close, zero loss
            m0.block.clear()
            resolved = False
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                al = _get_json(base + "/alerts")
                row = [r for r in al["rules"]
                       if r["alert"] == "canary_absent_bb-e0"][0]
                if row["state"] in ("resolved", "inactive"):
                    resolved = True
                    break
                time.sleep(0.1)
            assert resolved, "absence alert never resolved"
            assert notifier.flush(15)
            pages = [json.loads(l) for l in
                     open(pages_path).read().splitlines()]
            assert any(p["to"] == "resolved"
                       and p["alert"] == "canary_absent_bb-e0"
                       for p in pages), pages

            deadline = time.monotonic() + 45
            closed = False
            while time.monotonic() < deadline:
                inc = _get_json(base + "/incidents")
                if not inc["open"]:
                    closed = True
                    break
                time.sleep(0.2)
            assert closed, inc["open"]
            assert any(r["id"] == incident_id for r in inc["recent"])

            # zero lost non-synthetic requests
            for f in futs:
                assert f.result(timeout=60) is not None
        notifier.stop()


# ---------------------------------------------------------------------------
# disabled paths: no threads, no families, microbench guard
# ---------------------------------------------------------------------------

_DISABLED_PROBE = r"""
import json, sys, threading, time
sys.path.insert(0, {root!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from mxnet_tpu import nd
from mxnet_tpu.serving import ServingEngine, ServingRouter
from mxnet_tpu.telemetry import events
from mxnet_tpu.telemetry.registry import REGISTRY

class Stub:
    def __call__(self, ids, tt, vl, seg, pos):
        return nd.array(ids.asnumpy().astype(np.float32)[..., None])

eng = ServingEngine(Stub(), bucket_lens=(32,), max_rows=2,
                    engine_id="off-e0").start()
router = ServingRouter(engines=[eng], poll_interval_s=0.5).start()
eng.warmup()
fut = router.submit([1, 2, 3])
fut.result(timeout=30)
# microbench: the always-installed incident tap must keep emit cheap
n = 20000
t0 = time.perf_counter()
for _ in range(n):
    events.emit("bench_not_a_signal", x=1)
per_us = (time.perf_counter() - t0) / n * 1e6
out = {{
    "canary_attr": router.canary is None,
    "threads": sorted(t.name for t in threading.enumerate()
                      if t.name.startswith(("mxnet_tpu_canary",
                                            "mxnet_tpu_alert_egress"))),
    "families": sorted(n for n in REGISTRY._metrics
                       if n.startswith(("mxnet_tpu_canary_",
                                        "mxnet_tpu_alert_egress_"))),
    "emit_us": per_us,
}}
router.stop()
eng.stop()
print("RESULT " + json.dumps(out))
"""


@pytest.mark.timeout(300)
def test_disabled_paths_no_threads_no_families():
    """MXNET_TPU_CANARY=0 / MXNET_TPU_ALERT_EGRESS=0: a full
    router+engine lifecycle spawns no canary/egress thread and
    registers none of their families (subprocess: the process registry
    must be born clean), and the always-on incident tap keeps
    events.emit micro-cheap."""
    env = dict(os.environ, MXNET_TPU_CANARY="0",
               MXNET_TPU_ALERT_EGRESS="0",
               MXNET_TPU_ALERT_EGRESS_FILE="/tmp/should_not_exist.jsonl",
               JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", _DISABLED_PROBE.format(root=ROOT)],
        capture_output=True, text=True, env=env, timeout=280)
    assert proc.returncode == 0, proc.stderr[-4000:]
    line = [l for l in proc.stdout.splitlines()
            if l.startswith("RESULT ")][-1]
    out = json.loads(line[len("RESULT "):])
    assert out["canary_attr"] is True
    assert out["threads"] == [], out["threads"]
    assert out["families"] == [], out["families"]
    assert out["emit_us"] < 50.0, out["emit_us"]
