"""Shadow-diff validation (mxnet_tpu/serving/shadow): mirror diffing,
the swap gate, fire-and-forget isolation from the live path, the
2-engine router drill (divergent candidate detected and refused, zero
lost live requests; faithful candidate admitted), the /capture +
/shadow exposition bodies and telemetry_dump's exit-6 contract, and
the ``MXNET_TPU_SHADOW=0`` disabled-path guarantees. Tier-1.
"""
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from mxnet_tpu import nd
from mxnet_tpu.serving import (ServingEngine, ServingRouter, ServingError,
                               ShadowMirror, SwapGateError)
from mxnet_tpu.serving.queue import InferenceFuture
from mxnet_tpu.telemetry.registry import REGISTRY

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class StubModel:
    """out[b, s, 0] == ids[b, s] + bias — bias 0 is the faithful
    candidate, any other bias is a DIVERGENT one (wrong outputs at
    identical latency, the case only output diffing catches)."""

    def __init__(self, bias=0.0):
        self.bias = bias

    def __call__(self, ids, token_types, valid_length, segment_ids,
                 positions):
        out = ids.asnumpy().astype(np.float32)[..., None] + self.bias
        return nd.array(out)


class FakeReq:
    def __init__(self, trace_id, tokens=(1, 2, 3)):
        self.trace_id = trace_id
        self.tokens = np.asarray(tokens, np.int32)
        self.decode = None
        self.model_id = None
        self.tenant = None
        self.tenant_class = None


class EchoTarget:
    """In-process shadow seat stand-in: answers each mirrored submit
    with fn(tokens) on the caller thread."""

    def __init__(self, fn):
        self.fn = fn
        self.seen = []

    def submit(self, tokens, trace_id=None, model_id=None, tenant=None,
               tenant_class=None):
        self.seen.append(trace_id)
        fut = InferenceFuture()
        fut.set_result(self.fn(np.asarray(tokens)))
        return fut


def _mirror(monkeypatch, min_requests=4, fraction=1.0, threshold=0.0):
    monkeypatch.setenv("MXNET_TPU_SHADOW", "1")
    monkeypatch.setenv("MXNET_TPU_SHADOW_MIN_REQUESTS",
                       str(min_requests))
    monkeypatch.setenv("MXNET_TPU_SHADOW_FRACTION", str(fraction))
    monkeypatch.setenv("MXNET_TPU_SHADOW_THRESHOLD", str(threshold))
    return ShadowMirror("r-test")


def _drive(mirror, n, live_fn=lambda t: t.astype(np.float32)):
    for i in range(n):
        req = FakeReq(f"req-{i}")
        mirror.mirror(req, live_fn(req.tokens), primary_ms=2.0)


# ---------------------------------------------------------------------------
# mirror diffing + verdict
# ---------------------------------------------------------------------------

def test_faithful_candidate_matches_and_gate_opens(monkeypatch):
    m = _mirror(monkeypatch, min_requests=4)
    m.set_target(EchoTarget(lambda t: t.astype(np.float32)),
                 model_id="m0", version="v2")
    _drive(m, 6)
    v = m.verdict()
    assert v["compared"] == 6 and v["divergences"] == 0
    assert v["passing"] is True and v["divergence_rate"] == 0.0
    assert v["latency"]["primary"]["count"] == 6
    ok, reason = m.gate()
    assert ok, reason
    m.close()


def test_divergent_candidate_fails_and_gate_refuses(monkeypatch):
    m = _mirror(monkeypatch, min_requests=4)
    m.set_target(EchoTarget(lambda t: t.astype(np.float32) + 7.0),
                 model_id="m0", version="v2")
    _drive(m, 6)
    v = m.verdict()
    assert v["divergences"] == 6 and v["passing"] is False
    assert v["recent_divergences"]
    assert v["recent_divergences"][-1]["max_abs_diff"] \
        == pytest.approx(7.0)
    ok, reason = m.gate()
    assert not ok and "divergence rate" in reason
    m.close()


def test_float_packing_noise_is_not_a_divergence(monkeypatch):
    m = _mirror(monkeypatch, min_requests=2)
    m.set_target(EchoTarget(
        lambda t: t.astype(np.float32) + np.float32(3e-6)),
        model_id="m0", version="v2")
    _drive(m, 4)
    v = m.verdict()
    assert v["divergences"] == 0 and v["passing"] is True
    m.close()


def test_verdict_inconclusive_below_min_requests(monkeypatch):
    m = _mirror(monkeypatch, min_requests=8)
    m.set_target(EchoTarget(lambda t: t.astype(np.float32)),
                 model_id="m0", version="v2")
    _drive(m, 3)
    assert m.verdict()["passing"] is None
    ok, reason = m.gate()
    assert not ok and "3" in reason
    m.close()


def test_canary_and_fraction_sampling(monkeypatch):
    m = _mirror(monkeypatch, min_requests=1, fraction=0.5)
    tgt = EchoTarget(lambda t: t.astype(np.float32))
    m.set_target(tgt, model_id="m0", version="v2")
    assert m.mirror(FakeReq("canary-r0-1"), np.zeros(2, np.float32),
                    1.0) is False
    _drive(m, 8)
    assert m.verdict()["mirrored"] == 4          # exactly fraction * n
    # mirrored trace ids are namespaced off the live ones
    assert all(t.startswith("shadow-req-") for t in tgt.seen)
    m.close()


def test_rearm_resets_verdict(monkeypatch):
    m = _mirror(monkeypatch, min_requests=2)
    m.set_target(EchoTarget(lambda t: t.astype(np.float32) + 1.0),
                 model_id="m0", version="v2")
    _drive(m, 4)
    assert m.verdict()["passing"] is False
    m.set_target(EchoTarget(lambda t: t.astype(np.float32)),
                 model_id="m0", version="v3")
    assert m.verdict()["compared"] == 0          # fresh evidence
    _drive(m, 4)
    assert m.verdict()["passing"] is True
    m.close()


def test_mirror_is_fire_and_forget(monkeypatch):
    m = _mirror(monkeypatch, min_requests=1)

    class NeverDone:
        def __init__(self):
            self.futs = []

        def submit(self, tokens, **kw):
            fut = InferenceFuture()
            self.futs.append(fut)
            return fut

    tgt = NeverDone()
    m.set_target(tgt, model_id="m0", version="v2")
    t0 = time.perf_counter()
    _drive(m, 20)
    dt = time.perf_counter() - t0
    # a shadow seat that never answers costs the live path ~nothing
    assert dt < 0.5, f"mirror blocked the live path: {dt:.3f}s"
    assert m.verdict()["compared"] == 0
    # late completions still land (outside any live wait)
    for fut in tgt.futs:
        fut.set_result(np.asarray([1, 2, 3], np.float32))
    assert m.verdict()["compared"] == 20
    m.close()


# ---------------------------------------------------------------------------
# the swap gate
# ---------------------------------------------------------------------------

def test_swap_model_gate_refuses_then_admits(monkeypatch):
    m = _mirror(monkeypatch, min_requests=2)
    with ServingEngine(StubModel(), bucket_lens=(16,), max_rows=2,
                       engine_id="gate0") as eng:
        eng.warmup()
        # an unarmed, evidence-free gate refuses (no silent flips)
        with pytest.raises(SwapGateError):
            eng.swap_model(StubModel(), version="v2", gate=m)
        # divergent candidate: evidence says no
        m.set_target(EchoTarget(lambda t: t.astype(np.float32) + 5.0),
                     model_id="default", version="v2")
        _drive(m, 4)
        with pytest.raises(SwapGateError) as ei:
            eng.swap_model(StubModel(bias=5.0), version="v2", gate=m)
        assert "divergence rate" in str(ei.value)
        assert eng.infer([1, 2, 3], timeout=30)[0] == 1.0  # still live
        # faithful candidate: evidence says yes, flip proceeds
        m.set_target(EchoTarget(lambda t: t.astype(np.float32)),
                     model_id="default", version="v3")
        _drive(m, 4)
        eng.swap_model(StubModel(), version="v3", gate=m)
        assert eng.infer([1, 2, 3], timeout=30)[0] == 1.0
    m.close()


# ---------------------------------------------------------------------------
# the router drill: divergent candidate behind a 2-engine fleet
# ---------------------------------------------------------------------------

def _wait_for(pred, timeout, what):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def test_router_shadow_drill_refuse_then_admit(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_SHADOW", "1")
    monkeypatch.setenv("MXNET_TPU_SHADOW_MIN_REQUESTS", "6")
    monkeypatch.setenv("MXNET_TPU_SHADOW_FRACTION", "1.0")
    engines = [ServingEngine(StubModel(), bucket_lens=(16,), max_rows=2,
                             engine_id=f"sd{i}") for i in range(2)]
    for eng in engines:
        eng.start()
        eng.warmup()
    router = ServingRouter(engines=engines, poll_interval_s=0.1)
    router.start()
    bad = ServingEngine(StubModel(bias=3.0), bucket_lens=(16,),
                        max_rows=2, engine_id="cand-bad")
    good = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=2,
                         engine_id="cand-good")
    bad.start(), good.start()
    try:
        router.set_shadow_target(bad, model_id="default", version="v2")
        futs = [router.submit([1 + (i % 5), 2, 3]) for i in range(10)]
        outs = [f.result(timeout=60) for f in futs]
        # ZERO lost live requests, correct live outputs throughout
        assert len(outs) == 10
        assert all(o[0] == 1 + (i % 5) for i, o in enumerate(outs))
        _wait_for(lambda: router.shadow_verdict()["compared"] >= 10,
                  30, "mirrored completions")
        v = router.shadow_verdict()
        assert v["passing"] is False and v["divergences"] >= 6
        assert v["latency"]["shadow"]["count"] >= 6
        # the flip is refused on EVERY seat while the verdict fails
        for eng in engines:
            with pytest.raises(SwapGateError):
                eng.swap_model(StubModel(bias=3.0), version="v2",
                               gate=router.shadow)

        # faithful candidate: fresh evidence, gate opens, swap lands
        router.set_shadow_target(good, model_id="default",
                                 version="v2")
        futs = [router.submit([2, 2, 3]) for _ in range(8)]
        for f in futs:
            f.result(timeout=60)
        _wait_for(lambda: router.shadow_verdict()["compared"] >= 8,
                  30, "faithful mirror completions")
        assert router.shadow_verdict()["passing"] is True
        for eng in engines:
            eng.swap_model(StubModel(), version="v2",
                           gate=router.shadow)
        assert router.submit([7, 2]).result(timeout=60)[0] == 7.0
    finally:
        router.stop()
        for eng in engines + [bad, good]:
            eng.stop()


# ---------------------------------------------------------------------------
# exposition + telemetry_dump
# ---------------------------------------------------------------------------

def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, r.read().decode()


def test_capture_and_shadow_endpoints(monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_TPU_CAPTURE", "1")
    monkeypatch.setenv("MXNET_TPU_CAPTURE_DIR", str(tmp_path / "c"))
    monkeypatch.setenv("MXNET_TPU_SHADOW", "1")
    monkeypatch.setenv("MXNET_TPU_SHADOW_MIN_REQUESTS", "2")
    monkeypatch.setenv("MXNET_TPU_SHADOW_FRACTION", "1.0")
    eng = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=2,
                        engine_id="ep0")
    with eng:
        eng.warmup()
        router = ServingRouter(engines=[eng], poll_interval_s=0.1)
        router.start()
        try:
            srv = router.expose()
            router.submit([1, 2, 3]).result(timeout=30)
            code, body = _get(srv.url("/capture"))
            assert code == 200
            cap = json.loads(body)
            assert cap["fleet"]["records_written"] >= 1
            assert "ep0" in cap["engines"]
            code, body = _get(srv.url("/shadow"))
            assert code == 200
            shad = json.loads(body)
            assert shad["enabled"] and shad["active"] is False

            sys.path.insert(0, os.path.join(ROOT, "tools"))
            import telemetry_dump
            assert telemetry_dump.main(
                ["--capture", srv.url("")]) == 0
            # an inconclusive (unarmed) verdict is not a FAILING one
            assert telemetry_dump.main(
                ["--shadow", srv.url("")]) == 0
            # arm at a divergent candidate, land divergences -> exit 6
            bad = ServingEngine(StubModel(bias=2.0), bucket_lens=(16,),
                                max_rows=2, engine_id="ep-bad")
            bad.start()
            try:
                router.set_shadow_target(bad, model_id="default",
                                         version="v9")
                for i in range(4):
                    router.submit([1 + i, 2]).result(timeout=30)
                _wait_for(
                    lambda: (router.shadow_verdict() or
                             {}).get("compared", 0) >= 4,
                    30, "mirror completions")
                assert router.shadow_verdict()["passing"] is False
                assert telemetry_dump.main(
                    ["--shadow", srv.url("")]) == 6
            finally:
                bad.stop()
        finally:
            router.stop()


def test_engine_capture_endpoint_disabled_404(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_CAPTURE", raising=False)
    with ServingEngine(StubModel(), bucket_lens=(16,), max_rows=2,
                       engine_id="ep-off") as eng:
        srv = eng.expose()
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.url("/capture"))
        assert ei.value.code == 404


# ---------------------------------------------------------------------------
# disabled path: MXNET_TPU_SHADOW=0 builds nothing
# ---------------------------------------------------------------------------

def test_shadow_disabled_builds_nothing(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_SHADOW", raising=False)
    before = set(threading.enumerate())
    with ServingEngine(StubModel(), bucket_lens=(16,), max_rows=2,
                       engine_id="off0") as eng:
        eng.warmup()
        router = ServingRouter(engines=[eng], poll_interval_s=0.1)
        router.start()
        try:
            assert router.shadow is None
            assert router.shadow_verdict() is None
            with pytest.raises(ServingError):
                router.set_shadow_target(eng)
            router.clear_shadow_target()        # no-op, never raises
            router.submit([1, 2, 3]).result(timeout=30)
            srv = router.expose()
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(srv.url("/shadow"))
            assert ei.value.code == 404
            extra = [t.name
                     for t in set(threading.enumerate()) - before]
            assert not any("shadow" in n.lower() for n in extra)
        finally:
            router.stop()
    assert f'owner="{router.router_id}"' not in REGISTRY.render_prometheus()
