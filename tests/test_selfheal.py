"""Self-healing fleet mechanics (ISSUE 14): the shared retry policy,
the remove_engine mid-burst race fix, SLO-aware routing weights,
router active/active HA (journal → adoption → cid dedupe), and the
burn/queue autoscaler. The end-to-end chaos drill lives in
tests/test_chaos.py; these are the per-mechanism contracts.
"""
import random
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401  (backend/env init)
from mxnet_tpu import nd
from mxnet_tpu.retrying import Reconnector, RetryPolicy
from mxnet_tpu.serving import (FleetAutoscaler, ServingEngine,
                               ServingRouter)


class StubModel:
    """out[b, s, 0] == ids[b, s] — responses bit-check against the
    request's own tokens (same contract stub as test_serving)."""

    def __init__(self, delay=0.0):
        self.delay = delay

    def __call__(self, ids, token_types, valid_length, segment_ids,
                 positions):
        if self.delay:
            time.sleep(self.delay)
        return nd.array(ids.asnumpy().astype(np.float32)[..., None])


def _stub_engine(engine_id, delay=0.0, **kw):
    kw.setdefault("bucket_lens", (16,))
    kw.setdefault("max_rows", 2)
    return ServingEngine(StubModel(delay=delay), engine_id=engine_id,
                         **kw)


def _wait(pred, timeout=30.0, what="condition", poll=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(poll)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# retrying.py: the one repo-wide policy
# ---------------------------------------------------------------------------

def test_retry_policy_delay_golden_and_call():
    """Doubling backoff + proportional jitter, retries+1 attempts,
    final failure re-raises — the egress semantics, now shared."""
    sleeps = []
    policy = RetryPolicy(retries=3, backoff_s=0.5, jitter=0.5,
                         sleep=sleeps.append, rng=random.Random(0))
    attempts = []

    def fail():
        attempts.append(1)
        raise OSError("down")

    retried = []
    with pytest.raises(OSError):
        policy.call(fail, on_retry=lambda a, e: retried.append(a))
    assert len(attempts) == 4 and retried == [0, 1, 2]
    assert len(sleeps) == 3
    for i, s in enumerate(sleeps):
        base = 0.5 * (2 ** i)
        assert base <= s <= base * 1.5, sleeps

    # success on attempt 2 stops retrying; cap bounds the pre-jitter
    seq = iter([OSError("x"), OSError("y"), "ok"])

    def flaky():
        v = next(seq)
        if isinstance(v, Exception):
            raise v
        return v

    assert policy.call(flaky) == "ok"
    capped = RetryPolicy(retries=8, backoff_s=1.0, jitter=0.0,
                         max_backoff_s=4.0, sleep=lambda s: None)
    assert capped.delay(0) == 1.0
    assert capped.delay(5) == 4.0       # capped, no jitter


def test_reconnector_backoff_gates_poll_ticks():
    """Consecutive failed connects push the next attempt out; success
    resets the ladder — a dead peer costs one dial per window."""
    clock = [0.0]
    recon = Reconnector(RetryPolicy(retries=0, backoff_s=1.0,
                                    jitter=0.0, max_backoff_s=8.0),
                        clock=lambda: clock[0])
    assert recon.ready()
    recon.failed()
    assert not recon.ready()            # 1.0 s backoff pending
    clock[0] = 0.5
    assert not recon.ready()
    clock[0] = 1.0
    assert recon.ready()
    recon.failed()                      # second failure: 2.0 s
    clock[0] = 2.5
    assert not recon.ready()
    clock[0] = 3.1
    assert recon.ready()
    recon.succeeded()
    recon.failed()                      # ladder reset: base again
    clock[0] = 4.2
    assert recon.ready()


# ---------------------------------------------------------------------------
# remove_engine racing in-flight dispatches (the regression)
# ---------------------------------------------------------------------------

def test_remove_engine_mid_burst_zero_loss():
    """Removing (and re-adding) a seat while a burst is in flight
    must never error a request: dispatches racing the removal land in
    the failover requeue and complete on a sibling or the
    replacement."""
    keep = _stub_engine("rm-keep", max_rows=1)
    victims = [_stub_engine("rm-victim", delay=0.01, max_rows=1)
               for _ in range(4)]
    router = ServingRouter(engines={"rm-keep": keep,
                                    "rm-victim": victims[0]},
                           poll_interval_s=30.0)
    keep.start()
    for v in victims:
        v.start()
    router.start()
    futs = []
    stop = threading.Event()

    def churn():
        # remove + replace the victim seat under the same id, over
        # and over, while the burst is dispatching
        gen = 0
        while not stop.is_set() and gen < len(victims) - 1:
            time.sleep(0.03)
            router.remove_engine("rm-victim")
            gen += 1
            router.add_engine("rm-victim", victims[gen])

    t = threading.Thread(target=churn, daemon=True, name="rm_churn")
    try:
        t.start()
        for i in range(120):
            futs.append(router.submit([7, 8, 9]))
            time.sleep(0.002)
        outs = [f.result(timeout=60) for f in futs]
        stop.set()
        t.join(timeout=30)
        for o in outs:
            assert o[0, 0] == 7.0       # nothing lost, nothing wrong
        assert router.count("completed") == len(futs)
        assert router.count("failed") == 0
        assert router.count("shed_no_engine") == 0
    finally:
        stop.set()
        router.stop()
        keep.stop()
        for v in victims:
            try:
                v.stop(drain=False)
            except Exception:
                pass


def test_replacement_seat_under_reused_id_is_fresh_candidate():
    """req.tried pins seat GENERATION tokens, not ids: a request that
    failed over from the old seat can still be served by its same-id
    replacement (previously the id was poisoned forever)."""
    a = _stub_engine("gen-a")
    b = _stub_engine("gen-b")
    router = ServingRouter(engines=[a, b], poll_interval_s=30.0)
    with a, b, router:
        with router._lock:
            old = router._seats["gen-a"]
        router.remove_engine("gen-a")
        a2 = _stub_engine("gen-a")
        a2.start()
        try:
            router.add_engine("gen-a", a2)
            with router._lock:
                new = router._seats["gen-a"]
                assert new.token != old.token
                # a request that already tried the OLD generation can
                # still pick the replacement
                picked = router._pick_locked({old.token,
                                              router._seats["gen-b"].token})
                assert picked is new
        finally:
            a2.stop()


# ---------------------------------------------------------------------------
# SLO-aware routing weights
# ---------------------------------------------------------------------------

def test_step_weight_hysteresis_and_floor():
    """Shed is smooth (gain-tracked), entry needs the target at the
    enter bound, recovery needs _W_OK_POLLS consecutive good polls —
    and the weight never leaves [floor, 1]."""
    from mxnet_tpu.serving import router as router_mod

    eng = _stub_engine("w-hys")
    r = ServingRouter(engines=[eng], poll_interval_s=30.0)
    with r._lock:
        seat = r._seats["w-hys"]
    # healthy seats ignore mild targets (no flapping on noise)
    r._step_weight(seat, 0.8)
    assert seat.hys == "healthy" and seat.weight == 1.0
    # a target at the enter bound degrades; weight tracks smoothly
    r._step_weight(seat, 0.1)
    assert seat.hys == "degraded"
    w1 = seat.weight
    assert w1 < 1.0
    r._step_weight(seat, 0.1)
    assert seat.weight < w1
    for _ in range(40):
        r._step_weight(seat, 0.05)
    assert seat.weight >= r._w_floor
    # recovery: needs _W_OK_POLLS consecutive good targets
    r._step_weight(seat, 1.0)
    assert seat.hys == "degraded"
    r._step_weight(seat, 0.5)           # blip resets the exit count
    r._step_weight(seat, 1.0)
    r._step_weight(seat, 1.0)
    assert seat.hys == "degraded"
    r._step_weight(seat, 1.0)
    assert seat.hys == "healthy" and seat.weight == 1.0
    assert router_mod._W_OK_POLLS == 3


def test_weighted_pick_prefers_healthy_seat():
    """With one seat shed to the floor, the picker sends it only
    overflow traffic — and with equal weights the order is exactly
    the classic least-outstanding."""
    a = _stub_engine("wp-a")
    b = _stub_engine("wp-b")
    r = ServingRouter(engines=[a, b], poll_interval_s=30.0)
    with r._lock:
        sa, sb = r._seats["wp-a"], r._seats["wp-b"]
        sb.weight = 0.05
        picks = []
        for _ in range(6):
            seat = r._pick_locked(set())
            picks.append(seat.engine_id)
            seat.outstanding += 1
        # the degraded seat only gets picked once the healthy one is
        # loaded: (o+1)/1 > 1/0.05 needs o >= 19 — never here
        assert picks == ["wp-a"] * 6
        sa.outstanding = sb.outstanding = 0
        sb.weight = 1.0
        picks = []
        for _ in range(4):
            seat = r._pick_locked(set())
            picks.append(seat.engine_id)
            seat.outstanding += 1
        assert sorted(picks[:2]) == ["wp-a", "wp-b"]


def test_router_sheds_weight_off_burning_seat(monkeypatch, tmp_path):
    """Integration: a seat whose forwards slow past the latency SLO
    burns its budget; the router's poll folds that burn into the
    seat's weight (degraded, under the enter bound) and traffic share
    moves to the healthy sibling. Clearing the slowdown recovers the
    weight through the hysteresis exit."""
    monkeypatch.setenv("MXNET_TPU_SLO_WINDOW_SCALE", "0.01")
    monkeypatch.setenv("MXNET_TPU_SLO_EVAL_S", "0.1")
    monkeypatch.setenv("MXNET_TPU_SLO_LATENCY_MS", "30")
    monkeypatch.setenv("MXNET_TPU_CANARY", "0")
    monkeypatch.setenv("MXNET_TPU_FLIGHT_DIR", str(tmp_path))
    slow_model = StubModel()
    slow = ServingEngine(slow_model, bucket_lens=(16,), max_rows=2,
                         engine_id="burn-slow")
    fast = _stub_engine("burn-fast")
    router = ServingRouter(engines=[slow, fast], poll_interval_s=0.15)
    stop = threading.Event()
    errors = []

    def load():
        rs = np.random.RandomState(3)
        while not stop.is_set():
            toks = rs.randint(1, 60, 6).astype(np.int32)
            try:
                router.submit(toks).result(timeout=30)
            except Exception as e:
                errors.append(repr(e))

    threads = [threading.Thread(target=load, daemon=True,
                                name=f"burn_load_{i}")
               for i in range(4)]
    with slow, fast, router:
        for t in threads:
            t.start()
        try:
            _wait(lambda: router.count("completed") > 8, what="traffic")
            slow_model.delay = 0.08     # the hot-spot
            _wait(lambda: (router.scoreboard()["burn-slow"]["weight"]
                           < 0.7), timeout=60,
                  what="the burning seat to shed weight")
            # measured share moves: the slow seat serves a fraction
            b0 = {k: v["dispatched"]
                  for k, v in router.scoreboard().items()}
            time.sleep(1.2)
            b1 = {k: v["dispatched"]
                  for k, v in router.scoreboard().items()}
            d_slow = b1["burn-slow"] - b0["burn-slow"]
            d_fast = b1["burn-fast"] - b0["burn-fast"]
            assert d_fast > 2 * max(1, d_slow), (d_slow, d_fast)
            slow_model.delay = 0.0      # recovery
            _wait(lambda: (router.scoreboard()["burn-slow"]["weight"]
                           >= 0.95), timeout=60,
                  what="the seat weight to recover")
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
    assert not errors, errors[:5]


# ---------------------------------------------------------------------------
# router active/active HA
# ---------------------------------------------------------------------------

@pytest.fixture()
def ha_pair():
    """Two peered routers over one 2-engine fleet, both exposed, HA
    links up. Yields (r_keep, r_kill, engines, urls)."""
    import contextlib

    with contextlib.ExitStack() as stack:
        engines = [_stub_engine("ha-e0", delay=0.05),
                   _stub_engine("ha-e1", delay=0.05)]
        for eng in engines:
            eng.start()
            stack.callback(lambda e=eng: e.stop(drain=False))
        fleet = {e.engine_id: e for e in engines}
        r_keep = ServingRouter(engines=dict(fleet),
                               poll_interval_s=0.15,
                               router_id="ha-keep")
        r_kill = ServingRouter(engines=dict(fleet),
                               poll_interval_s=0.15,
                               router_id="ha-kill")
        stack.callback(lambda: r_kill.stop(drain=False))
        stack.callback(lambda: r_keep.stop(drain=False))
        ks = r_keep.expose()
        xs = r_kill.expose()
        keep_url = f"http://{ks.host}:{ks.port}"
        kill_url = f"http://{xs.host}:{xs.port}"
        r_keep.set_peer(kill_url)
        r_kill.set_peer(keep_url)
        r_keep.start()
        r_kill.start()
        _wait(lambda: r_keep._peer_alive and r_kill._peer_alive,
              what="peer liveness")
        _wait(lambda: (r_keep._peer is not None
                       and r_keep._peer.has_live()
                       and r_kill._peer is not None
                       and r_kill._peer.has_live()),
              what="journal links")
        yield r_keep, r_kill, engines, (keep_url, kill_url)


def test_ha_journal_and_release(ha_pair):
    """Every admitted submit is journaled to the peer before dispatch
    and released on completion — the peer's journal never outlives a
    resolved request."""
    r_keep, r_kill, _engines, _urls = ha_pair
    fut = r_kill.submit([1, 2, 3], cid="cid-journal-1")
    # journaled on the peer (ack-before-enqueue: already visible)
    with r_keep._lock:
        assert "cid-journal-1" in r_keep._journal
    assert fut.result(timeout=30)[0, 0] == 1.0
    _wait(lambda: "cid-journal-1" not in r_keep._journal,
          what="release to reach the peer")


def test_ha_adoption_on_router_death_zero_loss(ha_pair):
    """The crash contract: r_kill dies with requests in flight; the
    survivor adopts every journaled orphan front-of-queue, completes
    it, and a client resubmitting its cid gets the SAME result
    without duplicate admission."""
    r_keep, r_kill, _engines, _urls = ha_pair
    cids = [f"cid-adopt-{i}" for i in range(6)]
    for cid in cids:
        r_kill.submit([4, 5, 6, 7], cid=cid)   # in flight (50 ms model)
    r_kill.die()
    # the survivor declares the peer dead off its health poll and
    # adopts the orphans
    _wait(lambda: r_keep.count("adopted") >= 1, timeout=30,
          what="orphan adoption")
    _wait(lambda: all(cid in r_keep._adopted for cid in cids),
          timeout=30, what="every orphan adopted")
    # adopted requests complete on the survivor
    for cid in cids:
        out = r_keep._adopted[cid].result(timeout=30)
        assert out[0, 0] == 4.0
    # client resubmit attaches (dedupe), not duplicate work
    before = r_keep.count("submitted")
    fut = r_keep.submit([4, 5, 6, 7], cid=cids[0])
    assert fut.result(timeout=30)[0, 0] == 4.0
    assert r_keep.count("submitted") == before     # attached, not new
    # the incident hold released: peer down -> adopted
    from mxnet_tpu.telemetry import incidents
    snap = incidents.snapshot()
    mine = [r for r in snap["open"] + snap["recent"]
            if any(f"peer:" in d for d in r.get("down_engines", []))]
    for r in mine:
        assert not r["down_engines"], r


def test_ha_resubmit_before_death_detection(ha_pair):
    """A client whose router died can resubmit IMMEDIATELY (before
    the survivor's health poll notices): the cid is found in the
    peer journal and consumed as an adoption — exactly-once."""
    r_keep, r_kill, _engines, _urls = ha_pair
    fut0 = r_kill.submit([9, 9, 9], cid="cid-fast-resubmit")
    del fut0
    with r_keep._lock:
        assert "cid-fast-resubmit" in r_keep._journal
    # no die() yet — the resubmit itself consumes the journal entry
    fut = r_keep.submit([9, 9, 9], cid="cid-fast-resubmit")
    assert fut.result(timeout=30)[0, 0] == 9.0
    with r_keep._lock:
        assert "cid-fast-resubmit" not in r_keep._journal


# ---------------------------------------------------------------------------
# autoscaler
# ---------------------------------------------------------------------------

def test_autoscaler_scale_up_hold_cooldown_and_down():
    """Scripted-clock ladder: pressure must HOLD before a seat is
    bought, the cooldown rate-limits, idle retires only
    autoscaler-added seats down to min_seats."""
    eng = _stub_engine("as-base")
    router = ServingRouter(engines=[eng], poll_interval_s=30.0)
    clock = [0.0]
    made = []

    def factory(engine_id):
        e = _stub_engine(engine_id)
        made.append(e)
        return e

    scaler = FleetAutoscaler(router, factory, min_seats=1, max_seats=3,
                             burn_threshold=6.0, queue_high=50,
                             hold_s=5.0, cooldown_s=30.0, idle_s=60.0,
                             replace_s=3.0, clock=lambda: clock[0])
    sig = {"burn": None, "queue": 0}
    scaler._signals = lambda: (sig["burn"], sig["queue"],
                               router.snapshot()["engines"])
    with eng, router:
        try:
            assert scaler.evaluate_once() is None       # quiet fleet
            sig["burn"] = 20.0                          # pressure on
            assert scaler.evaluate_once() is None       # not held yet
            clock[0] = 4.0
            assert scaler.evaluate_once() is None
            clock[0] = 6.0
            rec = scaler.evaluate_once()                # held: buy
            assert rec and rec["action"] == "scale_up"
            assert rec["ttft_ms"] is not None
            assert "auto1" in router.engine_ids()
            clock[0] = 10.0
            assert scaler.evaluate_once() is None       # cooldown
            clock[0] = 50.0                             # pressure held
            rec = scaler.evaluate_once()                # through the
            assert rec and rec["action"] == "scale_up"  # cooldown: buy
            assert len(router.engine_ids()) == 3        # at max now
            clock[0] = 85.0
            assert scaler.evaluate_once() is None       # max respected
            sig["burn"] = 0.5                           # idle
            sig["queue"] = 0
            clock[0] = 100.0
            assert scaler.evaluate_once() is None       # idle not held
            clock[0] = 161.0
            rec = scaler.evaluate_once()
            assert rec and rec["action"] == "scale_down"
            assert rec["engine_id"] == "auto2"          # LIFO retire
            clock[0] = 230.0
            assert scaler.evaluate_once() is None       # idle restarts
            clock[0] = 292.0
            rec = scaler.evaluate_once()
            assert rec and rec["action"] == "scale_down"
            assert router.engine_ids() == ["as-base"]   # min respected
            clock[0] = 360.0
            assert scaler.evaluate_once() is None
        finally:
            scaler.stop(stop_seats=True)


def test_autoscaler_replaces_dead_seat_warm():
    """A seat held unroutable past the debounce is replaced under the
    same id with a manifest-warmed, TTFT-probed engine — on EVERY
    router it fronts (active/active seat-state sharing)."""
    e0 = _stub_engine("rep-e0")
    e1 = _stub_engine("rep-e1")
    r1 = ServingRouter(engines=[e0, e1], poll_interval_s=0.1,
                       router_id="rep-r1")
    r2 = ServingRouter(engines=[e0, e1], poll_interval_s=0.1,
                       router_id="rep-r2")
    spawned = []

    def factory(engine_id):
        e = _stub_engine(engine_id)
        spawned.append(e)
        return e

    scaler = FleetAutoscaler([r1, r2], factory, min_seats=2,
                             max_seats=3, interval_s=0.1,
                             replace_s=0.3, cooldown_s=0.5,
                             hold_s=1.0)
    with e0, e1, r1, r2:
        for eng in (e0, e1):
            eng.warmup()        # visited shapes -> fleet manifest
        _wait(lambda: (r1.snapshot()["manifest_shapes"] or 0) > 0,
              what="fleet manifest collected")
        scaler.start()
        try:
            e0.stop(drain=False)
            rec = _wait(lambda: next(
                (a for a in scaler.actions
                 if a["action"] == "replace"
                 and a["engine_id"] == "rep-e0"), None),
                timeout=60, what="replacement")
            assert rec["ttft_ms"] is not None
            assert rec["manifest_shapes"] >= 1      # admitted WARM
            for r in (r1, r2):
                _wait(lambda r=r: r.scoreboard()
                      .get("rep-e0", {}).get("routable"), timeout=30,
                      what=f"replacement routable on {r.router_id}")
            # the replacement actually serves
            out = r1.submit([5, 5]).result(timeout=30)
            assert out[0, 0] == 5.0
        finally:
            scaler.stop(stop_seats=True)
