"""Telemetry subsystem tests (mxnet_tpu/telemetry): registry
concurrency exactness, Prometheus text-format golden, live
ServingEngine /metrics + /healthz + /stats scrape, loadgen
server/client reconciliation, trace-id correlation across the serving
event log + Chrome trace and across the dist_async wire (two REAL
processes), and the disabled-path cost guard the acceptance criteria
require.
"""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, profiler
from mxnet_tpu.serving import ServingEngine, ServingStats
from mxnet_tpu.telemetry import (MetricsRegistry, REGISTRY,
                                 histogram_quantile, parse_prometheus_text,
                                 TelemetryServer, events, trace_context)
from mxnet_tpu.telemetry.expo import parse_labels

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


class StubModel:
    def __call__(self, ids, token_types, valid_length, segment_ids,
                 positions):
        return nd.array(ids.asnumpy().astype(np.float32)[..., None])


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_concurrent_totals_exact():
    """The concurrency contract: N threads bumping/observing in
    parallel lose NOTHING — totals are exact, not approximate."""
    reg = MetricsRegistry()
    c = reg.counter("t_total", "x", ("worker",))
    h = reg.histogram("t_ms", "x", buckets=(1.0, 10.0, 100.0))
    g = reg.gauge("t_depth")
    n_threads, per_thread = 8, 5000

    def work(i):
        child = c.labels(worker=i % 2)
        for j in range(per_thread):
            child.inc()
            h.observe(float(j % 200))
            g.inc()

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = n_threads * per_thread
    assert c.labels(worker=0).value + c.labels(worker=1).value == total
    assert h.count == total
    # +Inf bucket of the rendered histogram equals the exact count too
    parsed = parse_prometheus_text(reg.render_prometheus())
    assert parsed['t_ms_bucket{le="+Inf"}'] == total
    assert g.value == total
    # histogram sum is the exact arithmetic series sum
    assert h.sum == n_threads * sum(float(j % 200)
                                    for j in range(per_thread))


def test_registry_idempotent_and_conflicts():
    reg = MetricsRegistry()
    a = reg.counter("same_total", "x", ("k",))
    assert reg.counter("same_total", "x", ("k",)) is a
    with pytest.raises(ValueError):
        reg.gauge("same_total")                  # kind conflict
    with pytest.raises(ValueError):
        reg.counter("same_total", "x", ("other",))   # label conflict
    with pytest.raises(ValueError):
        a.labels(k="v").inc(-1)                  # counters only go up
    with pytest.raises(ValueError):
        a.inc()                                  # labeled family needs labels
    h = reg.histogram("same_ms", "x", buckets=(1.0, 2.0))
    assert reg.histogram("same_ms", "x", buckets=(1.0, 2.0)) is h
    assert reg.histogram("same_ms", "x") is h    # None = no opinion
    with pytest.raises(ValueError):
        reg.histogram("same_ms", "x", buckets=(5.0,))  # bucket conflict


def test_prometheus_text_golden():
    """Exact text-format golden: escaping, deterministic ordering,
    histogram bucket CUMULATIVITY and +Inf == _count."""
    reg = MetricsRegistry()
    c = reg.counter("req_total", "requests served", ("path", "code"))
    c.labels(path='/a"b\\c\nd', code=200).inc(3)
    c.labels(path="/plain", code=500).inc()
    g = reg.gauge("depth", "queue depth")
    g.set(7)
    h = reg.histogram("lat_ms", "latency", buckets=(1.0, 5.0, 25.0))
    # binary-exact values: the _sum golden must not chase float repr
    for v in (0.5, 0.75, 3.0, 30.0, 100.0):
        h.observe(v)
    golden = "\n".join([
        '# HELP depth queue depth',
        '# TYPE depth gauge',
        'depth 7',
        '# HELP lat_ms latency',
        '# TYPE lat_ms histogram',
        'lat_ms_bucket{le="1"} 2',
        'lat_ms_bucket{le="5"} 3',
        'lat_ms_bucket{le="25"} 3',
        'lat_ms_bucket{le="+Inf"} 5',
        'lat_ms_sum 134.25',
        'lat_ms_count 5',
        '# HELP req_total requests served',
        '# TYPE req_total counter',
        'req_total{path="/a\\"b\\\\c\\nd",code="200"} 3',
        'req_total{path="/plain",code="500"} 1',
    ]) + "\n"
    assert reg.render_prometheus() == golden
    # the scrape parser inverts the renderer
    parsed = parse_prometheus_text(golden)
    assert parsed['req_total{path="/a\\"b\\\\c\\nd",code="200"}'] == 3.0
    assert parsed['lat_ms_bucket{le="+Inf"}'] == 5.0
    name, labels = parse_labels('req_total{path="/a\\"b\\\\c\\nd",code="200"}')
    assert name == "req_total" and labels["path"] == '/a"b\\c\nd'
    # backslash-then-'n' must survive the round trip (NOT a newline)
    from mxnet_tpu.telemetry.registry import escape_label_value
    tricky = 'C:\\new"\\\\q'
    _, rt = parse_labels('m{v="' + escape_label_value(tricky) + '"}')
    assert rt["v"] == tricky
    # quantile estimate lands inside the right bucket
    p50 = histogram_quantile(parsed, "lat_ms", 50)
    assert 0.0 < p50 <= 5.0


def test_snapshot_and_compact():
    reg = MetricsRegistry()
    reg.counter("a_total").inc(2)
    reg.counter("zero_total")                 # no samples: not compacted
    reg.histogram("h_ms", buckets=(1.0,)).observe(0.5)
    snap = reg.snapshot()
    assert snap["a_total"]["kind"] == "counter"
    compact = reg.snapshot_compact()
    assert compact["a_total"] == {"": 2.0}
    assert "zero_total" not in compact
    assert compact["h_ms"] == {"": 1}


# ---------------------------------------------------------------------------
# events + trace
# ---------------------------------------------------------------------------

def test_event_log_records_and_trace_context(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    log = events.EventLog(path, component="test")
    with trace_context("tid-123"):
        log.emit("thing_happened", n=3)
    log.emit("other")
    log.close()
    recs = events.read_events(path)
    assert len(recs) == 2
    assert recs[0]["event"] == "thing_happened"
    assert recs[0]["trace_id"] == "tid-123" and recs[0]["n"] == 3
    assert recs[0]["component"] == "test"
    assert recs[1]["trace_id"] is None
    assert recs[0]["pid"] == os.getpid()
    assert isinstance(recs[0]["ts"], float) and recs[0]["mono"] > 0
    assert events.read_events(path, event="other") == [recs[1]]


def test_event_log_directory_mode(tmp_path):
    """MXNET_TPU_EVENT_LOG pointing at a DIRECTORY gives each process
    its own events-<pid>.jsonl — the multi-process launch contract."""
    from mxnet_tpu.telemetry.events import _resolve_path
    p = _resolve_path(str(tmp_path))
    assert p == os.path.join(str(tmp_path), f"events-{os.getpid()}.jsonl")
    assert _resolve_path("/x/y.jsonl") == "/x/y.jsonl"


def test_trace_ids_unique_and_scoped():
    from mxnet_tpu.telemetry import current_trace_id, new_trace_id
    ids = {new_trace_id() for _ in range(1000)}
    assert len(ids) == 1000
    assert current_trace_id() is None
    with trace_context("outer"):
        assert current_trace_id() == "outer"
        with trace_context("inner"):
            assert current_trace_id() == "inner"
        assert current_trace_id() == "outer"
    assert current_trace_id() is None


# ---------------------------------------------------------------------------
# exposition server
# ---------------------------------------------------------------------------

def test_expo_endpoints_and_health_transitions():
    reg = MetricsRegistry()
    reg.counter("up_total").inc(5)
    health = {"ok": True}
    srv = TelemetryServer(registry=reg,
                          healthz_fn=lambda: (health["ok"],
                                              {"note": "unit"}),
                          stats_fn=lambda: {"x": 1}, port=0)
    try:
        code, body = _get(srv.url("/metrics"))
        assert code == 200 and parse_prometheus_text(body)["up_total"] == 5
        code, body = _get(srv.url("/healthz"))
        assert code == 200 and json.loads(body)["ok"] is True
        code, body = _get(srv.url("/stats"))
        assert code == 200 and json.loads(body) == {"x": 1}
        health["ok"] = False
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.url("/healthz"))
        assert ei.value.code == 503
        with pytest.raises(urllib.error.HTTPError) as e2:
            _get(srv.url("/nope"))
        assert e2.value.code == 404
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# serving end to end: scrape a live engine, reconcile with the loadgen
# ---------------------------------------------------------------------------

def test_live_engine_scrape_and_loadgen_reconciliation():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    from serve_loadgen import run_load

    eng = ServingEngine(StubModel(), bucket_lens=(64,), max_rows=4,
                        max_queue_depth=256)
    with eng:
        srv = eng.expose()
        code, body = _get(srv.url("/healthz"))
        assert code == 200 and json.loads(body)["worker_alive"] is True
        report = run_load(eng, n_clients=6, requests_per_client=8,
                          min_len=8, max_len=48, vocab=60,
                          metrics_url=srv.url("/metrics"))
        # /stats serves the engine snapshot dict, scrapeable
        code, body = _get(srv.url("/stats"))
        stats = json.loads(body)
        assert stats["counters"]["completed"] == 48
        assert stats["running"] is True
    assert report["completed"] == 48 and report["errors"] == 0
    server = report["server"]
    assert server["reconciled"] is True, server["mismatches"]
    assert server["requests_total_delta"]["completed"] == 48
    assert server["requests_total_delta"]["submitted"] == 48
    assert server["latency"]["p50_ms_est"] is not None
    # engine.stop() closed the exposition server with it
    with pytest.raises(Exception):
        _get(srv.url("/healthz"), timeout=2)


def test_request_trace_id_in_event_log_and_chrome_trace(tmp_path):
    """The acceptance wiring: one request's trace id (minted at
    submit) is findable in BOTH the structured event log and the
    Chrome-trace events the profiler dumps."""
    events.configure(str(tmp_path / "serve.jsonl"))
    profiler.set_state("run")
    try:
        eng = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=2)
        with eng:
            fut = eng.submit([1, 2, 3])
            fut.result(timeout=30)
            tid = fut.trace_id
        assert tid and tid.startswith("req")
    finally:
        profiler.set_state("stop")
        log_path = events.get_log().path
        events.configure(None)
    recs = events.read_events(log_path)
    by_event = {r["event"] for r in recs}
    assert {"engine_start", "engine_stop", "compile_begin", "compile_end",
            "batch_dispatch"} <= by_event, by_event
    dispatched = [r for r in recs if r["event"] == "batch_dispatch"]
    assert any(tid in r["trace_ids"] for r in dispatched)
    # and the same id rode the contextvar into the profiler span args
    from mxnet_tpu.profiler import _EVENTS
    spans = [e for e in _EVENTS
             if e.get("name") == "serving/forward" and "args" in e]
    assert any(tid in e["args"].get("trace_id", "") for e in spans), \
        [e.get("args") for e in spans]


def test_shed_and_expiry_events(tmp_path):
    events.configure(str(tmp_path / "shed.jsonl"))
    try:
        eng = ServingEngine(StubModel(), bucket_lens=(8,), max_rows=1)
        with eng:
            with pytest.raises(Exception):
                eng.submit(list(range(9)))       # too long -> shed event
        log_path = events.get_log().path
    finally:
        events.configure(None)
    shed = events.read_events(log_path, event="request_shed")
    assert shed and shed[0]["reason"] == "too_long"
    assert shed[0]["trace_id"].startswith("req")


# ---------------------------------------------------------------------------
# serving stats bridge details
# ---------------------------------------------------------------------------

def test_submit_validation_does_not_skew_submitted_counter():
    """An invalid request raises to the caller BEFORE any counter
    moves, preserving submitted == sum(outcomes) — the invariant the
    loadgen cross-check reconciles."""
    eng = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=1)
    with eng:
        before = eng.stats.count("submitted")
        with pytest.raises(ValueError):
            eng.submit([])                       # empty request
        with pytest.raises(ValueError):
            eng.submit([1, 2], token_types=[0])  # length mismatch
        assert eng.stats.count("submitted") == before


def test_serving_stats_window_public_and_reset_preserves():
    assert ServingStats(128).window == 128
    eng = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=1,
                        stats_window=77)
    assert eng.stats.window == 77
    eng.reset_stats()
    assert eng.stats.window == 77


def test_compile_cache_and_bucket_counters():
    reg_hits = REGISTRY.counter("mxnet_tpu_serving_compile_cache_total",
                                "", ("engine_id", "result"))
    eng = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=1)
    eid = eng.engine_id
    # engine_id labels (ROADMAP per-chip metrics): a FRESH engine's
    # children start at zero — no cross-engine accumulation to diff
    assert reg_hits.labels(engine_id=eid, result="memory_hit").value == 0
    with eng:
        eng.infer([1, 2], timeout=30)
        eng.infer([3, 4], timeout=30)
        eng.infer([5], timeout=30)
    # first visit is a compile (miss, or persistent_hit when the
    # on-disk cache already held it); repeats are memory_hits
    assert (reg_hits.labels(engine_id=eid, result="miss").value
            + reg_hits.labels(engine_id=eid,
                              result="persistent_hit").value) >= 1
    assert reg_hits.labels(engine_id=eid, result="memory_hit").value >= 1
    tokens = REGISTRY.counter("mxnet_tpu_serving_batch_tokens_total",
                              "", ("engine_id", "bucket"))
    assert tokens.labels(engine_id=eid, bucket=16).value > 0


# ---------------------------------------------------------------------------
# kvstore wire: trace id across a real socket + server-side metrics
# ---------------------------------------------------------------------------

def test_param_server_handles_traced_frames_and_logs(tmp_path):
    import socket

    from mxnet_tpu.kvstore import _ParameterServer, _recv_msg, _send_msg

    events.configure(str(tmp_path / "srv.jsonl"))
    try:
        srv = _ParameterServer("127.0.0.1", 0, num_workers=1)
        try:
            port = srv._srv.getsockname()[1]
            s = socket.create_connection(("127.0.0.1", port), timeout=5)
            # legacy 3-tuple still served (no trace field)
            _send_msg(s, ("init", "k", np.full((3,), 2.0, np.float32)))
            assert _recv_msg(s)[0] == "ok"
            # 4-tuple: trace id rides the frame
            _send_msg(s, ("pull", "k", None, "wire-tid-7"))
            status, arr = _recv_msg(s)
            assert status == "ok" and np.allclose(arr, 2.0)
            s.close()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                handled = events.read_events(
                    events.get_log().path, event="kvstore_server_handle")
                if len(handled) >= 2:
                    break
                time.sleep(0.05)
        finally:
            srv._srv.close()
    finally:
        log_path = events.get_log().path
        events.configure(None)
    handled = events.read_events(log_path, event="kvstore_server_handle")
    by_op = {r["op"]: r for r in handled}
    assert by_op["init"]["trace_id"] is None
    assert by_op["pull"]["trace_id"] == "wire-tid-7"
    assert by_op["pull"]["bytes_out"] > 0 and by_op["pull"]["ms"] >= 0
    # server-side registry families saw the traffic
    lat = REGISTRY.get("mxnet_tpu_kvstore_server_rpc_ms")
    assert lat is not None and lat.labels(op="pull").count >= 1


@pytest.mark.timeout(600)
def test_dist_async_trace_id_crosses_processes(tmp_path):
    """Two REAL processes: the same trace id shows up in the pushing
    worker's client event log and the server-side log in worker 0's
    process — the id crossed the wire inside the typed frame. Span
    parenting crosses too (ISSUE 4): worker 1 prints its client RPC
    span id, worker 0 prints the parent of its server handle span,
    and the two must be EQUAL — one span tree over two processes."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = ROOT
    env["MXNET_TPU_EVENT_LOG"] = str(tmp_path)
    port = 9161 + (os.getpid() % 400)
    cmd = [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
           "-n", "2", "--launcher", "local", "--port", str(port),
           sys.executable, os.path.join(ROOT, "tests",
                                        "dist_async_trace_worker.py")]
    res = subprocess.run(cmd, env=env, cwd=ROOT, capture_output=True,
                         text=True, timeout=540)
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-4000:]
    assert "TRACE_WORKER_0_OK" in out and "TRACE_WORKER_1_OK" in out, \
        out[-4000:]
    logs = sorted(tmp_path.glob("events-*.jsonl"))
    assert len(logs) == 2, logs
    rpc, handled = [], []
    for p in logs:
        rpc += events.read_events(str(p), event="kvstore_rpc")
        handled += events.read_events(str(p), event="kvstore_server_handle")
    pushes_sent = [r for r in rpc if r["op"] == "push"
                   and r["trace_id"] == "trace-golden-push"]
    pushes_served = [r for r in handled if r["op"] == "push"
                     and r["trace_id"] == "trace-golden-push"]
    assert pushes_sent, "client-side push event lost"
    assert pushes_served, "server-side push event lost"
    # the two records came from DIFFERENT processes
    assert pushes_sent[0]["pid"] != pushes_served[0]["pid"]
    # and byte accounting matches across the wire for that frame
    assert pushes_sent[0]["bytes_out"] == pushes_served[0]["bytes_in"]
    # span parenting crossed the wire: client rpc span id == server
    # handle span's parent (each printed from its own process's ring)
    rpc_id = handle_parent = None
    for line in out.splitlines():
        if line.startswith("SPAN_RPC="):
            rpc_id = line.split("=", 1)[1].strip()
        if line.startswith("SPAN_HANDLE_PARENT="):
            handle_parent = line.split("=", 1)[1].strip()
    assert rpc_id and handle_parent, out[-4000:]
    assert rpc_id == handle_parent
    # the span ids also landed in the structured event logs
    assert pushes_sent[0].get("span_id") == rpc_id
    assert pushes_served[0].get("parent_span_id") == rpc_id


# ---------------------------------------------------------------------------
# telemetry_dump tool
# ---------------------------------------------------------------------------

def test_telemetry_dump_renders_sources(tmp_path, capsys):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    import telemetry_dump

    reg = MetricsRegistry()
    reg.counter("d_total", "x").inc(4)
    h = reg.histogram("d_ms", "x", buckets=(1.0, 10.0))
    h.observe(0.5)
    h.observe(5.0)
    telemetry_dump.dump_metrics(reg.render_prometheus())
    out = capsys.readouterr().out
    assert "d_total" in out and "d_ms" in out and "4" in out

    path = str(tmp_path / "ev.jsonl")
    log = events.EventLog(path)
    with trace_context("tid-dump"):
        log.emit("request_shed", reason="queue_full")
    log.close()
    telemetry_dump.dump_events(path)
    out = capsys.readouterr().out
    assert "request_shed" in out and "tid-dump" in out


# ---------------------------------------------------------------------------
# the disabled-path cost guard (acceptance criterion)
# ---------------------------------------------------------------------------

def test_disabled_paths_stay_cheap():
    """With no exporter attached and no event log configured the
    instrumented hot paths cost microseconds: stats.bump (serving
    dispatch) and events.emit (everywhere) must stay far below any
    measurable effect on a model step. Budgets are ~50x the observed
    cost so the guard catches regressions (an accidental flush, a
    render on the hot path), not scheduler noise."""
    assert events.get_log() is None     # precondition: nothing attached
    stats = ServingStats(256)
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        stats.bump("submitted")
    per_bump = (time.perf_counter() - t0) / n
    t0 = time.perf_counter()
    for _ in range(n):
        stats.total_ms.observe(1.0)
    per_obs = (time.perf_counter() - t0) / n
    t0 = time.perf_counter()
    for _ in range(n):
        events.emit("noop")
    per_emit = (time.perf_counter() - t0) / n
    assert per_bump < 100e-6, f"bump {per_bump * 1e6:.1f}us"
    assert per_obs < 100e-6, f"observe {per_obs * 1e6:.1f}us"
    assert per_emit < 20e-6, f"emit {per_emit * 1e6:.1f}us"
