"""Legacy symbolic mx.rnn cell API (reference python/mxnet/rnn/rnn_cell.py
+ tests/python/unittest/test_rnn.py): unroll shapes, numpy-golden LSTM
numerics, stacked cells, and BucketingModule integration."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def test_rnn_cell_unroll_shapes():
    cell = mx.rnn.RNNCell(num_hidden=8, prefix="r_")
    data = mx.sym.Variable("data")  # (N, T, C)
    outputs, states = cell.unroll(3, data, layout="NTC", merge_outputs=True)
    _, out_shapes, _ = outputs.infer_shape(data=(2, 3, 5))
    assert out_shapes[0] == (2, 3, 8)
    assert len(states) == 1


def test_lstm_cell_numpy_golden():
    """Unrolled LSTMCell forward == numpy LSTM with the i,f,c,o gate
    order, weights injected through the executor arg dict."""
    H, I, N, T = 4, 3, 2, 3
    rng = np.random.RandomState(0)
    wx = rng.randn(4 * H, I).astype(np.float32) * 0.4
    wh = rng.randn(4 * H, H).astype(np.float32) * 0.4
    bx = rng.randn(4 * H).astype(np.float32) * 0.1
    bh = rng.randn(4 * H).astype(np.float32) * 0.1
    x = rng.randn(N, T, I).astype(np.float32)

    cell = mx.rnn.LSTMCell(num_hidden=H, prefix="l_", forget_bias=0.0)
    data = mx.sym.Variable("data")
    outputs, _ = cell.unroll(T, data, layout="NTC", merge_outputs=True)
    h0 = np.zeros((N, H), np.float32)
    c0 = np.zeros((N, H), np.float32)
    args = {"data": mx.nd.array(x),
            "l_i2h_weight": mx.nd.array(wx), "l_i2h_bias": mx.nd.array(bx),
            "l_h2h_weight": mx.nd.array(wh), "l_h2h_bias": mx.nd.array(bh)}
    exe = outputs.bind(mx.current_context(), args)
    got = exe.forward()[0].asnumpy()

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    h, c = h0, c0
    want = []
    for t in range(T):
        g = x[:, t] @ wx.T + bx + h @ wh.T + bh
        i, f, n, o = np.split(g, 4, axis=1)
        c = sig(f) * c + sig(i) * np.tanh(n)
        h = sig(o) * np.tanh(c)
        want.append(h)
    want = np.stack(want, axis=1)
    assert_almost_equal(got, want, rtol=1e-4, atol=1e-5)


def test_stacked_cells_and_dropout():
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.LSTMCell(num_hidden=6, prefix="l0_"))
    stack.add(mx.rnn.DropoutCell(0.0))
    stack.add(mx.rnn.GRUCell(num_hidden=5, prefix="g0_"))
    data = mx.sym.Variable("data")
    outputs, states = stack.unroll(4, data, merge_outputs=True)
    _, out_shapes, _ = outputs.infer_shape(data=(3, 4, 7))
    assert out_shapes[0] == (3, 4, 5)
    assert len(states) == 3  # lstm h,c + gru h


def test_rnn_cells_with_bucketing_module():
    """The upstream pairing: mx.rnn cells + BucketingModule train a tiny
    variable-length sequence classifier (reference example/rnn/bucketing)."""
    rng = np.random.RandomState(2)
    buckets = [5, 3]  # default bucket (5) binds first

    def gen_sym(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        cell = mx.rnn.LSTMCell(num_hidden=8, prefix="lstm_")
        outputs, _ = cell.unroll(seq_len, data, merge_outputs=False)
        fc = mx.sym.FullyConnected(outputs[-1], num_hidden=2, name="fc")
        return mx.sym.SoftmaxOutput(fc, label, name="softmax"), \
            ["data"], ["softmax_label"]

    mod = mx.module.BucketingModule(gen_sym, default_bucket_key=5)
    # two batches per bucket: class = sign of the sequence mean
    for epoch in range(30):
        for blen in buckets:
            x = rng.randn(8, blen, 4).astype(np.float32) + \
                (rng.randint(0, 2, (8, 1, 1)) * 2 - 1) * 0.8
            y = (x.mean(axis=(1, 2)) > 0).astype(np.float32)
            batch = mx.io.DataBatch(data=[mx.nd.array(x)],
                                    label=[mx.nd.array(y)],
                                    bucket_key=blen,
                                    provide_data=[("data", (8, blen, 4))],
                                    provide_label=[("softmax_label", (8,))])
            if not mod.binded:
                mod.bind(data_shapes=batch.provide_data,
                         label_shapes=batch.provide_label)
                mod.init_params(mx.initializer.Xavier())
                mod.init_optimizer(optimizer="adam",
                                   optimizer_params={"learning_rate": 5e-3})
            mod.forward_backward(batch)
            mod.update()
    # the trained model must beat chance comfortably on fresh data
    correct = total = 0
    for blen in buckets:
        for _ in range(4):
            x = rng.randn(8, blen, 4).astype(np.float32) + \
                (rng.randint(0, 2, (8, 1, 1)) * 2 - 1) * 0.8
            y = (x.mean(axis=(1, 2)) > 0).astype(np.float32)
            batch = mx.io.DataBatch(data=[mx.nd.array(x)],
                                    label=[mx.nd.array(y)],
                                    bucket_key=blen,
                                    provide_data=[("data", (8, blen, 4))],
                                    provide_label=[("softmax_label", (8,))])
            mod.forward(batch, is_train=False)
            pred = mod.get_outputs()[0].asnumpy().argmax(axis=1)
            correct += (pred == y).sum()
            total += len(y)
    assert correct / total > 0.8, correct / total


def test_lstm_forget_bias_baked_into_init():
    """forget_bias lands in h2h_bias at INIT (reference init.LSTMBias),
    not as a runtime add — checkpoint parity with the reference."""
    H = 4
    cell = mx.rnn.LSTMCell(num_hidden=H, prefix="fb_", forget_bias=1.0)
    data = mx.sym.Variable("data")
    outputs, _ = cell.unroll(2, data, merge_outputs=True)
    mod = mx.module.Module(outputs, data_names=["data"], label_names=[])
    mod.bind(data_shapes=[("data", (2, 2, 3))], for_training=False)
    mod.init_params(mx.initializer.Zero())
    args, _ = mod.get_params()
    b = args["fb_h2h_bias"].asnumpy()
    assert np.allclose(b[H:2 * H], 1.0)       # forget gate slice
    assert np.allclose(b[:H], 0.0) and np.allclose(b[2 * H:], 0.0)


def test_residual_and_bidirectional_cells():
    """ResidualCell adds input to output; BidirectionalCell concats a
    forward and a reversed pass (reference rnn_cell.py ModifierCell
    family)."""
    # residual: base RNNCell output + input (needs matching dims)
    cell = mx.rnn.ResidualCell(mx.rnn.RNNCell(num_hidden=5, prefix="res_"))
    data = mx.sym.Variable("data")
    outputs, _ = cell.unroll(3, data, merge_outputs=True)
    _, out_shapes, _ = outputs.infer_shape(data=(2, 3, 5))
    assert out_shapes[0] == (2, 3, 5)

    # the residual path really adds the input: zero weights -> tanh(0)=0
    # -> output == input
    args = {"data": mx.nd.array(np.ones((2, 3, 5), np.float32) * 0.3)}
    for n in ("res_i2h_weight", "res_h2h_weight"):
        args[n] = mx.nd.zeros((5, 5))
    for n in ("res_i2h_bias", "res_h2h_bias"):
        args[n] = mx.nd.zeros((5,))
    exe = outputs.bind(mx.current_context(), args)
    assert_almost_equal(exe.forward()[0].asnumpy(),
                        np.ones((2, 3, 5), np.float32) * 0.3)

    # bidirectional: output width = l + r hidden, states from both
    bi = mx.rnn.BidirectionalCell(mx.rnn.GRUCell(4, prefix="f_"),
                                  mx.rnn.GRUCell(6, prefix="b_"))
    outputs, states = bi.unroll(3, mx.sym.Variable("data"),
                                merge_outputs=True)
    _, out_shapes, _ = outputs.infer_shape(data=(2, 3, 7))
    assert out_shapes[0] == (2, 3, 10)
    assert len(states) == 2
    import pytest
    with pytest.raises(mx.base.MXNetError):
        bi(mx.sym.Variable("x"), [])


def test_residual_wraps_bidirectional():
    """ResidualCell.unroll delegates to base_cell.unroll, so it composes
    with unroll-only cells (reference ResidualCell.unroll contract)."""
    bi = mx.rnn.BidirectionalCell(mx.rnn.RNNCell(3, prefix="f2_"),
                                  mx.rnn.RNNCell(3, prefix="b2_"))
    res = mx.rnn.ResidualCell(bi)
    outputs, _ = res.unroll(2, mx.sym.Variable("data"), merge_outputs=True)
    _, out_shapes, _ = outputs.infer_shape(data=(2, 2, 6))
    assert out_shapes[0] == (2, 2, 6)  # 3+3 concat + residual add
    import pytest
    with pytest.raises(mx.base.MXNetError):
        res.unroll(2, mx.sym.Variable("data"), layout="NC")
