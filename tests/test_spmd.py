"""SPMD train-step builders (parallel/spmd.py — the compiled
Trainer→KVStore→NCCL replacement, SURVEY §3.2) on the virtual 8-device
mesh, including the chained micro-batch mode."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from mxnet_tpu.parallel import (build_mesh, make_data_parallel_step,
                                make_sharded_train_step)
from mxnet_tpu.test_utils import device_tols

RTOL, ATOL = device_tols("float32")
N = 8


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < N:
        pytest.skip(f"needs {N} devices")
    return build_mesh({"dp": N})


def _problem():
    rs = np.random.RandomState(0)
    params = {"w": jnp.asarray(rs.randn(6, 4), jnp.float32),
              "b": jnp.zeros((4,), jnp.float32)}
    opt = jax.tree_util.tree_map(jnp.zeros_like, params)

    def loss_fn(p, batch):
        x, y = batch
        pred = x @ p["w"] + p["b"]
        return ((pred - y) ** 2).mean()

    def sgd(p, g, o):
        o = jax.tree_util.tree_map(lambda m, gg: 0.9 * m + gg, o, g)
        p = jax.tree_util.tree_map(lambda pp, m: pp - 0.05 * m, p, o)
        return p, o

    return params, opt, loss_fn, sgd, rs


def test_data_parallel_step_runs(mesh):
    params, opt, loss_fn, sgd, rs = _problem()
    step = make_data_parallel_step(loss_fn, sgd, mesh, donate=False)
    x = jnp.asarray(rs.rand(16, 6), jnp.float32)
    y = jnp.asarray(rs.rand(16, 4), jnp.float32)
    p, o, loss = step(params, opt, (x, y))
    assert np.isfinite(float(loss))
    assert p["w"].shape == (6, 4)


def test_chained_step_matches_sequential(mesh):
    """chain=k over stacked micro-batches == k sequential dispatches on
    the same micro-batches (REAL steps, distinct data per sub-step)."""
    params, opt, loss_fn, sgd, rs = _problem()
    k = 5
    xs = jnp.asarray(rs.rand(k, 16, 6), jnp.float32)
    ys = jnp.asarray(rs.rand(k, 16, 4), jnp.float32)

    seq = make_data_parallel_step(loss_fn, sgd, mesh, donate=False)
    p1, o1 = params, opt
    seq_losses = []
    for i in range(k):
        p1, o1, l = seq(p1, o1, (xs[i], ys[i]))
        seq_losses.append(float(l))

    chained = make_data_parallel_step(loss_fn, sgd, mesh, donate=False,
                                      chain=k)
    p2, o2, losses = chained(params, opt, (xs, ys))
    np.testing.assert_allclose(np.asarray(losses), seq_losses,
                               rtol=RTOL, atol=ATOL)
    for key in params:
        np.testing.assert_allclose(np.asarray(p1[key]), np.asarray(p2[key]),
                                   rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(np.asarray(o1[key]), np.asarray(o2[key]),
                                   rtol=RTOL, atol=ATOL)


def test_sharded_train_step_chain_and_tp(mesh):
    """make_sharded_train_step with a tp-style param rule AND chain>1:
    compiles, runs, and the batch spec shifts past the scan axis."""
    mesh2 = build_mesh({"dp": N // 2, "tp": 2})
    params, opt, loss_fn, sgd, rs = _problem()
    k = 3

    def pspec(path, aval):
        return P(None, "tp") if "w" in path and aval.ndim == 2 else P()

    builder = make_sharded_train_step(loss_fn, sgd, mesh2,
                                      param_spec_fn=pspec,
                                      batch_spec=P("dp"), donate=False,
                                      chain=k)
    xs = jnp.asarray(rs.rand(k, 8, 6), jnp.float32)
    ys = jnp.asarray(rs.rand(k, 8, 4), jnp.float32)
    step = builder(params, opt, (xs, ys))
    p, o, losses = step(params, opt, (xs, ys))
    assert losses.shape == (k,)
    assert np.isfinite(np.asarray(losses)).all()
