"""SPMD train-step builders (parallel/spmd.py — the compiled
Trainer→KVStore→NCCL replacement, SURVEY §3.2) on the virtual 8-device
mesh, including the chained micro-batch mode."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from mxnet_tpu.parallel import (build_mesh, make_data_parallel_step,
                                make_sharded_train_step)
from mxnet_tpu.test_utils import device_tols

RTOL, ATOL = device_tols("float32")
N = 8


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < N:
        pytest.skip(f"needs {N} devices")
    return build_mesh({"dp": N})


def _problem():
    rs = np.random.RandomState(0)
    params = {"w": jnp.asarray(rs.randn(6, 4), jnp.float32),
              "b": jnp.zeros((4,), jnp.float32)}
    opt = jax.tree_util.tree_map(jnp.zeros_like, params)

    def loss_fn(p, batch):
        x, y = batch
        pred = x @ p["w"] + p["b"]
        return ((pred - y) ** 2).mean()

    def sgd(p, g, o):
        o = jax.tree_util.tree_map(lambda m, gg: 0.9 * m + gg, o, g)
        p = jax.tree_util.tree_map(lambda pp, m: pp - 0.05 * m, p, o)
        return p, o

    return params, opt, loss_fn, sgd, rs


def test_data_parallel_step_runs(mesh):
    params, opt, loss_fn, sgd, rs = _problem()
    step = make_data_parallel_step(loss_fn, sgd, mesh, donate=False)
    x = jnp.asarray(rs.rand(16, 6), jnp.float32)
    y = jnp.asarray(rs.rand(16, 4), jnp.float32)
    p, o, loss = step(params, opt, (x, y))
    assert np.isfinite(float(loss))
    assert p["w"].shape == (6, 4)


def test_chained_step_matches_sequential(mesh):
    """chain=k over stacked micro-batches == k sequential dispatches on
    the same micro-batches (REAL steps, distinct data per sub-step)."""
    params, opt, loss_fn, sgd, rs = _problem()
    k = 5
    xs = jnp.asarray(rs.rand(k, 16, 6), jnp.float32)
    ys = jnp.asarray(rs.rand(k, 16, 4), jnp.float32)

    seq = make_data_parallel_step(loss_fn, sgd, mesh, donate=False)
    p1, o1 = params, opt
    seq_losses = []
    for i in range(k):
        p1, o1, l = seq(p1, o1, (xs[i], ys[i]))
        seq_losses.append(float(l))

    chained = make_data_parallel_step(loss_fn, sgd, mesh, donate=False,
                                      chain=k)
    p2, o2, losses = chained(params, opt, (xs, ys))
    np.testing.assert_allclose(np.asarray(losses), seq_losses,
                               rtol=RTOL, atol=ATOL)
    for key in params:
        np.testing.assert_allclose(np.asarray(p1[key]), np.asarray(p2[key]),
                                   rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(np.asarray(o1[key]), np.asarray(o2[key]),
                                   rtol=RTOL, atol=ATOL)


def test_sharded_train_step_chain_and_tp(mesh):
    """make_sharded_train_step with a tp-style param rule AND chain>1:
    compiles, runs, and the batch spec shifts past the scan axis."""
    mesh2 = build_mesh({"dp": N // 2, "tp": 2})
    params, opt, loss_fn, sgd, rs = _problem()
    k = 3

    def pspec(path, aval):
        return P(None, "tp") if "w" in path and aval.ndim == 2 else P()

    builder = make_sharded_train_step(loss_fn, sgd, mesh2,
                                      param_spec_fn=pspec,
                                      batch_spec=P("dp"), donate=False,
                                      chain=k)
    xs = jnp.asarray(rs.rand(k, 8, 6), jnp.float32)
    ys = jnp.asarray(rs.rand(k, 8, 4), jnp.float32)
    step = builder(params, opt, (xs, ys))
    p, o, losses = step(params, opt, (xs, ys))
    assert losses.shape == (k,)
    assert np.isfinite(np.asarray(losses)).all()


def test_zero1_matches_replicated_and_shards_state(mesh):
    """ZeRO-1 (optimizer state sharded over dp) is numerically identical
    to the replicated step, and the returned moments really live
    sharded on the mesh (1/N per device)."""
    from mxnet_tpu.parallel import make_zero1_train_step
    from mxnet_tpu.parallel.spmd import zero1_spec

    # adam-ish update with two moment trees — the ZeRO-1 payoff case
    def make_opt(params):
        return {"m": jax.tree_util.tree_map(jnp.zeros_like, params),
                "v": jax.tree_util.tree_map(jnp.zeros_like, params)}

    def adam(p, g, o):
        m = jax.tree_util.tree_map(lambda m_, g_: 0.9 * m_ + 0.1 * g_,
                                   o["m"], g)
        v = jax.tree_util.tree_map(lambda v_, g_: 0.99 * v_ + 0.01 * g_ * g_,
                                   o["v"], g)
        new_p = jax.tree_util.tree_map(
            lambda p_, m_, v_: p_ - 0.05 * m_ / (jnp.sqrt(v_) + 1e-8),
            p, m, v)
        return new_p, {"m": m, "v": v}

    rs = np.random.RandomState(1)
    params = {"w": jnp.asarray(rs.randn(16, 4), jnp.float32),
              "b": jnp.zeros((4,), jnp.float32)}
    opt = make_opt(params)

    def loss_fn(p, batch):
        x, y = batch
        return (((x @ p["w"] + p["b"]) - y) ** 2).mean()

    x = jnp.asarray(rs.rand(16, 16), jnp.float32)
    y = jnp.asarray(rs.rand(16, 4), jnp.float32)

    repl = make_data_parallel_step(loss_fn, adam, mesh, donate=False)
    p_r, o_r, l_r = repl(params, opt, (x, y))

    z1 = make_zero1_train_step(loss_fn, adam, mesh, donate=False)
    step = z1(params, opt, (x, y))
    p_z, o_z, l_z = step(params, opt, (x, y))

    np.testing.assert_allclose(float(l_r), float(l_z), rtol=RTOL, atol=ATOL)
    for key in params:
        np.testing.assert_allclose(np.asarray(p_r[key]), np.asarray(p_z[key]),
                                   rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(np.asarray(o_r["m"][key]),
                                   np.asarray(o_z["m"][key]),
                                   rtol=RTOL, atol=ATOL)

    # the w moments are truly sharded: 1/N rows per device
    shard = o_z["m"]["w"].addressable_shards[0]
    assert shard.data.shape == (16 // N, 4)
    # bias (4,) is too small to split over 8 — stays replicated by rule
    assert o_z["m"]["b"].addressable_shards[0].data.shape == (4,)
    # and the spec helper says exactly that
    sp = zero1_spec(mesh, "dp")
    assert sp("m/w", o_z["m"]["w"]) == P("dp")
    assert sp("m/b", o_z["m"]["b"]) == P()


def test_zero1_chained(mesh):
    """ZeRO-1 composes with the chained micro-batch mode."""
    from mxnet_tpu.parallel import make_zero1_train_step
    params, opt, loss_fn, sgd, rs = _problem()
    k = 3
    xs = jnp.asarray(rs.rand(k, 16, 6), jnp.float32)
    ys = jnp.asarray(rs.rand(k, 16, 4), jnp.float32)
    z1 = make_zero1_train_step(loss_fn, sgd, mesh, donate=False, chain=k)
    step = z1(params, opt, (xs, ys))
    p, o, losses = step(params, opt, (xs, ys))
    assert losses.shape == (k,) and np.isfinite(np.asarray(losses)).all()
