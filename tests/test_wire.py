"""Binary dispatch wire (mxnet_tpu/serving/wire.py): the persistent
multiplexed router↔engine transport.

Mirrors ``test_kvstore_wire.py`` for the SERVING dispatch port — the
codec is shared, so this file owns what's new: hostile frames against
a live dispatch listener (truncated frames, length bombs, unknown
frame types, garbage correlation ids must error the frame or the
connection, never the process), the end-to-end 2-remote-engine parity
golden with ZERO threads spawned per request on the wire path,
kill-the-connection-mid-request failover (requeue loses nothing), the
JSON-only-engine fallback regression, and the bounded HTTP waiter
pool that replaced the legacy thread-per-in-flight-request shape.
"""
import socket
import struct
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import nd
from mxnet_tpu.serving import ServingEngine, ServingRouter
from mxnet_tpu.serving import wire as wiremod
from mxnet_tpu.serving.router import _FallbackPool
from mxnet_tpu.serving.wire import (FrameTooLargeError, WireClient,
                                    WireError, recv_frame, send_frame,
                                    wire_decode, wire_encode)


def model(ids, token_types, valid_length, segment_ids, positions):
    """out[b, s, 0] == ids[b, s]: responses bit-match their request."""
    return nd.array(ids.asnumpy().astype(np.float32)[..., None])


class SlowModel:
    def __init__(self, delay):
        self.delay = delay
        self.started = threading.Event()

    def __call__(self, ids, token_types, valid_length, segment_ids,
                 positions):
        self.started.set()
        time.sleep(self.delay)
        return nd.array(ids.asnumpy().astype(np.float32)[..., None])


def _engine(engine_id, m=model, **kw):
    kw.setdefault("bucket_lens", (32,))
    kw.setdefault("max_rows", 2)
    return ServingEngine(m, engine_id=engine_id, **kw)


def _wait_transport(router, transport, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        board = router.scoreboard()
        if board and all(r.get("transport") == transport
                         for r in board.values()):
            return board
        time.sleep(0.05)
    raise AssertionError(
        f"fleet never reached transport={transport}: "
        f"{router.scoreboard()}")


# ---------------------------------------------------------------------------
# codec: shared with kvstore (one wire encoding in the repo)
# ---------------------------------------------------------------------------

def test_codec_is_the_kvstore_codec():
    import mxnet_tpu.kvstore as kvmod
    msg = ("SUBMIT", 7, {"tokens": np.arange(9, dtype=np.int32),
                         "trace_id": "req-x", "deadline_ms": None})
    raw = wire_encode(msg)
    assert kvmod._wire_encode(msg) == raw
    got = wire_decode(raw)
    assert got[0] == "SUBMIT" and got[1] == 7
    assert got[2]["tokens"].dtype == np.int32
    assert np.array_equal(got[2]["tokens"], np.arange(9))


def test_frame_cap_refused_before_allocation():
    a, b = socket.socketpair()
    try:
        with pytest.raises(FrameTooLargeError):
            send_frame(a, b"x" * 2048, max_frame=1024)
        # a hostile LENGTH PREFIX is refused off the header alone
        a.sendall(struct.pack("<Q", 1 << 40))
        with pytest.raises(FrameTooLargeError) as ei:
            recv_frame(b, max_frame=1024)
        # both historical refusal taxonomies hold
        from mxnet_tpu.base import MXNetError
        assert isinstance(ei.value, (MXNetError,))
        assert isinstance(ei.value, ValueError)
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# hostile frames against a live dispatch listener
# ---------------------------------------------------------------------------

def test_dispatch_port_refuses_hostile_frames():
    """Undecodable/oversized frames drop THE CONNECTION; unknown frame
    types and garbage correlation ids error THE FRAME; the engine
    process survives all of it and keeps serving."""
    eng = _engine("hostile")
    with eng:
        srv = eng.expose(port=0)
        port = eng._wire.port
        addr = ("127.0.0.1", port)

        # (a) raw garbage after the length prefix: connection dropped
        s = socket.create_connection(addr, timeout=5.0)
        s.sendall(struct.pack("<Q", 5) + b"zjunk")
        assert s.recv(1) == b""          # peer closed, no reply
        s.close()

        # (b) length bomb: refused off the 8-byte header, dropped
        s = socket.create_connection(addr, timeout=5.0)
        s.sendall(struct.pack("<Q", 1 << 62))
        assert s.recv(1) == b""
        s.close()

        # (c) truncated frame then close: never kills the process
        s = socket.create_connection(addr, timeout=5.0)
        payload = wire_encode(("SUBMIT", 1, {"tokens": np.arange(4)}))
        s.sendall(struct.pack("<Q", len(payload)) + payload[:7])
        s.close()

        # (d) well-formed frame of an UNKNOWN type: ERROR frame back,
        # connection stays up for the next frame
        s = socket.create_connection(addr, timeout=5.0)
        send_frame(s, ("DECODE", 3, {"x": 1}))
        frame, _ = recv_frame(s)
        assert frame[0] == "ERROR" and frame[1] == 3
        assert "unknown frame type" in frame[2]["error"]

        # (e) garbage correlation id on a SUBMIT: frame errored (the
        # reply can't be matched, so corr rides back as None)
        send_frame(s, ("SUBMIT", "not-a-corr-id",
                       {"tokens": np.arange(4, dtype=np.int32)}))
        frame, _ = recv_frame(s)
        assert frame[0] == "ERROR" and frame[1] is None
        assert "correlation id" in frame[2]["error"]

        # (f) SUBMIT payload of the wrong shape: errored, not fatal
        send_frame(s, ("SUBMIT", 9, "tokens"))
        frame, _ = recv_frame(s)
        assert frame[0] == "ERROR" and frame[1] == 9
        s.close()

        # the engine survived everything above: a REAL wire round trip
        # and the in-process path both still serve
        client = WireClient("127.0.0.1", port, client_id="t",
                            expect_engine_id="hostile", conns=1)
        try:
            assert client.ensure() == 1
            assert client.ping(timeout_s=5.0)
            got = {}
            evt = threading.Event()

            def on_done(exc, body):
                got["exc"], got["body"] = exc, body
                evt.set()

            toks = np.arange(1, 11, dtype=np.int32)
            client.dispatch({"tokens": toks}, on_done, timeout_s=30.0)
            assert evt.wait(30.0)
            assert got["exc"] is None, got
            assert np.array_equal(
                np.asarray(got["body"]["result"]).ravel()[:10],
                toks.astype(np.float32))
        finally:
            client.close()
        out = eng.submit(np.arange(1, 5, dtype=np.int32)).result(30.0)
        assert np.array_equal(np.asarray(out).ravel()[:4],
                              np.arange(1, 5, dtype=np.float32))
        srv  # keepalive


def test_wire_client_refuses_wrong_engine_and_non_wire_port():
    """The handshake rejects a port answering as a DIFFERENT engine
    (stale/recycled port) and a port speaking another protocol."""
    eng = _engine("who")
    with eng:
        eng.expose(port=0)
        c = WireClient("127.0.0.1", eng._wire.port, client_id="t",
                       expect_engine_id="somebody-else", conns=1)
        assert c.ensure() == 0
        assert not c.has_live()
        c.close()
        # the HTTP exposition port does not speak the wire protocol
        c2 = WireClient("127.0.0.1", eng._expo.port, client_id="t",
                        conns=1, timeout_s=2.0)
        assert c2.ensure() == 0
        c2.close()


# ---------------------------------------------------------------------------
# end-to-end: router over 2 remote engines on the binary wire
# ---------------------------------------------------------------------------

def test_router_wire_parity_zero_threads_per_request(monkeypatch):
    """The acceptance golden: 2 remote engines behind a wire router —
    results bit-match the request tokens under 8 concurrent clients,
    both engines serve, and the steady-state thread set does NOT grow
    with in-flight requests (the wire path spawns per CONNECTION, the
    legacy path spawned per REQUEST). The canary prober is pinned off:
    its own per-seat wire connections come up asynchronously (once the
    health poll advertises the port) and would shift the steady-state
    thread snapshot this test pins."""
    monkeypatch.setenv("MXNET_TPU_CANARY", "0")
    with _engine("w0") as e0, _engine("w1") as e1:
        u0, u1 = e0.expose(port=0), e1.expose(port=0)
        router = ServingRouter(poll_interval_s=0.1)
        router.add_engine("w0", f"http://127.0.0.1:{u0.port}")
        router.add_engine("w1", f"http://127.0.0.1:{u1.port}")
        with router:
            _wait_transport(router, "wire")
            # prime: one request through, then snapshot the wire/pool
            # thread population
            router.submit(np.arange(1, 5, dtype=np.int32)).result(30.0)

            def dispatch_threads():
                return sorted(
                    t.name for t in threading.enumerate()
                    if t.name.startswith(("mxnet_tpu_wire_",
                                          "mxnet_tpu_router_http_",
                                          "mxnet_tpu_router_rpc_")))

            before = dispatch_threads()
            assert not [n for n in before
                        if n.startswith("mxnet_tpu_router_")], before

            results = {}
            errors = []

            def client(cid):
                rs = np.random.RandomState(cid)
                for k in range(6):
                    toks = rs.randint(
                        1, 1000, rs.randint(4, 30)).astype(np.int32)
                    try:
                        out = router.submit(toks).result(timeout=60.0)
                    except Exception as e:       # pragma: no cover
                        errors.append(repr(e))
                        return
                    results[(cid, k)] = (
                        toks, np.asarray(out).ravel()[:toks.size])

            threads = [threading.Thread(target=client, args=(c,),
                                        name=f"t_wire_client_{c}",
                                        daemon=True) for c in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120.0)
            assert not errors, errors
            assert len(results) == 48
            for toks, out in results.values():
                assert np.array_equal(out, toks.astype(np.float32))
            # zero threads per request: the dispatch-thread population
            # is exactly what it was before the 48-request burst
            assert dispatch_threads() == before
            # both seats actually served over the wire
            board = router.scoreboard()
            assert all(r["transport"] == "wire" for r in board.values())
            assert all(r["dispatched"] > 0 for r in board.values())
            snap = router.snapshot()
            assert snap["counters"]["completed"] >= 48
            over = snap["dispatch_overhead"]
            assert over.get("wire", {}).get("count", 0) >= 48
            assert "json" not in over or over["json"]["count"] == 0


def test_kill_connection_mid_request_loses_nothing():
    """Severing every wire connection to one engine mid-request fails
    its in-flight dispatches with WireError → the router requeues them
    to the sibling: every submitted request completes."""
    slow = SlowModel(0.4)
    with _engine("k0", m=slow, max_rows=1) as e0, \
            _engine("k1", max_rows=2) as e1:
        u0, u1 = e0.expose(port=0), e1.expose(port=0)
        router = ServingRouter(poll_interval_s=0.1)
        router.add_engine("k0", f"http://127.0.0.1:{u0.port}")
        router.add_engine("k1", f"http://127.0.0.1:{u1.port}")
        with router:
            _wait_transport(router, "wire")
            futs = [router.submit(np.arange(1, 9, dtype=np.int32))
                    for _ in range(6)]
            assert slow.started.wait(10.0)   # k0 is mid-forward
            seat = router._seats["k0"]
            wire = seat._wire
            assert wire is not None and wire.has_live()
            for conn in list(wire._slots):   # kill the CONNECTIONS,
                if conn is not None:         # not the engine
                    conn.sock.shutdown(socket.SHUT_RDWR)
            outs = [np.asarray(f.result(timeout=120.0)) for f in futs]
            for out in outs:
                assert np.array_equal(out.ravel()[:8],
                                      np.arange(1, 9, dtype=np.float32))
            # the kill was observed as failover, not silent loss
            assert router.count("requeued") >= 1
            assert router.count("completed") == 6


# ---------------------------------------------------------------------------
# fallback: JSON-only engines keep working behind a wire router
# ---------------------------------------------------------------------------

def test_json_only_engine_behind_wire_router(monkeypatch):
    """An old engine with no wire listener (MXNET_TPU_WIRE=0 at
    expose) behind a wire-capable router: dispatch falls back to the
    HTTP/JSON long-poll, counted on the fallback counter."""
    from mxnet_tpu.serving.metrics import wire_fallback_counter

    monkeypatch.setenv("MXNET_TPU_WIRE", "0")
    with _engine("legacy") as eng:
        srv = eng.expose(port=0)
        assert eng._wire is None     # no listener started
        monkeypatch.setenv("MXNET_TPU_WIRE", "1")
        router = ServingRouter(poll_interval_s=0.1)
        router.add_engine("legacy", f"http://127.0.0.1:{srv.port}")
        fall = wire_fallback_counter().labels(engine_id="legacy")
        f0 = fall.value
        with router:
            time.sleep(0.3)          # a couple of polls: no wire port
            toks = np.arange(1, 13, dtype=np.int32)
            out = np.asarray(router.submit(toks).result(timeout=60.0))
            assert np.array_equal(out.ravel()[:12],
                                  toks.astype(np.float32))
            assert router.scoreboard()["legacy"]["transport"] == "json"
            assert router.scoreboard()["legacy"]["wire_port"] is None
            assert fall.value == f0 + 1
            # the JSON leg feeds the same overhead axis
            over = router.snapshot()["dispatch_overhead"]
            assert over.get("json", {}).get("count", 0) >= 1


def test_wire_disabled_router_stays_on_json(monkeypatch):
    """ServingRouter(wire=False) never upgrades even when the engine
    advertises a wire port (the bench A/B pin)."""
    with _engine("pin") as eng:
        srv = eng.expose(port=0)
        assert eng._wire is not None
        router = ServingRouter(wire=False, poll_interval_s=0.1)
        router.add_engine("pin", f"http://127.0.0.1:{srv.port}")
        with router:
            time.sleep(0.3)
            out = np.asarray(router.submit(
                np.arange(1, 5, dtype=np.int32)).result(timeout=60.0))
            assert out.ravel()[0] == 1.0
            assert router.scoreboard()["pin"]["transport"] == "json"
            assert router._seats["pin"]._wire is None


# ---------------------------------------------------------------------------
# bounded HTTP fallback pool (the legacy thread-bomb fix)
# ---------------------------------------------------------------------------

def test_fallback_pool_bounds_waiter_threads(monkeypatch):
    """8 concurrent HTTP dispatches against a slow engine run on at
    most MXNET_TPU_WIRE_HTTP_POOL waiter threads — the legacy shape
    spawned 8. Canary pinned off: its probe would trip the model's
    started-event before the 8 dispatches are even queued."""
    monkeypatch.setenv("MXNET_TPU_CANARY", "0")
    monkeypatch.setenv("MXNET_TPU_WIRE_HTTP_POOL", "2")
    slow = SlowModel(0.2)
    with _engine("pool", m=slow, max_rows=2,
                 max_queue_depth=64) as eng:
        srv = eng.expose(port=0)
        router = ServingRouter(wire=False, poll_interval_s=0.2)
        router.add_engine("pool", f"http://127.0.0.1:{srv.port}")
        with router:
            futs = [router.submit(np.arange(1, 6, dtype=np.int32))
                    for _ in range(8)]
            assert slow.started.wait(10.0)
            waiters = [t.name for t in threading.enumerate()
                       if t.name.startswith("mxnet_tpu_router_http_pool")]
            assert 1 <= len(waiters) <= 2, waiters
            for f in futs:
                out = np.asarray(f.result(timeout=120.0))
                assert out.ravel()[0] == 1.0


def test_fallback_pool_unit():
    """Pool mechanics in isolation: lazy spawn up to the bound, FIFO
    drain, close() refuses new jobs but drains queued ones."""
    pool = _FallbackPool("unit", 2)
    gate = threading.Event()
    ran = []

    def job(i):
        gate.wait(10.0)
        ran.append(i)

    import functools
    for i in range(6):
        assert pool.submit(functools.partial(job, i))
    time.sleep(0.1)
    assert pool._threads <= 2
    pool.close()
    assert not pool.submit(lambda: ran.append("late"))
    gate.set()
    deadline = time.monotonic() + 10.0
    while len(ran) < 6 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert sorted(ran) == list(range(6))    # queued jobs drained


# ---------------------------------------------------------------------------
# remote-router client failover (tools/serve_loadgen.py --router-url)
# ---------------------------------------------------------------------------

def test_loadgen_router_client_failover():
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    from serve_loadgen import RouterClient

    with _engine("rc0") as eng:
        router = ServingRouter(engines=[eng], poll_interval_s=0.2)
        with router:
            srv = router.expose(port=0)
            live = f"http://127.0.0.1:{srv.port}"
            # a dead url first: the client fails over and goes sticky
            client = RouterClient(["http://127.0.0.1:1", live],
                                  timeout_s=60.0)
            toks = np.arange(1, 9, dtype=np.int32)
            out = client.submit(toks).result(timeout=60.0)
            assert np.array_equal(out.ravel()[:8],
                                  toks.astype(np.float32))
            assert client.failovers == 1
            fut = client.submit(toks)
            fut.result(timeout=60.0)
            assert client.failovers == 1     # sticky: no re-probe
            assert fut.trace_id and fut.cost
            assert client.scoreboard()       # run_load's router surface
        # router stopped: every url now refuses
        from mxnet_tpu.serving import NoEngineAvailableError
        with pytest.raises(NoEngineAvailableError):
            RouterClient(["http://127.0.0.1:1"]).submit(toks) \
                .result(timeout=5.0)


# ---------------------------------------------------------------------------
# wire-safety: the new module is inside mxlint's enforced scope
# ---------------------------------------------------------------------------

def test_mxlint_wire_safety_covers_wire_module():
    import os
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    from tools.mxlint.core import Project
    from tools.mxlint.passes.wire_safety import WireSafetyPass

    # the dispatch wire is inside the enforced scope...
    assert WireSafetyPass().applies("mxnet_tpu/serving/wire.py")
    # ...and the shipped module is clean under the REAL pass
    proj = Project(root=root, passes=[WireSafetyPass()])
    findings = proj.lint_path(
        os.path.join(root, "mxnet_tpu", "serving", "wire.py"))
    assert findings == [], findings
    # negative control: an executable decoder in this module WOULD fire
    evil = ("import pickle\n"
            "def decode(raw):\n"
            "    return pickle.loads(raw)\n")
    bad = Project(root=root, passes=[WireSafetyPass()]).lint_source(
        evil, "mxnet_tpu/serving/wire.py")
    assert any(f.rule == "wire-unsafe" for f in bad)
