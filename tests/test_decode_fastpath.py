"""Decode fast path: prefix KV cache reuse, chunked prefill through
the iteration loop, and seeded sampling.

Covers the ISSUE-16 acceptance surface:

- prefix-index COW/refcount invariants at the pool level: divergence
  exactly at a page boundary shares read-only with zero copies; a
  partial page is NEVER shared (COW-attached, never registered);
  refcount-zero recycle under register/release churn with the index
  yielding to live sequences on demand; ``check_isolated`` over owner
  SETS (shared pages at the same table index everywhere);
- write-frontier copy-on-write: ``prepare_write`` privatizes a pinned
  or multi-owner page, the source stays cached/shared;
- the shared-prefix SOLO-PARITY golden at the engine level: a prompt
  served through the warm prefix cache is byte-identical to its cold
  run, and the pool drains to zero live pages with the prefix pages
  still cached;
- chunked prefill: a long prompt admitted into a running batch is
  sliced through the iteration loop (prefill_chunks counter moves),
  short streams keep flowing, and replays stay byte-identical;
- seeded sampling: greedy default byte-stable, identical seeds give
  identical sequences, distinct seeds diverge; out-of-range sampling
  params raise the typed :class:`InvalidSamplingError` at submit on
  the engine, the router, and as HTTP 400 on ``/submit``.
"""
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu  # noqa: F401  (configures jax for the CPU mesh)


def _mk_model(**kw):
    from mxnet_tpu.serving import PagedCausalLM

    args = dict(vocab=64, units=32, layers=2, heads=4, max_len=128,
                seed=7)
    args.update(kw)
    return PagedCausalLM(**args)


def _mk_engine(model=None, **kw):
    from mxnet_tpu.serving import DecodeEngine

    args = dict(prefill_bucket_lens=(8, 16), max_rows=4, page_size=8,
                n_pages=24, max_new_tokens=6)
    args.update(kw)
    return DecodeEngine(model if model is not None else _mk_model(),
                        **args)


def _mk_pool(engine_id, **kw):
    from mxnet_tpu.serving import PagedKVPool

    args = dict(page_size=8, n_pages=12, prefix_cache=True,
                prefix_pages=8)
    args.update(kw)
    return PagedKVPool(2, 4, 16, engine_id=engine_id, **args)


def _accounted(pool):
    """used + cached + free must cover the pool exactly, always."""
    occ = pool.occupancy()
    assert (occ["pages_used"] + occ["pages_cached"] + occ["pages_free"]
            == occ["pages_total"]), occ
    return occ


# ---------------------------------------------------------------------------
# prefix index: sharing, divergence, COW, recycle
# ---------------------------------------------------------------------------
def test_prefix_divergence_at_page_boundary_shares_readonly():
    pool = _mk_pool("px_t0")
    toks_a = np.arange(1, 21, dtype=np.int32)        # 20 tokens
    pool.ensure("a", toks_a.size)
    # only the two FULL pages index; the 4-token tail page never does
    assert pool.register_prefix("a", toks_a) == 2
    refs = pool.page_refcounts()
    tail = pool.table("a")[2]
    assert not refs[tail]["pinned"]

    # b shares page 0 byte-for-byte, diverges at EXACTLY the boundary
    toks_b = np.concatenate([toks_a[:8],
                             np.arange(50, 58)]).astype(np.int32)
    matched, copies = pool.match_prefix("b", toks_b)
    assert matched == 8 and copies == []
    shared = pool.table("a")[0]
    assert pool.table("b")[0] == shared
    assert pool.owners_of(shared) == {"a", "b"}
    # sole-owner view refuses to name a shared page
    assert pool.owner_of(shared) is None
    pool.check_isolated()
    occ = _accounted(pool)
    assert occ["pages_shared"] >= 1

    st = pool.prefix_stats()
    assert st["hits"] == 1 and st["tokens_reused"] == 8
    pool.release("a")
    pool.release("b")
    pool.check_isolated()
    assert _accounted(pool)["pages_used"] == 0


def test_prefix_partial_page_is_cowed_never_shared():
    pool = _mk_pool("px_t1")
    toks_a = np.arange(1, 17, dtype=np.int32)        # 2 full pages
    pool.ensure("a", toks_a.size)
    pool.register_prefix("a", toks_a)

    # c matches page 0 fully and the first 4 slots of page 1, then
    # diverges MID-page: the match must come back as a private copy
    toks_c = np.concatenate([toks_a[:12],
                             np.arange(60, 64)]).astype(np.int32)
    matched, copies = pool.match_prefix("c", toks_c)
    assert matched == 12
    assert len(copies) == 1
    src, dst = copies[0]
    assert src == pool.table("a")[1]
    assert dst == pool.table("c")[1] and dst != src
    # the partially-matching SOURCE page is never in c's owner set
    assert "c" not in pool.owners_of(src)
    assert pool.owners_of(dst) == {"c"}
    pool.copy_pages(copies)
    pool.check_isolated()

    # prompt-end mid-page takes the same partial-COW arm
    toks_d = toks_a[:13]
    matched_d, copies_d = pool.match_prefix("d", toks_d)
    # limit is prompt_len - 1: the last token always prefills (its
    # logits produce the first generated token)
    assert matched_d == 12 and len(copies_d) == 1
    assert copies_d[0][0] == pool.table("a")[1]
    pool.copy_pages(copies_d)
    pool.check_isolated()

    for owner in ("a", "c", "d"):
        pool.release(owner)
    assert _accounted(pool)["pages_used"] == 0


def test_prefix_refcount_zero_recycle_under_churn():
    pool = _mk_pool("px_t2", n_pages=10, prefix_pages=6)
    base = np.arange(1, 17, dtype=np.int32)
    pool.ensure("s0", base.size)
    pool.register_prefix("s0", base)

    # churn: joiners share the cached prefix, then leave in a
    # different order than they came
    joined = []
    for i in range(4):
        owner = f"j{i}"
        matched, copies = pool.match_prefix(owner, base)
        assert matched == 15          # 1 full page + 7-slot COW tail
        pool.copy_pages(copies)
        joined.append(owner)
        pool.check_isolated()
        _accounted(pool)
        # the COW tail pages exhaust the pool unless refcount-zero
        # recycle keeps returning them
        pool.release(owner)
        pool.check_isolated()
    pool.release("s0")
    occ = _accounted(pool)
    assert occ["pages_used"] == 0
    # the registered pages survive their sequences (cached, pinned)
    assert occ["pages_cached"] == 2

    # live allocation reclaims cached pages on demand: the index can
    # never starve admission
    pool.ensure("big", pool.n_pages * pool.page_size)
    occ = _accounted(pool)
    assert occ["pages_used"] == pool.n_pages
    assert occ["pages_cached"] == 0
    assert pool.prefix_stats()["evictions"] >= 2
    pool.release("big")
    occ = _accounted(pool)
    assert occ["pages_free"] == pool.n_pages
    pool.check_isolated()


def test_prepare_write_cows_frozen_pages():
    pool = _mk_pool("px_t3")
    toks = np.arange(1, 17, dtype=np.int32)
    pool.ensure("a", toks.size)
    pool.register_prefix("a", toks)

    # a pinned page at the write frontier: a's own page 1 is indexed,
    # so writing into it must first privatize it
    src_dst = pool.prepare_write("a", 8)
    assert src_dst is not None
    src, dst = src_dst
    assert pool.table("a")[1] == dst and dst != src
    pool.copy_pages([src_dst])
    # the source page survives as a cached index entry
    assert pool.page_refcounts()[src]["pinned"]
    assert pool.owners_of(src) == frozenset()
    pool.check_isolated()

    # a multi-owner page: b shares page 0; b writing into it COWs,
    # a keeps the original
    matched, copies = pool.match_prefix("b", toks)
    pool.copy_pages(copies)
    page0 = pool.table("a")[0]
    assert pool.owners_of(page0) == {"a", "b"}
    pair = pool.prepare_write("b", 0)
    assert pair is not None and pair[0] == page0
    pool.copy_pages([pair])
    assert pool.owners_of(page0) == {"a"}
    assert pool.table("b")[0] == pair[1]
    # a PRIVATE unpinned page is the no-op fast path
    assert pool.prepare_write("b", 0) is None
    pool.check_isolated()
    pool.release("a")
    pool.release("b")
    assert _accounted(pool)["pages_used"] == 0


def test_prefix_disabled_pool_is_inert():
    pool = _mk_pool("px_t4", prefix_cache=False)
    toks = np.arange(1, 17, dtype=np.int32)
    pool.ensure("a", toks.size)
    assert pool.register_prefix("a", toks) == 0
    assert pool.match_prefix("b", toks) == (0, [])
    assert pool.prefix_stats()["enabled"] is False
    pool.release("a")
    occ = _accounted(pool)
    assert occ["pages_cached"] == 0 and occ["pages_used"] == 0


# ---------------------------------------------------------------------------
# engine level: shared-prefix solo parity, chunked prefill, sampling
# ---------------------------------------------------------------------------
def test_prefix_hit_is_byte_identical_to_cold_run():
    prompt = list(range(1, 14))                      # 13 tokens
    with _mk_engine() as eng:
        cold = eng.infer(prompt, max_new_tokens=6).tolist()
        occ = eng.pool.occupancy()
        # drained: no live pages, the prompt's full page stays cached
        assert occ["pages_used"] == 0
        assert occ["pages_cached"] >= 1
        hit = eng.infer(prompt, max_new_tokens=6).tolist()
        assert hit == cold
        st = eng.pool.prefix_stats()
        assert st["hits"] >= 1
        assert st["tokens_reused"] >= 8
        eng.pool.check_isolated()
        # the scheduler-state bundle carries the index + refcounts
        state = eng.scheduler_state()
        assert state["prefix"]["hits"] >= 1
        assert isinstance(state["page_refcounts"], dict)


def test_chunked_prefill_interleaves_with_running_decode():
    import time

    with _mk_engine(prefill_bucket_lens=(8, 64), prefill_budget=8,
                    max_rows=4, n_pages=32, max_new_tokens=8) as eng:
        short = [3, 2, 1]
        f1 = eng.submit(short, max_new_tokens=8, stream=True)
        it = f1.stream(timeout=60)
        first = next(it)                  # decode is live
        assert "token" in first
        # a LONG prompt (8 budget-sized chunks) joins the running batch
        long_p = list(range(1, 61))
        f2 = eng.submit(long_p, max_new_tokens=4)
        rest = [p["token"] for p in it]
        out1 = np.asarray(f1.result(timeout=0)).tolist()
        assert [first["token"]] + rest == out1
        out2 = np.asarray(f2.result(timeout=60)).tolist()
        assert len(out2) == 4
        snap = eng.decode_stats.snapshot()
        assert snap["prefill_chunks"] >= 8
        assert snap["prefill_chunk_tokens"] >= 60
        # deadline for the stats scrape thread is irrelevant; what
        # matters is the pool drained and stayed consistent
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline \
                and eng.pool.occupancy()["pages_used"]:
            time.sleep(0.01)
        assert eng.pool.occupancy()["pages_used"] == 0
        eng.pool.check_isolated()
        # replay: same prompt through the now-warm prefix cache is
        # byte-identical (greedy)
        assert eng.infer(long_p, max_new_tokens=4).tolist() == out2
        assert eng.pool.prefix_stats()["hits"] >= 1


def test_static_mode_keeps_dense_prefill():
    with _mk_engine(iteration_level=False) as eng:
        assert eng._prefill_budget == 0
        assert eng.pool.prefix_enabled is False
        out = eng.infer([1, 2, 3, 4, 5], max_new_tokens=4)
        assert len(out) == 4
        assert eng.decode_stats.snapshot()["prefill_chunks"] == 0


def test_seeded_sampling_deterministic():
    prompt = [5, 6, 7]
    with _mk_engine(max_new_tokens=8) as eng:
        g1 = eng.infer(prompt).tolist()
        g2 = eng.infer(prompt).tolist()
        assert g1 == g2                   # greedy default, byte-stable
        kw = dict(temperature=2.0, top_k=0, top_p=1.0)
        s1 = eng.infer(prompt, seed=77, **kw).tolist()
        s2 = eng.infer(prompt, seed=77, **kw).tolist()
        assert s1 == s2                   # identical seeds, identical
        s3 = eng.infer(prompt, seed=78, **kw).tolist()
        # 8 near-uniform draws from a 64-token vocab: a collision of
        # the whole sequence would be a once-per-2^48 event
        assert s3 != s1
        # truncation composes with the seed the same way
        t1 = eng.infer(prompt, temperature=0.9, top_k=16, top_p=0.9,
                       seed=5).tolist()
        t2 = eng.infer(prompt, temperature=0.9, top_k=16, top_p=0.9,
                       seed=5).tolist()
        assert t1 == t2


def test_sampling_validation_typed_errors():
    from mxnet_tpu.serving import (InvalidSamplingError, ServingRouter,
                                   validate_sampling)

    # the validator itself: normalization + refusals
    assert validate_sampling(None, None, None, None) == (None,) * 4
    assert validate_sampling(0.0, 0, 1.0, 3) == (0.0, 0, 1.0, 3)
    for bad in ((-0.5, None, None, None),
                (float("nan"), None, None, None),
                (None, -2, None, None),
                (None, None, 0.0, None),
                (None, None, 1.5, None)):
        with pytest.raises(InvalidSamplingError):
            validate_sampling(*bad)

    with _mk_engine() as eng:
        for kw in ({"temperature": -1.0}, {"top_k": -3},
                   {"top_p": 0.0}, {"top_p": 2.0}):
            with pytest.raises(InvalidSamplingError):
                eng.submit([1, 2, 3], **kw)
        # the router refuses BEFORE journaling/dispatch, same type
        with ServingRouter(engines=[eng]) as router:
            with pytest.raises(InvalidSamplingError):
                router.submit([1, 2, 3], temperature=-1.0)
        # HTTP surface: a typed 400, not a 500 from inside a step
        srv = eng.expose()
        req = urllib.request.Request(
            srv.url("/submit"),
            data=json.dumps({"tokens": [1, 2, 3],
                             "temperature": -1.0}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 400
        body = json.loads(ei.value.read().decode())
        assert body["error_type"] == "InvalidSamplingError"


def test_seeded_sampling_rides_the_router_relay():
    """A seeded streamed request through a router-fronted seat: parts
    match the final result and a same-seed solo run — the dispatch
    payload carries the seed, so replay is seat-independent."""
    from mxnet_tpu.serving import ServingRouter

    kw = dict(temperature=1.5, top_k=0, top_p=1.0)
    with _mk_engine(max_new_tokens=8) as eng:
        solo = eng.infer([9, 8, 7], seed=321, **kw).tolist()
        with ServingRouter(engines=[eng]) as router:
            fut = router.submit([9, 8, 7], max_new_tokens=8,
                                stream=True, seed=321, **kw)
            parts = [p["token"] for p in fut.stream(timeout=60)]
            out = np.asarray(fut.result(timeout=0)).tolist()
        assert parts == out == solo
