"""Retrospective observability tests (ISSUE 18): the bounded on-disk
history store (tier downsampling, segment rotation, crash reload with
a torn final line), the scrape→store→query golden path (rate and
quantile-over-time, straight numbers), the exposition endpoints
(``/query_range`` + ``/series``), incident forensics (an opened
incident freezes the PRECEDING window into the flight bundle), retro
SLO replay (the live firing decision reproduces from the persisted
evidence — and fails to reproduce at a healthy instant, proving the
audit has teeth), the exemplar-bearing tenant merge round-trip, the
torn-tail ``read_events`` hardening, and the bench_regress sentry
(flags an injected regression, passes the real trajectory).

CPU-only, thread-light: the store and scraper are driven manually
with explicit timestamps wherever determinism matters.
"""
import glob
import io
import json
import os
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from mxnet_tpu import nd
from mxnet_tpu.serving import ServingEngine
from mxnet_tpu.telemetry import alerts as alerts_mod
from mxnet_tpu.telemetry import events as events_mod
from mxnet_tpu.telemetry import history as hist_mod
from mxnet_tpu.telemetry import incidents as incidents_mod
from mxnet_tpu.telemetry import recorder as flight
from mxnet_tpu.telemetry import slo as slo_mod
from mxnet_tpu.telemetry.expo import (merge_prometheus_texts,
                                      parse_prometheus_text)
from mxnet_tpu.telemetry.registry import MetricsRegistry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(ROOT, "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

T0 = 1_700_000_000.0        # 10s/60s-aligned synthetic wall epoch


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


def _get_json(url, timeout=10):
    return json.loads(_get(url, timeout)[1])


class StubModel:
    def __init__(self, delay=0.0):
        self.delay = delay

    def __call__(self, ids, token_types, valid_length, segment_ids,
                 positions):
        if self.delay:
            time.sleep(self.delay)
        return nd.array(ids.asnumpy().astype(np.float32)[..., None])


@pytest.fixture(autouse=True)
def _no_history_env(monkeypatch):
    """Stores built here are memory-only unless a test passes a dir."""
    monkeypatch.delenv("MXNET_TPU_HISTORY_DIR", raising=False)
    monkeypatch.delenv("MXNET_TPU_HISTORY", raising=False)


def _key(family, **labels):
    inner = ",".join(f'{k}="{v}"' for k, v in labels.items())
    return f"{family}{{{inner}}}"


# ---------------------------------------------------------------------------
# store: tiers, retention, range evaluation goldens
# ---------------------------------------------------------------------------

def test_family_of_strips_suffixes_and_labels():
    assert hist_mod.family_of(
        'mxnet_tpu_serving_latency_ms_bucket{le="10"}') \
        == "mxnet_tpu_serving_latency_ms"
    assert hist_mod.family_of("mxnet_tpu_serving_latency_ms_count") \
        == "mxnet_tpu_serving_latency_ms"
    assert hist_mod.family_of(
        'mxnet_tpu_serving_requests_total{event="completed"}') \
        == "mxnet_tpu_serving_requests_total"


def test_tier_downsampling_keeps_last_sample_per_bucket():
    store = hist_mod.HistoryStore(dirpath="", retain_s=7200)
    key = _key("mxnet_tpu_serving_queue_depth", engine_id="tier0")
    for i in range(26):
        store.append(T0 + i, {key: float(i)})
    raw = store.tiers[0].series[key]
    assert len(raw) == 26
    # 10s tier: two CLOSED buckets, each flushed at its END edge with
    # the bucket's LAST sample (cumulative counters diff exactly
    # across edges); the third bucket is still pending
    t10 = store.tiers[1].series[key]
    assert t10 == [(T0 + 10.0, 9.0), (T0 + 20.0, 19.0)]
    assert store.tiers[2].series.get(key) is None   # 60s: still open
    # stitched view prefers the finest tier wherever raw covers
    pts = store.points(key)
    assert pts == raw


def test_store_rate_increase_and_counter_reset_golden():
    store = hist_mod.HistoryStore(dirpath="", retain_s=7200)
    key = _key("mxnet_tpu_serving_requests_total",
               engine_id="g0", event="completed")
    for i in range(61):
        store.append(T0 + i, {key: 2.0 * i})
    out = store.query_range("mxnet_tpu_serving_requests_total",
                            start=T0 + 30, end=T0 + 60, step=5,
                            window=10, fn="rate", now=T0 + 60)
    [row] = out["series"]
    assert row["labels"] == {"engine_id": "g0", "event": "completed"}
    for _, v in row["points"]:
        assert v == pytest.approx(2.0)
    inc = store.query_range("mxnet_tpu_serving_requests_total",
                            start=T0 + 60, end=T0 + 60, step=1,
                            window=10, fn="increase", now=T0 + 60)
    assert inc["series"][0]["points"][-1][1] == pytest.approx(20.0)

    # counter reset: climb to 50, restart at 0, climb to 27 — the
    # increase over the whole window re-anchors at the reset value
    rkey = _key("mxnet_tpu_serving_requests_total",
                engine_id="reset0", event="completed")
    for i in range(11):
        store.append(T0 + i, {rkey: 5.0 * i})
    for i in range(11, 21):
        store.append(T0 + i, {rkey: 3.0 * (i - 11)})
    out = store.query_range("mxnet_tpu_serving_requests_total",
                            start=T0 + 20, end=T0 + 20, step=1,
                            window=20, fn="increase", now=T0 + 20,
                            match={"engine_id": "reset0"})
    [row] = out["series"]
    assert row["points"][-1][1] == pytest.approx(50.0 + 27.0)


def test_query_range_quantile_over_time_golden():
    store = hist_mod.HistoryStore(dirpath="", retain_s=7200)
    fam = "mxnet_tpu_serving_latency_ms"
    for i in range(31):
        store.append(T0 + i, {
            _key(fam + "_bucket", engine_id="q0", le="10"): float(i),
            _key(fam + "_bucket", engine_id="q0", le="100"): 2.0 * i,
            _key(fam + "_bucket", engine_id="q0", le="+Inf"): 2.0 * i,
        })
    # window of 10 scrapes: 10 obs <=10ms, 10 more <=100ms. PromQL
    # interpolation: q50 rank sits exactly at the first bucket's
    # upper bound; q75 interpolates half-way into (10, 100]
    out = store.query_range(fam, start=T0 + 30, end=T0 + 30, step=1,
                            window=10, fn="quantile", q=50, now=T0 + 30)
    [row] = out["series"]
    assert row["labels"] == {"engine_id": "q0"}
    assert row["points"][-1][1] == pytest.approx(10.0)
    out = store.query_range(fam, start=T0 + 30, end=T0 + 30, step=1,
                            window=10, fn="quantile", q=75, now=T0 + 30)
    assert out["series"][0]["points"][-1][1] == pytest.approx(55.0)


def test_value_staleness_marks_gaps_null():
    store = hist_mod.HistoryStore(dirpath="", retain_s=7200)
    key = _key("mxnet_tpu_serving_queue_depth", engine_id="stale0")
    store.append(T0, {key: 3.0})
    out = store.query_range("mxnet_tpu_serving_queue_depth",
                            start=T0, end=T0 + 600, step=60,
                            fn="value", now=T0 + 600)
    pts = out["series"][0]["points"]
    assert pts[0][1] == 3.0
    assert pts[-1][1] is None     # 600s past the last sample: stale


# ---------------------------------------------------------------------------
# store: disk persistence, rotation, crash reload
# ---------------------------------------------------------------------------

def test_disk_segments_rotate_reload_and_skip_torn_line(tmp_path):
    d = str(tmp_path / "hist")
    store = hist_mod.HistoryStore(dirpath=d, retain_s=7200,
                                  max_mb=64, segment_mb=0.000001)
    key = _key("mxnet_tpu_serving_requests_total",
               engine_id="disk0", event="completed")
    gkey = _key("mxnet_tpu_serving_queue_depth", engine_id="disk0")
    n = 400
    for i in range(n):
        store.append(T0 + i, {key: 2.0 * i, gkey: float(i % 7)})
    store.close()
    fam_dir = os.path.join(d, "mxnet_tpu_serving_requests_total")
    segs = [f for f in os.listdir(fam_dir) if f.startswith("raw-")]
    assert len(segs) >= 2, "tiny segment_mb must have rotated"

    # hard-kill simulation: tear the newest raw segment mid multi-byte
    # UTF-8 sequence, plus a corrupt-JSON line
    newest = os.path.join(fam_dir, sorted(segs)[-1])
    with open(newest, "ab") as fh:
        fh.write(b'{"t": 17, "s": {"x\xe2\x82')
    reloaded = hist_mod.HistoryStore(dirpath=d, retain_s=7200,
                                     max_mb=64, now=T0 + n)
    assert reloaded.load_skipped >= 1
    pts = reloaded.points(key)
    assert pts and pts[-1] == (T0 + n - 1, 2.0 * (n - 1))
    # reloaded store answers range queries identically to the live one
    out = reloaded.query_range("mxnet_tpu_serving_requests_total",
                               start=T0 + n - 1, end=T0 + n - 1,
                               step=1, window=10, fn="rate",
                               now=T0 + n - 1)
    assert out["series"][0]["points"][-1][1] == pytest.approx(2.0)
    reloaded.close()


def test_disk_budget_drops_oldest_sealed_segments(tmp_path):
    d = str(tmp_path / "hist")
    store = hist_mod.HistoryStore(dirpath=d, retain_s=7200,
                                  max_mb=0.008, segment_mb=0.000001)
    key = _key("mxnet_tpu_serving_requests_total",
               engine_id="budget0", event="completed")
    for i in range(1200):
        store.append(T0 + i, {key: float(i)})
    fam_dir = os.path.join(d, "mxnet_tpu_serving_requests_total")
    assert not os.path.exists(os.path.join(fam_dir, "raw-00000001.seg"))
    sealed = sum(os.path.getsize(os.path.join(fam_dir, f))
                 for f in os.listdir(fam_dir))
    # bounded: budget plus at most the open segments' slack
    assert sealed <= 0.008 * 1024 * 1024 + 3 * store.segment_bytes
    store.close()


# ---------------------------------------------------------------------------
# scrape -> store -> query golden (the acceptance cycle)
# ---------------------------------------------------------------------------

def test_scrape_store_query_golden_rate_and_quantile():
    reg = MetricsRegistry()
    req = reg.counter("mxnet_tpu_serving_requests_total",
                      "doc", ("engine_id", "event"))
    lat = reg.histogram("mxnet_tpu_serving_latency_ms", "doc",
                        ("engine_id", "stage"), buckets=(10.0, 100.0))
    # a family NO recording rule names must not be stored
    other = reg.counter("mxnet_tpu_serving_batches_total", "doc",
                        ("engine_id",))
    scraper = hist_mod.HistoryScraper("golden0", registry=reg,
                                      interval_s=999)
    c = req.labels(engine_id="g0", event="completed")
    h = lat.labels(engine_id="g0", stage="total")
    o = other.labels(engine_id="g0")
    for i in range(31):
        c.inc(2)
        h.observe(5.0)
        h.observe(50.0)
        o.inc()
        kept = scraper.scrape_once(now=T0 + i)
        assert kept > 0
    assert scraper.scrapes == 31
    store = scraper.store
    assert not any("batches" in k for k in store.keys())

    out = store.query_range("mxnet_tpu_serving_requests_total",
                            start=T0 + 10, end=T0 + 30, step=5,
                            window=10, fn="rate", now=T0 + 30,
                            match={"engine_id": "g0"})
    [row] = out["series"]
    for _, v in row["points"]:
        assert v == pytest.approx(2.0)      # +2 per 1s scrape

    # per scrape: one obs in (0,10], one in (10,100] — the windowed
    # histogram is the quantile golden from the pure-store test
    out = store.query_range("mxnet_tpu_serving_latency_ms",
                            start=T0 + 30, end=T0 + 30, step=1,
                            window=10, fn="quantile", q=75,
                            now=T0 + 30)
    [row] = out["series"]
    assert row["labels"]["engine_id"] == "g0"
    assert row["points"][-1][1] == pytest.approx(55.0)

    body = store.series()
    assert body["count"] == len(store.keys())
    names = {r["family"] for r in body["series"]}
    assert names == {"mxnet_tpu_serving_requests_total",
                     "mxnet_tpu_serving_latency_ms"}


def test_merged_tenant_exemplars_survive_into_history(monkeypatch):
    """Satellite: two engines' exemplar-bearing tenant-labeled
    histograms merge (worst trace per series survives), and the
    merged text feeds a history scrape-store-query cycle."""
    regs = [MetricsRegistry(), MetricsRegistry()]
    children = []
    for i, reg in enumerate(regs):
        fam = reg.histogram(
            "mxnet_tpu_serving_tenant_latency_ms", "doc",
            ("engine_id", "tenant", "tenant_class", "model"),
            buckets=(10.0, 100.0))
        children.append(fam.labels(engine_id=f"mx{i}", tenant="acme",
                                   tenant_class="std", model="m1"))

    def merged():
        return merge_prometheus_texts(
            [r.render_prometheus() for r in regs])

    scraper = hist_mod.HistoryScraper("merge0", text_fn=merged,
                                      interval_s=999)
    for i in range(21):
        children[0].observe(5.0, exemplar=f"tr-fast-{i}")
        children[1].observe(80.0, exemplar="tr-slow")
        scraper.scrape_once(now=T0 + i)

    ex = {}
    parsed = parse_prometheus_text(merged(), exemplars=ex)
    traces = {e["trace_id"] for e in ex.values()}
    assert "tr-slow" in traces          # the merge kept the worst trace
    inf_keys = [k for k in parsed
                if k.startswith("mxnet_tpu_serving_tenant_latency_ms_"
                                "bucket") and 'le="+Inf"' in k]
    assert len(inf_keys) == 2           # engine-labeled: disjoint series

    # the tenant axis queries straight out of history: one row per
    # engine, both entirely under the 100ms bucket
    out = scraper.store.query_range(
        "mxnet_tpu_serving_tenant_latency_ms",
        start=T0 + 20, end=T0 + 20, step=1, window=10,
        fn="quantile", q=99, now=T0 + 20, match={"tenant": "acme"})
    rows = {r["labels"]["engine_id"]: r["points"][-1][1]
            for r in out["series"]}
    assert set(rows) == {"mx0", "mx1"}
    assert 0.0 < rows["mx0"] <= 10.0             # all obs in (0,10]
    assert 10.0 < rows["mx1"] <= 100.0


# ---------------------------------------------------------------------------
# exposition endpoints + the mxtop consumer
# ---------------------------------------------------------------------------

def test_engine_history_endpoints_and_mxtop(monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_TPU_FLIGHT_DIR", str(tmp_path / "flight"))
    eng = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=2,
                        engine_id="hist-ep0")
    with eng:
        srv = eng.expose()
        eng.warmup()
        assert eng._history is not None, \
            "MXNET_TPU_HISTORY defaults on: engine start runs a scraper"
        for _ in range(4):
            eng.infer([1, 2, 3], timeout=30)
        eng._history.scrape_once()
        for _ in range(4):
            eng.infer([1, 2, 3], timeout=30)
        time.sleep(0.02)
        eng._history.scrape_once()

        series = _get_json(srv.url("/series"))
        assert series["count"] > 0
        fams = {r["family"] for r in series["series"]}
        assert "mxnet_tpu_serving_requests_total" in fams

        out = _get_json(srv.url(
            "/query_range?family=mxnet_tpu_serving_requests_total"
            "&fn=increase&window=3600&engine_id=hist-ep0"))
        assert out["fn"] == "increase"
        # increase anchors at the FIRST stored sample (4 completed at
        # scrape one, 8 at scrape two): the window saw +4
        last = {r["labels"].get("event"): r["points"][-1][1]
                for r in out["series"]}
        assert last.get("completed", 0) >= 4

        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.url("/query_range?fn=rate"))    # no family
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.url("/query_range?family=x&fn=bogus"))
        assert ei.value.code == 400

        # the terminal console renders one frame off the same store
        import mxtop
        buf = io.StringIO()
        firing = mxtop.render(srv.url("").rstrip("/"), 300.0, out=buf)
        frame = buf.getvalue()
        assert "mxtop" in frame and "alerts" in frame
        assert isinstance(firing, int)
    assert eng._history._thread is None      # stop() joined the scraper


def test_mxtop_sparkline_and_format():
    import mxtop
    assert mxtop.sparkline([]) == "····"
    line = mxtop.sparkline([(0, 0.0), (1, None), (2, 1.0), (3, 2.0)])
    assert len(line) == 3
    assert line[0] == mxtop.SPARK[0] and line[-1] == mxtop.SPARK[-1]
    assert mxtop._fmt(None) == "  -"
    assert mxtop._fmt(2_500_000).strip().startswith("2.5M")


# ---------------------------------------------------------------------------
# incident forensics + retro replay (the acceptance drill)
# ---------------------------------------------------------------------------

def _synthetic_burn_drill(owner, on_page=None, register=False):
    """Drive an availability SLO + fast-burn rule + history scraper
    over a synthetic wall timeline ending NOW: 20s of good traffic,
    then everything fails — the page fires mid-timeline. With
    ``register`` the scraper is started first (registered with the
    incident hook and the flight recorder; its thread idles at the
    999s interval). Returns (daemon, scraper, evaluator,
    timestamps)."""
    reg = MetricsRegistry()
    req = reg.counter("mxnet_tpu_serving_requests_total", "doc",
                      ("engine_id", "event"))
    evaluator = slo_mod.SloEvaluator(owner, registry=reg, scale=0.01)
    evaluator.add(slo_mod.AvailabilitySLO(
        "hist_avail", target=0.99, match={"engine_id": owner},
        registry=reg))
    daemon = alerts_mod.AlertDaemon(evaluator, eval_s=999,
                                    registry=reg, on_page=on_page)
    daemon.add_rule(alerts_mod.BurnRateRule(
        "hist_avail_fast_burn", "hist_avail", long_window="1h",
        short_window="5m", factor=14.4,
        severity=alerts_mod.PAGE, for_s=60.0))
    clock = {"t": 0.0}
    scraper = hist_mod.HistoryScraper(
        owner, registry=reg, interval_s=999,
        slo_fn=lambda: evaluator.snapshot(now=clock["t"], tick=False),
        alerts_fn=daemon.snapshot)
    if register:
        scraper.start()
    end = time.time()
    ts = [end - 60.0 + i for i in range(61)]
    good = req.labels(engine_id=owner, event="completed")
    bad = req.labels(engine_id=owner, event="failed")
    for i, t in enumerate(ts):
        (good if i < 20 else bad).inc(5)
        clock["t"] = t
        daemon.evaluate_once(now=t)
        scraper.scrape_once(now=t)
    return daemon, scraper, evaluator, ts


def test_replay_history_reproduces_the_firing_decision():
    daemon, scraper, _, ts = _synthetic_burn_drill("replay0")
    assert daemon.state("hist_avail_fast_burn") == "firing"

    freeze = scraper.freeze("inc-replay-test")
    assert freeze["series"], "freeze must carry the series window"
    assert freeze["alerts"]["rules"][0]["state"] == "firing"

    rep = slo_mod.replay_history(freeze)
    assert rep["reproduces"] is True
    [rule] = rep["rules"]
    assert rule["alert"] == "hist_avail_fast_burn"
    assert rule["active"] is True and rule["live_state"] == "firing"
    assert rule["detail"]["burn_short"] > 14.4
    obj = rep["objectives"]["hist_avail"]
    assert obj["sli"] is not None and obj["sli"] < 0.99
    assert rep["ticks"] > 0 and rep["scale"] == pytest.approx(0.01)

    # the audit has teeth: judged at a HEALTHY instant the replay
    # must NOT reproduce a firing decision
    rep2 = slo_mod.replay_history(freeze, at=ts[15])
    assert rep2["reproduces"] is False
    assert rep2["rules"][0]["active"] in (False, None)


def test_incident_open_freezes_preceding_window_into_bundle(
        monkeypatch, tmp_path):
    """The chaos-drill acceptance path, synthetically induced: the
    fast-burn page opens an incident, the incident freezes every live
    scraper's PRECEDING window, and the page's flight bundle carries
    ``history_<owner>.json`` — from which replay reproduces the
    decision."""
    flight_dir = str(tmp_path / "flight")
    monkeypatch.setenv("MXNET_TPU_FLIGHT_DIR", flight_dir)
    rec = flight.RECORDER
    rec._last_bundle = None
    rec._last_dump.clear()
    incidents_mod.TRACKER.reset()
    incidents_mod.install()
    scraper = None
    try:
        daemon, scraper, _, ts = _synthetic_burn_drill(
            "pagehist0", register=True)
        assert scraper in hist_mod.scrapers()
        assert hist_mod.default_store() is scraper.store
        assert daemon.state("hist_avail_fast_burn") == "firing"
        open_inc = incidents_mod.TRACKER.open_incidents()
        assert len(open_inc) == 1
        inc_id = open_inc[0]["id"]

        with scraper._lock:
            freezes = list(scraper._freezes)
        assert freezes and freezes[-1]["incident_id"] == inc_id
        # the window precedes the incident: coverage starts back in
        # the healthy phase, not at the moment the page fired
        first_t = min(p[0] for pts in freezes[-1]["series"].values()
                      for p in pts)
        assert first_t <= ts[5]

        bundles = [p for p in glob.glob(os.path.join(flight_dir, "*"))
                   if "alert_hist_avail_fast_burn" in p]
        assert len(bundles) == 1
        section_path = os.path.join(bundles[0],
                                    "history_pagehist0.json")
        assert os.path.exists(section_path)
        with open(section_path, encoding="utf-8") as fh:
            section = json.load(fh)
        assert section["owner"] == "pagehist0"

        # replay straight off the BUNDLE section, exactly as a
        # postmortem would (a bundle section replays its newest
        # freeze), judged at the newest stored sample — the synthetic
        # timeline lags the wall clock the freeze is stamped with
        frozen = section["freezes"][-1]
        last_t = max(p[0] for pts in frozen["series"].values()
                     for p in pts)
        rep = slo_mod.replay_history(section, at=last_t)
        assert rep["reproduces"] is True
        with open(os.path.join(bundles[0], "meta.json"),
                  encoding="utf-8") as fh:
            meta = json.load(fh)
        assert meta["incident_id"] == inc_id
    finally:
        if scraper is not None:
            scraper.stop()
        incidents_mod.TRACKER.reset()
        rec._last_bundle = None
        rec._last_dump.clear()


# ---------------------------------------------------------------------------
# events: torn-tail hardening
# ---------------------------------------------------------------------------

def test_read_events_skips_and_counts_torn_tail(tmp_path):
    p = tmp_path / "events.jsonl"
    with open(p, "w", encoding="utf-8") as fh:
        fh.write(json.dumps({"event": "a", "n": 1}) + "\n")
        fh.write("[1, 2, 3]\n")                  # parseable, not a dict
        fh.write(json.dumps({"event": "b", "n": 2}) + "\n")
    with open(p, "ab") as fh:
        # hard kill mid-write, cut INSIDE a multi-byte UTF-8 sequence:
        # a strict decode would raise mid-postmortem
        fh.write(b'{"event": "c", "msg": "\xf0\x9f')
    skipped = {}
    recs = events_mod.read_events(str(p), skipped=skipped)
    assert [r["event"] for r in recs] == ["a", "b"]
    assert skipped == {str(p): 2}
    # filter still applies; a caller that doesn't ask doesn't pay
    assert [r["n"] for r in
            events_mod.read_events(str(p), event="b")] == [2]


# ---------------------------------------------------------------------------
# bench_regress: the perf-regression sentry
# ---------------------------------------------------------------------------

def _bench_rec(**metrics):
    tail = "".join(json.dumps({"metric": k, "value": v}) + "\n"
                   for k, v in metrics.items())
    return {"n": 1, "cmd": "x", "rc": 0, "tail": tail, "parsed": None}


def test_bench_regress_judge_directions_and_noise():
    import bench_regress as br
    assert br.direction("bert_base_train_tokens_per_sec_per_chip") == 1
    assert br.direction("serving_p99_ms") == -1
    assert br.direction("suite_budget_skipped") == 0

    recs = [("r1", {}, {"syn_tokens_per_sec": 100.0}),
            ("r2", {}, {"syn_tokens_per_sec": 102.0}),
            ("r3", {}, {"syn_tokens_per_sec": 80.0})]
    rows, regressions = br.judge(recs, floor=0.10)
    assert [r["metric"] for r in regressions] == ["syn_tokens_per_sec"]
    assert regressions[0]["status"] == "REGRESSION"

    # a metric the candidate misses is a visibility gap, not a flag
    recs = [("r1", {}, {"syn_p99_ms": 10.0, "gone_per_sec": 5.0}),
            ("r2", {}, {"syn_p99_ms": 30.0})]
    rows, regressions = br.judge(recs, floor=0.10)
    by = {r["metric"]: r for r in rows}
    assert by["gone_per_sec"]["status"] == "skipped"
    assert by["syn_p99_ms"]["status"] == "REGRESSION"   # latency UP

    # historically jittery metric: tolerance widens past the floor
    recs = [("r%d" % i, {}, {"syn_tokens_per_sec": v})
            for i, v in enumerate([100.0, 140.0, 100.0, 140.0])]
    recs.append(("cand", {}, {"syn_tokens_per_sec": 80.0}))
    rows, regressions = br.judge(recs, floor=0.10)
    assert not regressions, rows      # 2x median step = 80% tolerance

    # best-of-repeats: the tail's best value per record is scored
    rec = _bench_rec()
    rec["tail"] = (json.dumps({"metric": "syn_tokens_per_sec",
                               "value": 90.0}) + "\n"
                   + json.dumps({"metric": "syn_tokens_per_sec",
                                 "value": 110.0}) + "\n")
    assert br.record_metrics(rec) == {"syn_tokens_per_sec": 110.0}


def test_bench_regress_cli_flags_injected_regression(tmp_path, capsys):
    import bench_regress as br
    paths = []
    for i, v in enumerate([100.0, 104.0, 101.0]):
        p = tmp_path / f"BENCH_r{i + 1:02d}.json"
        p.write_text(json.dumps(_bench_rec(
            syn_tokens_per_sec=v, syn_p99_ms=20.0 + i)))
        paths.append(str(p))
    assert br.main(paths) == 0
    assert br.main(["--dir", str(tmp_path)]) == 0
    assert br.main(paths + ["--inject",
                            "syn_tokens_per_sec=50.0"]) == 1
    assert br.main([paths[0]]) == 2             # one record: no diff
    capsys.readouterr()
    assert br.main(paths + ["--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["regressions"] == 0
    assert out["candidate"] == "BENCH_r03.json"


def test_bench_regress_passes_real_trajectory():
    import bench_regress as br
    paths = sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json")))
    if len(paths) < 2:
        pytest.skip("repo carries fewer than two BENCH records")
    assert br.main(paths) == 0, \
        "the committed bench trajectory must judge clean"
    # the sentry actually fires: inject a halved throughput on a
    # metric the real history carries
    recs = br.load_records(paths)
    rows, _ = br.judge(recs, floor=0.10)
    judged = [r for r in rows if r["status"] in ("ok", "REGRESSION")
              and r.get("direction") == "higher"]
    assert judged, "no judged higher-is-better metric in real records"
    metric = judged[0]["metric"]
    ref = judged[0]["reference"]
    assert br.main(paths + ["--inject",
                            f"{metric}={ref * 0.4}"]) == 1
