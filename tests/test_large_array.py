"""int64-indexing tests (reference tests/nightly/test_large_array.py).

The reference's nightly tier allocates >2^32-element tensors to prove
int64 index paths; that allocation is gated here behind
MXNET_TPU_NIGHTLY=1 (CI hosts don't have 20 GB to spare), while the
always-run portion pins the int64 *semantics*: index dtypes survive
take/Embedding/slice/argmax round-trips and values above 2^31 don't
wrap (jax_enable_x64 is on globally — see ops/pallas/_util.py x32).
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal

NIGHTLY = os.environ.get("MXNET_TPU_NIGHTLY", "") == "1"


def test_int64_indices_take():
    table = nd.array(np.arange(40, dtype=np.float32).reshape(10, 4))
    idx = nd.array(np.array([9, 0, 7], dtype=np.int64))
    out = nd.take(table, idx)
    assert out.shape == (3, 4)
    assert_almost_equal(out.asnumpy()[0], np.arange(36, 40, dtype=np.float32))


def test_int64_scalar_values_do_not_wrap():
    # 2^31 + 7 survives an NDArray round-trip and arithmetic: the int32
    # overflow the reference's large-array tier guards against
    big = np.array([2**31 + 7, 2**33], dtype=np.int64)
    a = nd.array(big)
    assert str(a.dtype) in ("int64", "<class 'jax.numpy.int64'>") or \
        a.asnumpy().dtype == np.int64
    out = (a + 1).asnumpy()
    assert out.tolist() == [2**31 + 8, 2**33 + 1]


def test_int64_argmax_and_shape_props():
    x = nd.zeros((3, 5))
    x[2, 4] = 1.0
    flat_idx = int(nd.argmax(x.reshape((-1,)), axis=0).asnumpy())
    assert flat_idx == 14
    assert x.size == 15 and isinstance(x.size, int)


def test_int64_embedding_indices():
    emb = nd.Embedding(nd.array(np.array([[3, 1]], dtype=np.int64)),
                       nd.array(np.eye(5, dtype=np.float32)),
                       input_dim=5, output_dim=5)
    got = emb.asnumpy()[0]
    assert got[0].argmax() == 3 and got[1].argmax() == 1


@pytest.mark.skipif(not NIGHTLY, reason="nightly tier: allocates >4 GB")
def test_large_array_over_int32_elements():
    # 2^31 + 8 elements of int8 ≈ 2 GB; indexing the tail exercises
    # 64-bit flat offsets end-to-end
    n = 2**31 + 8
    a = nd.zeros((n,), dtype="int8")
    a[n - 1] = 1
    assert int(a[n - 1].asnumpy()) == 1
    assert int(a[n - 2].asnumpy()) == 0
    assert int(nd.sum(a.astype("int64")).asnumpy()) == 1
