"""Pallas kernel numerics (interpret mode on CPU).

Mirrors the reference's cross-backend golden harness
(tests/python/gpu/test_operator_gpu.py check_consistency): the fused
kernel path is compared against the plain jnp/XLA lowering.
"""
import jax
import jax.numpy as jnp
import numpy as np

from mxnet_tpu.test_utils import device_tols, _on_tpu
RTOL, ATOL = device_tols("float32")
# near-zero grad rows (layernorm, masked attention) need absolute
# headroom on-chip; the CPU/interpret golden path keeps the tight floor
# so interpreted-kernel numeric regressions stay visible
ATOL = max(ATOL, 1e-4 if _on_tpu() else 1e-5)
import pytest

from mxnet_tpu.ops.pallas.flash_attention import (flash_attention,
                                                  flash_attention_with_lse)
from mxnet_tpu.ops.pallas.layer_norm import layer_norm_fused
from mxnet_tpu.ops.pallas.softmax_xent import softmax_xent_fused


def _ln_ref(x, g, b, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    v = x.var(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(v + eps) * g + b


def _attn_ref(q, k, v, causal=False):
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        m = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(m, s, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)


@pytest.mark.parametrize("shape", [(37, 96), (8, 3, 128), (130, 768)])
def test_layer_norm_fused_fwd_bwd(shape):
    rng = np.random.RandomState(0)
    d = shape[-1]
    x = jnp.asarray(rng.randn(*shape).astype(np.float32))
    g = jnp.asarray(rng.randn(d).astype(np.float32))
    b = jnp.asarray(rng.randn(d).astype(np.float32))

    out = layer_norm_fused(x, g, b, 1e-5, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ln_ref(x, g, b)),
                               rtol=RTOL, atol=ATOL)

    # weighted sum so per-element grads differ
    w = jnp.asarray(rng.randn(*shape).astype(np.float32))
    gp = jax.grad(lambda x, g, b: (layer_norm_fused(x, g, b, 1e-5, True) * w).sum(),
                  argnums=(0, 1, 2))(x, g, b)
    gr = jax.grad(lambda x, g, b: (_ln_ref(x, g, b) * w).sum(),
                  argnums=(0, 1, 2))(x, g, b)
    for a, c in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=RTOL, atol=ATOL)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sq,skv", [(64, 64), (100, 100), (48, 120)])
def test_flash_attention_fwd_bwd(causal, sq, skv):
    rng = np.random.RandomState(1)
    B, H, D = 2, 3, 64
    q = jnp.asarray(rng.randn(B, H, sq, D).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(B, H, skv, D).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(B, H, skv, D).astype(np.float32))
    # end-aligned causal for sq != skv (KV-cache decode convention,
    # matches the op layer's q_offset wiring)
    q_off = skv - sq if causal else 0

    o = flash_attention(q, k, v, None, causal, q_off, True)
    np.testing.assert_allclose(np.asarray(o),
                               np.asarray(_attn_ref(q, k, v, causal)),
                               rtol=RTOL, atol=ATOL)

    w = jnp.asarray(rng.randn(B, H, sq, D).astype(np.float32))
    gf = jax.grad(lambda q, k, v: (flash_attention(q, k, v, None, causal, q_off, True) * w).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: (_attn_ref(q, k, v, causal) * w).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for a, c in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=RTOL, atol=ATOL)


def test_flash_attention_fully_masked_rows():
    # sq > skv causal (negative q_offset — the KV-cache shape
    # op_impl_nn.flash_attention_op produces): rows before the first
    # visible key must return exactly zero (not an average of V), with
    # lse at the -inf sentinel and zero gradients through those rows.
    rng = np.random.RandomState(4)
    B, H, sq, skv, D = 1, 2, 120, 48, 32
    q = jnp.asarray(rng.randn(B, H, sq, D).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(B, H, skv, D).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(B, H, skv, D).astype(np.float32))
    q_off = skv - sq
    nm = sq - skv  # rows 0..nm-1 see no keys

    o, lse = flash_attention_with_lse(q, k, v, causal=True,
                                      q_offset=q_off, interpret=True)
    assert np.all(np.asarray(o[:, :, :nm]) == 0.0)
    assert np.all(np.asarray(lse[:, :, :nm]) <= -1e29)

    # visible region matches the jnp fallback (op_impl_nn masking)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    m = jnp.tril(jnp.ones((sq, skv), bool), k=skv - sq)
    p = jax.nn.softmax(jnp.where(m, s, -1e30), -1)
    p = jnp.where(m.any(-1, keepdims=True), p, 0.0)
    ref = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    np.testing.assert_allclose(np.asarray(o[:, :, nm:]),
                               np.asarray(ref[:, :, nm:]),
                               rtol=RTOL, atol=ATOL)

    w = jnp.asarray(rng.randn(B, H, sq, D).astype(np.float32))
    g = jax.grad(lambda q, k, v: (flash_attention(
        q, k, v, None, True, q_off, True) * w).sum(),
        argnums=(0, 1, 2))(q, k, v)
    assert np.all(np.asarray(g[0][:, :, :nm]) == 0.0)
    for a in g:
        assert np.all(np.isfinite(np.asarray(a)))


def test_flash_attention_lse():
    rng = np.random.RandomState(2)
    B, H, S, D = 1, 2, 100, 32
    q = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    o, lse = flash_attention_with_lse(q, k, v, causal=True, interpret=True)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    m = jnp.tril(jnp.ones((S, S), bool))
    ref = jax.scipy.special.logsumexp(jnp.where(m, s, -np.inf), axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref),
                               rtol=RTOL, atol=ATOL)


@pytest.mark.parametrize("n,v", [(50, 1000), (64, 128), (33, 513)])
def test_softmax_xent_fused(n, v):
    rng = np.random.RandomState(3)
    logits = jnp.asarray(rng.randn(n, v).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, v, n).astype(np.int32))
    loss = softmax_xent_fused(logits, labels, True)
    ref = -jax.nn.log_softmax(logits)[jnp.arange(n), labels]
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref),
                               rtol=RTOL, atol=ATOL)

    w = jnp.asarray(rng.randn(n).astype(np.float32))
    gx = jax.grad(lambda l: (softmax_xent_fused(l, labels, True) * w).sum())(logits)
    gr = jax.grad(lambda l: ((-jax.nn.log_softmax(l)[jnp.arange(n), labels]) * w).sum())(logits)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gr),
                               rtol=RTOL, atol=ATOL)


def test_op_dispatch_interpret(monkeypatch):
    """mx.nd ops route through the Pallas path under the interpret env."""
    monkeypatch.setenv("MXNET_TPU_PALLAS_INTERPRET", "1")
    import mxnet_tpu as mx

    rng = np.random.RandomState(4)
    x = mx.nd.array(rng.randn(10, 64).astype(np.float32))
    g = mx.nd.array(rng.randn(64).astype(np.float32))
    b = mx.nd.array(rng.randn(64).astype(np.float32))
    out = mx.nd.LayerNorm(x, g, b, axis=-1, eps=1e-5)
    ref = _ln_ref(x._data, g._data, b._data)
    np.testing.assert_allclose(out.asnumpy(), np.asarray(ref),
                               rtol=RTOL, atol=ATOL)

    # autograd through the fused op
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd.LayerNorm(x, g, b, axis=-1, eps=1e-5)
        loss = (y * y).sum()
    loss.backward()
    gr = jax.grad(lambda x: (_ln_ref(x, g._data, b._data) ** 2).sum())(x._data)
    np.testing.assert_allclose(x.grad.asnumpy(), np.asarray(gr),
                               rtol=RTOL, atol=ATOL)

    q = mx.nd.array(rng.randn(2, 2, 32, 16).astype(np.float32))
    k = mx.nd.array(rng.randn(2, 2, 32, 16).astype(np.float32))
    v = mx.nd.array(rng.randn(2, 2, 32, 16).astype(np.float32))
    o = mx.nd.flash_attention(q, k, v, causal=True)
    ref = _attn_ref(q._data, k._data, v._data, causal=True)
    np.testing.assert_allclose(o.asnumpy(), np.asarray(ref),
                               rtol=RTOL, atol=ATOL)


def test_flash_attention_under_high_matmul_precision():
    """Regression: the process-wide jax_default_matmul_precision='high'
    (set by mxnet_tpu/__init__.py for f32 parity) must not leak into the
    kernel's dots — Mosaic rejects HIGH ('Unsupported dot precision').
    Kernel dots carry explicit static precision chosen per input dtype."""
    from mxnet_tpu.ops.pallas.flash_attention import _dot_precision
    assert _dot_precision(jnp.float32) == jax.lax.Precision.HIGHEST
    assert _dot_precision(jnp.bfloat16) == jax.lax.Precision.DEFAULT
    k = jax.random.PRNGKey(3)
    q = jax.random.normal(k, (1, 2, 64, 32), jnp.float32)
    kk = jax.random.normal(jax.random.fold_in(k, 1), (1, 2, 64, 32),
                           jnp.float32)
    v = jax.random.normal(jax.random.fold_in(k, 2), (1, 2, 64, 32),
                          jnp.float32)
    # on the real chip run NON-interpreted so Mosaic actually compiles
    # the dots (interpret mode cannot reproduce the crash); the CPU
    # suite can only exercise the interpreter
    with jax.default_matmul_precision("high"):
        o = flash_attention(q, kk, v, causal=True, interpret=not _on_tpu())
    ref = _attn_ref(q, kk, v, causal=True)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=RTOL, atol=ATOL)


def test_flash_attention_large_asymmetric_blocks(monkeypatch):
    """seq 384 with FORCED 256x128 tiles: a genuine multi-block grid
    with bq != bk and causal block-skip — golden vs jnp. (The defaults
    clamp to one 384x384 block at this length, which would not cover
    the multi-block path the 512-cap defaults enable on-chip.)"""
    monkeypatch.setenv("MXNET_TPU_FLASH_BLOCK_Q", "256")
    monkeypatch.setenv("MXNET_TPU_FLASH_BLOCK_K", "128")
    rng = np.random.RandomState(6)
    B, H, S, D = 1, 2, 384, 64
    q = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    for causal in (False, True):
        o = flash_attention(q, k, v, None, causal, 0, True)
        np.testing.assert_allclose(
            np.asarray(o), np.asarray(_attn_ref(q, k, v, causal)),
            rtol=RTOL, atol=ATOL)
    w = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    g = jax.grad(lambda q, k, v: (flash_attention(
        q, k, v, None, True, 0, True) * w).sum(), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: (_attn_ref(q, k, v, True) * w).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for a, c in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=RTOL, atol=ATOL)


def test_flash_attention_fused_vs_split_bwd(monkeypatch):
    """The single-pass backward (default) and the two-kernel path
    (MXNET_TPU_FLASH_SPLIT_BWD=1) must produce identical gradients on a
    genuine multi-block grid — nq=2, nk=2 at 128x128 tiles (nk=2 is the
    LARGEST grid the fused path accepts before the nk>2 dq-partial
    fallback reroutes to split; S=200 with ragged padding exercises the
    fused kernel's multi-k dq partial sum and the causal invisible-pair
    zeroing branch), causal and not."""
    monkeypatch.setenv("MXNET_TPU_FLASH_BLOCK_Q", "128")
    monkeypatch.setenv("MXNET_TPU_FLASH_BLOCK_K", "128")
    rng = np.random.RandomState(7)
    B, H, S, D = 1, 2, 200, 64
    q = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    w = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))

    for causal in (False, True):
        def f(q, k, v):
            return (flash_attention(q, k, v, None, causal, 0, True) * w).sum()

        monkeypatch.setenv("MXNET_TPU_FLASH_SPLIT_BWD", "0")
        g_fused = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        monkeypatch.setenv("MXNET_TPU_FLASH_SPLIT_BWD", "1")
        g_split = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        monkeypatch.delenv("MXNET_TPU_FLASH_SPLIT_BWD")
        gr = jax.grad(lambda q, k, v: (_attn_ref(q, k, v, causal) * w).sum(),
                      argnums=(0, 1, 2))(q, k, v)
        for a, c, r in zip(g_fused, g_split, gr):
            # fused vs split: same math, same f32 accumulation order up
            # to the cross-k partial sum — tight tolerance
            np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                       rtol=1e-6, atol=1e-6)
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=RTOL, atol=ATOL)


@pytest.mark.skipif(not _on_tpu(), reason="memory analysis needs the real chip")
def test_flash_attention_o_of_s_memory():
    """The flash kernel's compiled temp footprint must be O(S) — far
    below the composed path's materialized (B,H,S,S) score block (the
    ring fold relies on this per step: VERDICT r2 #6 'O(C) per-step
    memory')."""
    B, H, S, D = 1, 8, 2048, 64
    q = jnp.zeros((B, H, S, D), jnp.bfloat16)
    score_bytes = B * H * S * S * 4  # one f32 (S,S) block per (b,h)

    flash = jax.jit(lambda q, k, v: flash_attention(q, k, v, None, True, 0))
    ref = jax.jit(lambda q, k, v: _attn_ref(q, k, v, True))
    m_flash = flash.lower(q, q, q).compile().memory_analysis()
    m_ref = ref.lower(q, q, q).compile().memory_analysis()
    if m_flash is None or m_ref is None:
        pytest.skip("memory_analysis unavailable on this backend")
    assert m_flash.temp_size_in_bytes < score_bytes / 4, (
        m_flash.temp_size_in_bytes, score_bytes)
    assert m_ref.temp_size_in_bytes > m_flash.temp_size_in_bytes * 4, (
        m_ref.temp_size_in_bytes, m_flash.temp_size_in_bytes)


def test_lstm_layer_fused_vs_scan(monkeypatch):
    """Whole-sequence fused LSTM kernel (interpret mode) vs the
    lax.scan cell: outputs, final states, and every gradient (gin,
    W_h2h, h0, c0 — including cotangents on the final states) agree."""
    monkeypatch.setenv("MXNET_TPU_PALLAS_INTERPRET", "1")
    monkeypatch.setenv("MXNET_TPU_FUSED_LSTM", "1")
    from mxnet_tpu.ops.pallas.lstm import lstm_layer_fused

    rng = np.random.RandomState(21)
    T, N, H = 7, 8, 24
    gin = jnp.asarray(rng.randn(T, N, 4 * H).astype(np.float32)) * 0.4
    w = jnp.asarray(rng.randn(H, 4 * H).astype(np.float32)) * 0.3
    h0 = jnp.asarray(rng.randn(N, H).astype(np.float32)) * 0.5
    c0 = jnp.asarray(rng.randn(N, H).astype(np.float32)) * 0.5

    def scan_ref(gin, w, h0, c0):
        def step(carry, gx):
            h, c = carry
            z = gx + h @ w
            i, f, g, o = jnp.split(z, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return (h_new, c_new), (h_new, c_new)
        (hl, cl), (out, cseq) = jax.lax.scan(step, (h0, c0), gin)
        return out, cseq

    out, cseq = lstm_layer_fused(gin, w, h0, c0)
    ro, rc = scan_ref(gin, w, h0, c0)
    # RTOL/ATOL are device-aware (real-chip f32 dots round differently
    # between the interpreted kernel and the scan reference)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ro),
                               rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(np.asarray(cseq), np.asarray(rc),
                               rtol=RTOL, atol=ATOL)

    # weighted loss touching the full sequence AND both final states so
    # every cotangent path (dout, dcseq, incl. [-1] entries) is live
    wo = jnp.asarray(rng.randn(T, N, H).astype(np.float32))
    wc = jnp.asarray(rng.randn(N, H).astype(np.float32))

    def loss_fused(gin, w, h0, c0):
        out, cseq = lstm_layer_fused(gin, w, h0, c0)
        return (out * wo).sum() + (cseq[-1] * wc).sum() + out[-1].sum()

    def loss_ref(gin, w, h0, c0):
        out, cseq = scan_ref(gin, w, h0, c0)
        return (out * wo).sum() + (cseq[-1] * wc).sum() + out[-1].sum()

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(gin, w, h0, c0)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(gin, w, h0, c0)
    for a, c in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=2e-4, atol=2e-4)


def test_lstm_fused_bidirectional_matches_scan(monkeypatch):
    """Bidirectional gluon LSTM: the fused kernel path must agree with
    the lax.scan path on outputs AND final states (the reverse
    direction's h_last is the last PROCESSED step, not out[-1] after
    the flip back to forward-time order)."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    rng = np.random.RandomState(31)
    x = rng.randn(5, 4, 12).astype(np.float32)  # (T, N, I), TNC

    def run(fused):
        if fused:
            monkeypatch.setenv("MXNET_TPU_PALLAS_INTERPRET", "1")
            monkeypatch.setenv("MXNET_TPU_FUSED_LSTM", "1")
        else:
            monkeypatch.delenv("MXNET_TPU_PALLAS_INTERPRET", raising=False)
            monkeypatch.delenv("MXNET_TPU_FUSED_LSTM", raising=False)
        mx.random.seed(7)
        net = mx.gluon.rnn.LSTM(8, num_layers=2, bidirectional=True)
        net.initialize()
        out, (h, c) = net(nd.array(x),
                          net.begin_state(batch_size=4))
        return out.asnumpy(), h.asnumpy(), c.asnumpy()

    o_s, h_s, c_s = run(False)
    o_f, h_f, c_f = run(True)
    np.testing.assert_allclose(o_f, o_s, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(h_f, h_s, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(c_f, c_s, rtol=1e-5, atol=1e-5)


def _attn_len_ref(q, k, v, kv_lens, causal=False):
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / np.sqrt(d)
    mask = jnp.arange(k.shape[2])[None, None, None, :] \
        < kv_lens[:, None, None, None]
    if causal:
        cm = jnp.tril(jnp.ones((s.shape[-2], s.shape[-1]), bool),
                      k=s.shape[-1] - s.shape[-2])
        mask = jnp.logical_and(mask, cm)
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.broadcast_to(mask, s.shape).any(-1, keepdims=True),
                  p, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("split_bwd", [False, True])
def test_flash_attention_variable_length(causal, split_bwd, monkeypatch):
    """Per-example kv_lens (VERDICT r3 #2): forward and all three
    gradients match the masked composed softmax, on both backward
    paths, with lengths crossing tile boundaries and the loss masking
    padded positions (the contract under which padded-row grads vanish
    identically)."""
    if split_bwd:
        monkeypatch.setenv("MXNET_TPU_FLASH_SPLIT_BWD", "1")
    rng = np.random.RandomState(7)
    B, H, S, D = 3, 2, 40, 16
    q = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    kv_lens = jnp.asarray([40, 17, 0], jnp.int32)  # incl. an EMPTY example

    o = flash_attention(q, k, v, None, causal, 0, True, kv_lens)
    ref = _attn_len_ref(q, k, v, kv_lens, causal)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=RTOL, atol=ATOL)
    assert np.all(np.asarray(o[2]) == 0.0)  # empty example -> exact zeros

    wmask = (jnp.arange(S)[None, :] < kv_lens[:, None]) \
        .astype(jnp.float32)[:, None, :, None]
    w = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * wmask
    gf = jax.grad(lambda q, k, v: (flash_attention(
        q, k, v, None, causal, 0, True, kv_lens) * w).sum(),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: (_attn_len_ref(
        q, k, v, kv_lens, causal) * w).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, c in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=RTOL, atol=ATOL)
    # keys past each example's length get identically-zero dk/dv
    for g in gf[1:]:
        arr = np.asarray(g)
        for b_ in range(B):
            assert np.all(arr[b_, :, int(kv_lens[b_]):] == 0.0)


def _attn_seg_ref(q, k, v, seg, kv_lens=None, causal=False):
    """Composed masked softmax with block-diagonal segment isolation —
    the golden the packed kernel must match exactly."""
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / np.sqrt(d)
    mask = seg[:, None, :, None] == seg[:, None, None, :]
    if kv_lens is not None:
        mask = jnp.logical_and(
            mask, jnp.arange(k.shape[2])[None, None, None, :]
            < kv_lens[:, None, None, None])
    if causal:
        cm = jnp.tril(jnp.ones((s.shape[-2], s.shape[-1]), bool))
        mask = jnp.logical_and(mask, cm)
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.broadcast_to(mask, s.shape).any(-1, keepdims=True),
                  p, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


def _packed_case(rng, B, H, S, D):
    q = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    seg = np.zeros((B, S), np.int32)
    # row 0: three segments + padding; row 1: one long segment + padding
    b0 = [0, S // 3, S // 2, int(S * 0.9)]
    seg[0, b0[0]:b0[1]] = 1
    seg[0, b0[1]:b0[2]] = 2
    seg[0, b0[2]:b0[3]] = 3
    seg[1, :int(S * 0.8)] = 1
    lens = jnp.asarray([int(S * 0.9), int(S * 0.8)], jnp.int32)
    return q, k, v, jnp.asarray(seg), lens


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("split_bwd", [False, True])
def test_flash_attention_segment_isolation(causal, split_bwd, monkeypatch):
    """Sequence packing: per-token segment_ids make attention exactly
    block-diagonal — forward and all three gradients match the composed
    masked softmax on BOTH backward paths, including causal mode and
    padding slots (id 0) that must emit exact zeros."""
    if split_bwd:
        monkeypatch.setenv("MXNET_TPU_FLASH_SPLIT_BWD", "1")
    rng = np.random.RandomState(11)
    B, H, S, D = 2, 2, 40, 16
    q, k, v, seg, lens = _packed_case(rng, B, H, S, D)

    o = flash_attention(q, k, v, None, causal, 0, True, lens, seg)
    ref = _attn_seg_ref(q, k, v, seg, lens, causal)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=RTOL, atol=ATOL)
    # padding rows (segment 0 past each row's used length) -> exact 0
    pad = np.asarray(seg) == 0
    assert np.all(np.asarray(o)[pad[:, None, :].repeat(H, 1)] == 0.0)

    # loss masks padding (the packed-training contract)
    w = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) \
        * (np.asarray(seg)[:, None, :, None] > 0)
    gf = jax.grad(lambda q, k, v: (flash_attention(
        q, k, v, None, causal, 0, True, lens, seg) * w).sum(),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: (_attn_seg_ref(
        q, k, v, seg, lens, causal) * w).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, c in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=RTOL, atol=ATOL)
    # padding slots get identically-zero dk/dv
    for g in gf[1:]:
        assert np.all(np.asarray(g)[pad[:, None, :, None]
                                    .repeat(H, 1).repeat(D, 3)] == 0.0)


def test_flash_attention_segment_multiblock(monkeypatch):
    """Multi-tile packed grid (forced 64x128 tiles over S=512): the
    SMEM segment-range whole-block skip and the lane-broadcast equality
    mask must agree with the composed reference across tile boundaries;
    128<block_k exercises the pltpu.repeat id layout."""
    monkeypatch.setenv("MXNET_TPU_FLASH_BLOCK_Q", "64")
    monkeypatch.setenv("MXNET_TPU_FLASH_BLOCK_K", "128")
    rng = np.random.RandomState(12)
    B, H, S, D = 2, 2, 512, 32
    q, k, v, seg, lens = _packed_case(rng, B, H, S, D)
    for causal in (False, True):
        o = flash_attention(q, k, v, None, causal, 0, True, lens, seg)
        ref = _attn_seg_ref(q, k, v, seg, lens, causal)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=RTOL, atol=ATOL)
    w = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) \
        * (np.asarray(seg)[:, None, :, None] > 0)
    gf = jax.grad(lambda q, k, v: (flash_attention(
        q, k, v, None, False, 0, True, lens, seg) * w).sum(),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: (_attn_seg_ref(
        q, k, v, seg, lens, False) * w).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, c in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=RTOL, atol=ATOL)
    # the repeat branch (block_k > 128) on the same case
    monkeypatch.setenv("MXNET_TPU_FLASH_BLOCK_K", "256")
    o = flash_attention(q, k, v, None, False, 0, True, lens, seg)
    np.testing.assert_allclose(
        np.asarray(o), np.asarray(_attn_seg_ref(q, k, v, seg, lens, False)),
        rtol=RTOL, atol=ATOL)


def test_flash_attention_segments_reject_cross_attention():
    """segment_ids with Sq != Skv (KV-cache decode) has no packed
    meaning — the kernel refuses instead of mis-masking."""
    rng = np.random.RandomState(13)
    q = jnp.asarray(rng.randn(1, 1, 8, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 1, 16, 8).astype(np.float32))
    seg = jnp.ones((1, 16), jnp.int32)
    with pytest.raises(ValueError):
        flash_attention(q, k, k, None, False, 0, True, None, seg)


def test_flash_attention_op_segment_dispatch(monkeypatch):
    """mx.nd.flash_attention(q, k, v, valid_len, segment_ids) routes
    the ids to the kernel AND the jnp fallback identically, and the
    packed output for each segment matches that segment run alone."""
    monkeypatch.setenv("MXNET_TPU_PALLAS_INTERPRET", "1")
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    rng = np.random.RandomState(14)
    B, H, S, D = 1, 2, 32, 8
    q = mx.nd.array(rng.randn(B, H, S, D).astype(np.float32) * 0.3)
    k = mx.nd.array(rng.randn(B, H, S, D).astype(np.float32) * 0.3)
    v = mx.nd.array(rng.randn(B, H, S, D).astype(np.float32))
    seg_np = np.zeros((B, S), np.int32)
    seg_np[0, :12] = 1
    seg_np[0, 12:26] = 2
    seg = mx.nd.array(seg_np, dtype="int32")
    vl = mx.nd.array(np.array([26], np.float32))

    out_kernel = nd.flash_attention(q, k, v, vl, seg)
    monkeypatch.setenv("MXNET_TPU_DISABLE_PALLAS", "1")
    out_jnp = nd.flash_attention(q, k, v, vl, seg)
    monkeypatch.delenv("MXNET_TPU_DISABLE_PALLAS")
    np.testing.assert_allclose(out_kernel.asnumpy(), out_jnp.asnumpy(),
                               rtol=RTOL, atol=ATOL)

    # each packed segment == the same tokens run alone (unpacked golden)
    for lo, hi in ((0, 12), (12, 26)):
        alone = nd.flash_attention(
            q[:, :, lo:hi], k[:, :, lo:hi], v[:, :, lo:hi])
        np.testing.assert_allclose(out_kernel.asnumpy()[:, :, lo:hi],
                                   alone.asnumpy(), rtol=RTOL, atol=ATOL)


def test_flash_attention_op_valid_len_dispatch(monkeypatch):
    """mx.nd.flash_attention(q, k, v, valid_len) routes the length to
    the kernel AND the jnp fallback identically."""
    monkeypatch.setenv("MXNET_TPU_PALLAS_INTERPRET", "1")
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    rng = np.random.RandomState(8)
    B, H, S, D = 2, 2, 24, 8
    q = mx.nd.array(rng.randn(B, H, S, D).astype(np.float32) * 0.3)
    k = mx.nd.array(rng.randn(B, H, S, D).astype(np.float32) * 0.3)
    v = mx.nd.array(rng.randn(B, H, S, D).astype(np.float32))
    vl = mx.nd.array(np.array([24, 9], np.float32))

    out_kernel = nd.flash_attention(q, k, v, vl)
    monkeypatch.setenv("MXNET_TPU_DISABLE_PALLAS", "1")
    out_jnp = nd.flash_attention(q, k, v, vl)
    np.testing.assert_allclose(out_kernel.asnumpy(), out_jnp.asnumpy(),
                               rtol=RTOL, atol=ATOL)
    # sanity: the length actually masks (row attending to only 9 keys
    # differs from the unmasked result)
    monkeypatch.delenv("MXNET_TPU_DISABLE_PALLAS")
    full = nd.flash_attention(q, k, v)
    assert np.abs(out_kernel.asnumpy()[1] - full.asnumpy()[1]).max() > 1e-3


def test_transformer_valid_length_end_to_end(monkeypatch):
    """BERT-style MultiHeadAttention with valid_length: flash path ==
    composed attention_length_mask path, gradients included."""
    monkeypatch.setenv("MXNET_TPU_PALLAS_INTERPRET", "1")
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon.nn.transformer import MultiHeadAttention

    rng = np.random.RandomState(9)
    B, S, C, Hd = 2, 20, 32, 4
    mx.random.seed(11)
    attn = MultiHeadAttention(C, Hd)
    x = mx.nd.array(rng.randn(B, S, C).astype(np.float32))
    attn.initialize(init=mx.initializer.Xavier())
    vl = mx.nd.array(np.array([20, 0], np.float32))

    wmask = mx.nd.array((np.arange(S)[None, :, None]
                         < np.array([20, 0])[:, None, None])
                        .astype(np.float32))

    x.attach_grad()
    with autograd.record():
        out_flash = attn(x, None, vl)
        (out_flash * wmask).sum().backward()
    g_flash = x.grad.asnumpy().copy()

    # force the composed path via a zero additive mask (same math)
    zero_mask = mx.nd.zeros((B, 1, S, S))
    x2 = mx.nd.array(x.asnumpy())
    x2.attach_grad()
    with autograd.record():
        out_comp = attn(x2, zero_mask, vl)
        (out_comp * wmask).sum().backward()

    # FULL-output agreement, including the empty (valid_len == 0)
    # example: both paths must emit the zero-attention result there
    # (attention_zero_empty_rows on the composed path, l==0 guard in
    # the kernel)
    np.testing.assert_allclose(out_flash.asnumpy(), out_comp.asnumpy(),
                               rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(g_flash, x2.grad.asnumpy(),
                               rtol=RTOL, atol=ATOL)
