"""Tail-latency attribution (ISSUE 19): per-request critical paths
through the decode loop and the fleet /whyslow engine.

- stamp/extractor unit goldens: sched_gap backfill, innermost-wins
  overlap resolution, clipping, the explicit ``unattributed``
  remainder (attributed + unattributed == wall by construction);
- span-tree → :func:`critical_path` golden incl. the legacy
  synthesized children (``serving/forward`` → ``compute``);
- live decode engine: a STREAMED request's ``InferenceFuture.
  breakdown`` decomposes its own wall gap-free, and the engine's
  ``/whyslow`` aggregator saw it;
- router relay identity: the engine-computed breakdown arrives
  UNCHANGED whether the dispatch rode the binary wire or chunked-JSON
  HTTP, and the router's own ``dispatch`` stage lands in the router's
  aggregator — never inside the engine's decomposition;
- alert payloads: a firing latency rule attaches the owner's
  top-stage attribution with a RETRIEVABLE exemplar trace;
- the ``MXNET_TPU_ATTRIBUTION=0`` disabled path: no stamp lists, no
  breakdowns, no stage metric families, stamps are no-ops.

CPU-only: stub/toy models, scaled SLO windows.
"""
import time

import numpy as np
import pytest

import mxnet_tpu  # noqa: F401  (configures jax for the CPU mesh)
from mxnet_tpu.telemetry import alerts as alerts_mod
from mxnet_tpu.telemetry import attribution as _attribution
from mxnet_tpu.telemetry import slo as slo_mod
from mxnet_tpu.telemetry import spans
from mxnet_tpu.telemetry.registry import MetricsRegistry


@pytest.fixture(autouse=True)
def _fresh_attribution():
    """Drop aggregators + cached gates around every test so one
    test's observations (or a disabled-path override) never leak into
    the next; restore the span recorder's slow threshold too."""
    slow_ms = spans.RECORDER.slow_ms
    _attribution.reset()
    yield
    _attribution.reset()
    spans.RECORDER.slow_ms = slow_ms


class _Req:
    """The slots :func:`attribution.stamp` needs, nothing else."""

    def __init__(self):
        self.stages = []
        self.t_activity = None
        self.trace_id = "t-unit"
        self.span = None


# ---------------------------------------------------------------------------
# stamp + extractor unit goldens
# ---------------------------------------------------------------------------

def test_stamp_gap_backfill_and_innermost_wins():
    req = _Req()
    t0 = 100.0
    _attribution.stamp(req, "wfq_wait", t0, t0 + 0.010, span=False)
    # 10ms idle before the prefill: backfilled as an explicit
    # sched_gap interval, not smeared into unattributed
    _attribution.stamp(req, "prefill", t0 + 0.020, t0 + 0.050,
                       span=False)
    _attribution.stamp(req, "decode_iter", t0 + 0.050, t0 + 0.100,
                       span=False)
    # nested COW copy inside the iteration: innermost wins, and the
    # activity clock must NOT rewind (no phantom gap after it)
    _attribution.stamp(req, "cow_copy", t0 + 0.060, t0 + 0.070,
                       span=False)
    _attribution.stamp(req, "decode_iter", t0 + 0.100, t0 + 0.120,
                       span=False)
    assert ("sched_gap", t0 + 0.010, t0 + 0.020) in req.stages

    bd = _attribution.breakdown_from_stamps(req.stages, t0, t0 + 0.120,
                                            trace_id=req.trace_id)
    assert bd["trace_id"] == "t-unit"
    assert bd["wall_ms"] == pytest.approx(120.0, abs=1e-6)
    per = {s["stage"]: s["ms"] for s in bd["stages"]}
    assert per["wfq_wait"] == pytest.approx(10.0, abs=1e-3)
    assert per["sched_gap"] == pytest.approx(10.0, abs=1e-3)
    assert per["prefill"] == pytest.approx(30.0, abs=1e-3)
    # 70ms of iteration residency minus the 10ms billed to the copy
    assert per["decode_iter"] == pytest.approx(60.0, abs=1e-3)
    assert per["cow_copy"] == pytest.approx(10.0, abs=1e-3)
    assert bd["unattributed_ms"] == pytest.approx(0.0, abs=1e-3)
    assert bd["attributed_ms"] + bd["unattributed_ms"] == \
        pytest.approx(bd["wall_ms"], abs=0.01)
    # ordered by first occurrence on the timeline
    assert [s["stage"] for s in bd["stages"]] == \
        ["wfq_wait", "sched_gap", "prefill", "decode_iter", "cow_copy"]
    # shares are of wall and sum to ~1 with nothing unattributed
    assert sum(s["share"] for s in bd["stages"]) == \
        pytest.approx(1.0, abs=0.01)


def test_breakdown_clips_to_wall_and_reports_holes():
    # first stamp overhangs the wall start, last overhangs the end,
    # and a 30ms hole sits between them: clipped + explicit remainder
    stamps = [("queue", 99.90, 100.02),
              ("compute", 100.05, 100.12)]
    bd = _attribution.breakdown_from_stamps(stamps, 100.0, 100.10)
    per = {s["stage"]: s["ms"] for s in bd["stages"]}
    assert per["queue"] == pytest.approx(20.0, abs=1e-3)
    assert per["compute"] == pytest.approx(50.0, abs=1e-3)
    assert bd["unattributed_ms"] == pytest.approx(30.0, abs=1e-3)
    assert bd["attributed_ms"] + bd["unattributed_ms"] == \
        pytest.approx(bd["wall_ms"], abs=0.01)
    # degenerate wall: empty decomposition, never a crash
    empty = _attribution.breakdown_from_stamps(stamps, 100.0, 100.0)
    assert empty["stages"] == [] and empty["wall_ms"] == 0.0


def test_stamp_rejects_unregistered_stage_and_noops_when_off():
    req = _Req()
    with pytest.raises(ValueError):
        _attribution.stamp(req, "warmupp", 0.0, 1.0, span=False)
    off = _Req()
    off.stages = None           # the disabled-request shape
    _attribution.stamp(off, "decode_iter", 0.0, 1.0, span=False)
    assert off.stages is None and off.t_activity is None


# ---------------------------------------------------------------------------
# span tree -> critical path
# ---------------------------------------------------------------------------

def test_critical_path_span_tree_golden():
    spans_list = [
        {"span_id": "r", "trace_id": "t1", "name": "serving/request",
         "ts_us": 0, "dur_us": 100_000},
        {"span_id": "a", "parent_id": "r", "trace_id": "t1",
         "name": "stage/wfq_wait", "ts_us": 0, "dur_us": 10_000},
        {"span_id": "b", "parent_id": "r", "trace_id": "t1",
         "name": "stage/decode_iter", "ts_us": 10_000,
         "dur_us": 60_000},
        # nested under the iteration: innermost wins
        {"span_id": "c", "parent_id": "b", "trace_id": "t1",
         "name": "stage/cow_copy", "ts_us": 20_000, "dur_us": 10_000},
        # legacy synthesized child: maps onto the canonical stage
        {"span_id": "d", "parent_id": "r", "trace_id": "t1",
         "name": "serving/forward", "ts_us": 70_000, "dur_us": 20_000},
        # structure, not a stage: ignored even though it spans the wall
        {"span_id": "e", "parent_id": "r", "trace_id": "t1",
         "name": "decode/loop", "ts_us": 0, "dur_us": 100_000},
        # a stage span from ANOTHER tree (unresolvable parent): must
        # not leak into this decomposition
        {"span_id": "x", "parent_id": "zz", "trace_id": "t9",
         "name": "stage/prefill", "ts_us": 0, "dur_us": 50_000},
    ]
    bd = _attribution.critical_path(spans_list)
    assert bd["trace_id"] == "t1"
    assert bd["wall_ms"] == pytest.approx(100.0)
    per = {s["stage"]: s["ms"] for s in bd["stages"]}
    assert per == {"wfq_wait": pytest.approx(10.0),
                   "decode_iter": pytest.approx(50.0),
                   "cow_copy": pytest.approx(10.0),
                   "compute": pytest.approx(20.0)}
    assert "prefill" not in per
    assert bd["unattributed_ms"] == pytest.approx(10.0)
    assert bd["attributed_ms"] + bd["unattributed_ms"] == \
        pytest.approx(bd["wall_ms"], abs=0.01)
    assert _attribution.critical_path([]) == {
        "wall_ms": 0.0, "stages": [], "attributed_ms": 0.0,
        "unattributed_ms": 0.0}


# ---------------------------------------------------------------------------
# live decode engine: streamed breakdown + /whyslow
# ---------------------------------------------------------------------------

def _mk_model(**kw):
    from mxnet_tpu.serving import PagedCausalLM

    args = dict(vocab=64, units=32, layers=2, heads=4, max_len=128,
                seed=7)
    args.update(kw)
    return PagedCausalLM(**args)


def _mk_engine(model=None, **kw):
    from mxnet_tpu.serving import DecodeEngine

    args = dict(prefill_bucket_lens=(8, 16), max_rows=4, page_size=8,
                n_pages=24, max_new_tokens=6)
    args.update(kw)
    return DecodeEngine(model if model is not None else _mk_model(),
                        **args)


def test_decode_streamed_breakdown_sums_to_wall():
    with _mk_engine(engine_id="bd0") as eng:
        fut = eng.submit([1, 2, 3, 4], max_new_tokens=6, stream=True)
        parts = list(fut.stream(timeout=60))
        out = fut.result(timeout=0)
        assert [p["token"] for p in parts] == np.asarray(out).tolist()

        bd = fut.breakdown
        assert bd is not None, "no breakdown on a completed future"
        names = [s["stage"] for s in bd["stages"]]
        assert set(names) <= set(_attribution.STAGES)
        assert "decode_iter" in names
        assert "wfq_wait" in names
        assert bd["trace_id"]
        # gap-free by construction...
        assert bd["attributed_ms"] + bd["unattributed_ms"] == \
            pytest.approx(bd["wall_ms"], abs=0.05)
        # ...and the stages actually cover the wall (the bench leg
        # holds the aggregate to >=95%; one quiet request clears 90%)
        assert bd["attributed_ms"] >= 0.9 * bd["wall_ms"], bd

        # the engine's /whyslow aggregator folded the same request in
        ws = eng.whyslow()
        assert ws["owner"] == "bd0" and ws["requests"] >= 1
        assert any(r["stage"] == "decode_iter" for r in ws["stages"])
        assert ws["top"] and ws["top"][0]["share"] > 0


# ---------------------------------------------------------------------------
# router relay: wire vs HTTP identity
# ---------------------------------------------------------------------------

def _drive_router(url, wire, prompt, n=3):
    from mxnet_tpu.serving import ServingRouter

    with ServingRouter({"bdx": url}, poll_interval_s=0.1,
                       wire=wire) as router:
        if wire:
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and not all(
                    row.get("transport") == "wire"
                    for row in router.scoreboard().values()):
                time.sleep(0.05)
            assert all(row.get("transport") == "wire"
                       for row in router.scoreboard().values()), \
                router.scoreboard()
        bds = []
        for _ in range(n):
            fut = router.submit(prompt, max_new_tokens=5)
            fut.result(timeout=60)
            bds.append(fut.breakdown)
        router_ws = router.whyslow()
        router_agg = _attribution.get_aggregator(router.router_id)
        router_snap = (router_agg.snapshot()
                       if router_agg is not None else None)
    return bds, router_ws, router_snap


def test_router_wire_vs_http_breakdown_identity(monkeypatch):
    """The engine-computed decomposition rides the reply VERBATIM on
    both transports — same shape, same canonical stages, summing to
    its own wall — and the router's transit time lands in the
    ROUTER's aggregator as ``dispatch``, never inside the engine's
    breakdown (no double counting in the fleet merge)."""
    monkeypatch.setenv("MXNET_TPU_WIRE", "1")
    with _mk_engine(engine_id="bdx") as eng:
        eng.expose()
        url = f"http://127.0.0.1:{eng._expo.port}"
        wire_bds, wire_ws, wire_snap = _drive_router(url, True,
                                                     [5, 4, 3])
        http_bds, _, http_snap = _drive_router(url, False, [5, 4, 3])

    for bds in (wire_bds, http_bds):
        for bd in bds:
            assert bd is not None
            assert set(bd) == {"wall_ms", "stages", "attributed_ms",
                               "unattributed_ms", "trace_id"}
            names = [s["stage"] for s in bd["stages"]]
            assert set(names) <= set(_attribution.STAGES)
            assert "decode_iter" in names
            # router-side stages never leak into the ENGINE's numbers
            assert "dispatch" not in names and "ha_ack" not in names
            assert bd["attributed_ms"] + bd["unattributed_ms"] == \
                pytest.approx(bd["wall_ms"], abs=0.05)
    # both transports produced the identical decomposition SHAPE
    assert set(wire_bds[0]) == set(http_bds[0])

    # the routers' own aggregators saw ONLY router-owned stages
    for snap in (wire_snap, http_snap):
        assert snap is not None, "router never observed its dispatch"
        stages = {r["stage"] for r in snap["stages"]}
        assert "dispatch" in stages
        assert stages <= {"dispatch", "ha_ack"}
    # and the fleet /whyslow merge stitches engine + router tables
    assert wire_ws.get("fleet") is True
    merged = {r["stage"] for r in wire_ws["stages"]}
    assert "decode_iter" in merged and "dispatch" in merged
    assert wire_ws["top"], wire_ws


# ---------------------------------------------------------------------------
# alert payloads carry attribution with a retrievable trace
# ---------------------------------------------------------------------------

def test_firing_latency_alert_attaches_top_stage_attribution():
    spans.configure(enabled=True, slow_ms=5.0)
    # a real recorded trace: the exemplar the page must link to
    sp = spans.start_span("serving/request", forced=True)
    tid = sp.trace_id
    time.sleep(0.002)
    sp.end()
    assert spans.get_trace(tid) is not None

    reg = MetricsRegistry()
    agg = _attribution.aggregator("ap-owner", registry=reg)
    agg.observe({"wall_ms": 50.0, "trace_id": tid,
                 "stages": [{"stage": "wfq_wait", "ms": 40.0,
                             "share": 0.8},
                            {"stage": "decode_iter", "ms": 8.0,
                             "share": 0.16}],
                 "unattributed_ms": 2.0},
                tenant_class="standard", model="m1", trace_id=tid)

    hist = reg.histogram("mxnet_tpu_t_ap_latency_ms", "t", ("stage",),
                         buckets=(10.0, 100.0))
    ev = slo_mod.SloEvaluator("ap-owner", registry=reg, scale=0.01,
                              budget_s=100.0)
    ev.add(slo_mod.LatencySLO("lat", threshold_ms=10.0,
                              family="mxnet_tpu_t_ap_latency_ms",
                              registry=reg))
    pages = []
    daemon = alerts_mod.AlertDaemon(ev, eval_s=3600.0, registry=reg,
                                    on_page=pages.append)
    daemon.add_rule(alerts_mod.BurnRateRule(
        "lat_fast", "lat", long_window="1h", short_window="5m",
        factor=14.4, severity=alerts_mod.PAGE, for_s=0.0))
    # scripted clock: every request blows the 10ms objective -> burn
    # 100x on both windows -> pending -> firing
    now0 = time.monotonic()
    daemon.evaluate_once(now0)
    state = None
    for i in range(1, 8):
        for _ in range(5):
            hist.labels(stage="total").observe(500.0, exemplar=tid)
        state = daemon.evaluate_once(now0 + i)["lat_fast"]
        if state == "firing":
            break
    assert state == "firing", daemon.snapshot()
    assert pages, "firing page never emitted"
    top = pages[-1].get("attribution")
    assert top, pages[-1]
    # ranked by share of attributed time: the injected bottleneck
    # stage leads, carrying the retrievable exemplar
    assert top[0]["stage"] == "wfq_wait"
    assert top[0]["share"] > 0.5
    assert top[0]["exemplar"] == tid
    assert spans.get_trace(top[0]["exemplar"]) is not None
    spans.reset()


def test_alert_attribution_fn_override_wins():
    """Routers point ``attribution_fn`` at the fleet /whyslow merge —
    the hook's rows must win over the owner-keyed default lookup."""
    reg = MetricsRegistry()
    hist = reg.histogram("mxnet_tpu_t_ov_latency_ms", "t", ("stage",),
                         buckets=(10.0, 100.0))
    ev = slo_mod.SloEvaluator("ov-owner", registry=reg, scale=0.01,
                              budget_s=100.0)
    ev.add(slo_mod.LatencySLO("lat", threshold_ms=10.0,
                              family="mxnet_tpu_t_ov_latency_ms",
                              registry=reg))
    pages = []
    daemon = alerts_mod.AlertDaemon(ev, eval_s=3600.0, registry=reg,
                                    on_page=pages.append)
    daemon.attribution_fn = lambda: [
        {"stage": "dispatch", "share": 0.9, "p99_ms": 12.0,
         "count": 3, "total_ms": 36.0, "exemplar": None}]
    daemon.add_rule(alerts_mod.BurnRateRule(
        "lat_fast", "lat", long_window="1h", short_window="5m",
        factor=14.4, severity=alerts_mod.PAGE, for_s=0.0))
    now0 = time.monotonic()
    daemon.evaluate_once(now0)
    for i in range(1, 8):
        for _ in range(5):
            hist.labels(stage="total").observe(500.0)
        if daemon.evaluate_once(now0 + i)["lat_fast"] == "firing":
            break
    assert pages and pages[-1]["attribution"][0]["stage"] == "dispatch"


# ---------------------------------------------------------------------------
# review regressions: decompose stays O(n log n), windows decay,
# fleet top is not truncation-blind, perf/mono stamps share one axis
# ---------------------------------------------------------------------------

def test_decompose_10k_stamps_is_fast():
    """A 10k-token generation stamps one decode_iter per token; the
    extractor runs inline on the decode-loop thread at completion, so
    a quadratic sweep (6s at 10k stamps, measured) freezes token
    emission for EVERY active stream. Bound it hard."""
    stamps = []
    t = 0.0
    for i in range(10_000):
        stamps.append(("decode_iter", t, t + 0.004))
        if i % 7 == 0:      # nested COW copies keep overlap resolution hot
            stamps.append(("cow_copy", t + 0.001, t + 0.002))
        t += 0.0041
    t0 = time.perf_counter()
    bd = _attribution.breakdown_from_stamps(stamps, 0.0, t)
    elapsed = time.perf_counter() - t0
    assert elapsed < 0.5, f"decompose took {elapsed:.2f}s at 10k stamps"
    assert bd["attributed_ms"] + bd["unattributed_ms"] == \
        pytest.approx(bd["wall_ms"], abs=0.01)
    per = {s["stage"]: s["ms"] for s in bd["stages"]}
    assert per["cow_copy"] == pytest.approx(1429.0, abs=1.0)


def test_stage_window_p99_and_exemplar_decay():
    """The windowed p99 must reflect the RECENT window: once an
    incident's slow samples age out, p99 (and the slowest-exemplar
    trace) drop back — an eviction policy that keeps extremes forever
    would report the stale tail as current indefinitely."""
    st = _attribution._StageStat(capacity=100)
    for _ in range(50):
        st.observe(5000.0, trace_id="t-incident")
    assert st.p99() == pytest.approx(5000.0)
    assert st.exemplar() == (5000.0, "t-incident")
    # incident resolves: 200 healthy requests push every slow sample
    # out of the 100-deep window
    for _ in range(200):
        st.observe(10.0, trace_id="t-calm")
    assert st.p99() == pytest.approx(10.0)
    assert st.exemplar() == (10.0, "t-calm")
    assert st.count == 250 and len(st.window) == 100


def test_perf_counter_stamps_land_on_monotonic_axis():
    """Engine pack/compute stamps are timed with perf_counter but
    compared against time.monotonic() wall endpoints; perf_to_mono
    must map between the axes (they differ on some platforms) so the
    intervals don't clip outside the wall as 100% unattributed."""
    p, m = time.perf_counter(), time.monotonic()
    assert spans.perf_to_mono(p) == pytest.approx(m, abs=0.05)


def test_merge_whyslow_sees_past_local_topn():
    """A stage that is below every engine's local top-N cutoff can
    still dominate fleet-wide; the merge must rank from the full
    per-stage rows, not the parts' pre-truncated top tables, and
    shares must be of ALL attributed time."""
    parts = []
    for e in range(4):
        agg = _attribution.StageBreakdown(f"e{e}",
                                          registry=MetricsRegistry())
        agg.observe({"wall_ms": 100.0,
                     "stages": [{"stage": "decode_iter", "ms": 31.0},
                                {"stage": "prefill", "ms": 30.0},
                                {"stage": "wfq_wait", "ms": 29.0},
                                {"stage": "cow_copy", "ms": 28.0}],
                     "unattributed_ms": 0.0})
        # top=1: cow_copy is #4 locally on every engine
        parts.append(agg.snapshot(top=1))
    for part in parts:
        assert [t["stage"] for t in part["top"]] == ["decode_iter"]
    merged = _attribution.merge_whyslow(parts, owner="r0")
    ranked = [t["stage"] for t in merged["top"]]
    assert "cow_copy" in ranked, ranked
    by = {t["stage"]: t for t in merged["top"]}
    assert by["cow_copy"]["total_ms"] == pytest.approx(112.0)
    # shares are over the fleet grand total, not the truncated tables
    assert by["decode_iter"]["share"] == pytest.approx(124.0 / 472.0,
                                                       abs=1e-3)
    assert sum(t["share"] for t in merged["top"]) == \
        pytest.approx(1.0, abs=0.01)


# ---------------------------------------------------------------------------
# fleet merge
# ---------------------------------------------------------------------------

def test_merge_whyslow_recomputes_fleet_top():
    a = _attribution.StageBreakdown("e0", registry=MetricsRegistry())
    b = _attribution.StageBreakdown("e1", registry=MetricsRegistry())
    a.observe({"wall_ms": 100.0,
               "stages": [{"stage": "decode_iter", "ms": 90.0}],
               "unattributed_ms": 10.0})
    b.observe({"wall_ms": 100.0,
               "stages": [{"stage": "wfq_wait", "ms": 60.0},
                          {"stage": "decode_iter", "ms": 40.0}],
               "unattributed_ms": 0.0})
    merged = _attribution.merge_whyslow(
        [a.snapshot(), None, b.snapshot()], owner="r0")
    assert merged["owner"] == "r0" and merged["fleet"] is True
    assert merged["engines"] == ["e0", "e1"]
    assert merged["requests"] == 2
    # decode_iter dominates fleet-wide (130ms vs 60ms wfq_wait)
    assert merged["top"][0]["stage"] == "decode_iter"
    assert merged["top"][0]["total_ms"] == pytest.approx(130.0)
    rows_engines = {r["engine_id"] for r in merged["stages"]}
    assert rows_engines == {"e0", "e1"}


# ---------------------------------------------------------------------------
# disabled path: MXNET_TPU_ATTRIBUTION=0 costs ~nothing
# ---------------------------------------------------------------------------

def test_disabled_path_no_families_no_breakdowns(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_ATTRIBUTION", "0")
    _attribution.reset()        # re-read the env gate
    assert not _attribution.enabled()
    with _mk_engine(engine_id="bdoff") as eng:
        fut = eng.submit([1, 2, 3], max_new_tokens=4, stream=True)
        list(fut.stream(timeout=60))
        fut.result(timeout=0)
        # no decomposition, no aggregator, no stage families minted
        assert fut.breakdown is None
        assert _attribution.get_aggregator("bdoff") is None
        assert _attribution._families_cache is None
        ws = eng.whyslow()
        assert ws["enabled"] is False
        assert ws.get("requests", 0) == 0 and not ws.get("stages")
    # the disabled stamp is one attribute check: far under a µs —
    # bound it loosely so a slow CI host never flakes
    off = _Req()
    off.stages = None
    t0 = time.perf_counter()
    for _ in range(10_000):
        _attribution.stamp(off, "decode_iter", 0.0, 1.0, span=False)
    per_call_us = (time.perf_counter() - t0) * 1e5 / 10_000 * 10
    assert per_call_us < 50.0, per_call_us
