"""Worker body for the distributed kvstore test — the
tests/nightly/dist_sync_kvstore.py analog (SURVEY §4): launched via
tools/launch.py with 2 local processes, each holding 2 virtual CPU
devices, asserting DistKVStore invariants over the REAL multi-process
jax.distributed stack (loopback rendezvous = the ps-lite scheduler
role).

Invariants (reference nightly test):
- rank/num_workers reflect the launch;
- init + pull broadcasts the initial value;
- push sums gradients across every device of every worker;
- fused pushpull reduces all keys in one compiled program whose HLO
  contains an all-reduce;
- barrier() synchronizes.
"""
import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx
from mxnet_tpu import nd, kvstore
from mxnet_tpu.parallel import comm


def main():
    kv = kvstore.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    assert nw == 2, f"expected 2 workers, got {nw}"
    assert jax.device_count() == 4, jax.device_count()
    ctxs = [mx.cpu(0), mx.cpu(1)]

    # init + broadcast
    kv.init("a", nd.full((4, 3), 7.0))
    out = nd.zeros((4, 3))
    kv.pull("a", out=out)
    assert (out.asnumpy() == 7.0).all()

    # push: worker r contributes 2r+1 and 2r+2 from its two devices
    vals = [nd.full((4, 3), float(rank * 2 + i + 1), ctx=c)
            for i, c in enumerate(ctxs)]
    kv.push("a", vals)
    kv.pull("a", out=out)
    assert (out.asnumpy() == 10.0).all(), out.asnumpy()  # 1+2+3+4

    kv.barrier()

    # fused multi-key pushpull across processes
    kv.init(0, nd.zeros((2,)))
    kv.init(1, nd.zeros((3, 2)))
    grads = [[nd.full((2,), float(rank + 1), ctx=c) for c in ctxs],
             [nd.full((3, 2), float(10 * (rank + 1)), ctx=c) for c in ctxs]]
    kv.pushpull([0, 1], grads, out=grads)
    assert np.allclose(grads[0][0].asnumpy(), 6.0), grads[0][0].asnumpy()
    assert np.allclose(grads[1][1].asnumpy(), 60.0), grads[1][1].asnumpy()
    hlo = comm.last_hlo_text()
    assert hlo and "all-reduce" in hlo, "cross-process reduce not compiled to all-reduce"

    kv.barrier()

    # horovod-mode store over the SAME live jax.distributed backend:
    # allreduce-only API must sum across both workers' devices too
    # (HorovodKVStore inherits DistKVStore's global-mesh reduce)
    hkv = kvstore.create("horovod")
    assert hkv.num_workers == 2 and hkv.rank == rank
    hvals = [nd.full((3,), float(rank * 2 + i + 1), ctx=c)
             for i, c in enumerate(ctxs)]
    hkv.pushpull("h", hvals, out=hvals)
    assert np.allclose(hvals[0].asnumpy(), 10.0), hvals[0].asnumpy()
    try:
        hkv.push("h", hvals)
        raise AssertionError("horovod push must raise")
    except mx.base.MXNetError:
        pass

    # broadcast with rank-DIVERGENT inputs: process 0's value must win
    # on every worker (upstream hvd.broadcast_parameters semantics —
    # rank-0-only checkpoint restores rely on this; ADVICE r2 medium)
    bval = nd.full((4,), float(100 * (rank + 1)))
    bout = nd.zeros((4,))
    hkv.broadcast("b", bval, out=bout)
    assert np.allclose(bout.asnumpy(), 100.0), bout.asnumpy()

    kv.barrier()

    # sharded checkpoint across processes: each worker writes the shards
    # of a globally-sharded array; rank 0 reassembles (SURVEY §5.4
    # extension exercised multi-host)
    import tempfile

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mxnet_tpu.ndarray.ndarray import _wrap

    tmpdir = os.environ.get("DIST_TEST_TMPDIR") or tempfile.gettempdir()
    prefix = os.path.join(tmpdir, "dist_ckpt")
    mesh = Mesh(np.asarray(jax.devices()), ("dp",))
    want = np.arange(16, dtype=np.float32).reshape(4, 4)
    garr = jax.make_array_from_callback(
        (4, 4), NamedSharding(mesh, P("dp", None)),
        lambda idx: want[idx])
    nd.save_sharded(prefix, {"w": _wrap(garr, mx.current_context())})
    kv.barrier()
    if rank == 0:
        back = nd.load_sharded(prefix)
        assert np.allclose(back["w"].asnumpy(), want), back["w"].asnumpy()
    kv.barrier()
    print(f"DIST_WORKER_{rank}_OK", flush=True)


if __name__ == "__main__":
    main()
