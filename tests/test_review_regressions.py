"""Regression tests for code-review findings (round 1)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.test_utils import assert_almost_equal


def test_set_data_on_deferred_param_survives_init():
    p = gluon.Parameter("w", shape=(0, 4), allow_deferred_init=True)
    p.initialize()
    p.set_data(nd.ones((3, 4)) * 5)
    p.shape = (3, 4)
    p._finish_deferred_init()
    assert (p.data().asnumpy() == 5).all()


def test_waitall_after_hybridized_forward():
    net = nn.HybridSequential()
    net.add(nn.Dense(3))
    net.initialize()
    net.hybridize()
    net(nd.ones((2, 4)))
    mx.waitall()  # must not crash on leaked tracers


def test_out_aliasing_input_grad_correct():
    x = nd.array([0.3, 0.7])
    x.attach_grad()
    x0 = x.asnumpy().copy()
    with autograd.record():
        y = nd.sin(x, out=x)  # out= aliases the input
    y.backward()
    assert_almost_equal(x.grad, np.cos(x0), rtol=1e-5)


def test_reverse_scalar_ops():
    x = nd.array([2.0, 4.0])
    assert_almost_equal(nd._rminus_scalar(x, scalar=1.0),
                        1.0 - x.asnumpy())
    assert_almost_equal(nd._rdiv_scalar(x, scalar=8.0), 8.0 / x.asnumpy())
    assert_almost_equal(nd._rpower_scalar(x, scalar=2.0),
                        2.0 ** x.asnumpy())
    # dunder path
    assert_almost_equal(1.0 - x, 1.0 - x.asnumpy())
    assert_almost_equal(8.0 / x, 8.0 / x.asnumpy())


def test_seed_affects_other_threads():
    import threading
    mx.random.seed(123)
    main_val = nd.random.uniform(shape=(4,)).asnumpy()
    mx.random.seed(123)
    result = {}

    def worker():
        result["val"] = nd.random.uniform(shape=(4,)).asnumpy()

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert np.allclose(main_val, result["val"])


def test_dataloader_early_break_releases():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    ds = ArrayDataset(np.arange(100).reshape(50, 2).astype(np.float32))
    dl = DataLoader(ds, batch_size=5, num_workers=2)
    for batch in dl:
        break  # early exit must not hang or leak
    it = iter(dl)
    n = sum(1 for _ in it)
    assert n == 10


def test_top_level_namespace_aliases():
    """Reference package aliases (mx.mod/mx.img/mx.kv/mx.init/mx.sym/
    mx.viz) resolve to their modules."""
    import mxnet_tpu as mx
    assert mx.mod is mx.module
    assert mx.img is mx.image
    assert mx.kv is mx.kvstore
    assert mx.init is mx.initializer
    assert mx.sym is mx.symbol
    assert mx.viz is mx.visualization


def test_symbol_scalar_before_later_symbol_arg():
    """ADVICE r5: sym.op(x, 2.0, y) — a scalar folded from a position
    BEFORE a later Symbol arg must bind around the scalar's signature
    slot at executor time, not collide with it ("multiple values")."""
    import mxnet_tpu.symbol as sym
    from mxnet_tpu.ndarray.register import register_op
    import mxnet_tpu.symbol.symbol as symmod

    if not hasattr(sym, "_test_scalar_mid"):
        @register_op("_test_scalar_mid")
        def _test_scalar_mid(x, a, y, b=1.0):
            return a * x + b * y
        symmod._populate_symbol_ops(sym)

    x = nd.array(np.full((2, 3), 2.0, np.float32))
    y = nd.array(np.full((2, 3), 10.0, np.float32))
    s = sym._test_scalar_mid(sym.Variable("x"), 3.0, sym.Variable("y"),
                             b=0.5)
    ex = s.bind(mx.cpu(), {"x": x, "y": y})
    out = ex.forward()[0].asnumpy()
    assert np.allclose(out, 11.0), out  # 3*2 + 0.5*10
    args, outs, _ = s.infer_shape(x=(2, 3), y=(2, 3))
    assert outs == [(2, 3)]
    # eager trailing-scalar folding unchanged
    got = nd.clip(nd.array(np.array([-2.0, 0.5, 9.0])), 0.0, 1.0)
    assert np.allclose(got.asnumpy(), [0.0, 0.5, 1.0])
