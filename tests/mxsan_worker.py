"""Subprocess golden: a real ServingEngine workload under
``MXNET_TPU_SANITIZE=1``.

Run by ``tests/test_sanitize.py``; proves the instrumented serving
stack (engine worker loop, queue condition, metrics registry, event
log) is sanitizer-clean end-to-end and that instrumentation actually
engaged (edges observed > 0). Prints one JSON line; exits 1 on any
unbaselined finding.
"""
import json
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import mxnet_tpu  # noqa: E402, F401  (installs the sanitizer)
from mxnet_tpu import _sanitize, nd  # noqa: E402
from mxnet_tpu.serving.engine import ServingEngine  # noqa: E402


class StubModel:
    def __call__(self, ids, token_types, valid_length, segment_ids,
                 positions):
        return nd.array(ids.asnumpy().astype(np.float32)[..., None])


def main():
    san = _sanitize.active()
    if san is None:
        print(json.dumps({"error": "sanitizer not installed"}))
        return 1
    # this file lives under the repo: module-attribute Lock() must be
    # instrumented here
    probe = threading.Lock()
    patched = type(probe).__name__ == "_SanLock"

    eng = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=2)
    eng.start()
    futs = [eng.submit(list(range(3 + (i % 5)))) for i in range(16)]
    for f in futs:
        f.result(timeout=30)
    eng.stop()

    findings = san.teardown_check()
    out = {
        "patched": patched,
        "edges": len(san._edges),
        "findings": [{"rule": f.rule,
                      "key": f.meta.get("key") or f.key(),
                      "message": f.message} for f in findings],
        "suppressed": [f.rule for f in san.suppressed],
    }
    print(json.dumps(out))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
