"""Traffic capture + deterministic replay (mxnet_tpu/serving/capture):
corpus durability (torn tail, disk budget, cross-process reload),
canary exclusion, payload modes, byte-identical replay against the
same code and divergence detection against perturbed code, and the
``MXNET_TPU_CAPTURE=0`` disabled-path guarantees. Marker-clean tier-1.
"""
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import nd
from mxnet_tpu.serving import (CaptureStore, ServingEngine, load_corpus,
                               output_digest, replay)
from mxnet_tpu.serving.capture import is_synthetic, merge_summaries
from mxnet_tpu.serving.queue import Request
from mxnet_tpu.telemetry.registry import REGISTRY

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class StubModel:
    """out[b, s, 0] == ids[b, s] (+ optional bias): bit-deterministic,
    so capture digests replay exactly — and a biased rebuild is the
    injected perturbation replay must catch."""

    def __init__(self, bias=0.0):
        self.bias = bias

    def __call__(self, ids, token_types, valid_length, segment_ids,
                 positions):
        out = ids.asnumpy().astype(np.float32)[..., None] + self.bias
        return nd.array(out)


def _req(tokens, trace_id=None, tenant=None, tenant_class=None):
    r = Request(tokens, trace_id=trace_id, tenant=tenant,
                tenant_class=tenant_class)
    r.span.end()
    return r


def _record(store, tokens, out=None, trace_id=None, outcome="completed",
            tenant=None, **kw):
    req = _req(tokens, trace_id=trace_id, tenant=tenant)
    if out is None:
        out = np.asarray(tokens, np.float32)
    return store.record_request(req, out, outcome, 12.5, **kw)


# ---------------------------------------------------------------------------
# store + corpus durability
# ---------------------------------------------------------------------------

def test_record_roundtrip_disk(tmp_path):
    store = CaptureStore("e0", dir=str(tmp_path), rate=1.0, max_mb=4)
    toks = np.array([3, 1, 4, 1, 5], np.int32)
    out = np.array([0.5, -1.5], np.float32)
    assert _record(store, toks, out, tenant="t-a", model="m0",
                   version="v1", engine_id="e0")
    store.close()

    records, skipped = load_corpus(str(tmp_path))
    assert skipped == 0 and len(records) == 1
    rec = records[0]
    assert rec["model"] == "m0" and rec["version"] == "v1"
    assert rec["engine_id"] == "e0"
    assert rec["outcome"] == "completed"
    # tokens ride the typed wire codec: int32, bit-exact
    got = np.asarray(rec["tokens"])
    assert got.dtype == np.int32 and np.array_equal(got, toks)
    assert rec["output_digest"] == output_digest(out)
    # small float outputs ride along for tolerance replay
    assert np.array_equal(np.asarray(rec["output_vals"]), out)
    assert rec["total_ms"] == 12.5
    assert rec["arrival_wall"] == pytest.approx(time.time(), abs=60.0)


def test_payload_digest_mode_not_replayable(tmp_path):
    store = CaptureStore("e0", dir=str(tmp_path), payload="digest")
    toks = np.arange(6, dtype=np.int32)
    assert _record(store, toks)
    store.close()
    records, _ = load_corpus(str(tmp_path))
    rec = records[0]
    assert rec["tokens"] is None and rec["output_vals"] is None
    assert rec["prompt_digest"] == output_digest(toks)
    assert rec["prompt_len"] == 6
    report = replay(records, target=None)
    assert report["replayed"] == 0
    assert report["skipped"]["no_payload"] == 1


def test_canary_traffic_never_enters_corpus(tmp_path):
    store = CaptureStore("e0", dir=str(tmp_path))
    assert is_synthetic("canary-abc") and not is_synthetic("req-abc")
    assert not _record(store, [1, 2], trace_id="canary-e0-7")
    assert _record(store, [1, 2], trace_id="req-real")
    store.close()
    records, _ = load_corpus(str(tmp_path))
    assert [r["trace_id"] for r in records] == ["req-real"]


def test_sampling_rate_deterministic_credit():
    store = CaptureStore("e0", dir=None, rate=0.25)
    picked = [store.should_sample(f"req-{i}") for i in range(12)]
    assert sum(picked) == 3          # exactly rate * n, no RNG
    assert store.should_sample("canary-x") is False


def test_torn_tail_skipped_not_fatal(tmp_path):
    store = CaptureStore("e0", dir=str(tmp_path))
    for i in range(3):
        assert _record(store, [i, i + 1], trace_id=f"req-{i}")
    store.close()
    segs = sorted(p for p in os.listdir(tmp_path) if p.endswith(".seg"))
    # simulate a crash mid-append: garbage half-frame at the tail
    with open(tmp_path / segs[-1], "ab") as fh:
        fh.write(b"\xde\xad\xbe\xef\x01")
    records, skipped = load_corpus(str(tmp_path))
    assert len(records) == 3 and skipped >= 1


def test_disk_budget_evicts_oldest_sealed_segments(tmp_path):
    # tiny budget => segment_bytes floors at 4 KiB; ~60-record frames
    # seal segments quickly and eviction must reclaim the oldest
    store = CaptureStore("e0", dir=str(tmp_path), max_mb=0.01)
    for i in range(600):
        assert _record(store, [i % 50, 1, 2], trace_id=f"req-{i}")
    store.close()
    assert store.corpus_bytes() <= 0.01 * 1024 * 1024 + 4096
    records, skipped = load_corpus(str(tmp_path))
    assert skipped == 0
    ids = [int(r["trace_id"].split("-")[1]) for r in records]
    # oldest evicted, newest retained, survivors contiguous
    assert 0 not in ids and 599 in ids
    assert ids == sorted(ids)


def test_in_memory_corpus_and_summary():
    store = CaptureStore("e0", dir=None, rate=1.0, max_mb=1)
    for i in range(4):
        assert _record(store, [i], trace_id=f"req-{i}")
    records, skipped = store.records()
    assert skipped == 0 and len(records) == 4
    s = store.summary()
    assert s["enabled"] and s["records_written"] == 4
    assert s["dir"] is None and s["corpus_bytes"] > 0
    assert s["age_s"] is not None and s["age_s"] >= 0
    store.close()


def test_merge_summaries_fleet_totals():
    a = {"records_written": 3, "corpus_bytes": 100, "write_errors": 0}
    b = {"records_written": 5, "corpus_bytes": 250, "write_errors": 1}
    merged = merge_summaries(
        [("e0", a), ("e1", b), ("e2", None)], owner="r0")
    assert merged["owner"] == "r0" and merged["enabled"]
    assert merged["fleet"]["records_written"] == 8
    assert merged["fleet"]["corpus_bytes"] == 350
    assert merged["fleet"]["write_errors"] == 1
    assert merged["missing"] == ["e2"]


# ---------------------------------------------------------------------------
# engine capture -> replay oracle
# ---------------------------------------------------------------------------

def _capture_engine(monkeypatch, tmp_path, bias=0.0, capture=True):
    monkeypatch.setenv("MXNET_TPU_CAPTURE", "1" if capture else "0")
    monkeypatch.setenv("MXNET_TPU_CAPTURE_DIR", str(tmp_path))
    return ServingEngine(StubModel(bias=bias), bucket_lens=(16,),
                         max_rows=2, engine_id="cap0")


def test_engine_replay_byte_identical_zero_divergences(
        monkeypatch, tmp_path):
    rs = np.random.RandomState(3)
    prompts = [rs.randint(1, 50, size=rs.randint(2, 14)).astype(np.int32)
               for _ in range(6)]
    with _capture_engine(monkeypatch, tmp_path / "c") as eng:
        eng.warmup()
        for p in prompts:
            eng.submit(p).result(timeout=30)
    records, skipped = load_corpus(str(tmp_path / "c"))
    # warmup is synthetic-free but capture samples only REAL submits
    assert skipped == 0 and len(records) == 6
    assert all(r["breakdown"] for r in records)

    # same code, fresh engine: zero divergences, all bitwise
    with _capture_engine(monkeypatch, tmp_path / "unused",
                         capture=False) as eng2:
        eng2.warmup()
        report = replay(records, eng2)
    assert report["replayed"] == 6 and report["matched"] == 6
    assert report["matched_bitwise"] == 6
    assert report["divergences"] == [] and report["errors"] == []


def test_engine_replay_detects_injected_perturbation(
        monkeypatch, tmp_path):
    with _capture_engine(monkeypatch, tmp_path / "c") as eng:
        eng.warmup()
        for i in range(4):
            eng.submit([1 + i, 2, 3]).result(timeout=30)
    records, _ = load_corpus(str(tmp_path / "c"))

    with _capture_engine(monkeypatch, tmp_path / "unused", bias=0.5,
                         capture=False) as bad:
        bad.warmup()
        report = replay(records, bad)
    assert report["matched"] == 0
    assert len(report["divergences"]) == 4
    named = {d["trace_id"] for d in report["divergences"]}
    assert named == {r["trace_id"] for r in records}
    for d in report["divergences"]:
        assert d["expected"] != d["got"]
        # fp outputs carry the numeric evidence + the replayed
        # request's own critical path
        assert d["max_abs_diff"] == pytest.approx(0.5)
        assert d["breakdown"] and d["breakdown"]["stages"]


def test_float_tolerance_accepts_packing_noise_only():
    # digest differs by sub-tolerance noise -> matched_within_tol;
    # a real regression (>> 1e-5) -> divergence
    out = np.linspace(-1, 1, 8, dtype=np.float32)
    store = CaptureStore("e0", dir=None)
    _record(store, [1, 2, 3], out, trace_id="req-tol")
    rec = store.records()[0][0]

    class OneShot:
        def __init__(self, value):
            self.value = value

        def submit(self, tokens, **kw):
            class F:
                def result(_self, timeout=None):
                    return self.value
            return F()

    noisy = out + np.float32(3e-6)          # ~packed-lane ulp noise
    report = replay([rec], OneShot(noisy))
    assert report["matched"] == 1 and report["matched_within_tol"] == 1
    report = replay([rec], OneShot(out + np.float32(1e-3)))
    assert report["matched"] == 0 and len(report["divergences"]) == 1
    store.close()


def test_decode_capture_replay_seeded_streams(monkeypatch, tmp_path):
    from mxnet_tpu.serving import DecodeEngine, PagedCausalLM

    monkeypatch.setenv("MXNET_TPU_CAPTURE", "1")
    monkeypatch.setenv("MXNET_TPU_CAPTURE_DIR", str(tmp_path / "c"))

    def mk(seed):
        lm = PagedCausalLM(vocab=64, units=32, layers=2, heads=4,
                           max_len=128, seed=seed)
        return DecodeEngine(lm, prefill_bucket_lens=(8, 16), max_rows=4,
                            page_size=8, n_pages=24, max_new_tokens=5)

    rs = np.random.RandomState(5)
    with mk(seed=7) as eng:
        for i in range(4):
            toks = rs.randint(1, 64, size=6).astype(np.int32)
            fut, _ = eng.submit_payload(
                {"tokens": toks, "temperature": 0.8, "top_k": 8,
                 "seed": 100 + i, "stream": False})
            fut.result(timeout=30)
    records, _ = load_corpus(str(tmp_path / "c"))
    assert len(records) == 4
    assert all(r["decode"]["seed"] == 100 + i
               for i, r in enumerate(records))

    monkeypatch.setenv("MXNET_TPU_CAPTURE", "0")
    # identical model + captured seeds: byte-identical token streams
    with mk(seed=7) as same:
        report = replay(records, same)
    assert report["matched_bitwise"] == 4 and not report["divergences"]
    # different weights: every seeded stream flips
    with mk(seed=8) as other:
        report = replay(records, other)
    assert len(report["divergences"]) == 4


def test_replay_pacing_speed(monkeypatch, tmp_path):
    with _capture_engine(monkeypatch, tmp_path / "c") as eng:
        eng.warmup()
        eng.submit([1, 2]).result(timeout=30)
        time.sleep(0.25)
        eng.submit([3, 4]).result(timeout=30)
    records, _ = load_corpus(str(tmp_path / "c"))
    with _capture_engine(monkeypatch, tmp_path / "u",
                         capture=False) as eng2:
        eng2.warmup()
        t0 = time.monotonic()
        fast = replay(records, eng2, speed=0)     # no pacing
        dt_fast = time.monotonic() - t0
        paced = replay(records, eng2, speed=1.0)  # original gaps
    assert fast["matched"] == 2 and paced["matched"] == 2
    assert dt_fast < 0.2
    assert paced["wall_s"] >= 0.2                 # ~the captured gap


# ---------------------------------------------------------------------------
# cross-process golden: corpus written THERE, replayed HERE
# ---------------------------------------------------------------------------

def test_cross_process_corpus_golden(tmp_path):
    corpus = tmp_path / "corpus"
    worker = subprocess.Popen(
        [sys.executable,
         os.path.join(ROOT, "tests", "serving_router_engine_worker.py"),
         "proc-cap"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 MXNET_TPU_CAPTURE="1",
                 MXNET_TPU_CAPTURE_DIR=str(corpus)))
    try:
        line = worker.stdout.readline()
        assert line.startswith("PORT "), line
        port = int(line.split()[1])
        import json
        import urllib.request
        for i in range(3):
            body = json.dumps({"tokens": [1 + i, 2, 3]}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/submit", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.status == 200
    finally:
        worker.stdin.close()
        worker.wait(timeout=30)

    records, skipped = load_corpus(str(corpus))
    assert skipped == 0 and len(records) == 3
    # replay the other process's corpus against an identical local
    # engine (the worker serves the same identity model as StubModel)
    with ServingEngine(StubModel(), bucket_lens=(32,), max_rows=2,
                       engine_id="local") as eng:
        eng.warmup()
        report = replay(records, eng)
    assert report["matched_bitwise"] == 3 and not report["divergences"]


# ---------------------------------------------------------------------------
# disabled path: MXNET_TPU_CAPTURE=0 builds nothing
# ---------------------------------------------------------------------------

def test_capture_disabled_no_files_no_families_no_threads(
        monkeypatch, tmp_path):
    monkeypatch.delenv("MXNET_TPU_CAPTURE", raising=False)
    monkeypatch.setenv("MXNET_TPU_CAPTURE_DIR", str(tmp_path / "c"))
    before = set(threading.enumerate())
    eng = ServingEngine(StubModel(), bucket_lens=(16,), max_rows=2,
                        engine_id="cap-off")
    with eng:
        eng.warmup()
        eng.infer([1, 2, 3], timeout=30)
        assert eng.capture is None and eng.capture_summary() is None
        # no capture thread beyond the engine's own machinery
        extra = [t.name for t in set(threading.enumerate()) - before]
        assert not any("capture" in n.lower() for n in extra)
    assert not (tmp_path / "c").exists()
    # no owner-labeled capture series for this engine
    text = REGISTRY.render_prometheus()
    assert 'owner="cap-off"' not in text
    # microbench guard: the per-request cost of capture-off is one
    # attribute check — submit/result stays well under a millisecond
    # of overhead per request on the StubModel
    with ServingEngine(StubModel(), bucket_lens=(16,), max_rows=2,
                       engine_id="cap-off-2") as eng2:
        eng2.warmup()
        n = 50
        t0 = time.perf_counter()
        for _ in range(n):
            eng2.infer([1, 2, 3], timeout=30)
        per = (time.perf_counter() - t0) / n
    assert per < 0.25, f"disabled-path request cost {per * 1e3:.1f}ms"
