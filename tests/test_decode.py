"""Autoregressive decode serving: paged KV cache, decode kernel,
iteration-level continuous batching, streamed tokens.

Covers the ISSUE-15 acceptance surface:

- decode-kernel goldens vs the dense reference (causal chunk, paged
  Sq=1, multi-page, ragged kv_len, zero-length rows);
- page-pool alloc/free/occupancy round trip, allocation atomicity,
  per-page owner attribution (isolation invariants), scratch-padded
  scatter coordinates;
- join/leave-mid-iteration SOLO-PARITY golden: sequences decoded in a
  churning batch are byte-identical to solo runs, and streamed ==
  non-streamed;
- KV-page backpressure: an exhausted pool DEFERS joins (nothing
  fails), pages recycle, everything completes;
- buffer donation: steady-state decode performs no per-step
  cache-sized allocation (RSS watermark bound);
- streamed RESULT frames over the wire incl. a LEGACY one-RESULT peer
  and a killed connection mid-stream (partial tokens are not replayed
  as new client-visible work: zero lost, zero duplicated);
- the HTTP chunked /submit fallback;
- decode observability: inter-token/TTFT families, the
  decode_inter_token SLO rule, the scheduler-state flight-bundle
  section, telemetry_dump's fleet decode split.
"""
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import mxnet_tpu  # noqa: F401  (configures jax for the CPU mesh)


def _mk_model(**kw):
    from mxnet_tpu.serving import PagedCausalLM

    args = dict(vocab=64, units=32, layers=2, heads=4, max_len=128,
                seed=7)
    args.update(kw)
    return PagedCausalLM(**args)


def _mk_engine(model=None, **kw):
    from mxnet_tpu.serving import DecodeEngine

    args = dict(prefill_bucket_lens=(8, 16), max_rows=4, page_size=8,
                n_pages=24, max_new_tokens=6)
    args.update(kw)
    return DecodeEngine(model if model is not None else _mk_model(),
                        **args)


# ---------------------------------------------------------------------------
# decode kernel vs dense reference
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("sq,kvls", [
    (1, (5, 17, 24)),          # steady-state decode, ragged lengths
    (4, (9, 20, 4)),           # chunked prefill, causal within chunk
    (8, (8, 24, 16)),          # chunk spanning whole pages
])
def test_paged_kernel_golden(monkeypatch, sq, kvls):
    monkeypatch.setenv("MXNET_TPU_PALLAS_INTERPRET", "1")
    from mxnet_tpu.ops.pallas.flash_attention import (
        paged_attention_reference, paged_flash_attention)

    rng = np.random.RandomState(0)
    p, h, psize, d = 10, 4, 8, 16
    b, npg = 3, 3
    k_pages = rng.randn(p, h, psize, d).astype(np.float32)
    v_pages = rng.randn(p, h, psize, d).astype(np.float32)
    # non-contiguous PHYSICAL pages: the gather must go through the
    # table, not assume adjacency
    table = rng.permutation(p)[:b * npg].reshape(b, npg).astype(np.int32)
    q = rng.randn(b, h, sq, d).astype(np.float32)
    kvl = np.asarray(kvls, np.int32)
    out = paged_flash_attention(q, k_pages, v_pages, table, kvl,
                                interpret=True)
    ref = paged_attention_reference(q, k_pages, v_pages, table, kvl)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # ... and against a from-scratch dense softmax over the gathered,
    # causally-masked history (independent of the reference helper)
    for r in range(b):
        hist = np.concatenate([k_pages[table[r, j]]
                               for j in range(npg)], axis=1)  # (h,S,d)
        vhist = np.concatenate([v_pages[table[r, j]]
                                for j in range(npg)], axis=1)
        for qi in range(sq):
            limit = kvl[r] - sq + qi + 1     # exclusive
            if limit <= 0:
                continue
            s = np.einsum("hd,hkd->hk", q[r, :, qi] / np.sqrt(d),
                          hist[:, :limit])
            w = np.exp(s - s.max(-1, keepdims=True))
            w /= w.sum(-1, keepdims=True)
            o = np.einsum("hk,hkd->hd", w, vhist[:, :limit])
            np.testing.assert_allclose(np.asarray(out)[r, :, qi], o,
                                       atol=2e-5, rtol=2e-5)


def test_paged_kernel_zero_and_pad_rows(monkeypatch):
    """kv_len 0 rows emit exact zeros; table-pad slots past the row's
    pages never contribute (widening the table changes nothing)."""
    monkeypatch.setenv("MXNET_TPU_PALLAS_INTERPRET", "1")
    from mxnet_tpu.ops.pallas.flash_attention import paged_flash_attention

    rng = np.random.RandomState(1)
    p, h, psize, d = 6, 2, 8, 16
    k_pages = rng.randn(p, h, psize, d).astype(np.float32)
    v_pages = rng.randn(p, h, psize, d).astype(np.float32)
    q = rng.randn(2, h, 1, d).astype(np.float32)
    kvl = np.asarray([0, 5], np.int32)
    narrow = np.asarray([[1, 0], [2, 0]], np.int32)
    wide = np.asarray([[1, 3, 4, 5], [2, 3, 4, 5]], np.int32)
    o1 = np.asarray(paged_flash_attention(q, k_pages, v_pages, narrow,
                                          kvl, interpret=True))
    o2 = np.asarray(paged_flash_attention(q, k_pages, v_pages, wide,
                                          kvl, interpret=True))
    assert np.all(o1[0] == 0.0)
    np.testing.assert_array_equal(o1, o2)


# ---------------------------------------------------------------------------
# page pool
# ---------------------------------------------------------------------------
def test_pool_alloc_free_round_trip():
    from mxnet_tpu.serving import KVPagesExhaustedError, PagedKVPool

    pool = PagedKVPool(2, 4, 16, page_size=8, n_pages=6,
                       engine_id="pool_t0")
    assert pool.pages_for(1) == 1 and pool.pages_for(8) == 1
    assert pool.pages_for(9) == 2
    t_a = pool.ensure("a", 20)          # 3 pages
    assert len(t_a) == 3 and pool.table("a") == t_a
    pool.ensure("b", 8)
    assert pool.occupancy()["pages_used"] == 4
    for page in t_a:
        assert pool.owner_of(page) == "a"
    pool.check_isolated()
    # growth extends IN PLACE (same leading pages)
    t_a2 = pool.ensure("a", 25)
    assert t_a2[:3] == t_a and len(t_a2) == 4
    # atomic refusal: "c" needs 2, only 1 free — nothing allocated
    with pytest.raises(KVPagesExhaustedError):
        pool.ensure("c", 16)
    assert pool.table("c") == []
    assert pool.occupancy()["pages_used"] == 5
    # release recycles everything, idempotently
    assert pool.release("a") == 4
    assert pool.release("a") == 0
    pool.release("b")
    occ = pool.occupancy()
    assert occ["pages_used"] == 0 and occ["pages_free"] == 6
    pool.check_isolated()
    # no fragmentation by construction: interleaved churn at full
    # capacity keeps succeeding (every page is the same size)
    for i in range(20):
        pool.ensure(f"x{i}", 48)        # the whole pool
        pool.release(f"x{i}")
    assert pool.occupancy()["pages_free"] == 6


def test_pool_scatter_and_padded_tables():
    from mxnet_tpu.serving import PagedKVPool

    pool = PagedKVPool(1, 2, 8, page_size=4, n_pages=8,
                       engine_id="pool_t1")
    pool.ensure("a", 6)                 # 2 pages
    phys, off = pool.scatter_indices("a", 6, padded=12)
    table = pool.table("a")
    assert list(phys[:4]) == [table[0]] * 4
    assert list(phys[4:6]) == [table[1]] * 2
    # padded tail lands on the scratch page, never a live one
    assert all(p == pool.scratch_page for p in phys[6:])
    assert list(off) == [0, 1, 2, 3] * 3
    tables = pool.padded_tables(["a", "nobody"], 4)
    assert tables.shape == (2, 4)
    assert list(tables[0, :2]) == table
    assert all(v == pool.scratch_page for v in tables[0, 2:])
    assert all(v == pool.scratch_page for v in tables[1])


# ---------------------------------------------------------------------------
# solo parity + streaming semantics
# ---------------------------------------------------------------------------
def test_join_leave_solo_parity_golden():
    """Sequences joining/leaving a churning decode batch produce
    byte-identical tokens to solo runs — and the streamed parts are
    byte-identical to the final (non-streamed) result."""
    model = _mk_model()
    prompts = [[1, 2, 3], [9, 8, 7, 6, 5], [4], [11, 12],
               [3, 1, 4, 1, 5, 9, 2, 6], [13, 10, 7]]
    news = [8, 3, 6, 10, 4, 7]
    # solo goldens: one request at a time
    solo = []
    with _mk_engine(model) as eng:
        for prompt, n_new in zip(prompts, news):
            solo.append(eng.infer(prompt, max_new_tokens=n_new).tolist())
        assert sorted({len(s) for s in solo}) == sorted(set(news))
    # churning batch: staggered joins from client threads, mixed
    # lengths so leaves happen mid-flight while others keep decoding
    model2 = _mk_model()                # fresh pool/caches, same seed
    with _mk_engine(model2) as eng:
        futs = [None] * len(prompts)

        def submit(i):
            time.sleep(0.003 * i)       # join at different iterations
            futs[i] = eng.submit(prompts[i], max_new_tokens=news[i],
                                 stream=True)

        threads = [threading.Thread(target=submit, args=(i,),
                                    name=f"parity_{i}", daemon=True)
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, fut in enumerate(futs):
            parts = [p["token"] for p in fut.stream(timeout=60)]
            out = fut.result(timeout=0).tolist()
            assert out == solo[i], (i, out, solo[i])
            assert parts == out, (i, parts, out)
            assert [p["index"] for p in fut.parts()] \
                == list(range(len(out)))
        snap = eng.snapshot()
        assert snap["decode"]["joins"] >= 2
        assert snap["decode"]["leaves"] >= 2
        eng.pool.check_isolated()
        assert eng.pool.occupancy()["pages_used"] == 0


def test_eos_and_max_tokens_leave():
    model = _mk_model()
    with _mk_engine(model) as eng:
        full = eng.infer([1, 2, 3, 4, 5], max_new_tokens=8).tolist()
    # pin eos to the 3rd generated token: greedy decode is
    # deterministic, so the truncated run must equal the prefix
    model2 = _mk_model()
    with _mk_engine(model2, eos_id=full[2]) as eng:
        out = eng.infer([1, 2, 3, 4, 5], max_new_tokens=8).tolist()
    assert out == full[:3]
    # a generation that ends AT PREFILL (max_new_tokens=1) still lands
    # in the ledger's requests column — the sum(bills) == ledger
    # reconciliation contract covers the never-joined path too
    with _mk_engine(_mk_model()) as eng:
        before = eng.costs.totals()["requests"]
        out = eng.infer([1, 2, 3], max_new_tokens=1)
        assert len(out) == 1
        assert eng.costs.totals()["requests"] == before + 1


def test_page_exhaustion_defers_not_fails():
    """A pool too small for the whole burst DEFERS joins: requests
    wait for pages to recycle and every one completes."""
    # worst case per request: pages_for(5 + 6) = 2 pages; 4 pages
    # total => at most 2 sequences live at once
    with _mk_engine(_mk_model(), page_size=8, n_pages=4,
                    max_rows=4) as eng:
        futs = [eng.submit([1, 2, 3, 4, 5], max_new_tokens=6)
                for _ in range(6)]
        outs = [f.result(timeout=120).tolist() for f in futs]
    assert len({tuple(o) for o in outs}) == 1      # same prompt, same out
    assert all(len(o) == 6 for o in outs)
    assert eng.stats.count("completed") == 6
    assert eng.pool.occupancy()["pages_used"] == 0


def test_static_mode_cohorts():
    """iteration_level=False (the bench A/B baseline) still completes
    everything, but never exceeds one cohort's membership: no join
    while a batch is live."""
    with _mk_engine(_mk_model(), iteration_level=False) as eng:
        futs = [eng.submit([i + 1, i + 2], max_new_tokens=3 + i)
                for i in range(5)]
        for f in futs:
            f.result(timeout=120)
    snap = eng.snapshot()
    assert snap["counters"]["completed"] == 5
    assert snap["iteration_level"] is False


def test_donation_no_per_step_cache_allocation():
    """Steady-state decode must not allocate a cache-sized buffer per
    step: the pool rides the jitted steps as donated arguments. The
    RSS watermark over many iterations stays under one cache size."""
    from mxnet_tpu.telemetry import resources

    model = _mk_model(units=128, heads=4, layers=2)
    with _mk_engine(model, page_size=8, n_pages=192, max_rows=2,
                    prefill_bucket_lens=(8,), max_new_tokens=40) as eng:
        cache_bytes = eng.pool.bytes_total
        assert cache_bytes > 1 << 20    # the bound must mean something
        # warm the steady-state path, then measure
        eng.infer([1, 2, 3], max_new_tokens=40)
        resources.sample()
        rss0 = resources.rss_bytes()
        steps = 0
        for _ in range(3):
            eng.infer([1, 2, 3], max_new_tokens=40)
            steps += 40
        resources.sample()
        grown = resources.rss_bytes() - rss0
    # without in-place updates this loop would have cycled
    # steps * cache_bytes (~0.3 GB) through the allocator; the
    # watermark bound tolerates one extra cache copy + noise
    assert grown < steps * cache_bytes / 8, (grown, steps, cache_bytes)


# ---------------------------------------------------------------------------
# streamed dispatch: wire + HTTP chunked + router
# ---------------------------------------------------------------------------
def _wire_client(eng):
    from mxnet_tpu.serving.wire import WireClient

    wc = WireClient("127.0.0.1", eng._wire.port, client_id="t",
                    expect_engine_id=eng.engine_id)
    wc.ensure()
    return wc


def test_wire_streamed_and_legacy_one_result(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_WIRE", "1")
    with _mk_engine(_mk_model()) as eng:
        eng.expose()
        solo = eng.infer([1, 2, 3, 4, 5], max_new_tokens=8).tolist()
        wc = _wire_client(eng)
        try:
            # streamed: partial RESULT frames then an authoritative
            # final carrying full sequence + final/seq markers
            parts, box, done = [], {}, threading.Event()
            wc.dispatch({"tokens": np.asarray([1, 2, 3, 4, 5], np.int32),
                         "max_new_tokens": 8, "stream": True},
                        lambda exc, body: (box.update(exc=exc,
                                                      body=body),
                                           done.set()),
                        30.0, on_part=lambda b: parts.append(b))
            assert done.wait(60)
            assert box["exc"] is None
            body = box["body"]
            assert body.get("final") is True and body.get("seq") == 8
            assert np.asarray(body["result"]).tolist() == solo
            assert [p["token"] for p in parts] == solo
            assert [p["seq"] for p in parts] == list(range(8))
            # LEGACY peer: no "stream" in the payload -> exactly one
            # RESULT frame with no "final" key (the pre-streaming
            # protocol, byte-compatible for old routers)
            box2, done2 = {}, threading.Event()
            wc.dispatch({"tokens": np.asarray([1, 2, 3, 4, 5], np.int32),
                         "max_new_tokens": 8},
                        lambda exc, body: (box2.update(exc=exc,
                                                       body=body),
                                           done2.set()), 30.0)
            assert done2.wait(60)
            assert box2["exc"] is None
            assert "final" not in box2["body"]
            assert np.asarray(box2["body"]["result"]).tolist() == solo
        finally:
            wc.close()


def test_http_chunked_submit_stream():
    with _mk_engine(_mk_model()) as eng:
        srv = eng.expose()
        solo = eng.infer([1, 2, 3], max_new_tokens=6).tolist()
        req = urllib.request.Request(
            srv.url("/submit"),
            data=json.dumps({"tokens": [1, 2, 3], "max_new_tokens": 6,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        parts, final = [], None
        with urllib.request.urlopen(req, timeout=60) as r:
            for line in r:
                if not line.strip():
                    continue
                obj = json.loads(line.decode())
                if obj.get("final", True):
                    final = obj
                    break
                parts.append(obj)
        assert final["ok"] and final["result"] == solo
        assert [p["token"] for p in parts] == solo
        assert final["seq"] == len(parts)
        assert final["cost"]["generated_tokens"] == 6


class _SlowStep:
    """Model wrapper stretching each decode iteration so a test can
    act mid-stream (kill a connection between tokens)."""

    def __init__(self, model, delay_s=0.02):
        self._m = model
        self._delay = delay_s
        self.spec = model.spec

    def prefill(self, *a, **k):
        return self._m.prefill(*a, **k)

    def prefill_chunk(self, *a, **k):
        return self._m.prefill_chunk(*a, **k)

    def decode_step(self, *a, **k):
        time.sleep(self._delay)
        return self._m.decode_step(*a, **k)


def test_kill_connection_mid_stream_zero_lost_zero_dup(monkeypatch):
    """Kill the wire connection while tokens are streaming through a
    router: the failover re-run must not replay already-delivered
    partial tokens as new client-visible work — the client stream
    stays strictly ordered with no gaps and no duplicates, and the
    final result is the complete sequence."""
    monkeypatch.setenv("MXNET_TPU_WIRE", "1")
    from mxnet_tpu.serving import ServingRouter

    # two seats with IDENTICAL weights: greedy decode is deterministic,
    # so the failover re-run regenerates the same sequence and the
    # router's index dedupe hides the replayed prefix
    engines = [_mk_engine(_SlowStep(_mk_model()), max_new_tokens=12,
                          engine_id=f"kill{i}") for i in range(2)]
    with engines[0], engines[1]:
        for eng in engines:
            eng.expose()
        solo = engines[0].infer([1, 2, 3], max_new_tokens=12).tolist()
        urls = {eng.engine_id: f"http://127.0.0.1:{eng._expo.port}"
                for eng in engines}
        with ServingRouter(urls, poll_interval_s=0.1) as router:
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and not all(
                    row.get("transport") == "wire"
                    for row in router.scoreboard().values()):
                time.sleep(0.05)
            assert all(row.get("transport") == "wire"
                       for row in router.scoreboard().values()), \
                router.scoreboard()
            fut = router.submit([1, 2, 3], max_new_tokens=12,
                                stream=True)
            seen = []
            killed = {"done": False}
            for part in fut.stream(timeout=60):
                seen.append(part)
                if len(seen) == 3 and not killed["done"]:
                    killed["done"] = True
                    # sever the dispatch connections of the seat
                    # CARRYING the stream (its partials stop mid-
                    # flight); the router must fail the dispatch over
                    # to the healthy sibling
                    busy = {eid for eid, row
                            in router.scoreboard().items()
                            if row.get("outstanding")}
                    assert busy, router.scoreboard()
                    for eng in engines:
                        if eng.engine_id in busy:
                            eng._wire.kill_connections()
            out = fut.result(timeout=0).tolist()
        assert killed["done"]
        assert out == solo
        idxs = [p["index"] for p in seen]
        toks = [p["token"] for p in seen]
        # zero duplicated: indices strictly increasing; zero lost:
        # every index present and every token the right one
        assert idxs == list(range(len(seen))), idxs
        assert toks == solo[:len(seen)], (toks, solo)
        assert len(seen) == len(solo)
        # the engines saw the request twice (original + failover re-
        # run) — but the CLIENT saw every token exactly once
        assert sum(e.stats.count("submitted") for e in engines) >= 2


def test_router_local_stream_and_parity():
    """Router-fronted in-process decode seat: streamed parts relay
    through, byte-identical to a direct engine run; non-streamed
    router result matches too."""
    from mxnet_tpu.serving import ServingRouter

    with _mk_engine(_mk_model()) as eng:
        solo = eng.infer([5, 4, 3], max_new_tokens=7).tolist()
        with ServingRouter(engines=[eng]) as router:
            fut = router.submit([5, 4, 3], max_new_tokens=7,
                                stream=True)
            parts = [p["token"] for p in fut.stream(timeout=60)]
            assert parts == solo
            assert fut.result(timeout=0).tolist() == solo
            plain = router.submit([5, 4, 3], max_new_tokens=7) \
                .result(timeout=60)
            assert np.asarray(plain).tolist() == solo


# ---------------------------------------------------------------------------
# observability: SLO rule, metrics, bundle section, fleet dump
# ---------------------------------------------------------------------------
def test_decode_observability_surface(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_SLO", "1")
    import io
    import os
    import sys

    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    from telemetry_dump import decode_split

    from mxnet_tpu.telemetry import recorder as _recorder
    from mxnet_tpu.telemetry.registry import REGISTRY

    eid = "obs_decode"
    with _mk_engine(_mk_model(), engine_id=eid) as eng:
        eng.warmup()
        # the decode_inter_token LatencySLO is declared by default
        assert eng.alerts is not None
        assert eng.alerts.evaluator.get("decode_inter_token") is not None
        # the scheduler-state flight-bundle section is live
        fn = _recorder.RECORDER.get_section(f"decode_scheduler_{eid}")
        assert fn is not None
        eng.infer([1, 2, 3, 4], max_new_tokens=5)
        state = fn()
        assert state["engine_id"] == eid
        assert "kv" in state and "prefill_queue_depth" in state
        # inter-token + ttft histograms moved under this engine's label
        fam = REGISTRY.get("mxnet_tpu_serving_inter_token_latency_ms")
        child = fam.labels(engine_id=eid)
        assert child.count >= 4          # 5 tokens -> >= 4 gaps
        assert REGISTRY.get("mxnet_tpu_serving_ttft_ms") \
            .labels(engine_id=eid).count == 1
        snap = eng.snapshot()
        assert snap["decode"]["tokens"] == 5
        assert snap["kv"]["pages_total"] == 24
        # telemetry_dump's fleet decode split reads the same families
        text = REGISTRY.render_prometheus()
        split = decode_split(text)
        assert split[eid]["tokens"] >= 5
        assert split[eid]["occupancy"] == 0.0   # drained
        assert split[eid]["join"] >= 1 and split[eid]["leave"] >= 1
    # section retired with the engine
    assert _recorder.RECORDER.get_section(f"decode_scheduler_{eid}") \
        is None


def test_warmup_manifest_round_trip():
    """Decode shape keys ((0, prefill_len) / (rows, width)) ride the
    fleet manifest machinery unchanged; an encoder-shaped replay
    skips them instead of crashing."""
    from mxnet_tpu import compile_cache

    with _mk_engine(_mk_model()) as eng:
        eng.warmup()
        manifest = eng.warmup_manifest()
    shapes = compile_cache.manifest_shapes(manifest)
    assert (0, 8) in shapes and (0, 16) in shapes
    assert any(r >= 1 for r, _w in shapes)
    # replay into a FRESH engine: every manifest shape is compatible,
    # so the warmup covers exactly the visited set
    with _mk_engine(_mk_model()) as eng2:
        eng2.warmup(manifest=manifest)
        assert set(compile_cache.manifest_shapes(
            eng2.warmup_manifest())) == set(shapes)


def test_stop_abort_fails_streams_loudly():
    """stop(drain=False) ends live streams with the engine-stopped
    failure after the received parts — the stream contract."""
    from mxnet_tpu.serving import EngineStoppedError

    eng = _mk_engine(_SlowStep(_mk_model(), delay_s=0.05),
                     max_new_tokens=50)
    eng.start()
    fut = eng.submit([1, 2, 3], max_new_tokens=50, stream=True)
    got = []
    with pytest.raises(EngineStoppedError):
        for part in fut.stream(timeout=30):
            got.append(part)
            if len(got) == 2:
                threading.Thread(target=eng.stop,
                                 kwargs={"drain": False},
                                 name="abort", daemon=True).start()
    assert len(got) >= 2
    assert eng.pool.occupancy()["pages_used"] == 0
