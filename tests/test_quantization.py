"""INT8 quantization tests (reference tests/python/quantization/ scope):
quantize/dequantize numerics, int8 compute ops vs fp32, the Gluon
quantize_net rewrite, and the HLO dtype proof that matmuls execute on
s8 operands with s32 accumulation.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.test_utils import assert_almost_equal

RS = np.random.RandomState(7)


def test_quantize_v2_roundtrip():
    x = RS.randn(4, 5).astype(np.float32)
    q, lo, hi = nd.quantize_v2(nd.array(x))
    assert q.dtype == np.int8
    deq = nd.dequantize_v2(q, lo, hi).asnumpy()
    amax = np.abs(x).max()
    assert np.abs(deq - x).max() <= amax / 127.0 + 1e-6


def test_quantize_v2_calibrated_range():
    x = RS.randn(4, 5).astype(np.float32)
    q, lo, hi = nd.quantize_v2(nd.array(x), min_calib_range=-2.0,
                               max_calib_range=2.0)
    assert float(hi.asnumpy()[0]) == 2.0
    deq = nd.dequantize_v2(q, lo, hi).asnumpy()
    assert np.abs(deq - np.clip(x, -2, 2)).max() <= 2.0 / 127.0 + 1e-6


def test_quantized_fully_connected_vs_fp32():
    from mxnet_tpu.ndarray.op_impl_quant import quantize_weight, quantize_act
    x = RS.randn(8, 16).astype(np.float32)
    w = RS.randn(4, 16).astype(np.float32)
    b = RS.randn(4).astype(np.float32)
    import jax.numpy as jnp
    wq, ws = quantize_weight(jnp.asarray(w))
    xq, xs = quantize_act(jnp.asarray(x))
    out = nd.quantized_fully_connected(
        nd.array(np.asarray(xq)), nd.array(np.asarray(wq)),
        nd.array(np.asarray(xs)), nd.array(np.asarray(ws)), nd.array(b),
        num_hidden=4).asnumpy()
    ref = x @ w.T + b
    scale = np.abs(ref).max()
    assert np.abs(out - ref).max() / scale < 0.05, np.abs(out - ref).max()


def test_quantized_matmul_hlo_is_int8():
    """The compiled computation must multiply s8 operands into an s32
    accumulator — the MXU int8 path (VERDICT r1 item #7 'assert on HLO
    dtype')."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ndarray.register import get_op

    fn = get_op("quantized_fully_connected").fn
    xq = jnp.zeros((8, 16), jnp.int8)
    wq = jnp.zeros((4, 16), jnp.int8)
    s = jnp.ones((1,), jnp.float32)
    txt = jax.jit(lambda a, b: fn(a, b, s, s, num_hidden=4)).lower(xq, wq)\
        .compile().as_text()
    assert "s8[" in txt, txt[:800]
    assert "s32[" in txt, txt[:800]


def test_quantized_conv_vs_fp32():
    from mxnet_tpu.ndarray.op_impl_quant import quantize_weight, quantize_act
    import jax.numpy as jnp
    x = RS.randn(2, 3, 8, 8).astype(np.float32)
    w = RS.randn(5, 3, 3, 3).astype(np.float32)
    wq, ws = quantize_weight(jnp.asarray(w))
    xq, xs = quantize_act(jnp.asarray(x))
    out = nd.quantized_conv(
        nd.array(np.asarray(xq)), nd.array(np.asarray(wq)),
        nd.array(np.asarray(xs)), nd.array(np.asarray(ws)),
        kernel=(3, 3), num_filter=5, pad=(1, 1), no_bias=True).asnumpy()
    ref = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                         num_filter=5, pad=(1, 1), no_bias=True).asnumpy()
    scale = np.abs(ref).max()
    assert np.abs(out - ref).max() / scale < 0.05


def _mlp():
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu", in_units=16),
            nn.Dense(8, in_units=32))
    net.initialize(init=mx.initializer.Xavier())
    return net


def test_quantize_net_dynamic():
    from mxnet_tpu.contrib.quantization import quantize_net, QuantizedDense
    net = _mlp()
    x = nd.array(RS.randn(8, 16).astype(np.float32))
    ref = net(x).asnumpy()
    qnet = quantize_net(net)
    layers = list(qnet._children.values())
    assert all(isinstance(l, QuantizedDense) for l in layers), layers
    out = qnet(x).asnumpy()
    scale = np.abs(ref).max()
    assert np.abs(out - ref).max() / scale < 0.1, np.abs(out - ref).max()


def test_quantize_net_calibrated():
    from mxnet_tpu.contrib.quantization import quantize_net
    net = _mlp()
    x = nd.array(RS.randn(8, 16).astype(np.float32))
    ref = net(x).asnumpy()
    calib = [[nd.array(RS.randn(8, 16).astype(np.float32))] for _ in range(4)]
    qnet = quantize_net(net, calib_data=calib)
    assert qnet._quant_ranges  # static ranges were collected
    out = qnet(x).asnumpy()
    scale = np.abs(ref).max()
    assert np.abs(out - ref).max() / scale < 0.15


def test_quantize_net_conv():
    from mxnet_tpu.contrib.quantization import quantize_net, QuantizedConv2D
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, kernel_size=3, padding=1, in_channels=3))
    net.initialize(init=mx.initializer.Xavier())
    x = nd.array(RS.randn(2, 3, 8, 8).astype(np.float32))
    ref = net(x).asnumpy()
    qnet = quantize_net(net)
    assert isinstance(list(qnet._children.values())[0], QuantizedConv2D)
    out = qnet(x).asnumpy()
    scale = np.abs(ref).max()
    assert np.abs(out - ref).max() / scale < 0.1


def test_quantize_net_save_load_roundtrip(tmp_path):
    """Quantized nets checkpoint through the normal parameter path
    (review regression: int8 weights/scales/ranges are registered
    Parameters, not loose attributes)."""
    from mxnet_tpu.contrib.quantization import quantize_net
    net = _mlp()
    x = nd.array(RS.randn(8, 16).astype(np.float32))
    calib = [[nd.array(RS.randn(8, 16).astype(np.float32))] for _ in range(2)]
    qnet = quantize_net(net, calib_data=calib)
    want = qnet(x).asnumpy()
    f = str(tmp_path / "q.params")
    qnet.save_parameters(f)

    net2 = quantize_net(_mlp())  # same structure, fresh weights
    net2.load_parameters(f)
    got = net2(x).asnumpy()
    assert_almost_equal(got, want, rtol=1e-5, atol=1e-6)


def test_quantize_net_folds_batchnorm():
    """Conv->BN->relu chains: quantize_net folds the BN inference
    affine into the int8 conv (per-out-channel weight scales) and
    removes the BN from the graph; outputs stay close to float
    predict-mode output."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.contrib.quantization import quantize_net
    from mxnet_tpu.gluon import nn

    rng = np.random.RandomState(5)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, kernel_size=3, padding=1, use_bias=False),
            nn.BatchNorm(), nn.Activation("relu"),
            nn.Conv2D(4, kernel_size=1, use_bias=False), nn.BatchNorm())
    net.initialize(init=mx.initializer.Xavier())
    x = nd.array(rng.randn(2, 3, 8, 8).astype(np.float32))
    # give BN non-trivial running stats
    for _ in range(3):
        with mx.autograd.record():
            net(nd.array(rng.randn(2, 3, 8, 8).astype(np.float32) * 2 + 0.3))
    with mx.autograd.predict_mode():
        ref = net(x).asnumpy()
        quantize_net(net, calib_data=[[x]], ctx=mx.current_context())
        got = net(x).asnumpy()

    kinds = [type(c).__name__ for c in net._children.values()]
    assert "BatchNorm" not in kinds, kinds
    assert kinds.count("Identity") == 2, kinds
    assert kinds.count("QuantizedConv2D") == 2, kinds
    # int8 tolerance: ~1% of dynamic range
    tol = 0.02 * max(1e-3, float(np.abs(ref).max()))
    np.testing.assert_allclose(got, ref, atol=tol, rtol=0.1)


def test_s8_interfaces_chain():
    """quantize_net(s8_interfaces=True): chained convs exchange s8
    tensors (producer requantizes into the consumer's calibrated
    scale); numerics match the bf16-interface int8 net closely and the
    chain actually engages."""
    import numpy as onp
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.contrib.quantization import (quantize_net,
                                                QuantizedConv2D)

    rs = onp.random.RandomState(0)
    x = nd.array(rs.rand(2, 3, 16, 16).astype("f"))

    def build():
        net = nn.HybridSequential()
        net.add(nn.Conv2D(8, 3, padding=1, use_bias=False),
                nn.BatchNorm(),
                nn.Activation("relu"),
                nn.Conv2D(8, 3, padding=1, use_bias=False),
                nn.BatchNorm(),
                nn.Activation("relu"),
                nn.Conv2D(4, 1))
        net.initialize(init=mx.initializer.Xavier())
        net(x)  # finalize shapes
        return net

    onp.random.seed(0); mx.random.seed(0)
    ref_net = build()
    # global name counters differ per instance — copy params by ORDER
    ref_params = [v.data() for _, v in ref_net.collect_params().items()]

    def clone():
        onp.random.seed(0); mx.random.seed(0)
        n = build()
        for (_, v), val in zip(n.collect_params().items(), ref_params):
            v.set_data(val)
        return n

    float_out = ref_net(x).asnumpy()
    q_plain = quantize_net(clone(), calib_data=[(x,)])
    out_plain = q_plain(x).asnumpy()
    q_s8 = quantize_net(clone(), calib_data=[(x,)], s8_interfaces=True)
    # the chain engaged: first two convs requantize, followers consume
    convs = [c for c in q_s8._children.values()
             if isinstance(c, QuantizedConv2D)]
    assert len(convs) == 3
    assert convs[0]._out_req is not None and convs[1]._out_req is not None
    assert convs[1]._prequantized and convs[2]._prequantized
    assert convs[2]._out_req is None  # tail conv emits float
    out_s8 = q_s8(x).asnumpy()
    # both int8 variants agree closely (same scales; only the
    # intermediate rounding point differs) and track the float net
    assert onp.abs(out_s8 - out_plain).max() < 0.12
    rel = onp.abs(out_s8 - float_out).mean() / (onp.abs(float_out).mean() + 1e-6)
    assert rel < 0.1, rel
    # hybridize works with s8 interfaces
    q_s8.hybridize()
    out_h = q_s8(x).asnumpy()
    assert onp.allclose(out_h, out_s8, atol=1e-5)
    # without calibration the mode refuses (dynamic ranges can't chain)
    with pytest.raises(Exception):
        quantize_net(clone(), s8_interfaces=True)


def test_s8_interfaces_validates_before_rewrite():
    """Review regression: the calib_data check fires BEFORE the
    destructive rewrite — the net stays float on failure."""
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.contrib.quantization import quantize_net
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, 3, padding=1))
    net.initialize()
    net(mx.nd.zeros((1, 2, 8, 8)))
    with pytest.raises(Exception, match="calib_data"):
        quantize_net(net, s8_interfaces=True)
    # net unchanged: still a float Conv2D
    assert type(list(net._children.values())[0]) is nn.Conv2D


def test_s8_interfaces_skip_shared_conv():
    """Advisor r4: chaining mutates the conv INSTANCE, so a producer
    shared by a second dataflow path would return s8 there too. The
    chain pass must leave any block reachable from more than one
    parent unchained."""
    import numpy as onp
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.contrib.quantization import (QuantizedConv2D,
                                                _chain_s8_interfaces,
                                                quantize_net)

    rs = onp.random.RandomState(0)
    x = nd.array(rs.rand(2, 3, 16, 16).astype("f"))
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, use_bias=False),
            nn.Activation("relu"),
            nn.Conv2D(8, 3, padding=1, use_bias=False))
    net.initialize(init=mx.initializer.Xavier())
    net(x)
    q = quantize_net(net, calib_data=[(x,)])  # bf16 interfaces
    qconvs = [c for c in q._children.values()
              if isinstance(c, QuantizedConv2D)]
    assert len(qconvs) == 2

    # control: unshared, the pass chains producer -> consumer
    _chain_s8_interfaces(q)
    assert qconvs[0]._out_req is not None and qconvs[1]._prequantized
    # reset (object.__setattr__: Block guards Parameter-attr rebinding)
    object.__setattr__(qconvs[0], "_out_req", None)
    qconvs[1]._prequantized = False

    # share the producer into a second parent: chaining must skip it
    root = nn.HybridSequential()
    branch = nn.HybridSequential()
    branch.add(qconvs[0])
    root.add(q, branch)
    _chain_s8_interfaces(root)
    assert qconvs[0]._out_req is None
    assert not qconvs[1]._prequantized


def test_entropy_threshold_ignores_outliers():
    """The KL-optimal threshold lands near the bulk of a skewed
    distribution, not at the outlier max (reference
    _get_optimal_thresholds semantics)."""
    import numpy as onp
    from mxnet_tpu.contrib.quantization import _optimal_threshold

    rs = onp.random.RandomState(0)
    bulk = rs.randn(100000).astype("f4")          # ~N(0,1)
    outliers = onp.full(100, 100.0, "f4")          # 0.1% at 100x
    vals = onp.concatenate([bulk, outliers])
    th = _optimal_threshold(vals)
    assert th < 20.0, th          # far below the minmax range (100)
    assert th > 2.0, th           # but still covers the bulk
    # pure gaussian: threshold close to its max (nothing to clip away)
    th_clean = _optimal_threshold(bulk)
    assert th_clean > 0.5 * float(onp.abs(bulk).max())
    # degenerate inputs
    assert _optimal_threshold(onp.zeros(10, "f4")) == 0.0


def test_entropy_calibration_reduces_quant_error():
    """quantize_net(calib_mode='entropy') picks narrower ranges than
    minmax on outlier-skewed activations and lowers the int8
    quantization error proxy (VERDICT r4 missing #2 done-criterion)."""
    import numpy as onp
    from mxnet_tpu import nd
    from mxnet_tpu.contrib.quantization import (CalibrationCollector,
                                                quantize_net)
    from mxnet_tpu.gluon import nn

    rs = onp.random.RandomState(1)
    act = rs.randn(4096).astype("f4")
    act[:4] = 80.0                                # rare huge outliers

    cmm = CalibrationCollector("naive")
    cen = CalibrationCollector("entropy")
    for c in (cmm, cen):
        c.collect("l", act)
    (lo_mm, hi_mm) = cmm.ranges()["l"]
    (lo_en, hi_en) = cen.ranges()["l"]
    amax_mm = max(abs(lo_mm), abs(hi_mm))
    amax_en = max(abs(lo_en), abs(hi_en))
    assert amax_en < 0.5 * amax_mm, (amax_en, amax_mm)

    def quant_err(amax):
        # mean ABSOLUTE error: the outlier-robust proxy (squared error
        # is dominated by the 4 clipped outliers by construction —
        # clipping them is exactly the point of entropy calibration)
        scale = amax / 127.0
        q = onp.clip(onp.round(act / scale), -127, 127) * scale
        return float(onp.abs(q - act).mean())

    assert quant_err(amax_en) < quant_err(amax_mm)

    # e2e: the mode plumbs through quantize_net and the net still runs
    x_np = rs.rand(32, 8).astype("f4")
    x_np[0, 0] = 60.0                             # input outlier
    x = nd.array(x_np)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize(init=mx.initializer.Xavier())
    float_out = net(x).asnumpy()
    q = quantize_net(net, calib_data=[(x,)], calib_mode="entropy")
    out = q(x).asnumpy()
    assert out.shape == float_out.shape
    assert onp.isfinite(out).all()
    # bad mode name fails loudly
    with pytest.raises(Exception, match="calib_mode"):
        CalibrationCollector("median")
