"""Subprocess helper for the tenancy cross-process goldens: one
ServingEngine hosting TWO models behind a :class:`ModelRegistry`
(``m-a``: identity, ``m-b``: identity + 100) with the binary dispatch
wire and the HTTP telemetry endpoint set up.

Prints ``PORT <http> WIRE <wire>`` on stdout once serving, then reads
stdin line commands until EOF (the parent test owns the lifetime):

- ``SWAP`` — live hot-swap ``m-b`` to v2 (identity + 200) and print
  ``SWAPPED`` — the parent verifies the /healthz version flip (the
  router-canary re-TOFU surface) and that post-swap wire traffic runs
  the new fn.

Usage: python tenancy_engine_worker.py <engine_id>
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MXNET_TPU_WATCHDOG", "0")

import numpy as np  # noqa: E402

from mxnet_tpu import nd  # noqa: E402
from mxnet_tpu.serving import ModelRegistry, ServingEngine  # noqa: E402


def _offset_model(off):
    def model(ids, token_types, valid_length, segment_ids, positions):
        return nd.array(
            ids.asnumpy().astype(np.float32)[..., None] + off)
    return model


def main():
    engine_id = sys.argv[1] if len(sys.argv) > 1 else "tenancy-worker"
    reg = ModelRegistry()
    reg.register("m-a", _offset_model(0.0), version="v1")
    reg.register("m-b", _offset_model(100.0), version="v1")
    eng = ServingEngine(reg, bucket_lens=(32,), max_rows=2,
                        engine_id=engine_id)
    with eng:
        srv = eng.expose(port=0)
        print(f"PORT {srv.port} WIRE {eng._wire.port}", flush=True)
        for line in sys.stdin:
            if line.strip() == "SWAP":
                eng.swap_model(_offset_model(200.0), model_id="m-b",
                               version="v2")
                print("SWAPPED", flush=True)


if __name__ == "__main__":
    main()
