"""Golden tests for every fused optimizer update op vs hand-written
numpy (reference src/operator/optimizer_op.cc update formulas; SURVEY
§2.1 optimizer row). Also checks the in-place `mutates` contract: state
inputs (mom/mean/var/...) are updated in place like the reference's
aux-state writes, and `out=` writes the new weight.
"""
import numpy as np
import pytest

from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal

RS = np.random.RandomState(11)
SHAPE = (4, 3)


def _wg():
    return (RS.randn(*SHAPE).astype(np.float32),
            RS.randn(*SHAPE).astype(np.float32))


def _run(op_name, arrays, params):
    """Invoke the op with out= pointing at the weight (the Updater call
    convention) and return (new_weight, state NDArrays)."""
    nds = [nd.array(a) for a in arrays]
    out = nd.zeros(SHAPE)
    getattr(nd, op_name)(*nds, out=out, **params)
    return out.asnumpy(), [x.asnumpy() for x in nds]


def _clip(g, c):
    return np.clip(g, -c, c) if c > 0 else g


def test_sgd_update():
    w, g = _wg()
    new_w, _ = _run("sgd_update", [w, g],
                    {"lr": 0.1, "wd": 0.01, "rescale_grad": 0.5,
                     "clip_gradient": 0.4})
    gs = _clip(g * 0.5, 0.4)
    assert_almost_equal(new_w, w - 0.1 * (gs + 0.01 * w), rtol=1e-5, atol=1e-6)


def test_sgd_mom_update():
    w, g = _wg()
    mom = RS.randn(*SHAPE).astype(np.float32)
    new_w, states = _run("sgd_mom_update", [w, g, mom.copy()],
                         {"lr": 0.1, "momentum": 0.9, "wd": 0.01})
    want_mom = 0.9 * mom - 0.1 * (g + 0.01 * w)
    assert_almost_equal(new_w, w + want_mom, rtol=1e-5, atol=1e-6)
    assert_almost_equal(states[2], want_mom, rtol=1e-5, atol=1e-6)  # in-place


def test_nag_mom_update():
    w, g = _wg()
    mom = RS.randn(*SHAPE).astype(np.float32)
    new_w, states = _run("nag_mom_update", [w, g, mom.copy()],
                         {"lr": 0.1, "momentum": 0.9, "wd": 0.01})
    gw = g + 0.01 * w
    want_mom = 0.9 * mom + gw
    assert_almost_equal(new_w, w - 0.1 * (gw + 0.9 * want_mom), rtol=1e-5, atol=1e-6)
    assert_almost_equal(states[2], want_mom, rtol=1e-5, atol=1e-6)


def test_mp_sgd_update():
    w32, g = _wg()
    w16 = w32.astype(np.float16)
    nds = [nd.array(w16.astype(np.float16)), nd.array(g.astype(np.float16)),
           nd.array(w32)]
    out = nd.zeros(SHAPE, dtype="float16")
    nd.mp_sgd_update(*nds, out=out, lr=0.1, wd=0.01)
    want32 = w32 - 0.1 * (g.astype(np.float16).astype(np.float32) + 0.01 * w32)
    assert_almost_equal(nds[2].asnumpy(), want32, rtol=1e-3, atol=1e-4)
    assert_almost_equal(out.asnumpy().astype(np.float32), want32,
                        rtol=1e-2, atol=1e-2)  # half-precision copy


def test_mp_sgd_mom_update():
    w32, g = _wg()
    mom = np.zeros(SHAPE, np.float32)
    nds = [nd.array(w32.astype(np.float16)), nd.array(g.astype(np.float16)),
           nd.array(mom), nd.array(w32)]
    out = nd.zeros(SHAPE, dtype="float16")
    nd.mp_sgd_mom_update(*nds, out=out, lr=0.1, momentum=0.9, wd=0.0)
    g32 = g.astype(np.float16).astype(np.float32)
    want_mom = -0.1 * g32
    assert_almost_equal(nds[2].asnumpy(), want_mom, rtol=1e-3, atol=1e-4)
    assert_almost_equal(nds[3].asnumpy(), w32 + want_mom, rtol=1e-3, atol=1e-4)


def test_adam_update():
    w, g = _wg()
    mean = RS.randn(*SHAPE).astype(np.float32) * 0.1
    var = np.abs(RS.randn(*SHAPE)).astype(np.float32) * 0.1
    new_w, states = _run("adam_update", [w, g, mean.copy(), var.copy()],
                         {"lr": 0.01, "beta1": 0.9, "beta2": 0.999,
                          "epsilon": 1e-8, "wd": 0.05})
    gw = g + 0.05 * w
    want_mean = 0.9 * mean + 0.1 * gw
    want_var = 0.999 * var + 0.001 * gw ** 2
    want_w = w - 0.01 * want_mean / (np.sqrt(want_var) + 1e-8)
    assert_almost_equal(new_w, want_w, rtol=1e-5, atol=1e-6)
    assert_almost_equal(states[2], want_mean, rtol=1e-5, atol=1e-6)
    assert_almost_equal(states[3], want_var, rtol=1e-5, atol=1e-6)


def test_adamw_update():
    w, g = _wg()
    mean = np.zeros(SHAPE, np.float32)
    var = np.zeros(SHAPE, np.float32)
    new_w, _ = _run("adamw_update", [w, g, mean, var],
                    {"lr": 0.01, "wd": 0.1, "eta": 1.0})
    want_mean = 0.1 * g
    want_var = 0.001 * g ** 2
    upd = want_mean / (np.sqrt(want_var) + 1e-8) + 0.1 * w
    assert_almost_equal(new_w, w - 0.01 * upd, rtol=1e-4, atol=1e-5)


def test_rmsprop_update():
    w, g = _wg()
    n = np.abs(RS.randn(*SHAPE)).astype(np.float32)
    new_w, states = _run("rmsprop_update", [w, g, n.copy()],
                         {"lr": 0.01, "gamma1": 0.9, "epsilon": 1e-8})
    want_n = 0.9 * n + 0.1 * g ** 2
    assert_almost_equal(new_w, w - 0.01 * g / np.sqrt(want_n + 1e-8),
                        rtol=1e-5, atol=1e-6)
    assert_almost_equal(states[2], want_n, rtol=1e-5, atol=1e-6)


def test_rmspropalex_update():
    w, g = _wg()
    n = np.abs(RS.randn(*SHAPE)).astype(np.float32)
    gacc = RS.randn(*SHAPE).astype(np.float32) * 0.1
    delta = np.zeros(SHAPE, np.float32)
    new_w, states = _run("rmspropalex_update",
                         [w, g, n.copy(), gacc.copy(), delta.copy()],
                         {"lr": 0.01, "gamma1": 0.95, "gamma2": 0.9})
    want_n = 0.95 * n + 0.05 * g ** 2
    want_g = 0.95 * gacc + 0.05 * g
    want_d = -0.01 * g / np.sqrt(want_n - want_g ** 2 + 1e-8)
    assert_almost_equal(new_w, w + want_d, rtol=1e-4, atol=1e-5)
    assert_almost_equal(states[2], want_n, rtol=1e-5, atol=1e-6)
    assert_almost_equal(states[3], want_g, rtol=1e-5, atol=1e-6)
    assert_almost_equal(states[4], want_d, rtol=1e-4, atol=1e-5)


def test_ftrl_update():
    w, g = _wg()
    z = RS.randn(*SHAPE).astype(np.float32) * 0.1
    n = np.abs(RS.randn(*SHAPE)).astype(np.float32) * 0.1
    new_w, states = _run("ftrl_update", [w, g, z.copy(), n.copy()],
                         {"lr": 0.1, "lamda1": 0.01, "beta": 1.0, "wd": 0.01})
    want_n = n + g ** 2
    sigma = (np.sqrt(want_n) - np.sqrt(n)) / 0.1
    want_z = z + g - sigma * w
    want_w = np.where(np.abs(want_z) <= 0.01, 0.0,
                      -(want_z - np.sign(want_z) * 0.01)
                      / ((1.0 + np.sqrt(want_n)) / 0.1 + 0.01))
    assert_almost_equal(new_w, want_w, rtol=1e-4, atol=1e-5)
    assert_almost_equal(states[2], want_z, rtol=1e-4, atol=1e-5)
    assert_almost_equal(states[3], want_n, rtol=1e-5, atol=1e-6)


def test_signsgd_update():
    w, g = _wg()
    new_w, _ = _run("signsgd_update", [w, g], {"lr": 0.1, "wd": 0.01})
    assert_almost_equal(new_w, w - 0.1 * (np.sign(g) + 0.01 * w),
                        rtol=1e-5, atol=1e-6)


def test_signum_update():
    w, g = _wg()
    mom = RS.randn(*SHAPE).astype(np.float32)
    new_w, states = _run("signum_update", [w, g, mom.copy()],
                         {"lr": 0.1, "momentum": 0.9, "wd": 0.01})
    gw = g + 0.01 * w
    want_mom = 0.9 * mom - 0.1 * gw
    assert_almost_equal(new_w, w + 0.1 * np.sign(want_mom), rtol=1e-5, atol=1e-6)
    assert_almost_equal(states[2], want_mom, rtol=1e-5, atol=1e-6)


def test_adagrad_update():
    w, g = _wg()
    hist = np.abs(RS.randn(*SHAPE)).astype(np.float32) * 0.1
    new_w, states = _run("adagrad_update", [w, g, hist.copy()],
                         {"lr": 0.1, "epsilon": 1e-7})
    want_h = hist + g ** 2
    assert_almost_equal(new_w, w - 0.1 * g / np.sqrt(want_h + 1e-7),
                        rtol=1e-5, atol=1e-6)
    assert_almost_equal(states[2], want_h, rtol=1e-5, atol=1e-6)


def test_adadelta_update():
    w, g = _wg()
    ag = np.abs(RS.randn(*SHAPE)).astype(np.float32) * 0.1
    ad = np.abs(RS.randn(*SHAPE)).astype(np.float32) * 0.1
    new_w, states = _run("adadelta_update", [w, g, ag.copy(), ad.copy()],
                         {"rho": 0.9, "epsilon": 1e-5})
    want_ag = 0.9 * ag + 0.1 * g ** 2
    delta = np.sqrt(ad + 1e-5) / np.sqrt(want_ag + 1e-5) * g
    want_ad = 0.9 * ad + 0.1 * delta ** 2
    assert_almost_equal(new_w, w - delta, rtol=1e-4, atol=1e-5)
    assert_almost_equal(states[2], want_ag, rtol=1e-5, atol=1e-6)
    assert_almost_equal(states[3], want_ad, rtol=1e-5, atol=1e-6)


def test_lamb_update_phases():
    w, g = _wg()
    mean = np.zeros(SHAPE, np.float32)
    var = np.zeros(SHAPE, np.float32)
    upd, states = _run("lamb_update_phase1", [w, g, mean, var],
                       {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-6,
                        "t": 1, "wd": 0.01})
    m = (0.1 * g) / (1 - 0.9)
    v = (0.001 * g ** 2) / (1 - 0.999)
    want_upd = m / (np.sqrt(v) + 1e-6) + 0.01 * w
    assert_almost_equal(upd, want_upd, rtol=1e-4, atol=1e-5)
    r1 = np.linalg.norm(w)
    r2 = np.linalg.norm(want_upd)
    out = nd.zeros(SHAPE)
    nd.lamb_update_phase2(nd.array(w), nd.array(want_upd),
                          nd.array(np.array([r1], np.float32)),
                          nd.array(np.array([r2], np.float32)),
                          out=out, lr=0.01)
    assert_almost_equal(out.asnumpy(), w - 0.01 * (r1 / r2) * want_upd,
                        rtol=1e-4, atol=1e-5)


def test_sparse_rsp_updates_match_dense():
    """row_sparse lazy updates touch only the rows present in the
    gradient and agree with the dense op on those rows (reference
    SGDMomLazyUpdateRspImpl contract; sparse.py convention was aligned
    with the dense op in round 1's advisor fix)."""
    from mxnet_tpu.ndarray import sparse as sp
    w = RS.randn(6, 3).astype(np.float32)
    rows = np.array([1, 4], np.int64)
    gval = RS.randn(2, 3).astype(np.float32)
    grad = sp.row_sparse_array((gval, rows), shape=(6, 3))
    weight = nd.array(w.copy())
    mom = nd.zeros((6, 3))
    out = sp.sgd_mom_update_rsp(weight, grad, mom, lr=0.1, momentum=0.9)
    dense_mom = np.zeros((6, 3), np.float32)
    dense_w = w.copy()
    dense_mom[rows] = 0.9 * dense_mom[rows] - 0.1 * gval
    dense_w[rows] += dense_mom[rows]
    assert_almost_equal(out.asnumpy() if hasattr(out, "asnumpy") else weight.asnumpy(),
                        dense_w, rtol=1e-5, atol=1e-6)


def test_multi_sgd_update_matches_single():
    ws = [RS.randn(*SHAPE).astype(np.float32) for _ in range(3)]
    gs = [RS.randn(*SHAPE).astype(np.float32) for _ in range(3)]
    lrs, wds = [0.1, 0.2, 0.05], [0.0, 0.01, 0.1]
    args = []
    for w, g in zip(ws, gs):
        args += [nd.array(w), nd.array(g)]
    outs = [nd.zeros(SHAPE) for _ in range(3)]
    nd.multi_sgd_update(*args, out=outs, lrs=lrs, wds=wds, num_weights=3)
    for w, g, lr, wd, o in zip(ws, gs, lrs, wds, outs):
        assert_almost_equal(o.asnumpy(), w - lr * (g + wd * w),
                            rtol=1e-5, atol=1e-6)


def test_multi_sgd_mom_update_matches_single():
    ws = [RS.randn(*SHAPE).astype(np.float32) for _ in range(2)]
    gs = [RS.randn(*SHAPE).astype(np.float32) for _ in range(2)]
    ms = [RS.randn(*SHAPE).astype(np.float32) for _ in range(2)]
    lrs, wds, mu = [0.1, 0.2], [0.01, 0.0], 0.9
    args, outs = [], []
    w_nd = [nd.array(w) for w in ws]
    m_nd = [nd.array(m) for m in ms]
    for w, g, m in zip(w_nd, gs, m_nd):
        args += [w, nd.array(g), m]
        outs += [w, m]
    nd.multi_sgd_mom_update(*args, out=outs, lrs=lrs, wds=wds, momentum=mu,
                            num_weights=2)
    for w, g, m, lr, wd, wn, mn in zip(ws, gs, ms, lrs, wds, w_nd, m_nd):
        want_m = mu * m - lr * (g + wd * w)
        assert_almost_equal(mn.asnumpy(), want_m, rtol=1e-5, atol=1e-6)
        assert_almost_equal(wn.asnumpy(), w + want_m, rtol=1e-5, atol=1e-6)


def test_multi_mp_sgd_updates():
    w32 = RS.randn(*SHAPE).astype(np.float32)
    g = RS.randn(*SHAPE).astype(np.float32)
    w16 = nd.array(w32.astype(np.float16))
    g16 = nd.array(g.astype(np.float16))
    m = nd.zeros(SHAPE)
    w32_nd = nd.array(w32)
    outs = [w16, m, w32_nd]
    nd.multi_mp_sgd_mom_update(w16, g16, m, w32_nd, out=outs,
                               lrs=[0.1], wds=[0.0], momentum=0.9,
                               num_weights=1)
    g32 = g.astype(np.float16).astype(np.float32)
    want_m = -0.1 * g32
    assert_almost_equal(m.asnumpy(), want_m, rtol=1e-3, atol=1e-4)
    assert_almost_equal(w32_nd.asnumpy(), w32 + want_m, rtol=1e-3, atol=1e-4)
    out = nd.zeros(SHAPE, dtype="float16")
    w32b = nd.array(w32)
    nd.multi_mp_sgd_update(nd.array(w32.astype(np.float16)), g16, w32b,
                           out=[out, w32b], lrs=[0.1], wds=[0.0],
                           num_weights=1)
    assert_almost_equal(w32b.asnumpy(), w32 - 0.1 * g32, rtol=1e-3, atol=1e-4)


def test_trainer_fused_update_single_dispatch():
    """Trainer._update batches every dense param into ONE multi-tensor
    op call (VERDICT r1 weak #2: no per-param eager dispatch loop)."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.ndarray import register as reg

    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu", in_units=4), nn.Dense(2, in_units=8))
    net.initialize(init=mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    x = nd.array(RS.randn(4, 4).astype(np.float32))
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()

    from mxnet_tpu.optimizer import optimizer as opt_mod
    calls = []
    orig = opt_mod._invoke

    def spy(op, inputs, params=None, **kw):
        calls.append(op.name)
        return orig(op, inputs, params, **kw)

    opt_mod._invoke = spy
    try:
        trainer.step(4)
    finally:
        opt_mod._invoke = orig
    assert calls.count("multi_sgd_mom_update") == 1, calls
    assert "sgd_mom_update" not in calls, calls


def test_multi_sgd_default_lrs_usable():
    """Declared defaults lrs=()/wds=() must fall back to the op's
    default hyperparameters, not crash (review regression)."""
    w, g = _wg()
    out = nd.zeros(SHAPE)
    nd.multi_sgd_update(nd.array(w), nd.array(g), out=[out], num_weights=1)
    assert_almost_equal(out.asnumpy(), w - 0.01 * g, rtol=1e-5, atol=1e-6)
