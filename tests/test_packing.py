"""Sequence packing (io/packing.py): round-trip exactness, layout
contract, data-layer wiring, and the packed bench leg's smoke.

The segment-isolation numerics (packed == unpacked through the flash
kernel and the full BERT stack) live in test_pallas.py /
test_transformer.py; this file owns the packing layer itself.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.io.packing import (PackedBatchify, PackedSeqIter,
                                  StreamingPacker, pack_sequences,
                                  packing_efficiency, stream_pack,
                                  unpack_sequences)


def _samples(rs, n, lo=3, hi=17, vocab=100):
    return [rs.randint(1, vocab, rs.randint(lo, hi)).astype(np.int32)
            for _ in range(n)]


def test_pack_roundtrip_restores_every_sample():
    rs = np.random.RandomState(0)
    seqs = _samples(rs, 37)
    labels = [s * 2 + 1 for s in seqs]
    batch = pack_sequences(seqs, 16, extras=[labels])
    back = unpack_sequences(batch)
    assert len(back) == len(seqs)
    for a, b in zip(back, seqs):
        assert np.array_equal(a, b)
    # extras share the layout: unpack any parallel array by placements
    back_l = unpack_sequences(batch.extras[0], batch.placements)
    for a, b in zip(back_l, labels):
        assert np.array_equal(a, b)


def test_pack_layout_contract():
    rs = np.random.RandomState(1)
    seqs = _samples(rs, 25)
    batch = pack_sequences(seqs, 16)
    R, L = batch.data.shape
    assert batch.segment_ids.shape == (R, L)
    assert batch.positions.shape == (R, L)
    assert batch.valid_length.shape == (R,)
    for r in range(R):
        vl = batch.valid_length[r]
        seg = batch.segment_ids[r]
        # contiguous from 0, padding strictly after, ids monotone 1..n
        assert (seg[:vl] > 0).all() and (seg[vl:] == 0).all()
        assert (np.diff(seg[:vl]) >= 0).all()
        # positions restart at 0 per segment and count up
        for sid in np.unique(seg[:vl]):
            pos = batch.positions[r][seg == sid]
            assert np.array_equal(pos, np.arange(len(pos)))
    # first-fit on arrival order: every sample placed, none split
    assert sum(len(s) for s in seqs) == int(batch.valid_length.sum())
    assert 0.0 < packing_efficiency(batch) <= 1.0


def test_pack_rejects_bad_lengths():
    with pytest.raises(ValueError):
        pack_sequences([np.arange(20)], 16)
    with pytest.raises(ValueError):
        pack_sequences([np.arange(0)], 16)
    with pytest.raises(ValueError):
        pack_sequences([np.arange(5)], 16, extras=[[np.arange(4)]])
    # max_rows refuses overflow placements instead of opening rows
    with pytest.raises(ValueError):
        pack_sequences([np.arange(1, 11)] * 3, 16, max_rows=1)


def test_packed_batchify_in_dataloader():
    from mxnet_tpu.gluon.data import DataLoader, SimpleDataset

    rs = np.random.RandomState(2)
    seqs = _samples(rs, 24)
    labels = [s + 1 for s in seqs]
    ds = SimpleDataset(list(zip(seqs, labels)))
    # process workers: PackedBatchify must stay numpy-only (worker-safe)
    dl = DataLoader(ds, batch_size=8, batchify_fn=PackedBatchify(16),
                    num_workers=2)
    seen = 0
    for data, seg, pos, vl, lab in dl:
        data, seg, lab = (x.asnumpy() if isinstance(x, nd.NDArray) else
                          np.asarray(x) for x in (data, seg, lab))
        assert data.shape == seg.shape == lab.shape
        assert ((lab == data + 1) | (seg == 0)).all()
        seen += int((np.asarray(seg) > 0).sum())
    assert seen == sum(len(s) for s in seqs)


def test_packed_seq_iter_module_contract():
    rs = np.random.RandomState(3)
    seqs = _samples(rs, 21)
    labels = [s + 3 for s in seqs]
    it = PackedSeqIter(seqs, 16, batch_size=4, labels=labels)
    names = [d.name for d in it.provide_data]
    assert names == ["data", "segment_ids", "positions", "valid_length"]
    rows = 0
    last = None
    for db in it:
        assert len(db.data) == 4 and len(db.label) == 1
        assert db.data[0].shape[0] == 4
        rows += 4 - (db.pad or 0)
        last = db
    assert rows == it.packed.data.shape[0]
    assert last is not None
    it.reset()
    assert it.next().data[0].shape[0] == 4


def test_streaming_packer_bounded_buffer_no_loss():
    """Online first-fit with a bounded open-row set: every token of an
    arbitrary stream comes back exactly once, rows respect the layout
    contract, and the open buffer never exceeds its bound."""
    rs = np.random.RandomState(4)
    seqs = _samples(rs, 83)
    labels = [s * 3 for s in seqs]
    packer = StreamingPacker(16, open_rows=3)
    rows = []
    for s, l in zip(seqs, labels):
        rows.extend(packer.add(s, (l,)))
        assert len(packer.open_rows) <= 3
    rows.extend(packer.flush())
    assert not packer.open_rows
    got, got_labels = [], []
    for row in rows:
        assert row.data.shape == (1, 16)
        vl = int(row.valid_length[0])
        assert (row.segment_ids[0, :vl] > 0).all()
        assert (row.segment_ids[0, vl:] == 0).all()
        got.extend(unpack_sequences(row))
        got_labels.extend(unpack_sequences(row.extras[0], row.placements))
    # rows close out of arrival order; compare as multisets of samples
    want = {s.tobytes() for s in seqs}
    assert {g.tobytes() for g in got} == want
    assert len(got) == len(seqs)
    for g, gl in zip(got, got_labels):
        assert np.array_equal(gl, g * 3)


def test_streaming_packer_validation():
    p = StreamingPacker(8, open_rows=2)
    with pytest.raises(ValueError):
        p.add(np.arange(9))
    with pytest.raises(ValueError):
        p.add(np.arange(1, 4), (np.arange(2),))
    p.add(np.arange(1, 4), (np.arange(3),))
    with pytest.raises(ValueError):
        p.add(np.arange(1, 4))          # extras arity changed
    with pytest.raises(ValueError):
        StreamingPacker(8, open_rows=0)


def test_stream_pack_batches_feed_epochs():
    """The corpus-reader entry: a generator of samples in, fixed
    (batch_rows, L) PackedBatches out, bounded memory, exact
    round-trip through placements."""
    rs = np.random.RandomState(5)
    seqs = _samples(rs, 41)
    labels = [s + 7 for s in seqs]
    batches = list(stream_pack(iter(zip(seqs, labels)), 16,
                               batch_rows=4, open_rows=3))
    total = 0
    for b in batches[:-1]:
        assert b.data.shape == (4, 16)
    assert batches[-1].data.shape[0] <= 4   # final flush may be short
    seen = set()
    for b in batches:
        for tok, lab in zip(unpack_sequences(b),
                            unpack_sequences(b.extras[0], b.placements)):
            assert np.array_equal(lab, tok + 7)
            seen.add(tok.tobytes())
            total += len(tok)
    assert total == sum(len(s) for s in seqs)
    assert seen == {s.tobytes() for s in seqs}
    # steady-state rows are dense on this mix
    effs = [packing_efficiency(b.segment_ids) for b in batches[:-1]]
    assert sum(effs) / len(effs) > 0.7


def test_segment_valid_len_op_dispatch():
    seg = nd.array(np.array([[1, 1, 2, 2, 0, 0], [1, 0, 0, 0, 0, 0]],
                            np.int32), dtype="int32")
    out = nd.segment_valid_len(seg)
    assert out.asnumpy().tolist() == [4, 1]


@pytest.mark.slow
def test_bench_packed_leg_smoke():
    """bench.py BENCH_PACKED=1 runs end-to-end at toy size and reports
    the packed-leg metrics (packing_efficiency, valid_tokens_per_sec)."""
    import json

    env = dict(os.environ, BENCH_MODEL="bert", BENCH_PACKED="1",
               BENCH_STEPS="2", BENCH_CHAIN="1", BENCH_WINDOWS="1",
               BENCH_BATCH="4", BENCH_SEQLEN="64",
               BENCH_PACK_ROWLEN="128", JAX_PLATFORMS="cpu")
    bench = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    r = subprocess.run([sys.executable, bench], env=env,
                       capture_output=True, text=True, timeout=560)
    assert r.returncode == 0, r.stderr[-2000:]
    line = [ln for ln in r.stdout.splitlines()
            if ln.startswith('{"metric"')][-1]
    rec = json.loads(line)
    assert rec["packed"] is True
    assert rec["packing_efficiency"] >= 0.9
    assert rec["valid_tokens_per_sec"] > 0
    # honest HBM accounting: the cost-model fallback must be flagged
    assert rec.get("hbm_est", False) in (True, False)
    if "hbm_frac" in rec and rec["hbm_frac"] > 1.0:
        assert rec["hbm_est"] is True
