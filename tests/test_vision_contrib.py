"""Contrib vision ops: ROIAlign, BilinearResize2D, AdaptiveAvgPooling2D,
box_encode/box_decode.

Goldens come from torch (CPU) where torch implements the same
semantics — torchvision isn't available, so ROIAlign is checked
against hand rules + gradient flow, while adaptive pooling and
align-corners bilinear resize check against torch.nn.functional
exactly. Reference: src/operator/contrib/{roi_align,bilinear_resize,
adaptive_avg_pooling}.cc + bounding_box.cc.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal, device_tols

torch = pytest.importorskip("torch")

RS = onp.random.RandomState(11)
# f32 contractions ride bf16 MXU passes on the real chip — goldens use
# the device tolerance table THERE, but keep the tight 1e-5 baseline on
# CPU (round-6 review: widening the CPU bar would hide regressions)
from mxnet_tpu.test_utils import _on_tpu
if _on_tpu():
    RTOL_G, ATOL_G = device_tols("float32")
else:
    RTOL_G, ATOL_G = 1e-5, 1e-5


def _nd(x, dtype="float32"):
    return nd.array(onp.asarray(x, dtype))


def test_adaptive_avg_pooling_vs_torch():
    x = RS.randn(2, 3, 7, 9).astype("f")
    for out_size in [(1, 1), (2, 3), (7, 9), (3, 3)]:
        got = nd.contrib.AdaptiveAvgPooling2D(_nd(x), output_size=out_size)
        want = torch.nn.functional.adaptive_avg_pool2d(
            torch.from_numpy(x), out_size).numpy()
        assert_almost_equal(got.asnumpy(), want, rtol=RTOL_G, atol=ATOL_G)


def test_adaptive_avg_pooling_grad():
    x = _nd(RS.randn(1, 2, 6, 6).astype("f"))
    x.attach_grad()
    with mx.autograd.record():
        y = nd.contrib.AdaptiveAvgPooling2D(x, output_size=(2, 2)).sum()
    y.backward()
    # each input cell participates in exactly one 3x3 bin -> grad 1/9
    assert_almost_equal(x.grad.asnumpy(),
                        onp.full((1, 2, 6, 6), 1.0 / 9.0, "f"),
                        rtol=1e-6, atol=1e-6)


def test_bilinear_resize_vs_torch():
    x = RS.randn(2, 3, 5, 7).astype("f")
    for oh, ow in [(10, 14), (3, 4), (5, 7), (1, 1)]:
        got = nd.contrib.BilinearResize2D(_nd(x), height=oh, width=ow)
        want = torch.nn.functional.interpolate(
            torch.from_numpy(x), size=(oh, ow), mode="bilinear",
            align_corners=True).numpy()
        assert_almost_equal(got.asnumpy(), want, rtol=RTOL_G, atol=ATOL_G)
    # scale mode
    got = nd.contrib.BilinearResize2D(_nd(x), scale_height=2.0,
                                      scale_width=2.0)
    assert got.shape == (2, 3, 10, 14)


def test_roi_align_basic():
    # constant image: any roi pools to the constant
    x = onp.full((1, 1, 8, 8), 3.5, "f")
    rois = _nd([[0.0, 1.0, 1.0, 6.0, 6.0]])
    out = nd.contrib.ROIAlign(_nd(x), rois, pooled_size=(2, 2),
                              spatial_scale=1.0)
    assert out.shape == (1, 1, 2, 2)
    assert_almost_equal(out.asnumpy(), onp.full((1, 1, 2, 2), 3.5, "f"),
                        rtol=1e-6, atol=1e-6)
    # linear ramp in x: bin centers reproduce the ramp values
    ramp = onp.tile(onp.arange(8, dtype="f")[None, None, None, :],
                    (1, 1, 8, 1))
    rois2 = _nd([[0.0, 0.0, 0.0, 4.0, 4.0]])
    out2 = nd.contrib.ROIAlign(_nd(ramp), rois2, pooled_size=(2, 2),
                               spatial_scale=1.0).asnumpy()
    # roi [0,4]x[0,4], 2x2 bins, sample mean per bin = bin center x
    assert_almost_equal(out2[0, 0], onp.array([[1.0, 3.0], [1.0, 3.0]], "f"),
                        rtol=1e-5, atol=1e-5)
    # batch routing: roi with batch_idx 1 reads image 1
    two = onp.stack([onp.zeros((1, 4, 4), "f"), onp.ones((1, 4, 4), "f")])
    r3 = _nd([[1.0, 0.0, 0.0, 3.0, 3.0]])
    o3 = nd.contrib.ROIAlign(_nd(two), r3, pooled_size=(1, 1))
    assert o3.asnumpy()[0, 0, 0, 0] == pytest.approx(1.0)


def test_roi_align_gradient_flows():
    x = _nd(RS.randn(1, 2, 6, 6).astype("f"))
    rois = _nd([[0.0, 0.5, 0.5, 4.5, 4.5]])
    x.attach_grad()
    with mx.autograd.record():
        y = nd.contrib.ROIAlign(x, rois, pooled_size=(2, 2)).sum()
    y.backward()
    g = x.grad.asnumpy()
    assert onp.isfinite(g).all() and onp.abs(g).sum() > 0
    # gradient mass is conserved: sum of grads == number of output cells
    assert g.sum() == pytest.approx(2 * 2 * 2, rel=1e-4)


def test_box_decode_encode_roundtrip():
    anchors = onp.array([[[0.1, 0.1, 0.4, 0.5],
                          [0.5, 0.4, 0.9, 0.8]]], "f")
    gt = onp.array([[[0.12, 0.15, 0.45, 0.52],
                     [0.48, 0.38, 0.88, 0.82]]], "f")
    samples = _nd([[1.0, 1.0]])
    matches = _nd([[0, 1]], "int32")
    targets, masks = nd.contrib.box_encode(
        samples, matches, _nd(anchors), _nd(gt))
    assert (masks.asnumpy() == 1).all()
    # decode the encoded targets back: must reproduce the GT boxes
    dec = nd.contrib.box_decode(targets, _nd(anchors),
                                std0=0.1, std1=0.1, std2=0.2, std3=0.2)
    assert_almost_equal(dec.asnumpy(), gt, rtol=1e-4, atol=1e-5)
    # non-positive samples mask out
    t2, m2 = nd.contrib.box_encode(_nd([[0.0, 1.0]]), matches,
                                   _nd(anchors), _nd(gt))
    assert (m2.asnumpy()[0, 0] == 0).all()
    assert (t2.asnumpy()[0, 0] == 0).all()


def test_box_decode_center_format_and_clip():
    anchors_center = onp.array([[[0.25, 0.3, 0.3, 0.4]]], "f")
    data = onp.zeros((1, 1, 4), "f")
    dec = nd.contrib.box_decode(_nd(data), _nd(anchors_center),
                                format="center").asnumpy()
    assert_almost_equal(dec[0, 0],
                        onp.array([0.1, 0.1, 0.4, 0.5], "f"),
                        rtol=1e-5, atol=1e-6)
    # clip bounds the exp() scale
    wide = onp.array([[[0.0, 0.0, 99.0, 99.0]]], "f")
    dec2 = nd.contrib.box_decode(_nd(wide), _nd(anchors_center),
                                 format="center", clip=2.0).asnumpy()
    w = dec2[0, 0, 2] - dec2[0, 0, 0]
    assert w <= 0.3 * 2.0 + 1e-5


def test_vision_contrib_jit_whole():
    import jax
    from mxnet_tpu.ops.vision_contrib import (adaptive_avg_pooling_2d,
                                              bilinear_resize_2d)
    x = RS.randn(1, 2, 5, 5).astype("f")
    f = jax.jit(lambda a: adaptive_avg_pooling_2d(a, output_size=(2, 2)))
    g = jax.jit(lambda a: bilinear_resize_2d(a, height=9, width=9))
    assert f(x).shape == (1, 2, 2, 2)
    assert g(x).shape == (1, 2, 9, 9)


def test_vision_contrib_review_regressions():
    """Review findings: PS-ROI raises, resize mode guard + size
    precedence, ROIAlign zero-outside boundary rule."""
    x = _nd(RS.randn(1, 4, 6, 6).astype("f"))
    rois = _nd([[0.0, 0.0, 0.0, 3.0, 3.0]])
    with pytest.raises(NotImplementedError):
        nd.contrib.ROIAlign(x, rois, pooled_size=(2, 2),
                            position_sensitive=True)
    with pytest.raises(NotImplementedError):
        nd.contrib.BilinearResize2D(x, height=3, width=3, mode="odd_scale")
    # explicit size wins over scales (reference ignores scales with size)
    out = nd.contrib.BilinearResize2D(x, height=3, width=3,
                                      scale_height=2.0, scale_width=2.0)
    assert out.shape == (1, 4, 3, 3)
    # samples far outside the image contribute ZERO (not edge values):
    # an roi fully beyond the border pools to 0 on a constant image
    const = _nd(onp.full((1, 1, 4, 4), 5.0, "f"))
    far = _nd([[0.0, 10.0, 10.0, 14.0, 14.0]])
    out2 = nd.contrib.ROIAlign(const, far, pooled_size=(1, 1))
    assert out2.asnumpy()[0, 0, 0, 0] == pytest.approx(0.0, abs=1e-6)
    # while an roi hugging the border (within the 1-px band) still reads
    near = _nd([[0.0, -0.5, -0.5, 2.0, 2.0]])
    out3 = nd.contrib.ROIAlign(const, near, pooled_size=(1, 1))
    assert out3.asnumpy()[0, 0, 0, 0] == pytest.approx(5.0, abs=1e-6)


def test_deformable_conv_zero_offset_equals_conv():
    """DCN with zero offsets IS the ordinary convolution (the defining
    identity; reference deformable_convolution.cc)."""
    x = RS.randn(2, 4, 9, 9).astype("f")
    wgt = (RS.randn(6, 4, 3, 3) * 0.2).astype("f")
    bias = RS.randn(6).astype("f")
    for strides, padding, dil in [((1, 1), (1, 1), (1, 1)),
                                  ((2, 2), (0, 0), (1, 1)),
                                  ((1, 1), (2, 2), (2, 2))]:
        ref = nd.Convolution(_nd(x), _nd(wgt), _nd(bias), kernel=(3, 3),
                             num_filter=6, stride=strides, pad=padding,
                             dilate=dil).asnumpy()
        oh, ow = ref.shape[2], ref.shape[3]
        off = _nd(onp.zeros((2, 18, oh, ow), "f"))
        got = nd.contrib.DeformableConvolution(
            _nd(x), off, _nd(wgt), _nd(bias), kernel=(3, 3), num_filter=6,
            stride=strides, pad=padding, dilate=dil).asnumpy()
        assert_almost_equal(got, ref, rtol=1e-4, atol=1e-4)


def test_deformable_conv_integer_shift():
    """A constant integer offset (+1 in x on every tap) equals the
    ordinary conv over the input shifted by one pixel (interior)."""
    x = RS.randn(1, 2, 8, 8).astype("f")
    wgt = (RS.randn(3, 2, 3, 3) * 0.3).astype("f")
    # plain conv on x shifted left by 1 (so tap reads x+1 column)
    xs = onp.zeros_like(x)
    xs[..., :, :-1] = x[..., :, 1:]
    ref = nd.Convolution(_nd(xs), _nd(wgt), None, kernel=(3, 3),
                         num_filter=3, no_bias=True).asnumpy()
    off = onp.zeros((1, 18, 6, 6), "f")
    off[:, 1::2] = 1.0  # dx channels = +1
    got = nd.contrib.DeformableConvolution(
        _nd(x), _nd(off), _nd(wgt), None, kernel=(3, 3), num_filter=3,
        no_bias=True).asnumpy()
    # interior only: the shifted-input ref zero-pads at the right edge
    assert_almost_equal(got[..., :, :-1], ref[..., :, :-1],
                        rtol=1e-4, atol=1e-4)


def test_deformable_conv_groups_and_grads():
    x = _nd(RS.randn(1, 4, 6, 6).astype("f"))
    wgt = _nd((RS.randn(4, 2, 3, 3) * 0.3).astype("f"))  # num_group=2
    off = _nd(onp.zeros((1, 36, 6, 6), "f"))  # 2 deformable groups
    x.attach_grad(), wgt.attach_grad(), off.attach_grad()
    with mx.autograd.record():
        y = nd.contrib.DeformableConvolution(
            x, off, wgt, None, kernel=(3, 3), num_filter=4, pad=(1, 1),
            num_group=2, num_deformable_group=2, no_bias=True)
        loss = (y * y).sum()
    loss.backward()
    assert y.shape == (1, 4, 6, 6)
    for t in (x, wgt, off):
        g = t.grad.asnumpy()
        assert onp.isfinite(g).all()
    assert onp.abs(x.grad.asnumpy()).sum() > 0
    assert onp.abs(wgt.grad.asnumpy()).sum() > 0
    # offset grad exists (zero-offset is a smooth point of bilinear
    # sampling; nonzero because neighboring pixels differ)
    assert onp.abs(off.grad.asnumpy()).sum() > 0


def test_psroi_pooling_bin_groups():
    """Channel group (i, j) feeds ONLY output bin (i, j): constant
    per-group planes recover the group index at each bin."""
    k, od = 2, 3
    c = od * k * k
    data = onp.zeros((1, c, 8, 8), "f")
    for d in range(od):
        for gi in range(k * k):
            data[0, d * k * k + gi] = d * 10 + gi
    rois = _nd([[0.0, 0.0, 0.0, 7.0, 7.0]])
    out = nd.contrib.PSROIPooling(_nd(data), rois, output_dim=od,
                                  pooled_size=k).asnumpy()
    assert out.shape == (1, od, k, k)
    for d in range(od):
        for i in range(k):
            for j in range(k):
                assert out[0, d, i, j] == pytest.approx(d * 10 + i * k + j)


def test_deformable_conv_validation():
    x = _nd(RS.randn(1, 4, 6, 6).astype("f"))
    wgt = _nd(RS.randn(4, 4, 3, 3).astype("f"))
    with pytest.raises(ValueError, match="offset"):
        nd.contrib.DeformableConvolution(
            x, _nd(onp.zeros((1, 6, 4, 4), "f")), wgt, None,
            kernel=(3, 3), num_filter=4, no_bias=True)
    with pytest.raises(ValueError, match="output_dim"):
        nd.contrib.PSROIPooling(x, _nd([[0.0, 0, 0, 3, 3]]),
                                output_dim=3, pooled_size=2)


def test_psroi_pooling_group_size_differs():
    """group_size != pooled_size: bin (i, j) pools channel group
    (floor(i*gs/k), floor(j*gs/k)) — review regression."""
    k, gs, od = 4, 2, 2
    c = od * gs * gs
    data = onp.zeros((1, c, 8, 8), "f")
    for d in range(od):
        for gi in range(gs * gs):
            data[0, d * gs * gs + gi] = d * 10 + gi
    rois = _nd([[0.0, 0.0, 0.0, 7.0, 7.0]])
    out = nd.contrib.PSROIPooling(_nd(data), rois, output_dim=od,
                                  pooled_size=k, group_size=gs).asnumpy()
    assert out.shape == (1, od, k, k)
    for i in range(k):
        for j in range(k):
            want = 0 * 10 + (i * gs // k) * gs + (j * gs // k)
            assert out[0, 0, i, j] == pytest.approx(want)


def test_psroi_rounding_half_away_from_zero():
    """C round() at *.5 coordinates (review regression): roi x1=0.5
    rounds to 1, not banker's 0."""
    k, od = 1, 1
    data = onp.zeros((1, 1, 4, 8), "f")
    data[0, 0, :, 0] = 100.0  # column 0 is hot
    # x1=0.5 -> rounds to 1: column 0 EXCLUDED from the pooled window
    out = nd.contrib.PSROIPooling(_nd(data),
                                  _nd([[0.0, 0.5, 0.0, 6.0, 3.0]]),
                                  output_dim=od, pooled_size=k).asnumpy()
    assert out[0, 0, 0, 0] == pytest.approx(0.0)
    # x1=0.4 -> rounds to 0: column 0 included
    out2 = nd.contrib.PSROIPooling(_nd(data),
                                   _nd([[0.0, 0.4, 0.0, 6.0, 3.0]]),
                                   output_dim=od, pooled_size=k).asnumpy()
    assert out2[0, 0, 0, 0] > 0


def test_roi_align_fixed_grid_deviation_bound():
    """ROIAlign resolves sample_ratio<=0 to a FIXED 2-sample grid per
    bin axis (static XLA shapes), while the reference samples
    ceil(roi_extent/pooled_size) adaptively. This test pins the
    deviation on the worst documented case — ROIs much larger than
    2x the pooled size — against a dense 8-sample grid standing in for
    the adaptive reference (advisor r4: make the tolerance explicit)."""
    # smooth feature map (the realistic case: conv features are locally
    # correlated): both grids approximate the same smooth integral
    yy, xx = onp.meshgrid(onp.linspace(0, 3, 32), onp.linspace(0, 3, 32),
                          indexing="ij")
    smooth = onp.stack([onp.sin(yy) * onp.cos(xx), yy * 0.1 + xx * 0.05])
    img = smooth[None].astype("f")
    # roi spans 28x28 over a (2,2) pool: reference would use 14 samples
    rois = onp.array([[0.0, 2.0, 2.0, 30.0, 30.0]], "f")
    out2 = nd.contrib.ROIAlign(_nd(img), _nd(rois), pooled_size=(2, 2),
                               sample_ratio=-1).asnumpy()
    out8 = nd.contrib.ROIAlign(_nd(img), _nd(rois), pooled_size=(2, 2),
                               sample_ratio=8).asnumpy()
    assert onp.abs(out2 - out8).max() < 0.02
    # white noise is the worst case: 4 vs 64 nearly-independent samples
    # of a 14x14-px bin — deviation up to ~0.5 absolute is EXPECTED.
    # Pinned here so the divergence from the reference's adaptive grid
    # is documented, not silent (advisor r4).
    noise = RS.randn(1, 2, 32, 32).astype("f")
    n2 = nd.contrib.ROIAlign(_nd(noise), _nd(rois), pooled_size=(2, 2),
                             sample_ratio=-1).asnumpy()
    n8 = nd.contrib.ROIAlign(_nd(noise), _nd(rois), pooled_size=(2, 2),
                             sample_ratio=8).asnumpy()
    assert onp.abs(n2 - n8).max() < 0.8  # documented worst-case band
    # explicit sample_ratio matches itself exactly (no hidden adaptivity)
    again = nd.contrib.ROIAlign(_nd(noise), _nd(rois), pooled_size=(2, 2),
                                sample_ratio=8).asnumpy()
    assert (again == n8).all()
