"""IO: NDArrayIter, RecordIO (python + native), image pipeline
(reference tests/python/unittest/test_io.py scope)."""
import os
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, recordio
from mxnet_tpu.io import NDArrayIter, DataBatch, DataDesc


def test_ndarrayiter_basic():
    x = np.arange(40).reshape(10, 4).astype(np.float32)
    y = np.arange(10).astype(np.float32)
    it = NDArrayIter(x, y, batch_size=3, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (3, 4)
    assert batches[-1].pad == 2
    it.reset()
    assert len(list(it)) == 4


def test_ndarrayiter_discard():
    x = np.zeros((10, 2), np.float32)
    it = NDArrayIter(x, None, batch_size=3, last_batch_handle="discard")
    assert len(list(it)) == 3


def test_ndarrayiter_shuffle_deterministic_reset():
    x = np.arange(20).reshape(10, 2).astype(np.float32)
    it = NDArrayIter(x, None, batch_size=5, shuffle=True)
    a = np.concatenate([b.data[0].asnumpy() for b in it])
    assert sorted(a[:, 0].tolist()) == sorted(x[:, 0].tolist())


def test_provide_data_desc():
    x = np.zeros((8, 3, 4, 4), np.float32)
    it = NDArrayIter(x, np.zeros(8), batch_size=2)
    desc = it.provide_data[0]
    assert desc.name == "data"
    assert desc.shape == (2, 3, 4, 4)


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "a.rec")
    w = recordio.MXRecordIO(path, "w")
    items = [b"hello", b"x" * 1000, b"", b"abc"]
    for it_ in items:
        w.write(it_)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    out = []
    while True:
        rec = r.read()
        if rec is None:
            break
        out.append(rec)
    assert out == items


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "b.rec")
    idxp = str(tmp_path / "b.idx")
    w = recordio.MXIndexedRecordIO(idxp, path, "w")
    for i in range(10):
        w.write_idx(i, bytes([i]) * (i + 1))
    w.close()
    r = recordio.MXIndexedRecordIO(idxp, path, "r")
    assert r.read_idx(7) == bytes([7]) * 8
    assert r.read_idx(0) == b"\x00"
    assert len(r.keys) == 10


def test_pack_unpack_header():
    h = recordio.IRHeader(0, 3.0, 7, 0)
    s = recordio.pack(h, b"payload")
    h2, payload = recordio.unpack(s)
    assert payload == b"payload"
    assert h2.label == 3.0
    assert h2.id == 7
    # vector label
    h = recordio.IRHeader(0, np.array([1.0, 2.0, 3.0], np.float32), 1, 0)
    s = recordio.pack(h, b"xy")
    h2, payload = recordio.unpack(s)
    assert payload == b"xy"
    assert np.allclose(h2.label, [1, 2, 3])


def test_native_reader_matches_python(tmp_path):
    from mxnet_tpu.io import native
    if not native.available():
        pytest.skip("native IO unavailable")
    path = str(tmp_path / "n.rec")
    idxp = str(tmp_path / "n.idx")
    w = recordio.MXIndexedRecordIO(idxp, path, "w")
    payloads = [np.random.bytes(np.random.randint(1, 200)) for _ in range(31)]
    for i, p in enumerate(payloads):
        w.write_idx(i, p)
    w.close()

    r = native.NativeRecordReader(path)
    got = []
    while (rec := r.read()) is not None:
        got.append(rec)
    assert got == payloads

    b = native.NativeBatcher(path, idxp, batch_size=8, num_threads=3)
    got2 = []
    while (batch := b.next()) is not None:
        got2.extend(batch)
    assert got2 == payloads


def test_image_record_iter(tmp_path):
    """Full image pipeline: pack → native batcher → decode → augment."""
    path = str(tmp_path / "img.rec")
    idxp = str(tmp_path / "img.idx")
    w = recordio.MXIndexedRecordIO(idxp, path, "w")
    rng = np.random.default_rng(0)
    for i in range(12):
        img = rng.integers(0, 255, (36, 36, 3), dtype=np.uint8)
        packed = recordio.pack_img(recordio.IRHeader(0, float(i % 3), i, 0),
                                   img, img_fmt=".png")
        w.write_idx(i, packed)
    w.close()

    from mxnet_tpu.io import ImageRecordIter
    it = ImageRecordIter(path_imgrec=path, path_imgidx=idxp,
                         data_shape=(3, 32, 32), batch_size=4,
                         rand_crop=True, rand_mirror=True)
    n = 0
    for batch in it:
        assert batch.data[0].shape == (4, 3, 32, 32)
        assert batch.label[0].shape == (4,)
        n += 1
    assert n == 3


def test_csv_iter(tmp_path):
    f = str(tmp_path / "d.csv")
    np.savetxt(f, np.arange(12).reshape(6, 2), delimiter=",")
    from mxnet_tpu.io import CSVIter
    it = CSVIter(data_csv=f, data_shape=(2,), batch_size=2)
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (2, 2)


def test_dataloader_multiprocess_workers():
    """num_workers>0 with thread_pool=False runs forked decode workers
    (reference multiprocessing+shared-mem contract): batches match the
    single-process loader exactly, in order."""
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.gluon.data.dataset import ArrayDataset
    x = np.arange(96, dtype=np.float32).reshape(24, 4)
    y = np.arange(24, dtype=np.float32)
    ds = ArrayDataset(x, y)
    ref = [(bx.asnumpy(), by.asnumpy())
           for bx, by in DataLoader(ds, batch_size=8, shuffle=False)]
    got = [(bx.asnumpy(), by.asnumpy())
           for bx, by in DataLoader(ds, batch_size=8, shuffle=False,
                                    num_workers=3, thread_pool=False)]
    assert len(got) == len(ref) == 3
    for (rx, ry), (gx, gy) in zip(ref, got):
        assert np.array_equal(rx, gx) and np.array_equal(ry, gy)


def test_dataloader_threaded_workers_still_work():
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.gluon.data.dataset import ArrayDataset
    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    ds = ArrayDataset(x)
    got = [b.asnumpy() for b in DataLoader(ds, batch_size=4, shuffle=False,
                                           num_workers=2, thread_pool=True)]
    assert np.array_equal(np.concatenate(got), x)


def test_device_prefetcher():
    from mxnet_tpu.gluon.data import DataLoader, DevicePrefetcher
    from mxnet_tpu.gluon.data.dataset import ArrayDataset
    import mxnet_tpu as mx
    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    ds = ArrayDataset(x)
    ctx = mx.cpu(1)
    batches = list(DevicePrefetcher(
        DataLoader(ds, batch_size=4, shuffle=False), ctx=ctx, depth=2))
    assert len(batches) == 2
    assert all(b.ctx == ctx for b in batches)
    assert np.array_equal(np.concatenate([b.asnumpy() for b in batches]), x)


def test_dataloader_ndarray_dataset_falls_back_to_threads():
    """A dataset whose __getitem__ yields NDArrays must not be run by
    forked workers (fork + XLA deadlock hazard) — the loader probes and
    falls back to thread workers (review regression)."""
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.gluon.data.dataset import Dataset
    from mxnet_tpu import nd as _nd

    class NDDataset(Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return _nd.full((4,), float(i))

    loader = DataLoader(NDDataset(), batch_size=4, shuffle=False,
                        num_workers=2, thread_pool=False)
    batches = [b.asnumpy() for b in loader]
    assert len(batches) == 2
    assert np.allclose(batches[0][:, 0], [0, 1, 2, 3])
    assert loader._fork_safe is False


def _make_rec(tmpdir, n=24, size=(32, 48)):
    import mxnet_tpu as mx
    rs = np.random.RandomState(0)
    rec_path = os.path.join(str(tmpdir), "imgs.rec")
    idx_path = os.path.join(str(tmpdir), "imgs.idx")
    rec = mx.recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    imgs = []
    for i in range(n):
        img = rs.randint(0, 255, (size[0], size[1], 3), np.uint8)
        imgs.append(img)
        rec.write_idx(i, mx.recordio.pack_img(
            mx.recordio.IRHeader(0, float(i % 5), i, 0), img, quality=95))
    rec.close()
    return rec_path, idx_path, imgs


def test_native_image_decode_matches_pil(tmp_path):
    """Native libjpeg decode+resize vs PIL decode of the same bytes."""
    from mxnet_tpu.io import native
    if not native.available():
        pytest.skip("native IO unavailable")
    import mxnet_tpu as mx
    rec_path, idx_path, imgs = _make_rec(tmp_path, n=4)
    rec = mx.recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    payload = rec.read_idx(0)
    header, jpeg_img = mx.recordio.unpack(payload)
    out = native.decode_jpeg(jpeg_img, 32, 48)
    assert out.shape == (3, 32, 48)
    _, pil_img = mx.recordio.unpack_img(payload)
    diff = np.abs(out.astype(np.int32)
                  - pil_img.transpose(2, 0, 1).astype(np.int32))
    assert diff.mean() < 2.0, diff.mean()  # both are libjpeg under the hood


def test_native_image_batcher(tmp_path):
    """The C++ threaded pipeline delivers correctly-shaped CHW batches
    with the right labels, deterministic order unshuffled, across
    epochs."""
    from mxnet_tpu.io import native
    if not native.available():
        pytest.skip("native IO unavailable")
    rec_path, idx_path, imgs = _make_rec(tmp_path, n=24)
    b = native.NativeImageBatcher(rec_path, idx_path, batch_size=8,
                                  data_shape=(3, 32, 48), num_threads=3)
    assert b.num_batches == 3
    for epoch in range(2):
        seen = 0
        while True:
            out = b.next()
            if out is None:
                break
            data, labels = out
            assert data.shape == (8, 3, 32, 48) and data.dtype == np.uint8
            want = [float((seen + j) % 5) for j in range(8)]
            assert labels.tolist() == want, (labels, want)
            # pixel content matches a PIL decode of the SAME jpeg bytes
            # (noise images lose a lot to jpeg; the decoded streams
            # must still agree)
            rec = mx.recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
            _, ref = mx.recordio.unpack_img(rec.read_idx(seen))
            diff = np.abs(data[0].astype(np.int32)
                          - ref.transpose(2, 0, 1).astype(np.int32))
            assert diff.mean() < 2.0, diff.mean()
            seen += 8
        assert seen == 24
        b.reset()
    b.close()


def test_native_image_batcher_sharding(tmp_path):
    """num_parts/part_index shard the dataset (dist workers)."""
    from mxnet_tpu.io import native
    if not native.available():
        pytest.skip("native IO unavailable")
    rec_path, idx_path, _ = _make_rec(tmp_path, n=24)
    b = native.NativeImageBatcher(rec_path, idx_path, batch_size=4,
                                  data_shape=(3, 32, 48), num_parts=2,
                                  part_index=1)
    out = b.next()
    assert out is not None
    _, labels = out
    # part 1 of 2 sees records 1,3,5,... → labels (i%5) for odd i
    assert labels.tolist() == [1.0, 3.0, 0.0, 2.0]


# ---------------------------------------------------------------------------
# LibSVMIter (src/io/iter_libsvm.cc analog) — the CSR input path of the
# sparse linear-classification examples
# ---------------------------------------------------------------------------

def _write_libsvm(path, lines):
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def test_libsvm_iter_parses_csr(tmp_path):
    p = str(tmp_path / "d.libsvm")
    _write_libsvm(p, [
        "1 0:1.5 3:2.0",
        "0 1:3.0",
        "2 0:0.5 2:1.0 4:4.0",
        "1 4:1.0",
    ])
    it = mx.io.LibSVMIter(data_libsvm=p, data_shape=(5,), batch_size=2)
    assert it.max_row_nnz == 3
    b1 = it.next()
    csr = b1.data[0]
    assert csr.stype == "csr"
    dense = csr.asnumpy()
    np.testing.assert_allclose(
        dense, [[1.5, 0, 0, 2.0, 0], [0, 3.0, 0, 0, 0]])
    np.testing.assert_allclose(b1.label[0].asnumpy(), [1.0, 0.0])
    b2 = it.next()
    np.testing.assert_allclose(
        b2.data[0].asnumpy(),
        [[0.5, 0, 1.0, 0, 4.0], [0, 0, 0, 0, 1.0]])
    with pytest.raises(StopIteration):
        it.next()
    it.reset()
    np.testing.assert_allclose(it.next().data[0].asnumpy(), dense)


def test_libsvm_iter_round_batch_and_pad(tmp_path):
    p = str(tmp_path / "d.libsvm")
    _write_libsvm(p, ["0 0:1.0", "1 1:1.0", "0 2:1.0"])
    it = mx.io.LibSVMIter(data_libsvm=p, data_shape=(4,), batch_size=2)
    it.next()
    b = it.next()  # 1 real row + 1 wrapped from the start
    assert b.pad == 1
    np.testing.assert_allclose(
        b.data[0].asnumpy(), [[0, 0, 1.0, 0], [1.0, 0, 0, 0]])


def test_libsvm_iter_separate_label_file(tmp_path):
    p = str(tmp_path / "d.libsvm")
    lp = str(tmp_path / "l.libsvm")
    _write_libsvm(p, ["0 0:1.0", "0 1:2.0"])
    _write_libsvm(lp, ["0:1.0 2:5.0", "1:3.0"])
    it = mx.io.LibSVMIter(data_libsvm=p, data_shape=(2,), label_libsvm=lp,
                          label_shape=(3,), batch_size=2)
    b = it.next()
    np.testing.assert_allclose(b.label[0].asnumpy(),
                               [[1.0, 0, 5.0], [0, 3.0, 0]])


def test_libsvm_iter_sharding(tmp_path):
    p = str(tmp_path / "d.libsvm")
    _write_libsvm(p, [f"{i} {i % 3}:1.0" for i in range(8)])
    it = mx.io.LibSVMIter(data_libsvm=p, data_shape=(3,), batch_size=2,
                          num_parts=2, part_index=1)
    assert it.num_data == 4
    b = it.next()
    np.testing.assert_allclose(b.label[0].asnumpy(), [4.0, 5.0])


def test_libsvm_iter_rejects_out_of_range(tmp_path):
    p = str(tmp_path / "d.libsvm")
    _write_libsvm(p, ["0 7:1.0"])
    with pytest.raises(mx.MXNetError, match="ZERO-based"):
        mx.io.LibSVMIter(data_libsvm=p, data_shape=(5,), batch_size=1)


def test_csr_to_ell_and_sparse_dot(tmp_path):
    from mxnet_tpu.ndarray import sparse
    rs = np.random.RandomState(0)
    dense = rs.rand(6, 9).astype(np.float32)
    dense[dense < 0.6] = 0.0
    csr = sparse.csr_matrix(dense)
    cols, vals = sparse.csr_to_ell(csr, 9)
    # reconstruct: scatter vals back by cols
    rebuilt = np.zeros_like(dense)
    c, v = cols.asnumpy(), vals.asnumpy()
    for i in range(dense.shape[0]):
        np.add.at(rebuilt[i], c[i], v[i])
    np.testing.assert_allclose(rebuilt, dense, rtol=1e-6)
    # csr @ dense without densify matches dense @ dense
    w = rs.rand(9, 4).astype(np.float32)
    out = sparse.dot(csr, mx.nd.array(w))
    np.testing.assert_allclose(out.asnumpy(), dense @ w, rtol=1e-5,
                               atol=1e-6)
    # transpose path
    outT = sparse.dot(csr, mx.nd.array(rs.rand(6, 4).astype(np.float32)),
                      transpose_a=True)
    assert outT.shape == (9, 4)


def test_kvstore_sparse_push_pull_roundtrip():
    """row_sparse push through the kvstore updater touches ONLY the
    pushed rows (sgd_update_rsp), and row_sparse_pull returns them."""
    from mxnet_tpu.ndarray import sparse
    kv = mx.kv.create("local")
    w = mx.nd.ones((6, 3))
    kv.init("w", w)
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5, wd=0.0,
                                      momentum=0.0))
    g = sparse.row_sparse_array(
        (np.full((2, 3), 2.0, np.float32), np.array([1, 4])), shape=(6, 3))
    kv.push("w", g)
    out = mx.nd.zeros((6, 3))
    kv.pull("w", out=out)
    got = out.asnumpy()
    exp = np.ones((6, 3), np.float32)
    exp[[1, 4]] -= 0.5 * 2.0
    np.testing.assert_allclose(got, exp)
    # sparse pull of a row subset
    rsp = sparse.row_sparse_array(
        (np.zeros((2, 3), np.float32), np.array([1, 2])), shape=(6, 3))
    kv.row_sparse_pull("w", out=rsp, row_ids=mx.nd.array([1, 2]))
    np.testing.assert_allclose(rsp.data.asnumpy(),
                               exp[[1, 2]])


def test_device_prefetcher_threaded_lifecycle():
    """Threaded DevicePrefetcher (r5): worker exceptions surface once
    then the stream TERMINATES (no deadlock on the next get), and
    close() releases the pump thread after an early break."""
    import numpy as onp

    from mxnet_tpu.gluon.data import DevicePrefetcher

    def bad():
        yield onp.ones((2, 2), onp.float32)
        raise RuntimeError("boom")

    it = iter(DevicePrefetcher(bad(), depth=2))
    assert next(it).shape == (2, 2)
    with pytest.raises(RuntimeError, match="boom"):
        next(it)
    with pytest.raises(StopIteration):
        next(it)

    p = DevicePrefetcher(iter([onp.ones((2, 2), onp.float32)] * 10),
                         depth=2)
    assert next(iter(p)).shape == (2, 2)
    p.close()
    assert p._worker is None
    # synchronous mode unchanged
    s = DevicePrefetcher([onp.zeros((1,), onp.float32)], threaded=False)
    assert [b.shape for b in s] == [(1,)]
