"""Drive the C++ unit-test tier (reference tests/cpp, run via `make test`).

Builds src/cc/test_io from source and runs it; the binary asserts
RecordIO framing, threaded batcher ordering/sharding, image decode
pipeline behavior (corrupt-record skip, CHW layout, epoch mechanics).
"""
import shutil
import subprocess
from pathlib import Path

import pytest

SRC = Path(__file__).resolve().parents[1] / "src" / "cc"


@pytest.mark.skipif(shutil.which("make") is None or shutil.which("g++") is None,
                    reason="native toolchain unavailable")
def test_native_io_cpp_suite(tmp_path):
    build = subprocess.run(["make", "-C", str(SRC), "test_io"],
                           capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr
    run = subprocess.run([str(SRC / "test_io"), str(tmp_path)],
                         capture_output=True, text=True, timeout=300)
    assert run.returncode == 0, run.stdout + run.stderr
    assert "ALL NATIVE IO TESTS PASSED" in run.stdout
